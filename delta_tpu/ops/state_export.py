"""Export table state as fixed-width columns for device computation.

The reference keeps table state as a Spark ``Dataset[SingleAction]``
(``Snapshot.scala:88-111``); scan planning filters it with Catalyst
expressions. Here the host turns AddFile metadata into SoA numpy columns —
paths and partition strings dictionary-encoded (int32 codes + host-side
dictionaries), sizes/timestamps/stats as int64/float64 lanes — which ship to
HBM for the pruning and replay kernels (``ops/pruning.py``,
``ops/replay_kernel.py``). Variable-length bytes never reach the device.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from delta_tpu.protocol.actions import Action, AddFile, Metadata, RemoveFile
from delta_tpu.utils.arrow import one_chunk as _one_chunk
from delta_tpu.schema.types import (
    ByteType,
    DataType,
    DateType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    ShortType,
    StructType,
    TimestampType,
)

__all__ = [
    "FileStateArrays",
    "files_to_arrays",
    "arrays_from_columns",
    "stats_json_table",
    "stats_table",
    "ReplayArrays",
    "actions_to_arrays",
]

_NUMERIC = (ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType,
            DateType, TimestampType)


def _stat_to_lane(v: Any, dt: DataType) -> Optional[float]:
    """Normalize a JSON stats value to a comparable float64 lane value.

    Integers beyond 2^53 don't fit a float64 lane exactly — treating them as
    missing keeps pruning conservative (NULL keeps the file) instead of
    silently pruning on a rounded bound."""
    if v is None:
        return None
    if isinstance(v, int) and abs(v) > 2**53:
        return None
    try:
        if isinstance(dt, DateType) and isinstance(v, str):
            import datetime as _dt

            return float((_dt.date.fromisoformat(v[:10]) - _dt.date(1970, 1, 1)).days)
        if isinstance(dt, TimestampType) and isinstance(v, str):
            import datetime as _dt

            s = v.replace(" ", "T")
            if s.endswith("Z"):
                s = s[:-1] + "+00:00"
            d = _dt.datetime.fromisoformat(s)
            # tz-naive stats are wall-clock UTC; offset-carrying ones are
            # converted to the same instant (matches the Arrow json reader)
            if d.tzinfo is None:
                d = d.replace(tzinfo=_dt.timezone.utc)
            return float(d.timestamp() * 1e6)
        return float(v)
    except (ValueError, TypeError):
        return None


@dataclass
class FileStateArrays:
    """Snapshot AddFile metadata as device-shippable columns.

    ``paths`` stays on host (the dictionary); everything else is numpy and can
    be placed on device. Row i across all arrays describes ``paths[i]``.
    """

    paths: List[str]
    size: np.ndarray  # int64
    modification_time: np.ndarray  # int64
    num_records: np.ndarray  # int64, -1 = unknown
    partition_codes: Dict[str, np.ndarray]  # int32 codes, -1 = null
    partition_dicts: Dict[str, List[str]]  # code -> raw partition string
    stats_min: Dict[str, np.ndarray]  # float64, NaN = missing
    stats_max: Dict[str, np.ndarray]
    stats_null_count: Dict[str, np.ndarray]  # int64, -1 = missing

    @property
    def num_files(self) -> int:
        return len(self.paths)

    def device_env(self):
        """Bind columns as :class:`delta_tpu.expr.jaxeval.DeviceColumn`s using
        the flat names the skipping rewrite emits (``min.c`` / ``max.c`` /
        ``nullCount.c`` / ``numRecords`` / partition columns as codes)."""
        from delta_tpu.expr.jaxeval import DeviceColumn

        env = {"numRecords": DeviceColumn.of(self.num_records, self.num_records >= 0)}
        env["size"] = DeviceColumn.of(self.size)
        # partition codes are intentionally NOT bound under the column name:
        # a predicate literal compares against the VALUE, not the dictionary
        # code — binding codes here made `year = 2021` prune wrongly. Kernels
        # that want code-space comparison bind `partition_code.<c>` explicitly.
        for c, codes in self.partition_codes.items():
            env[f"partition_code.{c}"] = DeviceColumn.of(codes, codes >= 0)
        for c, mn in self.stats_min.items():
            env[f"min.{c}"] = DeviceColumn.of(mn, ~np.isnan(mn))
        for c, mx in self.stats_max.items():
            env[f"max.{c}"] = DeviceColumn.of(mx, ~np.isnan(mx))
        for c, nc in self.stats_null_count.items():
            env[f"nullCount.{c}"] = DeviceColumn.of(nc, nc >= 0)
        return env


def files_to_arrays(
    files: Sequence[AddFile],
    metadata: Metadata,
    stats_columns: Optional[Sequence[str]] = None,
) -> FileStateArrays:
    """Columnarize AddFiles. ``stats_columns`` defaults to every numeric leaf
    of the data schema (the first ``dataSkippingNumIndexedCols`` columns —
    `DeltaConfig.scala:383` semantics are applied by the caller)."""
    schema: StructType = metadata.schema
    part_cols = list(metadata.partition_columns)
    if stats_columns is None:
        stats_columns = [
            f.name
            for f in schema.fields
            if f.name not in part_cols and isinstance(f.data_type, _NUMERIC)
        ]
    col_types: Dict[str, DataType] = {f.name: f.data_type for f in schema.fields}

    n = len(files)
    paths = [f.path for f in files]
    size = np.fromiter((f.size or 0 for f in files), np.int64, n)
    mtime = np.fromiter((f.modification_time or 0 for f in files), np.int64, n)

    part_codes: Dict[str, np.ndarray] = {}
    part_dicts: Dict[str, List[str]] = {}
    for c in part_cols:
        codes = np.empty(n, np.int32)
        mapping: Dict[str, int] = {}
        dictionary: List[str] = []
        for i, f in enumerate(files):
            v = (f.partition_values or {}).get(c)
            if v is None:
                codes[i] = -1
                continue
            code = mapping.get(v)
            if code is None:
                code = mapping[v] = len(dictionary)
                dictionary.append(v)
            codes[i] = code
        part_codes[c] = codes
        part_dicts[c] = dictionary

    num_records = np.full(n, -1, np.int64)
    smin = {c: np.full(n, np.nan) for c in stats_columns}
    smax = {c: np.full(n, np.nan) for c in stats_columns}
    snull = {c: np.full(n, -1, np.int64) for c in stats_columns}
    for i, f in enumerate(files):
        st = f.stats_dict()
        if not st:
            continue
        nr = st.get("numRecords")
        if nr is not None:
            num_records[i] = int(nr)
        mins = st.get("minValues") or {}
        maxs = st.get("maxValues") or {}
        nulls = st.get("nullCount") or {}
        for c in stats_columns:
            dt = col_types.get(c, DoubleType())
            v = _stat_to_lane(mins.get(c), dt)
            if v is not None:
                smin[c][i] = v
            v = _stat_to_lane(maxs.get(c), dt)
            if v is not None:
                smax[c][i] = v
            if nulls.get(c) is not None:
                snull[c][i] = int(nulls[c])

    return FileStateArrays(
        paths=paths,
        size=size,
        modification_time=mtime,
        num_records=num_records,
        partition_codes=part_codes,
        partition_dicts=part_dicts,
        stats_min=smin,
        stats_max=smax,
        stats_null_count=snull,
    )


def _temporal_to_lane(arr: pa.Array, dt: DataType) -> Optional[np.ndarray]:
    """Vectorized string→lane conversion for date/timestamp stats columns.
    Returns float64 with NaN for unparseable/missing, or None when the whole
    column can't be converted (caller treats as missing — conservative)."""
    import pyarrow.compute as pc

    def _to_ts_us(a: pa.Array) -> pa.Array:
        if pa.types.is_timestamp(a.type):
            # the json reader already normalized zone designators to UTC
            return a.cast(pa.timestamp("us")) if a.type.tz is None else (
                a.cast(pa.timestamp("us", tz="UTC")).cast(pa.timestamp("us")))
        s = a.cast(pa.string())
        try:
            return pc.cast(s, pa.timestamp("us"))  # tz-naive = wall-clock UTC
        except Exception:
            z = pc.replace_substring_regex(s, r"Z$", "+00:00")
            aware = pc.cast(z, pa.timestamp("us", tz="UTC"))
            return aware.cast(pa.timestamp("us"))

    try:
        if isinstance(dt, DateType):
            if pa.types.is_timestamp(arr.type) or pa.types.is_date(arr.type):
                days = arr.cast(pa.date32()).cast(pa.int32())
            else:
                days = arr.cast(pa.string()).cast(pa.date32()).cast(pa.int32())
            out = days.to_numpy(zero_copy_only=False).astype(np.float64)
        elif isinstance(dt, TimestampType):
            ts = _to_ts_us(arr)
            out = ts.cast(pa.int64()).to_numpy(zero_copy_only=False).astype(np.float64)
        else:
            return None
    except Exception:
        return None
    nulls = pc.is_null(arr).to_numpy(zero_copy_only=False)
    out[nulls] = np.nan
    return out


def _numeric_to_lane(arr: pa.Array) -> Optional[np.ndarray]:
    """Numeric stats column → float64 lane; int64 magnitudes beyond 2^53 are
    masked to NaN (same conservative rule as :func:`_stat_to_lane`)."""
    if not pa.types.is_integer(arr.type) and not pa.types.is_floating(arr.type):
        return None
    nulls = np.asarray(arr.is_null())
    if pa.types.is_integer(arr.type):
        ints = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
        out = ints.astype(np.float64)
        out[np.abs(ints) > 2**53] = np.nan
    else:
        out = arr.cast(pa.float64()).to_numpy(zero_copy_only=False).astype(np.float64)
    out[nulls] = np.nan
    return out


def string_prefix_lane_value(s: str) -> float:
    """First-6-bytes big-endian integer of a string's UTF-8 form, as an
    EXACT float64 (48 bits < 2^53). Monotone non-strict w.r.t. byte order:
    s1 <= s2 implies prefix(s1) <= prefix(s2), so range pruning over
    prefix lanes keeps a superset (never drops a match)."""
    b = s.encode("utf-8")[:6]
    v = 0
    for i, byte in enumerate(b):
        v += byte << (8 * (5 - i))
    return float(v)


def _string_prefix_lanes(arr) -> Optional[np.ndarray]:
    """Vectorized 6-byte prefix values for a pyarrow string array
    (null/non-string -> NaN). Pure-numpy over the Arrow buffers — no
    per-string Python objects."""
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if not pa.types.is_string(arr.type):
        return None
    valid = np.asarray(pc.is_valid(arr))
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32,
                            count=len(arr) + 1, offset=arr.offset * 4)
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None else \
        np.empty(0, np.uint8)
    starts = offsets[:-1].astype(np.int64)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    idx = starts[:, None] + np.arange(6)[None, :]
    mask = np.arange(6)[None, :] < np.minimum(lens, 6)[:, None]
    safe = np.clip(idx, 0, max(len(data) - 1, 0))
    b = np.where(mask, data[safe] if len(data) else 0, 0)
    weights = (256.0 ** np.arange(5, -1, -1))
    out = (b * weights[None, :]).sum(axis=1)
    out[~valid] = np.nan
    return out


def stats_json_table(st: pa.Array, explicit_schema: Optional[pa.Schema] = None):
    """One C++ ndjson parse of a per-file stats JSON string column.

    Returns ``(kind, parsed, idx)``: ``idx`` are the input row positions
    whose stats were non-blank and ``parsed`` is the Arrow table aligned
    with them (``kind == "ok"``). ``kind == "empty"`` means no stats at
    all; ``"newline"`` means a pretty-printed stats string would desync
    the ndjson rows (callers take a per-row path); ``"malformed"`` means
    the batch parse failed (callers treat every stat as missing — pruning
    stays conservative).

    ``explicit_schema`` pins the parsed column types (extra JSON fields are
    ignored). Callers that PERSIST the parsed values (the struct-stats
    checkpoint writer) must pass one: without it the Arrow JSON reader
    type-infers, and a *string* column whose values look like ISO dates
    ('2021-01-01') comes back as timestamp[s] — rendering it back to text
    would store a different literal than the table holds.

    The newline-join runs entirely in C++ (a ListArray wrapping slices of
    the column, then ``binary_join``) — a ``to_pylist`` + ``"\\n".join``
    here round-trips every string through Python objects and dominates the
    cold cache build. Joins run in <=1 GiB slices: one giant join would
    hit Arrow's 2 GiB int32 offset capacity on ~10M-file tables.
    """
    import pyarrow.compute as pc
    import pyarrow.json as pajson

    st = _one_chunk(st)
    blank = pc.if_else(pc.equal(pc.utf8_trim_whitespace(st.fill_null("")), ""), None, st)
    if bool(pc.any(pc.match_substring(blank.fill_null(""), "\n")).as_py() or False):
        return "newline", None, None
    valid = np.asarray(pc.is_valid(blank))
    idx = np.nonzero(valid)[0]
    compact = blank.drop_null()
    if isinstance(compact, pa.ChunkedArray):
        compact = compact.combine_chunks()
    if len(compact) == 0:
        return "empty", None, idx
    try:
        parts = []
        total = len(compact)
        start = 0
        budget = 1 << 30
        offs = np.frombuffer(compact.buffers()[1], np.int32,
                             count=total + 1, offset=compact.offset * 4)
        while start < total:
            end = start + 1
            base = offs[start]
            while end < total and offs[end + 1] - base <= budget:
                end += 1
            sl = compact.slice(start, end - start)
            sl = pa.concat_arrays([sl])  # re-materialize exact offsets
            lst = pa.ListArray.from_arrays(
                pa.array([0, len(sl)], pa.int32()), sl.cast(pa.string()))
            raw = pc.binary_join(lst, "\n").cast(pa.binary())[0].as_buffer()
            parse_opts = (pajson.ParseOptions(
                explicit_schema=explicit_schema,
                unexpected_field_behavior="ignore",
            ) if explicit_schema is not None else None)
            parts.append(pajson.read_json(
                pa.BufferReader(raw),
                read_options=pajson.ReadOptions(use_threads=True,
                                                block_size=8 << 20),
                parse_options=parse_opts,
            ))
            start = end
        parsed = (parts[0] if len(parts) == 1
                  else pa.concat_tables(parts, promote_options="permissive"))
    except Exception:
        return "malformed", None, None
    if parsed.num_rows != len(idx):
        return "malformed", None, None
    return "ok", parsed, idx


def arrays_from_columns(
    cols,
    rows_mask: np.ndarray,
    metadata: Metadata,
    stats_columns: Optional[Sequence[str]] = None,
    sort_by_path: bool = False,
    string_prefix_cols: Sequence[str] = (),
) -> Optional[FileStateArrays]:
    """Vectorized :class:`FileStateArrays` straight from a columnar segment
    (``delta_tpu.log.columnar.SegmentColumns``) — no AddFile dataclasses.

    Stat lanes prefer the checkpoint's typed ``stats_parsed`` struct
    columns (zero JSON: float64 lanes build directly from typed Arrow
    leaves); rows or columns the struct doesn't cover fall back to one C++
    ndjson pass over the raw stats strings (``pyarrow.json``), replacing a
    Python loop over ``stats_dict()`` calls — at 1M files this is the
    difference between a cache build in seconds vs minutes. Partition
    values come vectorized from the checkpoint map columns (or the tail's
    JSON lines). Returns None for shapes neither path can carry, and
    callers fall back to :func:`files_to_arrays`.
    """
    import pyarrow.compute as pc

    rows = np.nonzero(rows_mask)[0] if rows_mask.dtype == bool else np.asarray(rows_mask)
    part_cols = list(metadata.partition_columns)
    part_codes: Dict[str, np.ndarray] = {}
    part_dicts: Dict[str, List[str]] = {}
    if part_cols:
        # dictionary-code partition values straight from the columnar batches
        # (checkpoint map columns / tail JSON lines) — the dynamic-key map
        # never materializes dataclasses
        strings = cols.partition_strings(rows, part_cols)
        if strings is None:
            return None
        for c in part_cols:
            enc = strings[c].dictionary_encode()
            if isinstance(enc, pa.ChunkedArray):
                enc = enc.combine_chunks()
            codes = enc.indices.fill_null(-1).to_numpy(
                zero_copy_only=False).astype(np.int32, copy=False)
            part_codes[c] = codes
            part_dicts[c] = enc.dictionary.to_pylist()
    paths = cols.paths_for(rows)
    size = cols.size[rows].copy()
    mtime = cols.modification_time[rows].copy()
    if sort_by_path:
        order = pc.sort_indices(pa.array(paths)).to_numpy(zero_copy_only=False)
        rows, size, mtime = rows[order], size[order], mtime[order]
        paths = [paths[i] for i in order]
        for c in part_cols:
            part_codes[c] = part_codes[c][order]

    schema: StructType = metadata.schema
    if stats_columns is None:
        stats_columns = [
            f.name for f in schema.fields
            if f.name not in set(part_cols) and isinstance(f.data_type, _NUMERIC)
        ]
    prefix_set = {c for c in string_prefix_cols if c not in set(part_cols)}
    stats_columns = list(stats_columns) + [
        c for c in sorted(prefix_set) if c not in set(stats_columns)
    ]
    col_types: Dict[str, DataType] = {f.name: f.data_type for f in schema.fields}

    n = len(rows)
    num_records = np.full(n, -1, np.int64)
    smin = {c: np.full(n, np.nan) for c in stats_columns}
    smax = {c: np.full(n, np.nan) for c in stats_columns}
    snull = {c: np.full(n, -1, np.int64) for c in stats_columns}
    out = FileStateArrays(
        paths=paths, size=size, modification_time=mtime, num_records=num_records,
        partition_codes=part_codes, partition_dicts=part_dicts,
        stats_min=smin, stats_max=smax, stats_null_count=snull,
    )
    if n == 0:
        return out

    import time as _time

    from delta_tpu.utils.telemetry import bump_counter

    _t0 = _time.perf_counter()

    def _lane_us():
        # stats-lane build time in µs (telemetry: the BENCH metric-6 "parse
        # time" component, isolated from the shared path/size extraction)
        bump_counter("stateExport.statsLanes.us",
                     int((_time.perf_counter() - _t0) * 1e6))

    # -- typed struct-stats fast path (zero JSON) --------------------------
    # Checkpoints written with `stats_parsed` (struct columns typed from the
    # table schema) surface it through the columnar segment; the lanes then
    # build from typed Arrow leaves with no JSON parse at all. Rows the
    # struct misses (JSON commit tails, old checkpoint parts) fall back to
    # the batched ndjson parse below, restricted to just those rows.
    struct_rows: Optional[np.ndarray] = None  # bool mask: struct-covered rows
    sp = cols.stats_parsed
    if sp is not None:
        sp = sp.take(pa.array(rows, pa.int64()))
        sp = _one_chunk(sp)
        struct_rows = _struct_stat_lanes(
            sp, stats_columns, prefix_set, col_types,
            num_records, smin, smax, snull)
    if struct_rows is not None and (cols.stats is None
                                    or bool(struct_rows.all())):
        # every row struct-served: never materialize the JSON string column
        bump_counter("stateExport.statsLanes.struct")
        _lane_us()
        return out

    st = None
    if cols.stats is not None:
        st = _one_chunk(cols.stats.take(pa.array(rows, pa.int64())))
    if struct_rows is not None:
        json_rows = np.asarray(pc.is_valid(st)) & ~struct_rows
        if not json_rows.any():
            bump_counter("stateExport.statsLanes.struct")
            _lane_us()
            return out
        # mask the struct-covered rows out of the JSON pass
        st = pc.if_else(pa.array(json_rows), st, pa.scalar(None, pa.string()))
        bump_counter("stateExport.statsLanes.mixed")
    if st is None:
        return out

    kind, parsed, idx = stats_json_table(st)
    if kind == "newline":
        # pretty-printed stats would desync the ndjson rows — bail to the
        # dataclass path, which parses per row
        return None
    if kind != "ok":
        _lane_us()
        return out  # no/malformed stats → all-missing (keeps every file)
    if struct_rows is None:
        bump_counter("stateExport.statsLanes.json")

    def _scatter_f(dst: np.ndarray, lane: Optional[np.ndarray]):
        if lane is not None:
            dst[idx] = lane

    names = parsed.column_names
    if "numRecords" in names:
        nr = parsed.column("numRecords").combine_chunks()
        lane = _numeric_to_lane(nr)
        if lane is not None:
            vals = np.where(np.isnan(lane), -1, lane).astype(np.int64)
            num_records[idx] = vals
    for struct_name, dest in (("minValues", smin), ("maxValues", smax)):
        if struct_name not in names:
            continue
        col = parsed.column(struct_name).combine_chunks()
        t = col.type
        if not pa.types.is_struct(t):
            continue
        fields = {t.field(i).name for i in range(t.num_fields)}
        for c in stats_columns:
            if c not in fields:
                continue
            leaf = pc.struct_field(col, c)
            if c in prefix_set:
                lane = _string_prefix_lanes(leaf)
            else:
                lane = _numeric_to_lane(leaf)
                if lane is None:
                    lane = _temporal_to_lane(leaf, col_types.get(c, DoubleType()))
            _scatter_f(dest[c], lane)
    if "nullCount" in names:
        col = parsed.column("nullCount").combine_chunks()
        t = col.type
        if pa.types.is_struct(t):
            fields = {t.field(i).name for i in range(t.num_fields)}
            for c in stats_columns:
                if c not in fields:
                    continue
                lane = _numeric_to_lane(pc.struct_field(col, c))
                if lane is not None:
                    snull[c][idx] = np.where(np.isnan(lane), -1, lane).astype(np.int64)
    _lane_us()
    return out




def _struct_fieldset(t: pa.DataType, name: str) -> set:
    if not pa.types.is_struct(t):
        return set()
    for i in range(t.num_fields):
        f = t.field(i)
        if f.name == name:
            if pa.types.is_struct(f.type):
                return {f.type.field(j).name for j in range(f.type.num_fields)}
            return set()
    return set()


def _struct_stat_lanes(sp, stats_columns, prefix_set, col_types,
                       num_records, smin, smax, snull) -> Optional[np.ndarray]:
    """Scatter stat lanes from a ``stats_parsed`` struct column (aligned
    with the output rows). Returns the bool mask of rows the struct served,
    or None when it cannot serve this request — struct absent/all-null, or
    a requested column missing from its min/max fields (the JSON path then
    computes everything, so no column is half-served)."""
    import pyarrow.compute as pc

    if sp is None or not pa.types.is_struct(sp.type):
        return None
    minf = _struct_fieldset(sp.type, "minValues")
    maxf = _struct_fieldset(sp.type, "maxValues")
    if not set(stats_columns) <= (minf & maxf):
        return None
    sp_valid = np.asarray(pc.is_valid(sp))
    if not sp_valid.any():
        return None
    idx = np.nonzero(sp_valid)[0]
    spc = sp if len(idx) == len(sp) else sp.take(pa.array(idx, pa.int64()))
    top = {sp.type.field(i).name for i in range(sp.type.num_fields)}
    if "numRecords" in top:
        lane = _numeric_to_lane(_one_chunk(pc.struct_field(spc, "numRecords")))
        if lane is not None:
            num_records[idx] = np.where(np.isnan(lane), -1, lane).astype(np.int64)
    for struct_name, dest in (("minValues", smin), ("maxValues", smax)):
        col = _one_chunk(pc.struct_field(spc, struct_name))
        for c in stats_columns:
            leaf = _one_chunk(pc.struct_field(col, c))
            if c in prefix_set:
                lane = _string_prefix_lanes(leaf)
            else:
                lane = _numeric_to_lane(leaf)
                if lane is None:
                    lane = _temporal_to_lane(leaf, col_types.get(c, DoubleType()))
            if lane is not None:
                dest[c][idx] = lane
    ncf = _struct_fieldset(sp.type, "nullCount")
    if ncf:
        col = _one_chunk(pc.struct_field(spc, "nullCount"))
        for c in stats_columns:
            if c not in ncf:
                continue
            lane = _numeric_to_lane(_one_chunk(pc.struct_field(col, c)))
            if lane is not None:
                snull[c][idx] = np.where(np.isnan(lane), -1, lane).astype(np.int64)
    return sp_valid


def stats_table(files: Sequence[AddFile], metadata: Metadata,
                stats_columns: Optional[Sequence[str]] = None) -> pa.Table:
    """Host (Arrow) view of per-file stats for the vectorized skipping path —
    includes string columns the device path can't carry."""
    from delta_tpu.expr.partition import typed_partition_row

    schema: StructType = metadata.schema
    part_cols = set(metadata.partition_columns)
    part_schema = metadata.partition_schema
    if stats_columns is None:
        stats_columns = [f.name for f in schema.fields if f.name not in part_cols]
    rows: List[Dict[str, Any]] = []
    for f in files:
        st = f.stats_dict() or {}
        row: Dict[str, Any] = {"numRecords": st.get("numRecords")}
        mins = st.get("minValues") or {}
        maxs = st.get("maxValues") or {}
        nulls = st.get("nullCount") or {}
        for c in stats_columns:
            row[f"min.{c}"] = mins.get(c)
            row[f"max.{c}"] = maxs.get(c)
            row[f"nullCount.{c}"] = nulls.get(c)
        # typed partition values: constant per file, bound so mixed
        # partition/data predicates evaluate the partition leg exactly
        row.update(typed_partition_row(f, part_schema))
        rows.append(row)
    return pa.Table.from_pylist(rows) if rows else pa.table({"numRecords": pa.nulls(0, pa.int64())})


# -- raw action-stream export for the replay kernel -----------------------


@dataclass
class ReplayArrays:
    """A log segment's Add/Remove stream as device columns, in commit order.

    ``seq`` is the global action order (commit version major, position within
    the commit minor) — the sort key that makes last-writer-wins a segmented
    max (`actions/InMemoryLogReplay.scala:43-65` semantics).
    """

    paths: List[str]  # dictionary: path_id -> path
    path_id: np.ndarray  # int32, one per action row
    seq: np.ndarray  # int64
    is_add: np.ndarray  # bool
    size: np.ndarray  # int64 (0 for removes without size)
    deletion_timestamp: np.ndarray  # int64, only for removes (0 otherwise)
    row_action: List[Action] = field(default_factory=list)  # aligned originals

    @property
    def num_rows(self) -> int:
        return len(self.path_id)


def actions_to_arrays(versioned_actions: Sequence[Tuple[int, Sequence[Action]]]) -> ReplayArrays:
    """Flatten ``[(version, actions), ...]`` into :class:`ReplayArrays`,
    keeping only file actions (Metadata/Protocol/txns replay on host)."""
    mapping: Dict[str, int] = {}
    dictionary: List[str] = []
    path_id: List[int] = []
    seq: List[int] = []
    is_add: List[bool] = []
    size: List[int] = []
    del_ts: List[int] = []
    originals: List[Action] = []
    for version, actions in versioned_actions:
        for pos, a in enumerate(actions):
            if isinstance(a, AddFile):
                add = True
                sz = a.size or 0
                dts = 0
            elif isinstance(a, RemoveFile):
                add = False
                sz = a.size or 0
                dts = a.delete_timestamp
            else:
                continue
            code = mapping.get(a.path)
            if code is None:
                code = mapping[a.path] = len(dictionary)
                dictionary.append(a.path)
            path_id.append(code)
            # 31 bits of intra-commit position (2B actions/commit), 32 of
            # version; overflow raises rather than silently sharing a seq
            # (ties would make the replay sort's last-writer-wins arbitrary)
            if pos >= 1 << 31:
                raise ValueError(
                    f"commit {version} has {pos + 1}+ file actions; "
                    "more than 2^31 per commit is unsupported"
                )
            if version >= 1 << 32:
                raise ValueError(
                    f"version {version} exceeds 2^32; seq encoding unsupported"
                )
            seq.append((version << 31) | pos)
            is_add.append(add)
            size.append(sz)
            del_ts.append(dts)
            originals.append(a)
    return ReplayArrays(
        paths=dictionary,
        path_id=np.asarray(path_id, np.int32),
        seq=np.asarray(seq, np.int64),
        is_add=np.asarray(is_add, bool),
        size=np.asarray(size, np.int64),
        deletion_timestamp=np.asarray(del_ts, np.int64),
        row_action=originals,
    )
