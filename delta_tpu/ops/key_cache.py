"""HBM-resident MERGE join keys — the data-plane sibling of
`ops/state_cache`.

The reference re-evaluates the join's target side from a fresh scan every
MERGE (`commands/MergeIntoCommand.scala:310-389`); on a TPU the dominant
cost of the device membership probe is *shipping the target keys* — 80 MB
for a 10M-row int64 lane dwarfs the 0.1 s device sort at any realistic
link. A CDC upsert loop merges into the same table every few minutes, so
the target key lane is the textbook resident operand: build it once
(streamed in tiles), keep it in HBM, and advance it incrementally as the
log tails forward — new files' keys append (a projected Parquet read of
just the new files), removed files' rows die, and deletion-vector growth
flips per-row validity. Steady-state merges then upload only the source
keys (a few MB) and download bit masks.

Layout: one int64 key lane per (table, join-key signature) in PHYSICAL row
order per file (deletion-vector-deleted rows stay in place but are marked
invalid — they must not match, or a source row whose only "match" is a
dead row would silently skip its NOT MATCHED insert). The probe returns
physical-space bits; `commands/merge.py` maps them onto its DV-filtered
decode via each file's position column.

Composite integer keys pack into one lane (hi<<32 | lo) exactly like the
upload path; the packing is part of the signature and is only built when
the target components fit int32 (the per-merge source side is checked at
probe time).

The probe is FUSED with the join's pairing step (PR 6): the kernel also
emits each matched slab row's first-match source index, compacted on
device into an O(matched) pair download — the host no longer re-derives
the pairing from decoded target keys. Cold builds stream per-file decoded
lanes straight onto a pre-sized HBM allocation (:class:`SlabBuilder`), so
the upload overlaps the remaining Parquet decode, and file rewrites
(OPTIMIZE / UPDATE-rewrite / RESTORE) bump a per-table epoch
(:meth:`KeyCache.bump_epoch`) that drops resident entries outright — a
stale slab can never serve a post-rewrite MERGE.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from delta_tpu.utils.jaxcompat import enable_x64
from delta_tpu.utils.config import conf

__all__ = ["ResidentJoinKeys", "KeyCache", "PhysicalProbe", "SlabBuilder",
           "key_cache_enabled"]


def key_cache_enabled() -> bool:
    """Whether the cross-MERGE resident key cache may serve/retain entries.
    ``delta.tpu.merge.keyCache.enabled`` is the documented name;
    ``delta.tpu.merge.residentKeys.enabled`` is honored for back-compat —
    either set to false disables caching (the fused device path itself is
    governed by ``delta.tpu.merge.devicePath.*``)."""
    return (conf.get_bool("delta.tpu.merge.keyCache.enabled", True)
            and conf.get_bool("delta.tpu.merge.residentKeys.enabled", True))

from delta_tpu.ops.state_cache import _next_pow2  # shared pad-size bucketing

# sentinel version for an entry whose tail application failed part-way:
# greater than any real snapshot version, so every staleness guard
# (`entry.version > snapshot.version`) discards the entry immediately
_POISON_VERSION = 1 << 62


class DeltaProbeOverflow(RuntimeError):
    """Internal control-flow signal: the probe kernel's candidate windows
    overflowed both tiers (pathologically skewed source); the caller takes
    the host-join fallback."""


@dataclass
class PhysicalProbe:
    """Probe output in physical slab space: per-source matched flags and —
    the fused-join addition — the matched PAIRS themselves (physical slab
    row → first matching source row), computed on device and downloaded
    O(matched). ``slabs`` maps file path → (offset, rows). ``t_pairs`` is
    None for an insert-only probe (only the source flags were fetched).
    ``t_bits`` (the full per-slab-row matched mask) materializes LAZILY
    from the pairs — the production merge path consumes only
    :meth:`pairs_for_file` and never pays the O(slab-rows) scatter."""

    s_matched: np.ndarray  # bool per source row
    any_multi: bool
    slabs: Dict[str, Tuple[int, int]]
    num_rows: int = 0  # live slab rows (t_bits length)
    # (physical slab rows ascending, first-match source row per pair)
    t_pairs: Optional[Tuple[np.ndarray, np.ndarray]] = None
    _bits: Optional[np.ndarray] = None

    @property
    def t_bits(self) -> Optional[np.ndarray]:
        """Bool per physical slab row; None for an insert-only probe."""
        if self._bits is None and self.t_pairs is not None:
            t = np.zeros(self.num_rows, bool)
            phys, _ = self.t_pairs
            t[phys[phys < self.num_rows]] = True
            self._bits = t
        return self._bits

    def bits_for_file(self, path: str, positions: Optional[np.ndarray],
                      num_rows: int) -> Optional[np.ndarray]:
        """Matched flags for a file's *decoded* rows. ``positions`` are the
        decoded rows' physical positions (None = decode was not DV-filtered,
        rows are physical 0..num_rows). None when the file isn't in the slab
        or shapes disagree (caller falls back)."""
        ent = self.slabs.get(path)
        if ent is None or self.t_bits is None:
            return None
        off, rows = ent
        if positions is None:
            if num_rows != rows:
                return None
            return self.t_bits[off:off + rows]
        if len(positions) and positions.max() >= rows:
            return None
        return self.t_bits[off + positions]

    def pairs_for_file(self, path: str, positions: Optional[np.ndarray],
                       num_rows: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """The matched pairs landing in one file, mapped onto its *decoded*
        rows: (decoded row indices, first-match source rows). ``positions``
        as in :meth:`bits_for_file`. None when the file isn't in the slab or
        the slab disagrees with the decode (a matched physical row absent
        from the DV-filtered decode) — callers fall back to the host join."""
        ent = self.slabs.get(path)
        if ent is None or self.t_pairs is None:
            return None
        off, rows = ent
        phys, srows = self.t_pairs
        lo = int(np.searchsorted(phys, off))
        hi = int(np.searchsorted(phys, off + rows))
        p_local = phys[lo:hi] - off
        s_local = srows[lo:hi]
        if positions is None:
            if num_rows != rows:
                return None
            return p_local, s_local
        if len(positions) and int(positions[-1]) >= rows:
            return None
        idx = np.searchsorted(positions, p_local)
        if (idx >= len(positions)).any():
            return None
        if len(idx) and not (positions[idx] == p_local).all():
            return None  # slab matched a row the decode dropped: fall back
        return idx, s_local


# same memoizing finalize wrapper as the upload path's handle
from delta_tpu.ops.join_kernel import PendingJoin as PendingProbe


def _block_rows(cap: int) -> int:
    """Coarse-fine granularity for the t_bits download: 4096-row blocks
    (512 B of packed bits each) whenever the capacity tiles evenly,
    else one block (tiny slabs)."""
    return 4096 if cap % 4096 == 0 else cap


@functools.lru_cache(maxsize=None)
def _sort_kernel():
    """Sort the slab's key lane once per KEY mutation (build/append), NOT
    per probe: steady-state probes against an unchanged table then skip
    the O(n log n) term entirely. Also emits the inverse permutation (so
    later deletion-vector validity flips update the sorted-space validity
    with a k-row scatter instead of an O(n) gather) and the sorted-space
    validity itself. Padding rows encode as int64.max so they sort to the
    tail; a real key equal to int64.max may share their run — harmless,
    validity excludes them."""
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(keys, valid, n):
        cap = keys.shape[0]
        iota = jnp.arange(cap, dtype=jnp.int32)
        enc = jnp.where(iota < n, keys, jnp.iinfo(jnp.int64).max)
        sk, perm = jax.lax.sort((enc, iota), num_keys=1)
        inv = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
        sv = (valid & (iota < n))[perm]
        return sk, perm, inv, sv

    return kernel


def _tier1_width(cap: int, m: int) -> int:
    """Tier-1 candidate-window width: ~4x the mean source-keys-per-block so
    uniformly distributed sources stay in tier 1; power of two, in
    [64, 4096]."""
    nb = max(cap // _block_rows(cap), 1)
    w = 64
    while w < min(4 * m // nb + 1, 4096):
        w *= 2
    return min(w, 4096)


@functools.lru_cache(maxsize=None)
def _probe_sorted_kernel():
    """Block-bucketed brute-force membership probe — the TPU-shaped design,
    fused with the join's pairing step.

    Measured on a v5e (100M-row slab): random O(n) gathers/scatters cost
    1-3 s and a 1M→100M searchsorted ~0.9 s, while dense elementwise
    compares run at VPU speed (~10^12 ops/s) and O(n) scans cost ~10 ms.
    So the kernel never gathers through the permutation at probe time:

      - the PRE-SORTED slab is tiled into 4096-row blocks;
      - two small searchsorteds (block boundary keys into the sorted
        source) give each block its candidate window [win_lo, win_hi);
      - each block brute-compares its 4096 keys against W window slots as
        a broadcast compare fused into three reductions (per-row any →
        t-side; valid-masked per-candidate any → s-side; per-row MIN of
        the matching candidates' original source index → the pairing) —
        ~cap*W int64 compares, a few ms of VPU time, nothing materialized;
      - a second tier re-runs the top-K widest windows at W2=4096, so
        locally clustered sources stay exact; wider-than-W2 windows set
        an overflow flag and the caller falls back to the host join.

    Outputs stay in SORTED space. One head array carries
    [multi | overflow | matched-count (4 bytes LE) | s_bits] — a single
    small fetch; the matched count sizes the O(matched) pair download
    (`_pair_compact_kernel`) without another round trip. The per-row
    first-match is the MINIMAL original source index among equal keys —
    exactly `_first_match_recovery`'s stable-tie semantics, so the fused
    path is row-identical to the host pairing."""
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(sorted_keys, sorted_valid, n, s_keys):
        cap = sorted_keys.shape[0]
        m = s_keys.shape[0]
        blk = _block_rows(cap)  # cap is static under jit; host must agree
        nb = cap // blk
        w1 = _tier1_width(cap, m)
        k2 = min(512, nb)
        # w2 must exceed blk: a block FULLY covered by source hits (a CDC
        # band upsert) has wsize >= blk plus its in-range misses
        w2 = 2 * blk
        s = s_keys.astype(sorted_keys.dtype)
        s_perm = jnp.arange(m, dtype=jnp.int32)
        s_sorted, s_perm = jax.lax.sort((s, s_perm), num_keys=1)
        keys_b = sorted_keys.reshape(nb, blk)
        valid_b = sorted_valid.reshape(nb, blk)
        # candidate windows: inclusive of boundary keys, so an equal-key
        # run crossing a block edge lands in BOTH blocks' windows. Ranges
        # clamp to REAL rows (< n): the i64.max padding tail would otherwise
        # give the boundary block a range swallowing every source key above
        # the slab maximum (sentinels included) and overflow the tiers.
        barange = jnp.arange(nb, dtype=jnp.int32)
        block_first = barange * blk
        last_real = jnp.minimum(block_first + (blk - 1), n - 1)
        block_lo_key = keys_b[:, 0]
        block_hi_key = sorted_keys[last_real]
        win_lo = jnp.searchsorted(s_sorted, block_lo_key, side="left",
                                  method="scan")
        win_hi = jnp.searchsorted(s_sorted, block_hi_key, side="right",
                                  method="scan")
        empty_block = block_first > (n - 1)
        win_hi = jnp.where(empty_block, win_lo, win_hi)
        wsize = jnp.maximum(win_hi - win_lo, 0)

        def tier(kb, vb, lo, hi, width):
            """(t_any (B, blk), t_first (B, blk), s_any (B, width),
            idx (B, width)) for the given blocks' windows, clipped/masked
            to [lo, hi). t_first is the minimal ORIGINAL source row index
            among the window's equal-key candidates, m when none."""
            idx = lo[:, None] + jnp.arange(width, dtype=lo.dtype)[None, :]
            in_win = idx < hi[:, None]
            safe = jnp.minimum(idx, m - 1)
            cand = s_sorted[safe]  # (B, width)
            # original source rows; out-of-window slots encode m so the
            # min-reduce ignores them
            cand_src = jnp.where(in_win, s_perm[safe], m)
            eq = kb[:, :, None] == cand[:, None, :]  # fused into reduces
            t_any = jnp.any(eq & in_win[:, None, :], axis=2)
            t_first = jnp.min(
                jnp.where(eq, cand_src[:, None, :], m), axis=2
            ).astype(jnp.int32)
            s_any = jnp.any(eq & vb[:, :, None], axis=1) & in_win
            return t_any, t_first, s_any, idx

        t1, f1, s1, idx1 = tier(keys_b, valid_b, win_lo, win_hi, w1)
        t_match_b = t1
        t_first_b = f1
        s_match_sorted = jnp.zeros(m, bool).at[
            jnp.minimum(idx1, m - 1).reshape(-1)
        ].max(s1.reshape(-1), mode="drop")
        if k2 > 0 and w1 < w2:
            top_w, top_b = jax.lax.top_k(wsize, k2)
            t2, f2, s2, idx2 = tier(keys_b[top_b], valid_b[top_b],
                                    win_lo[top_b], win_hi[top_b], w2)
            # tier 2 supersedes tier 1 on its blocks (windows are prefixes)
            t_match_b = t_match_b.at[top_b].set(t2)
            t_first_b = t_first_b.at[top_b].set(f2)
            s_match_sorted = s_match_sorted.at[
                jnp.minimum(idx2, m - 1).reshape(-1)
            ].max(s2.reshape(-1), mode="drop")
            in_top = jnp.zeros(nb, bool).at[top_b].set(True)
            overflow = (jnp.any((wsize > w1) & ~in_top)
                        | jnp.any(top_w > w2))
        else:
            overflow = jnp.any(wsize > w1)
        t_match_sorted = (t_match_b & valid_b).reshape(cap)
        s_first_sorted = t_first_b.reshape(cap)
        s_match = jnp.zeros(m, bool).at[s_perm].set(s_match_sorted)
        s_bits = jnp.packbits(s_match.astype(jnp.uint8))
        # multi-match: a matched key duplicated in the sorted source
        dup = jnp.concatenate([
            jnp.zeros(1, bool), s_sorted[1:] == s_sorted[:-1]
        ])
        dup = dup | jnp.concatenate([dup[1:], jnp.zeros(1, bool)])
        multi = jnp.any(dup & s_match_sorted)
        mc = jnp.sum(t_match_sorted.astype(jnp.int32))
        mc_bytes = (
            jnp.right_shift(mc, jnp.array([0, 8, 16, 24], jnp.int32)) & 0xFF
        ).astype(jnp.uint8)
        head = jnp.concatenate([
            multi.astype(jnp.uint8).reshape(1),
            overflow.astype(jnp.uint8).reshape(1),
            mc_bytes, s_bits,
        ])
        return head, t_match_sorted, s_first_sorted

    return kernel


def _decode_head(head: np.ndarray, cap_s: int, m: int):
    """Decode the probe head fetched from device: (multi, overflow,
    matched_count, s_matched[:m]). Layout documented on
    `_probe_sorted_kernel` — shared with the bench's phase decomposition
    so the two cannot drift."""
    multi = bool(head[0])
    overflow = bool(head[1])
    mc = (int(head[2]) | (int(head[3]) << 8) | (int(head[4]) << 16)
          | (int(head[5]) << 24))
    s = np.unpackbits(head[6:6 + cap_s // 8], count=cap_s)[:m].astype(bool)
    return multi, overflow, mc, s


@functools.lru_cache(maxsize=None)
def _pair_compact_kernel():
    """O(matched) pair download: compact the matched sorted-space rows into
    a dense (2, out_cap) int32 buffer of (physical row, first-match source
    row) via a cumsum + scatter — the host then fetches exactly the pairs
    instead of the whole cap/8 mask plus an O(n·log n) host pairing pass.
    ``out_cap`` is a static pow2 bucket sized from the head's matched
    count; slots past the count hold zeros (sliced off host-side)."""
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(3,))
    def kernel(t_match_sorted, s_first_sorted, perm, out_cap):
        pos = jnp.cumsum(t_match_sorted.astype(jnp.int32)) - 1
        idx = jnp.where(t_match_sorted, pos, out_cap)
        out_t = jnp.zeros(out_cap, jnp.int32).at[idx].set(perm, mode="drop")
        out_s = jnp.zeros(out_cap, jnp.int32).at[idx].set(
            s_first_sorted, mode="drop")
        return jnp.stack([out_t, out_s])

    return kernel


@functools.lru_cache(maxsize=None)
def _update_kernels():
    import jax
    import jax.numpy as jnp

    return {
        "kill": jax.jit(lambda v, r: v.at[r].set(False, mode="drop")),
        "revive": jax.jit(lambda v, r: v.at[r].set(True, mode="drop")),
        "append": jax.jit(
            lambda k, v, r, nk, nv: (
                k.at[r].set(nk.astype(k.dtype), mode="drop"),
                v.at[r].set(nv, mode="drop"),
            )
        ),
        # int32-shipped slabs widen to the kernel's int64 on device
        "widen": jax.jit(lambda k: k.astype(jnp.int64)),
        # row indices -> sorted positions through the inverse permutation;
        # padding rows (>= cap) map out of range so the next scatter drops
        "map_rows": jax.jit(
            lambda inv, r: jnp.where(
                r < inv.shape[0],
                jnp.take(inv, jnp.minimum(r, inv.shape[0] - 1)),
                inv.shape[0],
            )
        ),
        # contiguous appends skip the row-index upload entirely (start is a
        # scalar); uploaded keys may arrive int32-narrowed and cast up here
        "slice_append": jax.jit(
            lambda k, v, start, nk, nv: (
                jax.lax.dynamic_update_slice(k, nk.astype(k.dtype), (start,)),
                jax.lax.dynamic_update_slice(v, nv, (start,)),
            )
        ),
    }


class ResidentJoinKeys:
    """One table's packed join-key lane, HBM-resident with host mirrors."""

    def __init__(self, log_path: str, metadata_id: str, version: int,
                 signature: str, key_cols: List[str]):
        self.log_path = log_path
        self.metadata_id = metadata_id
        self.version = version
        self.signature = signature
        self.key_cols = key_cols
        # table rewrite generation at build time (KeyCache.bump_epoch):
        # an entry from a pre-rewrite epoch is never cached or served
        self.epoch = 0
        self.slabs: Dict[str, Tuple[int, int]] = {}  # path -> (offset, rows)
        # path -> (storageType, pathOrInlineDv, cardinality) of the deletion
        # vector whose positions are currently masked (None = no DV applied)
        self.dv_tags: Dict[str, Optional[Tuple[str, str, int]]] = {}
        self.h_keys = np.empty(0, np.int64)
        self.h_valid = np.empty(0, bool)
        # immutable per row once appended: key is non-NULL. h_valid is
        # derived: null_ok AND file alive AND not deletion-vector-deleted
        self.h_nullok = np.empty(0, bool)
        # conservative valid-key range, maintained on append (kills/DV masks
        # only shrink the valid set, so the range stays a superset): keeps
        # the per-probe sentinel/narrowing decision O(source), not O(slab)
        self.h_min = np.iinfo(np.int64).max
        self.h_max = np.iinfo(np.int64).min
        self.num_rows = 0
        self.capacity = 1024
        self._dead = 0
        self._dev = None
        self._pending = None  # batched device updates (see device_batch)
        # True when the resident sorted view (sorted_keys + perm) lags the
        # key lane: set by key appends, NOT by validity flips (DV kills and
        # revives don't change sort order). The next probe re-sorts once.
        self._sort_stale = True
        self._lock = threading.RLock()
        self.last_used = 0.0
        # device-memory accounting (gc-backstopped so a transient
        # SlabBuilder slab or popped cache entry that dies resident still
        # returns its bytes)
        from delta_tpu.obs.hbm_ledger import Account

        self._hbm = Account("keyCache")

    # -- batched device updates ------------------------------------------
    #
    # A log-tail advance touches many files (kill + revive + append per
    # file); dispatching per file costs a link round trip each — ~100ms x
    # 2 x n_files on a tunneled chip. Inside a device_batch the mutators
    # accumulate row indices and the flush issues at most three kernels.

    def device_batch(self):
        import contextlib

        @contextlib.contextmanager
        def batch():
            with self._lock:
                self._pending = {"kill": [], "revive": [],
                                 "rows": [], "keys": [], "valid": []}
            try:
                yield
            finally:
                self._flush_batch()

        return batch()

    def _flush_batch(self) -> None:
        with self._lock:
            p, self._pending = self._pending, None
            if p is None or self._dev is None:
                return  # device copy dropped mid-batch: mirrors re-ship later
            # row scatter FIRST: a file appended and DV-masked in the same
            # batch carries pre-DV validity in the scatter — the kill of its
            # masked rows must land after, never be overwritten
            if p["rows"]:
                rows = np.concatenate(p["rows"]).astype(np.int32)
                keys = np.concatenate(p["keys"]).astype(np.int64)
                valid = np.concatenate(p["valid"]).astype(bool)
                self._dev_scatter_rows(rows, keys, valid)
            if p["kill"]:
                self._dev_kill(np.concatenate(p["kill"]).astype(np.int32))
            if p["revive"]:
                self._dev_revive(np.concatenate(p["revive"]).astype(np.int32))

    # -- host-side maintenance -------------------------------------------

    def _append_file(self, path: str, keys: np.ndarray, valid: np.ndarray) -> bool:
        with self._lock:
            n = len(keys)
            if path in self.slabs:
                return False
            self.slabs[path] = (self.num_rows, n)
            self.h_keys = np.concatenate([self.h_keys, keys.astype(np.int64)])
            self.h_valid = np.concatenate([self.h_valid, valid.astype(bool)])
            self.h_nullok = np.concatenate([self.h_nullok, valid.astype(bool)])
            if valid.any():
                self.h_min = min(self.h_min, int(keys[valid].min()))
                self.h_max = max(self.h_max, int(keys[valid].max()))
            start = self.num_rows
            self.num_rows += n
            if self.num_rows > self.capacity:
                # regrow: drop device arrays; next probe re-ships the mirrors.
                # Bucketing matches join_kernel._bucket (pow2 to 4M, then 2M
                # steps) with 25% headroom, so a steady append stream (CDC
                # rounds) doesn't cross a bucket — and recompile the probe +
                # re-upload the slab — every few commits.
                from delta_tpu.ops.join_kernel import _bucket

                self._dev = None
                self._hbm.off()  # before capacity changes: bytes were old-cap
                self.capacity = max(_bucket(int(self.num_rows * 1.25)), 1024)
                return True
            if self._pending is not None:
                self._pending["rows"].append(
                    np.arange(start, start + n, dtype=np.int32))
                self._pending["keys"].append(keys.astype(np.int64))
                self._pending["valid"].append(valid.astype(bool))
            elif self._dev is not None:
                self._dev_scatter_rows(
                    np.arange(start, start + n, dtype=np.int32),
                    keys.astype(np.int64), valid.astype(bool))
            return True

    def _kill_file(self, path: str) -> None:
        with self._lock:
            ent = self.slabs.pop(path, None)
            self.dv_tags.pop(path, None)
            if ent is None:
                return
            off, rows = ent
            self.h_valid[off:off + rows] = False
            self._dead += rows
            if self._pending is not None:
                self._pending["kill"].append(
                    np.arange(off, off + rows, dtype=np.int32))
            elif self._dev is not None:
                self._dev_kill(np.arange(off, off + rows, dtype=np.int32))

    def _set_dv(self, path: str, positions: np.ndarray) -> bool:
        """Install a file's deletion-vector state EXACTLY: validity becomes
        null_ok AND NOT deleted. Handles growth, shrink (RESTORE), and
        replacement — the device gets only the diff rows, both directions.

        Returns False when the DV disagrees with the slab (positions beyond
        the recorded row count, or no slab at all): masking the mismatch
        would leave deleted rows valid and matchable, so the caller must
        rebuild the entry instead."""
        with self._lock:
            ent = self.slabs.get(path)
            if ent is None:
                return False
            off, rows = ent
            if len(positions) and int(positions.max()) >= rows:
                return False
            pos = positions
            new_valid = self.h_nullok[off:off + rows].copy()
            if len(pos):
                new_valid[pos] = False
            old_valid = self.h_valid[off:off + rows]
            diff = np.nonzero(new_valid != old_valid)[0]
            if len(diff) == 0:
                return True
            self.h_valid[off:off + rows] = new_valid
            if self._pending is not None:
                to_false = diff[~new_valid[diff]]
                to_true = diff[new_valid[diff]]
                if len(to_false):
                    self._pending["kill"].append((off + to_false).astype(np.int32))
                if len(to_true):
                    self._pending["revive"].append((off + to_true).astype(np.int32))
            elif self._dev is not None:
                to_false = diff[~new_valid[diff]]
                to_true = diff[new_valid[diff]]
                if len(to_false):
                    self._dev_kill((off + to_false).astype(np.int32))
                if len(to_true):
                    self._dev_revive((off + to_true).astype(np.int32))
            return True

    @property
    def garbage_fraction(self) -> float:
        return self._dead / max(self.num_rows, 1)

    # -- device residency -------------------------------------------------

    @property
    def device_bytes(self) -> int:
        # keys(8) + valid(1) + sorted view: sorted_keys(8) + perm(4) +
        # inv_perm(4) + sorted_valid(1)
        return self.capacity * 26

    @property
    def is_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        with self._lock:
            self._dev = None
            self._hbm.off()

    def alloc_device(self) -> None:
        """Pre-size the device arrays WITHOUT uploading the host mirrors —
        the cold-build pipeline (:class:`SlabBuilder`) then scatters each
        file's lane as it decodes, so the link transfer overlaps the
        remaining Parquet decode instead of following it. No-op when a
        device copy already exists."""
        import jax.numpy as jnp

        with self._lock:
            if self._dev is not None:
                return
            with enable_x64():
                self._dev = {
                    "keys": jnp.zeros(self.capacity, jnp.int64),
                    "valid": jnp.zeros(self.capacity, bool),
                }
            self._sort_stale = True
            self._hbm.on(self, self.device_bytes)

    def ensure_resident(self) -> None:
        """Ship the mirrors to HBM in bounded tiles (the uploads queue on
        the transfer engine and overlap, and no single transfer stalls the
        process for the whole slab)."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            if self._dev is not None:
                return
            keys = np.zeros(self.capacity, np.int64)
            keys[: self.num_rows] = self.h_keys
            valid = np.zeros(self.capacity, bool)
            valid[: self.num_rows] = self.h_valid
            # halve the big transfer when every key fits int32 (upload is
            # the whole cost of residency on a tunneled link): ship narrow,
            # cast up on device. Invalid/null rows store 0, so a raw
            # min/max scan is the exact narrowing test.
            narrow = (self.num_rows == 0 or (
                int(keys.min()) >= np.iinfo(np.int32).min
                and int(keys.max()) <= np.iinfo(np.int32).max))
            # per-transfer overhead on a tunneled link is ~0.3s regardless
            # of size; ~32MB tiles amortize it without any single transfer
            # stalling the process for the whole slab (tile counts are in
            # ELEMENTS, derived from the byte budget per dtype)
            tile_bytes = 32 << 20
            with enable_x64():
                def ship(arr):
                    step = max(tile_bytes // arr.itemsize, 1)
                    if len(arr) <= step:
                        return jax.device_put(arr)
                    return jnp.concatenate([
                        jax.device_put(arr[i:i + step])
                        for i in range(0, len(arr), step)
                    ])

                if narrow:
                    dk = _update_kernels()["widen"](ship(keys.astype(np.int32)))
                else:
                    dk = ship(keys)
                dv = ship(valid)
                jax.block_until_ready((dk, dv))
            self._dev = {"keys": dk, "valid": dv}
            self._sort_stale = True
            self._hbm.on(self, self.device_bytes)

    def _ensure_sorted(self) -> None:
        """Dispatch the slab sort if the sorted view is stale (caller holds
        the entry lock). The dispatch is async (~ms); the probe kernel that
        consumes the handles queues behind it on the device."""
        import jax
        import jax.numpy as jnp

        if self._dev is None:
            return
        if not self._sort_stale and "sorted_keys" in self._dev:
            return
        with enable_x64():
            sk, pm, inv, sv = _sort_kernel()(
                self._dev["keys"], self._dev["valid"],
                jnp.asarray(np.int32(self.num_rows)))
        self._dev["sorted_keys"] = sk
        self._dev["perm"] = pm
        self._dev["inv_perm"] = inv
        self._dev["sorted_valid"] = sv
        self._sort_stale = False

    def _dev_flip_valid(self, rows: np.ndarray, value: bool) -> None:
        """Validity flip in ROW space plus, when the sorted view is live,
        the mirrored flip in SORTED space via the resident inverse
        permutation (a k-row gather+scatter — never an O(n) rebuild)."""
        import jax.numpy as jnp

        d = _next_pow2(max(len(rows), 1), floor=64)
        padded = np.full(d, self.capacity, np.int32)
        padded[: len(rows)] = rows
        kern = _update_kernels()["kill" if not value else "revive"]
        rows_dev = jnp.asarray(padded)
        self._dev["valid"] = kern(self._dev["valid"], rows_dev)
        if not self._sort_stale and "sorted_valid" in self._dev:
            spos = _update_kernels()["map_rows"](
                self._dev["inv_perm"], rows_dev)
            self._dev["sorted_valid"] = kern(
                self._dev["sorted_valid"], spos)

    def _dev_kill(self, rows: np.ndarray) -> None:
        self._dev_flip_valid(rows, False)

    def _dev_revive(self, rows: np.ndarray) -> None:
        self._dev_flip_valid(rows, True)

    def _dev_scatter_rows(self, row_idx: np.ndarray, keys: np.ndarray,
                          valid: np.ndarray) -> None:
        import jax
        import jax.numpy as jnp

        k = len(keys)
        a = _next_pow2(max(k, 1), floor=64)
        i32 = np.iinfo(np.int32)
        kdtype = (np.int32 if len(keys) and keys.min() >= i32.min
                  and keys.max() <= i32.max else np.int64)
        nk = np.zeros(a, kdtype)
        nk[:k] = keys
        nv = np.zeros(a, bool)
        nv[:k] = valid
        contiguous = (
            k > 0
            and row_idx[0] + a <= self.capacity
            and bool((row_idx == np.arange(row_idx[0], row_idx[0] + k,
                                           dtype=row_idx.dtype)).all())
        )
        # key rows changed: the sorted view lags; drop it (frees HBM) and
        # let the next probe re-sort
        self._sort_stale = True
        for k in ("sorted_keys", "perm", "inv_perm", "sorted_valid"):
            self._dev.pop(k, None)
        with enable_x64():
            if contiguous:
                self._dev["keys"], self._dev["valid"] = (
                    _update_kernels()["slice_append"](
                        self._dev["keys"], self._dev["valid"],
                        jnp.asarray(np.int32(row_idx[0])),
                        jnp.asarray(nk), jnp.asarray(nv),
                    )
                )
                return
            rows = np.full(a, self.capacity, np.int32)
            rows[:k] = row_idx
            self._dev["keys"], self._dev["valid"] = _update_kernels()["append"](
                self._dev["keys"], self._dev["valid"],
                jnp.asarray(rows), jnp.asarray(nk), jnp.asarray(nv),
            )

    # -- probing ----------------------------------------------------------

    def probe_async(self, s_keys: np.ndarray, s_ok: np.ndarray,
                    expected_version: Optional[int] = None,
                    insert_only: bool = False) -> Optional[PendingProbe]:
        """Membership probe of sentinel-encodable source keys against the
        resident slab — fused with the join's pairing: the probe kernel also
        emits each matched slab row's first-match source index, and the
        finalize downloads the compacted O(matched) pairs instead of the
        full mask. Returns None when no sentinel room exists (valid keys
        span int64) — callers fall back to the host join.

        ``insert_only``: the caller consumes only the per-source matched
        flags (the reference's left-anti fast path) — the finalize then
        fetches the head alone and skips the pair download entirely.

        ``expected_version`` guards the advance race: a tail advance holds
        the entry lock for its whole multi-step application, so under the
        lock the slab is either fully at the caller's version or fully past
        it — never half-advanced. Past it → None (caller falls back)."""
        import jax
        import jax.numpy as jnp

        from delta_tpu.ops.join_kernel import _bucket

        with self._lock:
            if expected_version is not None and self.version != expected_version:
                return None
            n = self.num_rows
            cap = self.capacity
            if n == 0:
                m = len(s_keys)
                slabs = dict(self.slabs)
                empty = np.empty(0, np.int64)
                return PendingProbe(lambda: PhysicalProbe(
                    np.zeros(m, bool), False, slabs, 0, (empty, empty)))
            s_key64 = np.ascontiguousarray(s_keys, np.int64)
            s_okb = np.asarray(s_ok, bool)
            # O(source) sentinel/narrowing decision: the slab's valid range
            # is maintained incrementally (h_min/h_max, a conservative
            # superset), so only the source is scanned here. Narrow the
            # uploaded side to int32 when every valid key fits — the source
            # sentinel then lives in int32 space and survives the device-
            # side cast. (The slab side needs no sentinel: the sorted-probe
            # kernel applies validity in sorted space via the permutation.)
            lo = min(self.h_min, int(np.min(s_key64, where=s_okb, initial=2**62)))
            hi = max(self.h_max, int(np.max(s_key64, where=s_okb, initial=-2**62)))
            i32, i64 = np.iinfo(np.int32), np.iinfo(np.int64)
            if lo >= i32.min + 2 and hi <= i32.max - 2:
                dtype = np.int32
                s_sent = i32.max - 1
            elif hi <= i64.max - 2:
                dtype = np.int64
                s_sent = i64.max - 1
            elif lo >= i64.min + 2:
                dtype = np.int64
                s_sent = i64.min + 1
            else:
                return None  # valid keys span int64: no sentinel room
            s_enc = np.where(s_okb, s_key64, s_sent).astype(dtype)
            self.ensure_resident()
            self._ensure_sorted()
            # pin this version's arrays: jax arrays are immutable, so a
            # concurrent tail advance replaces, never mutates, these
            dev = {"sorted_keys": self._dev["sorted_keys"],
                   "sorted_valid": self._dev["sorted_valid"],
                   "perm": self._dev["perm"]}
            slabs = dict(self.slabs)
        m = len(s_enc)
        cap_s = _bucket(m)
        s_in = np.full(cap_s, s_sent, s_enc.dtype)
        s_in[:m] = s_enc
        state: dict = {}
        from delta_tpu.obs import hbm_ledger
        from delta_tpu.utils import telemetry

        # transient probe scratch (the uploaded source lane) in the HBM
        # ledger while the probe is in flight; released on the staging
        # thread, which always runs to completion
        scratch_bytes = int(s_in.nbytes)
        hbm_ledger.adjust("scratch", scratch_bytes)
        # scratch growth applies eviction pressure immediately (no cache or
        # entry lock held at this point; this probe's arrays are pinned in
        # `dev`, so even self-eviction cannot break the in-flight probe)
        hbm_ledger.maybe_relieve()
        # carry the caller's open span chain (the MERGE command span) into
        # the staging thread: the probe's device pipeline then shows up in
        # `export_chrome_trace` on its own thread lane, parented under
        # `delta.dml.merge`, instead of as an orphan root
        probe_ctx = telemetry.span_context()

        def launch():
            # the whole device pipeline runs on this staging thread so every
            # round trip (kernel, head fetch, pair compaction dispatch)
            # overlaps the caller's host-side Parquet decode; finalize only
            # joins the thread and fetches the compacted pairs
            try:
                with telemetry.adopt_span_context(probe_ctx), \
                        telemetry.record_operation(
                            "delta.merge.deviceProbe",
                            {"slabRows": int(n), "sourceRows": int(m),
                             "insertOnly": insert_only}):
                    with enable_x64():
                        head_dev, t_match_dev, s_first_dev = _probe_sorted_kernel()(
                            dev["sorted_keys"], dev["sorted_valid"],
                            jnp.asarray(np.int32(n)), jax.device_put(s_in),
                        )
                        head = np.asarray(head_dev)  # blocks until kernel done
                        state["head"] = head
                        _multi, overflow, mc, _s = _decode_head(head, cap_s, m)
                        if overflow or insert_only or mc == 0:
                            return
                        out_cap = _next_pow2(mc, floor=64)
                        state["pairs_dev"] = _pair_compact_kernel()(
                            t_match_dev, s_first_dev, dev["perm"], out_cap)
            except BaseException as e:
                state["err"] = e
            finally:
                hbm_ledger.adjust("scratch", -scratch_bytes)

        th = threading.Thread(target=launch, daemon=True,
                              name="delta-merge-device-probe")
        th.start()

        def finalize() -> PhysicalProbe:
            th.join()
            if "err" in state:
                raise state["err"]
            multi, overflow, mc, s = _decode_head(state["head"], cap_s, m)
            if overflow:
                # candidate window overflowed both tiers (pathologically
                # skewed source): the mask would be incomplete — callers
                # fall back to the host join
                raise DeltaProbeOverflow(
                    "probe candidate window overflow; host fallback")
            if insert_only:
                # left-anti fast path: the head already carried everything
                return PhysicalProbe(s, multi, slabs, n, None)
            if mc == 0:
                empty = np.empty(0, np.int64)
                return PhysicalProbe(s, multi, slabs, n, (empty, empty))
            pairs = np.asarray(state["pairs_dev"])
            phys = pairs[0, :mc].astype(np.int64)
            srows = pairs[1, :mc].astype(np.int64)
            order = np.argsort(phys, kind="stable")
            phys, srows = phys[order], srows[order]
            return PhysicalProbe(s, multi, slabs, n, (phys, srows))

        return PendingProbe(finalize)


# -- building / advancing ----------------------------------------------------


def _file_keys(data_path: str, add, key_cols: List[str], exprs) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Evaluate the packed target key lane over a file's PHYSICAL rows
    (no DV filtering; DV positions are masked invalid separately)."""
    import os
    import urllib.parse

    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.expr.vectorized import evaluate

    path = add.path
    if "://" in path or os.path.isabs(path):
        abs_path = urllib.parse.unquote(path)
    else:
        abs_path = os.path.join(
            data_path, urllib.parse.unquote(path).replace("/", os.sep))
    try:
        pf = pq.ParquetFile(abs_path, memory_map=True)
        present = [c for c in key_cols if c in pf.schema_arrow.names]
        if len(present) != len(key_cols):
            return None
        tab = pf.read(columns=present)
    except Exception:
        return None
    return _pack_lanes(tab, exprs, evaluate)


def _pack_lanes(tab, exprs, evaluate) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    import pyarrow as pa
    import pyarrow.compute as pc

    lanes = []
    for e in exprs:
        try:
            vals = evaluate(e, tab)
        except Exception:
            return None
        arr = vals.combine_chunks() if isinstance(vals, pa.ChunkedArray) else vals
        if not pa.types.is_integer(arr.type):
            return None
        valid = ~np.asarray(pc.is_null(arr))
        keys = np.asarray(arr.fill_null(0).cast(pa.int64()))
        lanes.append((keys, valid))
    if len(lanes) == 1:
        return lanes[0]
    if len(lanes) != 2:
        return None
    i32 = np.iinfo(np.int32)
    (k0, v0), (k1, v1) = lanes
    ok = v0 & v1
    if (np.min(k0, where=ok, initial=0) < i32.min
            or np.max(k0, where=ok, initial=0) > i32.max
            or np.min(k1, where=ok, initial=0) < i32.min
            or np.max(k1, where=ok, initial=0) > i32.max):
        return None
    return (k0 << 32) | (k1 & 0xFFFFFFFF), ok


def _dv_tag(dv_dict) -> Optional[Tuple[str, str, int]]:
    if not dv_dict:
        return None
    return (dv_dict.get("storageType"), dv_dict.get("pathOrInlineDv"),
            int(dv_dict.get("cardinality", -1)))


def _dv_positions(dv_dict, data_path: str) -> Optional[np.ndarray]:
    from delta_tpu.protocol.deletion_vectors import (
        DeletionVectorDescriptor, read_deletion_vector,
    )

    try:
        return read_deletion_vector(
            DeletionVectorDescriptor.from_dict(dv_dict), data_path)
    except Exception:
        return None


class SlabBuilder:
    """Streamed cold build of a :class:`ResidentJoinKeys` slab from per-file
    decoded key tables — the upload leg of the fused device MERGE pipeline
    (`commands/merge.py`). Files arrive in decode-completion order; each
    file's packed lane scatters straight onto a pre-sized HBM allocation
    (a contiguous slice append), so the link transfer overlaps the
    remaining Parquet decode instead of following it.

    Slab layout must be exact per file even though the decode arrives
    DV-filtered: per-file PHYSICAL row counts come from AddFile stats
    (``numRecords`` is physical as this engine writes it; logical ==
    physical when no deletion vector) or the cached Parquet footer when a
    deletion vector is present or stats are absent."""

    def __init__(self, log_path: str, metadata_id: str, version: int,
                 signature: str, key_cols: List[str], exprs,
                 data_path: str, files, device: bool = True, epoch: int = 0):
        from delta_tpu.ops.join_kernel import _bucket

        self.exprs = list(exprs)
        self.data_path = data_path
        self.failed: Optional[str] = None
        self.device = device
        self._alloc_failed = False
        self._phys: Dict[str, int] = {}
        total = 0
        for add in files:
            nrec = add.num_logical_records
            if add.deletion_vector is not None or nrec is None:
                n = self._footer_rows(add)
                if n is None:
                    self.failed = f"no physical row count for {add.path}"
                    break
            else:
                n = int(nrec)
            self._phys[add.path] = n
            total += n
        entry = ResidentJoinKeys(log_path, metadata_id, version, signature,
                                 list(key_cols))
        entry.epoch = epoch
        entry.capacity = max(_bucket(max(total, 1)), 1024)
        self.entry = entry

    def _footer_rows(self, add) -> Optional[int]:
        from delta_tpu.exec import rowgroups
        from delta_tpu.exec.scan import _abs_data_path

        try:
            return int(rowgroups.read_footer(
                _abs_data_path(self.data_path, add.path)).num_rows)
        except Exception:
            return None

    def add_file(self, add, table, positions: Optional[np.ndarray]) -> bool:
        """Pack one decoded file's key lane and append+upload it.
        ``positions`` are the decoded rows' physical positions (None when
        the decode was not DV-filtered). Any disagreement with the recorded
        physical row count poisons the build (the merge falls back to its
        other executors)."""
        if self.failed is not None:
            return False
        from delta_tpu.expr.vectorized import evaluate

        phys = self._phys.get(add.path)
        packed = _pack_lanes(table, self.exprs, evaluate)
        if phys is None or packed is None:
            self.failed = f"unpackable key lane for {add.path}"
            return False
        keys, valid = packed
        if positions is None:
            if len(keys) != phys:
                self.failed = f"row count mismatch for {add.path}"
                return False
            full_k = np.ascontiguousarray(keys, np.int64)
            full_v = np.asarray(valid, bool)
        else:
            if len(positions) != len(keys) or (
                    len(positions) and int(positions[-1]) >= phys):
                self.failed = f"position/physical mismatch for {add.path}"
                return False
            full_k = np.zeros(phys, np.int64)
            full_v = np.zeros(phys, bool)
            full_k[positions] = keys
            full_v[positions] = valid
        e = self.entry
        if self.device and e._dev is None and not self._alloc_failed:
            try:
                e.alloc_device()
            except Exception:
                self._alloc_failed = True  # host mirrors still work
        if not e._append_file(add.path, full_k, full_v):
            self.failed = f"duplicate file {add.path}"
            return False
        e.dv_tags[add.path] = _dv_tag(add.deletion_vector)
        return True

    def finish(self, expected_files: int) -> Optional[ResidentJoinKeys]:
        if self.failed is not None or len(self.entry.slabs) != expected_files:
            return None
        return self.entry


class KeyCache:
    """Process-wide registry of resident join-key lanes, keyed by
    (log path, signature). Mirrors `DeviceStateCache`'s locking: registry
    lock for lookups, per-entry build locks for the slow work."""

    _instance: Optional["KeyCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._entries: Dict[Tuple[str, str], ResidentJoinKeys] = {}
        self._build_locks: Dict[Tuple[str, str], threading.Lock] = {}
        self._lock = threading.RLock()
        self._tick = 0
        # per-table rewrite generation (bump_epoch): entries built under an
        # older epoch are never served or cached
        self._epochs: Dict[str, int] = {}
        # tables whose residency gauge was last published non-zero, so a
        # full drop publishes an explicit 0 (see _publish_residency), and
        # the last value published per table (unchanged values skip the
        # telemetry lock)
        self._last_resident: set = set()
        self._published_bytes: Dict[str, int] = {}

    @classmethod
    def instance(cls) -> "KeyCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = KeyCache()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            cls._instance = None

    def invalidate(self, log_path: str) -> None:
        with self._lock:
            for k in [k for k in self._entries if k[0] == log_path]:
                e = self._entries.pop(k, None)
                self._build_locks.pop(k, None)
                if e is not None:
                    e.drop_device()  # return its bytes to the HBM ledger
        self._publish_residency()

    def epoch(self, log_path: str) -> int:
        with self._lock:
            return self._epochs.get(log_path, 0)

    def bump_epoch(self, log_path: str) -> None:
        """File-rewrite invalidation (OPTIMIZE / UPDATE-rewrite / RESTORE):
        drop the table's resident entries outright — a stale slab must never
        serve a post-rewrite MERGE, and after a rewrite most of the slab is
        garbage anyway (an advance would kill + re-append nearly every
        row). In-flight holders of a dropped entry fail their version guard:
        the version is poisoned before release."""
        from delta_tpu.utils.telemetry import bump_counter

        with self._lock:
            self._epochs[log_path] = self._epochs.get(log_path, 0) + 1
            stale = [k for k in self._entries if k[0] == log_path]
            for k in stale:
                e = self._entries.pop(k)
                e.version = _POISON_VERSION
                self._build_locks.pop(k, None)
                e.drop_device()  # return its bytes to the HBM ledger
        if stale:
            bump_counter("merge.keyCache.invalidations", len(stale))
            self._publish_residency()

    def register(self, entry: ResidentJoinKeys) -> bool:
        """Adopt an externally built slab (the merge cold pipeline's
        :class:`SlabBuilder` output) so later MERGEs against the table
        cache-hit. Refused when the table's epoch moved during the build (a
        rewrite raced it) or a newer entry already holds the key — the
        caller's probe of the transient entry stays valid either way."""
        from delta_tpu.utils.telemetry import bump_counter

        if not key_cache_enabled():
            return False
        key = (entry.log_path, entry.signature)
        with self._lock:
            if entry.epoch != self._epochs.get(entry.log_path, 0):
                return False
            cur = self._entries.get(key)
            if cur is not None and cur.version >= entry.version:
                return False
            self._tick += 1
            entry.last_used = self._tick
            self._entries[key] = entry
            self._build_locks.setdefault(key, threading.Lock())
        bump_counter("merge.keyCache.builds")  # inline cold build adopted
        self._evict(keep=key)
        return True

    def peek(self, log_path: str, signature: str) -> Optional[ResidentJoinKeys]:
        with self._lock:
            return self._entries.get((log_path, signature))

    def get(self, snapshot, signature: str, key_cols: List[str],
            exprs, build_if_missing: bool = True) -> Optional[ResidentJoinKeys]:
        """Entry current at the snapshot's version, advancing incrementally
        through the log tail (appending new files' keys, killing removed
        files, masking DV growth). ``build_if_missing=False`` only serves /
        advances an existing entry — the cold build policy stays with the
        caller (merge builds in the background after an eligible merge)."""
        from delta_tpu.utils.telemetry import bump_counter

        if not key_cache_enabled():
            return None
        log_path = snapshot.delta_log.log_path
        key = (log_path, signature)
        with self._lock:
            self._tick += 1
            tick = self._tick
            cur_epoch = self._epochs.get(log_path, 0)
            build_lock = self._build_locks.setdefault(key, threading.Lock())
            e = self._entries.get(key)
        if e is not None and (e.metadata_id != snapshot.metadata.id
                              or e.version > snapshot.version
                              or e.epoch != cur_epoch):
            e = None
        if e is not None and e.version == snapshot.version:
            e.last_used = tick
            return e
        if e is None and not build_if_missing:
            return None
        with build_lock:
            with self._lock:
                cur_epoch = self._epochs.get(log_path, 0)
                e = self._entries.get(key)
            if e is not None and (e.metadata_id != snapshot.metadata.id
                                  or e.version > snapshot.version
                                  or e.epoch != cur_epoch):
                e = None
            if e is not None and e.version == snapshot.version:
                e.last_used = tick
                return e
            if e is not None:
                if self._advance(e, snapshot, key_cols, exprs):
                    bump_counter("merge.keyCache.advances")
                else:
                    # a failed advance may have half-applied its tail: the
                    # entry must not stay visible at its (stale) version
                    with self._lock:
                        if self._entries.get(key) is e:
                            self._entries.pop(key, None)
                    e = None
            if e is None:
                if not build_if_missing:
                    return None
                e = self._build(snapshot, signature, key_cols, exprs,
                                epoch=cur_epoch)
                if e is None:
                    return None
                bump_counter("merge.keyCache.builds")
                with self._lock:
                    # a rewrite may have raced the build: the entry stays
                    # exact for the caller's snapshot (file contents are
                    # immutable), so serve it — but only CACHE it when the
                    # epoch still matches
                    if self._epochs.get(log_path, 0) == cur_epoch:
                        self._entries[key] = e
            e.last_used = tick
            self._evict(keep=key)
            return e

    def _build(self, snapshot, signature, key_cols, exprs,
               epoch: int = 0) -> Optional[ResidentJoinKeys]:
        e = ResidentJoinKeys(
            snapshot.delta_log.log_path, snapshot.metadata.id,
            snapshot.version, signature, list(key_cols),
        )
        e.epoch = epoch
        data_path = snapshot.delta_log.data_path
        for add in snapshot.all_files:
            kv = _file_keys(data_path, add, key_cols, exprs)
            if kv is None:
                return None
            keys, valid = kv
            e._append_file(add.path, keys, valid)
            if add.deletion_vector is not None:
                pos = _dv_positions(add.deletion_vector, data_path)
                if pos is None:
                    return None
                if not e._set_dv(add.path, pos):
                    return None
                e.dv_tags[add.path] = _dv_tag(add.deletion_vector)
        return e

    def _advance(self, e: ResidentJoinKeys, snapshot, key_cols, exprs) -> bool:
        """Apply the log tail (e.version, snapshot.version]."""
        from delta_tpu.log.columnar import decode_segment
        from delta_tpu.protocol import filenames
        from delta_tpu.protocol.actions import AddFile, Metadata, RemoveFile

        if e.garbage_fraction > 0.5 and e.num_rows > 1 << 20:
            return False  # too much garbage: rebuild compacts
        log = snapshot.delta_log
        paths = [
            f"{log.log_path}/{filenames.delta_file(v)}"
            for v in range(e.version + 1, snapshot.version + 1)
        ]
        try:
            cols = decode_segment(log.store, [], paths)
        except Exception:
            return False
        if any(isinstance(a, Metadata) for a in cols.other_actions):
            return False
        w = cols.winner_mask()
        actions = cols.materialize(w)
        data_path = log.data_path
        # hold the ENTRY lock across the whole multi-step application (and
        # the version bump): a concurrent probe then sees the slab either
        # fully at its version or fully past it, never in between
        with e._lock, e.device_batch():
            # poison a half-applied tail BEFORE releasing the entry lock —
            # on clean failure AND on exceptions (a raise would otherwise
            # bypass get()'s pop and leave the entry serving probes at its
            # old version with some files killed and others not appended)
            ok = False
            try:
                for a in actions:
                    if isinstance(a, RemoveFile):
                        e._kill_file(a.path)
                    elif isinstance(a, AddFile):
                        if a.path not in e.slabs:
                            kv = _file_keys(data_path, a, key_cols, exprs)
                            if kv is None:
                                return False
                            if not e._append_file(a.path, *kv):
                                return False
                        # re-adds keep their keys (physical rows are
                        # immutable); only the DV validity may change
                        new_tag = _dv_tag(a.deletion_vector)
                        if e.dv_tags.get(a.path) != new_tag:
                            if a.deletion_vector is not None:
                                pos = _dv_positions(a.deletion_vector, data_path)
                                if pos is None:
                                    return False
                            else:
                                pos = np.empty(0, np.int64)
                            if not e._set_dv(a.path, pos):
                                return False
                            e.dv_tags[a.path] = new_tag
                ok = True
                return True
            finally:
                # poison ABOVE any real version: get()'s `e.version >
                # snapshot.version` staleness guard then discards the entry
                # in O(1) instead of attempting a from-zero tail decode
                e.version = snapshot.version if ok else _POISON_VERSION

    def _publish_residency(self) -> None:
        """Per-table ``keyCache.residentBytes`` gauges for the fleet plane
        (label: hashed table path). Runs only on mutation paths (build /
        advance / evict / invalidate / epoch bump — pure cache hits return
        before ``_evict``); unchanged values skip the telemetry lock, and
        tables whose last entry just dropped publish an explicit 0 so
        scraped series show the release."""
        from delta_tpu.obs.fleet import table_label
        from delta_tpu.utils.telemetry import set_gauge

        with self._lock:
            by_table: Dict[str, int] = {t: 0 for t in self._last_resident}
            for (log_path, _sig), e in self._entries.items():
                if e.is_resident:
                    table = log_path[:-len("/_delta_log")] \
                        if log_path.endswith("/_delta_log") else log_path
                    by_table[table] = by_table.get(table, 0) + e.device_bytes
            self._last_resident = {t for t, b in by_table.items() if b}
            changed = {t: b for t, b in by_table.items()
                       if self._published_bytes.get(t) != b}
            self._published_bytes.update(changed)
            # published under the lock: two racing mutators (a drop and a
            # register) must not land their gauge writes out of order and
            # leave a stale value standing
            for table, total in changed.items():
                set_gauge("keyCache.residentBytes", total,
                          table=table_label(table))

    def _evict(self, keep) -> None:
        budget = int(conf.get("delta.tpu.keyCache.maxBytes", 1 << 30))
        # the process-wide device-memory soft budget (obs/hbm_ledger): the
        # key cache yields to state-cache lanes and in-flight scratch, so
        # growth anywhere becomes LRU pressure here instead of OOM
        from delta_tpu.obs import hbm_ledger

        allowance = hbm_ledger.key_cache_allowance()
        if allowance is not None:
            budget = min(budget, allowance)
        with self._lock:
            resident = [(k, e) for k, e in self._entries.items() if e.is_resident]
            total = sum(e.device_bytes for _, e in resident)
            for k, e in sorted(resident, key=lambda kv: kv[1].last_used):
                if total <= budget:
                    break
                if k == keep:
                    continue
                e.drop_device()
                total -= e.device_bytes
            max_entries = int(conf.get("delta.tpu.keyCache.maxEntries", 8))
            if len(self._entries) > max_entries:
                for k, e in sorted(self._entries.items(),
                                   key=lambda kv: kv[1].last_used):
                    if k == keep:
                        continue
                    self._entries.pop(k, None)
                    self._build_locks.pop(k, None)
                    e.drop_device()  # return its bytes to the HBM ledger
                    if len(self._entries) <= max_entries:
                        break
        self._publish_residency()
