"""LogStore contract tests (≈ ``LogStoreSuite``): atomic visibility, mutual
exclusion, sorted listing — including real multi-thread conflict detection."""
import os
import threading

import pytest

from delta_tpu.storage.logstore import (
    FileStatus,
    LocalLogStore,
    MemoryLogStore,
    ObjectStoreLogStore,
)


@pytest.fixture(params=["local", "memory", "objectstore"])
def store_and_root(request, tmp_path):
    if request.param == "local":
        return LocalLogStore(), str(tmp_path)
    if request.param == "memory":
        return MemoryLogStore(), "/mem/tbl"
    return ObjectStoreLogStore(LocalLogStore()), str(tmp_path)


def test_read_write(store_and_root):
    store, root = store_and_root
    p = f"{root}/_delta_log/00000000000000000000.json"
    store.write(p, ["zero", "none"])
    assert store.read(p) == ["zero", "none"]
    assert store.exists(p)


def test_write_no_overwrite_fails(store_and_root):
    store, root = store_and_root
    p = f"{root}/_delta_log/00000000000000000000.json"
    store.write(p, ["first"])
    with pytest.raises(FileExistsError):
        store.write(p, ["second"])
    assert store.read(p) == ["first"]
    store.write(p, ["third"], overwrite=True)
    assert store.read(p) == ["third"]


def test_list_from_sorted(store_and_root):
    store, root = store_and_root
    base = f"{root}/_delta_log"
    for v in (2, 0, 1, 10):
        store.write(f"{base}/{'%020d' % v}.json", [str(v)])
    names = [s.name for s in store.list_from(f"{base}/{'%020d' % 1}.json")]
    assert names == [
        "00000000000000000001.json",
        "00000000000000000002.json",
        "00000000000000000010.json",
    ]


def test_list_from_missing_dir_raises(store_and_root):
    store, root = store_and_root
    with pytest.raises(FileNotFoundError):
        list(store.list_from(f"{root}/nonexistent/00000000000000000000.json"))


def test_concurrent_writers_exactly_one_wins(store_and_root):
    """Mutual exclusion under real threads (≈ LogStoreSuite 'detects conflict')."""
    store, root = store_and_root
    p = f"{root}/_delta_log/00000000000000000001.json"
    barrier = threading.Barrier(8)
    results = []
    lock = threading.Lock()

    def writer(i):
        barrier.wait()
        try:
            store.write(p, [f"writer-{i}"])
            with lock:
                results.append(("ok", i))
        except FileExistsError:
            with lock:
                results.append(("conflict", i))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [r for r in results if r[0] == "ok"]
    assert len(wins) == 1, f"expected exactly one winner, got {results}"
    winner = wins[0][1]
    assert store.read(p) == [f"writer-{winner}"]


def test_local_store_no_temp_droppings(tmp_path):
    store = LocalLogStore()
    p = str(tmp_path / "_delta_log" / "00000000000000000000.json")
    store.write(p, ["x"])
    with pytest.raises(FileExistsError):
        store.write(p, ["y"])
    leftovers = [n for n in os.listdir(tmp_path / "_delta_log") if n.endswith(".tmp")]
    assert leftovers == []


def test_object_store_partial_write_invisible_flag(tmp_path):
    assert ObjectStoreLogStore(LocalLogStore()).is_partial_write_visible("x") is False
    assert LocalLogStore().is_partial_write_visible("x") is True


def test_memory_store_fault_injection():
    store = MemoryLogStore()
    seen = []
    store.before_write = lambda p: seen.append(p)
    store.write("/t/_delta_log/f", ["1"])
    assert seen == ["/t/_delta_log/f"]
    store.set_mtime("/t/_delta_log/f", 42)
    (status,) = list(store.list_from("/t/_delta_log/"))
    assert status.modification_time == 42
