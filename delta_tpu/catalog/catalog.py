"""Name-based table catalog.

The reference resolves table *names* through Spark's DSv2 catalog plugin
(`catalog/DeltaCatalog.scala:57`, `DeltaTableV2.scala:50`), backed by a
metastore. This engine has no metastore; the equivalent is a small
name→path registry with optional JSON-file persistence, giving the API
surface (`DeltaTable.for_name`, CREATE/DROP by name) without path-typing
every call site.

Identifiers are case-insensitive, optionally qualified (``db.table``; the
default database is ``default``). ``delta.`/abs/path``` identifiers resolve
directly to paths, mirroring the reference's path-table escape hatch
(`DeltaTableIdentifier.scala`).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence

from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["Catalog", "default_catalog", "resolve_identifier"]


def _normalize(name: str) -> str:
    parts = [p.strip().strip("`") for p in name.split(".")]
    if len(parts) == 1:
        parts = ["default"] + parts
    if len(parts) != 2 or not all(parts):
        raise errors.invalid_table_identifier(name)
    return ".".join(p.lower() for p in parts)


class Catalog:
    """name → path registry; optionally persisted as a JSON file so
    multiple processes share one namespace."""

    def __init__(self, store_path: Optional[str] = None):
        self._store_path = store_path
        self._tables: Dict[str, str] = {}
        # in-flight CREATE claims: name → {path, pid, host, ts_ms}. Kept out
        # of ``_tables`` so lookups never resolve a half-created table.
        self._claims: Dict[str, Dict] = {}
        self._lock = threading.RLock()
        if store_path and os.path.exists(store_path):
            self._load()

    # -- persistence ------------------------------------------------------
    #
    # Cross-process safety: every load-mutate-save cycle holds an OS file
    # lock (flock on <store>.lock) in addition to the in-process RLock, so
    # two processes registering tables concurrently cannot lose a write
    # (the in-process lock alone only orders threads).

    def _file_lock(self):
        import contextlib

        if not self._store_path:
            return contextlib.nullcontext()

        import fcntl

        @contextlib.contextmanager
        def locked():
            os.makedirs(os.path.dirname(self._store_path) or ".", exist_ok=True)
            with open(self._store_path + ".lock", "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)

        return locked()

    def _load(self) -> None:
        try:
            with open(self._store_path) as f:
                data = json.load(f)
            self._tables = dict(data.get("tables", {}))
            self._claims = dict(data.get("claims", {}))
        except (OSError, json.JSONDecodeError):
            self._tables = {}
            self._claims = {}

    def _save(self) -> None:
        if not self._store_path:
            return
        os.makedirs(os.path.dirname(self._store_path) or ".", exist_ok=True)
        tmp = self._store_path + ".tmp"
        try:
            # delta-lint: ignore[lock-blocking] -- catalog persistence is a
            # read-modify-write; the mutex must span the staged JSON write
            with open(tmp, "w") as f:
                json.dump({"tables": self._tables, "claims": self._claims},
                          f, indent=1, sort_keys=True)
            os.replace(tmp, self._store_path)
        finally:
            try:
                os.unlink(tmp)  # no-op after a successful replace
            except OSError:
                pass

    def _claim_is_live(self, claim: Dict) -> bool:
        """Is an in-flight CREATE claim still owned by a live creator?

        Same-host claims are checked by pid liveness; foreign-host claims (a
        shared store on network storage) fall back to an age bound
        (``delta.tpu.catalog.claimTimeoutMs``) — a creator that takes longer
        forfeits the name."""
        import socket
        import time

        timeout_ms = int(conf.get("delta.tpu.catalog.claimTimeoutMs", 600_000))
        within_age = (time.time() * 1000 - claim.get("ts_ms", 0)) < timeout_ms
        if claim.get("host") == socket.gethostname():
            pid = claim.get("pid")
            if pid == os.getpid():
                return True
            try:
                os.kill(int(pid), 0)
                alive = True
            except ProcessLookupError:
                alive = False  # definitely gone
            except PermissionError:
                alive = True  # exists, owned by another user
            except (OSError, TypeError, ValueError):
                alive = True  # unknown: never hijack on doubt
            # age bound also applies same-host: a recycled pid would
            # otherwise block the name forever
            return alive and within_age
        return within_age

    def _new_claim(self, path: str) -> Dict:
        import socket
        import time

        return {"path": path, "pid": os.getpid(),
                "host": socket.gethostname(), "ts_ms": int(time.time() * 1000)}

    # -- registry ---------------------------------------------------------

    def register(self, name: str, path: str) -> None:
        """Point ``name`` at an existing table location (external table)."""
        key = _normalize(name)
        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            if key in self._tables:
                raise errors.table_already_exists_in_catalog(name)
            claim = self._claims.get(key)
            if claim is not None and self._claim_is_live(claim):
                raise errors.table_being_created_concurrently(name)
            self._claims.pop(key, None)
            self._tables[key] = os.path.abspath(path)
            self._save()

    def create_table(self, name: str, path: str, schema=None,
                     partition_columns: Sequence[str] = (),
                     configuration=None, data=None, mode: str = "create"):
        """CREATE TABLE by name: registers the identifier and runs the
        create command at ``path`` (`DeltaCatalog.createTable :183`)."""
        from delta_tpu.api.tables import DeltaTable

        key = _normalize(name)
        abs_path = os.path.abspath(path)
        # Claim the name inside the first critical section, then run the
        # (possibly long) CTAS/create outside the lock so unrelated catalog
        # operations aren't serialized behind data writes. A concurrent
        # creator of the same name fails BEFORE materializing any data (no
        # orphan table directory). Claims live in a separate map carrying
        # owner liveness (pid/host/ts), so a crashed creator's claim is
        # reclaimable while a live in-progress one blocks the race — and
        # lookups never resolve a name whose table hasn't committed yet.
        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            if mode == "create":
                if key in self._tables:
                    raise errors.table_already_exists_in_catalog(name)
                claim = self._claims.get(key)
                if claim is not None and self._claim_is_live(claim):
                    raise errors.table_being_created_concurrently(name)
            my_claim = self._new_claim(abs_path)
            self._claims[key] = my_claim
            self._save()

        def _release(register_table: bool):
            with self._lock, self._file_lock():
                if self._store_path:
                    self._load()
                cur = self._claims.get(key)
                if cur and cur.get("pid") == my_claim["pid"] and cur.get("ts_ms") == my_claim["ts_ms"]:
                    self._claims.pop(key, None)
                if register_table:
                    self._tables[key] = abs_path
                self._save()

        try:
            table = DeltaTable.create(
                path, schema, partition_columns, configuration, data, mode=mode
            )
        except BaseException:
            _release(register_table=False)
            raise
        _release(register_table=True)
        return table

    def drop_table(self, name: str) -> None:
        """Remove the name mapping (the data/log stay on disk, like dropping
        an external table)."""
        key = _normalize(name)
        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            if key not in self._tables:
                raise errors.table_not_found_in_catalog(name)
            del self._tables[key]
            self._save()

    def table_path(self, name: str) -> str:
        key = _normalize(name)
        with self._lock:
            if self._store_path:
                self._load()
            path = self._tables.get(key)
        if path is None:
            raise errors.table_not_found_in_catalog(name)
        return path

    def table_exists(self, name: str) -> bool:
        try:
            self.table_path(name)
            return True
        except DeltaAnalysisError:
            return False

    def load_table(self, name: str):
        from delta_tpu.api.tables import DeltaTable

        return DeltaTable.for_path(self.table_path(name))

    def list_tables(self, database: str = "default"):
        with self._lock:
            if self._store_path:
                self._load()
            prefix = database.lower() + "."
            return sorted(
                k[len(prefix):] for k in self._tables if k.startswith(prefix)
            )


_default: Optional[Catalog] = None
_default_lock = threading.Lock()


def default_catalog() -> Catalog:
    """Process-default catalog; persists to ``delta.tpu.catalog.path`` when
    that conf is set, else stays in-memory."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Catalog(conf.get("delta.tpu.catalog.path"))
        return _default


def reset_default_catalog() -> None:
    global _default
    with _default_lock:
        _default = None


def resolve_identifier(identifier: str, catalog: Optional[Catalog] = None) -> str:
    """``delta.`/path``` → the path; anything else → catalog lookup."""
    ident = identifier.strip()
    if ident.lower().startswith("delta.`") and ident.endswith("`"):
        return ident[len("delta.`"):-1]
    return (catalog or default_catalog()).table_path(ident)
