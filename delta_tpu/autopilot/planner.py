"""Autopilot planning — turn doctor verdicts + advisor recommendations into
an ordered, guardrail-filtered list of :class:`~delta_tpu.obs.actions.
MaintenanceAction`\\ s.

Both input surfaces already speak the shared action catalog
(`obs/actions.py`): the doctor's per-dimension ``remedy`` and the advisor's
per-recommendation ``remedy`` are catalog keys, so planning is a mapping
walk, not string matching. The planner is pure decision logic — it reads
reports and the persistent action ledger (journal kind ``autopilot``) and
never touches the table; `delta_tpu/autopilot/executor.py` acts.

Guardrail inputs computed here:

* **cooldowns** — an action key ATTEMPTED (started/executed/failed/
  interrupted/abortedContention) inside ``delta.tpu.autopilot.cooldownMs``
  is not re-planned. "Started" entries are flushed to disk before
  execution, so a crash mid-maintenance still arms the cooldown — the
  crash-loop guard.
* **contention backoff** — any ``abortedContention`` ledger entry inside
  ``delta.tpu.autopilot.contentionBackoffMs`` blocks the whole table.
* **quiet window** — the journal's recent commit entries, bucketed the
  same way the advisor buckets contention (60s windows): the table is
  quiet when at most ``quietMaxCommits`` foreground commits landed inside
  the last ``quietWindowMs``. Maintenance operations (OPTIMIZE/REORG/
  RESTORE) don't count — the autopilot's own commits must not un-quiet
  the window for its next tick.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from delta_tpu.obs import journal as journal_mod
from delta_tpu.obs.actions import (
    CATALOG,
    COOLDOWN_PHASES,
    MaintenanceAction,
    RECOMMENDATION_ACTIONS,
    attempts_in_cooldown,
)
from delta_tpu.obs.doctor import SEVERITY_RANK
from delta_tpu.utils.config import conf

__all__ = ["plan", "quiet_window", "ledger_entries", "cooldown_blocked",
           "contention_backoff_until", "shadow_gate", "COOLDOWN_PHASES"]

#: commit operation names that are maintenance, not foreground traffic
_MAINTENANCE_OPS = frozenset({"OPTIMIZE", "REORG", "VACUUM"})

#: advisor recommendation kinds the autopilot executes (the rest are
#: conf/schema changes — surfaced, never auto-applied)
_EXECUTABLE_REC_KINDS = frozenset(
    {"ZORDER", "CHECKPOINT_INTERVAL", "CALIBRATION"})


def ledger_entries(log_path: str) -> List[Dict[str, Any]]:
    """The table's persisted action ledger, oldest first."""
    journal_mod.flush(log_path)
    return journal_mod.read_entries(log_path, kinds=["autopilot"])


def cooldown_blocked(ledger: List[Dict[str, Any]], now_ms: int,
                     log_path: Optional[str] = None
                     ) -> Dict[str, Dict[str, Any]]:
    """Action keys inside their cooldown, mapped to the arming entry —
    the shared `obs/actions.attempts_in_cooldown` rule (the same one the
    advisor's suppression runs), so the two surfaces can never drift.
    With ``log_path``, the sweep-proof sidecar is merged in: a ledger
    segment evicted by the journal's size/age sweep must not un-arm a
    cooldown."""
    cooldown = conf.get_int("delta.tpu.autopilot.cooldownMs", 6 * 3_600_000)
    state = journal_mod.attempt_state(log_path) if log_path else None
    return attempts_in_cooldown(ledger, now_ms, cooldown, state=state)


def contention_backoff_until(ledger: List[Dict[str, Any]], now_ms: int,
                             log_path: Optional[str] = None
                             ) -> Optional[int]:
    """End of the table-wide backoff armed by the last abortedContention
    attempt (ledger + sweep-proof sidecar), or None when none is active."""
    backoff = conf.get_int("delta.tpu.autopilot.contentionBackoffMs", 300_000)
    latest = 0
    for e in ledger:
        if e.get("phase") == "abortedContention":
            latest = max(latest, int(e.get("ts") or 0))
    if log_path is not None:
        for st in journal_mod.attempt_state(log_path).values():
            if st.get("phase") == "abortedContention":
                latest = max(latest, int(st.get("ts") or 0))
    until = latest + backoff
    return until if latest and until > now_ms else None


def quiet_window(log_path: str, now_ms: int,
                 commits: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """Is the table quiet right now? Counts journaled foreground commits
    whose ``ts`` falls inside the trailing window. ``commits`` lets a
    caller that already parsed the journal (the daemon reads it once per
    pass) skip the re-read."""
    window_ms = conf.get_int("delta.tpu.autopilot.quietWindowMs", 60_000)
    max_commits = conf.get_int("delta.tpu.autopilot.quietMaxCommits", 0)
    if commits is None:
        commits = journal_mod.read_entries(log_path, kinds=["commit"])
    recent = 0
    for e in commits:
        ts = int(e.get("ts") or 0)
        if now_ms - ts > window_ms:
            continue
        op = (e.get("stats") or {}).get("operation")
        if op in _MAINTENANCE_OPS:
            continue
        recent += 1
    return {
        "quiet": recent <= max_commits,
        "recentCommits": recent,
        "windowMs": window_ms,
        "maxCommits": max_commits,
    }


# ---------------------------------------------------------------------------
# Plan synthesis
# ---------------------------------------------------------------------------

#: doctor dimension → (action kind, predicted-metric keys) for the
#: dimensions whose remedies the autopilot executes
_DIMENSION_ACTIONS = {
    "smallFiles": ("OPTIMIZE", ("count", "estReduction", "bytes")),
    "checkpoint": ("CHECKPOINT", ("commitsSince", "tailBytes")),
    "dv": ("PURGE", ("deletedPct", "filesPastPurge")),
    "tombstones": ("VACUUM", ("count", "bytes")),
    "device": ("EVICT", ("hbmBytes", "pressure")),
}


def _doctor_actions(doctor_report) -> List[MaintenanceAction]:
    out: List[MaintenanceAction] = []
    for d in doctor_report.dimensions:
        if d.severity == "ok" or not d.remedy:
            continue
        mapped = _DIMENSION_ACTIONS.get(d.name)
        if mapped is None or mapped[0] != d.remedy:
            # dimensions whose remedy isn't theirs to execute (stats →
            # OPTIMIZE is owned by smallFiles; REPARTITION is human)
            continue
        kind, metric_keys = mapped
        if not CATALOG[kind].executable:
            continue
        out.append(MaintenanceAction(
            kind=kind,
            table_path=doctor_report.path,
            source=f"doctor:{d.name}",
            priority=SEVERITY_RANK[d.severity] * 10.0,
            evidence=dict(d.metrics),
            predicted={k: d.metrics[k] for k in metric_keys
                       if k in d.metrics},
        ))
    return out


def _advisor_actions(advisor_report) -> List[MaintenanceAction]:
    out: List[MaintenanceAction] = []
    if getattr(advisor_report, "status", "") != "ok":
        return out
    for r in advisor_report.recommendations:
        if r.kind not in _EXECUTABLE_REC_KINDS:
            continue
        kind = RECOMMENDATION_ACTIONS[r.kind]
        if not CATALOG[kind].executable:
            continue
        params: Dict[str, Any] = {}
        target = ""
        if r.kind == "ZORDER":
            target = r.target
            params["columns"] = [r.target]
        out.append(MaintenanceAction(
            kind=kind,
            table_path=advisor_report.path,
            target=target,
            params=params,
            source=f"advisor:{r.kind}",
            priority=float(r.score),
            evidence=dict(r.evidence),
            predicted=dict(r.evidence),
        ))
    return out


#: rewrite-class action kinds the ``requireShadow`` guardrail covers —
#: the ones that spend real IO reshaping data layout
_SHADOW_GATED_KINDS = frozenset({"OPTIMIZE", "ZORDER", "PURGE"})


def _est_bytes(a: MaintenanceAction) -> Optional[int]:
    for src in (a.evidence, a.predicted):
        for key in ("bytes", "estBytes", "tailBytes"):
            v = src.get(key)
            if v is not None:
                try:
                    return int(v)
                except (TypeError, ValueError):
                    pass
    return None


def shadow_gate(actions: List[MaintenanceAction], log_path: str,
                entries: Optional[List[Dict[str, Any]]] = None):
    """The ``delta.tpu.autopilot.requireShadow`` guardrail: rewrite-class
    actions at/above ``requireShadowMinBytes`` only pass once a journaled
    shadow run CONFIRMED their (kind, target) — refuted ones are suppressed
    with the measured deltas cited, untested ones deferred until a shadow
    run exists. Unknown rewrite sizes are treated as over the threshold
    (fail closed). Returns ``(kept, deferred)`` where each deferred row
    cites the action key, the verdict, and the covering shadow evidence.
    No-op (everything kept) while the conf is off — shadow validation is
    opt-in, like dry-run is opt-out."""
    if not conf.get_bool("delta.tpu.autopilot.requireShadow", False):
        return list(actions), []
    min_bytes = conf.get_int("delta.tpu.autopilot.requireShadowMinBytes", 0)
    if entries is None:
        journal_mod.flush(log_path)
        entries = journal_mod.read_entries(log_path, kinds=["shadow"])
    from delta_tpu.replay.shadow import shadow_verdicts

    verdicts = shadow_verdicts(entries)
    kept: List[MaintenanceAction] = []
    deferred: List[Dict[str, Any]] = []
    for a in actions:
        if a.kind not in _SHADOW_GATED_KINDS:
            kept.append(a)
            continue
        est = _est_bytes(a)
        if est is not None and est < min_bytes:
            kept.append(a)  # too small to be worth a shadow run
            continue
        hit = verdicts.get((a.kind, (a.target or "").lower()))
        verdict = str((hit or {}).get("verdict", "untested"))
        if verdict == "confirmed":
            # measured evidence rides into the plan (and the journal's
            # ``planned`` entry) — NOT into ``predicted``, which stays
            # the advisor's forecast for the longitudinal audit
            a.evidence["shadow"] = dict(hit)
            kept.append(a)
        else:
            deferred.append({
                "action": a.key, "kind": a.kind, "target": a.target,
                "verdict": verdict, "estBytes": est,
                "reason": ("refuted by shadow run"
                           if verdict == "refuted"
                           else "no confirming shadow run"),
                "shadow": dict(hit) if hit else None,
            })
    return kept, deferred


def plan(doctor_report, advisor_report) -> List[MaintenanceAction]:
    """Merge both surfaces into one deduped, priority-ordered plan.
    Cooldown/backoff filtering happens in the daemon (it owns the ledger
    read) — this is the raw decision layer.

    A firing per-table SLO alert (`obs/slo`) boosts every planned action
    for that table by ``delta.tpu.obs.slo.priorityBoost`` and is cited in
    the action's evidence — across a fleet, the burning table's
    maintenance outranks routine debt elsewhere."""
    merged: Dict[str, MaintenanceAction] = {}
    for a in _doctor_actions(doctor_report) + _advisor_actions(advisor_report):
        prev = merged.get(a.key)
        if prev is None or a.priority > prev.priority:
            merged[a.key] = a
    if merged:
        from delta_tpu.obs import slo

        boost, alerts = slo.priority_boost(doctor_report.path)
        if boost:
            for a in merged.values():
                a.priority += boost
                a.evidence["sloAlerts"] = [
                    {"objective": al["objective"],
                     "burnFast": al["burnFast"], "burnSlow": al["burnSlow"]}
                    for al in alerts]
                a.evidence["sloPriorityBoost"] = boost
    return sorted(merged.values(), key=lambda a: -a.priority)
