"""RESTORE TABLE semantics (beyond-reference; modern Delta's RESTORE):
state rollback as a new commit, schema restore, DV awareness, VACUUM
interaction, and timestamp form.
"""
import os

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaIllegalStateError,
    VersionNotFoundError,
)


def make(tmp_table, **kw):
    return DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array([1, 2], pa.int64()),
                       "v": pa.array(["a", "b"])}),
        **kw,
    )


def append(t, ids):
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array(ids, pa.int64()),
        "v": pa.array([f"x{i}" for i in ids]),
    })).run()


def test_restore_undoes_appends(tmp_table):
    t = make(tmp_table)
    append(t, [10])
    append(t, [20])
    assert t.to_arrow().num_rows == 4
    m = t.restore_to_version(0)
    assert m["numRemovedFiles"] == 2 and m["numRestoredFiles"] == 0
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2]
    # restore is a commit, not history rewrite
    assert t.version == 3
    assert t.history()[0]["operation"] == "RESTORE"


def test_restore_undoes_delete(tmp_table):
    t = make(tmp_table)
    t.delete("id = 1")
    assert t.to_arrow().num_rows == 1
    m = t.restore_to_version(0)
    assert m["numRestoredFiles"] == 1
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2]


def test_restore_forward_again(tmp_table):
    """Restore can itself be undone by restoring to the pre-restore version."""
    t = make(tmp_table)
    append(t, [10])          # v1
    t.restore_to_version(0)  # v2
    t.restore_to_version(1)  # v3
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2, 10]


def test_restore_restores_schema(tmp_table):
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.schema.types import LongType, StructField

    t = make(tmp_table)
    add_columns(t.delta_log, [StructField("extra", LongType())])
    assert "extra" in t.schema().field_names
    t.restore_to_version(0)
    assert "extra" not in t.schema().field_names


def test_restore_dv_state(tmp_table):
    t = make(tmp_table, configuration={"delta.tpu.enableDeletionVectors": "true"})
    t.delete("id = 1")  # v1: DV on the file
    assert t.to_arrow().num_rows == 1
    t.restore_to_version(0)
    assert t.to_arrow().num_rows == 2, "restore must drop the DV'd entry"
    t.restore_to_version(1)
    assert t.to_arrow().num_rows == 1, "restore forward re-applies the DV"


def test_restore_to_missing_version_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises((VersionNotFoundError, DeltaAnalysisError)):
        t.restore_to_version(99)


def test_restore_requires_exactly_one_selector(tmp_table):
    t = make(tmp_table)
    from delta_tpu.commands.restore import RestoreCommand

    with pytest.raises(DeltaAnalysisError):
        RestoreCommand(t.delta_log)
    with pytest.raises(DeltaAnalysisError):
        RestoreCommand(t.delta_log, version=0, timestamp="2024-01-01")


def test_restore_past_vacuum_fails_cleanly(tmp_table):
    clock_now = [None]
    import time as _time

    clock_now[0] = int(_time.time() * 1000)
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(tmp_table, clock=lambda: clock_now[0])
    t = make(tmp_table)
    t.delete()  # v1 removes the file
    clock_now[0] += 14 * 24 * 3_600_000
    t.vacuum()  # physically deletes it
    with pytest.raises(DeltaIllegalStateError, match="no longer exists"):
        t.restore_to_version(0)
    # and the failed restore committed nothing
    assert t.version == 1


def test_restore_with_missing_dv_sidecar_fails_cleanly(tmp_table, monkeypatch):
    """The target version's AddFile can reference a deletion-vector sidecar
    ('u' storage) that cleanup already deleted even though the data file
    survives; the restore pre-check must catch the missing sidecar, not
    commit a state whose scans crash with a raw FileNotFoundError."""
    import glob

    from delta_tpu.protocol import deletion_vectors as dv_mod

    monkeypatch.setattr(dv_mod, "INLINE_THRESHOLD_BYTES", -1)  # force sidecar
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array(range(100), pa.int64()),
                       "v": pa.array([f"a{i}" for i in range(100)])}),
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    )
    t.delete("id < 10")          # v1: DV sidecar on the file
    target = t.delta_log.update()
    dv_files = [f for f in target.all_files if f.deletion_vector]
    assert dv_files and dv_files[0].deletion_vector["storageType"] == "u"
    t.optimize().execute_purge()  # v2: rewrites, drops the DV reference
    for p in glob.glob(os.path.join(tmp_table, "deletion_vector*")):
        os.remove(p)             # the sidecar is gone, the data file is not
    with pytest.raises(DeltaIllegalStateError, match="deletion-vector"):
        t.restore_to_version(1)
    assert t.version == 2


def test_restore_by_timestamp(tmp_table):
    from delta_tpu.protocol import filenames

    t = make(tmp_table)
    append(t, [10])
    HOUR = 3_600_000
    base = 1_700_000_000_000
    for v in (0, 1):
        p = f"{t.delta_log.log_path}/{filenames.delta_file(v)}"
        os.utime(p, ((base + v * HOUR) / 1000,) * 2)
    DeltaLog.clear_cache()
    t = DeltaTable.for_path(tmp_table)
    t.restore_to_timestamp(base + HOUR // 2)  # between v0 and v1 -> v0
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2]


def test_restore_noop_when_already_there(tmp_table):
    t = make(tmp_table)
    m = t.restore_to_version(0)
    assert m["numRestoredFiles"] == 0 and m["numRemovedFiles"] == 0
    assert t.to_arrow().num_rows == 2


def test_restore_sql_statement(tmp_table):
    from delta_tpu.sql.parser import execute_sql

    t = make(tmp_table)
    append(t, [10])
    DeltaLog.clear_cache()
    m = execute_sql(f"RESTORE TABLE delta.`{tmp_table}` TO VERSION AS OF 0")
    assert m["numRemovedFiles"] == 1
    assert sorted(DeltaTable.for_path(tmp_table).to_arrow()
                  .column("id").to_pylist()) == [1, 2]


def test_restore_sql_bad_forms(tmp_table):
    from delta_tpu.sql.parser import parse_statement
    from delta_tpu.utils.errors import DeltaParseError

    make(tmp_table)
    with pytest.raises(DeltaParseError):
        parse_statement(f"RESTORE TABLE delta.`{tmp_table}` TO VERSION 0")
    with pytest.raises(DeltaParseError):
        parse_statement(f"RESTORE TABLE delta.`{tmp_table}` VERSION AS OF 0")


def test_restore_sql_epoch_millis_timestamp(tmp_table):
    from delta_tpu.protocol import filenames
    from delta_tpu.sql.parser import execute_sql

    t = make(tmp_table)
    append(t, [10])
    base = 1_700_000_000_000
    for v in (0, 1):
        p = f"{t.delta_log.log_path}/{filenames.delta_file(v)}"
        os.utime(p, ((base + v * 3_600_000) / 1000,) * 2)
    DeltaLog.clear_cache()
    execute_sql(
        f"RESTORE TABLE delta.`{tmp_table}` TO TIMESTAMP AS OF {base + 60_000}"
    )
    assert sorted(DeltaTable.for_path(tmp_table).to_arrow()
                  .column("id").to_pylist()) == [1, 2]


def test_restore_malformed_timestamp_clean_error(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError, match="Invalid timestamp"):
        t.restore_to_timestamp("not-a-time")
    with pytest.raises(DeltaAnalysisError, match="Invalid timestamp"):
        t.to_arrow(timestamp="also/not/a/time")
