"""Multi-writer chaos: concurrent command-level DML against one table.

The reference simulates multi-writer concurrency with real threads and
multiple DeltaLog instances in one JVM (SURVEY §4 "Multi-node without a
cluster"); this suite does the same at the COMMAND level — mixed appends,
deletes, updates, and merges race, each either committing through the OCC
retry loop or failing with a *typed* concurrency error, and the final
table state must equal a serial execution of the successful operations.
"""
import threading

import pyarrow as pa

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.utils.errors import DeltaConcurrentModificationException


def run_threads(workers):
    errs = []

    def wrap(fn):
        def inner():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - collected for assertion
                errs.append(e)
        return inner

    ts = [threading.Thread(target=wrap(w)) for w in workers]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return errs


def test_concurrent_appends_all_land(tmp_table):
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([0], pa.int64())})
    )
    N = 12

    def appender(i):
        def go():
            WriteIntoDelta(t.delta_log, "append", pa.table({
                "id": pa.array([100 + i], pa.int64()),
            })).run()
        return go

    errs = run_threads([appender(i) for i in range(N)])
    assert errs == []
    ids = sorted(t.to_arrow().column("id").to_pylist())
    assert ids == [0] + [100 + i for i in range(N)]
    assert t.version == N


def test_concurrent_disjoint_partition_deletes(tmp_table):
    parts = [chr(ord("a") + i) for i in range(6)]
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"p": pa.array(parts), "x": pa.array(range(6), pa.int64())}),
        partition_columns=["p"],
    )

    def deleter(p):
        def go():
            t.delete(f"p = '{p}'")
        return go

    errs = run_threads([deleter(p) for p in parts[:4]])
    # disjoint partition deletes never truly conflict, but the engine may
    # surface retry-exhaustion only as a TYPED concurrency error
    assert all(isinstance(e, DeltaConcurrentModificationException) for e in errs)
    remaining = sorted(t.to_arrow().column("p").to_pylist())
    assert set(remaining) >= set(parts[4:])
    assert len(remaining) == 6 - 4 + len(errs)


def test_concurrent_merges_distinct_keys_serialize(tmp_table):
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array(range(10), pa.int64()),
                       "v": pa.array(["x"] * 10)}),
    )
    N = 6

    def merger(i):
        def go():
            src = pa.table({"id": pa.array([1000 + i], pa.int64()),
                            "v": pa.array([f"m{i}"])})
            (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
             .when_matched_update_all().when_not_matched_insert_all().execute())
        return go

    errs = run_threads([merger(i) for i in range(N)])
    ok = N - len(errs)
    assert all(isinstance(e, DeltaConcurrentModificationException) for e in errs)
    got = t.to_arrow()
    inserted = [v for v in got.column("id").to_pylist() if v >= 1000]
    assert len(inserted) == ok
    assert got.num_rows == 10 + ok


def test_writer_vs_reader_snapshot_stability(tmp_table):
    """Readers pinned to a snapshot never see torn state while writers
    churn — every read returns a row count that some version had."""
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([0], pa.int64())})
    )
    stop = threading.Event()
    bad = []

    def writer():
        for i in range(15):
            WriteIntoDelta(t.delta_log, "append", pa.table({
                "id": pa.array([i + 1], pa.int64()),
            })).run()
        stop.set()

    def reader():
        while not stop.is_set():
            n = t.to_arrow().num_rows
            if not (1 <= n <= 16):
                bad.append(n)

    errs = run_threads([writer, reader, reader])
    assert errs == [] and bad == []
    assert t.to_arrow().num_rows == 16


def test_two_delta_log_instances_same_table(tmp_table):
    """Two independent DeltaLog objects over one path (the reference's
    multiple-DeltaLog-instances pattern): commits interleave through the
    storage-level atomic create, state converges."""
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([0], pa.int64())})
    )
    other = DeltaLog(t.delta_log.data_path)  # bypass the singleton cache

    def via(log, i):
        def go():
            WriteIntoDelta(log, "append", pa.table({
                "id": pa.array([i], pa.int64()),
            })).run()
        return go

    errs = run_threads([via(t.delta_log, 1), via(other, 2),
                        via(t.delta_log, 3), via(other, 4)])
    assert errs == []
    assert sorted(t.to_arrow().column("id").to_pylist()) == [0, 1, 2, 3, 4]
    assert other.update().version == t.delta_log.update().version
