"""Expression IR — the engine's predicate/projection language.

The reference leans on Spark Catalyst for predicates, update expressions,
generated columns and constraints (SURVEY §7 "Hard parts"). This is our
replacement: a small, SQL-semantics (3-valued logic, casts) expression tree
with three evaluators:

* :meth:`Expression.eval` — row-at-a-time over a ``dict`` (host, used for
  partition-value pruning, conflict checking, constraint messages);
* ``delta_tpu.expr.vectorized`` — pyarrow/numpy columnar evaluation (host
  scan filtering, DML projection);
* ``delta_tpu.expr.jaxeval`` — compile to ``jnp`` ops over device-resident
  columns (stats pruning and DML kernels on TPU).

NULL is represented as Python ``None`` / masked lanes; comparisons with NULL
yield NULL; AND/OR use Kleene logic — matching Spark SQL.
"""
from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from delta_tpu.schema.types import (
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    LongType,
    StringType,
    TimestampType,
)
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = [
    "Expression",
    "Column",
    "Literal",
    "Alias",
    "And",
    "Or",
    "Not",
    "Eq",
    "NullSafeEq",
    "Ne",
    "Lt",
    "Le",
    "Gt",
    "Ge",
    "In",
    "IsNull",
    "IsNotNull",
    "Add",
    "Sub",
    "Mul",
    "Div",
    "Mod",
    "Neg",
    "Cast",
    "Like",
    "StartsWith",
    "Coalesce",
    "CaseWhen",
    "Func",
    "TRUE",
    "FALSE",
    "and_all",
    "split_conjuncts",
    "references",
]


class Expression:
    children: Tuple["Expression", ...] = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        raise NotImplementedError

    # -- tree utilities --------------------------------------------------

    def walk(self) -> Iterator["Expression"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def transform(self, fn: Callable[["Expression"], Optional["Expression"]]) -> "Expression":
        replaced = fn(self)
        if replaced is not None:
            return replaced
        new_children = tuple(c.transform(fn) for c in self.children)
        if new_children == self.children:
            return self
        clone = object.__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone.children = new_children
        return clone

    def sql(self) -> str:
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.sql()

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.sql() == other.sql()

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.sql()))


def references(expr: Expression) -> List[str]:
    """Column names referenced (lower-cased for case-insensitive resolution)."""
    out = []
    for e in expr.walk():
        if isinstance(e, Column):
            out.append(e.name)
    return out


def split_conjuncts(expr: Expression) -> List[Expression]:
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def and_all(exprs: Sequence[Expression]) -> Expression:
    if not exprs:
        return TRUE
    out = exprs[0]
    for e in exprs[1:]:
        out = And(out, e)
    return out


class Column(Expression):
    def __init__(self, name: str):
        self.name = name
        self.children = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        if self.name in row:
            return row[self.name]
        # case-insensitive fallback (Delta is case-insensitive by default)
        lname = self.name.lower()
        for k, v in row.items():
            if k.lower() == lname:
                return v
        raise errors.column_not_found_in_row(self.name, row)

    def sql(self) -> str:
        if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", self.name):
            return self.name
        escaped = self.name.replace("`", "``")
        return f"`{escaped}`"


class Literal(Expression):
    def __init__(self, value: Any, data_type: Optional[DataType] = None):
        self.value = value
        self.data_type = data_type or _infer_type(value)
        self.children = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        return self.value

    def sql(self) -> str:
        if self.value is None:
            return "NULL"
        if isinstance(self.value, bool):
            return "TRUE" if self.value else "FALSE"
        if isinstance(self.value, str):
            escaped = self.value.replace("'", "''")
            return f"'{escaped}'"
        return str(self.value)


TRUE = Literal(True, BooleanType())
FALSE = Literal(False, BooleanType())


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self) -> Expression:
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row)

    def sql(self) -> str:
        return f"{self.child.sql()} AS {self.name}"


def _infer_type(v: Any) -> DataType:
    if v is None:
        return StringType()
    if isinstance(v, bool):
        return BooleanType()
    if isinstance(v, int):
        return LongType()
    if isinstance(v, float):
        return DoubleType()
    if isinstance(v, str):
        return StringType()
    return StringType()


class _Binary(Expression):
    op = ""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    def sql(self) -> str:
        return f"({self.left.sql()} {self.op} {self.right.sql()})"


class And(_Binary):
    op = "AND"

    def eval(self, row):
        l = self.left.eval(row)
        if l is False:
            return False
        r = self.right.eval(row)
        if r is False:
            return False
        if l is None or r is None:
            return None
        return True


class Or(_Binary):
    op = "OR"

    def eval(self, row):
        l = self.left.eval(row)
        if l is True:
            return True
        r = self.right.eval(row)
        if r is True:
            return True
        if l is None or r is None:
            return None
        return False


class Not(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        v = self.child.eval(row)
        if v is None:
            return None
        return not v

    def sql(self) -> str:
        return f"(NOT {self.child.sql()})"


def _parse_temporal_str(s: str, like: Any):
    import datetime as _dt

    from delta_tpu.utils.timeparse import iso_to_date, iso_to_naive_utc

    if isinstance(like, _dt.datetime):
        out = iso_to_naive_utc(s)
        if like.tzinfo is not None:
            out = out.replace(tzinfo=_dt.timezone.utc)  # compare as aware
        return out
    return iso_to_date(s)


def _coerce_pair(l: Any, r: Any) -> Tuple[Any, Any]:
    """Numeric cross-type comparisons; strings compare as strings — except
    against dates/timestamps, where the string side parses as ISO-8601
    (Spark's implicit cast of temporal literals)."""
    import datetime as _dt

    if isinstance(l, bool) or isinstance(r, bool):
        return l, r
    if isinstance(l, (int, float)) and isinstance(r, (int, float)):
        return l, r
    if isinstance(l, str) and isinstance(r, (_dt.datetime, _dt.date)):
        try:
            return _parse_temporal_str(l, r), r
        except ValueError:
            return l, r
    if isinstance(r, str) and isinstance(l, (_dt.datetime, _dt.date)):
        try:
            return l, _parse_temporal_str(r, l)
        except ValueError:
            return l, r
    return l, r


class _Comparison(_Binary):
    py = staticmethod(lambda l, r: None)

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        if l is None or r is None:
            return None
        l, r = _coerce_pair(l, r)
        try:
            return self.py(l, r)
        except TypeError:
            raise errors.cannot_compare_types(
                type(l).__name__, type(r).__name__, self.sql())


class Eq(_Comparison):
    op = "="
    py = staticmethod(lambda l, r: l == r)


class NullSafeEq(_Binary):
    op = "<=>"

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        return l == r  # None <=> None is True


class Ne(_Comparison):
    op = "!="
    py = staticmethod(lambda l, r: l != r)


class Lt(_Comparison):
    op = "<"
    py = staticmethod(lambda l, r: l < r)


class Le(_Comparison):
    op = "<="
    py = staticmethod(lambda l, r: l <= r)


class Gt(_Comparison):
    op = ">"
    py = staticmethod(lambda l, r: l > r)


class Ge(_Comparison):
    op = ">="
    py = staticmethod(lambda l, r: l >= r)


class In(Expression):
    def __init__(self, value: Expression, options: Sequence[Expression]):
        self.children = (value, *options)

    @property
    def value(self):
        return self.children[0]

    @property
    def options(self):
        return self.children[1:]

    def eval(self, row):
        v = self.value.eval(row)
        if v is None:
            return None
        saw_null = False
        for o in self.options:
            ov = o.eval(row)
            if ov is None:
                saw_null = True
            elif ov == v:
                return True
        return None if saw_null else False

    def sql(self) -> str:
        opts = ", ".join(o.sql() for o in self.options)
        return f"({self.value.sql()} IN ({opts}))"


class IsNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row) is None

    def sql(self) -> str:
        return f"({self.child.sql()} IS NULL)"


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row) is not None

    def sql(self) -> str:
        return f"({self.child.sql()} IS NOT NULL)"


class _Arith(_Binary):
    py = staticmethod(lambda l, r: None)

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        if l is None or r is None:
            return None
        try:
            return self.py(l, r)
        except TypeError:
            raise errors.cannot_apply_operator(
                self.op, type(l).__name__, type(r).__name__, self.sql())


class Add(_Arith):
    op = "+"
    py = staticmethod(lambda l, r: l + r)


class Sub(_Arith):
    op = "-"
    py = staticmethod(lambda l, r: l - r)


class Mul(_Arith):
    op = "*"
    py = staticmethod(lambda l, r: l * r)


class Div(_Arith):
    op = "/"

    @staticmethod
    def py(l, r):
        if r == 0:
            return None  # Spark: div by zero yields NULL (ansi off)
        return l / r


class Mod(_Arith):
    op = "%"

    @staticmethod
    def py(l, r):
        if r == 0:
            return None
        return math.fmod(l, r) if isinstance(l, float) or isinstance(r, float) else l % r


class Neg(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        v = self.child.eval(row)
        return None if v is None else -v

    def sql(self) -> str:
        return f"(- {self.child.sql()})"


class Cast(Expression):
    def __init__(self, child: Expression, data_type: DataType):
        self.children = (child,)
        self.data_type = data_type

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return cast_value(self.child.eval(row), self.data_type)

    def sql(self) -> str:
        return f"CAST({self.child.sql()} AS {self.data_type.simple_string().upper()})"


def cast_value(v: Any, dt: DataType) -> Any:
    """Spark-style permissive cast; invalid casts yield NULL (ansi off)."""
    if v is None:
        return None
    try:
        name = dt.name if not isinstance(dt, DecimalType) else "decimal"
        if isinstance(dt, BooleanType):
            if isinstance(v, str):
                s = v.strip().lower()
                if s in ("true", "t", "yes", "y", "1"):
                    return True
                if s in ("false", "f", "no", "n", "0"):
                    return False
                return None
            return bool(v)
        if name in ("byte", "short", "integer", "long"):
            if isinstance(v, bool):
                return int(v)
            if isinstance(v, str):
                v = v.strip()
                return int(float(v)) if "." in v or "e" in v.lower() else int(v)
            return int(v)
        if name in ("float", "double", "decimal"):
            return float(v)
        if isinstance(dt, StringType):
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        if isinstance(dt, DateType):
            if isinstance(v, int):
                return v
            import datetime as _dt

            return (_dt.date.fromisoformat(str(v)[:10]) - _dt.date(1970, 1, 1)).days
        if isinstance(dt, TimestampType):
            if isinstance(v, int):
                return v
            import datetime as _dt

            s = str(v).replace(" ", "T")
            return int(_dt.datetime.fromisoformat(s).replace(tzinfo=_dt.timezone.utc).timestamp() * 1_000_000)
    except (ValueError, TypeError):
        return None
    return v


class Like(_Binary):
    """SQL LIKE with % and _ wildcards."""

    op = "LIKE"
    _rx_cache: Optional[Tuple[str, Any]] = None

    def eval(self, row):
        v = self.left.eval(row)
        p = self.right.eval(row)
        if v is None or p is None:
            return None
        if not isinstance(v, str) or not isinstance(p, str):
            raise errors.like_requires_strings(type(v).__name__, self.sql())
        cached = self._rx_cache
        if cached is None or cached[0] != p:
            rx = re.compile(
                "".join(".*" if ch == "%" else "." if ch == "_" else re.escape(ch) for ch in p),
                re.DOTALL,
            )
            self._rx_cache = cached = (p, rx)
        return cached[1].fullmatch(v) is not None


class StartsWith(_Binary):
    op = "STARTSWITH"

    def eval(self, row):
        v = self.left.eval(row)
        p = self.right.eval(row)
        if v is None or p is None:
            return None
        return str(v).startswith(str(p))

    def sql(self) -> str:
        return f"startswith({self.left.sql()}, {self.right.sql()})"


class Coalesce(Expression):
    def __init__(self, *options: Expression):
        self.children = tuple(options)

    def eval(self, row):
        for o in self.children:
            v = o.eval(row)
            if v is not None:
                return v
        return None

    def sql(self) -> str:
        return f"coalesce({', '.join(o.sql() for o in self.children)})"


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE d END. Children layout:
    (c1, v1, c2, v2, ..., default)."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 default: Optional[Expression] = None):
        flat: List[Expression] = []
        for c, v in branches:
            flat.extend((c, v))
        flat.append(default if default is not None else Literal(None))
        self.children = tuple(flat)
        self.n_branches = len(branches)

    def eval(self, row):
        for i in range(self.n_branches):
            if self.children[2 * i].eval(row) is True:
                return self.children[2 * i + 1].eval(row)
        return self.children[-1].eval(row)

    def sql(self) -> str:
        parts = ["CASE"]
        for i in range(self.n_branches):
            parts.append(f"WHEN {self.children[2*i].sql()} THEN {self.children[2*i+1].sql()}")
        parts.append(f"ELSE {self.children[-1].sql()} END")
        return " ".join(parts)


def _substring(s, pos, ln=None):
    """Spark substring window semantics: 1-based positive positions, 0
    treated as 1, negative positions count from the end — and when the
    window begins BEFORE the string (|pos| > length), the out-of-range
    prefix still consumes length: substring('abc', -5, 4) = 'ab'."""
    if s is None or pos is None:
        return None
    n = len(s)
    start0 = pos - 1 if pos > 0 else (n + pos if pos < 0 else 0)
    end0 = n if ln is None else start0 + max(ln, 0)
    return s[max(start0, 0):max(end0, 0)]


def _to_date(s, fmt=None):
    import datetime as _dt

    if s is None:
        return None
    if isinstance(s, _dt.datetime):
        return s.date()
    if isinstance(s, _dt.date):
        return s
    try:
        if fmt is None:
            return _dt.date.fromisoformat(str(s)[:10])
        return _dt.datetime.strptime(str(s), java_fmt_to_strftime(fmt)).date()
    except ValueError:
        return None  # Spark's to_date returns NULL on unparseable input


def _as_date(d):
    import datetime as _dt

    if isinstance(d, _dt.datetime):
        return d.date()
    if isinstance(d, _dt.date):
        return d
    return _dt.date(1970, 1, 1) + _dt.timedelta(days=int(d))


def _date_add(d, n, sign=1):
    import datetime as _dt

    if d is None or n is None:
        return None
    return _as_date(d) + _dt.timedelta(days=sign * int(n))


def _datediff(a, b):
    if a is None or b is None:
        return None
    return (_as_date(a) - _as_date(b)).days


def _pad(s, n, pad, left: bool):
    if s is None or n is None:
        return None
    n = int(n)
    if n <= 0:
        return ""
    if len(s) >= n:
        return s[:n]  # Spark truncates to the target width
    if not pad:
        return s
    fill = (pad * n)[: n - len(s)]
    return fill + s if left else s + fill


def _pow(x, y):
    if x is None or y is None:
        return None
    try:
        r = float(x) ** float(y)
    except ZeroDivisionError:
        return math.inf  # 0 ** negative: IEEE (and Spark/Arrow) say inf
    except OverflowError:
        return math.inf
    if isinstance(r, complex):
        return math.nan  # negative base, fractional exponent (IEEE pow)
    return r


def _log(*args):
    if any(a is None for a in args):
        return None
    if len(args) == 1:
        return math.log(args[0]) if args[0] > 0 else None
    base, x = args
    if x <= 0 or base <= 0 or base == 1:
        return None  # Spark yields NULL outside the domain
    return math.log(x, base)


class Func(Expression):
    """Named scalar function — the engine's analogue of the reference's
    generated-column whitelist (``SupportedGenerationExpressions.scala``).
    Exact (row) semantics live here; the Arrow and JAX evaluators vectorize
    the subset they can reproduce bit-for-bit and fall back otherwise."""

    FUNCS: Dict[str, Callable[..., Any]] = {
        "abs": lambda x: None if x is None else abs(x),
        "length": lambda x: None if x is None else len(x),
        "lower": lambda x: None if x is None else str(x).lower(),
        "upper": lambda x: None if x is None else str(x).upper(),
        "trim": lambda x: None if x is None else str(x).strip(),
        "concat": lambda *xs: None if any(x is None for x in xs) else "".join(str(x) for x in xs),
        "substring": _substring,
        "substr": _substring,
        "year": lambda d: None if d is None else _epoch_day_field(d, "year"),
        "month": lambda d: None if d is None else _epoch_day_field(d, "month"),
        "day": lambda d: None if d is None else _epoch_day_field(d, "day"),
        "hour": lambda t: None if t is None else ((t // 3_600_000_000) % 24),
        "minute": lambda t: None if t is None else ((t // 60_000_000) % 60),
        "second": lambda t: None if t is None else ((t // 1_000_000) % 60),
        "floor": lambda x: None if x is None else math.floor(x),
        "ceil": lambda x: None if x is None else math.ceil(x),
        "round": lambda x, n=0: None if x is None else round(x, n),
        "to_date": _to_date,
        "date_add": _date_add,
        "date_sub": lambda d, n: _date_add(d, n, sign=-1),
        "datediff": _datediff,
        "lpad": lambda s, n, pad=" ": _pad(s, n, pad, left=True),
        "rpad": lambda s, n, pad=" ": _pad(s, n, pad, left=False),
        "format_string": lambda fmt, *xs: (
            None if fmt is None or any(x is None for x in xs) else fmt % tuple(xs)
        ),
        "pow": lambda x, y: _pow(x, y),
        "power": lambda x, y: _pow(x, y),
        "exp": lambda x: None if x is None else math.exp(x),
        "log": _log,
        "sqrt": lambda x: None if x is None else (math.sqrt(x) if x >= 0 else None),
    }

    def __init__(self, name: str, args: Sequence[Expression]):
        self.name = name.lower()
        if self.name not in self.FUNCS:
            raise errors.unsupported_function(name)
        self.children = tuple(args)

    def eval(self, row):
        return self.FUNCS[self.name](*(a.eval(row) for a in self.children))

    def sql(self) -> str:
        return f"{self.name}({', '.join(a.sql() for a in self.children)})"


def _epoch_day_field(days: Any, field: str) -> Optional[int]:
    import datetime as _dt

    if isinstance(days, _dt.date):
        d = days
    else:
        d = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(days))
    return getattr(d, field)


_JAVA_FMT = {
    "yyyy": "%Y", "yy": "%y", "MM": "%m", "dd": "%d",
    "HH": "%H", "mm": "%M", "ss": "%S",
}


def java_fmt_to_strftime(fmt: str) -> str:
    """Translate the common subset of Java SimpleDateFormat patterns (what
    the reference's to_date/unix_timestamp take) into strftime. Unknown
    letter runs raise — silently misparsing dates corrupts data."""
    out: List[str] = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c.isalpha():
            j = i
            while j < len(fmt) and fmt[j] == c:
                j += 1
            run = fmt[i:j]
            if run not in _JAVA_FMT:
                raise errors.unsupported_function(f"to_date format token {run!r}")
            out.append(_JAVA_FMT[run])
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)
