"""DeltaLog / snapshot semantics (≈ ``DeltaLogSuite``): segments, updates,
time travel, contiguity errors, checkpoint interplay, golden-table reads."""
import os

import pytest

from tests.conftest import commit_manually, init_metadata

from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import AddFile, Metadata, Protocol, RemoveFile
from delta_tpu.utils.errors import DeltaIllegalStateError, ProtocolError, VersionNotFoundError


def add(path, size=1, ts=0):
    return AddFile(path, {}, size, ts, True)


def bootstrap(tmp_table, n_commits=1, files_per_commit=1):
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [Protocol(1, 2), init_metadata(), add("f-0-0")])
    for v in range(1, n_commits):
        commit_manually(log, v, [add(f"f-{v}-{i}") for i in range(files_per_commit)])
    return log


def test_empty_table(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    assert not log.table_exists
    assert log.snapshot.version == -1
    assert log.snapshot.all_files == []


def test_snapshot_after_commits(tmp_table):
    log = bootstrap(tmp_table, n_commits=3)
    snap = log.update()
    assert snap.version == 2
    assert len(snap.all_files) == 3
    assert snap.metadata.schema.field_names == ["id", "value"]
    assert snap.protocol == Protocol(1, 2)


def test_update_early_exit_same_segment(tmp_table):
    log = bootstrap(tmp_table)
    s1 = log.update()
    s2 = log.update()
    assert s1 is s2  # unchanged segment returns identical snapshot object


def test_update_sees_new_commits(tmp_table):
    log = bootstrap(tmp_table)
    assert log.update().version == 0
    commit_manually(log, 1, [add("f-1")])
    assert log.update().version == 1


def test_remove_applies(tmp_table):
    log = bootstrap(tmp_table)
    commit_manually(log, 1, [RemoveFile("f-0-0", deletion_timestamp=10**15)])
    snap = log.update()
    assert snap.all_files == []
    assert [t.path for t in snap.tombstones] == ["f-0-0"]


def test_checkpoint_and_reload(tmp_table):
    log = bootstrap(tmp_table, n_commits=12)
    log.checkpoint()
    assert log.store.exists(f"{log.log_path}/{filenames.checkpoint_file_single(11)}")
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table(tmp_table)
    snap = log2.snapshot
    assert snap.version == 11
    assert len(snap.all_files) == 12
    assert snap.segment.checkpoint_version == 11


def test_checkpoint_then_more_commits(tmp_table):
    log = bootstrap(tmp_table, n_commits=5)
    log.checkpoint()
    commit_manually(log, 5, [add("f-5")])
    commit_manually(log, 6, [add("f-6")])
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(tmp_table).snapshot
    assert snap.version == 6
    assert len(snap.all_files) == 7
    assert snap.segment.checkpoint_version == 4
    assert [f.name for f in snap.segment.deltas] == [
        filenames.delta_file(5), filenames.delta_file(6)
    ]


def test_non_contiguous_versions_error(tmp_table):
    log = bootstrap(tmp_table, n_commits=3)
    log.store.delete(f"{log.log_path}/{filenames.delta_file(1)}")
    DeltaLog.clear_cache()
    with pytest.raises(DeltaIllegalStateError):
        DeltaLog.for_table(tmp_table).snapshot.all_files  # noqa: B018


def test_missing_version_zero_error(tmp_table):
    log = bootstrap(tmp_table, n_commits=2)
    log.store.delete(f"{log.log_path}/{filenames.delta_file(0)}")
    DeltaLog.clear_cache()
    with pytest.raises(DeltaIllegalStateError):
        DeltaLog.for_table(tmp_table).snapshot.all_files  # noqa: B018


def test_time_travel(tmp_table):
    log = bootstrap(tmp_table, n_commits=10)
    snap3 = log.get_snapshot_at(3)
    assert snap3.version == 3
    assert len(snap3.all_files) == 4
    # with a checkpoint in between
    log.checkpoint()
    snap5 = log.get_snapshot_at(5)
    assert len(snap5.all_files) == 6


def test_time_travel_version_not_found(tmp_table):
    log = bootstrap(tmp_table, n_commits=2)
    with pytest.raises((VersionNotFoundError, DeltaIllegalStateError)):
        log.get_snapshot_at(17)


def test_get_changes(tmp_table):
    log = bootstrap(tmp_table, n_commits=4)
    changes = list(log.get_changes(2))
    assert [v for v, _ in changes] == [2, 3]
    assert any(isinstance(a, AddFile) for a in changes[0][1])


def test_protocol_gating(tmp_table):
    log = bootstrap(tmp_table)
    commit_manually(log, 1, [Protocol(99, 99)])
    snap = log.update()
    with pytest.raises(ProtocolError):
        log.assert_protocol_read(snap.protocol)
    with pytest.raises(ProtocolError):
        log.assert_protocol_write(snap.protocol)


def test_crc_written_and_validated(tmp_table):
    from delta_tpu.log import checksum as crc

    log = bootstrap(tmp_table, n_commits=2)
    snap = log.update()
    log.write_checksum_for(snap)
    assert log.store.exists(f"{log.log_path}/{filenames.checksum_file(1)}")
    crc.validate_checksum(snap)  # should not raise
    # corrupt it
    log.store.write(f"{log.log_path}/{filenames.checksum_file(1)}",
                    ['{"tableSizeBytes":999,"numFiles":999,"numMetadata":1,"numProtocol":1,"numTransactions":0}'],
                    overwrite=True)
    with pytest.raises(DeltaIllegalStateError):
        crc.validate_checksum(snap)


GOLDEN = "/root/reference/core/src/test/resources/delta/delta-0.1.0"


@pytest.mark.skipif(not os.path.isdir(GOLDEN), reason="reference goldens not mounted")
def test_golden_table_delta_0_1_0():
    """Read a table written by Delta Lake 0.1.0 (format compatibility)."""
    log = DeltaLog.for_table(GOLDEN)
    snap = log.snapshot
    assert snap.version >= 3
    assert snap.segment.checkpoint_version == 3
    assert snap.metadata.schema.field_names == ["id", "value"]
    assert len(snap.all_files) > 0
    for f in snap.all_files:
        assert f.path.endswith(".parquet")


@pytest.mark.skipif(not os.path.isdir(GOLDEN), reason="reference goldens not mounted")
def test_golden_table_time_travel():
    log = DeltaLog.for_table(GOLDEN)
    s0 = log.get_snapshot_at(0)
    assert s0.version == 0
    assert len(s0.all_files) > 0


def test_deleted_checkpoint_recovers_from_listing(tmp_table):
    """_last_checkpoint points at a vanished checkpoint: reader must fall back
    to a full listing, not report an empty table (SnapshotManagement.scala:118-126)."""
    log = bootstrap(tmp_table, n_commits=12)
    log.checkpoint()
    # delete the checkpoint parquet but keep the pointer
    assert log.store.delete(f"{log.log_path}/{filenames.checkpoint_file_single(11)}")
    commit_manually(log, 12, [add("f-12")])
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(tmp_table).snapshot
    assert snap.version == 12
    assert len(snap.all_files) == 13


# -- async stale-ok snapshot updates ---------------------------------------


def test_stale_ok_serves_stale_and_converges(tmp_table):
    """A read during a slow listing serves the stale snapshot immediately
    and the background refresh converges (SnapshotManagement.scala:251-263)."""
    import threading
    import time

    import numpy as np
    import pyarrow as pa

    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.utils.config import conf

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(3)})).run()
    v0 = log.update().version

    # a second "process" advances the table (a fresh DeltaLog instance, so
    # our reader's cached snapshot genuinely goes stale)
    other = DeltaLog(tmp_table)
    WriteIntoDelta(other, "append", pa.table({"a": np.arange(3)})).run()

    # make listings slow: the stale-ok read must not wait on them
    gate = threading.Event()
    real_list = log.store.list_from

    def slow_list(path):
        gate.wait(timeout=10)
        return real_list(path)

    log.store.list_from = slow_list
    try:
        with conf.set_temporarily(**{"delta.tpu.snapshot.stalenessLimitMs": 60_000}):
            t0 = time.monotonic()
            snap = log.update(stale_ok=True)
            served_in = time.monotonic() - t0
            assert snap.version == v0, "must serve the stale snapshot"
            assert served_in < 1.0, "stale-ok read must not block on listing"
            gate.set()
            f = log._refresh_future
            assert f is not None
            f.result(timeout=10)
            assert log.update(stale_ok=True).version == v0 + 1
    finally:
        log.store.list_from = real_list
        gate.set()


def test_stale_ok_beyond_limit_is_synchronous(tmp_table):
    import numpy as np
    import pyarrow as pa

    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.utils.config import conf

    clock = {"now": 1_000_000}
    log = DeltaLog.for_table(tmp_table, clock=lambda: clock["now"])
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(3)})).run()
    v1 = log.update().version
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(3)})).run()
    clock["now"] += 120_000  # older than the limit
    with conf.set_temporarily(**{"delta.tpu.snapshot.stalenessLimitMs": 60_000}):
        assert log.update(stale_ok=True).version == v1 + 1


def test_stale_ok_without_limit_stays_synchronous(tmp_table):
    import numpy as np
    import pyarrow as pa

    from delta_tpu.commands.write import WriteIntoDelta

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(3)})).run()
    v = log.update().version
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(3)})).run()
    assert log.update(stale_ok=True).version == v + 1


def test_update_coalescing_adopts_concurrent_listing(tmp_table):
    """A waiter queued on the update lock whose arrival predates the
    completion of a listing that STARTED after it arrived adopts that
    result instead of re-listing — a K-writer convoy costs one listing.
    Sequential update() calls still always re-list (a listing started
    BEFORE the caller's arrival never satisfies the adoption check)."""
    import threading

    from delta_tpu.utils import telemetry

    log = bootstrap(tmp_table, n_commits=2)
    log.update()

    lists = {"n": 0}
    orig = log.store.list_from

    def counting_list(path):
        lists["n"] += 1
        # slow the listing so the racers below genuinely QUEUE while the
        # leader lists — on a fast host a ~50µs real listing can finish
        # before the other threads even reach the lock, and zero
        # coalescing is then correct behavior (flaky assert)
        import time as _time

        _time.sleep(0.05)
        return orig(path)

    log.store.list_from = counting_list
    before = telemetry.counters("log").get("log.update.coalesced", 0)

    barrier = threading.Barrier(6)
    results = []

    def racer():
        barrier.wait()
        results.append(log.update().version)

    threads = [threading.Thread(target=racer) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [1] * 6
    # the first lock-holder lists; every waiter that arrived before that
    # listing finished adopts it (allow a straggler that arrived late)
    assert lists["n"] <= 2
    assert telemetry.counters("log").get("log.update.coalesced", 0) >= before + 4

    # sequential calls are never coalesced: an external commit is always
    # observed by the very next update()
    commit_manually(log, 2, [add("f-2-0")])
    assert log.update().version == 2
