"""Fleet observability plane (`delta_tpu/obs/fleet`, `obs/timeseries`,
`obs/slo`): the process-wide table registry, the metrics scraper's bounded
rings, the multi-window SLO burn-rate state machine, and the end-to-end
degradation scenario (one of K tables burns its commit-latency budget ->
exactly that table's alert fires through /slo, the flight recorder, and the
autopilot planner; recovery clears it).
"""
import json
import threading
import time
import urllib.parse

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.obs import fleet, flight_recorder, slo, timeseries
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_plane():
    for mod in (fleet, timeseries, slo):
        mod.reset()
    telemetry.reset_all()
    yield
    for mod in (fleet, timeseries, slo):
        mod.reset()
    telemetry.reset_all()


def _ids(n, start=0):
    return pa.table({"id": np.arange(start, start + n).astype("int64")})


T0 = 1_700_000_000_000  # pinned evaluation clock (ms)

#: pinned SLO windows used throughout: fast 60s, slow 600s
WINDOWS = {"delta.tpu.obs.slo.fastWindowMs": 60_000,
           "delta.tpu.obs.slo.slowWindowMs": 600_000}


def _observe_commit(label, value_ms, n=1, path="/fleet/test"):
    for _ in range(n):
        telemetry.observe("delta.commit.duration_ms", float(value_ms),
                          path=path, table=label)


# -- registry ----------------------------------------------------------------


def test_deltalog_autoregisters_in_fleet(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    live = fleet.live_tables()
    assert tmp_table in live and live[tmp_table] is t.delta_log
    status = fleet.fleet_status()
    assert status["tables"] == 1
    [row] = status["entries"]
    assert row["path"] == tmp_table and row["alive"]
    assert row["table"] == fleet.table_label(tmp_table)
    # the registry publishes its size as a cataloged gauge
    assert telemetry.gauges("fleet.tables")[("fleet.tables", ())] == 1


def test_fleet_registry_blackout_inert(tmp_table):
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        DeltaTable.create(tmp_table, data=_ids(5))
        assert fleet.live_tables() == {}
    # the switch alone also gates it
    with conf.set_temporarily(delta__tpu__obs__fleet__enabled=False):
        DeltaLog.clear_cache()
        DeltaLog.for_table(tmp_table)
        assert fleet.live_tables() == {}


def test_fleet_registry_weakref_never_keeps_a_table_alive(tmp_table):
    import gc

    DeltaTable.create(tmp_table, data=_ids(5))
    assert tmp_table in fleet.live_tables()
    DeltaLog.clear_cache()  # drop the only strong reference
    gc.collect()
    assert tmp_table not in fleet.live_tables()


def test_table_label_stable_and_reversible(tmp_table):
    a = fleet.table_label(tmp_table)
    assert a == fleet.table_label(tmp_table)
    assert len(a) == 12 and a != tmp_table
    assert fleet.label_path(a) == tmp_table
    assert fleet.label_path("nope") is None


def test_fleet_doctor_ranks_degraded_table_first(tmp_path):
    healthy = str(tmp_path / "healthy")
    degraded = str(tmp_path / "degraded")
    DeltaTable.create(healthy, data=_ids(100))
    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 10}):
        DeltaTable.create(degraded, data=_ids(400))  # 40 tiny files
    report = fleet.fleet_doctor()
    assert report.entries[0].path == degraded
    assert report.entries[0].severity in ("warn", "critical")
    assert report.entries[0].worst_dimension == "smallFiles"
    assert "OPTIMIZE" in report.entries[0].remedies
    assert report.entries[-1].path == healthy
    json.dumps(report.to_dict())
    assert telemetry.counters("fleet.sweeps") == {"fleet.sweeps": 1}


def test_fleet_doctor_survives_a_broken_table(tmp_path):
    import shutil

    ok = str(tmp_path / "ok")
    broken = str(tmp_path / "broken")
    DeltaTable.create(ok, data=_ids(10))
    DeltaTable.create(broken, data=_ids(10))
    shutil.rmtree(broken)  # the table dir vanishes under the handle
    report = fleet.fleet_doctor()
    by_path = {e.path: e for e in report.entries}
    assert by_path[ok].error is None
    # the broken table either reports an error or degrades to an empty
    # report — either way the sweep completed with both entries present
    assert len(report.entries) == 2


def test_fleet_advise_ranks_by_recommendation_score(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(100))
    t.to_arrow(filters=["id < 5"])
    report = fleet.fleet_advise()
    assert [e.path for e in report.entries] == [tmp_table]
    assert report.entries[0].detail["status"] in ("ok", "no history")


# -- scraper + rings ---------------------------------------------------------


def test_scrape_once_snapshots_all_metric_kinds():
    telemetry.bump_counter("commit.total", 5)
    telemetry.set_gauge("fleet.tables", 2)
    telemetry.observe("delta.commit.duration_ms", 12.0, table="abc")
    n = timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
    assert n == 3
    snap = timeseries.series_snapshot()
    assert snap["counters"]["commit.total"] == [[T0, 5.0]]
    assert snap["gauges"]["fleet.tables"] == [[T0, 2.0]]
    [(key, samples)] = snap["histograms"].items()
    assert key == "delta.commit.duration_ms{table=abc}"
    assert samples == [[T0, 1, 12.0]]
    assert timeseries.scrape_count() == 1


def test_scrape_rings_bounded_and_resizable():
    telemetry.bump_counter("commit.total")
    with conf.set_temporarily(delta__tpu__obs__scrape__keep=5):
        for i in range(20):
            timeseries.scrape_once(now_ms=T0 + i * 1000,
                                   evaluate_slo=False)
        samples = timeseries.series_snapshot()["counters"]["commit.total"]
        assert len(samples) == 5  # ring bound holds
        assert samples[-1][0] == T0 + 19_000  # newest kept


def test_counter_window_rate():
    telemetry.bump_counter("commit.total", 10)
    timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
    telemetry.bump_counter("commit.total", 30)
    timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
    # windows never reach before the first scrape: the 10 counts that
    # predate it are history, not signal — only the scraped delta counts
    win = timeseries.counter_window("commit.total", 60_000,
                                    now_ms=T0 + 10_000)
    assert win["delta"] == 30.0 and win["ratePerSec"] == pytest.approx(3.0)
    win = timeseries.counter_window("commit.total", 5_000,
                                    now_ms=T0 + 10_000)
    assert win["delta"] == 30.0 and win["ratePerSec"] == pytest.approx(3.0)
    # a single sample can compute no delta at all
    timeseries.reset()
    telemetry.bump_counter("commit.total", 5)
    timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
    win = timeseries.counter_window("commit.total", 60_000, now_ms=T0)
    assert win["delta"] == 0.0


def test_quantile_window_from_bucket_deltas():
    _observe_commit("q", 10.0, n=100)
    timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
    _observe_commit("q", 5000.0, n=100)
    timeseries.scrape_once(now_ms=T0 + 30_000, evaluate_slo=False)
    labels = (("path", "/fleet/test"), ("table", "q"))
    # window covering only the slow batch: p99 lands in the 8192 bucket
    v, n = timeseries.quantile_window("delta.commit.duration_ms", labels,
                                      0.99, 20_000, now_ms=T0 + 30_000)
    assert n == 100 and v == 8192.0
    # a huge window still baselines at the FIRST scrape — the 100 fast
    # observations that predate it never enter any window
    v, n = timeseries.quantile_window("delta.commit.duration_ms", labels,
                                      0.50, 600_000, now_ms=T0 + 30_000)
    assert n == 100 and v == 8192.0
    # empty window
    v, n = timeseries.quantile_window("delta.commit.duration_ms", labels,
                                      0.99, 1, now_ms=T0 + 90_000_000)
    assert v is None and n == 0


def test_full_ring_window_does_not_widen_to_all_time():
    """Once a ring has evicted history, a window bigger than the retained
    span must baseline at the oldest RETAINED sample — not fall back to
    counts-from-zero, which would let an ancient incident keep the slow
    burn hot forever."""
    labels = (("path", "/fleet/test"), ("table", "ev"))
    with conf.set_temporarily(delta__tpu__obs__scrape__keep=4):
        _observe_commit("ev", 9000.0, n=100)      # the ancient incident
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("ev", 10.0, n=50)
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        _observe_commit("ev", 10.0, n=50)
        for i in (2, 3, 4):                        # T0 sample falls out
            timeseries.scrape_once(now_ms=T0 + i * 10_000,
                                   evaluate_slo=False)
        v, n = timeseries.quantile_window(
            "delta.commit.duration_ms", labels, 0.99, 3_600_000,
            now_ms=T0 + 40_000)
    # only the 50 goods observed after the oldest retained sample count;
    # the 100 ancient bads (and the first 50 goods) are excluded
    assert n == 50 and v == 16.0


def test_series_cap_evicts_stalest_series():
    """Under table churn, dead tables' labeled series stop changing and
    must age out once the maxSeries cap is hit."""
    with conf.set_temporarily(delta__tpu__obs__scrape__maxSeries=10):
        for i in range(40):                        # 40 dead-table series
            telemetry.observe("delta.commit.duration_ms", 5.0,
                              path=f"/dead/{i}", table=f"dead{i}")
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        for i in range(1, 6):                      # one live counter moves
            telemetry.bump_counter("commit.total")
            timeseries.scrape_once(now_ms=T0 + i * 10_000,
                                   evaluate_slo=False)
        snap = timeseries.series_snapshot()
        total = (len(snap["counters"]) + len(snap["gauges"])
                 + len(snap["histograms"]))
        assert total <= 10
        assert "commit.total" in snap["counters"]  # the live one survived


def test_fleet_status_reports_dead_handle_before_prune(tmp_table):
    import gc

    DeltaTable.create(tmp_table, data=_ids(5))
    DeltaLog.clear_cache()
    gc.collect()
    [row] = fleet.fleet_status()["entries"]
    assert row["path"] == tmp_table and row["alive"] is False
    fleet.live_tables()                            # prunes
    assert fleet.fleet_status()["entries"] == []


def test_scraper_blackout_zero_series_zero_work():
    telemetry.bump_counter("commit.total", 5)
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        assert timeseries.scrape_once(now_ms=T0) == 0
        assert timeseries.scrape_count() == 0
    snap = timeseries.series_snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    # not even the scrape tick counter moved — zero wakeup work
    assert telemetry.counters("obs.scrape") == {}


def test_scraper_daemon_runs_and_stops():
    telemetry.bump_counter("commit.total")
    with conf.set_temporarily(delta__tpu__obs__scrape__intervalMs=10):
        s = timeseries.start_scraper()
        assert s.running
        assert timeseries.start_scraper() is s  # idempotent
        deadline = time.time() + 10
        while timeseries.scrape_count() < 3 and time.time() < deadline:
            s.tick()
            time.sleep(0.02)
        assert timeseries.scrape_count() >= 3
        timeseries.stop_scraper()
        assert not s.running


def test_concurrent_scrape_torture():
    """Scraper daemon at a hot interval while writer threads mutate the
    registry: no torn snapshots (cumulative counters never decrease within
    a ring, timestamps are monotonic), ring bounds hold."""
    stop = threading.Event()

    def load(tid):
        i = 0
        while not stop.is_set():
            telemetry.bump_counter("commit.total")
            telemetry.observe("delta.commit.duration_ms", (i % 37) + 1.0,
                              path="/torture", table=f"tt{tid}")
            telemetry.set_gauge("fleet.tables", i % 7)
            i += 1

    threads = [threading.Thread(target=load, args=(tid,),
                                name=f"delta-journal-writer")  # reuse a lane
               for tid in range(3)]
    with conf.set_temporarily(delta__tpu__obs__scrape__intervalMs=1,
                              delta__tpu__obs__scrape__keep=16,
                              delta__tpu__obs__slo__enabled=False):
        for t in threads:
            t.start()
        s = timeseries.start_scraper()
        deadline = time.time() + 15
        while timeseries.scrape_count() < 40 and time.time() < deadline:
            s.tick()
            time.sleep(0.005)
        stop.set()
        for t in threads:
            t.join()
        timeseries.stop_scraper()
    assert timeseries.scrape_count() >= 40
    snap = timeseries.series_snapshot()
    ctr = snap["counters"]["commit.total"]
    assert len(ctr) <= 16  # ring bound held under load
    assert all(a[0] <= b[0] for a, b in zip(ctr, ctr[1:]))  # ts monotonic
    assert all(a[1] <= b[1] for a, b in zip(ctr, ctr[1:]))  # never torn
    for key, samples in snap["histograms"].items():
        counts = [c for _t, c, _s in samples]
        assert all(a <= b for a, b in zip(counts, counts[1:])), key


def test_concurrent_scrape_blackout_stays_dark():
    """The torture shape under blackout: daemon running, load running, and
    the rings stay byte-for-byte empty."""
    stop = threading.Event()

    def load():
        while not stop.is_set():
            telemetry.bump_counter("commit.total")

    t = threading.Thread(target=load, name="delta-journal-writer")
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False,
                              delta__tpu__obs__scrape__intervalMs=1):
        t.start()
        s = timeseries.start_scraper()
        for _ in range(20):
            s.tick()
            time.sleep(0.005)
        stop.set()
        t.join()
        timeseries.stop_scraper()
    assert timeseries.scrape_count() == 0
    snap = timeseries.series_snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}


# -- SLO burn-rate matrix ----------------------------------------------------


def _eval_commit_rows(now_ms):
    rows = slo.evaluate(now_ms=now_ms)
    return [r for r in rows if r["objective"] == "commitLatencyP99"]


def test_slo_both_windows_fire():
    with conf.set_temporarily(**WINDOWS):
        _observe_commit("bad", 10.0, n=1)  # the series must predate the
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)  # baseline
        _observe_commit("bad", 9000.0, n=50)
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        [row] = _eval_commit_rows(T0 + 10_000)
        assert row["burnFast"] > 1 and row["burnSlow"] > 1
        assert row["alert"]["firing"]
    [alert] = slo.active_alerts()
    assert alert["objective"] == "commitLatencyP99"
    assert alert["table"] == "bad"
    assert telemetry.counters("slo.alerts.fired") == {"slo.alerts.fired": 1}
    g = telemetry.gauges("slo.alerts")
    assert g[("slo.alerts", ())] == 1


def test_slo_fast_only_does_not_fire():
    """A short blip inside a healthy slow window never pages."""
    with conf.set_temporarily(**WINDOWS):
        _observe_commit("blip", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("blip", 10.0, n=2000)  # long good history
        timeseries.scrape_once(now_ms=T0 + 100_000, evaluate_slo=False)
        _observe_commit("blip", 9000.0, n=15)  # bad samples, recent
        timeseries.scrape_once(now_ms=T0 + 550_000, evaluate_slo=False)
        [row] = _eval_commit_rows(T0 + 550_000)
        assert row["burnFast"] > 1          # the blip is the whole window
        assert row["burnSlow"] < 1          # diluted by the good history
        assert "alert" not in row
    assert slo.active_alerts() == []


def test_slo_slow_only_does_not_fire():
    """An already-recovered incident (bad history, quiet now) never pages."""
    with conf.set_temporarily(**WINDOWS):
        _observe_commit("old", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0 - 10_000, evaluate_slo=False)
        _observe_commit("old", 9000.0, n=500)  # the incident...
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        # ...then quiet: nothing new lands in the fast window
        timeseries.scrape_once(now_ms=T0 + 120_000, evaluate_slo=False)
        [row] = _eval_commit_rows(T0 + 120_000)
        assert row["burnFast"] == 0.0       # nothing in the fast window
        assert row["burnSlow"] > 1
        assert "alert" not in row
    assert slo.active_alerts() == []


def test_slo_recovery_clears_alert():
    with conf.set_temporarily(**WINDOWS):
        _observe_commit("rec", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("rec", 9000.0, n=50)
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 10_000)
        assert len(slo.active_alerts()) == 1
        # the fast window drains past the bad batch: recovery
        timeseries.scrape_once(now_ms=T0 + 200_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 200_000)
    assert slo.active_alerts() == []
    assert telemetry.counters("slo.alerts.cleared") == {
        "slo.alerts.cleared": 1}
    assert telemetry.gauges("slo.alerts")[("slo.alerts", ())] == 0
    # the cleared alert stays visible in status with its clear timestamp
    [hist] = slo.status()["alerts"]
    assert not hist["firing"] and hist["clearedAt"] == T0 + 200_000


def test_slo_hysteresis_on_flapping_series():
    """Between clearRatio and 1.0 the alert neither re-fires nor clears —
    a flapping series holds one alert instead of strobing."""
    with conf.set_temporarily(**WINDOWS, **{
            "delta.tpu.obs.slo.commitLatencyP99Ms": 1250.0}):
        _observe_commit("flap", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("flap", 2000.0, n=100)    # p99 bucket 2048
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 10_000)          # burn 1.64: fires
        assert len(slo.active_alerts()) == 1
        _observe_commit("flap", 800.0, n=100)     # p99 bucket 1024
        timeseries.scrape_once(now_ms=T0 + 130_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 130_000)         # burn 0.82 ∈ [0.8, 1)
        assert len(slo.active_alerts()) == 1      # still firing: hysteresis
        _observe_commit("flap", 300.0, n=100)     # p99 bucket 512
        timeseries.scrape_once(now_ms=T0 + 250_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 250_000)         # burn 0.41 < 0.8: clears
        assert slo.active_alerts() == []
    c = telemetry.counters("slo.alerts")
    assert c["slo.alerts.fired"] == 1 and c["slo.alerts.cleared"] == 1


def test_slo_cold_start_history_never_pages():
    """All-time process history must not page when the scraper starts: the
    first sample of a series is the baseline, never zero — a process with
    lifetime counters/histograms full of old badness starts clean."""
    with conf.set_temporarily(**WINDOWS):
        # pre-scraper history: lifetime 30% conflict ratio + slow commits
        telemetry.bump_counter("commit.total", 1000)
        telemetry.bump_counter("commit.conflicts", 300)
        _observe_commit("cold", 9000.0, n=500)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        rows = slo.evaluate(now_ms=T0)
        assert all(r["burnFast"] == 0.0 and r["burnSlow"] == 0.0
                   for r in rows), rows
        assert slo.active_alerts() == []


def test_slo_observation_floor_holds_back_tiny_windows():
    """A handful of bad samples below minObservations must not page, and
    the floor is conf-tunable."""
    with conf.set_temporarily(**WINDOWS):
        _observe_commit("cold", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("cold", 9000.0, n=3)  # 3 outliers < floor of 10
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        [row] = _eval_commit_rows(T0 + 10_000)
        assert row["burnFast"] > 1 and row["burnSlow"] > 1
        assert "alert" not in row             # floor (10) holds it back
        assert slo.active_alerts() == []
        # the floor is conf-tunable: at 1 the same series fires
        with conf.set_temporarily(
                **{"delta.tpu.obs.slo.minObservations": 1}):
            slo.evaluate(now_ms=T0 + 10_000)
            assert len(slo.active_alerts()) == 1


def test_series_snapshot_negative_limit_degrades_to_full_series():
    telemetry.bump_counter("commit.total")
    for i in range(8):
        timeseries.scrape_once(now_ms=T0 + i * 1000, evaluate_slo=False)
    full = timeseries.series_snapshot()["counters"]["commit.total"]
    neg = timeseries.series_snapshot(limit=-5)["counters"]["commit.total"]
    assert neg == full                # not a head-truncated pseudo-tail
    tail = timeseries.series_snapshot(limit=3)["counters"]["commit.total"]
    assert tail == full[-3:]


def test_slo_ratio_objective_fires_and_clears():
    with conf.set_temporarily(**WINDOWS):
        telemetry.bump_counter("commit.total", 100)
        telemetry.bump_counter("commit.conflicts", 0)  # series must predate
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)  # the baseline
        telemetry.bump_counter("commit.total", 100)
        telemetry.bump_counter("commit.conflicts", 30)  # 30% >> 5%
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        rows = [r for r in slo.evaluate(now_ms=T0 + 10_000)
                if r["objective"] == "commitConflictRate"]
        [row] = rows
        assert row["burnFast"] > 1 and row["burnSlow"] > 1
        [alert] = slo.active_alerts()
        assert alert["objective"] == "commitConflictRate"
        assert alert["table"] is None      # process-wide, not per-table
        # conflict-free traffic drains the fast window: clears
        telemetry.bump_counter("commit.total", 500)
        timeseries.scrape_once(now_ms=T0 + 120_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 120_000)
        assert slo.active_alerts() == []


def test_slo_evaluate_blackout_inert():
    _observe_commit("dark", 9000.0, n=50)
    timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        assert slo.evaluate(now_ms=T0 + 10_000) == []
    assert slo.active_alerts() == []


def test_slo_alert_writes_flight_recorder_incident(tmp_path):
    inc_dir = str(tmp_path / "incidents")
    with conf.set_temporarily(delta__tpu__obs__incidentDir=inc_dir,
                              **WINDOWS):
        _observe_commit("inc", 10.0, n=1)
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit("inc", 9000.0, n=50)
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 10_000)
    [path] = flight_recorder.incident_files(inc_dir)
    incident = json.load(open(path, encoding="utf-8"))
    assert incident["opType"] == "delta.slo.alert"
    assert "SloBreach" in incident["error"]
    assert incident["data"]["objective"] == "commitLatencyP99"
    assert incident["tags"]["table"] == "inc"


# -- autopilot consumption ---------------------------------------------------


def test_planner_boosts_and_cites_slo_alert(tmp_table):
    from delta_tpu.autopilot import planner
    from delta_tpu.obs.advisor import advise
    from delta_tpu.obs.doctor import doctor

    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 10}):
        t = DeltaTable.create(tmp_table, data=_ids(400))  # small-file debt
    base_plan = planner.plan(doctor(t), advise(t))
    assert base_plan, "debt table must plan at least one action"
    base_priority = base_plan[0].priority

    label = fleet.table_label(tmp_table)
    with conf.set_temporarily(**WINDOWS):
        timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
        _observe_commit(label, 9000.0, n=50, path=tmp_table)
        timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
        slo.evaluate(now_ms=T0 + 10_000)
    assert slo.active_alerts(tmp_table), "the alert must resolve the path"

    boosted = planner.plan(doctor(t), advise(t))
    assert boosted[0].priority == pytest.approx(base_priority + 25.0)
    cited = boosted[0].evidence["sloAlerts"]
    assert cited[0]["objective"] == "commitLatencyP99"
    assert boosted[0].evidence["sloPriorityBoost"] == 25.0
    # the citation survives into the journaled action dict
    assert "sloAlerts" in boosted[0].to_dict()["evidence"]


# -- end-to-end degradation scenario (acceptance) ----------------------------


def test_degradation_scenario_end_to_end(tmp_path):
    """One of K tables inflates its commit latency: exactly that table's
    SLO alert fires through all three consumers — /slo, a flight-recorder
    incident on disk, and an autopilot plan citing the alert — recovery
    clears it, and fleet_doctor ranks the degraded table first."""
    import http.client

    from delta_tpu.autopilot import daemon as ap_daemon
    from delta_tpu.obs.server import ObsServer

    inc_dir = str(tmp_path / "incidents")
    paths = [str(tmp_path / f"t{i}") for i in range(3)]
    degraded = paths[1]
    tables = {}
    for p in paths:
        if p == degraded:  # debt so the doctor/autopilot have a remedy
            with conf.set_temporarily(
                    **{"delta.tpu.write.targetFileRows": 10}):
                tables[p] = DeltaTable.create(p, data=_ids(400))
        else:
            tables[p] = DeltaTable.create(p, data=_ids(50))
        tables[p].write(_ids(10, start=1000))  # real commits: series exist
    assert set(fleet.live_tables()) == set(paths)

    srv = ObsServer(port=0)
    try:
        with conf.set_temporarily(delta__tpu__obs__incidentDir=inc_dir,
                                  **WINDOWS):
            timeseries.scrape_once(now_ms=T0, evaluate_slo=False)
            # forced commit-latency inflation on the degraded table only
            _observe_commit(fleet.table_label(degraded), 9000.0, n=50,
                            path=degraded)
            timeseries.scrape_once(now_ms=T0 + 10_000, evaluate_slo=False)
            slo.evaluate(now_ms=T0 + 10_000)

            # consumer 1: /slo names exactly the degraded table
            c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            c.request("GET", "/slo")
            doc = json.loads(c.getresponse().read())
            c.close()
            firing = [a for a in doc["alerts"] if a["firing"]]
            assert [a["path"] for a in firing] == [degraded]
            assert firing[0]["objective"] == "commitLatencyP99"

            # consumer 2: one incident file on disk, attributed
            [inc] = flight_recorder.incident_files(inc_dir)
            blob = json.load(open(inc, encoding="utf-8"))
            assert blob["data"]["path"] == degraded

            # consumer 3: the autopilot plan cites the alert as priority
            report = ap_daemon.run_once(degraded)  # dry-run default
            assert report.planned, "the degraded table must plan actions"
            top = report.planned[0]
            assert top["evidence"]["sloAlerts"][0]["objective"] == \
                "commitLatencyP99"
            assert top["priority"] >= 25.0
            # ...and the healthy neighbours plan WITHOUT any boost
            for p in paths:
                if p == degraded:
                    continue
                rep = ap_daemon.run_once(p)
                for a in rep.planned:
                    assert "sloAlerts" not in a["evidence"]

            # the fleet sweep ranks the degraded table first
            sweep = fleet.fleet_doctor()
            assert sweep.entries[0].path == degraded

            # recovery: the fast window drains and the alert clears
            timeseries.scrape_once(now_ms=T0 + 200_000, evaluate_slo=False)
            slo.evaluate(now_ms=T0 + 200_000)
            assert slo.active_alerts() == []
            c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            c.request("GET", "/slo")
            doc = json.loads(c.getresponse().read())
            c.close()
            assert doc["firing"] == 0
    finally:
        srv.stop()


# -- blackout: the whole plane is inert --------------------------------------


def test_fleet_plane_blackout_smoke(tmp_table):
    """PR 4/8-style blackout guarantee for the whole plane: no registry
    entries, no scraper work, no series bytes, no SLO evaluation."""
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        t = DeltaTable.create(tmp_table, data=_ids(100))
        t.to_arrow(filters=["id < 5"])
        assert fleet.live_tables() == {}
        assert timeseries.scrape_once() == 0
        assert slo.evaluate() == []
        assert timeseries.series_snapshot()["counters"] == {}
        # fleet sweeps still ANSWER (pull-by-call, like doctor under
        # blackout) but see an empty registry
        assert fleet.fleet_doctor().entries == []
    # scan planning histograms are span-derived: blackout recorded nothing
    assert telemetry.histograms("delta.scan.planning.duration_ms") == {}
