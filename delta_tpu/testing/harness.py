"""Crash-consistency torture harness — a seeded workload under injected faults.

Drives a random (but **seeded**, hence reproducible) mix of appends,
deletes, streaming-sink batches, checkpoints, and OPTIMIZE against one
table while :class:`~delta_tpu.storage.faults.FaultInjectingLogStore`
injects faults at every registered fault point. A
:class:`~delta_tpu.storage.faults.SimulatedCrash` is handled exactly the
way a real process death is: throw the ``DeltaLog`` away, build a fresh
one over the same directory, and *reconcile* — probe the table (through a
clean, fault-free oracle store) to learn whether the in-flight operation's
commit actually landed, then update the expected-state ledger accordingly.
A crashed streaming batch is re-delivered with the same ``batchId``, so the
SetTransaction dedup path gets exercised by every streaming crash.

Invariants checked throughout (``check_invariants``):

1. **No committed row lost, none duplicated** — the oracle read's id
   multiset equals the ledger exactly.
2. **Snapshot always constructible** — every recovery builds a snapshot
   from whatever the crash left (torn checkpoints, stale pointers, orphans).
3. **Doctor clean** — the protocol health dimension is never ``critical``.
4. **Bounded failure time** — no step (including its retries) exceeds the
   configured deadline-derived bound; recorded in the report.

Determinism witness: ``FaultPlan.per_point`` — same seed, same workload
==> identical per-fault-point kind sequences, so any torture failure
replays exactly.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from delta_tpu.storage.faults import ALL_KINDS, FaultPlan, SimulatedCrash

__all__ = ["TortureHarness", "TortureReport", "run_torture"]

_B = 16  # rows per batch


@dataclass
class TortureReport:
    steps: int = 0
    crashes: int = 0
    recoveries: int = 0
    reconciled_ambiguous: int = 0
    stream_replays: int = 0
    op_counts: Dict[str, int] = field(default_factory=dict)
    quarantined_groups: int = 0
    items_retried: int = 0
    slices_recovered: int = 0
    faults_injected: int = 0
    fault_kinds: Dict[str, int] = field(default_factory=dict)
    per_point: Dict[str, List[str]] = field(default_factory=dict)
    max_step_s: float = 0.0
    invariant_checks: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class TortureHarness:
    def __init__(self, path: str, seed: int, plan: Optional[FaultPlan] = None,
                 rate: float = 0.08, kinds=ALL_KINDS,
                 max_step_s: float = 60.0,
                 group_commit: bool = False,
                 async_checkpoint: bool = False,
                 autopilot: bool = False,
                 autopilot_cooldown_ms: int = 2000,
                 distributed: bool = False):
        self.path = path
        self.seed = seed
        self.plan = plan or FaultPlan(seed=seed, rate=rate, kinds=kinds)
        self.rng = random.Random(seed)
        self.max_step_s = max_step_s
        # high-traffic commit path (ISSUE 9): run the same workload through
        # the group-commit coordinator and/or the async incremental
        # checkpointer. NOTE: with the async builder the checkpoint fault
        # draws key on different files run-over-run (request coalescing is
        # timing-dependent), so per_point determinism is only a witness for
        # the default synchronous configuration.
        self.group_commit = group_commit
        self.async_checkpoint = async_checkpoint
        # autopilot mode (ISSUE 13): interleave non-dry-run maintenance
        # passes (delta_tpu/autopilot.run_once) with the faulted workload —
        # a SimulatedCrash mid-maintenance must leave the table consistent,
        # the interrupted action journaled, and the cooldown armed against
        # crash-loop re-execution. The extra weighted op changes the seeded
        # op sequence, so per_point determinism is only comparable between
        # runs with the same autopilot setting.
        self.autopilot = autopilot
        self.autopilot_cooldown_ms = autopilot_cooldown_ms
        # distributed mode (ISSUE 20): OPTIMIZE runs on the supervised
        # sharded executor (4 workers, on_failure="quarantine") and, on a
        # seeded coin flip, as coordinator of a faked 2-host job — which
        # exercises the lease write/heartbeat/clear path and, when a crash
        # leaves an orphan lease behind, the coordinator's expired-lease
        # recovery on a LATER optimize step. The extra faulted points
        # (dist.itemExec / dist.workerSpawn / dist.heartbeat /
        # dist.leaseWrite) change the seeded draw sequence, so per_point
        # determinism is only comparable between runs with the same
        # distributed setting.
        self.distributed = distributed
        self._weighted_ops = list(self._WEIGHTED_OPS)
        if autopilot:
            self._weighted_ops.append(("autopilot", 6))
        self.report = TortureReport()
        # ledger: batch id -> ("present" | "deleted", [ids])
        self.batches: Dict[int, Tuple[str, List[int]]] = {}
        self.next_batch = 0
        self.next_stream_batch = 0
        self.stream_query = f"torture-stream-{seed}"
        self._log = None
        self._generation = 0  # bumped by every _recover()

    # -- plumbing ---------------------------------------------------------

    def _fresh_log(self):
        """A brand-new DeltaLog over the table — what a restarted process
        builds. Goes through the session conf, so the shared FaultPlan
        re-wraps the store and fault state continues across 'restarts'."""
        from delta_tpu.log.deltalog import DeltaLog

        DeltaLog.invalidate_cache(self.path)
        return DeltaLog(self.path)

    def _oracle_snapshot(self):
        """Fault-free ground-truth snapshot (fresh log, injector disabled):
        what any OTHER healthy process would see right now."""
        from delta_tpu.log.deltalog import DeltaLog
        from delta_tpu.utils.config import conf

        with conf.set_temporarily(delta__tpu__faults__plan=None):
            return DeltaLog(self.path).snapshot

    def _oracle_batch_rows(self, bid: int, stream: bool = False) -> int:
        from delta_tpu.exec.scan import scan_to_table

        col = "sbatch" if stream else "batch"
        snap = self._oracle_snapshot()
        return scan_to_table(snap, [f"{col} = {bid}"], ["id"]).num_rows

    def _rows(self, ids: List[int], bid: int, stream: bool = False) -> pa.Table:
        n = len(ids)
        cols = {
            "id": pa.array(ids, pa.int64()),
            "batch": pa.array([-1 if stream else bid] * n, pa.int64()),
            "sbatch": pa.array([bid if stream else -1] * n, pa.int64()),
        }
        if self.distributed:
            # distributed mode partitions by a 4-way shard column so OPTIMIZE
            # plans SEVERAL groups — the multi-item pool path (work stealing,
            # heartbeats, speculation) is the whole fault surface under test;
            # an unpartitioned table collapses to one group and runs inline
            cols["shard"] = pa.array([i % 4 for i in ids], pa.int64())
        return pa.table(cols)

    def _expected_ids(self) -> List[int]:
        out: List[int] = []
        for status, ids in self.batches.values():
            if status == "present":
                out.extend(ids)
        return out

    def _alloc_ids(self) -> List[int]:
        start = (self.next_batch + self.next_stream_batch) * 1_000_000
        return list(range(start, start + _B))

    # -- setup ------------------------------------------------------------

    def create_table(self) -> None:
        """Create the table fault-free (the torture targets a live table,
        not CREATE)."""
        from delta_tpu.api.tables import DeltaTable
        from delta_tpu.utils.config import conf

        with conf.set_temporarily(delta__tpu__faults__plan=None):
            DeltaTable.create(
                self.path, data=self._rows([], -1),
                partition_columns=["shard"] if self.distributed else ())
        self._log = self._fresh_log()

    # -- workload ops -----------------------------------------------------

    def _op_append(self) -> None:
        from delta_tpu.commands.write import WriteIntoDelta

        bid = self.next_batch
        self.next_batch += 1
        ids = self._alloc_ids()
        try:
            WriteIntoDelta(self._log, "append", self._rows(ids, bid)).run()
            self.batches[bid] = ("present", ids)
        except BaseException:
            self._recover()
            if self._oracle_batch_rows(bid) > 0:  # commit landed pre-crash
                self.batches[bid] = ("present", ids)
                self.report.reconciled_ambiguous += 1
            raise

    def _op_delete(self) -> None:
        from delta_tpu.api.tables import DeltaTable

        present = sorted(
            b for b, (s, _) in self.batches.items()
            if isinstance(b, int) and s == "present"  # stream batches keyed ("s", n)
        )
        if not present:
            return
        bid = present[self.rng.randrange(len(present))]
        ids = self.batches[bid][1]
        try:
            metrics = DeltaTable(self._log).delete(f"batch = {bid}")
            # a lagged listing can hand the DELETE a snapshot from before
            # this batch's (blind) append — under WriteSerializable the
            # delete legally serializes FIRST and removes nothing. The
            # ledger must follow what the commit actually did, not what the
            # driver hoped: 0 files removed = the batch is still live.
            if metrics.get("numRemovedFiles", 0) > 0 or metrics.get(
                    "numDeletedRows", 0) > 0:
                self.batches[bid] = ("deleted", ids)
        except BaseException:
            self._recover()
            if self._oracle_batch_rows(bid) == 0:  # delete landed pre-crash
                self.batches[bid] = ("deleted", ids)
                self.report.reconciled_ambiguous += 1
            raise

    def _op_stream(self) -> None:
        """Streaming-sink batch; a crashed delivery is RE-DELIVERED with the
        same batchId — SetTransaction dedup must make it exactly-once."""
        from delta_tpu.streaming.sink import DeltaSink

        sbid = self.next_stream_batch
        self.next_stream_batch += 1
        ids = self._alloc_ids()
        data = self._rows(ids, sbid, stream=True)
        key = ("s", sbid)
        try:
            DeltaSink(self._log, self.stream_query).add_batch(sbid, data)
            self.batches[key] = ("present", ids)  # type: ignore[index]
        except BaseException:
            self._recover()
            # exactly-once replay: re-deliver the SAME batchId until it goes
            # through; SetTransaction dedup makes the landed-then-crashed
            # case a no-op, so the rows appear exactly once either way
            for _ in range(10):
                try:
                    DeltaSink(self._log, self.stream_query).add_batch(sbid, data)
                    self.batches[key] = ("present", ids)  # type: ignore[index]
                    self.report.stream_replays += 1
                    break
                # delta-lint: ignore[crash-swallow] -- the harness IS the crash
                # driver: it absorbs the simulated death and replays the batchId
                except BaseException:
                    self._recover()
            else:
                # replay budget exhausted under extreme fault rates: settle
                # via the oracle — no writer remains, the state is final
                if self._oracle_batch_rows(sbid, stream=True) > 0:
                    self.batches[key] = ("present", ids)  # type: ignore[index]
                    self.report.reconciled_ambiguous += 1
            raise

    def _op_checkpoint(self) -> None:
        from delta_tpu.utils.config import conf

        if conf.get_bool("delta.tpu.checkpoint.async", False):
            # run the async builder's build path ON THIS THREAD (not
            # request+flush — the daemon could drain the request first and
            # swallow the injected crash), so a crash mid-build surfaces to
            # the driver deterministically, exactly like a process death
            # during a background checkpoint would
            from delta_tpu.log import checkpointer

            checkpointer.build_checkpoint(
                self._log, self._log.update().version)
        else:
            self._log.checkpoint()

    def _op_optimize(self) -> None:
        from delta_tpu.api.tables import DeltaTable

        if not self.distributed:
            DeltaTable(self._log).optimize().execute_compaction()
            return
        from delta_tpu.commands.optimize import OptimizeCommand
        from delta_tpu.parallel import distributed as dist_mod

        # seeded coin flip: plain supervised sharded execution, or the same
        # posing as coordinator of a 2-host job. The phantom peer never
        # appears (its slice simply stays uncompacted — rearrange-only, so
        # no row is owed to it), but the pose makes the run write/clear its
        # own lease and reconcile any expired orphan a crashed earlier step
        # left behind — sliceRecovered under live fault injection.
        pose_multihost = self.rng.random() < 0.5
        cmd = OptimizeCommand(self._log, workers=4,
                              distribute=pose_multihost,
                              on_failure="quarantine")
        if pose_multihost:
            orig = dist_mod.process_info
            dist_mod.process_info = lambda: (0, 2)
            try:
                cmd.run()
            finally:
                dist_mod.process_info = orig
        else:
            cmd.run()
        # retry/quarantine evidence is read from the telemetry counters in
        # run() — counted the moment they happen, so a job that crashes
        # AFTER a retry still contributes

    def _op_read(self) -> None:
        from delta_tpu.exec.scan import scan_to_table

        scan_to_table(self._log.snapshot, [], ["id"])

    def _op_autopilot(self) -> None:
        """One non-dry-run maintenance pass under fault injection.
        ``force=True`` skips the quiet-window check (the torture workload
        is never quiet by construction); every other guardrail — cost
        caps, cooldowns, capped commit attempts, durable started entries —
        runs exactly as in production."""
        from delta_tpu import autopilot as autopilot_mod

        autopilot_mod.run_once(self._log, force=True)

    # -- crash handling ---------------------------------------------------

    def _recover(self) -> None:
        """The restarted process: fresh DeltaLog over whatever the crash
        left behind. Snapshot constructibility IS invariant #2 — recovery
        itself fails the run if the log can't produce a snapshot."""
        self.report.recoveries += 1
        self._generation += 1
        last: Optional[BaseException] = None
        for _ in range(5):  # injected read transients may outlast the
            try:            # retry layer; a real operator would also re-run
                self._log = self._fresh_log()
                return
            except SimulatedCrash:
                continue
            except Exception as e:  # noqa: BLE001
                last = e
        raise AssertionError(
            f"invariant violated: snapshot not constructible after crash: {last}"
        )

    # -- invariants -------------------------------------------------------

    def check_invariants(self) -> None:
        from delta_tpu.exec.scan import scan_to_table
        from delta_tpu.obs.doctor import doctor

        self.report.invariant_checks += 1
        snap = self._oracle_snapshot()  # invariant 2: constructible
        got = scan_to_table(snap, [], ["id"]).column("id").to_pylist()
        expected = self._expected_ids()
        assert len(got) == len(set(got)), (
            f"invariant violated: duplicated rows "
            f"({len(got) - len(set(got))} dups of {len(got)})"
        )
        missing = set(expected) - set(got)
        assert not missing, (
            f"invariant violated: {len(missing)} committed rows lost "
            f"(e.g. {sorted(missing)[:5]})"
        )
        phantom = set(got) - set(expected)
        assert not phantom, (
            f"invariant violated: {len(phantom)} phantom rows present "
            f"(e.g. {sorted(phantom)[:5]})"
        )
        report = doctor(self.path, snapshot=snap, publish_gauges=False)
        proto = report.dimension("protocol")
        assert proto.severity != "critical", (
            f"invariant violated: doctor protocol dimension critical: {proto}"
        )

    # -- driver -----------------------------------------------------------

    _WEIGHTED_OPS = (
        ("append", 32), ("delete", 14), ("stream", 14),
        ("checkpoint", 12), ("optimize", 8), ("read", 20),
    )

    def _pick_op(self) -> str:
        total = sum(w for _, w in self._weighted_ops)
        r = self.rng.randrange(total)
        for name, w in self._weighted_ops:
            if r < w:
                return name
            r -= w
        raise AssertionError("unreachable")

    def step(self) -> None:
        op = self._pick_op()
        self.report.op_counts[op] = self.report.op_counts.get(op, 0) + 1
        fn = getattr(self, f"_op_{op}")
        t0 = time.monotonic()
        gen = self._generation
        try:
            fn()
        except SimulatedCrash:
            # a crash ALWAYS costs a process restart; ops that reconcile
            # their ledger already recovered (generation moved) — don't
            # restart twice for one death
            self.report.crashes += 1
            if self._generation == gen:
                self._recover()
        except Exception:  # noqa: BLE001 — retry-exhaustion etc.: the op
            # failed determinately or was already reconciled by the op body
            if self._generation == gen:
                self._recover()
        dt = time.monotonic() - t0
        self.report.max_step_s = max(self.report.max_step_s, dt)
        assert dt <= self.max_step_s, (
            f"invariant violated: step {op!r} took {dt:.1f}s "
            f"(bound {self.max_step_s}s) — unbounded failure time"
        )

    def run(self, steps: int, check_every: int = 10) -> TortureReport:
        """Run the seeded workload with faults active; returns the report."""
        from delta_tpu.utils.config import conf

        if self._log is None:
            self.create_table()
        extra = {}
        if self.group_commit:
            extra["delta.tpu.commit.group.enabled"] = True
            extra["delta.tpu.commit.group.maxWaitMs"] = 0
        if self.async_checkpoint:
            extra["delta.tpu.checkpoint.async"] = True
            extra["delta.tpu.checkpoint.incremental"] = True
        if self.autopilot:
            extra["delta.tpu.autopilot.enabled"] = True
            extra["delta.tpu.autopilot.dryRun"] = False
            extra["delta.tpu.autopilot.cooldownMs"] = \
                self.autopilot_cooldown_ms
            extra["delta.tpu.autopilot.contentionBackoffMs"] = 500
        if self.distributed:
            # fast supervision: retries back off in single-digit ms, the
            # supervisor polls every 10ms, and leases expire after 1s so a
            # crashed step's orphan is recoverable within the same run
            extra["delta.tpu.distributed.retry.baseDelayMs"] = 1
            extra["delta.tpu.distributed.retry.maxDelayMs"] = 20
            extra["delta.tpu.distributed.retry.deadlineMs"] = 2_000
            extra["delta.tpu.distributed.supervisor.intervalMs"] = 10
            extra["delta.tpu.distributed.lease.ttlMs"] = 1_000
            extra["delta.tpu.distributed.lease.settleMs"] = 20
        with conf.set_temporarily(
            delta__tpu__faults__plan=self.plan,
            delta__tpu__storage__retry__baseDelayMs=1,
            delta__tpu__storage__retry__maxDelayMs=20,
            delta__tpu__storage__retry__deadlineMs=5_000,
            # small parts => multi-part checkpoints => torn checkpoints real
            delta__tpu__checkpointPartSize=8,
            **extra,
        ):
            # re-wrap under the plan now that it is installed
            self._log = self._fresh_log()
            from delta_tpu.utils import telemetry

            def _dist_counts():
                c = telemetry.counters("dist")
                return (c.get("dist.items.retried", 0),
                        c.get("dist.items.quarantined", 0),
                        c.get("dist.slice.recovered", 0))

            base = _dist_counts()
            for i in range(steps):
                self.step()
                if (i + 1) % check_every == 0:
                    self.check_invariants()
            self.check_invariants()
            end = _dist_counts()
            self.report.items_retried = end[0] - base[0]
            self.report.quarantined_groups = end[1] - base[1]
            self.report.slices_recovered = end[2] - base[2]
        self.report.steps = steps
        self.report.faults_injected = self.plan.total_injected()
        self.report.fault_kinds = self.plan.kinds_seen()
        self.report.per_point = {k: list(v) for k, v in self.plan.per_point.items()}
        return self.report


def run_torture(path: str, seed: int, steps: int,
                rate: float = 0.08, kinds=ALL_KINDS,
                check_every: int = 10,
                group_commit: bool = False,
                async_checkpoint: bool = False,
                autopilot: bool = False,
                distributed: bool = False) -> TortureReport:
    """One-call torture run: fresh harness, seeded plan, invariants on."""
    h = TortureHarness(path, seed, rate=rate, kinds=kinds,
                       group_commit=group_commit,
                       async_checkpoint=async_checkpoint,
                       autopilot=autopilot,
                       distributed=distributed)
    return h.run(steps, check_every=check_every)
