"""Deletion vectors: row-level tombstones instead of whole-file rewrites.

A beyond-reference feature (the 0.9 reference always rewrites files for DML,
`commands/DeleteCommand.scala:137-171`, `MergeIntoCommand.scala:456-561`).
Covers: the bitmap codec, DELETE/UPDATE/MERGE semantics parity with the
rewrite path, protocol gating at (3, 7), checkpoint round-trips, vacuum
sidecar retention, OPTIMIZE purge, and time travel across DV commits.
"""
import glob
import os

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import deletion_vectors as dv_mod
from delta_tpu.protocol.actions import Protocol

DV_PROPS = {"delta.tpu.enableDeletionVectors": "true"}


def make_table(path, n=100, dv=True, n_files=1):
    data = pa.table({
        "id": pa.array(range(n), pa.int64()),
        "value": pa.array([f"v{i}" for i in range(n)]),
    })
    t = DeltaTable.create(path, data=data, configuration=DV_PROPS if dv else None)
    for k in range(1, n_files):
        from delta_tpu.commands.write import WriteIntoDelta

        extra = pa.table({
            "id": pa.array(range(k * 1000, k * 1000 + n), pa.int64()),
            "value": pa.array([f"f{k}-{i}" for i in range(n)]),
        })
        WriteIntoDelta(t.delta_log, "append", extra).run()
    return t


def data_files(t):
    return {f.path for f in t.delta_log.update().all_files}


# -- codec --------------------------------------------------------------------


def test_bitmap_round_trip_random():
    rng = np.random.RandomState(3)
    rows = rng.choice(1_000_000, 5000, replace=False)
    got = dv_mod.decode_bitmap(dv_mod.encode_bitmap(rows))
    assert np.array_equal(got, np.sort(rows).astype(np.uint32))


def test_bitmap_round_trip_runs_and_edges():
    rows = np.array([0, 1, 2, 3, 1000, 1001, 2**32 - 1], np.uint32)
    assert np.array_equal(dv_mod.decode_bitmap(dv_mod.encode_bitmap(rows)), rows)


def test_bitmap_empty():
    assert dv_mod.decode_bitmap(dv_mod.encode_bitmap(np.array([], np.uint32))).size == 0


def test_bitmap_dedups():
    rows = np.array([5, 5, 5, 2], np.uint32)
    assert list(dv_mod.decode_bitmap(dv_mod.encode_bitmap(rows))) == [2, 5]


def test_descriptor_inline_vs_sidecar(tmp_path):
    d = str(tmp_path)
    small = dv_mod.write_deletion_vector(np.arange(10, dtype=np.uint32), d)
    assert small.storage_type == "i"
    assert small.cardinality == 10
    assert np.array_equal(dv_mod.read_deletion_vector(small, d), np.arange(10))
    rng = np.random.RandomState(1)
    big_rows = rng.choice(10_000_000, 200_000, replace=False)
    big = dv_mod.write_deletion_vector(big_rows, d)
    assert big.storage_type == "u"
    assert os.path.exists(os.path.join(d, big.path_or_inline_dv))
    assert np.array_equal(
        dv_mod.read_deletion_vector(big, d), np.sort(big_rows).astype(np.uint32)
    )


def test_bad_magic_rejected():
    with pytest.raises(ValueError):
        dv_mod.decode_bitmap(b"garbage-payload")


# -- DELETE -------------------------------------------------------------------


def test_delete_marks_rows_without_rewriting(tmp_table):
    t = make_table(tmp_table)
    before = data_files(t)
    m = t.delete("id < 10")
    assert m["numDeletedRows"] == 10
    after_files = t.delta_log.update().all_files
    assert {f.path for f in after_files} == before, "data file must be kept"
    assert after_files[0].deletion_vector is not None
    got = t.to_arrow()
    assert got.num_rows == 90
    assert min(got.column("id").to_pylist()) == 10


def test_delete_without_dv_property_rewrites(tmp_table):
    t = make_table(tmp_table, dv=False)
    before = data_files(t)
    t.delete("id < 10")
    assert data_files(t) != before, "non-DV table must rewrite the file"
    assert t.to_arrow().num_rows == 90


def test_second_delete_unions_dv(tmp_table):
    t = make_table(tmp_table)
    t.delete("id < 10")
    t.delete("id >= 90")
    got = t.to_arrow()
    assert got.num_rows == 80
    ids = got.column("id").to_pylist()
    assert min(ids) == 10 and max(ids) == 89
    f = t.delta_log.update().all_files[0]
    desc = dv_mod.DeletionVectorDescriptor.from_dict(f.deletion_vector)
    assert desc.cardinality == 20


def test_delete_all_rows_collapses_to_remove(tmp_table):
    t = make_table(tmp_table)
    t.delete("id >= 0")
    assert t.delta_log.update().all_files == []
    assert t.to_arrow().num_rows == 0


def test_delete_then_full_delete_via_dv_union(tmp_table):
    t = make_table(tmp_table)
    t.delete("id < 50")
    t.delete("id >= 50")
    assert t.delta_log.update().all_files == []


def test_whole_table_delete_still_metadata_only(tmp_table):
    t = make_table(tmp_table)
    m = t.delete()
    assert m["numDeletedRows"] == -1  # no data read (case 1)
    assert t.to_arrow().num_rows == 0


# -- UPDATE -------------------------------------------------------------------


def test_update_writes_only_changed_rows(tmp_table):
    t = make_table(tmp_table)
    original = data_files(t)
    m = t.update({"value": "'changed'"}, "id < 5")
    assert m["numUpdatedRows"] == 5
    files = t.delta_log.update().all_files
    paths = {f.path for f in files}
    assert original < paths, "original file kept, new rows file added"
    got = t.to_arrow()
    assert got.num_rows == 100
    vals = dict(zip(got.column("id").to_pylist(), got.column("value").to_pylist()))
    assert all(vals[i] == "changed" for i in range(5))
    assert vals[50] == "v50"
    # the small new file must NOT carry a DV; the original must
    by_path = {f.path: f for f in files}
    assert by_path[next(iter(original))].deletion_vector is not None


def test_update_parity_with_rewrite_path(tmp_table, tmp_path):
    t_dv = make_table(tmp_table)
    t_rw = make_table(str(tmp_path / "rw"), dv=False)
    for t in (t_dv, t_rw):
        t.update({"value": "'x'"}, "id % 10 = 3")
    a = sorted(t_dv.to_arrow().to_pylist(), key=lambda r: r["id"])
    b = sorted(t_rw.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert a == b


# -- MERGE --------------------------------------------------------------------


def merge_upsert(t, keys, new_vals):
    src = pa.table({"id": pa.array(keys, pa.int64()),
                    "value": pa.array(new_vals)})
    return (
        t.alias("t").merge(src, "t.id = s.id", source_alias="s")
        .when_matched_update_all()
        .when_not_matched_insert_all()
        .execute()
    )


def test_merge_upsert_with_dv(tmp_table):
    t = make_table(tmp_table)
    before = data_files(t)
    m = merge_upsert(t, [5, 6, 200, 201], ["U5", "U6", "N200", "N201"])
    assert m["numTargetRowsUpdated"] == 2
    assert m["numTargetRowsInserted"] == 2
    assert m["numTargetRowsCopied"] == 0, "DV merge must copy nothing"
    files = t.delta_log.update().all_files
    assert before < {f.path for f in files}
    got = t.to_arrow()
    assert got.num_rows == 102
    vals = dict(zip(got.column("id").to_pylist(), got.column("value").to_pylist()))
    assert vals[5] == "U5" and vals[200] == "N200" and vals[7] == "v7"


def test_merge_parity_dv_vs_rewrite(tmp_table, tmp_path):
    t_dv = make_table(tmp_table, n_files=3)
    t_rw = make_table(str(tmp_path / "rw"), dv=False, n_files=3)
    keys = [1, 2, 1005, 2050, 7777]
    vals = [f"m{k}" for k in keys]
    for t in (t_dv, t_rw):
        merge_upsert(t, keys, vals)
    a = sorted(t_dv.to_arrow().to_pylist(), key=lambda r: r["id"])
    b = sorted(t_rw.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert a == b


def test_merge_matched_delete_with_dv(tmp_table):
    t = make_table(tmp_table)
    src = pa.table({"id": pa.array([3, 4], pa.int64()),
                    "value": pa.array(["", ""])})
    m = (
        t.alias("t").merge(src, "t.id = s.id", source_alias="s")
        .when_matched_delete()
        .execute()
    )
    assert m["numTargetRowsDeleted"] == 2
    got = t.to_arrow()
    assert got.num_rows == 98
    assert 3 not in got.column("id").to_pylist()


def test_repeated_merges_accumulate_dv(tmp_table):
    t = make_table(tmp_table)
    for round_ in range(3):
        merge_upsert(t, [round_, 500 + round_], [f"u{round_}", f"n{round_}"])
    got = t.to_arrow()
    assert got.num_rows == 103
    vals = dict(zip(got.column("id").to_pylist(), got.column("value").to_pylist()))
    assert vals[0] == "u0" and vals[2] == "u2" and vals[502] == "n2"


# -- protocol gating ----------------------------------------------------------


def test_dv_table_gets_protocol_3_7(tmp_table):
    t = make_table(tmp_table)
    p = t.delta_log.update().protocol
    assert (p.min_reader_version, p.min_writer_version) == (3, 7)
    # table-features versions REQUIRE the feature lists
    assert "tpu.deletionVectors" in (p.reader_features or ())
    assert "tpu.deletionVectors" in (p.writer_features or ())


def test_reader_gate_refuses_unsupported_features(tmp_table):
    """A table-features table listing a feature this engine lacks (e.g. a
    real-Delta DV table with RoaringBitmap payloads) must be refused cleanly
    — not read with silently wrong results."""
    from tests.conftest import commit_manually, init_metadata
    from delta_tpu.utils.errors import ProtocolError

    log = DeltaLog.for_table(tmp_table)
    commit_manually(
        log, 0,
        [Protocol(3, 7, ("deletionVectors",), ("deletionVectors",)),
         init_metadata()],
    )
    with pytest.raises(ProtocolError):
        log.assert_protocol_read(log.update().protocol)


def test_reader_gate_refuses_version_2_column_mapping(tmp_table):
    from tests.conftest import commit_manually, init_metadata
    from delta_tpu.utils.errors import ProtocolError

    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [Protocol(2, 5), init_metadata()])
    with pytest.raises(ProtocolError):
        log.assert_protocol_read(log.update().protocol)


def test_reader_gate_refuses_v3_without_feature_list(tmp_table):
    """minReaderVersion=3 with NO readerFeatures key is spec-invalid (a
    foreign writer's malformed protocol action) — refuse, don't guess."""
    from tests.conftest import init_metadata
    from delta_tpu.protocol import filenames
    from delta_tpu.utils.errors import ProtocolError

    log = DeltaLog.for_table(tmp_table)
    log.store.write(
        f"{log.log_path}/{filenames.delta_file(0)}",
        ['{"protocol":{"minReaderVersion":3,"minWriterVersion":7}}',
         init_metadata().json()],
    )
    with pytest.raises(ProtocolError):
        log.assert_protocol_read(log.update().protocol)


def test_protocol_json_carries_feature_lists():
    p = Protocol(3, 7, ("tpu.deletionVectors",), ("tpu.deletionVectors",))
    d = p.to_dict()
    assert d["readerFeatures"] == ["tpu.deletionVectors"]
    assert d["writerFeatures"] == ["tpu.deletionVectors"]
    assert Protocol.from_dict(d) == p
    # legacy protocols stay bare (byte-compat with the reference)
    assert "readerFeatures" not in Protocol(1, 2).to_dict()


def test_non_dv_table_keeps_default_protocol(tmp_table):
    t = make_table(tmp_table, dv=False)
    p = t.delta_log.update().protocol
    assert p.min_reader_version == 1


def test_enabling_dv_on_pinned_3_7_declares_feature(tmp_table):
    """A table already AT (3,7) (pinned versions, no DV) must still get a
    Protocol action declaring tpu.deletionVectors when DVs are enabled —
    version comparison alone would skip it and commit undeclared DV files."""
    data = pa.table({"id": pa.array(range(10), pa.int64()),
                     "value": pa.array([f"v{i}" for i in range(10)])})
    t = DeltaTable.create(tmp_table, data=data, configuration={
        "delta.minReaderVersion": "3", "delta.minWriterVersion": "7",
    })
    p0 = t.delta_log.update().protocol
    assert (p0.min_reader_version, p0.min_writer_version) == (3, 7)
    assert "tpu.deletionVectors" not in (p0.reader_features or ())

    from delta_tpu.commands.alter import set_table_properties

    set_table_properties(t.delta_log, DV_PROPS)
    p = t.delta_log.update().protocol
    assert (p.min_reader_version, p.min_writer_version) == (3, 7)
    assert "tpu.deletionVectors" in (p.reader_features or ())
    assert "tpu.deletionVectors" in (p.writer_features or ())
    t.delete("id < 3")
    assert any(x.deletion_vector for x in t.delta_log.update().all_files)


def test_enabling_dv_later_bumps_protocol(tmp_table):
    t = make_table(tmp_table, dv=False)
    from delta_tpu.commands.alter import set_table_properties

    set_table_properties(t.delta_log, DV_PROPS)
    p = t.delta_log.update().protocol
    assert (p.min_reader_version, p.min_writer_version) == (3, 7)
    assert "tpu.deletionVectors" in (p.reader_features or ())
    t.delete("id < 10")
    f = t.delta_log.update().all_files
    assert any(x.deletion_vector for x in f)


# -- log/checkpoint round trip ------------------------------------------------


def test_dv_survives_checkpoint(tmp_table):
    t = make_table(tmp_table)
    t.delete("id < 25")
    t.delta_log.checkpoint()
    DeltaLog.clear_cache()
    t2 = DeltaTable.for_path(tmp_table)
    assert t2.to_arrow().num_rows == 75
    f = t2.delta_log.update().all_files[0]
    desc = dv_mod.DeletionVectorDescriptor.from_dict(f.deletion_vector)
    assert desc.cardinality == 25


def test_dv_survives_fresh_log_replay(tmp_table):
    t = make_table(tmp_table)
    t.delete("id >= 95")
    DeltaLog.clear_cache()
    t2 = DeltaTable.for_path(tmp_table)
    assert t2.to_arrow().num_rows == 95


def test_time_travel_before_dv_delete(tmp_table):
    t = make_table(tmp_table)
    v0 = t.version
    t.delete("id < 30")
    assert t.to_arrow(version=v0).num_rows == 100
    assert t.to_arrow().num_rows == 70


# -- vacuum / optimize --------------------------------------------------------


def test_vacuum_keeps_live_dv_sidecar(tmp_table, monkeypatch):
    # force sidecar storage (regular stride patterns compress below the
    # inline threshold, so pin it to zero for this test)
    monkeypatch.setattr(dv_mod, "INLINE_THRESHOLD_BYTES", 0)
    t = make_table(tmp_table, n=60_000)
    t.delete("id % 2 = 1")
    f = t.delta_log.update().all_files[0]
    desc = dv_mod.DeletionVectorDescriptor.from_dict(f.deletion_vector)
    assert desc.storage_type == "u"
    side = os.path.join(tmp_table, desc.path_or_inline_dv)
    assert os.path.exists(side)
    res = t.vacuum(retention_hours=0, retention_check_enabled=False)
    assert os.path.exists(side), "vacuum must not delete a referenced DV"
    assert t.to_arrow().num_rows == 30_000


def test_optimize_purges_dvs(tmp_table):
    t = make_table(tmp_table, n_files=3)
    t.delete("id % 7 = 0")
    assert any(f.deletion_vector for f in t.delta_log.update().all_files)
    expect = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    t.optimize().execute_compaction()
    files = t.delta_log.update().all_files
    assert all(f.deletion_vector is None for f in files), "compaction drops DVs"
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got == expect


def test_json_action_round_trip_with_dv(tmp_table):
    from delta_tpu.protocol.actions import AddFile, action_from_json

    desc = dv_mod.DeletionVectorDescriptor("i", "payload", 10, 3)
    a = AddFile("f1", {}, 1, 2, True, deletion_vector=desc.to_dict())
    back = action_from_json(a.json())
    assert back.deletion_vector == desc.to_dict()
    assert back.remove().deletion_vector == desc.to_dict()


def test_merge_device_path_with_dv(tmp_table):
    """Forced device join on a DV table: the key-projection reuse path must
    still carry physical positions for DV marking (bench-caught KeyError)."""
    from delta_tpu.utils.config import conf

    t = make_table(tmp_table, n=50)
    src = pa.table({"id": pa.array([5, 6, 999], pa.int64()),
                    "value": pa.array(["U5", "U6", "N"])})
    with conf.set_temporarily(**{"delta.tpu.merge.devicePath.mode": "force"}):
        m = (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
             .when_matched_update_all().when_not_matched_insert_all().execute())
    assert m["numTargetRowsUpdated"] == 2 and m["numTargetRowsInserted"] == 1
    got = t.to_arrow()
    vals = dict(zip(got.column("id").to_pylist(), got.column("value").to_pylist()))
    assert vals[5] == "U5" and vals[999] == "N" and vals[7] == "v7"
    assert got.num_rows == 51


def test_reorg_purge_rewrites_only_dv_files(tmp_table):
    """REORG/PURGE: exactly the DV-carrying files rewrite (deletes
    materialize, DVs drop); clean files stay byte-identical in place."""
    t = make_table(tmp_table, n_files=3)
    t.delete("id < 10")  # DVs land only on file 1 (ids 0..99)
    files_before = {f.path: f for f in t.delta_log.update().all_files}
    dv_paths = {p for p, f in files_before.items() if f.deletion_vector}
    clean_paths = set(files_before) - dv_paths
    assert len(dv_paths) == 1 and len(clean_paths) == 2

    m = t.optimize().execute_purge()
    assert m["numRemovedFiles"] == 1
    assert t.history()[0]["operation"] == "REORG"  # auditable, not OPTIMIZE
    files_after = {f.path: f for f in t.delta_log.update().all_files}
    assert clean_paths <= set(files_after), "clean files untouched"
    assert not (dv_paths & set(files_after)), "DV file replaced"
    assert all(f.deletion_vector is None for f in files_after.values())
    got = t.to_arrow()
    assert got.num_rows == 290
    assert min(v for v in got.column("id").to_pylist() if v < 1000) == 10


def test_purge_noop_without_dvs(tmp_table):
    t = make_table(tmp_table, n_files=2)
    m = t.optimize().execute_purge()
    assert m["numRemovedFiles"] == 0 and m["numAddedFiles"] == 0


def test_purge_is_rearrange_only_for_streams(tmp_table):
    """PURGE commits dataChange=false: a streaming source tailing the table
    must not re-emit or fail on the rewrite."""
    from delta_tpu.streaming.source import DeltaSource

    t = make_table(tmp_table)
    src = DeltaSource(t.delta_log)
    cur = src.initial_offset()
    end = src.latest_offset(cur)
    t.delete("id < 5")       # data change: needs ignore_* to pass -> use CDF-free path
    # consume up to the delete with ignore_changes
    src2 = DeltaSource(t.delta_log, ignore_changes=True)
    cur2 = src2.initial_offset()
    while True:
        nxt = src2.latest_offset(cur2)
        if nxt is None:
            break
        src2.get_batch(cur2, nxt)
        cur2 = nxt
    t.optimize().execute_purge()
    nxt = src2.latest_offset(cur2)
    if nxt is not None:
        batch = src2.get_batch(cur2, nxt)
        assert batch.num_rows == 0, "purge must not re-emit data"
