"""Operator observability layer (`delta_tpu/obs/`): the table-health doctor,
the per-query scan reports, the HTTP endpoint, and the failure flight
recorder — plus the blackout guarantee (everything off or zero-overhead when
``delta.tpu.telemetry.enabled=false``).
"""
import http.client
import json

import pyarrow as pa
import pytest

from tests.conftest import init_metadata

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands import operations as ops
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.obs import flight_recorder, metric_names
from delta_tpu.obs import scan_report as scan_report_mod
from delta_tpu.obs.doctor import SEVERITY_RANK, doctor
from delta_tpu.obs.scan_report import last_scan_report
from delta_tpu.obs.server import ObsServer
from delta_tpu.protocol.actions import AddFile, Metadata, RemoveFile
from delta_tpu.schema.types import IntegerType, StringType, StructType
from delta_tpu.utils import errors, telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_all()
    scan_report_mod.clear_last_report()
    yield
    telemetry.reset_all()


def _ids(n, start=0):
    import numpy as np

    return pa.table({"id": np.arange(start, start + n).astype("int64")})


# -- doctor ------------------------------------------------------------------


def test_doctor_on_known_debt_table(tmp_table):
    """Acceptance: a table with 200 tiny files, ~30% DV-deleted rows, and a
    stale checkpoint gets the expected severities and remedies."""
    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 10}):
        t = DeltaTable.create(
            tmp_table, data=_ids(2000),
            configuration={"delta.tpu.enableDeletionVectors": "true",
                           "delta.checkpointInterval": "1000"},
        )
    # every 10-row file soft-deletes 3 rows -> each file past the 30% purge
    # threshold, table 30% deleted
    t.delete("id % 10 < 3")
    # stale checkpoint: > 20 commits, none checkpointed (interval 1000)
    for i in range(21):
        t.write(_ids(10, start=10_000 + 10 * i))

    report = t.doctor()
    assert report.severity == "critical"

    ckpt = report.dimension("checkpoint")
    assert ckpt.severity == "warn" and ckpt.remedy == "CHECKPOINT"
    assert ckpt.metrics["commitsSince"] == report.version + 1  # never ckpted
    assert ckpt.metrics["tailBytes"] > 0

    small = report.dimension("smallFiles")
    assert small.severity == "critical" and small.remedy == "OPTIMIZE"
    assert small.metrics["count"] >= 200
    assert small.metrics["estReduction"] >= 200

    dv = report.dimension("dv")
    assert dv.severity == "critical" and dv.remedy == "PURGE"
    assert dv.metrics["deletedRows"] == 600
    # 600 of 2000 + 210 staleness-commit rows
    assert dv.metrics["deletedPct"] == pytest.approx(600 / 2210, abs=0.01)
    assert dv.metrics["filesPastPurge"] >= 200

    assert report.dimension("stats").severity == "ok"
    assert report.dimension("partition").severity == "ok"
    assert report.remedies()[0] in ("OPTIMIZE", "PURGE")
    assert set(report.remedies()) == {"OPTIMIZE", "PURGE", "CHECKPOINT"}

    # every number doubled as a catalog-registered table.health gauge
    gauges = telemetry.gauges("table.health")
    assert gauges, "doctor must publish gauges"
    for (name, labels) in gauges:
        assert name in metric_names.GAUGES, name
        assert ("path", tmp_table) in labels
    key = ("table.health.severity", (("path", tmp_table),))
    assert gauges[key] == SEVERITY_RANK["critical"]

    # the report is JSON-able end to end
    json.dumps(report.to_dict())


def test_doctor_empty_table(tmp_table):
    schema = StructType().add("id", IntegerType())
    t = DeltaTable.create(tmp_table, schema=schema)
    report = t.doctor()
    assert report.severity == "ok"
    assert report.num_files == 0
    assert all(d.severity == "ok" for d in report.dimensions)
    assert report.remedies() == []


def test_doctor_fully_removed_table_suggests_vacuum(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(100))
    t.delete()  # 100% of files removed
    report = t.doctor()
    assert report.num_files == 0
    tomb = report.dimension("tombstones")
    assert tomb.severity == "warn" and tomb.remedy == "VACUUM"
    assert tomb.metrics["count"] >= 1
    # no live files: the file-shape dimensions stay vacuous-ok
    assert report.dimension("smallFiles").severity == "ok"
    assert report.dimension("stats").severity == "ok"
    assert report.severity == "warn"


def test_doctor_zero_stats_coverage(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    txn = log.start_transaction()
    txn.update_metadata(init_metadata())
    txn.commit([], ops.ManualUpdate())
    txn = log.start_transaction()
    txn.commit(
        [AddFile(f"f{i}", {}, size=1, modification_time=1, stats=None)
         for i in range(3)],
        ops.Write(mode="Append"),
    )
    report = doctor(log)
    stats = report.dimension("stats")
    assert stats.severity == "critical" and stats.remedy == "OPTIMIZE"
    assert stats.metrics["coveragePct"] == 0.0


PART_SCHEMA = StructType().add("id", IntegerType()).add("p", StringType())


def _partitioned_log(tmp_table, sizes):
    log = DeltaLog.for_table(tmp_table)
    txn = log.start_transaction()
    txn.update_metadata(Metadata(schema_string=PART_SCHEMA.to_json(),
                                 partition_columns=["p"]))
    txn.commit([], ops.ManualUpdate())
    txn = log.start_transaction()
    txn.commit(
        [AddFile(f"p{i}/f{i}", {"p": f"p{i}"}, size=s, modification_time=1)
         for i, s in enumerate(sizes)],
        ops.Write(mode="Append"),
    )
    return log


def test_doctor_partition_skew(tmp_table):
    # one partition holds ~all bytes across 8 partitions
    log = _partitioned_log(tmp_table, [1 << 30] + [1] * 7)
    dim = doctor(log).dimension("partition")
    assert dim.severity == "critical" and dim.remedy == "REPARTITION"
    assert dim.metrics["count"] == 8
    assert dim.metrics["gini"] > 0.8


def test_doctor_balanced_partitions_ok(tmp_table):
    log = _partitioned_log(tmp_table, [1000] * 8)
    dim = doctor(log).dimension("partition")
    assert dim.severity == "ok" and dim.metrics["gini"] == 0.0


def test_describe_detail_gains_health_columns(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    d = t.detail()
    assert d["healthSeverity"] in ("ok", "warn", "critical")
    assert set(d["health"]) == {
        "checkpoint", "smallFiles", "dv", "stats", "partition",
        "tombstones", "protocol", "device", "distributed",
    }
    assert d["numCommitsSinceCheckpoint"] >= 1
    assert d["statsCoveragePct"] == 1.0
    assert d["numDeletionVectorFiles"] == 0
    assert d["numTombstones"] == 0


def test_maintenance_feeds_doctor_gauges(tmp_table):
    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 10}):
        t = DeltaTable.create(tmp_table, data=_ids(100))
    version = t.delta_log.update().version
    t.optimize().execute_compaction()
    g = telemetry.gauges("table.maintenance.lastOptimizeVersion")
    assert g[("table.maintenance.lastOptimizeVersion",
              (("path", tmp_table),))] == version + 1
    c = telemetry.counters("maintenance.optimize")
    assert c["maintenance.optimize.filesCompacted"] == 10
    assert c["maintenance.optimize.filesWritten"] >= 1

    t.vacuum(retention_hours=0, retention_check_enabled=False)
    g = telemetry.gauges("table.maintenance.lastVacuumTimestamp")
    assert g[("table.maintenance.lastVacuumTimestamp",
              (("path", tmp_table),))] > 0
    c = telemetry.counters("maintenance.vacuum")
    assert c["maintenance.vacuum.filesDeleted"] == 10
    assert c["maintenance.vacuum.bytesReclaimed"] > 0


# -- scan reports ------------------------------------------------------------


def test_scan_report_matches_rowgroup_counters_exactly(tmp_table):
    """Acceptance: last_scan_report() for a pruned query equals the
    scan.rowgroups.* / scan.bytes.* counter deltas."""
    with conf.set_temporarily(**{"delta.tpu.write.rowGroupRows": 1000}):
        t = DeltaTable.create(tmp_table, data=_ids(20_000))
    telemetry.reset_all()
    out = t.to_arrow(filters=["id < 1500"])
    assert out.num_rows == 1500
    rep = last_scan_report()
    assert rep is not None
    c = telemetry.counters("scan")
    assert rep.row_groups_total == c.get("scan.rowgroups.total", 0) > 0
    assert rep.row_groups_pruned == c.get("scan.rowgroups.pruned", 0) > 0
    assert rep.row_groups_late_skipped == c.get("scan.rowgroups.lateSkipped", 0)
    assert rep.bytes_skipped == c.get("scan.bytes.skipped", 0) > 0
    assert rep.bytes_read == c.get("scan.bytes.read", 0) > 0
    assert rep.files_scanned == c.get("scan.files.read", 0) == 1
    assert rep.rows_out == 1500
    assert rep.predicate == "(id < 1500)"
    assert set(rep.phase_ms) == {"planning", "read", "filter"}
    assert rep.version == t.delta_log.update().version
    json.dumps(rep.to_dict())


def test_scan_report_file_tier_pruning(tmp_table):
    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 1000}):
        t = DeltaTable.create(tmp_table, data=_ids(10_000))
    telemetry.reset_all()
    t.to_arrow(filters=["id < 500"])
    rep = last_scan_report()
    assert rep.files_total == 10
    assert rep.files_scanned == 1
    assert rep.files_pruned == 9


def test_scan_report_attached_to_scan_span(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(100))
    telemetry.clear_events()
    t.to_arrow()
    [scan] = [e for e in telemetry.recent_events("delta.scan")
              if e.op_type == "delta.scan"]
    assert scan.data["scanReport"] == last_scan_report().to_dict()


def test_failed_scan_does_not_overwrite_last_report(tmp_table, tmp_path):
    import os

    t = DeltaTable.create(tmp_table, data=_ids(100))
    t.to_arrow()
    good = last_scan_report()
    assert good is not None
    # corrupt the data file: the next scan raises mid-read
    snap = t.delta_log.update()
    data_file = os.path.join(tmp_table, snap.all_files[0].path)
    with open(data_file, "wb") as f:
        f.write(b"garbage")
    DeltaLog.clear_cache()
    with pytest.raises(Exception):
        DeltaTable.for_path(tmp_table).to_arrow()
    assert last_scan_report() is good  # half-filled report never published


def test_server_events_limit_zero(tmp_table):
    srv = ObsServer(port=0)
    try:
        DeltaTable.create(tmp_table, data=_ids(5))
        status, _, body = _get(srv, "/events?limit=0")
        assert status == 200 and json.loads(body) == []
    finally:
        srv.stop()


def test_streaming_backlog_capped(tmp_table):
    from delta_tpu.streaming.source import DeltaSource

    t = DeltaTable.create(tmp_table, data=_ids(10))
    source = DeltaSource(t.delta_log, max_files_per_trigger=1)
    start = source.initial_offset()
    end = source.latest_offset(start)
    for i in range(3):
        t.write(_ids(10, start=100 * (i + 1)))
    with conf.set_temporarily(delta__tpu__obs__streamingBacklogMaxFiles=2):
        source.get_batch(start, end)
    g = telemetry.gauges("streaming.source.backlogFiles")
    # the walk stops at the cap: the count is a floor, not the full tail
    assert g[("streaming.source.backlogFiles", (("path", tmp_table),))] == 2


def test_scan_report_blackout(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(100))
    scan_report_mod.clear_last_report()
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        out = t.to_arrow(filters=["id < 10"])
    assert out.num_rows == 10
    assert last_scan_report() is None


# -- streaming consumer lag --------------------------------------------------


def test_streaming_source_publishes_backlog_gauges(tmp_table):
    from delta_tpu.streaming.source import DeltaSource

    t = DeltaTable.create(tmp_table, data=_ids(10))
    source = DeltaSource(t.delta_log, max_files_per_trigger=1)
    # plan the snapshot batch at version 0...
    start = source.initial_offset()
    end = source.latest_offset(start)
    # ...then three single-file commits land before it is served
    for i in range(3):
        t.write(_ids(10, start=100 * (i + 1)))
    source.get_batch(start, end)

    g = telemetry.gauges("streaming.source")
    key = lambda name: (name, (("path", tmp_table),))  # noqa: E731
    # batch 0 served the snapshot (1 file admitted); 3 tail files pending
    assert g[key("streaming.source.backlogFiles")] == 3
    assert g[key("streaming.source.backlogBytes")] > 0
    assert g[key("streaming.source.lastBatchVersionLag")] == 3

    # drain fully: backlog falls to zero
    cur = end
    while True:
        nxt = source.latest_offset(cur)
        if nxt is None:
            break
        source.get_batch(cur, nxt)
        cur = nxt
    g = telemetry.gauges("streaming.source")
    assert g[key("streaming.source.backlogFiles")] == 0
    assert g[key("streaming.source.lastBatchVersionLag")] == 0


def test_streaming_backlog_not_tracked_in_blackout(tmp_table):
    from delta_tpu.streaming.source import DeltaSource

    t = DeltaTable.create(tmp_table, data=_ids(10))
    source = DeltaSource(t.delta_log)
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        start = source.initial_offset()
        end = source.latest_offset(start)
        batch = source.get_batch(start, end)
    assert batch.num_rows == 10
    assert telemetry.gauges("streaming.source") == {}


# -- HTTP endpoint -----------------------------------------------------------


@pytest.fixture
def obs_server():
    srv = ObsServer(port=0)
    yield srv
    srv.stop()


def _get(srv, route):
    c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        c.request("GET", route)
        r = c.getresponse()
        return r.status, r.getheader("Content-Type", ""), r.read()
    finally:
        c.close()


def test_server_healthz_and_metrics(tmp_table, obs_server):
    DeltaTable.create(tmp_table, data=_ids(10))
    status, ctype, body = _get(obs_server, "/healthz")
    assert status == 200 and ctype.startswith("application/json")
    health = json.loads(body)
    assert health["status"] == "ok"
    assert "footerCache" in health

    status, ctype, body = _get(obs_server, "/metrics")
    assert status == 200 and ctype.startswith("text/plain")
    assert body.decode() == telemetry.prometheus_text()
    assert b"commit_total_total" in body


def test_server_events_prefix_and_trace(tmp_table, obs_server):
    DeltaTable.create(tmp_table, data=_ids(10))
    status, _, body = _get(obs_server, "/events?prefix=delta.commit")
    assert status == 200
    events = json.loads(body)
    assert events and all(e["opType"].startswith("delta.commit")
                          for e in events)
    status, _, body = _get(obs_server, "/events?prefix=delta.commit&limit=1")
    assert len(json.loads(body)) == 1

    status, _, body = _get(obs_server, "/trace")
    trace = json.loads(body)
    assert {"delta.commit"} <= {r["name"] for r in trace["traceEvents"]}


def test_server_doctor_route_matches_in_process_report(tmp_table, obs_server):
    """Acceptance: GET /doctor?path= returns the same report as doctor()."""
    import urllib.parse

    with conf.set_temporarily(**{"delta.tpu.write.targetFileRows": 10}):
        t = DeltaTable.create(tmp_table, data=_ids(300))
    status, _, body = _get(
        obs_server, f"/doctor?path={urllib.parse.quote(tmp_table)}"
    )
    assert status == 200
    served = json.loads(body)
    local = doctor(t).to_dict()
    served.pop("generatedAt"), local.pop("generatedAt")
    assert served == json.loads(json.dumps(local))
    assert served["severity"] == "warn"  # 30 tiny files -> small-file debt
    assert "OPTIMIZE" in served["remedies"]


def test_server_error_routes(obs_server):
    status, _, body = _get(obs_server, "/doctor")
    assert status == 400
    status, _, body = _get(obs_server, "/doctor?path=/nowhere/nothing")
    assert status in (200, 500)  # nonexistent table -> empty report or error
    status, _, body = _get(obs_server, "/nope")
    assert status == 404
    assert "routes" in json.loads(body)


def test_server_garbage_query_params_never_500(tmp_table, obs_server):
    """Regression (ISSUE 15 satellite): `/events?limit=abc` 500'd through
    the bare int() while /router and /advisor degraded — every route's
    numeric params now share one degrading parser (`server._q_int`)."""
    import urllib.parse

    DeltaTable.create(tmp_table, data=_ids(10))
    quoted = urllib.parse.quote(tmp_table)
    routes = [
        "/events?limit=abc", "/events?limit=", "/events?limit=%20",
        "/events?prefix=delta.commit&limit=abc",
        "/router?limit=abc", "/router?limit=1e3",
        f"/advisor?path={quoted}&limit=abc",
        f"/autopilot?path={quoted}&limit=abc",
        f"/doctor?path={quoted}&limit=abc",   # ignored param: still fine
        "/autopilot?limit=abc",
        "/fleet?limit=abc&sweep=bogus&samples=xyz",
        "/fleet?series=&samples=abc",
        "/slo?limit=abc",
        "/metrics?limit=abc", "/healthz?limit=abc", "/trace?limit=abc",
    ]
    for route in routes:
        status, _, body = _get(obs_server, route)
        assert status == 200, (route, body)
    # a malformed limit behaves exactly like an absent one
    _, _, with_garbage = _get(obs_server, "/events?limit=abc")
    _, _, without = _get(obs_server, "/events")
    assert json.loads(with_garbage) == json.loads(without)
    # negative limits clamp to "none" rather than erroring
    status, _, body = _get(obs_server, "/events?limit=-3")
    assert status == 200 and json.loads(body) == []


def test_reply_swallows_client_abort():
    """A client hanging up mid-response must be counted, not logged as a
    500-on-a-dead-socket cascade."""
    from delta_tpu.obs.server import _Handler

    class _DeadWfile:
        def write(self, data):
            raise BrokenPipeError("client went away")

    class _FakeHandler:
        close_connection = False
        wfile = _DeadWfile()

        def send_response(self, status):
            pass

        def send_header(self, k, v):
            pass

        def end_headers(self):
            pass

    before = telemetry.counters("obs.server.clientAborts").get(
        "obs.server.clientAborts", 0)
    fake = _FakeHandler()
    _Handler._reply(fake, 200, b"payload", "application/json")  # no raise
    assert fake.close_connection
    after = telemetry.counters("obs.server.clientAborts")
    assert after["obs.server.clientAborts"] == before + 1

    class _ResetWfile:
        def write(self, data):
            raise ConnectionResetError("reset")

    fake = _FakeHandler()
    fake.wfile = _ResetWfile()
    _Handler._reply(fake, 200, b"payload", "application/json")
    assert telemetry.counters("obs.server.clientAborts")[
        "obs.server.clientAborts"] == before + 2


def test_server_fleet_and_slo_routes(tmp_table, obs_server):
    from delta_tpu.obs import fleet

    t = DeltaTable.create(tmp_table, data=_ids(10))
    status, _, body = _get(obs_server, "/fleet")
    assert status == 200
    doc = json.loads(body)
    assert doc["tables"] >= 1
    assert any(e["path"] == tmp_table for e in doc["entries"])
    assert doc["sweep"]["kind"] == "doctor"
    status, _, body = _get(obs_server, "/fleet?sweep=advisor&limit=1")
    doc = json.loads(body)
    assert doc["sweep"]["kind"] == "advisor"
    assert len(doc["sweep"]["entries"]) <= 1
    status, _, body = _get(obs_server, "/fleet?sweep=none&series=fleet")
    doc = json.loads(body)
    assert "sweep" not in doc and "series" in doc

    status, _, body = _get(obs_server, "/slo")
    assert status == 200
    doc = json.loads(body)
    assert {o["name"] for o in doc["objectives"]} == {
        "commitLatencyP99", "scanPlanningP99", "commitConflictRate",
        "retryExhaustion", "journalDropRate"}
    fleet.unregister(tmp_table)
    del t


def test_start_server_requires_opt_in():
    from delta_tpu.obs.server import start_server

    assert conf.get("delta.tpu.obs.port") is None
    with pytest.raises(ValueError):
        start_server()


def test_start_server_reads_conf_port():
    from delta_tpu.obs.server import start_server, stop_server

    with conf.set_temporarily(delta__tpu__obs__port=0):
        srv = start_server()
        try:
            status, _, _ = _get(srv, "/healthz")
            assert status == 200
            # idempotent: second call returns the same server
            assert start_server() is srv
        finally:
            stop_server()


# -- flight recorder ---------------------------------------------------------


def test_flight_recorder_off_by_default(tmp_path):
    assert conf.get("delta.tpu.obs.incidentDir") is None
    with pytest.raises(ValueError):
        with telemetry.record_operation("delta.test.noincident"):
            raise ValueError("boom")
    assert flight_recorder.incident_files(str(tmp_path)) == []


def test_commit_conflict_writes_one_incident_with_span_stack(tmp_table, tmp_path):
    """Acceptance: a forced commit conflict leaves exactly one incident JSON
    containing the failing span stack (commit -> write -> conflictCheck)."""
    inc_dir = str(tmp_path / "incidents")
    log = DeltaLog.for_table(tmp_table)
    txn = log.start_transaction()
    txn.update_metadata(init_metadata())
    txn.commit([], ops.ManualUpdate())
    log.start_transaction().commit(
        [AddFile("f0", {}, 1, 1)], ops.Write(mode="Append"))

    a = log.start_transaction()
    a.filter_files()
    b = log.start_transaction()
    b.filter_files()
    b.commit([RemoveFile("f0", deletion_timestamp=1)], ops.Delete())

    with conf.set_temporarily(delta__tpu__obs__incidentDir=inc_dir):
        with pytest.raises(errors.ConcurrentDeleteReadException):
            a.commit([AddFile("a1", {}, 1, 1)], ops.Write(mode="Append"))

    files = flight_recorder.incident_files(inc_dir)
    assert len(files) == 1, "one failure = one incident file"
    with open(files[0], encoding="utf-8") as f:
        incident = json.load(f)
    assert "ConcurrentDeleteReadException" in incident["error"]
    stack = [s["opType"] for s in incident["spanStack"]]
    assert stack == ["delta.commit", "delta.commit.write",
                     "delta.commit.retry.conflictCheck"]
    assert incident["opType"] == "delta.commit.retry.conflictCheck"
    assert incident["recentEvents"]  # ring-buffer tail rides along
    assert incident["counters"].get("commit.conflicts", 0) == 1
    assert telemetry.counters("obs.incidents") == {"obs.incidents.written": 1}


def test_flight_recorder_keep_bound(tmp_path):
    inc_dir = str(tmp_path / "incidents")
    with conf.set_temporarily(delta__tpu__obs__incidentDir=inc_dir,
                              delta__tpu__obs__incidentKeep=3):
        for i in range(5):
            with pytest.raises(ValueError):
                with telemetry.record_operation("delta.test.boom"):
                    raise ValueError(f"boom {i}")
    files = flight_recorder.incident_files(inc_dir)
    assert len(files) == 3
    kept = [json.load(open(f, encoding="utf-8"))["error"] for f in files]
    assert kept == ["ValueError: boom 2", "ValueError: boom 3",
                    "ValueError: boom 4"]  # oldest pruned first


def test_flight_recorder_nested_spans_single_incident(tmp_path):
    inc_dir = str(tmp_path / "incidents")
    with conf.set_temporarily(delta__tpu__obs__incidentDir=inc_dir):
        with pytest.raises(RuntimeError):
            with telemetry.record_operation("delta.test.outer"):
                with telemetry.record_operation("delta.test.outer.inner"):
                    raise RuntimeError("deep")
    files = flight_recorder.incident_files(inc_dir)
    assert len(files) == 1
    incident = json.load(open(files[0], encoding="utf-8"))
    # recorded at the innermost span: fullest stack
    assert [s["opType"] for s in incident["spanStack"]] == [
        "delta.test.outer", "delta.test.outer.inner"]


# -- blackout: obs layer is off or zero-overhead when telemetry is off -------


def test_obs_blackout_smoke(tmp_table, tmp_path):
    inc_dir = str(tmp_path / "incidents")
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False,
                              delta__tpu__obs__incidentDir=inc_dir):
        t = DeltaTable.create(tmp_table, data=_ids(100))
        # doctor still computes (pull-by-call is the operator asking) but
        # records no events
        report = t.doctor()
        assert report.severity in ("ok", "warn", "critical")
        assert telemetry.recent_events() == []
        # scans produce no reports
        scan_report_mod.clear_last_report()
        t.to_arrow(filters=["id < 5"])
        assert last_scan_report() is None
        # failing spans never reach the recorder: no incidents
        with pytest.raises(ValueError):
            with telemetry.record_operation("delta.test.dark"):
                raise ValueError("unseen")
    assert flight_recorder.incident_files(inc_dir) == []
