"""OptimisticTransaction — snapshot-pinned read/write with OCC commit.

Reference: ``OptimisticTransaction.scala:84-936``. A transaction pins the
table snapshot at creation, records what it reads (predicates, files, app
ids), stages metadata changes, and commits by atomically creating the next
``<v>.json``; on a lost race it replays winning commits through the conflict
checker (``delta_tpu.txn.conflicts``) and retries.
"""
from __future__ import annotations

import contextvars
import json
import logging
import uuid
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence

from delta_tpu.expr import ir
from delta_tpu.expr import partition as part
from delta_tpu.expr.parser import parse_expression
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import (
    DV_FEATURE_NAME,
    Action,
    AddCDCFile,
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    actions_from_lines,
)
from delta_tpu.schema import schema_utils
from delta_tpu.txn import conflicts as conflicts_mod
from delta_tpu.txn import isolation
from delta_tpu.utils.config import DeltaConfigs, conf
from delta_tpu.utils import errors
from delta_tpu.utils import retries as retries_mod
from delta_tpu.utils import telemetry
from delta_tpu.utils.telemetry import record_operation

logger = logging.getLogger(__name__)

__all__ = ["OptimisticTransaction", "CommitStats", "commit_attempts_cap",
           "effective_max_commit_attempts"]

_active_txn: "contextvars.ContextVar[Optional[OptimisticTransaction]]" = contextvars.ContextVar(
    "active_delta_txn", default=None
)

# Background-maintenance commit-attempts cap (delta_tpu/autopilot): a
# maintenance commit must LOSE gracefully to foreground writers instead of
# retry-storming through delta.tpu.maxCommitAttempts (10M) under the commit
# lock. Thread-confined by contextvar so a daemon's cap never leaks to
# foreground writers; the cap is stamped onto the txn at commit() time so
# the group-commit LEADER (a different thread) enforces the member's cap.
_commit_attempts_cap: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "delta_commit_attempts_cap", default=None
)


class commit_attempts_cap:
    """Context manager bounding commit attempts for transactions committed
    inside it: ``with commit_attempts_cap(3): OptimizeCommand(...).run()``.
    ``None``/``<= 0`` is a no-op (the registry default applies)."""

    def __init__(self, attempts: Optional[int]):
        self._attempts = int(attempts) if attempts else None
        self._token = None

    def __enter__(self) -> "commit_attempts_cap":
        if self._attempts and self._attempts > 0:
            self._token = _commit_attempts_cap.set(self._attempts)
        return self

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _commit_attempts_cap.reset(self._token)
        return False


def effective_max_commit_attempts(txn=None) -> int:
    """``delta.tpu.maxCommitAttempts`` bounded by any active
    :class:`commit_attempts_cap`. A txn that went through commit() carries
    its OWN stamp (``_attempts_cap``, possibly None = uncapped) and that
    stamp is authoritative — the current thread's contextvar must NOT be
    consulted for it, or a group-commit leader running inside a maintenance
    cap would leak the cap onto its foreground batchmates."""
    limit = conf.get("delta.tpu.maxCommitAttempts")
    if txn is not None and hasattr(txn, "_attempts_cap"):
        cap = txn._attempts_cap
    else:
        cap = _commit_attempts_cap.get()
    return min(limit, cap) if cap else limit


def commit_backoff_s(attempts: int) -> float:
    """Backoff before re-attempting a provably-not-landed ambiguous create —
    one policy, shared by the ungrouped retry loop and the group-commit
    leader (``txn/group_commit``)."""
    return min(0.05 * (2 ** min(attempts, 6)), 2.0)


def max_attempts_exceeded(attempts: int) -> "errors.CommitAttemptsExhausted":
    """The maxCommitAttempts exhaustion error, shared with the grouped path."""
    return errors.CommitAttemptsExhausted(
        f"This commit has failed as it has been tried {attempts - 1} times but did not succeed."
    )


@dataclass
class CommitStats:
    """Telemetry emitted per commit (``OptimisticTransaction.scala:45-71``)."""

    start_version: int = -1
    committed_version: int = -1
    attempts: int = 0
    txn_duration_ms: int = 0
    commit_duration_ms: int = 0
    num_add: int = 0
    num_remove: int = 0
    bytes_new: int = 0
    num_files_total: int = 0
    size_in_bytes_total: int = 0
    isolation_level: str = ""
    is_blind_append: bool = False
    # per-phase wall times: prepare / conflictCheck / write / postCommit
    phase_durations_ms: Dict[str, int] = field(default_factory=dict)

    def to_event_data(self) -> Dict[str, Any]:
        """The ``delta.commit.stats`` payload, reference CommitStats field
        names (``OptimisticTransaction.scala:45-71``)."""
        return {
            "readVersion": self.start_version,
            "commitVersion": self.committed_version,
            "attempts": self.attempts,
            "txnDurationMs": self.txn_duration_ms,
            "commitDurationMs": self.commit_duration_ms,
            "numAdd": self.num_add,
            "numRemove": self.num_remove,
            "bytesNew": self.bytes_new,
            "numFilesTotal": self.num_files_total,
            "sizeInBytesTotal": self.size_in_bytes_total,
            "isolationLevel": self.isolation_level,
            "isBlindAppend": self.is_blind_append,
            "phaseDurationsMs": dict(self.phase_durations_ms),
        }


class OptimisticTransaction:
    def __init__(self, delta_log, snapshot=None):
        self.delta_log = delta_log
        self.snapshot = snapshot if snapshot is not None else delta_log.snapshot
        self.read_version: int = self.snapshot.version
        self._start_ms = delta_log.clock()

        # read-set tracking (OptimisticTransaction.scala:167-179)
        self.read_predicates: List[ir.Expression] = []
        # keyed by path — AddFile carries dict fields and is not hashable
        self.read_files: Dict[str, AddFile] = {}
        self.read_the_whole_table: bool = False
        self.read_txn: List[str] = []

        # staged changes
        self.new_metadata: Optional[Metadata] = None
        self.new_protocol: Optional[Protocol] = None

        self._committed = False
        self.commit_isolation_level = isolation.WriteSerializable
        self.staged_removes: List[RemoveFile] = []
        self.post_commit_hooks: List = []
        self.operation_metrics: Dict[str, str] = {}
        self.user_metadata: Optional[str] = None
        # caller-supplied commit token (commitInfo.txnId): a distributed
        # slice records it in its lease BEFORE executing, so a coordinator
        # can later decide "did that host's commit land?" from the log alone
        # (parallel/leases.py orphan recovery) — the same ambiguous-outcome
        # reconciliation the token already serves inside _do_commit_retry
        self.preset_txn_id: Optional[str] = None
        self.stats = CommitStats(start_version=self.read_version)

    # -- ambient active transaction (scala:99-144) ----------------------

    @staticmethod
    def set_active(txn: "OptimisticTransaction"):
        if _active_txn.get() is not None:
            raise errors.DeltaIllegalStateError("Cannot set a new txn as active when one is already active")
        return _active_txn.set(txn)

    @staticmethod
    def clear_active(token) -> None:
        _active_txn.reset(token)

    @staticmethod
    def get_active() -> Optional["OptimisticTransaction"]:
        return _active_txn.get()

    # -- current view ----------------------------------------------------

    @property
    def metadata(self) -> Metadata:
        return self.new_metadata if self.new_metadata is not None else self.snapshot.metadata

    @property
    def protocol(self) -> Protocol:
        return self.new_protocol if self.new_protocol is not None else self.snapshot.protocol

    def txn_version(self, app_id: str) -> int:
        """Latest committed version for a streaming appId; records the read
        for conflict detection (``DeltaSink`` idempotency)."""
        self.read_txn.append(app_id)
        return self.snapshot.transaction_version(app_id)

    # -- metadata --------------------------------------------------------

    def update_metadata(self, metadata: Metadata) -> None:
        """Stage a metadata update; allowed once per txn, before writes
        (``OptimisticTransaction.scala:232-361``)."""
        if self._committed:
            raise errors.DeltaIllegalStateError("Cannot update metadata in a committed txn")
        if self.new_metadata is not None:
            raise errors.DeltaIllegalStateError("Cannot change the metadata more than once in a transaction.")
        if self.read_version == -1 or self.snapshot.metadata.schema_string is None:
            metadata = replace(
                metadata,
                configuration=DeltaConfigs.merge_global_configs(metadata.configuration),
            )
        if metadata.schema_string is not None:
            schema_utils.check_column_names(metadata.schema)
            schema_utils.check_partition_columns(metadata.partition_columns, metadata.schema)
            from delta_tpu.schema import generated as generated_mod

            generated_mod.validate_generated_columns(metadata.schema)
        cfg = DeltaConfigs.validate_configuration(metadata.configuration)
        metadata = replace(metadata, configuration=cfg)
        # keep table id stable across metadata updates
        if self.read_version >= 0 and self.snapshot.metadata.id:
            metadata = replace(metadata, id=self.snapshot.metadata.id)
        self.new_metadata = metadata
        self.new_protocol = self._required_protocol_upgrade(metadata)

    def _required_protocol_upgrade(self, metadata: Metadata) -> Optional[Protocol]:
        """Feature-driven minimum protocol (``actions.scala:124-159``)."""
        required_writer = 2
        props = metadata.configuration or {}
        schema = metadata.schema
        uses_generated = any(
            "delta.generationExpression" in (f.metadata or {}) for f in schema.fields
        )
        uses_constraints = any(k.lower().startswith("delta.constraints.") for k in props)
        uses_cdf = props.get("delta.enableChangeDataFeed", "false").lower() == "true"
        if uses_generated or uses_cdf:
            required_writer = 4
        elif uses_constraints:
            required_writer = max(required_writer, 3)
        required_reader = 1
        feature_names: set = set()
        if props.get("delta.tpu.enableDeletionVectors", "false").lower() == "true":
            # DV-bearing files change read semantics: table-features (3, 7)
            # with the engine's DV feature listed, so pre-DV engines refuse
            # the table instead of resurrecting deleted rows
            required_reader, required_writer = 3, 7
            feature_names.add(DV_FEATURE_NAME)
        pinned_reader = props.get("delta.minReaderVersion")
        pinned_writer = props.get("delta.minWriterVersion")
        cur = self.protocol
        new_reader = max(cur.min_reader_version, required_reader,
                         int(pinned_reader) if pinned_reader else 1)
        new_writer = max(cur.min_writer_version, required_writer if required_writer > 2 else cur.min_writer_version,
                         int(pinned_writer) if pinned_writer else 1)

        def _features(versions):
            # versions 3/7 REQUIRE the feature lists (table-features spec);
            # preserve any features the table already declares
            r, w = versions
            names = set(feature_names)
            names.update(cur.reader_features or ())
            names.update(cur.writer_features or ())
            rf = tuple(sorted(names)) if r >= 3 else None
            wf = tuple(sorted(names)) if w >= 7 else None
            return rf, wf

        if self.read_version == -1:
            # new table: start at spec default unless features demand more
            new_writer = max(2, required_writer, int(pinned_writer) if pinned_writer else 0)
            new_reader = max(1, required_reader, int(pinned_reader) if pinned_reader else 0)
            rf, wf = _features((new_reader, new_writer))
            return Protocol(new_reader, new_writer, rf, wf)
        if (new_reader, new_writer) != (cur.min_reader_version, cur.min_writer_version):
            rf, wf = _features((new_reader, new_writer))
            return Protocol(new_reader, new_writer, rf, wf)
        # Versions unchanged (e.g. table already pinned at (3,7)) but the
        # required feature set adds names the table doesn't declare yet:
        # still emit a Protocol action, or DV files would be committed with
        # the feature undeclared and foreign engines wouldn't refuse cleanly.
        if feature_names:
            rf, wf = _features((new_reader, new_writer))
            if (set(rf or ()) - set(cur.reader_features or ())
                    or set(wf or ()) - set(cur.writer_features or ())):
                return Protocol(new_reader, new_writer, rf, wf)
        return self.new_protocol

    # -- reads -----------------------------------------------------------

    def filter_files(self, predicates: Optional[Sequence] = None) -> List[AddFile]:
        """Files matching partition ``predicates``; records the read set
        (``OptimisticTransaction.scala:364-380``)."""
        exprs = [parse_expression(p) if isinstance(p, str) else p for p in (predicates or [])]
        pcols = self.metadata.partition_columns
        partition_preds = [e for e in exprs if part.is_partition_predicate(e, pcols)]
        if not exprs:
            self.read_predicates.append(ir.TRUE)
        else:
            self.read_predicates.extend(partition_preds if partition_preds else [ir.TRUE])
        matched = part.filter_files(self.snapshot.all_files, partition_preds, self.metadata)
        self.read_files.update({f.path: f for f in matched})
        return matched

    def read_whole_table(self) -> None:
        self.read_predicates.append(ir.TRUE)
        self.read_the_whole_table = True

    # -- commit ----------------------------------------------------------

    def commit(self, actions: Sequence[Action], op, tags: Optional[Dict[str, str]] = None) -> int:
        """Run the full commit pipeline; returns the committed version
        (``OptimisticTransaction.scala:422-490``)."""
        with record_operation("delta.commit", path=self.delta_log.data_path) as commit_ev:
            with record_operation("delta.commit.prepare", path=self.delta_log.data_path) as pev:
                actions = self._prepare_commit(list(actions))
            self.stats.phase_durations_ms["prepare"] = pev.duration_ms or 0

            if DeltaConfigs.SYMLINK_FORMAT_MANIFEST_ENABLED.from_metadata(self.metadata):
                from delta_tpu.hooks.symlink_manifest import SymlinkManifestHook

                self.register_post_commit_hook(SymlinkManifestHook())

            # Isolation pick (scala:432-440): rearrange-only commits can use
            # SnapshotIsolation; data-changing commits use the TABLE's level
            # (`delta.isolationLevel`, default WriteSerializable —
            # isolationLevels.scala:75), resolved through the config registry
            # so session-level defaults apply and only data-changing commits
            # ever consult (and validate) the stored value.
            no_data_changed = all(
                not a.data_change for a in actions if isinstance(a, (AddFile, RemoveFile))
            )
            if no_data_changed:
                self.commit_isolation_level = isolation.SnapshotIsolation
            else:
                self.commit_isolation_level = isolation.ALL_LEVELS[
                    DeltaConfigs.ISOLATION_LEVEL.from_metadata(self.metadata)
                ]

            # Blind-append detection (scala:442-447)
            only_add_files = all(
                isinstance(a, AddFile)
                for a in actions
                if isinstance(a, (AddFile, RemoveFile, AddCDCFile))
            )
            depends_on_files = bool(self.read_predicates) or bool(self.read_files)
            is_blind_append = only_add_files and not depends_on_files

            self.staged_removes = [a for a in actions if isinstance(a, RemoveFile)]

            # per-commit ownership token: if the log-entry create returns an
            # indeterminate error, re-reading version N and comparing this
            # token decides won/lost (never double-commit, never false-fail)
            self._commit_token = self.preset_txn_id or uuid.uuid4().hex
            # stamp any maintenance attempts cap now: the group-commit
            # leader runs on ANOTHER thread, where the contextvar is unset
            self._attempts_cap = _commit_attempts_cap.get()
            commit_info = CommitInfo(
                timestamp=self.delta_log.clock(),
                operation=op.name,
                operation_parameters=op.json_encoded_values,
                read_version=self.read_version if self.read_version >= 0 else None,
                isolation_level=self.commit_isolation_level.name,
                is_blind_append=is_blind_append,
                operation_metrics=self._final_metrics(op),
                user_metadata=self.user_metadata or op.user_metadata,
                engine_info="delta-tpu/0.1.0",
                txn_id=self._commit_token,
            )
            full_actions = [commit_info] + actions

            commit_start = self.delta_log.clock()
            with record_operation("delta.commit.write", path=self.delta_log.data_path) as wev:
                from delta_tpu.txn.group_commit import group_commit_enabled

                if group_commit_enabled():
                    # group commit: enqueue the prepared actions; a leader
                    # amortizes the tail read / conflict check / CAS across
                    # the batch (txn/group_commit.py). Off (the default),
                    # this branch is never taken and the path below is the
                    # unmodified ungrouped pipeline.
                    version = self.delta_log.group_coordinator.commit(
                        self, full_actions)
                    gm = getattr(self, "_group_meta", None)
                    if gm is not None:
                        self.stats.attempts = gm["attempts"]
                        self.stats.phase_durations_ms["conflictCheck"] = int(
                            gm["conflictCheckMs"])
                else:
                    version = self._do_commit_retry(full_actions)
            # conflictCheck runs inside the retry loop (so its span nests
            # under write); report the write phase NET of it, keeping the
            # phases additive: prepare+conflictCheck+write+postCommit ≈ commit
            self.stats.phase_durations_ms["write"] = max(
                0, (wev.duration_ms or 0)
                - self.stats.phase_durations_ms.get("conflictCheck", 0))
            self._committed = True

            self.stats.committed_version = version
            self.stats.commit_duration_ms = self.delta_log.clock() - commit_start
            self.stats.txn_duration_ms = self.delta_log.clock() - self._start_ms
            self.stats.isolation_level = self.commit_isolation_level.name
            self.stats.is_blind_append = is_blind_append
            self.stats.num_add = sum(isinstance(a, AddFile) for a in actions)
            self.stats.num_remove = sum(isinstance(a, RemoveFile) for a in actions)
            self.stats.bytes_new = sum(
                a.size for a in actions if isinstance(a, AddFile) and a.data_change
            )

            with record_operation("delta.commit.postCommit", path=self.delta_log.data_path) as hev:
                self._post_commit(version)
            self.stats.phase_durations_ms["postCommit"] = hev.duration_ms or 0

            # CommitStats parity: one delta.commit.stats event per commit
            # (the reference's `CommitStats` recordDeltaEvent), with the
            # command's operationMetrics riding along when history metrics
            # are enabled — the same gate as CommitInfo.operationMetrics.
            # function-level like every engine-side obs import — the obs
            # package must load lazily, not as an engine import side effect
            from delta_tpu.obs.fleet import table_label as _table_label

            stats_data = self.stats.to_event_data()
            stats_data["operation"] = op.name
            op_metrics = self._final_metrics(op)
            if op_metrics:
                stats_data["opMetrics"] = op_metrics
            gm = getattr(self, "_group_meta", None)
            if gm is not None:
                # grouped commits carry their batch evidence into the stats
                # event AND the journal entry below, so the advisor's
                # COMMIT_CONTENTION verdict cites measured queue waits and
                # batch sizes instead of inferring from time buckets
                stats_data["batchSize"] = gm["batchSize"]
                stats_data["queueWaitMs"] = round(gm["queueWaitMs"], 3)
                telemetry.observe("commit.queueWaitMs", gm["queueWaitMs"],
                                  path=self.delta_log.data_path,
                                  table=_table_label(self.delta_log.data_path))
            commit_ev.data.update(stats_data)
            telemetry.record_event(
                "delta.commit.stats", stats_data, path=self.delta_log.data_path
            )
            telemetry.bump_counter("commit.total")
            if self.stats.attempts > 1:
                telemetry.bump_counter("commit.retries", self.stats.attempts - 1)
            telemetry.observe(
                "delta.commit.duration_ms", self.stats.commit_duration_ms,
                path=self.delta_log.data_path,
                # hashed table label: the cross-table aggregation key the
                # fleet plane (obs/fleet, obs/slo) groups by
                table=_table_label(self.delta_log.data_path),
            )
            # workload journal: CommitStats + the reconcile outcome persist
            # across processes so the advisor can find contention windows
            # (buffered; inert under blackout / journal disabled)
            from delta_tpu.obs import journal as journal_mod

            journal_mod.record_commit(
                self.delta_log.log_path, stats_data,
                outcome=("reconciledWin"
                         if getattr(self, "_reconcile_outcome", None) is True
                         else "committed"),
            )
            return version

    # -- commit internals ------------------------------------------------

    def _prepare_commit(self, actions: List[Action]) -> List[Action]:
        """Validation + first-commit injection
        (``OptimisticTransaction.scala:496-579``)."""
        if self._committed:
            raise errors.DeltaIllegalStateError("Transaction already committed.")

        metadata_actions = [a for a in actions if isinstance(a, Metadata)]
        if self.new_metadata is not None:
            if metadata_actions:
                raise errors.DeltaIllegalStateError(
                    "Cannot change the metadata more than once in a transaction."
                )
            actions = [self.new_metadata] + actions
            metadata_actions = [self.new_metadata]
        if len(metadata_actions) > 1:
            raise errors.DeltaIllegalStateError(
                "Cannot change the metadata more than once in a transaction."
            )

        if self.new_protocol is not None:
            actions = [self.new_protocol] + actions

        if self.read_version == -1:
            # Initialize a brand-new table (scala:516-528)
            if not any(isinstance(a, Metadata) for a in actions):
                raise errors.DeltaIllegalStateError(
                    "Couldn't find required Metadata action to create the table's first commit."
                )
            if not any(isinstance(a, Protocol) for a in actions):
                actions = [self.protocol] + actions

        current_metadata = next(
            (a for a in actions if isinstance(a, Metadata)), self.metadata
        )
        if current_metadata.schema_string is None and any(
            isinstance(a, AddFile) for a in actions
        ):
            raise errors.DeltaIllegalStateError(
                "Table schema is not set. Write data to it or use CREATE TABLE to set the schema."
            )

        # AddFile partitioning consistency (scala:545-564)
        pcols = current_metadata.partition_columns
        for a in actions:
            if isinstance(a, AddFile):
                if sorted(a.partition_values.keys()) != sorted(pcols):
                    raise errors.DeltaIllegalStateError(
                        f"The AddFile contains partitioning schema different from the "
                        f"table's partitioning schema: {sorted(a.partition_values)} vs {sorted(pcols)}"
                    )

        # Append-only enforcement (scala:575-576). A deletion-vector re-add
        # logically deletes rows too — refuse it like a remove (first commit
        # exempt: a table may be CREATED/CLONED with pre-existing DVs).
        if DeltaConfigs.IS_APPEND_ONLY.from_metadata(current_metadata):
            for a in actions:
                if isinstance(a, RemoveFile) and a.data_change:
                    raise errors.modify_append_only_table()
                if (
                    self.read_version >= 0
                    and isinstance(a, AddFile)
                    and a.data_change
                    and a.deletion_vector is not None
                ):
                    raise errors.modify_append_only_table()

        # Protocol write gate for the (possibly updated) protocol
        proto = next((a for a in actions if isinstance(a, Protocol)), self.protocol)
        self.delta_log.assert_protocol_write(proto)

        # CDC writes are protocol-gated like the reference blocks them (actions.scala:151-156)
        if any(isinstance(a, AddCDCFile) for a in actions):
            if not DeltaConfigs.CHANGE_DATA_FEED.from_metadata(current_metadata):
                raise errors.DeltaUnsupportedOperationError(
                    "Cannot write change data files to a table without delta.enableChangeDataFeed=true"
                )
        return actions

    def _do_commit_retry(self, actions: List[Action]) -> int:
        """Retry loop (``doCommitRetryIteratively``, scala:610-642)."""
        max_attempts = effective_max_commit_attempts(self)
        attempt_version = self.read_version + 1
        attempts = 0
        with self.delta_log.lock:
            while True:
                attempts += 1
                self.stats.attempts = attempts
                if attempts > max_attempts:
                    raise max_attempts_exceeded(attempts)
                try:
                    self._write_commit(attempt_version, actions)
                    return attempt_version
                except FileExistsError:
                    attempt_version = self._check_and_retry(attempt_version, actions)
                # delta-lint: ignore[crash-except] -- transient-classified below
                # (non-transient re-raises); SimulatedCrash is BaseException and
                # pierces to the workload driver
                except Exception as e:  # noqa: BLE001 — classified below
                    if not retries_mod.is_transient(e):
                        raise
                    # Indeterminate outcome: the create MAY have landed (lost
                    # response). Resolve by reading version N back and
                    # comparing our commit token — never retry the create
                    # blind (double-commit), never fail a commit that won.
                    outcome = self._reconcile_ambiguous_commit(attempt_version, e)
                    if outcome is True:
                        return attempt_version
                    if outcome is False:
                        attempt_version = self._check_and_retry(attempt_version, actions)
                    else:
                        # None: version N provably absent — our write never
                        # happened and re-attempting the same version is
                        # safe. The create bypasses the retry layer by
                        # design, so back off HERE: a store whose writes
                        # flap persistently must not hot-loop through
                        # maxCommitAttempts reconciliations.
                        import time as _time

                        # delta-lint: ignore[lock-blocking] -- bounded backoff on
                        # the transient-ambiguous path only; the commit lock
                        # serializes in-process committers by design
                        _time.sleep(commit_backoff_s(attempts))

    def _write_commit(self, version: int, actions: List[Action]) -> None:
        path = f"{self.delta_log.log_path}/{filenames.delta_file(version)}"
        # Stamp CommitInfo with the version for history readers.
        out = []
        for a in actions:
            if isinstance(a, CommitInfo):
                a = a.with_version_timestamp(version)
            out.append(a.json())
        # delta-lint: ignore[lock-blocking] -- the commit CAS itself: the
        # in-process commit lock exists to serialize exactly this write
        self.delta_log.store.write(path, out, overwrite=False)

    def _reconcile_ambiguous_commit(self, version: int, cause: Exception) -> Optional[bool]:
        """Decide the outcome of a commit create that failed indeterminately
        (connection reset after the PUT may have landed). Re-reads version
        N's ``commitInfo.txnId`` and compares the per-commit token:

        * True  — the file is ours: the commit SUCCEEDED (the response was
          lost, not the write);
        * False — someone else owns version N: a plain lost race, go
          through the conflict checker;
        * None  — version N does not exist: our write provably never
          happened and the create is safe to re-attempt.

        ≈ the byte-equality disambiguation ``storage/http_store.py`` does
        per request, lifted to the transaction layer so EVERY store gets it.
        """
        path = f"{self.delta_log.log_path}/{filenames.delta_file(version)}"
        won: Optional[bool]
        try:
            # delta-lint: ignore[lock-blocking] -- reconciliation read-back of
            # version N must happen before the next attempt under the same lock
            lines = self.delta_log.store.read(path)
        except FileNotFoundError:
            won = None
        else:
            token = None
            if lines:
                try:
                    token = (json.loads(lines[0]).get("commitInfo") or {}).get("txnId")
                except (ValueError, AttributeError):
                    token = None
            won = token is not None and token == getattr(self, "_commit_token", None)
            if won is False:
                # a lost race re-enters _check_and_retry at exactly this
                # version: seed the tail cache so the file isn't re-read
                try:
                    tail = getattr(self, "_tail_cache", None)
                    if tail is None:
                        tail = self._tail_cache = {}
                    tail[version] = actions_from_lines(lines)
                except Exception:  # noqa: BLE001 — cache only, never fatal
                    pass
        outcome = {True: "won", False: "lost", None: "not_landed"}[won]
        self._reconcile_outcome = won
        telemetry.bump_counter("commit.reconciled")
        telemetry.record_event(
            "delta.commit.reconcile",
            {"version": version, "won": won, "outcome": outcome,
             "cause": f"{type(cause).__name__}: {cause}"},
            path=self.delta_log.data_path,
        )
        logger.warning(
            "Ambiguous commit outcome at version %s for %s reconciled: %s (%s)",
            version, self.delta_log.data_path, outcome, cause,
        )
        return won

    def _note_logical_conflict(self, conflict_version: int) -> None:
        """A genuine logical conflict (not just a lost race): count it and
        journal the aborted attempt — contention analysis needs the
        failures too. Shared by the ungrouped retry loop and the group-
        commit leader (``txn/group_commit``)."""
        telemetry.bump_counter("commit.conflicts")
        from delta_tpu.obs import journal as journal_mod

        journal_mod.record_commit(
            self.delta_log.log_path,
            {"readVersion": self.read_version,
             "attempts": self.stats.attempts,
             "conflictVersion": conflict_version},
            outcome="conflict",
        )

    def _check_and_retry(self, failed_version: int, actions: List[Action]) -> int:
        """Replay winning commits through the conflict checker
        (``checkForConflicts``); returns the next version to attempt.

        Tail actions are cached per transaction (``_tail_cache``): across an
        N-attempt retry each winning commit file is read ONCE — a version
        already fetched by a previous attempt, by the ambiguous-commit
        reconciliation read, or by the group-commit leader's shared tail
        snapshot is served from the cache instead of re-read."""
        with record_operation("delta.commit.retry.conflictCheck", path=self.delta_log.data_path) as cev:
            tail = getattr(self, "_tail_cache", None)
            if tail is None:
                tail = self._tail_cache = {}
            next_attempt = failed_version
            while True:
                winning = tail.get(next_attempt)
                if winning is None:
                    path = f"{self.delta_log.log_path}/{filenames.delta_file(next_attempt)}"
                    try:
                        # delta-lint: ignore[lock-blocking] -- conflict-check tail
                        # read; each winner fetched once (cached) under the lock
                        winning = actions_from_lines(self.delta_log.store.read_iter(path))
                    except FileNotFoundError:
                        break
                    tail[next_attempt] = winning
                try:
                    conflicts_mod.check_for_conflicts(self, next_attempt, winning)
                except errors.DeltaConcurrentModificationException:
                    # let the error unwind through the open conflictCheck
                    # span — the obs flight recorder snapshots the failing
                    # span stack from there. Other exceptions (bugs,
                    # interrupts) propagate uncounted.
                    self._note_logical_conflict(next_attempt)
                    raise
                next_attempt += 1
            # checked windows never overlap (the next one starts at
            # next_attempt), so consumed entries are dead weight: evict them
            # and keep the cache O(1) across a long retry storm instead of
            # accumulating every winning commit's actions for the txn's life
            for v in [v for v in tail if v < next_attempt]:
                del tail[v]
            cev.data["winningCommits"] = next_attempt - failed_version
            if next_attempt == failed_version:
                # The write failed but the file doesn't exist: storage lied about
                # mutual exclusion (scala:683-691).
                raise errors.concurrent_write_exception()
        # duration_ms is stamped when the span closes; accumulate across the
        # retry loop's successive conflict checks
        self.stats.phase_durations_ms["conflictCheck"] = (
            self.stats.phase_durations_ms.get("conflictCheck", 0)
            + (cev.duration_ms or 0)
        )
        return next_attempt

    def _post_commit(self, version: int) -> None:
        """Checkpointing, checksum, hooks (scala:582-594, 880-915)."""
        snapshot = None
        if getattr(self, "_group_meta", None) is not None:
            # grouped: the leader installed one post-batch snapshot for the
            # whole batch — reuse it instead of K per-member re-listings.
            # Consequence: the version-checksum guard below only fires for
            # the batch-final member, so intermediate versions get no .crc
            # — the same advisory skip the ungrouped path takes whenever a
            # racing writer advances the snapshot past the committed
            # version (validators treat a missing .crc as nothing to check)
            snap = self.delta_log.unsafe_volatile_snapshot
            if snap is not None and snap.version >= version:
                snapshot = snap
        if snapshot is None:
            snapshot = self.delta_log.update_after_commit(version)
        if snapshot.version == version:
            self.delta_log.write_checksum_for(snapshot)
        interval = DeltaConfigs.CHECKPOINT_INTERVAL.from_metadata(self.metadata)
        if version != 0 and version % interval == 0:
            if conf.get_bool("delta.tpu.checkpoint.async", False):
                # off the committing writer's critical path: the background
                # checkpoint daemon builds it (incrementally when
                # delta.tpu.checkpoint.incremental is on)
                from delta_tpu.log import checkpointer

                checkpointer.request_checkpoint(self.delta_log, version)
            else:
                try:
                    self.delta_log.checkpoint(
                        snapshot if snapshot.version == version else self.delta_log.get_snapshot_at(version)
                    )
                except Exception:  # noqa: BLE001 — checkpointing must not fail the commit
                    logger.warning("Post-commit checkpoint at version %s failed", version, exc_info=True)
        for hook in self.post_commit_hooks:
            try:
                hook.run(self, version, snapshot)
            except Exception as e:  # noqa: BLE001
                logger.warning("Post-commit hook %s failed: %s", getattr(hook, "name", hook), e)
                handler = getattr(hook, "handle_error", None)
                if handler:
                    handler(e, version)

    def register_post_commit_hook(self, hook) -> None:
        if hook not in self.post_commit_hooks:
            self.post_commit_hooks.append(hook)

    def _final_metrics(self, op) -> Optional[Dict[str, str]]:
        if not conf.get("delta.tpu.history.metricsEnabled"):
            return None
        if not self.operation_metrics:
            return None
        whitelist = set(op.metric_whitelist)
        if not whitelist:
            return dict(self.operation_metrics)
        return {k: v for k, v in self.operation_metrics.items() if k in whitelist}

    def report_metrics(self, **metrics: Any) -> None:
        """DML rewrite metrics — one layer feeding both
        ``CommitInfo.operationMetrics`` (DESCRIBE HISTORY) and the enclosing
        telemetry span (``delta.dml.*``), so MERGE's numTargetRowsUpdated et
        al. show up on the trace without a second bookkeeping path."""
        for k, v in metrics.items():
            self.operation_metrics[k] = str(v)
        if conf.get("delta.tpu.history.metricsEnabled"):
            telemetry.add_span_data(**{k: str(v) for k, v in metrics.items()})
