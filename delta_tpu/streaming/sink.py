"""Streaming sink — exactly-once micro-batch writes.

Mirrors `sources/DeltaSink.scala:37-113`: each `addBatch(batchId, data)`
commits inside one transaction carrying `SetTransaction(queryId, batchId)`;
a replayed batch (engine retry / query restart) is detected by
``txn.txn_version(queryId) >= batchId`` and skipped (`:87-91,100`). Complete
output mode removes all existing files first (`:93-98`).
"""
from __future__ import annotations

import time
from typing import Any, List, Sequence

from delta_tpu.commands import operations as ops
from delta_tpu.commands.write import coerce_to_table, update_metadata_on_write
from delta_tpu.exec import write as write_exec
from delta_tpu.protocol.actions import Action, SetTransaction
from delta_tpu.schema.arrow_interop import schema_from_arrow
from delta_tpu.utils.errors import DeltaIllegalArgumentError

__all__ = ["DeltaSink"]


class DeltaSink:
    def __init__(
        self,
        delta_log,
        query_id: str,
        output_mode: str = "append",
        partition_columns: Sequence[str] = (),
        merge_schema: bool = False,
    ):
        if output_mode not in ("append", "complete"):
            raise DeltaIllegalArgumentError(
                f"Data source delta does not support {output_mode} output mode"
            )
        self.delta_log = delta_log
        self.query_id = query_id
        self.output_mode = output_mode
        self.partition_columns = list(partition_columns)
        self.merge_schema = merge_schema

    def add_batch(self, batch_id: int, data: Any) -> bool:
        """Write one micro-batch; returns False when the batch was already
        committed (idempotent skip)."""
        from delta_tpu.utils import telemetry

        with telemetry.record_operation(
            "delta.streaming.sink.addBatch",
            {"batchId": batch_id, "queryId": self.query_id},
            path=self.delta_log.data_path,
        ) as bev:
            committed = self._add_batch_impl(batch_id, data, bev)
        if bev.duration_ms is not None:  # unmeasured (telemetry disabled)
            telemetry.observe(
                "delta.streaming.sink.batch_ms", bev.duration_ms,
                path=self.delta_log.data_path,
            )
        return committed

    def _add_batch_impl(self, batch_id: int, data: Any, bev) -> bool:
        table = coerce_to_table(data)
        bev.data["numInputRows"] = table.num_rows

        def body(txn) -> bool:
            if txn.txn_version(self.query_id) >= batch_id:
                return False  # already committed by a previous attempt
            update_metadata_on_write(
                txn,
                schema_from_arrow(table.schema),
                self.partition_columns or txn.metadata.partition_columns,
                is_overwrite=self.output_mode == "complete",
                merge_schema=self.merge_schema,
                overwrite_schema=False,
            )
            metadata = txn.metadata
            actions: List[Action] = [
                SetTransaction(
                    app_id=self.query_id,
                    version=batch_id,
                    last_updated=int(time.time() * 1000),
                )
            ]
            if self.output_mode == "complete":
                txn.read_whole_table()
                actions.extend(f.remove() for f in txn.filter_files())
            actions.extend(
                write_exec.write_files(
                    self.delta_log.data_path, table, metadata, data_change=True
                )
            )
            op = ops.StreamingUpdate(
                output_mode=self.output_mode,
                query_id=self.query_id,
                epoch_id=batch_id,
            )
            txn.commit(actions, op)
            return True

        committed = self.delta_log.with_new_transaction(body)
        bev.data["committed"] = committed
        from delta_tpu.utils.telemetry import bump_counter

        bump_counter("streaming.sink.batches" if committed
                     else "streaming.sink.batchesSkipped")
        return committed
