"""Pool-naming pass: every thread and pool carries a registered lane name.

The Chrome-trace export labels Perfetto lanes from thread names
(``telemetry.export_chrome_trace`` thread_name metadata), and
``adopt_span_context`` propagation audits assume worker provenance is
readable from the thread name. An anonymous ``Thread()`` or
``ThreadPoolExecutor()`` shows up as ``Thread-N`` — an unattributable
lane. Rule:

``pool-name``
    Every ``threading.Thread(...)`` construction passes ``name=`` and every
    ``ThreadPoolExecutor(...)`` passes ``thread_name_prefix=``, as a string
    constant present in :data:`REGISTERED_POOLS` below. The registry IS
    this module — adding a pool means adding its name here, which is
    exactly the reviewable event the pass exists to force.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from delta_tpu.analysis.core import AnalysisContext, AnalysisPass, Finding
from delta_tpu.analysis.modgraph import terminal_name

__all__ = ["PoolNamingPass", "REGISTERED_POOLS"]

#: Every engine thread/pool lane name. Perfetto lanes and the thread-name
#: metadata rows in export_chrome_trace render these verbatim.
REGISTERED_POOLS = frozenset({
    # pools (ThreadPoolExecutor thread_name_prefix)
    "delta-parquet-read",         # exec/parquet.py decode pool
    "delta-parquet-write",        # exec/write.py write pool
    "delta-scan-decode",          # exec/scan.py scan decode pool
    "delta-ckpt-part",            # log/checkpoints.py part writers
    "delta-ckpt-decode",          # log/columnar.py part decoders
    "delta-vacuum-list",          # commands/vacuum.py partition listing
    "delta-vacuum-delete",        # commands/vacuum.py parallel delete
    "delta-replay-prep",          # replay/shadow.py candidate clone prep
    "delta-dist-exec",            # parallel/executor.py sharded work items
    # dedicated threads (threading.Thread name)
    "delta-dist-supervisor",      # parallel/executor.py heartbeat watchdog
    "delta-ckpt-async",           # log/checkpointer.py coalescing daemon
    "delta-journal-writer",       # obs/journal.py writer daemon
    "delta-state-update",         # log/deltalog.py async snapshot refresh
    "delta-obs-server",           # obs/server.py HTTP endpoint
    "delta-merge-slab-upload",    # commands/merge.py slab uploader
    "delta-merge-device-probe",   # ops/key_cache.py probe staging thread
    "delta-merge-keys-build",     # commands/merge.py background key build
    "delta-join-upload",          # ops/join_kernel.py async kernel launch
    "delta-object-store-http",    # storage/object_store_emulator.py server
    "delta-autopilot",            # autopilot/daemon.py maintenance daemon
    "delta-obs-scraper",          # obs/timeseries.py metrics scraper daemon
})

_CTOR_KW = {
    "Thread": "name",
    "ThreadPoolExecutor": "thread_name_prefix",
}


def _name_kwarg(call: ast.Call, kwarg: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == kwarg:
            return kw.value
    return None


class PoolNamingPass(AnalysisPass):
    name = "pool-naming"
    description = ("Thread/ThreadPoolExecutor constructions carry a "
                   "registered delta-* lane name")
    rules = ("pool-name",)

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        for sf in ctx.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                ctor = terminal_name(node.func)
                kwarg = _CTOR_KW.get(ctor or "")
                if kwarg is None:
                    continue
                value = _name_kwarg(node, kwarg)
                if value is None:
                    out.append(Finding(
                        "pool-name", sf.rel, node.lineno,
                        f"{ctor} constructed without {kwarg}= — the lane "
                        f"is unattributable in Perfetto; pass a name "
                        f"registered in analysis/passes/pool_naming.py"))
                    continue
                if not (isinstance(value, ast.Constant)
                        and isinstance(value.value, str)):
                    out.append(Finding(
                        "pool-name", sf.rel, node.lineno,
                        f"{ctor} {kwarg}= must be a string constant so the "
                        f"lane registry stays statically checkable"))
                    continue
                if value.value not in REGISTERED_POOLS:
                    out.append(Finding(
                        "pool-name", sf.rel, node.lineno,
                        f"{ctor} lane name '{value.value}' is not in the "
                        f"registered pool registry "
                        f"(analysis/passes/pool_naming.py)"))
        return out
