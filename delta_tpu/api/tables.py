"""User-facing table API — the `DeltaTable` / `DeltaMergeBuilder` surface.

Mirrors `python/delta/tables.py` (`DeltaTable :23`, `DeltaMergeBuilder :425`)
and the Scala `io/delta/tables/DeltaTable.scala:45-547` +
`DeltaMergeBuilder.scala:123-457`: forPath / isDeltaTable / convertToDelta,
alias, toArrow (the engine's DataFrame analogue), delete / update /
updateExpr, the fluent merge builder, vacuum, history, detail, generate,
upgradeTableProtocol — plus optimize/Z-order, which the reference's format
supports but its API doesn't ship.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import pyarrow as pa

from delta_tpu.commands.convert import ConvertToDeltaCommand
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.describe import describe_detail, describe_history
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.optimize import OptimizeCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.vacuum import VacuumCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.expr import ir
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol.actions import Protocol
from delta_tpu.schema.types import StructType
from delta_tpu.utils import errors

__all__ = ["DeltaTable", "DeltaMergeBuilder", "DeltaOptimizeBuilder"]


class DeltaTable:
    """Programmatic handle on a Delta table (`tables.py:23`)."""

    def __init__(self, delta_log: DeltaLog, alias: Optional[str] = None,
                 default_version: Optional[int] = None,
                 default_timestamp=None):
        self.delta_log = delta_log
        self._alias = alias
        # pinned by `path@v123` / `path@yyyyMMddHHmmssSSS` identifiers:
        # reads resolve here unless the call passes explicit options
        self._default_version = default_version
        self._default_timestamp = default_timestamp

    # -- constructors -----------------------------------------------------

    @classmethod
    def for_path(cls, path: str, store=None, clock=None) -> "DeltaTable":
        log = DeltaLog.for_table(path, store=store, clock=clock)
        if not log.table_exists:
            # `path@v123` embedded time travel (`DeltaTimeTravelSpec.scala
            # :137`): only when the literal path is not itself a table
            from delta_tpu.log.deltalog import extract_path_time_travel

            spec = extract_path_time_travel(path)
            if spec is not None:
                base, v, ts = spec
                base_log = DeltaLog.for_table(base, store=store, clock=clock)
                if base_log.table_exists:
                    return cls(base_log, default_version=v,
                               default_timestamp=ts)
            raise errors.not_a_delta_table(path)
        return cls(log)

    @classmethod
    def for_name(cls, name: str, catalog=None) -> "DeltaTable":
        """Resolve a table by catalog name (``DeltaTable.forName :690``;
        `catalog/catalog.py`). ``delta.`/path``` identifiers bypass the
        catalog."""
        from delta_tpu.catalog.catalog import resolve_identifier

        return cls.for_path(resolve_identifier(name, catalog))

    @classmethod
    def is_delta_table(cls, path: str) -> bool:
        """``DeltaTable.isDeltaTable :726``; unreadable paths are False."""
        try:
            return DeltaLog.for_table(path).table_exists
        except Exception:
            return False

    @classmethod
    def convert_to_delta(cls, path: str,
                         partition_schema: Optional[StructType] = None) -> "DeltaTable":
        log = DeltaLog.for_table(path)
        ConvertToDeltaCommand(log, partition_schema=partition_schema).run()
        return cls(log)

    @classmethod
    def create(cls, path: str, schema: Optional[StructType] = None,
               partition_columns: Sequence[str] = (),
               configuration: Optional[Dict[str, str]] = None,
               data: Any = None, mode: str = "create") -> "DeltaTable":
        """CREATE [OR REPLACE] TABLE [AS SELECT] (`commands/create.py` ≈
        `CreateDeltaTableCommand.scala`). ``mode`` is one of ``create``,
        ``create_if_not_exists``, ``replace``, ``create_or_replace``;
        ``data`` makes it a CTAS."""
        from delta_tpu.commands.create import CreateDeltaTableCommand

        log = DeltaLog.for_table(path)
        CreateDeltaTableCommand(
            log, schema=schema, mode=mode,
            partition_columns=partition_columns, configuration=configuration,
            data=data,
        ).run()
        return cls(log)

    @classmethod
    def replace(cls, path: str, schema: Optional[StructType] = None,
                partition_columns: Sequence[str] = (),
                configuration: Optional[Dict[str, str]] = None,
                data: Any = None, or_create: bool = False) -> "DeltaTable":
        """REPLACE TABLE / CREATE OR REPLACE TABLE [AS SELECT]."""
        return cls.create(
            path, schema, partition_columns, configuration, data,
            mode="create_or_replace" if or_create else "replace",
        )

    # -- reads ------------------------------------------------------------

    def alias(self, name: str) -> "DeltaTable":
        return DeltaTable(self.delta_log, alias=name,
                          default_version=self._default_version,
                          default_timestamp=self._default_timestamp)

    def to_arrow(self, filters: Sequence[Union[str, ir.Expression]] = (),
                 columns: Optional[Sequence[str]] = None,
                 version: Optional[int] = None,
                 timestamp: Optional[Union[str, int]] = None) -> pa.Table:
        """Read the table (optionally time-traveled) as an Arrow table —
        the engine's `toDF` (`DeltaTable.scala` toDF + time-travel options)."""
        snap = self._snapshot(version, timestamp)
        return scan_to_table(snap, filters, columns)

    def _snapshot(self, version: Optional[int] = None,
                  timestamp: Optional[Union[str, int]] = None):
        # reads may serve within the staleness window (background refresh);
        # copy-like surfaces resolve their own snapshots synchronously
        if version is None and timestamp is None:
            version = self._default_version
            timestamp = self._default_timestamp
        return self.delta_log.snapshot_for(version, timestamp, stale_ok=True)

    def plan_queries(self, queries, k: int = 256):
        """Plan a batch of queries in one shot — each element is a list of
        filter strings/expressions; returns per-query
        :class:`delta_tpu.exec.scan.QueryPlan` (pruned file paths + exact
        counts). With the table's scan lanes HBM-resident
        (`ops/state_cache`), the whole batch is a single device dispatch —
        the serving shape for dashboards / query routers."""
        from delta_tpu.exec.scan import plan_scans
        from delta_tpu.utils import errors

        for q in queries:
            if isinstance(q, (str, ir.Expression)):
                raise errors.DeltaIllegalArgumentError(
                    "plan_queries takes a list of QUERIES, each a list of "
                    f"filters — wrap the filter in a list: [[{q!r}]]"
                )
        return plan_scans(self._snapshot(), queries, k=k)

    @property
    def version(self) -> int:
        return self._snapshot().version

    def schema(self) -> StructType:
        return self._snapshot().metadata.schema

    # -- writes -----------------------------------------------------------

    def write(self, data: Any, mode: str = "append", **options) -> int:
        self._check_mutable("write to")
        return WriteIntoDelta(self.delta_log, mode, data, **options).run()

    def _check_mutable(self, operation: str) -> None:
        """DML on a `path@v` / `path@timestamp` pinned handle is rejected
        (the reference refuses modification of time-travelled relations)."""
        if self._default_version is not None or self._default_timestamp is not None:
            raise errors.DeltaAnalysisError(
                f"Cannot {operation} a time-travelled table handle: the "
                "table was resolved with an embedded version/timestamp."
            )

    def delete(self, condition: Optional[Union[str, ir.Expression]] = None) -> Dict[str, int]:
        self._check_mutable("DELETE from")
        cmd = DeleteCommand(self.delta_log, condition)
        cmd.run()
        return cmd.metrics

    def update(self, set: Dict[str, Union[str, ir.Expression]],
               condition: Optional[Union[str, ir.Expression]] = None) -> Dict[str, int]:
        self._check_mutable("UPDATE")
        cmd = UpdateCommand(self.delta_log, set, condition)
        cmd.run()
        return cmd.metrics

    # updateExpr is the same entry point here: expressions are SQL strings
    update_expr = update

    def merge(self, source: Any, condition: Union[str, ir.Expression],
              source_alias: Optional[str] = None) -> "DeltaMergeBuilder":
        self._check_mutable("MERGE into")
        return DeltaMergeBuilder(
            self, source, condition,
            source_alias=source_alias, target_alias=self._alias,
        )

    # -- utilities --------------------------------------------------------

    def vacuum(self, retention_hours: Optional[float] = None,
               dry_run: bool = False, retention_check_enabled: bool = True):
        self._check_mutable("VACUUM")
        return VacuumCommand(
            self.delta_log, retention_hours, dry_run=dry_run,
            retention_check_enabled=retention_check_enabled,
        ).run()

    def history(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        return describe_history(self.delta_log, limit)

    def table_changes(self, starting_version: int,
                      ending_version: Optional[int] = None) -> pa.Table:
        """Change Data Feed between two versions (inclusive): rows with
        ``_change_type`` / ``_commit_version`` / ``_commit_timestamp``.
        Requires ``delta.enableChangeDataFeed=true`` for row-accurate
        UPDATE/MERGE capture; append/delete-only commits reconstruct from
        file actions either way."""
        from delta_tpu.exec import cdf as cdf_exec

        return cdf_exec.read_changes(
            self.delta_log, starting_version, ending_version
        )

    def detail(self) -> Dict[str, Any]:
        return describe_detail(self.delta_log)

    def doctor(self):
        """Table-health report: per-dimension severities (checkpoint
        staleness, small-file debt, deletion-vector debt, stats coverage,
        partition skew, tombstones, protocol) with suggested remedies, all
        numbers published as ``table.health.*`` gauges. Beyond the reference
        — see `delta_tpu/obs/doctor.py`."""
        from delta_tpu.obs.doctor import doctor as _doctor

        return _doctor(self.delta_log, snapshot=self._snapshot())

    def advise(self, limit: Optional[int] = None):
        """Layout advisor: aggregate this table's persistent workload
        journal (scans, commits, DML routing — `delta_tpu/obs/journal.py`)
        into ranked, evidence-backed recommendations (Z-ORDER/partition
        column candidates, checkpoint-interval and row-group tuning,
        calibration/HBM-budget hints). The longitudinal counterpart of
        :meth:`doctor`; degrades to an explicit ``status="no history"``
        report when nothing has been journaled. Beyond the reference — see
        `delta_tpu/obs/advisor.py`."""
        from delta_tpu.obs.advisor import advise as _advise

        return _advise(self.delta_log, snapshot=self._snapshot(), limit=limit)

    def restore_to_version(self, version: int) -> Dict[str, int]:
        """Roll the table back to ``version`` as a NEW commit (history is
        preserved). Beyond the reference — modern Delta's RESTORE TABLE."""
        from delta_tpu.commands.restore import RestoreCommand

        self._check_mutable("RESTORE")
        cmd = RestoreCommand(self.delta_log, version=version)
        cmd.run()
        return cmd.metrics

    def restore_to_timestamp(self, timestamp: Union[str, int]) -> Dict[str, int]:
        from delta_tpu.commands.restore import RestoreCommand

        self._check_mutable("RESTORE")
        cmd = RestoreCommand(self.delta_log, timestamp=timestamp)
        cmd.run()
        return cmd.metrics

    def clone(self, target_path: str, version: Optional[int] = None,
              timestamp: Optional[Union[str, int]] = None) -> "DeltaTable":
        """Shallow-clone this table (optionally at a past version) into
        ``target_path``: the clone references this table's data files in
        place. Beyond the reference — modern Delta's SHALLOW CLONE."""
        from delta_tpu.commands.clone import CloneCommand

        CloneCommand(self.delta_log, target_path,
                     version=version, timestamp=timestamp).run()
        return DeltaTable.for_path(target_path)

    def generate(self, mode: str = "symlink_format_manifest") -> None:
        if mode != "symlink_format_manifest":
            raise errors.unsupported_generate_mode(mode)
        from delta_tpu.hooks.symlink_manifest import generate_full_manifest

        generate_full_manifest(self.delta_log)

    def optimize(self, predicate: Optional[str] = None) -> "DeltaOptimizeBuilder":
        self._check_mutable("OPTIMIZE")
        return DeltaOptimizeBuilder(self, predicate)

    def upgrade_table_protocol(self, reader_version: int, writer_version: int) -> None:
        self._check_mutable("upgrade the protocol of")
        self.delta_log.upgrade_protocol(
            Protocol(min_reader_version=reader_version, min_writer_version=writer_version)
        )


class DeltaMergeBuilder:
    """Fluent MERGE builder (`DeltaMergeBuilder.scala:123-457`). Clause order
    is execution order, as in the reference."""

    def __init__(self, target: DeltaTable, source: Any, condition,
                 source_alias: Optional[str] = None,
                 target_alias: Optional[str] = None):
        self._target = target
        self._source = source
        self._condition = condition
        self._source_alias = source_alias
        self._target_alias = target_alias
        self._matched: List[MergeClause] = []
        self._not_matched: List[MergeClause] = []

    def when_matched_update(self, set: Dict[str, Any],
                            condition: Optional[str] = None) -> "DeltaMergeBuilder":
        self._matched.append(MergeClause("update", condition, dict(set)))
        return self

    def when_matched_update_all(self, condition: Optional[str] = None) -> "DeltaMergeBuilder":
        self._matched.append(MergeClause("update", condition, None))
        return self

    def when_matched_delete(self, condition: Optional[str] = None) -> "DeltaMergeBuilder":
        self._matched.append(MergeClause("delete", condition))
        return self

    def when_not_matched_insert(self, values: Dict[str, Any],
                                condition: Optional[str] = None) -> "DeltaMergeBuilder":
        self._not_matched.append(MergeClause("insert", condition, dict(values)))
        return self

    def when_not_matched_insert_all(self, condition: Optional[str] = None) -> "DeltaMergeBuilder":
        self._not_matched.append(MergeClause("insert", condition, None))
        return self

    def execute(self) -> Dict[str, int]:
        cmd = MergeIntoCommand(
            self._target.delta_log,
            self._source,
            self._condition,
            self._matched,
            self._not_matched,
            source_alias=self._source_alias,
            target_alias=self._target_alias,
        )
        cmd.run()
        return cmd.metrics


class DeltaOptimizeBuilder:
    """`table.optimize(predicate).execute_compaction() / execute_z_order_by()`."""

    def __init__(self, target: DeltaTable, predicate: Optional[str] = None):
        self._target = target
        self._predicate = predicate

    def execute_compaction(self, target_rows: Optional[int] = None) -> Dict[str, int]:
        kwargs = {"target_rows": target_rows} if target_rows else {}
        cmd = OptimizeCommand(self._target.delta_log, self._predicate, **kwargs)
        cmd.run()
        return cmd.metrics

    def execute_z_order_by(self, *columns: str,
                           target_rows: Optional[int] = None) -> Dict[str, int]:
        kwargs = {"target_rows": target_rows} if target_rows else {}
        cmd = OptimizeCommand(
            self._target.delta_log, self._predicate,
            z_order_by=list(columns), **kwargs,
        )
        cmd.run()
        return cmd.metrics

    def execute_purge(self) -> Dict[str, int]:
        """Rewrite exactly the files carrying deletion vectors, materializing
        their deletes (modern Delta's ``REORG TABLE ... APPLY (PURGE)``)."""
        cmd = OptimizeCommand(
            self._target.delta_log, self._predicate, purge=True,
        )
        cmd.run()
        return cmd.metrics
