"""Config-registry pass: every ``delta.tpu.*`` key resolves to the registry.

``SqlConf.get`` silently returns the call-site default for an unknown key,
so a typo'd key (``delta.tpu.snapshot.stalenessLimit`` vs
``…stalenessLimitMs``) reads as "feature off" forever with no error. Two
rules close the loop against the ``_DEFAULTS`` registry in
``delta_tpu/utils/config.py``:

``config-unregistered``
    A constant ``delta.tpu.*`` key passed to ``conf.get``/``conf.get_bool``
    that is not in ``SqlConf._DEFAULTS``. (The dynamic
    ``delta.tpu.properties.defaults.*`` family is exempt.)
``config-dead``
    A registered key that no analyzed code reads — either the feature it
    gated was removed, or its reader typo'd the key and this is the other
    half of an ``config-unregistered`` pair. Keys covered by a dynamic
    f-string read prefix (``f"delta.tpu.keyCache.{x}"``) are exempt.

The registry is read from the analyzed AST, not imported — fixtures can
supply a synthetic ``utils/config.py``. When no registry file is in the
context the pass is silent.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.analysis.core import AnalysisContext, AnalysisPass, Finding
from delta_tpu.analysis.modgraph import terminal_name

__all__ = ["ConfigRegistryPass"]

PREFIX = "delta.tpu."

#: key families constructed at runtime inside utils/config.py itself
ALWAYS_DYNAMIC = ("delta.tpu.properties.defaults.",)

_CONF_RECEIVERS = frozenset({"conf", "_conf"})
_CONF_METHODS = frozenset({"get", "get_bool", "get_int"})


def _registry_from(sf) -> Optional[Dict[str, int]]:
    """``{key: lineno}`` of the ``_DEFAULTS`` dict literal, if present."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        out: Dict[str, int] = {}
        for k in node.value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
        return out
    return None


def _is_conf_read(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _CONF_METHODS):
        return False
    recv = terminal_name(f.value)
    return recv in _CONF_RECEIVERS


class ConfigRegistryPass(AnalysisPass):
    name = "config-registry"
    description = ("constant delta.tpu.* conf reads must resolve to the "
                   "SqlConf registry; registered keys must have readers")
    rules = ("config-unregistered", "config-dead")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        reg_file = ctx.find_suffix("utils/config.py")
        registry = _registry_from(reg_file) if reg_file is not None else None
        if registry is None:
            return []
        const_reads: List[Tuple[str, str, int]] = []  # (key, rel, line)
        dynamic_prefixes: Set[str] = set(ALWAYS_DYNAMIC)
        for sf in ctx.files:
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and _is_conf_read(node)):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str):
                    if arg.value.startswith(PREFIX):
                        const_reads.append((arg.value, sf.rel, node.lineno))
                elif isinstance(arg, ast.JoinedStr):
                    # an f-string READ (conf.get(f"delta.tpu.family.{x}"))
                    # shields its constant prefix from config-dead; an
                    # f-string anywhere else (log messages) must NOT
                    prefix = ""
                    for part in arg.values:
                        if isinstance(part, ast.Constant) and isinstance(
                                part.value, str):
                            prefix = part.value
                        break
                    # a bare "delta.tpu." prefix (conf.get(f"delta.tpu.{x}"))
                    # would shield EVERY registered key and silently neuter
                    # config-dead — require at least one family segment
                    if prefix.startswith(PREFIX) and len(prefix) > len(PREFIX):
                        dynamic_prefixes.add(prefix)
        out: List[Finding] = []
        read_keys = {k for k, _r, _l in const_reads}
        for key, rel, line in const_reads:
            if key in registry:
                continue
            if any(key.startswith(p) for p in ALWAYS_DYNAMIC):
                continue
            out.append(Finding(
                "config-unregistered", rel, line,
                f"conf key '{key}' is not registered in "
                f"SqlConf._DEFAULTS (utils/config.py) — a typo here "
                f"silently returns the call-site default"))
        for key, line in sorted(registry.items()):
            if key in read_keys:
                continue
            if any(key.startswith(p) for p in dynamic_prefixes):
                continue
            out.append(Finding(
                "config-dead", reg_file.rel, line,
                f"registered conf key '{key}' is never read by the "
                f"engine — dead knob or a typo'd reader elsewhere"))
        return out
