"""Worker for the 2-process DCN integration test (`test_multihost.py`).

Each process joins a real `jax.distributed` CPU cluster, then drives the
engine's multi-host paths against a SHARED table directory — the
coordination model is the store, not RPC (SURVEY §2.8):

  scan        — each host decodes its strided partition of the file list
  checkpoint  — each host writes its slice of the parts; proc 0 publishes
                `_last_checkpoint` after all parts are visible
  convert     — each host footers/stats its slice; proc 0 gathers the
                fragments from the store and commits
  vacuum      — each host deletes its slice of the expired files

``dist`` mode drives the sharded-execution plane instead: each host takes
its byte-weighted LPT slice of the OPTIMIZE bin-pack groups and commits its
own rearrange-only transaction, then proc 0 runs a probe-restricted MERGE.
``dist-crash`` kills proc 1 with a SimulatedCrash mid-OPTIMIZE (no cluster
join — the store is the coordination model, and a dead peer must not hang
the survivor's jax.distributed teardown; leases are disabled so the
survivor-only semantics stay isolated from the recovery path below).
``dist-recover`` is the lease-recovery flavor (ISSUE 20): proc 1 crashes
mid-slice AFTER publishing its lease; proc 0 — launched by the parent once
the lease's heartbeat has been aged past the ttl — commits its own slice,
then reconciles the orphan via the coordinator recovery path and reports
the recovered end state.

Results land in <out>/result-<proc>.json for the parent to assert.
"""
import json
import os
import sys
import time


def _barrier(out_dir: str, name: str, proc: int, n_procs: int) -> None:
    """Store-based barrier: marker files on the shared directory."""
    open(os.path.join(out_dir, f"{name}-{proc}"), "w").close()
    deadline = time.time() + 60
    while not all(
        os.path.exists(os.path.join(out_dir, f"{name}-{i}"))
        for i in range(n_procs)
    ):
        if time.time() > deadline:
            raise TimeoutError(f"barrier {name} timed out on proc {proc}")
        time.sleep(0.05)


def dist_body(proc: int, n_procs: int, table: str, out_dir: str,
              crash: bool) -> None:
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.exec.scan import scan_to_table

    # distributed tracing: the parent exports DELTA_TPU_TRACEPARENT (adopted
    # lazily by telemetry itself) and the spool directory; with the dir set,
    # every span this worker runs lands in its own JSONL spool for the
    # parent's collector to stitch
    trace_dir = os.environ.get("DELTA_TPU_TRACE_DIR")
    if trace_dir:
        from delta_tpu.utils.config import conf as _conf

        _conf.set("delta.tpu.trace.dir", trace_dir)

    result = {"proc": proc}
    log = DeltaLog.for_table(table)
    snap = log.update()

    # sharded scan: the byte-weighted LPT partitions tile the table
    part = scan_to_table(snap, distribute=True)
    result["scan_ids"] = sorted(part.column("id").to_pylist())

    if crash:
        # the crash flavor isolates SURVIVOR semantics: a dead peer commits
        # nothing and must not hang the survivor. Leases stay off so the
        # coordinator does not block on (and then recover) the orphaned
        # slice — that path is the `dist-recover` mode's subject.
        from delta_tpu.utils.config import conf as _cconf

        _cconf.set("delta.tpu.distributed.lease.enabled", False)

    if crash and proc == 1:
        # SimulatedCrash (a BaseException) mid-job: fires on this host's
        # SECOND group rewrite, after real work started but before commit
        from delta_tpu.exec import write as write_exec
        from delta_tpu.storage.faults import SimulatedCrash

        orig = write_exec.write_files
        state = {"n": 0}

        def crashing(*a, **k):
            state["n"] += 1
            if state["n"] >= 2:
                raise SimulatedCrash("dist.optimize.rewrite")
            return orig(*a, **k)

        write_exec.write_files = crashing

    cmd = OptimizeCommand(log, min_file_size=1 << 30, workers=2,
                          distribute=True)
    version = cmd.run()
    result["optimize_version"] = version
    result["optimize_groups"] = (
        len(cmd.shard_report.results) if cmd.shard_report else 0)
    result["shard_timings"] = (
        cmd.shard_report.timings() if cmd.shard_report else [])

    if not crash:
        _barrier(out_dir, "opt", proc, n_procs)
        if proc == 0:
            from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
            from delta_tpu.utils.config import conf

            DeltaLog.clear_cache()
            mlog = DeltaLog.for_table(table)
            src = pa.table({
                "id": pa.array([3, 75, 1000], pa.int64()),
                "part": pa.array(["p0", "p3", "p0"]),
                "v": pa.array([-1.0, -2.0, -3.0]),
            })
            with conf.set_temporarily(
                **{"delta.tpu.distributed.merge.probe.minFiles": 2}
            ):
                m = MergeIntoCommand(
                    mlog, src, "t.id = s.id",
                    [MergeClause("update", assignments=None)],
                    [MergeClause("insert", assignments=None)],
                    source_alias="s", target_alias="t")
                m.run()
            result["merge_updated"] = m.metrics["numTargetRowsUpdated"]
            result["merge_inserted"] = m.metrics["numTargetRowsInserted"]
            result["merge_probed"] = "probe_ms" in m.phase_ms
        _barrier(out_dir, "merge", proc, n_procs)

    DeltaLog.clear_cache()
    fsnap = DeltaLog.for_table(table).update()
    final = scan_to_table(fsnap)
    result["final_ids"] = sorted(final.column("id").to_pylist())
    result["final_files"] = fsnap.num_of_files
    result["final_version"] = fsnap.version

    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


def dist_recover_body(proc: int, n_procs: int, table: str,
                      out_dir: str) -> None:
    trace_dir = os.environ.get("DELTA_TPU_TRACE_DIR")
    if trace_dir:
        from delta_tpu.utils.config import conf as _conf

        _conf.set("delta.tpu.trace.dir", trace_dir)

    from delta_tpu import DeltaLog
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(table)

    if proc == 1:
        # die on the SECOND group rewrite: the lease is already published
        # (written before slice execution) and real work has started — the
        # classic orphaned-slice shape. The SimulatedCrash (a BaseException)
        # pierces the executor and kills this process with a traceback.
        from delta_tpu.exec import write as write_exec
        from delta_tpu.storage.faults import SimulatedCrash

        orig = write_exec.write_files
        state = {"n": 0}

        def crashing(*a, **k):
            state["n"] += 1
            if state["n"] >= 2:
                raise SimulatedCrash("dist.itemExec")
            return orig(*a, **k)

        write_exec.write_files = crashing
        OptimizeCommand(log, min_file_size=1 << 30, workers=2,
                        distribute=True).run()
        raise AssertionError("proc 1 must have crashed mid-slice")

    # proc 0 — the coordinator: commit our slice, then recover the orphan
    from delta_tpu.obs import journal
    from delta_tpu.parallel import leases
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf
    from delta_tpu.exec.scan import scan_to_table

    with conf.set_temporarily(
            **{"delta.tpu.distributed.lease.settleMs": 20}):
        cmd = OptimizeCommand(log, min_file_size=1 << 30, workers=2,
                              distribute=True)
        version = cmd.run()

    journal.flush(log.log_path)
    DeltaLog.clear_cache()
    fsnap = DeltaLog.for_table(table).update()
    final = scan_to_table(fsnap)
    result = {
        "proc": proc,
        "optimize_version": version,
        "final_ids": sorted(final.column("id").to_pylist()),
        "final_files": fsnap.num_of_files,
        "final_version": fsnap.version,
        "recovered": telemetry.counters("dist").get(
            "dist.slice.recovered", 0),
        "leases_left": len(leases.read_leases(log.log_path)),
        "dist_events": [e.get("event") for e in journal.read_entries(
            log.log_path, kinds=("dist",))],
    }
    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


def main() -> None:
    proc = int(sys.argv[1])
    n_procs = int(sys.argv[2])
    port = sys.argv[3]
    table = sys.argv[4]
    convert_dir = sys.argv[5]
    out_dir = sys.argv[6]
    mode = sys.argv[7] if len(sys.argv) > 7 else "classic"

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from delta_tpu.parallel import distributed as dist

    if mode == "dist-crash":
        # no cluster join: a peer that dies mid-job must not hang the
        # survivor's jax.distributed teardown; slicing reads process_info
        dist.process_info = lambda: (proc, n_procs)
        dist_body(proc, n_procs, table, out_dir, crash=True)
        return

    if mode == "dist-recover":
        # no cluster join either: the two phases run sequentially (the
        # parent ages the dead host's lease between them), so there is no
        # live cluster to coordinate with
        dist.process_info = lambda: (proc, n_procs)
        dist_recover_body(proc, n_procs, table, out_dir)
        return

    pid, count = dist.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=n_procs,
        process_id=proc,
    )
    assert (pid, count) == (proc, n_procs), (pid, count)

    if mode == "dist":
        dist_body(proc, n_procs, table, out_dir, crash=False)
        return

    from delta_tpu import DeltaLog
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.log import checkpoints as ckpt_mod

    result = {"proc": proc, "count": count}

    # -- scan: this host's partition of the pruned file list --------------
    log = DeltaLog.for_table(table)
    snap = log.update()
    part = scan_to_table(snap, distribute=True)
    full = scan_to_table(snap)
    result["scan_rows"] = part.num_rows
    result["scan_ids"] = sorted(part.column("id").to_pylist())
    result["full_rows"] = full.num_rows

    # -- checkpoint: each host writes its slice of the parts --------------
    md = ckpt_mod.write_checkpoint(
        log.store, log.log_path, snap.version, snap.checkpoint_actions(),
        parts=4, distribute=True,
    )
    result["ckpt_parts"] = md.parts

    # -- convert: fragment exchange through the store ---------------------
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    clog = DeltaLog.for_table(convert_dir)
    version = ConvertToDeltaCommand(
        clog, collect_stats=True, distribute=True
    ).run()
    result["convert_version"] = version
    DeltaLog.clear_cache()
    csnap = DeltaLog.for_table(convert_dir).update()
    result["convert_files"] = csnap.num_of_files

    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
