"""Expression IR + parser + partition pruning semantics."""
import pytest

from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression
from delta_tpu.expr import partition as part
from delta_tpu.protocol.actions import AddFile, Metadata
from delta_tpu.schema.types import (
    DateType,
    IntegerType,
    LongType,
    StringType,
    StructType,
)
from delta_tpu.utils.errors import DeltaAnalysisError


def ev(s, row=None):
    return parse_expression(s).eval(row or {})


class TestParserEval:
    def test_literals(self):
        assert ev("1 + 2") == 3
        assert ev("2 * 3 + 4") == 10
        assert ev("2 + 3 * 4") == 14
        assert ev("(2 + 3) * 4") == 20
        assert ev("'it''s'") == "it's"
        assert ev("TRUE") is True
        assert ev("NULL") is None
        assert ev("1.5e2") == 150.0
        assert ev("-3") == -3

    def test_comparisons(self):
        assert ev("1 < 2") is True
        assert ev("1 >= 2") is False
        assert ev("'a' = 'a'") is True
        assert ev("'a' != 'b'") is True
        assert ev("1 <> 2") is True

    def test_three_valued_logic(self):
        assert ev("NULL = 1") is None
        assert ev("NULL AND FALSE") is False
        assert ev("NULL AND TRUE") is None
        assert ev("NULL OR TRUE") is True
        assert ev("NULL OR FALSE") is None
        assert ev("NOT NULL") is None
        assert ev("NULL <=> NULL") is True
        assert ev("1 <=> NULL") is False

    def test_columns(self):
        row = {"id": 5, "name": "x"}
        assert ev("id > 3", row) is True
        assert ev("ID > 3", row) is True  # case-insensitive
        assert ev("name = 'x'", row) is True
        with pytest.raises(DeltaAnalysisError):
            ev("missing = 1", row)

    def test_in_between_like(self):
        assert ev("3 IN (1, 2, 3)") is True
        assert ev("4 IN (1, 2, 3)") is False
        assert ev("4 NOT IN (1, 2, 3)") is True
        assert ev("NULL IN (1, 2)") is None
        assert ev("5 IN (1, NULL)") is None  # null in list w/o match
        assert ev("5 BETWEEN 1 AND 10") is True
        assert ev("'abc' LIKE 'a%'") is True
        assert ev("'abc' LIKE 'a_c'") is True
        assert ev("'abc' NOT LIKE 'b%'") is True

    def test_is_null(self):
        assert ev("NULL IS NULL") is True
        assert ev("1 IS NOT NULL") is True

    def test_cast(self):
        assert ev("CAST('12' AS INT)") == 12
        assert ev("CAST(1 AS STRING)") == "1"
        assert ev("CAST('abc' AS INT)") is None  # permissive
        assert ev("CAST('true' AS BOOLEAN)") is True

    def test_div_by_zero_null(self):
        assert ev("1 / 0") is None
        assert ev("1 % 0") is None

    def test_case_when(self):
        assert ev("CASE WHEN 1 < 2 THEN 'a' ELSE 'b' END") == "a"
        assert ev("CASE WHEN 1 > 2 THEN 'a' END") is None

    def test_functions(self):
        assert ev("abs(-3)") == 3
        assert ev("upper('ab')") == "AB"
        assert ev("length('abc')") == 3
        assert ev("concat('a', 'b')") == "ab"
        assert ev("substring('hello', 2, 3)") == "ell"
        assert ev("year(CAST('2021-03-05' AS DATE))") == 2021

    def test_backtick_and_dotted(self):
        assert ev("`weird col` = 1", {"weird col": 1}) is True
        e = parse_expression("a.b = 1")
        assert isinstance(e.left, ir.Column) and e.left.name == "a.b"

    def test_errors(self):
        with pytest.raises(DeltaAnalysisError):
            parse_expression("1 +")
        with pytest.raises(DeltaAnalysisError):
            parse_expression("nosuchfunc(1)")
        with pytest.raises(DeltaAnalysisError):
            parse_expression("a = 1 extra")

    def test_sql_roundtrip(self):
        for s in ["((a > 1) AND (b = 'x'))", "(a IN (1, 2))", "(a IS NULL)"]:
            assert parse_expression(parse_expression(s).sql()) == parse_expression(s)


SCHEMA = (
    StructType()
    .add("id", LongType())
    .add("date", StringType())
    .add("part", IntegerType())
)
META = Metadata(schema_string=SCHEMA.to_json(), partition_columns=["part", "date"])


def f(part_vals, path="f"):
    return AddFile(path, part_vals, 1, 1, True)


class TestPartitionPruning:
    def test_typed_cast(self):
        files = [f({"part": "1", "date": "a"}, "f1"), f({"part": "2", "date": "b"}, "f2")]
        pred = parse_expression("part = 1")  # int literal vs string-stored value
        out = part.filter_files(files, [pred], META)
        assert [x.path for x in out] == ["f1"]

    def test_null_partition_value(self):
        files = [f({"part": None, "date": "a"}, "fnull"), f({"part": "3", "date": "a"}, "f3")]
        assert [x.path for x in part.filter_files(files, [parse_expression("part IS NULL")], META)] == ["fnull"]
        # null never matches an equality
        assert [x.path for x in part.filter_files(files, [parse_expression("part = 3")], META)] == ["f3"]

    def test_split_predicates(self):
        ppreds, dpreds = part.split_partition_and_data_predicates(
            "part = 1 AND id > 10 AND date = 'x'", ["part", "date"]
        )
        assert [p.sql() for p in ppreds] == ["(part = 1)", "(date = 'x')"]
        assert [p.sql() for p in dpreds] == ["(id > 10)"]

    def test_conservative_matching(self):
        fl = f({"part": None, "date": "a"})
        pred = parse_expression("part = 1")
        assert part.matches(pred, fl, META.partition_schema) is False
        assert part.matches_maybe(pred, fl, META.partition_schema) is True
