"""Asynchronous, incremental checkpoint builder.

Synchronous interval checkpointing stalls every
``delta.checkpointInterval``-th committer on an O(table) write: the
snapshot's whole segment (base checkpoint Parquet + log tail) decodes and
re-serializes on the committing writer's thread (``txn/transaction.
_post_commit`` → ``DeltaLog.checkpoint``). Under sustained write traffic
that is the commit path's p99. This module moves the build **off the
critical path** and makes it **incremental**:

* **Async** (``delta.tpu.checkpoint.async``): ``_post_commit`` enqueues a
  checkpoint request; a ``delta-ckpt-async`` daemon thread (the
  ``obs/journal`` writer-daemon pattern) coalesces requests per table
  (newest version wins) and builds them in the background. A failed or
  crashed build loses nothing but the optimization — the log tail stays
  replayable and the next interval re-requests.
* **Incremental** (``delta.tpu.checkpoint.incremental``): checkpoint N is
  built from the **cached reconciled columns** of the last checkpoint M
  plus a decode of ONLY the tail commits M+1..N
  (``log/columnar.extend_segment_columns`` — the columnar twin of the
  state cache's ``apply_tail``), instead of re-reading and re-decoding the
  whole base checkpoint. Any gap (no cached base, missing tail file,
  process restart) falls back to full reconstruction and re-seeds the
  cache; ``checkpoint.incremental.{built,fallback}`` count both paths.
  Dead rows accumulated across incremental rounds are compacted by
  re-decoding the just-written checkpoint once they exceed the live count.

The actual Parquet/pointer writes go through ``DeltaLog.checkpoint`` —
multi-part semantics, ``_last_checkpoint`` publication and expired-log
cleanup are unchanged, and the existing ``write.checkpoint`` /
``write.lastCheckpoint`` fault points cover the IO. The builder itself
draws at the ``checkpoint.asyncBuild`` fault point once per request, so a
torture plan can tear an incremental build deterministically.

Both confs default OFF; with them off this module is never imported on the
commit path.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from delta_tpu.protocol import filenames
from delta_tpu.utils.config import conf
from delta_tpu.utils import telemetry

logger = logging.getLogger(__name__)

__all__ = ["request_checkpoint", "build_checkpoint", "flush", "reset",
           "pending_requests", "base_version"]

_LOCK = threading.Lock()
#: data_path -> (delta_log, version): coalesced, newest version wins
_REQUESTS: Dict[str, Tuple[object, int]] = {}
_WAKE = threading.Event()
_WRITER: Optional[threading.Thread] = None
#: serializes builds: a synchronous flush() or direct build_checkpoint()
#: call (harness, tests) never interleaves with the daemon mid-build —
#: re-entrant because _drain holds it across its build_checkpoint calls
_IO_LOCK = threading.RLock()

_BASE_LOCK = threading.Lock()


@dataclass
class _Base:
    """Cached reconciled columns of the last checkpoint built for a table."""

    version: int
    cols: object  # log/columnar.SegmentColumns


#: data_path -> _Base, LRU-bounded by delta.tpu.checkpoint.incremental.maxTables
_BASES: Dict[str, _Base] = {}


def _max_tables() -> int:
    try:
        n = int(conf.get("delta.tpu.checkpoint.incremental.maxTables", 8))
    except (TypeError, ValueError):
        n = 8
    return max(n, 1)


def base_version(data_path: str) -> Optional[int]:
    """The cached incremental base's version for a table (tests/doctor)."""
    with _BASE_LOCK:
        b = _BASES.get(data_path.rstrip("/"))
        return b.version if b is not None else None


def _seed_base(data_path: str, version: int, cols) -> None:
    with _BASE_LOCK:
        _BASES.pop(data_path, None)
        _BASES[data_path] = _Base(version, cols)  # re-insert = most recent
        while len(_BASES) > _max_tables():
            _BASES.pop(next(iter(_BASES)))


def _drop_base(data_path: str) -> None:
    with _BASE_LOCK:
        _BASES.pop(data_path, None)


# ---------------------------------------------------------------------------
# Request queue + daemon
# ---------------------------------------------------------------------------


def request_checkpoint(delta_log, version: int) -> None:
    """Enqueue a background checkpoint of ``delta_log`` at ``version``.
    Requests coalesce per table — only the newest requested version builds.
    Never blocks and never raises into the committing writer."""
    try:
        with _LOCK:
            prev = _REQUESTS.get(delta_log.data_path)
            if prev is None or prev[1] < version:
                _REQUESTS[delta_log.data_path] = (delta_log, version)
        _ensure_writer()
        _WAKE.set()
    except Exception:  # noqa: BLE001 — the checkpoint is an optimization
        logger.debug("async checkpoint request failed", exc_info=True)


def _ensure_writer() -> None:
    global _WRITER
    if _WRITER is not None and _WRITER.is_alive():
        return
    with _LOCK:
        if _WRITER is not None and _WRITER.is_alive():
            return
        _WRITER = threading.Thread(target=_writer_loop, daemon=True,
                                   name="delta-ckpt-async")
        _WRITER.start()


def _writer_loop() -> None:  # pragma: no cover — exercised via flush() too
    while True:
        _WAKE.wait(timeout=2.0)
        _WAKE.clear()
        try:
            _drain(raise_errors=False)
        # delta-lint: ignore[crash-except] -- deliberately narrowed from
        # BaseException: SimulatedCrash now pierces and kills the daemon
        except Exception:  # noqa: BLE001 — the daemon survives IO failures,
            # but a BaseException (SimulatedCrash = process death,
            # KeyboardInterrupt) kills this thread like a real crash would;
            # the next request_checkpoint() revives a fresh writer — the
            # crash-resume shape the torture harness replays
            logger.debug("async checkpoint drain failed", exc_info=True)


def _drain(raise_errors: bool) -> int:
    built = 0
    with _IO_LOCK:
        while True:
            with _LOCK:
                if not _REQUESTS:
                    return built
                data_path = next(iter(_REQUESTS))
                delta_log, version = _REQUESTS.pop(data_path)
            try:
                build_checkpoint(delta_log, version)
                built += 1
            except BaseException as e:
                # a torn build (injected crash, IO failure) loses only the
                # optimization; the base may no longer match what landed on
                # disk, so forget it — the next build reconstructs fully
                _drop_base(data_path)
                if raise_errors or not isinstance(e, Exception):
                    # a SimulatedCrash/KeyboardInterrupt mid-batch must
                    # pierce even on the daemon path: swallowing it here
                    # would let a "dead" writer keep draining the queue
                    raise
                logger.warning("async checkpoint at version %s failed for %s",
                               version, data_path, exc_info=True)


def flush() -> int:
    """Synchronously build every pending request on the CALLING thread
    (tests, the torture harness, bench teardown); returns builds completed.
    Unlike the daemon, failures propagate to the caller."""
    return _drain(raise_errors=True)


def reset() -> None:
    """Drop pending requests and cached bases (tests, bench per-config
    isolation). On-disk checkpoints are untouched."""
    with _LOCK:
        _REQUESTS.clear()
    with _BASE_LOCK:
        _BASES.clear()


def pending_requests() -> Dict[str, int]:
    with _LOCK:
        return {p: v for p, (_dl, v) in _REQUESTS.items()}


# ---------------------------------------------------------------------------
# Builds
# ---------------------------------------------------------------------------


def build_checkpoint(delta_log, version: int):
    """Build and publish the checkpoint at ``version``: incrementally from
    the cached base when ``delta.tpu.checkpoint.incremental`` allows it,
    else by full reconstruction (which seeds the base for next time).
    Returns the :class:`~delta_tpu.log.checkpoints.CheckpointMetaData`.

    Serialized under ``_IO_LOCK``: a direct caller (the torture harness's
    on-thread build, tests) never interleaves part writes or base seeding
    with the daemon building the same table."""
    from delta_tpu.storage import faults as faults_mod

    with _IO_LOCK:
        faults_mod.fire("checkpoint.asyncBuild",
                        filenames.checkpoint_file_single(version))
        incremental = conf.get_bool("delta.tpu.checkpoint.incremental", False)
        if incremental:
            md = _build_incremental(delta_log, version)
            if md is not None:
                telemetry.bump_counter("checkpoint.incremental.built")
                return md
            telemetry.bump_counter("checkpoint.incremental.fallback")
        snap = delta_log.unsafe_volatile_snapshot
        if snap is None or snap.version != version:
            snap = delta_log.get_snapshot_at(version)
        md = delta_log.checkpoint(snap)
        if incremental:
            _seed_base(delta_log.data_path, version,
                       _maybe_compact(delta_log, md, snap, snap._columnar))
        return md


def _facade_snapshot(delta_log, version: int, cols):
    """A Snapshot whose columnar state is pre-populated with ``cols`` — the
    checkpoint writers (columnar AND dataclass paths) read state through
    ``_columnar``/``_alive_mask``/``checkpoint_actions`` only, so this is a
    complete stand-in for a freshly decoded snapshot at ``version``."""
    from delta_tpu.log.snapshot import LogSegment, Snapshot

    seg = LogSegment(delta_log.log_path, version, deltas=[],
                     checkpoint_files=[], checkpoint_version=None,
                     last_commit_timestamp=delta_log.clock())
    snap = Snapshot(delta_log, version, seg)
    snap.__dict__["_columnar"] = cols  # primes the cached_property
    return snap


def _build_incremental(delta_log, version: int):
    """Checkpoint ``version`` = cached base at M + decode of commits
    M+1..version only. None when the base is missing/stale — caller falls
    back to full reconstruction."""
    from delta_tpu.log import columnar

    with _BASE_LOCK:
        base = _BASES.get(delta_log.data_path)
    if base is None or base.version >= version:
        return None
    tail_paths = [f"{delta_log.log_path}/{filenames.delta_file(v)}"
                  for v in range(base.version + 1, version + 1)]
    try:
        tail = columnar.decode_segment(delta_log.store, [], tail_paths)
    except FileNotFoundError:
        return None  # a tail commit is gone (cleanup/corruption): rebuild
    cols = columnar.extend_segment_columns(base.cols, tail)
    snap = _facade_snapshot(delta_log, version, cols)
    md = delta_log.checkpoint(snap)
    _seed_base(delta_log.data_path, version,
               _maybe_compact(delta_log, md, snap, cols))
    return md


def _maybe_compact(delta_log, md, snap, cols):
    """Bound the cached base's garbage: superseded rows accumulate across
    incremental rounds (each removed file keeps its dead add row). Once
    dead rows exceed the live count (floor 4096), re-decode the checkpoint
    just written — off the commit path, on this builder thread — and cache
    the compact form instead."""
    try:
        alive = int(snap._alive_mask.sum()) + len(snap.tombstones)
        if cols.num_rows <= max(4096, 2 * alive):
            return cols
        from delta_tpu.log import columnar
        from delta_tpu.log.checkpoints import CheckpointInstance

        inst = CheckpointInstance(md.version, md.parts)
        return columnar.decode_segment(
            delta_log.store, inst.paths(delta_log.log_path), [])
    except Exception:  # noqa: BLE001 — compaction is hygiene, not correctness
        return cols
