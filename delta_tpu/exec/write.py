"""Transactional write path: Arrow batch → partitioned Parquet → AddFiles.

Equivalent of `files/TransactionalWrite.scala:43-207` +
`files/DelayedCommitProtocol.scala:41-164`: normalize the batch to the table
schema, enforce constraints (vectorized, `schema/constraints.py`), split by
partition values, write `part-<n>-<uuid>.c000.snappy.parquet` files directly
into partition directories (no rename — the commit *is* the transaction log
entry), and return `AddFile` actions carrying protocol-format stats.

Like the reference's committer, files become visible only via the commit;
orphaned files from failed writes are invisible to readers and reaped by
VACUUM.
"""
from __future__ import annotations

import os
import urllib.parse
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.exec import parquet as pq_exec
from delta_tpu.expr.vectorized import arrow_type_for
from delta_tpu.protocol.actions import AddFile, Metadata
from delta_tpu.schema import constraints as constraints_mod
from delta_tpu.schema.types import StructType
from delta_tpu.utils.config import DeltaConfigs
from delta_tpu.utils.errors import SchemaMismatchError

__all__ = ["normalize_data", "write_files", "escape_partition_value", "partition_path"]

# Hive-style partition-path escaping (util/PartitionUtils.scala vendored copy
# of Spark's ExternalCatalogUtils): these characters are %-encoded in dir names.
_ESCAPE = set('\\"#%\'*/:=?\x7f[]^ \t\n\x0b\x0c\r{}')
HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"


def escape_partition_value(v: Optional[str]) -> str:
    if v is None or v == "":
        return HIVE_DEFAULT_PARTITION
    return "".join(f"%{ord(c):02X}" if c in _ESCAPE or ord(c) < 0x20 else c for c in v)


def unescape_partition_value(s: str) -> Optional[str]:
    if s == HIVE_DEFAULT_PARTITION:
        return None
    return urllib.parse.unquote(s)


def partition_path(partition_values: Dict[str, Optional[str]], partition_columns: Sequence[str]) -> str:
    return "/".join(
        f"{c}={escape_partition_value(partition_values.get(c))}" for c in partition_columns
    )


def _resolve(table: pa.Table, name: str) -> Optional[str]:
    if name in table.column_names:
        return name
    low = name.lower()
    for c in table.column_names:
        if c.lower() == low:
            return c
    return None


def normalize_data(table: pa.Table, schema: StructType) -> pa.Table:
    """Reorder/case-normalize/cast the batch to the table schema
    (`TransactionalWrite.scala:79-115` normalizeData)."""
    cols = []
    fields = []
    for f in schema.fields:
        src = _resolve(table, f.name)
        target_type = arrow_type_for(f.data_type)
        if src is None:
            # missing column → nulls (schema enforcement happens upstream)
            cols.append(pa.nulls(table.num_rows, target_type))
        else:
            col = table.column(src)
            if col.type != target_type:
                try:
                    col = pc.cast(col, target_type)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
                    raise SchemaMismatchError(
                        f"Cannot cast column {f.name} from {col.type} to {target_type}: {e}"
                    )
            cols.append(col)
        fields.append(pa.field(f.name, target_type, f.nullable))
    extra = [
        c for c in table.column_names
        if all(c.lower() != f.name.lower() for f in schema.fields)
    ]
    if extra:
        raise SchemaMismatchError(
            f"Data columns {extra} not present in table schema "
            f"{[f.name for f in schema.fields]} (enable mergeSchema to add them)"
        )
    return pa.table(cols, schema=pa.schema(fields))


def _split_by_partition(
    table: pa.Table, part_cols: Sequence[str]
) -> List[Tuple[Dict[str, Optional[str]], pa.Table]]:
    """One sort + linear run-boundary scan instead of one full-table mask per
    partition value (O(n log n) vs O(groups × rows))."""
    import numpy as np

    t = table.sort_by([(c, "ascending") for c in part_cols])
    n = t.num_rows
    if n == 0:
        return []
    change = np.zeros(n, bool)
    change[0] = True
    for c in part_cols:
        col = pa.chunked_array(t.column(c)).combine_chunks()
        prev, cur = col.slice(0, n - 1), col.slice(1)
        neq = pc.fill_null(pc.not_equal(cur, prev), False)
        # null↔value transitions are boundaries; null↔null is not
        null_b = pc.xor(pc.is_null(cur), pc.is_null(prev))
        m = pc.or_(neq, null_b)
        if pa.types.is_floating(col.type):
            # NaN != NaN would split every NaN row into its own group
            both_nan = pc.and_(
                pc.fill_null(pc.is_nan(cur), False),
                pc.fill_null(pc.is_nan(prev), False),
            )
            m = pc.and_(m, pc.invert(both_nan))
        change[1:] |= np.asarray(m)
    starts = np.flatnonzero(change)
    bounds = np.append(starts, n)
    out: List[Tuple[Dict[str, Optional[str]], pa.Table]] = []
    for i, s in enumerate(starts):
        chunk = t.slice(int(s), int(bounds[i + 1] - s))
        pv = {c: _partition_value_str(chunk.column(c)[0]) for c in part_cols}
        out.append((pv, chunk))
    return out


def _partition_value_str(scalar: pa.Scalar) -> Optional[str]:
    v = scalar.as_py()
    if v is None:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def write_files(
    data_path: str,
    table: pa.Table,
    metadata: Metadata,
    data_change: bool = True,
    target_file_rows: Optional[int] = None,
    constraints: Optional[List[constraints_mod.Constraint]] = None,
) -> List[AddFile]:
    """Write a normalized batch as partitioned Parquet; return AddFiles.

    Files encode in parallel on a thread pool (Arrow's Parquet writer drops
    the GIL) — the host fan-out the reference gets from `FileFormatWriter`
    parallel tasks (`files/TransactionalWrite.scala:182-192`). Batches larger
    than ``delta.tpu.write.targetFileRows`` split into multiple files so the
    encode parallelizes and later scans decode in parallel."""
    from delta_tpu.utils.config import conf

    schema: StructType = metadata.schema
    part_cols = list(metadata.partition_columns)
    # ambiguous (case-insensitively duplicated) batch columns would silently
    # drop data during cast/resolution — reject at ANY nesting level, and
    # before generated-column computation whose lookups would KeyError on
    # them (`SchemaUtils.checkColumnNameDuplication`)
    from delta_tpu.schema.arrow_interop import schema_from_arrow
    from delta_tpu.schema.schema_utils import check_column_name_duplication

    check_column_name_duplication(
        schema_from_arrow(table.schema), "in the data to save"
    )
    # generated columns: compute the missing, verify the provided — must see
    # the batch before normalize_data turns missing columns into nulls
    from delta_tpu.schema import generated as generated_mod

    table = generated_mod.compute_on_write(table, schema)
    table = normalize_data(table, schema)
    # Defragment heavily-chunked inputs (join/filter outputs arrive as
    # hundreds of small chunks): one contiguous copy is cheap next to the
    # per-chunk costs the Parquet encoder pays on fragmented columns.
    if table.num_columns and table.column(0).num_chunks > 4:
        table = table.combine_chunks()
    # char/varchar write semantics: pad char(n) to width, enforce length
    # bounds (CharVarcharUtils.scala write-side behavior)
    from delta_tpu.schema import char_varchar

    table = char_varchar.apply_write_semantics(table, metadata)
    if constraints is None:
        constraints = constraints_mod.from_metadata(metadata)
    constraints_mod.enforce(constraints, table)
    num_indexed = DeltaConfigs.DATA_SKIPPING_NUM_INDEXED_COLS.from_metadata(metadata)
    if target_file_rows is None:
        target_file_rows = int(conf.get("delta.tpu.write.targetFileRows", 4_000_000))

    data_cols = [f.name for f in schema.fields if f.name not in part_cols]

    groups: List[Tuple[Dict[str, Optional[str]], pa.Table]] = []
    if part_cols:
        groups = _split_by_partition(table, part_cols)
    else:
        groups.append(({}, table))

    # plan all (partition values, relative path, file table) jobs up front,
    # then encode on a thread pool
    jobs: List[Tuple[Dict[str, Optional[str]], str, pa.Table]] = []
    for pv, part_table in groups:
        if part_table.num_rows == 0:
            continue
        chunks: List[pa.Table] = []
        if target_file_rows and part_table.num_rows > target_file_rows:
            for start in range(0, part_table.num_rows, target_file_rows):
                chunks.append(part_table.slice(start, target_file_rows))
        else:
            chunks.append(part_table)
        prefix = partition_path(pv, part_cols)
        for idx, chunk in enumerate(chunks):
            file_data = chunk.select(data_cols) if part_cols else chunk
            name = f"part-{idx:05d}-{uuid.uuid4()}.c000.snappy.parquet"
            rel = f"{prefix}/{name}" if prefix else name
            jobs.append((pv, rel, file_data))

    def write_one(job) -> AddFile:
        pv, rel, file_data = job
        abs_path = os.path.join(data_path, rel.replace("/", os.sep))
        size, mtime = pq_exec.write_parquet_file(file_data, abs_path)
        return AddFile(
            # AddFile.path is URI-encoded per the protocol (the hive-
            # escaped dir's '%' becomes '%25'); readers unquote once.
            # safe set = URI path chars java Path.toUri leaves bare.
            path=urllib.parse.quote(rel, safe="/:@!$&'()*+,;=-._~"),
            partition_values=pv,
            size=size,
            modification_time=mtime,
            data_change=data_change,
            stats=pq_exec.stats_json(file_data, num_indexed),
        )

    if len(jobs) <= 1:
        return [write_one(j) for j in jobs]
    from concurrent.futures import ThreadPoolExecutor

    from delta_tpu.utils import telemetry

    workers = min(len(jobs), os.cpu_count() or 4)
    # span-context propagation: per-file write counters/events parent under
    # the enclosing command span instead of orphan worker roots
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="delta-parquet-write") as pool:
        return list(pool.map(telemetry.propagated(write_one), jobs))
