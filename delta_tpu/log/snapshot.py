"""Snapshot: immutable table state at a version.

Reference: ``Snapshot.scala:55-410``. The reference reconstructs state as a
50-partition Spark Dataset replay; here state reconstruction has two paths:

* **host path** (this module): stream checkpoint Parquet + delta JSON through
  :class:`delta_tpu.log.replay.LogReplay` — exact, used for all transactional
  decisions;
* **device path** (``delta_tpu.ops.replay_kernel``): the AddFile metadata is
  exported as fixed-width columns (:meth:`Snapshot.files_arrays`) and the
  last-writer-wins reconciliation / pruning run as sharded JAX kernels over a
  device mesh — used for scan planning and the checkpoint-replay benchmark.
"""
from __future__ import annotations

import json
import time
from functools import cached_property
from typing import Any, Dict, List, Optional, Sequence, TYPE_CHECKING

from delta_tpu.log.replay import LogReplay
from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import (
    Action,
    AddFile,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    actions_from_lines,
)
from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.errors import DeltaIllegalStateError
from delta_tpu.utils.config import DeltaConfigs

if TYPE_CHECKING:
    from delta_tpu.log.deltalog import DeltaLog

__all__ = ["LogSegment", "Snapshot", "InitialSnapshot"]


class LogSegment:
    """The files that define a version: checkpoint parts + contiguous deltas
    after it (``SnapshotManagement.scala:394-421``)."""

    def __init__(
        self,
        log_path: str,
        version: int,
        deltas: Sequence[FileStatus],
        checkpoint_files: Sequence[FileStatus] = (),
        checkpoint_version: Optional[int] = None,
        last_commit_timestamp: int = 0,
    ):
        self.log_path = log_path
        self.version = version
        self.deltas = list(deltas)
        self.checkpoint_files = list(checkpoint_files)
        self.checkpoint_version = checkpoint_version
        self.last_commit_timestamp = last_commit_timestamp

    def __eq__(self, other: Any) -> bool:
        """Segment equivalence for early-exit update
        (``SnapshotManagement.scala:286-330``)."""
        if not isinstance(other, LogSegment):
            return False
        return (
            self.log_path == other.log_path
            and self.version == other.version
            and [f.path for f in self.deltas] == [f.path for f in other.deltas]
            and [f.path for f in self.checkpoint_files] == [f.path for f in other.checkpoint_files]
        )

    @staticmethod
    def empty(log_path: str) -> "LogSegment":
        return LogSegment(log_path, -1, [])

    def __repr__(self) -> str:
        return (
            f"LogSegment(v={self.version}, ckpt={self.checkpoint_version}, "
            f"deltas={[f.name for f in self.deltas]})"
        )


class Snapshot:
    def __init__(
        self,
        delta_log: "DeltaLog",
        version: int,
        segment: LogSegment,
        min_file_retention_timestamp: Optional[int] = None,
        timestamp: Optional[int] = None,
    ):
        self.delta_log = delta_log
        self.version = version
        self.segment = segment
        self.timestamp = timestamp if timestamp is not None else segment.last_commit_timestamp
        self._min_file_retention_timestamp = min_file_retention_timestamp

    # -- state reconstruction -------------------------------------------

    @property
    def store(self) -> LogStore:
        return self.delta_log.store

    def min_file_retention_timestamp(self) -> int:
        if self._min_file_retention_timestamp is not None:
            return self._min_file_retention_timestamp
        retention = DeltaConfigs.TOMBSTONE_RETENTION.from_metadata(self.metadata)
        return self.delta_log.clock() - retention

    @cached_property
    def _replay(self) -> LogReplay:
        """Replay checkpoint + deltas (``Snapshot.scala:88-111``)."""
        # Tombstone expiry needs metadata (retention conf) which itself comes
        # from replay; do a first pass with retention 0 then compute cutoff.
        replay = LogReplay(min_file_retention_timestamp=0)
        ckpt_actions = self._checkpoint_actions()
        if ckpt_actions:
            base_version = self.segment.checkpoint_version
            replay.current_version = base_version - 1 if base_version is not None else -1
            replay.append(base_version if base_version is not None else 0, ckpt_actions)
        for fs in self.segment.deltas:
            v = filenames.delta_version(fs.name)
            replay.append(v, actions_from_lines(self.store.read_iter(fs.path)))
        if replay.current_version == -1 and self.version >= 0:
            replay.current_version = self.version
        return replay

    def _checkpoint_actions(self) -> List[Action]:
        if not self.segment.checkpoint_files:
            return []
        return ckpt_mod.read_checkpoint_actions(
            self.store, [f.path for f in self.segment.checkpoint_files]
        )

    # -- reconciled state ------------------------------------------------

    @cached_property
    def protocol(self) -> Protocol:
        p = self._replay.current_protocol
        if p is None:
            return Protocol()
        return p

    @cached_property
    def metadata(self) -> Metadata:
        m = self._replay.current_metadata
        if m is None:
            return Metadata()
        return m

    @cached_property
    def set_transactions(self) -> Dict[str, SetTransaction]:
        return dict(self._replay.transactions)

    def transaction_version(self, app_id: str) -> int:
        t = self.set_transactions.get(app_id)
        return t.version if t else -1

    @cached_property
    def all_files(self) -> List[AddFile]:
        """Active AddFiles sorted by path (deterministic scan order)."""
        return sorted(self._replay.active_files.values(), key=lambda a: a.path)

    @cached_property
    def tombstones(self) -> List[RemoveFile]:
        cutoff = self.min_file_retention_timestamp()
        return [r for r in self._replay.get_tombstones() if r.delete_timestamp > cutoff]

    def tombstones_newer_than(self, cutoff_ms: int) -> List[RemoveFile]:
        """Un-expired tombstones against a caller-supplied horizon — VACUUM
        must apply its own retention, not the snapshot's clock-cached one."""
        return self._replay.get_tombstones(cutoff_ms)

    @property
    def num_of_files(self) -> int:
        return len(self.all_files)

    @property
    def size_in_bytes(self) -> int:
        return sum(a.size for a in self.all_files)

    @property
    def num_of_metadata(self) -> int:
        return 1 if self._replay.current_metadata is not None else 0

    @property
    def num_of_protocol(self) -> int:
        return 1 if self._replay.current_protocol is not None else 0

    @property
    def num_of_removes(self) -> int:
        return len(self.tombstones)

    @property
    def num_of_set_transactions(self) -> int:
        return len(self.set_transactions)

    @property
    def schema(self):
        return self.metadata.schema

    @property
    def partition_columns(self) -> List[str]:
        return self.metadata.partition_columns

    def checkpoint_actions(self) -> List[Action]:
        replay = self._replay
        replay.min_file_retention_timestamp = self.min_file_retention_timestamp()
        return replay.checkpoint_actions()

    def checkpoint_size_estimate(self) -> int:
        return (
            self.num_of_files
            + self.num_of_removes
            + self.num_of_set_transactions
            + self.num_of_metadata
            + self.num_of_protocol
        )

    # -- columnar export for the device path -----------------------------

    def files_arrays(self, stats_columns: Optional[Sequence[str]] = None):
        """Export AddFile metadata as numpy columns for the device scan planner
        (path dictionary stays on host; hashes/sizes/stats go to HBM).
        See ``delta_tpu.ops.pruning``."""
        from delta_tpu.ops.state_export import files_to_arrays

        return files_to_arrays(self.all_files, self.metadata, stats_columns)

    def __repr__(self) -> str:
        return f"Snapshot(version={self.version}, files={len(self.all_files)})"


class InitialSnapshot(Snapshot):
    """Snapshot of a table that has no commits yet
    (``Snapshot.scala:392-410``)."""

    def __init__(self, delta_log: "DeltaLog", metadata: Optional[Metadata] = None):
        super().__init__(
            delta_log,
            version=-1,
            segment=LogSegment.empty(delta_log.log_path),
            min_file_retention_timestamp=0,
            timestamp=-1,
        )
        self._initial_metadata = metadata or Metadata(
            configuration=DeltaConfigs.merge_global_configs({})
        )

    @cached_property
    def _replay(self) -> LogReplay:
        return LogReplay(0)

    @cached_property
    def metadata(self) -> Metadata:
        return self._initial_metadata

    @cached_property
    def protocol(self) -> Protocol:
        return Protocol()
