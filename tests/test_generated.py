"""Generated columns (reference spec: ``GeneratedColumnSuite``, 690 LoC;
semantics `GeneratedColumn.scala:79-365` + `SupportedGenerationExpressions`)."""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.schema.generated import generated_field, validate_generated_columns
from delta_tpu.schema.types import IntegerType, LongType, StringType, StructType
from delta_tpu.utils.errors import DeltaAnalysisError, InvariantViolationError


def gen_schema():
    return (
        StructType()
        .add("id", LongType())
        .add("name", StringType())
        .add_field(generated_field("id2", LongType(), "id * 2"))
        .add_field(generated_field("uname", StringType(), "upper(name)"))
    )


@pytest.fixture
def gtable(tmp_table):
    schema = gen_schema()
    if not hasattr(StructType, "add_field"):
        pytest.skip("no add_field")
    return DeltaTable.create(tmp_table, schema)


def rows(log):
    return sorted(scan_to_table(log.update()).to_pylist(), key=lambda r: r["id"])


def test_missing_generated_columns_computed(gtable):
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    assert rows(gtable.delta_log) == [
        {"id": 1, "name": "a", "id2": 2, "uname": "A"},
        {"id": 2, "name": "b", "id2": 4, "uname": "B"},
    ]


def test_provided_matching_values_accepted(gtable):
    gtable.write({"id": [3], "name": ["c"], "id2": [6], "uname": ["C"]})
    assert rows(gtable.delta_log)[0]["id2"] == 6


def test_provided_mismatching_values_rejected(gtable):
    with pytest.raises(InvariantViolationError, match="Generated Column"):
        gtable.write({"id": [3], "name": ["c"], "id2": [7]})


def test_null_inputs_propagate(gtable):
    gtable.write({"id": [5], "name": [None]})
    r = rows(gtable.delta_log)[0]
    assert r["uname"] is None and r["id2"] == 10


def test_protocol_bumped_to_writer_4(gtable):
    p = gtable.delta_log.update().protocol
    assert p.min_writer_version == 4


def test_unknown_function_rejected():
    schema = StructType().add("id", LongType()).add_field(
        generated_field("r", LongType(), "rand(id)")
    )
    with pytest.raises(DeltaAnalysisError):
        validate_generated_columns(schema)


def test_unknown_reference_rejected():
    schema = StructType().add("id", LongType()).add_field(
        generated_field("g", LongType(), "nope + 1")
    )
    with pytest.raises(DeltaAnalysisError, match="unknown"):
        validate_generated_columns(schema)


def test_generated_referencing_generated_rejected():
    schema = (
        StructType()
        .add("id", LongType())
        .add_field(generated_field("g1", LongType(), "id + 1"))
        .add_field(generated_field("g2", LongType(), "g1 + 1"))
    )
    with pytest.raises(DeltaAnalysisError, match="reference each other"):
        validate_generated_columns(schema)


def test_create_table_validates(tmp_table):
    schema = StructType().add("id", LongType()).add_field(
        generated_field("g", LongType(), "nope + 1")
    )
    with pytest.raises(DeltaAnalysisError):
        DeltaTable.create(tmp_table, schema)


def test_update_recomputes_generated(gtable):
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    UpdateCommand(gtable.delta_log, {"id": "id + 10"}, condition="name = 'a'").run()
    assert rows(gtable.delta_log) == [
        {"id": 2, "name": "b", "id2": 4, "uname": "B"},
        {"id": 11, "name": "a", "id2": 22, "uname": "A"},
    ]


def test_merge_update_recomputes_and_insert_computes(gtable):
    log = gtable.delta_log
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    src = pa.table({"k": [2, 5], "nm": ["bb", "e"]})
    MergeIntoCommand(
        log, src, "t.id = s.k",
        [MergeClause("update", assignments={"name": "s.nm"})],
        [MergeClause("insert", assignments={"id": "s.k", "name": "s.nm"})],
        source_alias="s", target_alias="t",
    ).run()
    assert rows(log) == [
        {"id": 1, "name": "a", "id2": 2, "uname": "A"},
        {"id": 2, "name": "bb", "id2": 4, "uname": "BB"},
        {"id": 5, "name": "e", "id2": 10, "uname": "E"},
    ]


def test_write_omitting_referenced_nullable_base_column(gtable):
    # omitting a nullable base column is legal; the generated column
    # computes over NULLs (name missing -> uname NULL, id2 still computed)
    gtable.write({"id": [7]})
    r = rows(gtable.delta_log)[0]
    assert r == {"id": 7, "name": None, "id2": 14, "uname": None}


# -- depth: partitions, DML interplay, evolution (GeneratedColumnSuite tail) --


def test_generated_partition_column(tmp_table):
    """Generated columns can partition the table — writers compute the
    partition value from the base column (the reference's headline use:
    date-derived partitions)."""
    schema = (
        StructType()
        .add("id", LongType())
        .add_field(generated_field("bucket", LongType(), "id % 3"))
    )
    t = DeltaTable.create(tmp_table, schema, partition_columns=["bucket"])
    t.write({"id": [0, 1, 2, 3, 4, 5]})
    snap = t.delta_log.update()
    assert snap.metadata.partition_columns == ["bucket"]
    got = t.to_arrow(filters=["bucket = 1"])
    assert sorted(got.column("id").to_pylist()) == [1, 4]
    # partition pruning actually prunes
    from delta_tpu.expr.parser import parse_predicate
    from delta_tpu.ops import pruning

    scan = pruning.files_for_scan(snap, [parse_predicate("bucket = 1")])
    assert len(scan.files) < len(snap.all_files)


def test_delete_on_generated_table_keeps_values(gtable):
    gtable.write({"id": [1, 2, 3], "name": ["a", "b", "c"]})
    gtable.delete("id2 = 4")  # predicate on the GENERATED column
    got = rows(gtable.delta_log)
    assert [r["id"] for r in got] == [1, 3]
    assert [r["id2"] for r in got] == [2, 6]


def test_generated_with_dv_table(tmp_table):
    schema = (
        StructType()
        .add("id", LongType())
        .add_field(generated_field("id2", LongType(), "id * 2"))
    )
    t = DeltaTable.create(
        tmp_table, schema,
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    )
    t.write({"id": [1, 2, 3]})
    t.update({"id": "id + 10"}, "id = 2")
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert [(r["id"], r["id2"]) for r in got] == [(1, 2), (3, 6), (12, 24)]


def test_merge_star_with_generated_uses_full_decode(tmp_table):
    """Projection pushdown must bail on generated columns (recompute needs
    base columns) — values stay correct under a DV-enabled star merge,
    which is exactly the configuration where pushdown would engage."""
    t = DeltaTable.create(
        tmp_table, gen_schema(),
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    )
    t.write({"id": [1, 2], "name": ["a", "b"]})
    src = pa.table({"id": pa.array([1, 9], pa.int64()),
                    "name": pa.array(["A", "n"])})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
     .when_matched_update_all().when_not_matched_insert_all().execute())
    got = rows(t.delta_log)
    assert [(r["id"], r["uname"]) for r in got] == [(1, "A"), (2, "B"), (9, "N")]


def test_alter_add_generated_column_nulls_old_rows_computes_new(gtable):
    """Adding a generated column to a table with existing rows: old rows
    read NULL (no stale/wrong values), and the NEXT write computes it."""
    from delta_tpu.commands.alter import add_columns

    gtable.write({"id": [1], "name": ["a"]})
    add_columns(gtable.delta_log, [generated_field("id3", LongType(), "id * 3")])
    got = rows(gtable.delta_log)
    assert got[0].get("id3") is None
    gtable.write({"id": [2], "name": ["b"]})
    got = rows(gtable.delta_log)
    assert [(r["id"], r["id3"]) for r in got] == [(1, None), (2, 6)]


def test_generated_column_in_constraint(gtable):
    from delta_tpu.commands.alter import add_constraint

    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    add_constraint(gtable.delta_log, "small", "id2 < 100")
    gtable.write({"id": [5], "name": ["e"]})  # id2=10, passes
    assert len(rows(gtable.delta_log)) == 3
    with pytest.raises(InvariantViolationError):
        gtable.write({"id": [500], "name": ["big"]})  # id2=1000 violates


def test_timestamp_date_generation(tmp_table):
    """The reference's canonical use: date partitions derived from a
    timestamp column."""
    import datetime

    from delta_tpu.schema.types import DateType, TimestampType

    schema = (
        StructType()
        .add("ts", TimestampType())
        .add_field(generated_field("d", DateType(), "cast(ts as date)"))
    )
    try:
        t = DeltaTable.create(tmp_table, schema)
    except DeltaAnalysisError:
        pytest.skip("cast-to-date not in the generation whitelist")
    t.write({"ts": [datetime.datetime(2024, 5, 1, 12, 30),
                    datetime.datetime(2024, 5, 2, 1, 0)]})
    got = t.to_arrow()
    assert got.column("d").to_pylist() == [
        datetime.date(2024, 5, 1), datetime.date(2024, 5, 2)
    ]
