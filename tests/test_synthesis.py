"""Predicate pushdown synthesis (`expr/synthesis`): soundness above all.

The spine is a seeded property harness: random predicates per rewrite
family over random tables, asserting a synthesized prune NEVER excludes a
file/row-group that contains a matching row — NULLs, NaN, negative ranges,
int64 boundaries, and unicode prefix edges included. Both pruning tiers
share one rewrite (`ops.pruning.skipping_predicate`), so the harness
exercises the rewrite against the stats-env semantics the tiers evaluate,
plus end-to-end result identity through the real scan path, the device
(jaxeval) file tier, and the resident device planner (router audit).
"""
import datetime as dt
import json
import math
import os

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.rowgroups import _StatsEnv
from delta_tpu.expr import ir, synthesis
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.ops import pruning, state_export
from delta_tpu.ops.state_cache import DeviceStateCache
from delta_tpu.protocol.actions import AddFile, Metadata
from delta_tpu.schema.types import (
    DateType, DoubleType, LongType, StringType, StructType, TimestampType,
)
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

SCHEMA = (StructType()
          .add("a", LongType()).add("b", LongType())
          .add("f", DoubleType()).add("s", StringType())
          .add("d", DateType()).add("ts", TimestampType()))
TYPES = {f.name: f.data_type for f in SCHEMA.fields}
META = Metadata(schema_string=SCHEMA.to_json())

PAIRS_PER_FAMILY = 500
FILES_PER_CASE = 3
ROWS_PER_FILE = 12


@pytest.fixture(autouse=True)
def _fresh_state_cache():
    DeviceStateCache.reset()
    yield
    DeviceStateCache.reset()


# ---------------------------------------------------------------------------
# Random tables + the exact stats env both tiers evaluate against
# ---------------------------------------------------------------------------

_INT_POOL = [-(2**62), -(2**31), -1000, -7, -1, 0, 1, 3, 7, 999,
             2**31, 2**53, 2**62, 2**63 - 1]
_STR_POOL = ["", "a", "ab", "us-west", "us-west-2", "eu-central-1",
             "zz", "éclair", "中文abc", "us-w￿",
             "US-WEST", "0", "  pad"]


def _gen_rows(rng):
    rows = []
    base_day = dt.date(2020, 1, 1)
    for _ in range(ROWS_PER_FILE):
        row = {}
        row["a"] = None if rng.random() < 0.12 else (
            int(rng.choice(_INT_POOL)) if rng.random() < 0.3
            else int(rng.integers(-10_000, 10_000)))
        row["b"] = None if rng.random() < 0.12 else (
            int(rng.choice(_INT_POOL)) if rng.random() < 0.2
            else int(rng.integers(-50, 50)))
        r = rng.random()
        row["f"] = (None if r < 0.1 else math.nan if r < 0.18
                    else float(rng.normal(0, 1e3)))
        row["s"] = None if rng.random() < 0.1 else str(rng.choice(_STR_POOL))
        row["d"] = None if rng.random() < 0.1 else (
            base_day + dt.timedelta(days=int(rng.integers(0, 3000))))
        row["ts"] = None if rng.random() < 0.1 else dt.datetime(
            2020, 1, 1) + dt.timedelta(minutes=int(rng.integers(0, 2_000_000)))
        rows.append(row)
    return rows


def _stat_json(v, round_up=False):
    from delta_tpu.exec.parquet import json_stat_value

    return json_stat_value(v, round_up)


def _stats_env(rows) -> _StatsEnv:
    """The file-tier stats env for one synthetic file: min/max over non-null
    (floats: non-NaN) values rendered the way JSON stats carry them."""
    env = _StatsEnv()
    env["numrecords"] = len(rows)
    for c in TYPES:
        vals = [r[c] for r in rows if r[c] is not None]
        if isinstance(TYPES[c], DoubleType):
            vals = [v for v in vals if not math.isnan(v)]
        env[f"nullcount.{c}"] = len(rows) - len([r for r in rows
                                                if r[c] is not None])
        if vals:
            mn, mx = _stat_json(min(vals)), _stat_json(max(vals), True)
            if mn is not None:
                env[f"min.{c}"] = mn
            if mx is not None:
                env[f"max.{c}"] = mx
    return env


def _matches(pred: ir.Expression, rows) -> bool:
    for r in rows:
        try:
            if pred.eval(dict(r)) is True:
                return True
        except Exception:
            return True  # un-evaluable row: treat as a potential match
    return False


def _soundness_case(pred: ir.Expression, files) -> None:
    rewritten = pruning.skipping_predicate(pred, frozenset(), TYPES)
    for rows in files:
        if not _matches(pred, rows):
            continue
        env = _stats_env(rows)
        try:
            verdict = rewritten.eval(env)
        except Exception:
            verdict = None  # the tiers keep on evaluation errors
        assert verdict is not False, (
            f"synthesized rewrite pruned a matching file\n"
            f"  predicate: {pred.sql()}\n  rewrite:   {rewritten.sql()}\n"
            f"  env: {dict(env)}\n  rows: {rows}")


# ---------------------------------------------------------------------------
# Random predicate generators per family
# ---------------------------------------------------------------------------


def _lit_num(rng):
    if rng.random() < 0.3:
        return ir.Literal(int(rng.choice(_INT_POOL)))
    if rng.random() < 0.5:
        return ir.Literal(float(rng.normal(0, 1e4)))
    return ir.Literal(int(rng.integers(-5_000, 5_000)))


_CMPS = [ir.Eq, ir.Lt, ir.Le, ir.Gt, ir.Ge]


def _arith_expr(rng, depth=0):
    r = rng.random()
    if depth >= 2 or r < 0.35:
        return ir.Column(str(rng.choice(["a", "b", "f"])))
    if r < 0.45:
        return _lit_num(rng)
    op = rng.choice(["add", "sub", "mul", "div", "mod", "neg"])
    if op == "neg":
        return ir.Neg(_arith_expr(rng, depth + 1))
    if op in ("div", "mod"):
        cls = ir.Div if op == "div" else ir.Mod
        return cls(_arith_expr(rng, depth + 1), _lit_num(rng))
    cls = {"add": ir.Add, "sub": ir.Sub, "mul": ir.Mul}[op]
    return cls(_arith_expr(rng, depth + 1), _arith_expr(rng, depth + 1))


def _gen_arith(rng):
    cmp_cls = rng.choice(_CMPS)
    l, r = _arith_expr(rng), _lit_num(rng)
    return cmp_cls(r, l) if rng.random() < 0.2 else cmp_cls(l, r)


def _gen_string(rng):
    col = ir.Column("s")
    kind = rng.choice(["substr", "like", "startswith", "substr_cmp"])
    prefix = str(rng.choice(_STR_POOL))
    if kind == "like":
        pat = prefix + rng.choice(["%", "%x", "_z%", "", "%_"])
        return ir.Like(col, ir.Literal(pat))
    if kind == "startswith":
        return ir.StartsWith(col, ir.Literal(prefix))
    k = int(rng.integers(0, 6))
    sub = ir.Func("substr", [col, ir.Literal(1), ir.Literal(k)])
    cmp_cls = rng.choice(_CMPS)
    return cmp_cls(sub, ir.Literal(prefix[:k] if kind == "substr" else prefix))


def _gen_temporal(rng):
    kind = rng.choice(["year", "to_date", "date_add", "cast_long",
                       "cast_double"])
    if kind == "year":
        return rng.choice(_CMPS)(
            ir.Func("year", [ir.Column("d")]),
            ir.Literal(int(rng.integers(2018, 2031))))
    if kind == "to_date":
        day = dt.date(2020, 1, 1) + dt.timedelta(days=int(rng.integers(0, 3000)))
        return rng.choice(_CMPS)(
            ir.Func("to_date", [ir.Column("ts")]), ir.Literal(day.isoformat()))
    if kind == "date_add":
        day = dt.date(2020, 1, 1) + dt.timedelta(days=int(rng.integers(0, 3000)))
        fn = rng.choice(["date_add", "date_sub"])
        # over BOTH temporal columns: on a timestamp the composite is
        # day-truncating, not strict monotone (the r12 review catch)
        col = str(rng.choice(["d", "ts"]))
        return rng.choice(_CMPS)(
            ir.Func(fn, [ir.Column(col), ir.Literal(int(rng.integers(-40, 40)))]),
            ir.Literal(day.isoformat()))
    target = LongType() if kind == "cast_long" else DoubleType()
    return rng.choice(_CMPS)(
        ir.Cast(_arith_expr(rng, depth=1), target), _lit_num(rng))


def _branch_val(rng):
    r = rng.random()
    if r < 0.3:
        return _lit_num(rng)
    if r < 0.4:
        return ir.Literal(None)
    return _arith_expr(rng, depth=1)


def _gen_conditional(rng):
    """abs / coalesce / CASE WHEN shapes (the r16 synthesis additions)."""
    cmp_cls = rng.choice(_CMPS)
    kind = rng.choice(["abs", "coalesce", "casewhen"])
    if kind == "abs":
        return cmp_cls(ir.Func("abs", [_arith_expr(rng, depth=1)]),
                       _lit_num(rng))
    if kind == "coalesce":
        n = int(rng.integers(1, 4))
        return cmp_cls(ir.Coalesce(*[_branch_val(rng) for _ in range(n)]),
                       _lit_num(rng))
    n = int(rng.integers(1, 3))
    branches = [(rng.choice(_CMPS)(ir.Column(str(rng.choice(["a", "b"]))),
                                   _lit_num(rng)), _branch_val(rng))
                for _ in range(n)]
    default = _branch_val(rng) if rng.random() < 0.7 else None
    return cmp_cls(ir.CaseWhen(branches, default), _lit_num(rng))


def _gen_colcol(rng):
    """Column-vs-column comparisons over every type pairing — the float,
    string, and mixed pairs must stay gated (UNKNOWN), the int/temporal
    pairs must stay sound."""
    cols = ["a", "b", "f", "s", "d", "ts"]
    l = ir.Column(str(rng.choice(cols)))
    r = ir.Column(str(rng.choice(cols)))
    return rng.choice(_CMPS)(l, r)


def _gen_colcol_typed(rng):
    """Row-evaluable pairings only (for compound conjuncts: an un-evaluable
    comparison would mark every row a 'potential match' and mask the other
    conjunct's exclusion in the harness's conservative accounting)."""
    groups = [["a", "b"], ["f"], ["s"], ["d"], ["ts"]]
    group = groups[int(rng.integers(0, len(groups)))]
    l = ir.Column(str(rng.choice(group)))
    r = ir.Column(str(rng.choice(group)))
    return rng.choice(_CMPS)(l, r)


def _gen_compound(rng):
    a = _gen_arith(rng)
    b = rng.choice([_gen_arith, _gen_string, _gen_conditional,
                    _gen_colcol_typed])(rng)
    r = rng.random()
    if r < 0.3:
        return ir.And(a, b)
    if r < 0.6:
        return ir.Or(a, b)
    if r < 0.8:
        return ir.Not(a)
    return ir.Not(ir.And(a, b) if rng.random() < 0.5 else ir.Or(a, b))


@pytest.mark.parametrize("family,gen", [
    ("arithmetic", _gen_arith),
    ("string", _gen_string),
    ("temporal", _gen_temporal),
    ("conditional", _gen_conditional),
    ("colcol", _gen_colcol),
    ("compound", _gen_compound),
])
def test_property_soundness(family, gen):
    """≥500 random predicate/table pairs per family: a matching row's file
    is never excluded by the synthesized rewrite (seeded, no wall clock)."""
    rng = np.random.default_rng(hash(family) % (2**32))
    for _ in range(PAIRS_PER_FAMILY):
        files = [_gen_rows(rng) for _ in range(FILES_PER_CASE)]
        _soundness_case(gen(rng), files)


def test_property_soundness_device_file_tier():
    """A slice of random arithmetic predicates through the REAL device file
    tier (jaxeval over FileStateArrays lanes): keep-set must be a superset
    of the files holding matches."""
    rng = np.random.default_rng(4242)
    n_checked = 0
    for _ in range(12):
        files = [_gen_rows(rng) for _ in range(FILES_PER_CASE)]
        pred = _gen_arith(rng)
        adds = [_addfile(i, rows) for i, rows in enumerate(files)]
        rewritten = pruning.skipping_predicate(
            pred, frozenset(), synthesis.schema_types(META))
        arrays = state_export.files_to_arrays(adds, META)
        keep = pruning._prune_device(arrays, rewritten)
        if keep is None:
            continue  # not device-compilable (e.g. rewrote to UNKNOWN+str)
        n_checked += 1
        for i, rows in enumerate(files):
            if _matches(pred, rows):
                assert keep[i], (pred.sql(), rewritten.sql(), rows)
    assert n_checked >= 4  # the slice must actually exercise the device


def _addfile(i, rows):
    stats = {
        "numRecords": len(rows),
        "minValues": {}, "maxValues": {}, "nullCount": {},
    }
    for c in TYPES:
        vals = [r[c] for r in rows if r[c] is not None]
        if isinstance(TYPES[c], DoubleType):
            vals = [v for v in vals if not math.isnan(v)]
        stats["nullCount"][c] = len(rows) - len(
            [r for r in rows if r[c] is not None])
        if vals:
            mn, mx = _stat_json(min(vals)), _stat_json(max(vals), True)
            if mn is not None:
                stats["minValues"][c] = mn
            if mx is not None:
                stats["maxValues"][c] = mx
    return AddFile(path=f"part-{i:05d}.parquet", partition_values={},
                   size=1000, modification_time=0, data_change=True,
                   stats=json.dumps(stats))


# ---------------------------------------------------------------------------
# Explicit edge matrix
# ---------------------------------------------------------------------------


def _env(d):
    e = _StatsEnv()
    for k, v in d.items():
        e[k.lower()] = v
    return e


def _rw(s, types=TYPES):
    return pruning.skipping_predicate(parse_predicate(s), frozenset(), types)


def test_edge_null_only_column():
    rw = _rw("a * b > 10")
    env = _env({"numRecords": 5, "nullCount.a": 5, "nullCount.b": 0,
                "min.b": 1, "max.b": 2})
    assert rw.eval(env) is None  # missing bounds: keep (conservative)


def test_edge_div_by_zero_crossing_interval_is_unknown():
    rw = _rw("a / b > 2")
    assert not synthesis.can_exclude(rw)
    rw2 = _rw("a / 0 > 2")  # literal zero divisor: NULL, never matches
    assert isinstance(rw2, ir.Literal) and rw2.value is False


def test_edge_int64_boundary_multiplication():
    """Products near ±2^63 must not wrap into a wrong exclusion: candidates
    evaluate in float64 where overflow saturates monotonically."""
    big = 2**62
    rows = [{"a": big, "b": 4, "f": 0.0, "s": None, "d": None, "ts": None}]
    pred = parse_predicate(f"a * b >= {big * 4}")
    _soundness_case(pred, [rows])
    # and the Arrow host tier end to end over AddFile stats
    adds = [_addfile(0, rows)]
    kept = pruning.prune_files(adds, META, [pred])
    assert kept == adds


def test_edge_nan_float_bounds_keep():
    rw = _rw("f * 2 > 100")
    env = _env({"numRecords": 3, "nullCount.f": 0})  # NaN bounds dropped
    assert rw.eval(env) is None


def test_edge_truncated_string_stats_keep():
    # binary/truncated footer bounds are dropped before the env is built
    # (exec/rowgroups._safe_bounds) — absent lanes must keep
    rw = _rw("substr(s, 1, 4) = 'us-w'")
    assert rw.eval(_env({"numRecords": 3, "nullCount.s": 0})) is None
    # present full-string bounds prune correctly
    env = _env({"numRecords": 3, "nullCount.s": 0,
                "min.s": "aa", "max.s": "bz"})
    assert rw.eval(env) is False


def test_edge_date_add_over_timestamp_is_day_truncating():
    """date_add over a TIMESTAMP truncates to a date first, so the shift is
    NOT strict monotone — an exact inversion onto the raw column would
    prune files whose rows fall later inside the matching day (caught in
    review; the rewrite must use the to_date monotone wrap instead)."""
    rows = [{"a": None, "b": None, "f": None, "s": None, "d": None,
             "ts": dt.datetime(2021, 6, 1, 8, 30)}]
    pred = parse_predicate("date_add(ts, 5) = '2021-06-06'")
    assert pred.eval(dict(rows[0])) is True
    _soundness_case(pred, [rows])
    rw = pruning.skipping_predicate(pred, frozenset(), TYPES)
    assert "to_date" in rw.sql()  # the wrap, not a raw ts comparison


def test_edge_unicode_prefix():
    rows = [{"a": None, "b": None, "f": None, "s": "éclair-42",
             "d": None, "ts": None}]
    for q in ["substr(s, 1, 2) = 'éc'", "s like 'écl%'"]:
        _soundness_case(parse_predicate(q), [rows])


def test_edge_null_literal_arithmetic_never_matches():
    rw = pruning.skipping_predicate(
        ir.Gt(ir.Add(ir.Column("a"), ir.Literal(None)), ir.Literal(1)),
        frozenset(), TYPES)
    assert isinstance(rw, ir.Literal) and rw.value is False


def test_edge_mod_bounds():
    # |a % 7| <= 7 always: an impossible comparison excludes everything...
    rw = _rw("a % 7 >= 100")
    assert isinstance(rw, ir.Literal) and rw.value is False
    # ...while a satisfiable one can never exclude on stats alone
    assert not synthesis.can_exclude(_rw("a % 7 < 3"))


def test_partition_columns_stay_unknown():
    types = dict(TYPES)
    rw = pruning.skipping_predicate(
        parse_predicate("a * 2 > 10"), frozenset({"a"}), types)
    assert not synthesis.can_exclude(rw)


def test_string_column_arithmetic_gated():
    # `s * 2 > 5` on a string column must NOT synthesize (str order is not
    # numeric order; Python would happily repeat-concatenate)
    rw = _rw("s * 2 > 5")
    assert not synthesis.can_exclude(rw)


def test_narrowing_cast_of_string_gated():
    rw = _rw("cast(s as long) > 5")
    assert not synthesis.can_exclude(rw)


def test_synthesis_conf_off_restores_base():
    with conf.set_temporarily(**{"delta.tpu.read.predicateSynthesis": False}):
        rw = _rw("a * b > 10")
    assert not synthesis.can_exclude(rw)


# ---------------------------------------------------------------------------
# NOT pushdown (satellite bugfix) — conservatism
# ---------------------------------------------------------------------------


def test_not_pushdown_comparisons():
    def base(s):
        return pruning.skipping_predicate(parse_predicate(s), frozenset(),
                                          TYPES)

    assert base("not a < 5").sql() == base("a >= 5").sql()
    assert base("not a >= 5").sql() == base("a < 5").sql()
    # Not(Ne) ≡ Eq needs no type gate (both FALSE for NaN)
    assert pruning.skipping_predicate(parse_predicate("not a != 5")).sql() \
        == base("a = 5").sql()
    # Not(Eq) stays UNKNOWN (documented conservatism)
    assert not synthesis.can_exclude(base("not a = 5"))
    # De Morgan: each branch rewrites conservatively
    assert synthesis.can_exclude(base("not (a < 5 and b < 5)"))


def test_not_inequality_flip_gated_on_float_nan_hazard():
    """`NOT (f < L)` is TRUE for a NaN row while `f >= L` is FALSE — the
    flip must not fire for floating columns (min/max stats ignore NaN, so
    it would prune the NaN row's file)."""
    rw = _rw("not f < 3000")
    assert not synthesis.can_exclude(rw)
    # and the full scenario: a file whose only match is the NaN row
    rows = [{"a": 1, "b": 1, "f": math.nan, "s": None, "d": None, "ts": None},
            {"a": 2, "b": 1, "f": 10.0, "s": None, "d": None, "ts": None}]
    _soundness_case(parse_predicate("not f < 3000"), [rows])
    # typeless callers keep the old UNKNOWN behavior for inequalities
    assert not synthesis.can_exclude(
        pruning.skipping_predicate(parse_predicate("not a < 5")))


def test_not_pushdown_conservative_on_nulls():
    """Not(Lt(a, 5)) ≡ Ge(a, 5) under 3-valued logic: a NULL row matches
    neither, so pruning to the flipped comparison never drops a match."""
    rng = np.random.default_rng(7)
    for _ in range(200):
        files = [_gen_rows(rng) for _ in range(FILES_PER_CASE)]
        cmp_cls = rng.choice(_CMPS)
        pred = ir.Not(cmp_cls(ir.Column("a"), _lit_num(rng)))
        _soundness_case(pred, files)


# ---------------------------------------------------------------------------
# End-to-end: result identity + attribution parity + both tiers
# ---------------------------------------------------------------------------


@pytest.fixture
def synth_table(tmp_table):
    with conf.set_temporarily(**{
        "delta.tpu.write.rowGroupRows": 250,
        "delta.tpu.write.targetFileRows": 1000,
    }):
        n = 4000
        ids = np.arange(n, dtype=np.int64)
        base = dt.datetime(2021, 1, 1)
        t = pa.table({
            "id": ids,
            "price": ids,  # sorted: tight per-file/group bounds
            "qty": pa.array([None if i % 13 == 0 else int(i % 7) + 1
                             for i in range(n)], pa.int64()),
            "sym": pa.array([f"{'us-w' if i < n // 2 else 'eu-c'}{i:06d}"
                             for i in range(n)]),
            "ts": pa.array([base + dt.timedelta(hours=i) for i in range(n)],
                           pa.timestamp("us")),
        })
        log = DeltaLog.for_table(tmp_table)
        WriteIntoDelta(log, "append", t).run()
    return DeltaTable.for_path(tmp_table)


E2E_PREDICATES = [
    "price * qty > 26000",
    "price * 2 + 10 >= 7000",
    "(price - 100) / 4 <= 20",
    "- price >= -50",
    "substr(sym, 1, 4) = 'eu-c'",
    "sym like 'us-w0001%'",
    "cast(price as double) * 1.5 > 5900",
    "not (price < 3900)",
    "to_date(ts) = '2021-02-01'",
    "price * qty > 26000 or sym like 'zz%'",
    "price % 1000 >= 0 and price * 3 > 11500",
]


@pytest.mark.parametrize("pred", E2E_PREDICATES)
def test_e2e_result_identity(synth_table, pred):
    on = synth_table.to_arrow(filters=[pred])
    with conf.set_temporarily(**{"delta.tpu.read.predicateSynthesis": False}):
        off = synth_table.to_arrow(filters=[pred])
    assert on.sort_by("id").equals(off.sort_by("id"))


def test_e2e_synthesis_actually_prunes(synth_table):
    from delta_tpu.obs import scan_report

    telemetry.reset_all()
    synth_table.to_arrow(filters=["price * qty > 26000"])
    rep = scan_report.last_scan_report()
    assert rep.files_pruned > 0 and rep.row_groups_pruned > 0
    assert rep.bytes_skipped > 0
    with conf.set_temporarily(**{"delta.tpu.read.predicateSynthesis": False}):
        telemetry.reset_all()
        synth_table.to_arrow(filters=["price * qty > 26000"])
        rep_off = scan_report.last_scan_report()
    assert rep_off.files_pruned == 0 and rep_off.row_groups_pruned == 0


def test_e2e_rewrites_fired_matches_counter(synth_table):
    from delta_tpu.obs import scan_report

    telemetry.reset_all()
    synth_table.to_arrow(
        filters=["price * qty > 26000 and substr(sym, 1, 4) = 'us-w'"])
    rep = scan_report.last_scan_report()
    fired = telemetry.counters().get("scan.rewrites.fired", 0)
    assert len(rep.rewrites_fired) == fired > 0
    families = {f["family"] for f in rep.rewrites_fired}
    assert "arithmetic" in families and "string" in families
    for f in rep.rewrites_fired:
        assert f["conjunct"] and f["rewrite"]
    # the journal fingerprint marks the same conjuncts synthesizable
    from delta_tpu.obs import journal

    log = synth_table.delta_log
    journal.flush(log.log_path)
    scans = journal.read_entries(log.log_path, kinds=("scan",))
    fp = scans[-1]["fingerprint"]
    assert all(c["synthesizable"] for c in fp["conjuncts"])
    assert fp["prunableColumns"]


def test_e2e_rowgroup_tier_without_file_tier(synth_table):
    """The row-group planner fires on the same rewrite even when the file
    tier can't help (predicate selective within files only)."""
    from delta_tpu.exec import rowgroups

    snap = synth_table.delta_log.update()
    scan = pruning.files_for_scan(snap, [parse_predicate("price * 2 >= 500")])
    add = scan.files[0]
    meta = rowgroups.read_footer(
        os.path.join(snap.delta_log.data_path, add.path))
    plan = rowgroups.plan_row_groups(
        meta, parse_predicate("price * 2 >= 500"), None, frozenset(),
        synthesis.schema_types(snap.metadata))
    assert 0 < len(plan.keep) < plan.total
    assert plan.fired and plan.fired[0]["family"] == "arithmetic"


def test_device_plan_path_serves_synthesized_rewrite(synth_table):
    """Acceptance: a synthesized numeric rewrite lowers to ranges and the
    RESIDENT device planner serves it — the router audit shows the device
    plan path engaged (auto mode, host priced out via a calibrated
    constant), and the scan still equals the host result."""
    from delta_tpu.obs import router_audit
    from delta_tpu.parallel import link

    telemetry.reset_all()
    router_audit.clear_audits()
    link.set_calibrated("HOST_PRUNE_S_PER_CELL", 10.0)  # price the host out
    try:
        on = synth_table.to_arrow(filters=["price * 2 + 10 >= 7000"])
        audits = [a for a in router_audit.recent_audits()
                  if a["op"] == "scan.plan"]
        assert audits and audits[-1]["decision"] == "device"
        assert telemetry.counters().get("stateCache.scan.resident", 0) >= 1
    finally:
        link.clear_calibrated()
    with conf.set_temporarily(**{"delta.tpu.read.predicateSynthesis": False}):
        off = synth_table.to_arrow(filters=["price * 2 + 10 >= 7000"])
    assert on.sort_by("id").equals(off.sort_by("id"))


# ---------------------------------------------------------------------------
# Advisor: staleShape regression over a pre-recorded journal segment
# ---------------------------------------------------------------------------


def test_advisor_stale_shape_from_pre_synthesis_journal(tmp_table):
    """Journal entries recorded BEFORE the synthesis feature carry no
    ``synthesizable`` field; when their shape is now coverable they get the
    distinct ``staleShape`` reason instead of polluting layout/shape
    evidence."""
    t = DeltaTable.create(tmp_table, data=pa.table({
        "price": pa.array(range(100), pa.int64()),
        "qty": pa.array(range(100), pa.int64()),
    }))
    from delta_tpu.obs import journal

    jdir = journal.journal_dir(t.delta_log.log_path)
    os.makedirs(jdir, exist_ok=True)
    entry = {
        "kind": "scan", "ts": 1_600_000_000_000,
        "report": {"filesTotal": 4, "filesAfterPartition": 4,
                   "filesScanned": 4, "rowGroupsTotal": 4,
                   "rowGroupsPruned": 0, "rowGroupsLateSkipped": 0},
        "fingerprint": {
            "columns": ["price", "qty"],
            "conjuncts": [{"shape": "gt(mul(price,qty),?)",
                           "columns": ["price", "qty"],
                           "prunable": False, "partition": False}],
            "prunableColumns": [], "residualColumns": ["price", "qty"],
            "key": "gt(mul(price,qty),?)",
        },
    }
    seg = os.path.join(jdir, "journal-0000000000001-99999-000001.jsonl")
    with open(seg, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(entry) + "\n")
    rep = t.advise()
    [g] = [g for g in rep.facts["neverPruned"]
           if g["fingerprint"] == "gt(mul(price,qty),?)"]
    assert g["reason"].startswith("staleShape")
    # a genuinely uncoverable legacy shape still reads as 'shape' —
    # coalesce/abs graduated to synthesizable in r16, so use a truly
    # non-monotone wrap (lower) that synthesis can never invert
    entry["fingerprint"] = {
        "columns": ["price"], "conjuncts": [
            {"shape": "eq(lower(price),?)", "columns": ["price"],
             "prunable": False, "partition": False}],
        "prunableColumns": [], "residualColumns": ["price"],
        "key": "eq(lower(price),?)",
    }
    with open(seg, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(entry) + "\n")
    rep = t.advise()
    [g2] = [g2 for g2 in rep.facts["neverPruned"]
            if g2["fingerprint"] == "eq(lower(price),?)"]
    assert g2["reason"].startswith("shape")


# ---------------------------------------------------------------------------
# Unit: rewrite shapes
# ---------------------------------------------------------------------------


def test_single_column_inversion_is_exact_lane_comparison():
    rw = _rw("price * 2 + 10 >= 1000", {"price": LongType()})
    assert rw.sql() == "(`max.price` >= 495)"
    rw = _rw("price * -2 >= 10", {"price": LongType()})
    assert rw.sql() == "(`min.price` <= -5)"
    rw = _rw("100 - price < 40", {"price": LongType()})
    assert rw.sql() == "(`max.price` > 60)"


def test_trunc_cast_pads_one_unit():
    rw = _rw("cast(f as long) = 10", {"f": DoubleType()})
    assert "9" in rw.sql() and "11" in rw.sql()


def test_interval_mul_emits_four_endpoint_products():
    rw = _rw("a * b > 100", {"a": LongType(), "b": LongType()})
    assert rw.sql().count("*") == 4


def test_abs_rewrite_shapes():
    # |a| < v excludes when the whole stats range sits outside (-v, v)
    rw = _rw("abs(a) < 10")
    env = _env({"numRecords": 2, "nullCount.a": 0, "min.a": 50, "max.a": 99})
    assert rw.eval(env) is False
    env2 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -5, "max.a": 99})
    assert rw.eval(env2) is not False
    # the upper test splits into the two signed comparisons
    rw = _rw("abs(a) > 100")
    assert synthesis.can_exclude(rw)
    env3 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -5, "max.a": 5})
    assert rw.eval(env3) is False
    env4 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -500, "max.a": 5})
    assert rw.eval(env4) is not False
    # impossible bounds are constant-folded to never-match
    for q in ["abs(a) < 0", "abs(a) <= -3", "abs(a) = -1"]:
        rw = _rw(q)
        assert isinstance(rw, ir.Literal) and rw.value is False
    # trivially-true bounds can never exclude (the interval fallback may
    # still emit an always-true rewrite — it must not evaluate False)
    rw = _rw("abs(a) >= 0")
    env5 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -5, "max.a": 5})
    assert rw.eval(env5) is not False


def test_abs_nested_in_interval():
    # abs below arithmetic goes through the interval path, whose lower
    # candidate 0 keeps the zero-crossing case sound
    rw = _rw("abs(a) * 2 > 100")
    env = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -5, "max.a": 5})
    assert rw.eval(env) is False
    env2 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -80, "max.a": 5})
    assert rw.eval(env2) is not False


def test_coalesce_casewhen_rewrites():
    # the 0 literal branch fails `> 10`, so only a's stats decide
    rw = _rw("coalesce(a, 0) > 10")
    env = _env({"numRecords": 2, "nullCount.a": 0, "min.a": -5, "max.a": 5})
    assert rw.eval(env) is False
    # a satisfying literal branch means some row may match: unprunable
    assert not synthesis.can_exclude(_rw("coalesce(a, 100) > 10"))
    # expression branches OR together
    rw = _rw("coalesce(a, b) > 10")
    env_hi = _env({"numRecords": 2, "nullCount.a": 0, "min.a": 50,
                   "max.a": 60, "min.b": 0, "max.b": 1})
    assert rw.eval(env_hi) is not False
    # CASE WHEN: branch values + default, conditions ignored
    pred = ir.Ge(ir.CaseWhen(
        [(ir.Gt(ir.Column("b"), ir.Literal(0)), ir.Column("a"))]),
        ir.Literal(1000))
    rw = pruning.skipping_predicate(pred, frozenset(), TYPES)
    env = _env({"numRecords": 2, "nullCount.a": 0, "min.a": 1, "max.a": 10})
    assert rw.eval(env) is False  # NULL default drops out; a's range too low
    env2 = _env({"numRecords": 2, "nullCount.a": 0, "min.a": 1,
                 "max.a": 5000})
    assert rw.eval(env2) is not False


def test_colcol_rewrite_shapes():
    rw = _rw("a < b")
    assert rw.sql() == "(`min.a` < `max.b`)"
    rw = _rw("a >= b")
    assert rw.sql() == "(`max.a` >= `min.b`)"
    rw = _rw("a = b")  # interval intersection
    s = rw.sql()
    assert "min.a" in s and "max.a" in s and "min.b" in s and "max.b" in s
    # strict self-comparison can never match
    rw = _rw("a < a")
    assert isinstance(rw, ir.Literal) and rw.value is False
    assert not synthesis.can_exclude(_rw("a <= a"))


def test_colcol_gates():
    # float columns are NaN-blind: gated (same hazard as the NOT flip)
    assert not synthesis.can_exclude(_rw("f < a"))
    assert not synthesis.can_exclude(_rw("a < f"))
    # string bounds may be truncated: gated
    assert not synthesis.can_exclude(pruning.skipping_predicate(
        parse_predicate("x < y"), frozenset(),
        {"x": StringType(), "y": StringType()}))
    # mixed temporal types: gated; same-type temporal fires
    assert not synthesis.can_exclude(_rw("d < ts"))
    assert synthesis.can_exclude(pruning.skipping_predicate(
        parse_predicate("x < y"), frozenset(),
        {"x": DateType(), "y": DateType()}))
    # partition columns have no stats lanes
    assert not synthesis.can_exclude(pruning.skipping_predicate(
        parse_predicate("a < b"), frozenset({"b"}), TYPES))


def test_colcol_temporal_soundness():
    rng = np.random.default_rng(1616)
    for _ in range(100):
        files = [_gen_rows(rng) for _ in range(FILES_PER_CASE)]
        for col in ("d", "ts"):
            pred = rng.choice(_CMPS)(ir.Column(col), ir.Column(col))
            _soundness_case(pred, files)


def test_classify_family():
    assert synthesis.classify_family(parse_predicate("a * b > 1")) == "arithmetic"
    assert synthesis.classify_family(
        parse_predicate("substr(s, 1, 2) = 'ab'")) == "string"
    assert synthesis.classify_family(
        parse_predicate("cast(a as long) > 1")) == "cast"
    assert synthesis.classify_family(parse_predicate("not a = 1")) == "not"
    assert synthesis.classify_family(parse_predicate("abs(a) > 1")) == "arithmetic"
    assert synthesis.classify_family(
        parse_predicate("coalesce(a, 0) > 1")) == "conditional"
    assert synthesis.classify_family(parse_predicate("a < b")) == "colcol"
