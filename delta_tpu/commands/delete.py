"""DELETE command — predicate-scoped file removal/rewrite.

Mirrors the 3-case structure of `commands/DeleteCommand.scala:92-181`:
(1) no predicate → remove every file (no data read);
(2) partition-only predicate → remove pruned files metadata-only;
(3) data predicate → find touched files by a vectorized scan, rewrite each
    keeping only non-matching rows (the reference rewrites with the negated
    predicate via Spark jobs, `:158-171`).
Emits the reference's operation metrics (numRemovedFiles/numAddedFiles/
numDeletedRows/scanTimeMs/rewriteTimeMs, `DeleteCommand.scala:56-63`).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import pyarrow.compute as pc

from delta_tpu.commands import operations as ops
from delta_tpu.commands.dml_common import (
    Timer,
    candidate_files,
    dv_enabled,
    dv_mark_from_mask,
    read_candidates,
)
from delta_tpu.exec import cdf
from delta_tpu.exec import write as write_exec
from delta_tpu.expr import ir
from delta_tpu.expr import partition as partition_expr
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.protocol.actions import Action

__all__ = ["DeleteCommand"]


class DeleteCommand:
    def __init__(self, delta_log, condition: Optional[Union[str, ir.Expression]] = None):
        self.delta_log = delta_log
        self.condition = (
            parse_predicate(condition) if isinstance(condition, str) else condition
        )
        self.metrics: Dict[str, int] = {}

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.delete", path=self.delta_log.data_path):
            return self.delta_log.with_new_transaction(self._body)

    def _body(self, txn) -> int:
        timer = Timer()
        self._rewrote_files = False
        actions = self._perform_delete(txn, timer)
        op = ops.Delete(
            predicate=[self.condition.sql()] if self.condition is not None else []
        )
        txn.report_metrics(**self.metrics)
        version = txn.commit(actions, op)
        # workload journal: DML entry (mode + rewrite metrics) for the
        # layout advisor (buffered; inert under blackout)
        from delta_tpu.obs import journal as journal_mod

        journal_mod.record_dml(
            self.delta_log.log_path, "delete",
            mode="rewrite" if self._rewrote_files else "dv-or-remove",
            version=version, metrics=dict(self.metrics),
        )
        if self._rewrote_files:
            # survivors rewritten into new files: bump the resident
            # key-cache epoch (ops/key_cache.py) — plain removes and DV
            # marks advance incrementally and need no invalidation
            from delta_tpu.ops.column_cache import ColumnCache
            from delta_tpu.ops.key_cache import KeyCache

            KeyCache.instance().bump_epoch(self.delta_log.log_path)
            ColumnCache.instance().bump_epoch(self.delta_log.log_path)
        return version

    def _perform_delete(self, txn, timer: Timer) -> List[Action]:
        metadata = txn.metadata
        if self.condition is not None:
            from delta_tpu.schema.char_varchar import pad_char_literals

            self.condition = pad_char_literals(self.condition, metadata)
        pcols = metadata.partition_columns

        if self.condition is None:
            # case 1: whole-table delete — no data read
            removes = [f.remove() for f in txn.filter_files()]
            txn.read_whole_table()
            self.metrics.update(
                numRemovedFiles=len(removes), numAddedFiles=0,
                numDeletedRows=-1, scanTimeMs=timer.lap_ms(), rewriteTimeMs=0,
            )
            return list(removes)

        conjuncts = ir.split_conjuncts(self.condition)
        if all(partition_expr.is_partition_predicate(c, pcols) for c in conjuncts):
            # case 2: metadata-only — prune and remove, never read data
            # (filter_files already evaluates the partition predicate exactly)
            to_remove = txn.filter_files([self.condition])
            self.metrics.update(
                numRemovedFiles=len(to_remove), numAddedFiles=0,
                numDeletedRows=-1, scanTimeMs=timer.lap_ms(), rewriteTimeMs=0,
            )
            return [f.remove() for f in to_remove]

        # case 3: scan + rewrite (or DV-mark when deletion vectors are on)
        use_dv = dv_enabled(metadata)
        use_cdf = cdf.cdf_enabled(metadata)
        candidates = candidate_files(txn, self.condition)
        touched = read_candidates(
            self.delta_log.data_path, candidates, metadata, self.condition,
            with_positions=use_dv,
            # DV mode only marks matched positions; the rewrite path needs
            # every non-matching row (it writes the survivors back)
            prune_row_groups=use_dv,
        )
        scan_ms = timer.lap_ms()

        removes: List[Action] = []
        adds: List[Action] = []
        cdf_blocks = []
        deleted_rows = 0
        for tf in touched:
            matches = pc.sum(tf.mask).as_py() or 0
            if not matches:
                continue  # file untouched
            deleted_rows += matches
            if use_cdf:
                cdf_blocks.append(("delete", tf.table.filter(tf.mask)))
            if use_dv:
                rm, re_add = dv_mark_from_mask(
                    self.delta_log.data_path, tf.add, tf.table, tf.mask
                )
                removes.append(rm)
                if re_add is not None:
                    adds.append(re_add)
                continue
            removes.append(tf.add.remove())
            if matches < tf.table.num_rows:
                survivors = tf.table.filter(pc.invert(tf.mask))
                adds.extend(
                    write_exec.write_files(
                        self.delta_log.data_path, survivors, metadata, data_change=True
                    )
                )
                self._rewrote_files = True
        cdc_actions: List[Action] = []
        if cdf_blocks:
            cdc_actions = list(
                cdf.write_change_data(
                    self.delta_log.data_path, cdf_blocks, metadata
                )
            )
        self.metrics.update(
            numRemovedFiles=len(removes),
            numAddedFiles=len(adds),
            numDeletedRows=deleted_rows,
            scanTimeMs=scan_ms,
            rewriteTimeMs=timer.lap_ms(),
        )
        return removes + adds + cdc_actions
