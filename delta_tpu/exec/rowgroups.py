"""Row-group data skipping: footer-stats pushdown + a bounded footer cache.

Second pruning tier inside the Parquet read path. File-level pruning
(`ops/pruning.files_for_scan`) decides WHICH files a query touches; this
module decides which *row groups inside each surviving file* must actually
decode, using the per-row-group min/max/null-count statistics every Parquet
footer already carries. The reference gets this for free from parquet-mr's
row-group/page filters (`ParquetFileFormat` pushdown); here the same
predicate IR (`expr/ir.py`) is rewritten once by
`ops.pruning.skipping_predicate` and evaluated row-group-at-a-time against a
stats environment — so both tiers share one conservativeness story:

* a row group is dropped only when the rewritten predicate is *definitely
  False*; NULL (missing/unsafe stats) keeps it (Kleene semantics);
* NaN float bounds (legacy writers) invalidate that column's bounds;
* binary bounds are never used (truncation is undetectable);
* columns missing from the file (schema evolution) resolve to NULL ⇒ keep;
* partition-column references (mixed OR branches) bind to the file's typed
  partition values, exactly like the file tier's ``stats_table``.

The footer cache (:class:`FooterCache`) is a bounded LRU keyed by
``abs_path`` and validated by ``(size, mtime_ns)`` so hot-table queries stop
re-parsing footers per open — a rewritten file (same path, new bytes) drops
its stale entry on the next lookup. Capacity:
``delta.tpu.read.footerCacheEntries`` (0 disables caching entirely).

:func:`stats_from_footer` derives protocol AddFile stats
(minValues/maxValues/nullCount/numRecords) from the same footer statistics —
CONVERT TO DELTA uses it to stop decoding whole data files just to compute
stats, falling back to a full decode when the footer is absent or unsafe.
"""
from __future__ import annotations

import math
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field as dataclass_field
from typing import Any, Dict, FrozenSet, List, Optional, Sequence

import numpy as np

from delta_tpu.expr import ir
from delta_tpu.utils.config import conf

__all__ = [
    "FooterCache",
    "read_footer",
    "footer_cache_info",
    "RowGroupPlan",
    "plan_row_groups",
    "row_group_offsets",
    "row_groups_for_positions",
    "stats_from_footer",
]


# ---------------------------------------------------------------------------
# Footer cache
# ---------------------------------------------------------------------------


class FooterCache:
    """Bounded LRU of parsed Parquet footers (``pq.FileMetaData``).

    Entries are keyed by absolute path and validated against the file's
    current ``(size, mtime_ns)`` on every lookup — an in-place rewrite
    invalidates the stale footer without any explicit purge. A parsed
    footer is immutable in Arrow, so one cached object serves concurrent
    readers; the cached metadata also feeds ``pq.ParquetFile(...,
    metadata=...)`` so a planned file opens without re-parsing its footer.
    """

    _instance: Optional["FooterCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        # abs_path -> ((size, mtime_ns), FileMetaData)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()

    @classmethod
    def instance(cls) -> "FooterCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = FooterCache()
            return cls._instance

    @staticmethod
    def capacity() -> int:
        return int(conf.get("delta.tpu.read.footerCacheEntries", 1024))

    def get(self, abs_path: str):
        """The file's parsed footer; cached when the cache is enabled."""
        import pyarrow.parquet as pq

        from delta_tpu.utils.telemetry import bump_counter

        cap = self.capacity()
        if cap <= 0:
            return pq.read_metadata(abs_path)
        st = os.stat(abs_path)
        key = (st.st_size, st.st_mtime_ns)
        with self._lock:
            hit = self._entries.get(abs_path)
            if hit is not None and hit[0] == key:
                self._entries.move_to_end(abs_path)
                bump_counter("footerCache.hits")
                return hit[1]
        meta = pq.read_metadata(abs_path)
        bump_counter("footerCache.misses")
        with self._lock:
            self._entries[abs_path] = (key, meta)
            self._entries.move_to_end(abs_path)
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
                bump_counter("footerCache.evictions")
        return meta

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def read_footer(abs_path: str):
    return FooterCache.instance().get(abs_path)


def footer_cache_info() -> dict:
    """Residency snapshot of the process footer cache — served by the obs
    endpoint's ``/healthz`` next to the hit/miss counters, so an operator
    can tell a cold cache from a disabled one."""
    cache = FooterCache.instance()
    return {"entries": len(cache), "capacity": cache.capacity()}


# ---------------------------------------------------------------------------
# Pushdown planner
# ---------------------------------------------------------------------------


class _StatsEnv(dict):
    """Row environment for the rewritten skipping predicate: lookups are
    case-insensitive and *missing stats resolve to NULL* instead of raising
    — NULL keeps the row group (the conservativeness invariant), which is
    exactly what absent/evolved columns must do."""

    def __contains__(self, key: object) -> bool:  # Column.eval probes first
        return True

    def __getitem__(self, key):
        if isinstance(key, str):
            return super().get(key.lower())
        return super().get(key)


def _column_index(meta) -> Dict[str, int]:
    """lowercased top-level leaf name -> column-chunk index. Nested leaves
    (``a.b``, list/map paths) are skipped — only flat columns carry stats
    lanes, matching the file tier."""
    out: Dict[str, int] = {}
    if meta.num_row_groups == 0:
        return out
    rg0 = meta.row_group(0)
    for j in range(rg0.num_columns):
        p = rg0.column(j).path_in_schema
        if "." in p:
            continue
        out[p.lower()] = j
    return out


def _float_leaves(meta, col_index: Dict[str, int]) -> FrozenSet[str]:
    out = set()
    for name, j in col_index.items():
        if meta.schema.column(j).physical_type in ("FLOAT", "DOUBLE"):
            out.add(name)
    return frozenset(out)


def _safe_bounds(mn: Any, mx: Any, is_float: bool):
    """Drop bound pairs the planner must not trust: binary (possibly
    truncated) and NaN floats (legacy writers put NaN in min/max, making
    the pair meaningless)."""
    if isinstance(mn, bytes) or isinstance(mx, bytes):
        return None, None
    if is_float and (
        (isinstance(mn, float) and math.isnan(mn))
        or (isinstance(mx, float) and math.isnan(mx))
    ):
        return None, None
    return mn, mx


def _rg_env(meta, i: int, col_index: Dict[str, int],
            float_leaves: FrozenSet[str],
            part_row: Optional[Dict[str, Any]]) -> _StatsEnv:
    rg = meta.row_group(i)
    env = _StatsEnv()
    env["numrecords"] = rg.num_rows
    for name, j in col_index.items():
        try:
            st = rg.column(j).statistics
        except Exception:
            st = None
        if st is None:
            continue
        try:
            if st.has_null_count:
                env[f"nullcount.{name}"] = st.null_count
            if st.has_min_max:
                mn, mx = _safe_bounds(st.min, st.max, name in float_leaves)
                if mn is not None:
                    env[f"min.{name}"] = mn
                if mx is not None:
                    env[f"max.{name}"] = mx
        except Exception:
            continue  # undecodable stats value: leave lanes NULL (keep)
    if part_row:
        for k, v in part_row.items():
            env[k.lower()] = v
    return env


@dataclass
class RowGroupPlan:
    """Surviving row groups of one file. ``skipped_bytes`` is the
    uncompressed size of the pruned groups (the decode work avoided);
    ``fired`` lists the synthesized rewrites that individually excluded at
    least one pruned group (family + conjunct/rewrite shape fingerprints)
    for ``ScanReport.rewritesFired`` attribution."""

    keep: List[int]
    total: int
    skipped_bytes: int = 0
    fired: List[Dict[str, str]] = dataclass_field(default_factory=list)


def plan_row_groups(
    meta,
    predicate: ir.Expression,
    part_row: Optional[Dict[str, Any]] = None,
    partition_cols: FrozenSet[str] = frozenset(),
    types: Optional[Dict[str, Any]] = None,
    rewrites: Optional[List] = None,
) -> RowGroupPlan:
    """Evaluate ``predicate`` against each row group's footer statistics;
    a group survives unless the rewritten can-match predicate is definitely
    False. Single-group files short-circuit: the file tier already ruled.
    ``types`` (lowercased column name → schema DataType) arms the predicate
    synthesis fallback for arithmetic/string/temporal shapes — the SAME
    shared rewrite the file tier evaluates, so both tiers keep one
    conservativeness story. ``rewrites`` short-circuits the rewrite: a
    scan-constant ``conjunct_rewrites(...)`` list computed ONCE by the
    caller (the per-file decode loop must not re-derive it per footer)."""
    from delta_tpu.expr import synthesis
    from delta_tpu.ops.pruning import conjunct_rewrites, skipping_predicate

    n = meta.num_row_groups
    all_groups = list(range(n))
    if n <= 1:
        return RowGroupPlan(all_groups, n)
    if rewrites is None and types is not None:
        rewrites = conjunct_rewrites([predicate], partition_cols, types)
    if rewrites is not None:
        rewritten = ir.and_all([r.rewritten for r in rewrites])
    else:
        rewritten = skipping_predicate(predicate, partition_cols)
    if isinstance(rewritten, ir.Literal) and rewritten.value is None:
        return RowGroupPlan(all_groups, n)  # nothing lowerable: keep all
    col_index = _column_index(meta)
    float_leaves = _float_leaves(meta, col_index)
    keep: List[int] = []
    skipped_bytes = 0
    pruned_envs: List[_StatsEnv] = []
    for i in all_groups:
        env = _rg_env(meta, i, col_index, float_leaves, part_row)
        try:
            verdict = rewritten.eval(env)
        except Exception:
            verdict = None  # uncomparable stats value vs literal: keep
        if verdict is False:
            skipped_bytes += meta.row_group(i).total_byte_size
            pruned_envs.append(env)
        else:
            keep.append(i)
    fired: List[Dict[str, str]] = []
    if pruned_envs and rewrites is not None:
        for r in rewrites:
            if not r.synthesized:
                continue
            if any(_safe_false(r.rewritten, env) for env in pruned_envs):
                fired.append({
                    "family": r.family or "other",
                    "conjunct": synthesis.shape(r.conjunct),
                    "rewrite": synthesis.shape(r.rewritten),
                })
    return RowGroupPlan(keep, n, skipped_bytes, fired)


def _safe_false(expr: ir.Expression, env: _StatsEnv) -> bool:
    try:
        return expr.eval(env) is False
    except Exception:
        return False


def row_group_offsets(meta) -> np.ndarray:
    """Physical row offset of each row group; length ``num_row_groups + 1``
    (the last entry is the file's row count). Positions emitted for pruned
    reads are offset by these so deletion-vector DML keeps writing TRUE
    file positions."""
    counts = np.asarray(
        [meta.row_group(i).num_rows for i in range(meta.num_row_groups)],
        dtype=np.int64,
    )
    off = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=off[1:])
    return off


def row_groups_for_positions(meta, positions) -> FrozenSet[int]:
    """Row groups containing any of the given PHYSICAL row positions — the
    position-targeted selection the CDF deletion-vector diff uses (it knows
    exactly which rows changed before reading a single data page)."""
    off = row_group_offsets(meta)
    pos = np.asarray(positions, dtype=np.int64)
    pos = pos[(pos >= 0) & (pos < off[-1])]
    if pos.size == 0:
        return frozenset()
    return frozenset(int(i) for i in np.unique(np.searchsorted(off, pos, side="right") - 1))


# ---------------------------------------------------------------------------
# Footer-derived AddFile stats (CONVERT TO DELTA)
# ---------------------------------------------------------------------------


def stats_from_footer(meta, num_indexed_cols: int = 32) -> Optional[Dict[str, Any]]:
    """Protocol stats (numRecords/minValues/maxValues/nullCount) derived
    from footer row-group statistics, or ``None`` when the footer cannot
    stand in for a full decode:

    * any indexed column chunk without a statistics block (stats-disabled
      writer, or bounds omitted for oversized binary values) while the
      chunk holds non-null values;
    * NaN float bounds (legacy writers — bounds untrustworthy).

    Bounds the decode path would not emit either (binary, decimal,
    non-finite floats) are simply omitted — that matches
    ``exec.parquet.collect_stats`` encoding rules, so footer-derived and
    decode-derived stats agree wherever both exist."""
    import pyarrow as pa

    from delta_tpu.exec.parquet import json_stat_value

    try:
        arrow_schema = meta.schema.to_arrow_schema()
    except Exception:
        return None
    col_index = _column_index(meta)
    n_rgs = meta.num_row_groups
    names = arrow_schema.names[: num_indexed_cols if num_indexed_cols >= 0 else None]
    mins: Dict[str, Any] = {}
    maxs: Dict[str, Any] = {}
    nulls: Dict[str, Any] = {}
    for name in names:
        j = col_index.get(name.lower())
        if j is None:
            return None  # nested/unmapped: the footer can't cover this column
        t = arrow_schema.field(name).type
        is_float = pa.types.is_floating(t)
        total_null = 0
        col_mins: List[Any] = []
        col_maxs: List[Any] = []
        bounds_incomplete = False
        for i in range(n_rgs):
            rg = meta.row_group(i)
            try:
                st = rg.column(j).statistics
            except Exception:
                st = None
            if st is None or not st.has_null_count:
                return None  # can't even derive nullCount: decode fallback
            total_null += st.null_count
            if st.has_min_max:
                try:
                    mn, mx = st.min, st.max
                except Exception:
                    return None
                if is_float and (
                    (isinstance(mn, float) and math.isnan(mn))
                    or (isinstance(mx, float) and math.isnan(mx))
                ):
                    return None  # NaN-polluted bounds: decode fallback
                col_mins.append(mn)
                col_maxs.append(mx)
            elif st.null_count != rg.num_rows:
                # values exist but the writer withheld bounds (e.g. long
                # binary): only a decode can produce them
                bounds_incomplete = True
        nulls[name] = total_null
        skippable = (
            pa.types.is_integer(t)
            or pa.types.is_floating(t)
            or pa.types.is_string(t)
            or pa.types.is_date(t)
            or pa.types.is_timestamp(t)
            or pa.types.is_boolean(t)
            or pa.types.is_decimal(t)
        )
        if not skippable or total_null == meta.num_rows:
            continue  # same columns collect_stats skips
        if bounds_incomplete or not col_mins:
            return None
        try:
            mn_v = min(col_mins)
            mx_v = max(col_maxs)
        except TypeError:
            return None
        mn_j = json_stat_value(mn_v)
        mx_j = json_stat_value(mx_v, round_up=True)
        if mn_j is not None:
            mins[name] = mn_j
        if mx_j is not None:
            maxs[name] = mx_j
    return {
        "numRecords": meta.num_rows,
        "minValues": mins,
        "maxValues": maxs,
        "nullCount": nulls,
    }
