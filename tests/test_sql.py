"""SQL front end: token-based statement parsing (sql/lexer.py + sql/parser.py).

Covers the reference grammar scope (`DeltaSqlBase.g4:74-81`) plus
CREATE/ALTER/MERGE, and the lexer-level cases the old regex matcher
mis-parsed: keywords inside string literals, comments, newlines."""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.sql.lexer import tokenize
from delta_tpu.sql.parser import execute_sql
from delta_tpu.utils.errors import DeltaError
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaParseError,
)


def _table(tmp_path, name="t", data=None):
    path = str(tmp_path / name)
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table(
        data or {"id": [1, 2, 3], "v": [10, 20, 30]})).run()
    return path, log


def _rows(log):
    from delta_tpu.exec.scan import scan_to_table

    return scan_to_table(log.update()).sort_by("id").to_pylist()


# -- lexer ------------------------------------------------------------------


def test_lexer_keywords_inside_strings():
    toks = tokenize("DELETE FROM t WHERE name = 'WHERE AND DELETE'")
    strings = [t for t in toks if t.kind == "STRING"]
    assert len(strings) == 1 and strings[0].value == "WHERE AND DELETE"


def test_lexer_comments_stripped():
    toks = tokenize("VACUUM -- line comment WHERE\n t /* block DELETE */ DRY RUN")
    words = [t.value for t in toks if t.kind == "WORD"]
    assert words == ["VACUUM", "t", "DRY", "RUN"]


def test_lexer_doubled_quote_escape():
    toks = tokenize("SELECT 'it''s'")
    assert [t.value for t in toks if t.kind == "STRING"] == ["it's"]


def test_lexer_unterminated_string_errors():
    with pytest.raises(DeltaParseError, match="Unterminated"):
        tokenize("DELETE FROM t WHERE x = 'oops")


def test_lexer_backquoted_identifier():
    toks = tokenize("VACUUM delta.`/tmp/my table`")
    assert [t.value for t in toks if t.kind == "QUOTED_IDENT"] == ["/tmp/my table"]


# -- utility statements ------------------------------------------------------


def test_vacuum_retain_dry_run(tmp_path):
    path, log = _table(tmp_path)
    out = execute_sql(f"VACUUM delta.`{path}` RETAIN 200 HOURS DRY RUN")
    assert out.dry_run and out.files_deleted == 0


def test_describe_history_limit(tmp_path):
    path, log = _table(tmp_path)
    WriteIntoDelta(log, "append", pa.table({"id": [4], "v": [40]})).run()
    hist = execute_sql(f"DESCRIBE HISTORY delta.`{path}` LIMIT 1")
    assert len(hist) == 1 and hist[0]["version"] == 1


def test_describe_detail(tmp_path):
    path, _ = _table(tmp_path)
    detail = execute_sql(f"DESCRIBE DETAIL delta.`{path}`")
    assert detail["numFiles"] == 1


def test_statement_trailing_semicolon_and_newlines(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(f"DELETE\nFROM\n  delta.`{path}`\nWHERE id = 1\n;")
    assert [r["id"] for r in _rows(log)] == [2, 3]


def test_keywords_in_string_literals_do_not_misparse(tmp_path):
    path = str(tmp_path / "s")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({
        "id": [1, 2], "name": ["x WHERE y", "z"]})).run()
    execute_sql(f"DELETE FROM delta.`{path}` WHERE name = 'x WHERE y'")
    assert [r["id"] for r in _rows(log)] == [2]


def test_update_with_comment_inside(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(
        f"UPDATE delta.`{path}` SET v = v + 1 -- bump\nWHERE id = 2"
    )
    assert _rows(log)[1] == {"id": 2, "v": 21}


def test_update_multiple_assignments(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(f"UPDATE delta.`{path}` SET v = v * 2, id = id + 10 WHERE id > 1")
    assert [r["id"] for r in _rows(log)] == [1, 12, 13]


def test_unsupported_statement_errors():
    with pytest.raises(DeltaAnalysisError, match="Unsupported SQL"):
        execute_sql("FROBNICATE TABLE x")


def test_trailing_garbage_errors(tmp_path):
    path, _ = _table(tmp_path)
    with pytest.raises(DeltaParseError, match="trailing"):
        execute_sql(f"VACUUM delta.`{path}` EXTRA STUFF")


# -- CREATE ------------------------------------------------------------------


def test_create_table_with_everything(tmp_path):
    path = str(tmp_path / "c1")
    execute_sql(
        f"CREATE TABLE delta.`{path}` ("
        "  id BIGINT NOT NULL COMMENT 'the key',"
        "  part STRING,"
        "  price DOUBLE,"
        "  d DECIMAL(12, 2)"
        ") USING DELTA "
        "PARTITIONED BY (part) "
        "TBLPROPERTIES ('delta.appendOnly' = 'true') "
        "COMMENT 'fact table'"
    )
    t = DeltaTable.for_path(path)
    meta = t.delta_log.update().metadata
    assert [f.name for f in meta.schema.fields] == ["id", "part", "price", "d"]
    assert meta.schema["id"].nullable is False
    assert meta.schema["id"].metadata["comment"] == "the key"
    assert meta.partition_columns == ["part"]
    assert meta.configuration["delta.appendOnly"] == "true"
    assert meta.description == "fact table"


def test_create_table_generated_column(tmp_path):
    path = str(tmp_path / "c2")
    execute_sql(
        f"CREATE TABLE delta.`{path}` ("
        "  id BIGINT, twice BIGINT GENERATED ALWAYS AS (id + id)"
        ") USING DELTA"
    )
    t = DeltaTable.for_path(path)
    t.write({"id": [3]})
    assert t.to_arrow().to_pylist() == [{"id": 3, "twice": 6}]


def test_create_if_not_exists_and_or_replace(tmp_path):
    path = str(tmp_path / "c3")
    execute_sql(f"CREATE TABLE delta.`{path}` (id INT) USING DELTA")
    with pytest.raises(DeltaAnalysisError, match="already exists"):
        execute_sql(f"CREATE TABLE delta.`{path}` (id INT) USING DELTA")
    execute_sql(f"CREATE TABLE IF NOT EXISTS delta.`{path}` (id INT) USING DELTA")
    execute_sql(f"CREATE OR REPLACE TABLE delta.`{path}` (id INT, v INT) USING DELTA")
    t = DeltaTable.for_path(path)
    assert [f.name for f in t.schema().fields] == ["id", "v"]


def test_create_named_table_with_location(tmp_path, monkeypatch):
    from delta_tpu.catalog import catalog as cat_mod

    monkeypatch.setattr(cat_mod, "_default", None, raising=False)
    cat_mod.reset_default_catalog()
    loc = str(tmp_path / "managed")
    execute_sql(f"CREATE TABLE sales (id INT) USING DELTA LOCATION '{loc}'")
    execute_sql("DESCRIBE DETAIL sales")  # resolves through the catalog
    cat_mod.reset_default_catalog()


# -- ALTER -------------------------------------------------------------------


def test_alter_set_unset_properties(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(f"ALTER TABLE delta.`{path}` SET TBLPROPERTIES ('delta.appendOnly' = 'true')")
    assert log.update().metadata.configuration["delta.appendOnly"] == "true"
    execute_sql(f"ALTER TABLE delta.`{path}` UNSET TBLPROPERTIES ('delta.appendOnly')")
    assert "delta.appendOnly" not in log.update().metadata.configuration


def test_alter_add_columns_with_positions(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(
        f"ALTER TABLE delta.`{path}` ADD COLUMNS (w STRING AFTER id, z INT FIRST)"
    )
    assert [f.name for f in log.update().metadata.schema.fields] == [
        "z", "id", "w", "v"
    ]


def test_alter_change_column(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(f"ALTER TABLE delta.`{path}` ALTER COLUMN v TYPE BIGINT COMMENT 'wide'")
    f = log.update().metadata.schema["v"]
    from delta_tpu.schema.types import LongType

    assert f.data_type == LongType()
    assert f.metadata["comment"] == "wide"
    execute_sql(f"ALTER TABLE delta.`{path}` CHANGE COLUMN v FIRST")
    assert [f.name for f in log.update().metadata.schema.fields] == ["v", "id"]


def test_alter_constraints_sql(tmp_path):
    path, log = _table(tmp_path)
    execute_sql(f"ALTER TABLE delta.`{path}` ADD CONSTRAINT pos CHECK (v > 0)")
    with pytest.raises(Exception):
        WriteIntoDelta(log, "append", pa.table({"id": [9], "v": [-1]})).run()
    execute_sql(f"ALTER TABLE delta.`{path}` DROP CONSTRAINT pos")
    WriteIntoDelta(log, "append", pa.table({"id": [9], "v": [-1]})).run()


# -- MERGE -------------------------------------------------------------------


def test_merge_sql_star_clauses(tmp_path):
    tpath, tlog = _table(tmp_path, "target")
    spath, _ = _table(tmp_path, "source", {"id": [2, 4], "v": [99, 40]})
    m = execute_sql(
        f"MERGE INTO delta.`{tpath}` t USING delta.`{spath}` s "
        "ON t.id = s.id "
        "WHEN MATCHED THEN UPDATE SET * "
        "WHEN NOT MATCHED THEN INSERT *"
    )
    assert m["numTargetRowsUpdated"] == 1
    assert m["numTargetRowsInserted"] == 1
    assert _rows(tlog) == [
        {"id": 1, "v": 10}, {"id": 2, "v": 99}, {"id": 3, "v": 30},
        {"id": 4, "v": 40},
    ]


def test_merge_sql_explicit_clauses_and_conditions(tmp_path):
    tpath, tlog = _table(tmp_path, "t2")
    spath, _ = _table(tmp_path, "s2", {"id": [1, 2, 9], "v": [-5, 99, 90]})
    m = execute_sql(
        f"MERGE INTO delta.`{tpath}` AS t USING delta.`{spath}` AS s "
        "ON t.id = s.id "
        "WHEN MATCHED AND s.v < 0 THEN DELETE "
        "WHEN MATCHED THEN UPDATE SET v = s.v + 1 "
        "WHEN NOT MATCHED AND s.v > 50 THEN INSERT (id, v) VALUES (s.id, s.v)"
    )
    assert m["numTargetRowsDeleted"] == 1
    assert m["numTargetRowsUpdated"] == 1
    assert m["numTargetRowsInserted"] == 1
    assert _rows(tlog) == [{"id": 2, "v": 100}, {"id": 3, "v": 30},
                           {"id": 9, "v": 90}]


def test_merge_sql_case_when_in_set_and_condition(tmp_path):
    tpath, tlog = _table(tmp_path, "tc")
    spath, _ = _table(tmp_path, "sc", {"id": [1, 2], "v": [-5, 99]})
    execute_sql(
        f"MERGE INTO delta.`{tpath}` t USING delta.`{spath}` s "
        "ON t.id = s.id "
        "WHEN MATCHED THEN UPDATE SET v = CASE WHEN s.v > 0 THEN s.v ELSE 0 END"
    )
    assert _rows(tlog) == [{"id": 1, "v": 0}, {"id": 2, "v": 99},
                           {"id": 3, "v": 30}]


def test_describe_history_bad_limit_is_parse_error(tmp_path):
    path, _ = _table(tmp_path)
    with pytest.raises(DeltaParseError, match="Invalid integer"):
        execute_sql(f"DESCRIBE HISTORY delta.`{path}` LIMIT 1e2")


def test_delta_dot_name_resolves_via_catalog(tmp_path):
    from delta_tpu.catalog import catalog as cat_mod

    cat_mod.reset_default_catalog()
    try:
        loc = str(tmp_path / "byname")
        execute_sql(f"CREATE TABLE facts (id INT) USING DELTA LOCATION '{loc}'")
        detail = execute_sql("DESCRIBE DETAIL delta.facts")
        assert detail["location"].endswith("byname")
    finally:
        cat_mod.reset_default_catalog()


def test_alter_change_column_inside_array_element(tmp_path):
    from delta_tpu.commands import alter
    from delta_tpu.schema.types import (
        ArrayType, IntegerType, LongType, StructType as ST,
    )

    elem = ST().add("x", IntegerType())
    t = DeltaTable.create(
        str(tmp_path / "arr"), ST().add("id", IntegerType()).add("a", ArrayType(elem))
    )
    alter.change_column(t.delta_log, "a.element.x", new_type=LongType())
    a_t = t.schema()["a"].data_type
    assert a_t.element_type["x"].data_type == LongType()


def test_convert_to_delta_sql(tmp_path):
    import pyarrow.parquet as pq

    d = tmp_path / "plain"
    d.mkdir()
    pq.write_table(pa.table({"id": [1, 2]}), str(d / "part-0.parquet"))
    execute_sql(f"CONVERT TO DELTA parquet.`{d}`")
    t = DeltaTable.for_path(str(d))
    assert t.to_arrow().num_rows == 2


# -- SELECT (round-4: the SQL read surface) ---------------------------------


def _select_table(tmp_path):
    import numpy as np

    path = str(tmp_path / "sel")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(10, dtype=np.int64),
        "v": np.arange(10, dtype=np.float64) * 1.5,
        "name": pa.array([f"u{i}" for i in range(10)]),
    })).run()
    return path, log


def test_select_star_where(tmp_path):
    path, _ = _select_table(tmp_path)
    t = execute_sql(f"SELECT * FROM delta.`{path}` WHERE id >= 7")
    assert t.num_rows == 3
    assert set(t.column_names) == {"id", "v", "name"}


def test_select_columns_exprs_aliases(tmp_path):
    path, _ = _select_table(tmp_path)
    t = execute_sql(
        f"SELECT id, v * 2 AS dbl, upper(name) AS nm FROM delta.`{path}` "
        "WHERE id < 3 ORDER BY id DESC"
    )
    assert t.column_names == ["id", "dbl", "nm"]
    assert t.column("id").to_pylist() == [2, 1, 0]
    assert t.column("dbl").to_pylist() == [6.0, 3.0, 0.0]
    assert t.column("nm").to_pylist() == ["U2", "U1", "U0"]


def test_select_limit_and_order(tmp_path):
    path, _ = _select_table(tmp_path)
    t = execute_sql(f"SELECT id FROM delta.`{path}` ORDER BY id DESC LIMIT 4")
    assert t.column("id").to_pylist() == [9, 8, 7, 6]


def test_select_version_as_of(tmp_path):
    import numpy as np

    path, log = _select_table(tmp_path)
    v0 = log.update().version
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, 105, dtype=np.int64),
        "v": np.zeros(5), "name": pa.array(["x"] * 5),
    })).run()
    t_now = execute_sql(f"SELECT * FROM delta.`{path}`")
    t_old = execute_sql(f"SELECT * FROM delta.`{path}` VERSION AS OF {v0}")
    assert t_now.num_rows == 15 and t_old.num_rows == 10


def test_select_write_read_roundtrip_sql_only(tmp_path):
    """The capability the VERDICT asked for: execute_sql users can read what
    they write, including time travel."""
    path = str(tmp_path / "rt")
    execute_sql(f"CREATE TABLE delta.`{path}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES (1, 1.5), (2, 2.5)")
    execute_sql(f"UPDATE delta.`{path}` SET v = v + 1 WHERE id = 2")
    t = execute_sql(f"SELECT id, v FROM delta.`{path}` ORDER BY id")
    assert t.column("v").to_pylist() == [1.5, 3.5]
    t1 = execute_sql(f"SELECT v FROM delta.`{path}` VERSION AS OF 1 ORDER BY v")
    assert t1.column("v").to_pylist() == [1.5, 2.5]


def test_insert_select_and_overwrite(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    execute_sql(f"CREATE TABLE delta.`{src}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{src}` VALUES (1, 1.0), (2, 2.0), (3, 3.0)")
    execute_sql(f"CREATE TABLE delta.`{dst}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{dst}` SELECT id, v FROM delta.`{src}` WHERE id >= 2")
    t = execute_sql(f"SELECT id FROM delta.`{dst}` ORDER BY id")
    assert t.column("id").to_pylist() == [2, 3]
    execute_sql(f"INSERT OVERWRITE delta.`{dst}` VALUES (9, 9.0)")
    t = execute_sql(f"SELECT * FROM delta.`{dst}`")
    assert t.column("id").to_pylist() == [9]


def test_insert_arity_mismatch_rejected(tmp_path):
    path = str(tmp_path / "t")
    execute_sql(f"CREATE TABLE delta.`{path}` (id BIGINT, v DOUBLE)")
    with pytest.raises(DeltaError, match="differ"):
        execute_sql(f"INSERT INTO delta.`{path}` (id) VALUES (1, 2.0)")


def test_select_unknown_statement_mentions_select():
    with pytest.raises(DeltaError, match="SELECT"):
        execute_sql("FROBNICATE x")


def test_select_order_by_unprojected_and_duplicate(tmp_path):
    path = str(tmp_path / "o")
    execute_sql(f"CREATE TABLE delta.`{path}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES (2, 20.0), (1, 10.0)")
    # sorting by a non-projected source column (standard SQL)
    t = execute_sql(f"SELECT v FROM delta.`{path}` ORDER BY id")
    assert t.column("v").to_pylist() == [10.0, 20.0]
    # duplicate output names survive
    t = execute_sql(f"SELECT id, id FROM delta.`{path}`")
    assert t.num_columns == 2
    # sorting by an alias
    t = execute_sql(f"SELECT v AS x FROM delta.`{path}` ORDER BY x DESC")
    assert t.column("x").to_pylist() == [20.0, 10.0]
    # unknown order column is a DeltaError, not a raw Arrow crash
    with pytest.raises(DeltaError, match="not found"):
        execute_sql(f"SELECT v AS x FROM delta.`{path}` ORDER BY zzz")


def test_insert_select_arity_enforced(tmp_path):
    src, dst = str(tmp_path / "s"), str(tmp_path / "d")
    execute_sql(f"CREATE TABLE delta.`{src}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{src}` VALUES (1, 1.0)")
    execute_sql(f"CREATE TABLE delta.`{dst}` (id BIGINT, v DOUBLE)")
    with pytest.raises(DeltaError, match="differ"):
        execute_sql(f"INSERT INTO delta.`{dst}` SELECT id FROM delta.`{src}`")
    with pytest.raises(DeltaError, match="differ"):
        execute_sql(f"INSERT INTO delta.`{dst}` (id) SELECT id, v FROM delta.`{src}`")


def test_select_aggregates_global(tmp_path):
    path = str(tmp_path / "agg")
    execute_sql(f"CREATE TABLE delta.`{path}` (g STRING, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES "
                "('a', 1.0), ('a', 3.0), ('b', 10.0), ('b', 20.0), ('b', 30.0)")
    t = execute_sql(f"SELECT count(*) AS n, sum(v) AS s, avg(v) AS m, "
                    f"min(v) AS lo, max(v) AS hi FROM delta.`{path}`")
    assert t.num_rows == 1
    assert t.column("n").to_pylist() == [5]
    assert t.column("s").to_pylist() == [64.0]
    assert t.column("m").to_pylist() == [12.8]
    assert t.column("lo").to_pylist() == [1.0]
    assert t.column("hi").to_pylist() == [30.0]


def test_select_group_by(tmp_path):
    path = str(tmp_path / "agg2")
    execute_sql(f"CREATE TABLE delta.`{path}` (g STRING, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES "
                "('a', 1.0), ('a', 3.0), ('b', 10.0), ('b', 20.0), ('b', 30.0)")
    t = execute_sql(
        f"SELECT g, count(*) AS n, sum(v * 2) AS s2 FROM delta.`{path}` "
        "GROUP BY g ORDER BY g"
    )
    assert t.column("g").to_pylist() == ["a", "b"]
    assert t.column("n").to_pylist() == [2, 3]
    assert t.column("s2").to_pylist() == [8.0, 120.0]
    # WHERE composes with GROUP BY
    t = execute_sql(
        f"SELECT g, max(v) AS hi FROM delta.`{path}` WHERE v > 1.0 "
        "GROUP BY g ORDER BY hi DESC"
    )
    assert t.column("g").to_pylist() == ["b", "a"]
    assert t.column("hi").to_pylist() == [30.0, 3.0]


def test_select_aggregates_empty_table_keeps_types(tmp_path):
    """Ungrouped aggregates over zero rows must yield null values of the
    aggregate's NATURAL type (r4 advisor: null-typed columns broke
    INSERT...SELECT casts downstream)."""
    path = str(tmp_path / "agg_empty")
    execute_sql(f"CREATE TABLE delta.`{path}` (g STRING, v DOUBLE)")
    t = execute_sql(f"SELECT count(*) AS n, sum(v) AS s, avg(v) AS m, "
                    f"min(v) AS lo, max(v) AS hi FROM delta.`{path}`")
    assert t.num_rows == 1
    assert t.column("n").to_pylist() == [0]
    for name in ("s", "m", "lo", "hi"):
        col = t.column(name)
        assert col.to_pylist() == [None]
        assert not pa.types.is_null(col.type), name
    assert pa.types.is_floating(t.column("s").type)
    # and the typed nulls survive an INSERT...SELECT round trip
    dst = str(tmp_path / "agg_empty_dst")
    execute_sql(f"CREATE TABLE delta.`{dst}` (lo DOUBLE, hi DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{dst}` "
                f"SELECT min(v) AS lo, max(v) AS hi FROM delta.`{path}`")
    out = execute_sql(f"SELECT lo, hi FROM delta.`{dst}`")
    assert out.num_rows == 1


def test_select_aggregate_errors(tmp_path):
    path = str(tmp_path / "agg3")
    execute_sql(f"CREATE TABLE delta.`{path}` (g STRING, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES ('a', 1.0)")
    with pytest.raises(DeltaError, match="GROUP BY"):
        execute_sql(f"SELECT g, sum(v) FROM delta.`{path}`")
    with pytest.raises(DeltaError, match=r"\(\*\)"):
        execute_sql(f"SELECT sum(*) FROM delta.`{path}`")


def test_group_by_order_by_unprojected_key(tmp_path):
    path = str(tmp_path / "agg4")
    execute_sql(f"CREATE TABLE delta.`{path}` (g STRING, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES "
                "('b', 1.0), ('a', 2.0), ('a', 4.0)")
    t = execute_sql(f"SELECT count(v) AS n FROM delta.`{path}` "
                    "GROUP BY g ORDER BY g")
    assert t.column_names == ["n"]
    assert t.column("n").to_pylist() == [2, 1]  # a first, then b


def test_sql_shallow_clone(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    execute_sql(f"CREATE TABLE delta.`{src}` (id BIGINT, v DOUBLE)")
    execute_sql(f"INSERT INTO delta.`{src}` VALUES (1, 1.0), (2, 2.0)")
    execute_sql(f"INSERT INTO delta.`{src}` VALUES (3, 3.0)")
    execute_sql(f"CREATE TABLE delta.`{dst}` SHALLOW CLONE delta.`{src}` VERSION AS OF 1")
    t = execute_sql(f"SELECT id FROM delta.`{dst}` ORDER BY id")
    assert t.column("id").to_pylist() == [1, 2]
    dst2 = str(tmp_path / "dst2")
    execute_sql(f"CREATE TABLE delta.`{dst2}` SHALLOW CLONE delta.`{src}`")
    assert execute_sql(f"SELECT * FROM delta.`{dst2}`").num_rows == 3
