"""Lock-discipline pass: the concurrency contracts PRs 6-9 grew by hand.

Three rules over the :class:`~delta_tpu.analysis.modgraph.ModuleGraph`
facts:

``lock-guard``
    State shared between a daemon-thread entry point (``Thread(target=…)``,
    ``pool.submit/map`` callables) and foreground paths must be mutated
    under a lock everywhere. A mutation site's *effective* locks are those
    lexically held plus the caller-context fixpoint (a private helper whose
    every module-local call site holds ``_IO_LOCK`` inherits it), so
    "callers hold the lock" conventions are seen without annotations.
``lock-blocking``
    No blocking call while a lock is held: LogStore IO (``store.read`` /
    ``write_bytes`` / ``list_from`` …), ``time.sleep``, ``Thread.join``,
    ``Future.result``, ``queue.get/put`` and raw ``open()``. The group
    commit leader's deliberate read-the-tail-once-under-the-commit-lock
    design carries inline waivers — the point is that each such hold is a
    *reviewed* decision.
``lock-order``
    Lock-acquisition-order cycles across the canonical lock graph
    (``_IO_LOCK``/``_LOCK`` module locks, ``DeltaLog.lock`` /
    ``_update_lock`` class locks, coordinator condition vars). An edge
    A→B means B was entered while A was held; any strongly connected
    component of ≥2 locks is a potential deadlock.

Scope limits (by design, see modgraph): call resolution is module-local,
``.acquire()`` pairs are not tracked, and a function called both with and
without a lock held resolves to "no lock assumed".
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.analysis.core import (AnalysisContext, AnalysisPass, Finding)
from delta_tpu.analysis.modgraph import (ModuleGraph, module_graph,
                                         terminal_name)

__all__ = ["LockDisciplinePass"]

STORE_OPS = frozenset({"read", "read_iter", "read_bytes", "write",
                       "write_bytes", "list_from", "exists", "delete",
                       "mkdirs"})

_THREADISH_RE = re.compile(r"(?:^th$|^t\d*$|thread|worker|writer|proc)",
                           re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"(?:^q$|queue)", re.IGNORECASE)


def _receiver_chain(expr: ast.expr) -> List[str]:
    out: List[str] = []
    while isinstance(expr, ast.Attribute):
        out.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        out.append(expr.id)
    return out


def blocking_desc(call: ast.Call) -> Optional[str]:
    """A short description when ``call`` is a known blocking primitive."""
    f = call.func
    if isinstance(f, ast.Name):
        return "open()" if f.id == "open" else None
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    recv = terminal_name(f.value)
    if attr == "sleep" and recv is not None and recv.lstrip("_") == "time":
        return "time.sleep"
    if attr == "join" and recv is not None and _THREADISH_RE.search(recv):
        return "Thread.join"
    if attr == "result":
        return "Future.result"
    if attr in STORE_OPS:
        chain = _receiver_chain(f.value)
        if any("store" in part.lower() for part in chain):
            return f"store.{attr}"
    if attr in ("get", "put") and recv is not None \
            and _QUEUEISH_RE.search(recv):
        return f"queue.{attr}"
    return None


class LockDisciplinePass(AnalysisPass):
    name = "lock-discipline"
    description = ("cross-thread mutation guards, blocking calls under "
                   "locks, lock-order cycles")
    rules = ("lock-guard", "lock-blocking", "lock-order")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        #: global lock-order edges: (from, to) -> witness (path, line)
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for sf in ctx.files:
            g = module_graph(ctx, sf)
            out.extend(self._guard_findings(g))
            out.extend(self._blocking_findings(g))
            self._collect_edges(g, edges)
        out.extend(self._order_findings(edges))
        return out

    # -- lock-guard -------------------------------------------------------

    def _guard_findings(self, g: ModuleGraph) -> List[Finding]:
        entries = g.thread_entries()
        if not entries:
            return []
        background = g.reachable_from(list(entries))
        #: key -> list of (qualname, MutateEvent, effective_locks)
        sites: Dict[str, List[Tuple[str, object, frozenset]]] = {}
        for qn, facts in g.facts.items():
            simple = qn.rsplit(".", 1)[-1]
            if simple in ("__init__", "__new__"):
                continue  # construction precedes sharing
            eff = g.effective.get(qn, frozenset())
            for ev in facts.mutations:
                sites.setdefault(ev.key, []).append(
                    (qn, ev, frozenset(ev.held) | eff))
        out: List[Finding] = []
        entry_desc = ", ".join(sorted(
            q.rsplit(".", 1)[-1] for q in entries))
        for key, slist in sorted(sites.items()):
            bg = [s for s in slist if s[0] in background]
            fg = [s for s in slist if s[0] not in background]
            if not bg or not fg:
                continue  # not cross-thread within this module
            common = None
            for _qn, _ev, eff in slist:
                common = eff if common is None else (common & eff)
            if common:
                continue  # one lock guards every site
            short = key.split("::", 1)[-1]
            unguarded = [s for s in slist if not s[2]]
            if unguarded:
                # the problem sites are the ones holding nothing
                for qn, ev, _eff in unguarded:
                    out.append(Finding(
                        "lock-guard", g.sf.rel, ev.node.lineno,
                        f"'{short}' is mutated without a lock in {qn} but "
                        f"is shared with daemon thread(s) ({entry_desc})"))
            else:
                # every site holds SOME lock, but no lock is common to all:
                # the two threads still race (the ISSUE's 'without a common
                # lock' case)
                for qn, ev, eff in slist:
                    locks = ", ".join(sorted(eff))
                    out.append(Finding(
                        "lock-guard", g.sf.rel, ev.node.lineno,
                        f"'{short}' is mutated under {locks} in {qn} but "
                        f"other sites use a different lock — no common "
                        f"lock across threads ({entry_desc})"))
        return out

    # -- lock-blocking ----------------------------------------------------

    def _blocking_findings(self, g: ModuleGraph) -> List[Finding]:
        out: List[Finding] = []
        for qn, facts in g.facts.items():
            eff = g.effective.get(qn, frozenset())
            for ev in facts.calls:
                desc = blocking_desc(ev.node)
                if desc is None:
                    continue
                held = frozenset(ev.held) | eff
                if not held:
                    continue
                locks = ", ".join(sorted(held))
                out.append(Finding(
                    "lock-blocking", g.sf.rel, ev.node.lineno,
                    f"blocking call {desc} in {qn} while holding {locks}"))
        return out

    # -- lock-order -------------------------------------------------------

    def _collect_edges(self, g: ModuleGraph,
                       edges: Dict[Tuple[str, str], Tuple[str, int]]) -> None:
        for qn, facts in g.facts.items():
            eff = g.effective.get(qn, frozenset())
            for ev in facts.enters:
                held = frozenset(ev.held_before) | eff
                for outer in held:
                    if outer != ev.lock:
                        edges.setdefault(
                            (outer, ev.lock),
                            (g.sf.rel, getattr(ev.node, "lineno", 1)))

    def _order_findings(self, edges: Dict[Tuple[str, str], Tuple[str, int]]
                        ) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out: List[Finding] = []
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            # anchor the finding at the first witness edge inside the cycle
            witness = min(
                (edges[e] for e in edges
                 if e[0] in scc and e[1] in scc),
                key=lambda w: (w[0], w[1]))
            out.append(Finding(
                "lock-order", witness[0], witness[1],
                "lock-acquisition-order cycle between "
                + " <-> ".join(cyc)))
        return out


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan's strongly connected components, iterative."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in idx:
            continue
        work: List[Tuple[str, iter]] = [(root, iter(sorted(graph[root])))]
        idx[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in idx:
                    idx[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], idx[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
    return out
