"""Extended MERGE scenario families — the remaining behavior catalogue of
the reference's `MergeIntoSuiteBase.scala` (testExtendedMerge /
testNullCase / testAnalysisErrorsInExtendedMerge / insert-only /
testEvolution groups), re-expressed against the engine-native API. Each
test states the scenario it mirrors; any intentional divergence is noted
in PARITY.md §divergences."""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaError,
    DeltaUnsupportedOperationError,
)


@pytest.fixture(params=["device", "host"])
def executor(request):
    mode = "force" if request.param == "device" else "off"
    with conf.set_temporarily(**{"delta.tpu.merge.devicePath.mode": mode}):
        yield request.param


def _write(path, data):
    log = DeltaLog.for_table(str(path))
    WriteIntoDelta(log, "append",
                   pa.table(data) if isinstance(data, dict) else data).run()
    return log


def _rows(log, sort="k"):
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(log.update())
    if sort and sort in t.column_names:
        t = t.sort_by(sort)
    return t.to_pylist()


def _merge(log, source, cond, matched=(), not_matched=(), **kw):
    kw.setdefault("source_alias", "s")
    kw.setdefault("target_alias", "t")
    cmd = MergeIntoCommand(
        log, pa.table(source) if isinstance(source, dict) else source, cond,
        list(matched), list(not_matched), **kw
    )
    cmd.run()
    return cmd


def up(cond=None, **assigns):
    return MergeClause("update", assignments=assigns or None, condition=cond)


def delete(cond=None):
    return MergeClause("delete", condition=cond)


def ins(cond=None, **assigns):
    return MergeClause("insert", assignments=assigns or None, condition=cond)


K64 = pa.int64()


def _kv(ks, vs):
    return {"k": pa.array(ks, K64), "v": pa.array(vs, pa.float64())}


# ---------------------------------------------------------------------------
# testExtendedMerge: clause-combination matrix
# ---------------------------------------------------------------------------


def test_only_conditional_update(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3], [0.0, 0.0, 0.0]))
    _merge(log, _kv([1, 2, 9], [10, 20, 90]), "t.k = s.k",
           matched=[up("s.v > 15", v="s.v")])
    assert [r["v"] for r in _rows(log)] == [0.0, 20.0, 0.0]


def test_only_conditional_update_unmet_is_noop(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([1], [5.0]), "t.k = s.k", matched=[up("s.v > 99", v="s.v")])
    assert _rows(log) == [{"k": 1, "v": 1.0}]


def test_only_delete(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3], [1, 2, 3]))
    _merge(log, _kv([2, 9], [0, 0]), "t.k = s.k", matched=[delete()])
    assert [r["k"] for r in _rows(log)] == [1, 3]


def test_only_conditional_delete(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3], [1.0, 2.0, 3.0]))
    _merge(log, _kv([1, 2, 3], [1, 99, 99]), "t.k = s.k",
           matched=[delete("s.v > 50 AND t.v < 3.0")])
    assert [r["k"] for r in _rows(log)] == [1, 3]


def test_conditional_update_then_delete(tmp_path, executor):
    """First matching clause wins: rows passing the update condition
    update; remaining matched rows delete."""
    log = _write(tmp_path / "t", _kv([1, 2, 3, 4], [1, 2, 3, 4]))
    _merge(log, _kv([1, 2, 3], [10, 20, 30]), "t.k = s.k",
           matched=[up("t.v >= 2.0", v="s.v"), delete()])
    assert _rows(log) == [
        {"k": 2, "v": 20.0}, {"k": 3, "v": 30.0}, {"k": 4, "v": 4.0}]


def test_conditional_delete_then_update_order_matters(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3, 4], [1, 2, 3, 4]))
    _merge(log, _kv([1, 2, 3], [10, 20, 30]), "t.k = s.k",
           matched=[delete("t.v >= 2.0"), up(v="s.v")])
    assert _rows(log) == [{"k": 1, "v": 10.0}, {"k": 4, "v": 4.0}]


def test_conditional_update_delete_insert_full_matrix(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3], [1, 2, 3]))
    _merge(log, _kv([1, 2, 8, 9], [10, 20, 80, 90]), "t.k = s.k",
           matched=[up("s.v <= 10", v="s.v"), delete()],
           not_matched=[ins("s.v >= 90")])
    assert _rows(log) == [
        {"k": 1, "v": 10.0}, {"k": 3, "v": 3.0}, {"k": 9, "v": 90.0}]


def test_update_plus_conditional_insert_no_updates_case(tmp_path):
    """Insert-only data through an update+insert merge: update clause never
    fires, conditional insert filters."""
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([7, 8], [70, 5]), "t.k = s.k",
           matched=[up(v="s.v")], not_matched=[ins("s.v > 10")])
    assert _rows(log) == [{"k": 1, "v": 1.0}, {"k": 7, "v": 70.0}]


def test_delete_plus_insert_multiple_matches_for_both(tmp_path, executor):
    """An unconditional single DELETE tolerates duplicate source matches;
    duplicate not-matched source keys insert once each (dup rows insert)."""
    log = _write(tmp_path / "t", _kv([1, 2], [1, 2]))
    _merge(log, _kv([1, 1, 9, 9], [0, 0, 90, 91]), "t.k = s.k",
           matched=[delete()], not_matched=[ins()])
    got = _rows(log)
    assert [r["k"] for r in got] == [2, 9, 9]
    assert sorted(r["v"] for r in got if r["k"] == 9) == [90.0, 91.0]


def test_multiple_not_matched_clauses_first_wins(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([5, 6], [50, 60]), "t.k = s.k",
           not_matched=[ins("s.v >= 60", v="s.v + 1000", k="s.k"), ins()])
    assert _rows(log) == [
        {"k": 1, "v": 1.0}, {"k": 5, "v": 50.0}, {"k": 6, "v": 1060.0}]


def test_only_conditional_update_with_multiple_matches_errors(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(DeltaError, match="[Mm]ultiple"):
        _merge(log, _kv([1, 1], [10, 20]), "t.k = s.k",
               matched=[up("s.v > 0", v="s.v")])


def test_only_delete_with_multiple_matches_ok(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2], [1, 2]))
    _merge(log, _kv([1, 1], [0, 0]), "t.k = s.k", matched=[delete()])
    assert [r["k"] for r in _rows(log)] == [2]


# ---------------------------------------------------------------------------
# testNullCase family
# ---------------------------------------------------------------------------


def _null_kv(ks, vs):
    return {"k": pa.array(ks, K64), "v": pa.array(vs, pa.float64())}


def test_null_value_in_target_nonkey(tmp_path, executor):
    log = _write(tmp_path / "t", _null_kv([1, 2], [None, 2.0]))
    _merge(log, _kv([1], [10]), "t.k = s.k", matched=[up(v="s.v")],
           not_matched=[ins()])
    assert _rows(log) == [{"k": 1, "v": 10.0}, {"k": 2, "v": 2.0}]


def test_null_value_in_source_nonkey_propagates(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _null_kv([1, 5], [None, None]), "t.k = s.k",
           matched=[up(v="s.v")], not_matched=[ins()])
    assert _rows(log) == [{"k": 1, "v": None}, {"k": 5, "v": None}]


def test_null_keys_both_sides_never_match(tmp_path, executor):
    """SQL equality: NULL = NULL is not true — null-key rows on both sides
    stay unmatched (source null keys insert)."""
    log = _write(tmp_path / "t", _null_kv([None, 2], [0.5, 2.0]))
    _merge(log, _null_kv([None, 2], [99.0, 20.0]), "t.k = s.k",
           matched=[up(v="s.v")], not_matched=[ins()])
    got = _rows(log)
    ks = [r["k"] for r in got]
    assert ks.count(None) == 2 and 2 in ks
    assert {r["v"] for r in got if r["k"] is None} == {0.5, 99.0}
    assert [r["v"] for r in got if r["k"] == 2] == [20.0]


def test_null_handling_is_null_in_condition(tmp_path, executor):
    """IS NULL conjuncts in the merge condition route through the residual
    evaluator with Kleene semantics."""
    log = _write(tmp_path / "t", _null_kv([1, None], [1.0, 5.0]))
    _merge(log, _kv([1], [10]), "t.k = s.k AND t.v IS NOT NULL",
           matched=[up(v="s.v")])
    got = _rows(log)
    assert [r["v"] for r in got if r["k"] == 1] == [10.0]
    assert [r["v"] for r in got if r["k"] is None] == [5.0]


def test_null_in_condition_literal(tmp_path):
    """A `= NULL` conjunct is never true: no row matches, inserts fire."""
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([1], [10]), "t.k = s.k AND t.v = NULL",
           matched=[up(v="s.v")], not_matched=[ins()])
    got = _rows(log)
    assert len(got) == 2 and sorted(r["v"] for r in got) == [1.0, 10.0]


def test_insert_only_null_in_source_key(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _null_kv([None, 7], [50.0, 70.0]), "t.k = s.k",
           not_matched=[ins()])
    got = _rows(log)
    assert len(got) == 3
    assert {r["v"] for r in got if r["k"] is None} == {50.0}


# ---------------------------------------------------------------------------
# analysis errors in extended syntax
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("clause_kind", ["update", "delete", "insert"])
def test_condition_unknown_reference_errors(tmp_path, clause_kind):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    bad_cond = "zzz > 0"
    if clause_kind == "update":
        clauses = dict(matched=[up(bad_cond, v="s.v")])
    elif clause_kind == "delete":
        clauses = dict(matched=[delete(bad_cond)])
    else:
        clauses = dict(not_matched=[ins(bad_cond)])
    with pytest.raises(DeltaError):
        _merge(log, _kv([1], [10]), "t.k = s.k", **clauses)


def test_insert_condition_referencing_target_errors(tmp_path):
    """NOT MATCHED conditions see only the source row (there IS no target
    row); a target-qualified reference must fail analysis."""
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(DeltaError):
        _merge(log, _kv([9], [90]), "t.k = s.k",
               not_matched=[ins("t.v > 0")])


def test_update_assignment_unknown_target_column_errors(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(DeltaError):
        _merge(log, _kv([1], [10]), "t.k = s.k",
               matched=[MergeClause("update", assignments={"nope": "s.v"})])


def test_update_assignments_conflict_same_column_errors(tmp_path):
    """Duplicate assignment targets in one UPDATE clause are rejected
    (reference: 'update assignments conflict')."""
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(DeltaError):
        _merge(log, _kv([1], [10]), "t.k = s.k",
               matched=[MergeClause("update",
                                    assignments={"v": "s.v", "V": "s.v + 1"})])


def test_delete_clause_with_assignments_errors(tmp_path):
    with pytest.raises(DeltaError):
        log = _write(tmp_path / "t", _kv([1], [1.0]))
        _merge(log, _kv([1], [10]), "t.k = s.k",
               matched=[MergeClause("delete", assignments={"v": "s.v"})])


def test_non_last_unconditional_matched_clause_errors(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(DeltaError):
        _merge(log, _kv([1], [10]), "t.k = s.k",
               matched=[up(v="s.v"), delete("s.v > 0")])


def test_aggregate_in_merge_condition_errors(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with pytest.raises(Exception):
        _merge(log, _kv([1], [10]), "t.k = s.k AND sum(s.v) > 0",
               matched=[up(v="s.v")])


# ---------------------------------------------------------------------------
# source shapes: self-merge, query-shaped sources, column order
# ---------------------------------------------------------------------------


def test_self_merge_table_as_its_own_source(tmp_path, executor):
    from delta_tpu.exec.scan import scan_to_table

    log = _write(tmp_path / "t", _kv([1, 2], [1.0, 2.0]))
    selfsrc = scan_to_table(log.update())
    _merge(log, selfsrc, "t.k = s.k", matched=[up(v="s.v + 100")])
    assert [r["v"] for r in _rows(log)] == [101.0, 102.0]


def test_source_is_filtered_query(tmp_path):
    """Source = the result of a computation (the reference's 'source is a
    query'): merge consumes any Arrow table."""
    import pyarrow.compute as pc

    log = _write(tmp_path / "t", _kv([1, 2, 3], [1, 2, 3]))
    big = pa.table(_kv([1, 2, 3, 4], [10, 20, 30, 40]))
    src = big.filter(pc.greater(big.column("v"), 15.0))
    _merge(log, src, "t.k = s.k", matched=[up(v="s.v")], not_matched=[ins()])
    assert [r["v"] for r in _rows(log)] == [1.0, 20.0, 30.0, 40.0]


def test_columns_specified_in_wrong_order(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    src = pa.table({"v": pa.array([10.0]), "k": pa.array([1], K64)})
    _merge(log, src, "t.k = s.k", matched=[up(v="s.v")], not_matched=[ins()])
    assert _rows(log) == [{"k": 1, "v": 10.0}]


def test_not_all_columns_specified_in_update(tmp_path):
    log = _write(tmp_path / "t", {
        "k": pa.array([1], K64), "a": pa.array([1.0]), "b": pa.array([2.0])})
    _merge(log, {"k": pa.array([1], K64), "a": pa.array([10.0]),
                 "b": pa.array([20.0])},
           "t.k = s.k", matched=[up(a="s.a")])
    assert _rows(log) == [{"k": 1, "a": 10.0, "b": 2.0}]


def test_same_column_names_in_source_and_target_resolved_by_alias(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([1], [9.0]), "t.k = s.k", matched=[up(v="t.v + s.v")])
    assert _rows(log) == [{"k": 1, "v": 10.0}]


def test_merge_by_unaliased_column_names(tmp_path):
    """Unqualified references resolve source-first in values, target in
    assignment targets (engine rule; reference resolves via plans)."""
    log = _write(tmp_path / "t", _kv([1, 5], [1.0, 5.0]))
    _merge(log, {"k": pa.array([1], K64), "nv": pa.array([10.0])},
           "t.k = s.k", matched=[up(v="nv")])
    assert [r["v"] for r in _rows(log)] == [10.0, 5.0]


def test_merge_source_column_sharing_char_target_name_not_padded(tmp_path):
    """ADVICE (high): a clause condition on a SOURCE column that merely
    shares a name with a target char(n) column must NOT get its literal
    padded — `s.status = 'x'` compares against the source's raw 'x', not
    'x    '. The reference pads only refs resolving to char attributes."""
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.schema.types import CharType, LongType, StructType

    path = str(tmp_path / "t")
    schema = StructType().add("k", LongType()).add("status", CharType(5))
    t = DeltaTable.create(path, schema)
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "k": pa.array([1, 2], pa.int64()),
        "status": pa.array(["a", "b"], pa.string()),
    })).run()
    src = pa.table({
        "k": pa.array([1, 2], pa.int64()),
        "status": pa.array(["x", "keep"], pa.string()),
    })
    cmd = _merge(t.delta_log, src, "t.k = s.k",
                 matched=[up("s.status = 'x'", status="s.status")])
    assert cmd.metrics["numTargetRowsUpdated"] == 1
    rows = _rows(t.delta_log)
    assert rows[0]["status"] == "x    "  # updated, then char-padded on write
    assert rows[1]["status"] == "b    "  # clause condition false: untouched

    # ... while a TARGET-qualified char comparison still pads its literal
    cmd2 = _merge(t.delta_log, src, "t.k = s.k",
                  matched=[delete("t.status = 'b'")])
    assert cmd2.metrics["numTargetRowsDeleted"] == 1
    assert [r["k"] for r in _rows(t.delta_log)] == [1]


# ---------------------------------------------------------------------------
# insert-only family
# ---------------------------------------------------------------------------


def test_insert_only_with_source_condition(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([5, 6, 7], [50, 60, 70]), "t.k = s.k",
           not_matched=[ins("s.v >= 60")])
    assert [r["k"] for r in _rows(log)] == [1, 6, 7]


def test_insert_only_predicate_on_key(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([5, 6], [50, 60]), "t.k = s.k AND s.k % 2 = 0",
           not_matched=[ins()])
    got = [r["k"] for r in _rows(log)]
    assert 5 in got and 6 in got  # non-equi conjunct only gates MATCHING


def test_insert_only_multiple_matches_duplicates_insert(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    _merge(log, _kv([9, 9], [90, 91]), "t.k = s.k", not_matched=[ins()])
    assert sorted(r["v"] for r in _rows(log) if r["k"] == 9) == [90.0, 91.0]


def test_insert_only_explicit_subset_of_columns(tmp_path):
    log = _write(tmp_path / "t", {
        "k": pa.array([1], K64), "a": pa.array([1.0]), "b": pa.array([2.0])})
    _merge(log, {"k": pa.array([9], K64), "a": pa.array([90.0])},
           "t.k = s.k", not_matched=[ins(k="s.k", a="s.a")])
    got = _rows(log)
    assert got[1] == {"k": 9, "a": 90.0, "b": None}


# ---------------------------------------------------------------------------
# schema evolution extras
# ---------------------------------------------------------------------------


def _evolve(**kw):
    return conf.set_temporarily(**{
        "delta.tpu.schema.autoMerge.enabled": True, **kw})


def test_evolution_new_column_with_only_insert_star(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with _evolve():
        _merge(log, {"k": pa.array([9], K64), "v": pa.array([90.0]),
                     "extra": pa.array(["x"])},
               "t.k = s.k", not_matched=[ins()])
    got = _rows(log)
    assert got[0]["extra"] is None and got[1]["extra"] == "x"


def test_evolution_new_column_with_only_update_star(tmp_path):
    log = _write(tmp_path / "t", _kv([1, 2], [1.0, 2.0]))
    with _evolve():
        _merge(log, {"k": pa.array([1], K64), "v": pa.array([10.0]),
                     "extra": pa.array([7], K64)},
               "t.k = s.k", matched=[up()])
    got = _rows(log)
    assert got[0]["extra"] == 7 and got[1]["extra"] is None


def test_evolution_update_star_with_column_not_in_source(tmp_path):
    """update * with a target column absent from the source keeps the
    target value (star expands over SOURCE columns)."""
    log = _write(tmp_path / "t", {
        "k": pa.array([1], K64), "a": pa.array([1.0]), "b": pa.array([5.0])})
    with _evolve():
        _merge(log, {"k": pa.array([1], K64), "a": pa.array([10.0])},
               "t.k = s.k", matched=[up()])
    assert _rows(log) == [{"k": 1, "a": 10.0, "b": 5.0}]


def test_evolution_mixed_star_and_explicit_clauses(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with _evolve():
        _merge(log, {"k": pa.array([1, 9], K64), "v": pa.array([10.0, 90.0]),
                     "nc": pa.array([100.0, 900.0])},
               "t.k = s.k",
               matched=[MergeClause("update", assignments={"v": "s.nc"})],
               not_matched=[ins()])
    got = _rows(log)
    assert got[0] == {"k": 1, "v": 100.0, "nc": None}
    assert got[1] == {"k": 9, "v": 90.0, "nc": 900.0}


def test_evolution_incompatible_type_change_errors(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    with _evolve():
        with pytest.raises(DeltaError):
            _merge(log, {"k": pa.array([1], K64),
                         "v": pa.array(["not-a-number"])},
                   "t.k = s.k", matched=[up()])


def test_evolution_on_partitioned_table(tmp_path):
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.schema.types import DoubleType, LongType, StringType, StructType

    path = str(tmp_path / "pt")
    schema = (StructType().add("p", StringType()).add("k", LongType())
              .add("v", DoubleType()))
    DeltaTable.create(path, schema, partition_columns=["p"])
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({
        "p": pa.array(["a"]), "k": pa.array([1], K64),
        "v": pa.array([1.0])})).run()
    with _evolve():
        _merge(log, {"p": pa.array(["a", "b"]), "k": pa.array([1, 2], K64),
                     "v": pa.array([10.0, 20.0]),
                     "extra": pa.array([5, 6], K64)},
               "t.k = s.k", matched=[up()], not_matched=[ins()])
    got = _rows(log)
    assert {r["p"] for r in got} == {"a", "b"}
    assert [r["extra"] for r in got] == [5, 6]


def test_star_expansion_with_dotted_source_names(tmp_path):
    """Reference parity ('star expansion with names including dots'): a
    flat source column whose NAME contains a dot evolves in as a flat
    column and round-trips its values."""
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    src = pa.table({"k": pa.array([1], K64), "v": pa.array([10.0]),
                    "v.x": pa.array([9.0])})
    with _evolve():
        _merge(log, src, "t.k = s.k", matched=[up()])
    got = _rows(log)
    assert got[0]["v"] == 10.0 and got[0]["v.x"] == 9.0


# ---------------------------------------------------------------------------
# metrics parity spot checks
# ---------------------------------------------------------------------------


def test_merge_metrics_update_delete_insert_counts(tmp_path, executor):
    log = _write(tmp_path / "t", _kv([1, 2, 3, 4], [1, 2, 3, 4]))
    cmd = _merge(log, _kv([1, 2, 9], [10, 0, 90]), "t.k = s.k",
                 matched=[up("s.v > 5", v="s.v"), delete()],
                 not_matched=[ins()])
    m = cmd.metrics
    assert m["numTargetRowsUpdated"] == 1
    assert m["numTargetRowsDeleted"] == 1
    assert m["numTargetRowsInserted"] == 1
    assert m["numSourceRows"] == 3


def test_merge_metrics_zero_touch_when_nothing_matches(tmp_path):
    log = _write(tmp_path / "t", _kv([1], [1.0]))
    cmd = _merge(log, _kv([9], [90]), "t.k = s.k", matched=[up(v="s.v")])
    assert cmd.metrics["numTargetRowsUpdated"] == 0
    assert cmd.metrics["numTargetRowsInserted"] == 0
