"""Vectorized (Arrow) and device (jnp) evaluators must agree with row eval.

The row evaluator (`Expression.eval`) is the semantics spec — the analogue of
Catalyst's interpreted path — and both columnar evaluators are checked
against it over a table with NULLs in every column.
"""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.expr import ir
from delta_tpu.expr.jaxeval import (
    DeviceColumn,
    NotDeviceCompilable,
    compile_expr,
)
from delta_tpu.expr.parser import parse_expression, parse_predicate
from delta_tpu.expr.vectorized import boolean_mask, evaluate, filter_table, project

ROWS = [
    {"a": 1, "b": 10.0, "s": "apple", "flag": True},
    {"a": 2, "b": None, "s": "banana", "flag": False},
    {"a": None, "b": 30.5, "s": None, "flag": None},
    {"a": 4, "b": -4.0, "s": "cherry", "flag": True},
    {"a": 5, "b": 0.0, "s": "apricot", "flag": False},
]
TABLE = pa.Table.from_pylist(ROWS)

PREDICATES = [
    "a > 2",
    "a >= 2 AND b < 20",
    "a = 1 OR s = 'banana'",
    "NOT (a = 2)",
    "a IS NULL",
    "s IS NOT NULL",
    "a IN (1, 4, 5)",
    "a + 1 > 3",
    "b / 2 > 1",
    "a * 2 = 8",
    "a % 2 = 0",
    "s LIKE 'ap%'",
    "s LIKE '%an%'",
    "b IS NULL OR b > 0",
    "a > 1 AND (b > 0 OR flag)",
    "CAST(a AS STRING) = '4'",
    "a = 1 AND a = 2",
]


@pytest.mark.parametrize("sql", PREDICATES)
def test_vectorized_matches_row_eval(sql):
    e = parse_predicate(sql)
    expected = [e.eval(r) for r in ROWS]
    got = evaluate(e, TABLE).to_pylist()
    assert got == expected, f"{sql}: {got} != {expected}"


def test_filter_table_null_is_dropped():
    out = filter_table(TABLE, parse_predicate("b > 0"))
    assert out.column("a").to_pylist() == [1, None]


def test_boolean_mask_nulls_false():
    mask = boolean_mask(parse_predicate("b > 0"), TABLE)
    assert mask.to_pylist() == [True, False, True, False, False]


def test_project_expressions():
    out = project(TABLE, {"x": parse_expression("a + 1"), "y": parse_expression("upper(s)")})
    assert out.column("x").to_pylist() == [2, 3, None, 5, 6]
    assert out.column("y").to_pylist() == ["APPLE", "BANANA", None, "CHERRY", "APRICOT"]


def test_case_when_vectorized():
    e = parse_expression("CASE WHEN a > 3 THEN 'big' WHEN a > 1 THEN 'mid' ELSE 'small' END")
    expected = [e.eval(r) for r in ROWS]
    assert evaluate(e, TABLE).to_pylist() == expected


def test_coalesce_vectorized():
    e = parse_expression("coalesce(b, a, 0)")
    expected = [float(x) if x is not None else None for x in (10.0, 2, 30.5, -4.0, 0.0)]
    assert evaluate(e, TABLE).to_pylist() == expected


# -- device evaluator -----------------------------------------------------

NUMERIC_PREDICATES = [
    "a > 2",
    "a >= 2 AND b < 20",
    "NOT (a = 2)",
    "a IS NULL",
    "a IN (1, 4, 5)",
    "a + 1 > 3",
    "b / 2 > 1",
    "a * 2 = 8",
    "b IS NULL OR b > 0",
    "a > 1 AND (b > 0 OR flag)",
    "a = 1 AND a = 2",
]


def _device_env():
    a = np.array([r["a"] if r["a"] is not None else 0 for r in ROWS])
    a_valid = np.array([r["a"] is not None for r in ROWS])
    b = np.array([r["b"] if r["b"] is not None else 0.0 for r in ROWS])
    b_valid = np.array([r["b"] is not None for r in ROWS])
    f = np.array([bool(r["flag"]) for r in ROWS])
    f_valid = np.array([r["flag"] is not None for r in ROWS])
    return {
        "a": DeviceColumn.of(a, a_valid),
        "b": DeviceColumn.of(b, b_valid),
        "flag": DeviceColumn.of(f, f_valid),
    }


@pytest.mark.parametrize("sql", NUMERIC_PREDICATES)
def test_jaxeval_matches_row_eval(sql):
    e = parse_predicate(sql)
    expected = [e.eval(r) for r in ROWS]
    col = compile_expr(e)(_device_env())
    values = np.asarray(col.values, dtype=bool)
    valid = np.asarray(col.valid, dtype=bool)
    got = [bool(v) if ok else None for v, ok in zip(values, valid)]
    assert got == expected, f"{sql}: {got} != {expected}"


def test_jaxeval_arithmetic_projection():
    e = parse_expression("a * 2 + 1")
    col = compile_expr(e)(_device_env())
    vals = np.asarray(col.values)
    valid = np.asarray(col.valid)
    assert list(vals[valid]) == [3, 5, 9, 11]


def test_jaxeval_case_when():
    e = parse_expression("CASE WHEN a > 3 THEN 1 WHEN a > 1 THEN 2 ELSE 3 END")
    col = compile_expr(e)(_device_env())
    expected = [e.eval(r) for r in ROWS]
    got = [int(v) if ok else None for v, ok in zip(np.asarray(col.values), np.asarray(col.valid))]
    assert got == expected


def test_jaxeval_rejects_strings():
    with pytest.raises(NotDeviceCompilable):
        compile_expr(parse_predicate("s LIKE 'ap%'"))


def test_jaxeval_under_jit():
    import jax

    e = parse_predicate("a > 2 AND b >= 0")
    fn = compile_expr(e)
    env = _device_env()
    out = jax.jit(lambda env: fn(env))(env)
    expected = [e.eval(r) for r in ROWS]
    got = [
        bool(v) if ok else None
        for v, ok in zip(np.asarray(out.values, bool), np.asarray(out.valid, bool))
    ]
    assert got == expected


# -- vectorized Func parity with the row evaluator ---------------------------


def _func_parity(expr_sql, table):
    from delta_tpu.expr.parser import parse_expression
    from delta_tpu.expr.vectorized import evaluate

    e = parse_expression(expr_sql)
    vec = evaluate(e, table).to_pylist()
    rows = [e.eval(r) for r in table.to_pylist()]
    assert vec == rows, (expr_sql, vec, rows)


def test_vectorized_concat_parity():
    import pyarrow as pa

    t = pa.table({
        "a": pa.array(["x", None, "z"]),
        "b": pa.array([1, 2, None], pa.int64()),
    })
    _func_parity("concat(a, 'mid', b)", t)


def test_vectorized_substring_parity():
    import pyarrow as pa

    t = pa.table({"s": pa.array(["hello", "ab", None, ""])})
    _func_parity("substring(s, 2, 3)", t)
    _func_parity("substring(s, 1)", t)
    _func_parity("substring(s, 2, NULL)", t)  # NULL length: row semantics


def test_vectorized_round_parity():
    import pyarrow as pa

    # decimal ndigits MUST keep exact row semantics (Arrow rounds the
    # binary-scaled value: round(2.675, 2) -> 2.68 vs Python's 2.67)
    t = pa.table({"x": pa.array([1.25, 2.5, None, -0.5, 2.675, 0.15])})
    _func_parity("round(x, 2)", t)
    _func_parity("round(x, 1)", t)
    _func_parity("round(x)", t)


def test_vectorized_hour_parity_on_int_micros():
    import pyarrow as pa

    t = pa.table({"t": pa.array([0, 3_600_000_000 * 5 + 17, None], pa.int64())})
    _func_parity("hour(t)", t)
