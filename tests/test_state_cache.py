"""Device-resident snapshot state (`ops/state_cache.py`): correctness of the
f32 conservative rounding, range extraction, batched planning parity
(device vs host mirrors), incremental tail application, invalidation, and
byte-budget eviction. Runs on the virtual CPU mesh like every device test."""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression
from delta_tpu.ops import pruning, state_cache
from delta_tpu.ops.state_cache import (
    DeviceStateCache, RangeSet, _f32_down, _f32_up, extract_ranges,
)
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_cache():
    DeviceStateCache.reset()
    yield
    DeviceStateCache.reset()


def _mk_table(path, n_files=6, rows=40, start=0):
    log = DeltaLog.for_table(path)
    rng = np.random.RandomState(1)
    for i in range(start, start + n_files):
        WriteIntoDelta(log, "append", pa.table({
            "a": np.arange(i * rows, (i + 1) * rows, dtype=np.int64),
            "b": rng.rand(rows),
        })).run()
    return log


def _ranges_for(snap, exprs):
    from delta_tpu.expr.synthesis import schema_types

    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None
    pcols = frozenset()
    types = schema_types(snap.metadata)  # the production planners pass these
    rs = []
    for e in exprs:
        pred = pruning.skipping_predicate(parse_expression(e), pcols, types)
        r = extract_ranges(pred, entry.columns)
        assert r is not None, e
        rs.append(r)
    return entry, rs


# -- rounding ---------------------------------------------------------------


def test_f32_rounding_directions():
    xs = np.array([0.1, -0.1, 1e300, -1e300, 1.0, np.nan])
    lo = _f32_down(xs)
    hi = _f32_up(xs)
    for i, x in enumerate(xs):
        if np.isnan(x):
            assert np.isnan(lo[i]) and np.isnan(hi[i])
        else:
            assert float(lo[i]) <= x <= float(hi[i])
    # exact f32 values stay exact
    assert float(lo[4]) == 1.0 == float(hi[4])
    # 1e300 overflows f32: down must stay finite-below, up goes +inf
    assert float(lo[2]) < np.inf and float(hi[2]) == np.inf


# -- range extraction -------------------------------------------------------


def test_extract_ranges_shapes():
    cols = ["a", "b"]
    p = lambda s: pruning.skipping_predicate(parse_expression(s), frozenset())
    r = extract_ranges(p("a = 5"), cols)
    assert r.lo[0] == 5 and r.hi[0] == 5 and np.isnan(r.lo[1])
    r = extract_ranges(p("a > 3 AND a < 10 AND b >= 0.5"), cols)
    assert r.lo[0] == 3 and r.hi[0] == 10 and r.lo[1] == 0.5
    # OR does not lower; null tests do not lower
    assert extract_ranges(p("a = 1 OR a = 2"), cols) is None
    assert extract_ranges(p("a IS NULL"), cols) is None
    # unknown column in the predicate -> not extractable
    assert extract_ranges(p("zzz = 1"), cols) is None
    # contradiction -> empty verdict
    r = extract_ranges(ir.Literal(False), cols)
    assert r.verdict == "empty"
    # unconstrained -> all verdict
    r = extract_ranges(ir.Literal(None), cols)
    assert r.verdict == "all"


# -- end-to-end parity ------------------------------------------------------


def test_plan_matches_files_for_scan(tmp_table):
    log = _mk_table(tmp_table)
    snap = log.update()
    queries = ["a = 25", "a >= 100 AND a <= 139", "a <= -1", "b <= 2.0"]
    entry, rs = _ranges_for(snap, queries)
    for use_device in (False, True):
        plans = entry.plan_ranges(rs, k=16, use_device=use_device)
        for q, plan in zip(queries, plans):
            scan = pruning.files_for_scan(snap, [parse_expression(q)])
            expect = sorted(f.path for f in scan.files)
            got = sorted(entry.paths[r] for r in plan.rows)
            assert got == expect, (q, use_device)
            assert plan.count == len(expect)


def test_plan_strict_bounds_keep_superset(tmp_table):
    """Strict comparisons relax to non-strict in the range lowering: the plan
    may keep a boundary file the exact evaluator drops, never the reverse,
    and device and host mirrors agree exactly with each other."""
    log = _mk_table(tmp_table)
    snap = log.update()
    queries = ["a < 40", "a > 199", "a < 0"]
    entry, rs = _ranges_for(snap, queries)
    host = entry.plan_ranges(rs, k=16, use_device=False)
    dev = entry.plan_ranges(rs, k=16, use_device=True)
    for q, h, d in zip(queries, host, dev):
        assert sorted(h.rows) == sorted(d.rows), q
        scan = pruning.files_for_scan(snap, [parse_expression(q)])
        expect = {f.path for f in scan.files}
        got = {entry.paths[r] for r in h.rows}
        assert expect <= got, q


def test_plan_overflow_falls_back_exact(tmp_table):
    log = _mk_table(tmp_table, n_files=8)
    snap = log.update()
    entry, rs = _ranges_for(snap, ["a >= 0"])  # matches all 8 files
    plans = entry.plan_ranges(rs, k=3, use_device=True)
    assert plans[0].count == 8
    assert plans[0].overflow and len(plans[0].rows) == 3


def test_f32_boundary_keeps_file(tmp_table):
    """A bound that f32 rounds past must keep the boundary file, not drop it:
    the file [lo, hi] with a query literal between f32 grid points."""
    log = DeltaLog.for_table(tmp_table)
    # 16777217 = 2^24 + 1 is not representable in f32 (rounds to 2^24)
    v = 2**24 + 1
    WriteIntoDelta(log, "append", pa.table({"a": np.array([v], np.int64)})).run()
    snap = log.update()
    entry, rs = _ranges_for(snap, [f"a = {v}"])
    for use_device in (False, True):
        plans = entry.plan_ranges(rs, k=4, use_device=use_device)
        assert plans[0].count == 1, use_device


# -- incremental tail -------------------------------------------------------


def test_incremental_tail_append(tmp_table):
    log = _mk_table(tmp_table, n_files=3)
    entry1 = DeviceStateCache.instance().get(log.update())
    entry1.ensure_resident()
    v1 = entry1.version
    _mk_table(tmp_table, n_files=2, start=3)  # two more commits
    snap2 = log.update()
    entry2 = DeviceStateCache.instance().get(snap2)
    assert entry2 is entry1, "tail must apply incrementally, not rebuild"
    assert entry2.version == snap2.version > v1
    assert entry2.num_rows == 5
    # parity after the incremental device update
    entry, rs = _ranges_for(snap2, ["a >= 120"])
    for use_device in (False, True):
        plans = entry.plan_ranges(rs, k=8, use_device=use_device)
        scan = pruning.files_for_scan(snap2, [parse_expression("a >= 120")])
        assert sorted(entry.paths[r] for r in plans[0].rows) == sorted(
            f.path for f in scan.files)


def test_incremental_tail_remove_and_readd(tmp_table):
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table, n_files=4)
    cache = DeviceStateCache.instance()
    e1 = cache.get(log.update())
    e1.ensure_resident()
    # delete one whole file's rows -> that file is removed
    DeleteCommand(log, "a < 40").run()
    snap = log.update()
    e2 = cache.get(snap)
    assert e2 is e1
    entry, rs = _ranges_for(snap, ["a >= 0"])
    plans = entry.plan_ranges(rs, k=16, use_device=True)
    scan = pruning.files_for_scan(snap, [parse_expression("a >= 0")])
    assert sorted(entry.paths[r] for r in plans[0].rows) == sorted(
        f.path for f in scan.files)
    assert plans[0].count == len(scan.files)


def test_metadata_change_rebuilds(tmp_table):
    from delta_tpu.commands.alter import set_table_properties

    log = _mk_table(tmp_table, n_files=2)
    cache = DeviceStateCache.instance()
    e1 = cache.get(log.update())
    set_table_properties(log, {"delta.logRetentionDuration": "interval 30 days"})
    snap = log.update()
    e2 = cache.get(snap)
    assert e2 is not None and e2.version == snap.version
    assert e2 is not e1, "a Metadata action in the tail must force a rebuild"


def test_table_replaced_invalidates(tmp_table):
    import shutil

    log = _mk_table(tmp_table, n_files=2)
    cache = DeviceStateCache.instance()
    e1 = cache.get(log.update())
    assert e1 is not None
    shutil.rmtree(tmp_table)
    DeltaLog.clear_cache()
    log2 = _mk_table(tmp_table, n_files=1)
    e2 = cache.get(log2.update())
    assert e2 is not e1 and e2.num_rows == 1


def test_time_travel_below_residency_serves_host(tmp_table):
    log = _mk_table(tmp_table, n_files=3)
    cache = DeviceStateCache.instance()
    cache.get(log.update())
    old = log.get_snapshot_at(0)
    assert cache.get(old) is None  # residency never serves an older version


def test_partitioned_table_builds_entry(tmp_table):
    """r5: partitioned tables get resident entries with dictionary-coded
    partition pseudo-lanes (was: unsupported -> None)."""
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.schema.types import IntegerType, StringType, StructType

    schema = StructType().add("p", StringType()).add("a", IntegerType())
    DeltaTable.create(tmp_table, schema, partition_columns=["p"])
    log = DeltaLog.for_table(tmp_table)
    for p, lo in (("b", 0), ("a", 100), ("c", 200)):
        WriteIntoDelta(log, "append", pa.table({
            "p": [p] * 10, "a": np.arange(lo, lo + 10, dtype=np.int32),
        })).run()
    snap = log.update()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None
    assert "p" in entry.part_info and "a" in entry.columns
    part = entry.part_info["p"]
    assert part.values == ["a", "b", "c"]  # value-sorted codes
    assert part.sorted and part.parsed is None


def _oracle_files(snap, q):
    """Exact pruner result with ALL resident serving disabled — the
    parity baseline must not itself be served by the state cache."""
    from delta_tpu.exec.scan import scan_files

    with conf.set_temporarily(**{"delta.tpu.stateCache.serveScans": False,
                                 "delta.tpu.stateCache.enabled": False}):
        return sorted(f.path for f in scan_files(snap, q).files)


def _mk_part_table(path, days=("2021-01-01", "2021-01-02", "2021-01-03"),
                   with_null=False):
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.schema.types import (
        IntegerType, LongType, StringType, StructType,
    )

    schema = (StructType().add("day", StringType()).add("year", IntegerType())
              .add("a", LongType()))
    DeltaTable.create(path, schema, partition_columns=["day", "year"])
    log = DeltaLog.for_table(path)
    lo = 0
    for i, d in enumerate(days):
        WriteIntoDelta(log, "append", pa.table({
            "day": pa.array([d] * 8, pa.string()),
            "year": pa.array([2020 + i] * 8, pa.int32()),
            "a": np.arange(lo, lo + 8, dtype=np.int64),
        })).run()
        lo += 8
    if with_null:
        WriteIntoDelta(log, "append", pa.table({
            "day": pa.array([None] * 4, pa.string()),
            "year": pa.array([None] * 4, pa.int32()),
            "a": np.arange(lo, lo + 4, dtype=np.int64),
        })).run()
    return log


def test_partitioned_plan_parity_with_host_pruner(tmp_table):
    """Resident partitioned planning (equality, ranges on string and
    numeric partition lanes, mixed with data-column stats) must match the
    exact host pruner file-for-file, device and host mirrors alike."""
    from delta_tpu.exec.scan import plan_scans, scan_files

    log = _mk_part_table(tmp_table, with_null=True)
    snap = log.update()
    queries = [
        ["day = '2021-01-02'"],
        ["year = 2021"],
        ["year >= 2021"],
        ["year > 2020 AND year <= 2022"],
        ["day >= '2021-01-02'"],
        ["day < '2021-01-02'"],
        ["day = '2021-01-02' AND a >= 10"],
        ["year = 1999"],          # absent value -> empty
        ["day = 'zzz'"],          # absent value -> empty
        ["a >= 12 AND a <= 20"],  # pure stats on a partitioned table
    ]
    for mode in ("off", "force"):
        with conf.set_temporarily(**{
                "delta.tpu.stateCache.devicePlan.mode": mode}):
            plans = plan_scans(snap, queries, k=64)
        for q, plan in zip(queries, plans):
            expect = _oracle_files(snap, q)
            assert sorted(plan.paths) == expect, (q, mode)
            assert plan.via != "scan", (q, mode)  # actually served resident


def test_partitioned_null_partition_pruned_exactly(tmp_table):
    from delta_tpu.exec.scan import plan_scans, scan_files

    log = _mk_part_table(tmp_table, with_null=True)
    snap = log.update()
    # every bounded predicate must exclude the null-partition file; an
    # unconstrained query must keep it
    plans = plan_scans(snap, [["year >= 1900"], []], k=64)
    q0 = set(_oracle_files(snap, ["year >= 1900"]))
    assert set(plans[0].paths) == q0
    null_files = {f.path for f in snap.all_files
                  if (f.partition_values or {}).get("year") is None}
    assert null_files and not (null_files & set(plans[0].paths))
    assert null_files < set(plans[1].paths)


def test_all_null_partition_column_builds_and_advances(tmp_table):
    """A partition column that is null in EVERY file (empty dictionary)
    must build an entry and apply tails without crashing (r5 review
    finding: empty rank/trans arrays were indexed eagerly)."""
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.exec.scan import plan_scans
    from delta_tpu.schema.types import LongType, StringType, StructType

    schema = StructType().add("p", StringType()).add("a", LongType())
    DeltaTable.create(tmp_table, schema, partition_columns=["p"])
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "p": pa.array([None] * 8, pa.string()),
        "a": np.arange(8, dtype=np.int64)})).run()
    snap = log.update()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None and entry.part_info["p"].values == []
    # a tail commit, also all-null
    WriteIntoDelta(log, "append", pa.table({
        "p": pa.array([None] * 4, pa.string()),
        "a": np.arange(100, 104, dtype=np.int64)})).run()
    snap2 = log.update()
    assert DeviceStateCache.instance().get(snap2) is entry
    plans = plan_scans(snap2, [["a >= 0"], ["p = 'x'"]], k=16)
    assert plans[0].count == 2  # both files, no partition constraint
    assert plans[1].count == 0  # null partitions never match equality


def test_partitioned_tail_advance_extends_dictionary(tmp_table):
    """A new partition value that sorts after the current maximum keeps
    the sorted invariant (range lowering stays); an out-of-order value
    clears it (equality still serves)."""
    from delta_tpu.exec.scan import plan_scans, scan_files

    log = _mk_part_table(tmp_table)
    snap = log.update()
    cache = DeviceStateCache.instance()
    entry = cache.get(snap)
    assert entry is not None
    # in-order extension: a NEW later day
    WriteIntoDelta(log, "append", pa.table({
        "day": pa.array(["2021-01-04"] * 4, pa.string()),
        "year": pa.array([2023] * 4, pa.int32()),
        "a": np.arange(100, 104, dtype=np.int64),
    })).run()
    snap2 = log.update()
    e2 = cache.get(snap2)
    assert e2 is entry, "tail must apply incrementally"
    assert entry.part_info["day"].sorted
    assert entry.part_info["day"].values[-1] == "2021-01-04"
    plans = plan_scans(snap2, [["day >= '2021-01-03'"]], k=64)
    expect = _oracle_files(snap2, ["day >= '2021-01-03'"])
    assert sorted(plans[0].paths) == expect and plans[0].via != "scan"
    # out-of-order extension: an EARLIER day arrives late
    WriteIntoDelta(log, "append", pa.table({
        "day": pa.array(["2020-12-31"] * 4, pa.string()),
        "year": pa.array([2019] * 4, pa.int32()),
        "a": np.arange(200, 204, dtype=np.int64),
    })).run()
    snap3 = log.update()
    e3 = cache.get(snap3)
    assert e3 is entry
    assert not entry.part_info["day"].sorted
    # equality still serves resident; ranges fall back to the exact scan
    plans = plan_scans(snap3, [["day = '2020-12-31'"],
                               ["day >= '2021-01-01'"]], k=64)
    eq_expect = _oracle_files(snap3, ["day = '2020-12-31'"])
    assert sorted(plans[0].paths) == eq_expect and plans[0].via != "scan"
    rng_expect = _oracle_files(snap3, ["day >= '2021-01-01'"])
    assert sorted(plans[1].paths) == rng_expect
    assert plans[1].via == "scan"  # unsorted dict: range lowering disabled


def test_string_prefix_lanes_prune_conservatively(tmp_table):
    """String stats ride 6-byte-prefix f64 lanes: resident plans must be
    SUPERSETS of the oracle (prefix truncation keeps, never drops) and
    actually prune disjoint files on equality/range/prefix shapes."""
    from delta_tpu.exec.scan import plan_scans

    log = DeltaLog.for_table(tmp_table)
    for head in ("apple", "banana", "cherry", "damson"):
        WriteIntoDelta(log, "append", pa.table({
            "s": pa.array([f"{head}{i:03d}" for i in range(20)], pa.string()),
            "v": np.arange(20, dtype=np.int64),
        })).run()
    snap = log.update()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None and "s" in entry.str_lanes
    queries = [["s = 'banana005'"], ["s >= 'cherry'"], ["s < 'b'"],
               ["s >= 'damson' AND s <= 'damson999'"]]
    plans = plan_scans(snap, queries, k=16)
    for q, plan in zip(queries, plans):
        expect = set(_oracle_files(snap, q))
        assert plan.via in ("device", "host-resident"), q
        assert expect <= set(plan.paths), q
    # equality on a single head hits exactly one file (prefix 6 bytes
    # distinguishes these heads)
    assert len(plans[0].paths) == 1


def test_partition_in_list_serves_resident(tmp_table):
    from delta_tpu.exec.scan import plan_scans

    log = _mk_part_table(tmp_table, days=("d1", "d2", "d3", "d4"))
    snap = log.update()
    queries = [["day IN ('d1', 'd3')"], ["day IN ('d2', 'd3', 'd4')"],
               ["day IN ('zz')"]]
    plans = plan_scans(snap, queries, k=16)
    for q, plan in zip(queries, plans):
        assert sorted(plan.paths) == _oracle_files(snap, q), q
        assert plan.via in ("device", "host-resident", "verdict"), q
    assert plans[2].count == 0


def test_partitioned_plans_race_dictionary_extension(tmp_table):
    """Planner threads race tail advances that EXTEND the partition
    dictionary: every plan must either match the exact pruner for ITS
    snapshot or fall back — never serve a wrong file set (the
    expected_version guard + under-lock dict extension)."""
    import threading

    from delta_tpu.exec.scan import plan_scans

    log = _mk_part_table(tmp_table, days=("d001", "d002"))
    cache = DeviceStateCache.instance()
    cache.get(log.update())
    stop = threading.Event()
    errors_seen = []

    def writer():
        i = 3
        while not stop.is_set() and i < 14:
            WriteIntoDelta(log, "append", pa.table({
                "day": pa.array([f"d{i:03d}"] * 4, pa.string()),
                "year": pa.array([2020 + i] * 4, pa.int32()),
                "a": np.arange(i * 100, i * 100 + 4, dtype=np.int64),
            })).run()
            i += 1

    from delta_tpu.expr import partition as pexpr
    from delta_tpu.expr.parser import parse_predicate

    def oracle(snap, q):
        # thread-safe exact pruner: conf.set_temporarily is process-global,
        # so the disabled-cache oracle helper must not run concurrently
        pred = parse_predicate(q)
        ps = snap.metadata.partition_schema
        return sorted(f.path for f in snap.all_files
                      if pexpr.matches(pred, f, ps))

    def planner():
        try:
            while not stop.is_set():
                snap = log.update()
                expect = {q: oracle(snap, q)
                          for q in ("day = 'd002'", "day >= 'd003'")}
                plans = plan_scans(
                    snap, [[q] for q in expect], k=64)
                for q, plan in zip(expect, plans):
                    if sorted(plan.paths) != expect[q]:
                        errors_seen.append((q, plan.via, plan.paths,
                                            expect[q]))
        except Exception as e:  # noqa: BLE001
            errors_seen.append(repr(e))

    w = threading.Thread(target=writer)
    ps = [threading.Thread(target=planner) for _ in range(2)]
    w.start()
    [t.start() for t in ps]
    w.join()
    stop.set()
    [t.join() for t in ps]
    assert not errors_seen, errors_seen[:3]
    # final state: in-order extension kept the sorted invariant
    entry = cache.get(log.update())
    assert entry is not None and entry.part_info["day"].sorted


def test_budget_eviction(tmp_path):
    cache = DeviceStateCache.instance()
    entries = []
    for i in range(3):
        log = _mk_table(str(tmp_path / f"t{i}"), n_files=2)
        e = cache.get(log.update())
        e.ensure_resident()
        entries.append(e)
    with conf.set_temporarily(**{"delta.tpu.stateCache.maxBytes": "1"}):
        log = _mk_table(str(tmp_path / "t3"), n_files=2)
        e3 = cache.get(log.update())
        e3.ensure_resident()
        cache._evict_over_budget(keep=e3.log_path)
    assert e3.is_resident  # the active entry is never evicted
    assert not any(e.is_resident for e in entries)
    # evicted entries still serve from host mirrors and can re-warm
    _, rs = _ranges_for(DeltaLog.for_table(str(tmp_path / "t0")).update(), ["a >= 0"])
    assert entries[0].plan_ranges(rs, k=8, use_device=False)[0].count == 2


def test_disabled_by_conf(tmp_table):
    log = _mk_table(tmp_table, n_files=1)
    with conf.set_temporarily(**{"delta.tpu.stateCache.enabled": "false"}):
        assert DeviceStateCache.instance().get(log.update()) is None


# -- batched planning API (exec/scan.plan_scans) ---------------------------


def test_plan_scans_batch(tmp_table):
    from delta_tpu.exec.scan import plan_scans, scan_files

    log = _mk_table(tmp_table, n_files=5)
    snap = log.update()
    queries = [
        ["a = 25"],                       # range -> resident path
        ["a >= 0 AND a <= 79"],           # range, 2 files
        ["a = 1 OR a = 190"],             # OR -> union of boxes (r5)
        ["b IS NULL"],                    # null test -> fallback
    ]
    plans = plan_scans(snap, queries, k=8)
    assert plans[0].via in ("device", "host-resident")
    assert plans[2].via in ("device", "host-resident")  # OR now lowers
    assert plans[3].via == "scan"
    for q, plan in zip(queries, plans):
        expect = {f.path for f in scan_files(snap, q).files}
        assert expect <= set(plan.paths), q
        assert plan.count == len(plan.paths)
    # OR union is exact here: equality boxes on both sides
    or_expect = sorted(f.path for f in scan_files(snap, ["a = 1 OR a = 190"]).files)
    assert sorted(plans[2].paths) == or_expect


def test_plan_scans_forced_device_matches_host(tmp_table):
    from delta_tpu.exec.scan import plan_scans

    log = _mk_table(tmp_table, n_files=4)
    snap = log.update()
    queries = [[f"a = {i * 40 + 7}"] for i in range(4)]
    with conf.set_temporarily(**{"delta.tpu.stateCache.devicePlan.mode": "force"}):
        dev = plan_scans(snap, queries, k=8)
    with conf.set_temporarily(**{"delta.tpu.stateCache.devicePlan.mode": "off"}):
        host = plan_scans(snap, queries, k=8)
    assert [sorted(p.paths) for p in dev] == [sorted(p.paths) for p in host]
    assert dev[0].via == "device" and host[0].via == "host-resident"


def test_plan_ranges_stale_version_returns_none(tmp_table):
    """A caller planning for snapshot v must not be served by an entry that
    advanced to v+1 (the apply_tail race): expected_version guards it."""
    log = _mk_table(tmp_table, n_files=2)
    snap1 = log.update()
    cache = DeviceStateCache.instance()
    cache.get(snap1)
    _mk_table(tmp_table, n_files=1, start=2)
    snap2 = log.update()
    entry = cache.get(snap2)  # entry advances to v2
    _, rs = _ranges_for(snap2, ["a >= 0"])
    assert entry.plan_ranges(rs, expected_version=snap1.version) is None
    assert entry.plan_ranges(rs, expected_version=snap2.version) is not None


def test_apply_tail_reject_leaves_entry_untouched():
    """A rejected apply_tail (capacity overflow / garbage) must be a clean
    no-op: the entry keeps its old version AND its old mirrors, so a
    concurrent plan_ranges(expected_version=old) that passes the version
    guard still sees every file alive at that snapshot (r4 advisor
    finding: mutate-then-check dropped files on the False path)."""
    from delta_tpu.ops.state_cache import ResidentState

    n = 4
    lanes = {
        "min": np.arange(n, dtype=np.float64)[None, :],
        "max": (np.arange(n, dtype=np.float64) + 1.0)[None, :],
        "size": np.ones(n, np.int64),
    }
    e = ResidentState("log", "mid", 7, ["a"], [f"p{i}" for i in range(n)], lanes)
    e.capacity = n  # shrink so the single append below overflows
    added = (["q0"], np.zeros((1, 1)), np.ones((1, 1)), np.ones(1, np.int64))
    assert e.apply_tail(8, ["p1", "p2"], added) is False
    assert e.version == 7
    assert e.h_alive.all()
    assert e.path_to_row == {f"p{i}": i for i in range(n)}
    assert e._dead == 0
    # a full-range plan at the old version still returns all 4 files
    rs = RangeSet(np.array([np.nan]), np.array([np.nan]), verdict="all")
    plans = e.plan_ranges([rs], k=8, expected_version=7)
    assert plans is not None and plans[0].count == n


def test_max_entries_evicts_whole_tables(tmp_path):
    cache = DeviceStateCache.instance()
    logs = [_mk_table(str(tmp_path / f"m{i}"), n_files=1) for i in range(4)]
    with conf.set_temporarily(**{"delta.tpu.stateCache.maxEntries": "2"}):
        for lg in logs:
            cache.get(lg.update())
    assert len(cache._entries) <= 3  # keep + at most maxEntries


def test_plan_scans_stale_entry_falls_back(tmp_table):
    """plan_scans against an older snapshot than residency: per-query scan."""
    from delta_tpu.exec.scan import plan_scans, scan_files

    log = _mk_table(tmp_table, n_files=3)
    old = log.update()
    cache = DeviceStateCache.instance()
    cache.get(old)
    _mk_table(tmp_table, n_files=1, start=3)
    cache.get(log.update())  # advance residency past `old`
    plans = plan_scans(old, [["a >= 0"]], k=16)
    assert plans[0].via == "scan"
    assert set(plans[0].paths) == {f.path for f in scan_files(old, ["a >= 0"]).files}
