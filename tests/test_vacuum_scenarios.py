"""VACUUM scenario matrix under a controlled clock (≈ ``DeltaVacuumSuite``,
611 LoC, which drives ManualClock + a CheckFiles scenario DSL). The engine's
clock is injectable per DeltaLog; file mtimes are pinned with os.utime.
"""
import os

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.vacuum import VacuumCommand
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.utils.errors import DeltaIllegalArgumentError

HOUR = 3_600_000
WEEK = 7 * 24 * HOUR


class ManualClock:
    """Starts at REAL now: action timestamps (RemoveFile.deletion_timestamp,
    file mtimes) are wall-clock, so a manual clock must begin aligned with
    them and only ever advance."""

    def __init__(self, now_ms=None):
        import time

        self.now = int(time.time() * 1000) if now_ms is None else now_ms

    def __call__(self):
        return self.now

    def advance(self, ms):
        self.now += ms


def make(tmp_table, clock, partitioned=False):
    data = pa.table({
        "part": pa.array(["a", "a", "b"]),
        "x": pa.array([1, 2, 3], pa.int64()),
    })
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    t = DeltaTable.create(
        tmp_table, data=data,
        partition_columns=["part"] if partitioned else (),
    )
    assert t.delta_log is log
    return t


def data_file_paths(t):
    import urllib.parse

    return [urllib.parse.unquote(f.path) for f in t.delta_log.update().all_files]


def pin_mtime(root, rel, ts_ms):
    os.utime(os.path.join(root, rel), (ts_ms / 1000, ts_ms / 1000))


def test_live_files_never_deleted(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    before = set(data_file_paths(t))
    clock.advance(52 * WEEK)
    r = t.vacuum()
    assert r.files_deleted == 0
    assert set(data_file_paths(t)) == before


def test_removed_file_kept_within_retention_deleted_after(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    [old] = data_file_paths(t)
    t.delete()  # tombstones the file at clock.now
    # within the default 1-week tombstone retention: kept
    clock.advance(2 * HOUR)
    assert t.vacuum().files_deleted == 0
    assert os.path.exists(os.path.join(tmp_table, old))
    # beyond retention: deleted (mtime is real wall time, well before the
    # advanced clock's cutoff)
    clock.advance(2 * WEEK)
    r = t.vacuum()
    assert r.files_deleted == 1
    assert not os.path.exists(os.path.join(tmp_table, old))


def test_dry_run_reports_without_deleting(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    [old] = data_file_paths(t)
    t.delete()
    clock.advance(2 * WEEK)
    r = t.vacuum(dry_run=True)
    assert r.files_deleted == 1 and r.deleted_paths == [old]
    assert os.path.exists(os.path.join(tmp_table, old))


def test_untracked_junk_deleted_after_retention(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    junk = os.path.join(tmp_table, "junk.parquet")
    with open(junk, "wb") as f:
        f.write(b"zz")
    # fresh junk (uncommitted in-flight write): kept
    assert t.vacuum().files_deleted == 0
    clock.advance(2 * WEEK)
    r = t.vacuum()
    assert r.files_deleted == 1
    assert not os.path.exists(junk)


def test_hidden_dirs_untouched(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    hidden = os.path.join(tmp_table, "_internal", "x.bin")
    os.makedirs(os.path.dirname(hidden))
    with open(hidden, "wb") as f:
        f.write(b"zz")
    pin_mtime(tmp_table, "_internal/x.bin", 0)
    clock.advance(2 * WEEK)
    t.vacuum()
    assert os.path.exists(hidden), "underscore-dirs are invisible to vacuum"
    assert os.path.exists(os.path.join(tmp_table, "_delta_log"))


def test_empty_partition_dirs_removed(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock, partitioned=True)
    t.delete("part = 'a'")
    clock.advance(2 * WEEK)
    r = t.vacuum()
    assert r.files_deleted == 1
    assert r.dirs_deleted >= 1
    assert not os.path.exists(os.path.join(tmp_table, "part=a"))
    assert os.path.exists(os.path.join(tmp_table, "part=b"))


def test_retention_shorter_than_tombstone_retention_rejected(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    with pytest.raises(DeltaIllegalArgumentError):
        t.vacuum(retention_hours=1)
    # explicit opt-out works (the reference's retentionDurationCheck)
    t.vacuum(retention_hours=1, retention_check_enabled=False)


def test_custom_tombstone_retention_property(tmp_table):
    clock = ManualClock()
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"x": pa.array([1], pa.int64())}),
        configuration={"delta.deletedFileRetentionDuration": "interval 1 hour"},
    )
    [old] = data_file_paths(t)
    t.delete()
    clock.advance(2 * HOUR)  # past the 1-hour property, within default week
    r = t.vacuum()
    assert r.files_deleted == 1
    assert not os.path.exists(os.path.join(tmp_table, old))


def test_vacuum_breaks_time_travel_to_removed_files(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    v0 = t.version
    t.delete()
    clock.advance(2 * WEEK)
    t.vacuum()
    with pytest.raises(FileNotFoundError):
        t.to_arrow(version=v0)


def test_vacuum_metrics_and_result_shape(tmp_table):
    clock = ManualClock()
    t = make(tmp_table, clock)
    r = t.vacuum(dry_run=True)
    assert r.path == tmp_table
    assert r.retention_ms == WEEK
    assert r.dry_run is True


def test_expired_dv_sidecar_deleted_with_its_file(tmp_table, monkeypatch):
    from delta_tpu.protocol import deletion_vectors as dv_mod

    monkeypatch.setattr(dv_mod, "INLINE_THRESHOLD_BYTES", 0)
    clock = ManualClock()
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"x": pa.array(range(100), pa.int64())}),
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    )
    t.delete("x % 2 = 0")  # DV sidecar
    side = [f for f in os.listdir(tmp_table) if f.startswith("deletion_vector_")]
    assert len(side) == 1
    # live DV: protected even past retention
    clock.advance(2 * WEEK)
    t.vacuum()
    assert os.path.exists(os.path.join(tmp_table, side[0]))
    # whole-file delete tombstones the add (and its DV); after retention both go
    t.delete()
    clock.advance(2 * WEEK)
    r = t.vacuum()
    assert not os.path.exists(os.path.join(tmp_table, side[0]))
