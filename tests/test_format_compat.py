"""Reverse-golden format compatibility: what THIS engine writes, checked
against the structures the reference writes (golden fixtures under
`/root/reference/core/src/test/resources/delta/`).

Forward direction (reading reference-written tables) lives in
`test_hardening.py`; this file is the reverse: commit-JSON key sets,
checkpoint column structure, `_last_checkpoint` shape, and file naming must
line up with the Spark-written golden log so the reference could load our
tables (modulo features it predates, which are protocol-gated).
"""
import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.protocol import filenames

GOLDEN = "/root/reference/core/src/test/resources/delta/delta-0.1.0/_delta_log"

needs_goldens = pytest.mark.skipif(
    not os.path.isdir(GOLDEN), reason="reference golden tables not mounted"
)


def build_table(tmp_table):
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array([1, 2], pa.int64()),
                       "value": pa.array(["a", "b"])}),
    )
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([3], pa.int64()), "value": pa.array(["c"]),
    })).run()
    t.delete("id = 1")
    t.delta_log.checkpoint()
    return t


def actions_by_key(path):
    out = {}
    with open(path) as f:
        for line in f:
            if line.strip():
                d = json.loads(line)
                [(k, v)] = d.items()
                out.setdefault(k, []).append(v)
    return out


def test_commit_json_key_sets_match_golden(tmp_table):
    """Every key our add/remove/metaData/protocol emit must be a key the
    reference understands (golden key sets ∪ spec'd optional keys)."""
    t = build_table(tmp_table)
    mine = {}
    for v in range(3):
        p = f"{t.delta_log.log_path}/{filenames.delta_file(v)}"
        for k, vs in actions_by_key(p).items():
            for d in vs:
                mine.setdefault(k, set()).update(d.keys())
    spec_keys = {
        "add": {"path", "partitionValues", "size", "modificationTime",
                "dataChange", "stats", "tags", "deletionVector"},
        "remove": {"path", "deletionTimestamp", "dataChange",
                   "extendedFileMetadata", "partitionValues", "size", "tags",
                   "deletionVector"},
        "metaData": {"id", "name", "description", "format", "schemaString",
                     "partitionColumns", "configuration", "createdTime"},
        "protocol": {"minReaderVersion", "minWriterVersion",
                     "readerFeatures", "writerFeatures"},
        "commitInfo": None,  # free-form provenance
        "txn": {"appId", "version", "lastUpdated"},
    }
    for kind, keys in mine.items():
        assert kind in spec_keys, f"unknown action kind {kind}"
        if spec_keys[kind] is not None:
            assert keys <= spec_keys[kind], (kind, keys - spec_keys[kind])


@needs_goldens
def test_metadata_schema_string_parses_like_golden(tmp_table):
    """schemaString uses the same type-json dialect as the golden table."""
    t = build_table(tmp_table)
    golden_meta = actions_by_key(os.path.join(GOLDEN, f"{0:020d}.json"))[
        "metaData"
    ][0]
    mine_meta = actions_by_key(
        f"{t.delta_log.log_path}/{filenames.delta_file(0)}"
    )["metaData"][0]
    g = json.loads(golden_meta["schemaString"])
    m = json.loads(mine_meta["schemaString"])
    assert m["type"] == g["type"] == "struct"
    assert set(m["fields"][0]) == set(g["fields"][0]) == {
        "name", "type", "nullable", "metadata"
    }
    assert mine_meta["format"] == {"provider": "parquet", "options": {}}


@needs_goldens
def test_checkpoint_columns_superset_of_golden(tmp_table):
    """Our checkpoint carries at least the golden checkpoint's columns with
    compatible nesting (extra nullable fields like deletionVector are fine —
    Parquet readers ignore unknown struct members)."""
    t = build_table(tmp_table)
    golden = pq.read_table(
        os.path.join(GOLDEN, f"{3:020d}.checkpoint.parquet")
    ).schema
    md = None
    for name in os.listdir(t.delta_log.log_path):
        if name.endswith(".checkpoint.parquet"):
            md = pq.read_table(os.path.join(t.delta_log.log_path, name)).schema
    assert md is not None
    assert set(golden.names) <= set(md.names)

    def field_names(schema, col):
        typ = schema.field(col).type
        return {typ.field(i).name for i in range(typ.num_fields)}

    for col in ("txn", "add", "remove", "metaData", "protocol"):
        assert field_names(golden, col) <= field_names(md, col), col


@needs_goldens
def test_last_checkpoint_shape_matches_golden(tmp_table):
    t = build_table(tmp_table)
    golden = json.loads(open(os.path.join(GOLDEN, "_last_checkpoint")).read())
    mine = json.loads(
        open(os.path.join(t.delta_log.log_path, "_last_checkpoint")).read()
    )
    assert set(golden) <= set(mine) | {"parts"}
    assert isinstance(mine["version"], int) and isinstance(mine["size"], int)


@needs_goldens
def test_file_naming_matches_golden_convention(tmp_table):
    t = build_table(tmp_table)
    names = sorted(os.listdir(t.delta_log.log_path))
    golden_names = sorted(os.listdir(GOLDEN))
    # same zero-padding and suffixes
    assert f"{0:020d}.json" in names and f"{0:020d}.json" in golden_names
    assert any(n.endswith(".checkpoint.parquet") for n in names)
    for n in names:
        assert (
            n.endswith(".json") or ".checkpoint" in n or n.endswith(".crc")
            or n == "_last_checkpoint"
        ), n


@needs_goldens
def test_golden_log_replays_identically_through_both_paths(tmp_table):
    """The golden table's state must reconstruct the same through our
    columnar path and the pure-Python oracle replay."""
    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.log.replay import LogReplay
    from delta_tpu.protocol.actions import AddFile, actions_from_lines

    root = os.path.dirname(GOLDEN)
    log = DeltaLog.for_table(root)
    columnar_paths = {f.path for f in log.update().all_files}

    replay = LogReplay()
    for v in range(4):
        with open(os.path.join(GOLDEN, f"{v:020d}.json")) as f:
            replay.append(v, actions_from_lines(f))
    oracle_paths = {
        a.path for a in replay.checkpoint_actions() if isinstance(a, AddFile)
    }
    assert columnar_paths == oracle_paths
    assert len(columnar_paths) == 3
