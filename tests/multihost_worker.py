"""Worker for the 2-process DCN integration test (`test_multihost.py`).

Each process joins a real `jax.distributed` CPU cluster, then drives the
engine's multi-host paths against a SHARED table directory — the
coordination model is the store, not RPC (SURVEY §2.8):

  scan        — each host decodes its strided partition of the file list
  checkpoint  — each host writes its slice of the parts; proc 0 publishes
                `_last_checkpoint` after all parts are visible
  convert     — each host footers/stats its slice; proc 0 gathers the
                fragments from the store and commits
  vacuum      — each host deletes its slice of the expired files

``dist`` mode drives the sharded-execution plane instead: each host takes
its byte-weighted LPT slice of the OPTIMIZE bin-pack groups and commits its
own rearrange-only transaction, then proc 0 runs a probe-restricted MERGE.
``dist-crash`` kills proc 1 with a SimulatedCrash mid-OPTIMIZE (no cluster
join — the store is the coordination model, and a dead peer must not hang
the survivor's jax.distributed teardown).

Results land in <out>/result-<proc>.json for the parent to assert.
"""
import json
import os
import sys
import time


def _barrier(out_dir: str, name: str, proc: int, n_procs: int) -> None:
    """Store-based barrier: marker files on the shared directory."""
    open(os.path.join(out_dir, f"{name}-{proc}"), "w").close()
    deadline = time.time() + 60
    while not all(
        os.path.exists(os.path.join(out_dir, f"{name}-{i}"))
        for i in range(n_procs)
    ):
        if time.time() > deadline:
            raise TimeoutError(f"barrier {name} timed out on proc {proc}")
        time.sleep(0.05)


def dist_body(proc: int, n_procs: int, table: str, out_dir: str,
              crash: bool) -> None:
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.exec.scan import scan_to_table

    # distributed tracing: the parent exports DELTA_TPU_TRACEPARENT (adopted
    # lazily by telemetry itself) and the spool directory; with the dir set,
    # every span this worker runs lands in its own JSONL spool for the
    # parent's collector to stitch
    trace_dir = os.environ.get("DELTA_TPU_TRACE_DIR")
    if trace_dir:
        from delta_tpu.utils.config import conf as _conf

        _conf.set("delta.tpu.trace.dir", trace_dir)

    result = {"proc": proc}
    log = DeltaLog.for_table(table)
    snap = log.update()

    # sharded scan: the byte-weighted LPT partitions tile the table
    part = scan_to_table(snap, distribute=True)
    result["scan_ids"] = sorted(part.column("id").to_pylist())

    if crash and proc == 1:
        # SimulatedCrash (a BaseException) mid-job: fires on this host's
        # SECOND group rewrite, after real work started but before commit
        from delta_tpu.exec import write as write_exec
        from delta_tpu.storage.faults import SimulatedCrash

        orig = write_exec.write_files
        state = {"n": 0}

        def crashing(*a, **k):
            state["n"] += 1
            if state["n"] >= 2:
                raise SimulatedCrash("dist.optimize.rewrite")
            return orig(*a, **k)

        write_exec.write_files = crashing

    cmd = OptimizeCommand(log, min_file_size=1 << 30, workers=2,
                          distribute=True)
    version = cmd.run()
    result["optimize_version"] = version
    result["optimize_groups"] = (
        len(cmd.shard_report.results) if cmd.shard_report else 0)
    result["shard_timings"] = (
        cmd.shard_report.timings() if cmd.shard_report else [])

    if not crash:
        _barrier(out_dir, "opt", proc, n_procs)
        if proc == 0:
            from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
            from delta_tpu.utils.config import conf

            DeltaLog.clear_cache()
            mlog = DeltaLog.for_table(table)
            src = pa.table({
                "id": pa.array([3, 75, 1000], pa.int64()),
                "part": pa.array(["p0", "p3", "p0"]),
                "v": pa.array([-1.0, -2.0, -3.0]),
            })
            with conf.set_temporarily(
                **{"delta.tpu.distributed.merge.probe.minFiles": 2}
            ):
                m = MergeIntoCommand(
                    mlog, src, "t.id = s.id",
                    [MergeClause("update", assignments=None)],
                    [MergeClause("insert", assignments=None)],
                    source_alias="s", target_alias="t")
                m.run()
            result["merge_updated"] = m.metrics["numTargetRowsUpdated"]
            result["merge_inserted"] = m.metrics["numTargetRowsInserted"]
            result["merge_probed"] = "probe_ms" in m.phase_ms
        _barrier(out_dir, "merge", proc, n_procs)

    DeltaLog.clear_cache()
    fsnap = DeltaLog.for_table(table).update()
    final = scan_to_table(fsnap)
    result["final_ids"] = sorted(final.column("id").to_pylist())
    result["final_files"] = fsnap.num_of_files
    result["final_version"] = fsnap.version

    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


def main() -> None:
    proc = int(sys.argv[1])
    n_procs = int(sys.argv[2])
    port = sys.argv[3]
    table = sys.argv[4]
    convert_dir = sys.argv[5]
    out_dir = sys.argv[6]
    mode = sys.argv[7] if len(sys.argv) > 7 else "classic"

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from delta_tpu.parallel import distributed as dist

    if mode == "dist-crash":
        # no cluster join: a peer that dies mid-job must not hang the
        # survivor's jax.distributed teardown; slicing reads process_info
        dist.process_info = lambda: (proc, n_procs)
        dist_body(proc, n_procs, table, out_dir, crash=True)
        return

    pid, count = dist.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=n_procs,
        process_id=proc,
    )
    assert (pid, count) == (proc, n_procs), (pid, count)

    if mode == "dist":
        dist_body(proc, n_procs, table, out_dir, crash=False)
        return

    from delta_tpu import DeltaLog
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.log import checkpoints as ckpt_mod

    result = {"proc": proc, "count": count}

    # -- scan: this host's partition of the pruned file list --------------
    log = DeltaLog.for_table(table)
    snap = log.update()
    part = scan_to_table(snap, distribute=True)
    full = scan_to_table(snap)
    result["scan_rows"] = part.num_rows
    result["scan_ids"] = sorted(part.column("id").to_pylist())
    result["full_rows"] = full.num_rows

    # -- checkpoint: each host writes its slice of the parts --------------
    md = ckpt_mod.write_checkpoint(
        log.store, log.log_path, snap.version, snap.checkpoint_actions(),
        parts=4, distribute=True,
    )
    result["ckpt_parts"] = md.parts

    # -- convert: fragment exchange through the store ---------------------
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    clog = DeltaLog.for_table(convert_dir)
    version = ConvertToDeltaCommand(
        clog, collect_stats=True, distribute=True
    ).run()
    result["convert_version"] = version
    DeltaLog.clear_cache()
    csnap = DeltaLog.for_table(convert_dir).update()
    result["convert_files"] = csnap.num_of_files

    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
