"""CREATE / REPLACE / CTAS table command.

Mirrors `commands/CreateDeltaTableCommand.scala` (448 LoC): one command
covering CREATE TABLE (empty), CREATE TABLE AS SELECT, REPLACE TABLE and
CREATE OR REPLACE, with existing-location reconciliation:

* CREATE on an existing table errors; IF NOT EXISTS is a no-op — but if a
  schema was given it must match the existing table's (reconciliation, the
  reference's `verifyTableMetadata`);
* REPLACE requires an existing table (CREATE OR REPLACE does not), stages
  fresh metadata, and removes every live file — all in ONE commit, so
  readers never observe a dropped table;
* CTAS writes the query result's files in the same commit.

Unlike the round-1 `DeltaTable.create` (an empty Arrow write), metadata is
committed from the caller's ``StructType`` directly, so schema field
metadata — generation expressions, invariants, comments — survives.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from delta_tpu.commands import operations as ops
from delta_tpu.exec import write as write_exec
from delta_tpu.protocol.actions import Action, Metadata
from delta_tpu.schema.types import StructType
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaIllegalArgumentError,
)
from delta_tpu.utils import errors

__all__ = ["CreateDeltaTableCommand"]

_MODES = ("create", "create_if_not_exists", "replace", "create_or_replace")


class CreateDeltaTableCommand:
    def __init__(
        self,
        delta_log,
        schema: Optional[StructType] = None,
        mode: str = "create",
        partition_columns: Sequence[str] = (),
        configuration: Optional[Dict[str, str]] = None,
        data: Any = None,
        name: Optional[str] = None,
        description: Optional[str] = None,
    ):
        if mode not in _MODES:
            raise DeltaIllegalArgumentError(
                f"Unknown create mode {mode!r} (expected one of {_MODES})"
            )
        if schema is None and data is None:
            raise DeltaAnalysisError(
                "CREATE TABLE requires a schema or data (CTAS)"
            )
        self.delta_log = delta_log
        if schema is not None:
            # char/varchar declare as STRING + type-string field metadata on
            # the wire (CharVarcharUtils.scala:35-60); lengths enforce on
            # every write (schema/char_varchar.py)
            from delta_tpu.schema.char_varchar import (
                replace_char_varchar_with_string,
            )

            schema = replace_char_varchar_with_string(schema)
        self.schema = schema
        self.mode = mode
        self.partition_columns = list(partition_columns)
        self.configuration = dict(configuration or {})
        self.name = name
        self.description = description
        if data is not None:
            from delta_tpu.commands.write import coerce_to_table

            self.data = coerce_to_table(data)
            if schema is None:
                from delta_tpu.schema.arrow_interop import schema_from_arrow

                self.schema = schema_from_arrow(self.data.schema)
        else:
            self.data = None

    # -- reconciliation ----------------------------------------------------

    def _reconcile_existing(self, existing_meta) -> None:
        """CREATE against an existing table: the provided description must
        agree with what is on disk (`CreateDeltaTableCommand.scala`
        verifyTableMetadata)."""
        if self.schema is not None and existing_meta.schema_string is not None:
            existing = existing_meta.schema
            if existing.to_json() != self.schema.to_json():
                raise DeltaAnalysisError(
                    "The specified schema does not match the existing schema "
                    f"at {self.delta_log.data_path}.\n"
                    f"== Specified ==\n{self.schema.simple_string()}\n"
                    f"== Existing ==\n{existing.simple_string()}"
                )
        if self.partition_columns and list(existing_meta.partition_columns) != self.partition_columns:
            raise DeltaAnalysisError(
                "The specified partitioning does not match the existing "
                f"partitioning at {self.delta_log.data_path}: "
                f"{self.partition_columns} vs {list(existing_meta.partition_columns)}"
            )
        for k, v in self.configuration.items():
            if existing_meta.configuration.get(k) != v:
                raise DeltaAnalysisError(
                    "The specified properties do not match the existing "
                    f"properties at {self.delta_log.data_path} (key {k!r})"
                )

    # -- main --------------------------------------------------------------

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.utility.createTable", mode=self.mode,
                              path=self.delta_log.data_path):
            return self._run_impl()

    def _run_impl(self) -> int:
        log = self.delta_log
        # pre-checks run on the current snapshot for fast failure, but the
        # authoritative existence read happens INSIDE the transaction (from
        # its pinned snapshot) — a table created concurrently between this
        # check and the commit is then caught by conflict detection instead
        # of slipping past a stale `exists` flag
        exists = log.update().version >= 0
        if exists:
            if self.mode == "create":
                raise errors.table_already_exists(log.data_path)
            if self.mode == "create_if_not_exists":
                self._reconcile_existing(log.snapshot.metadata)
                return log.snapshot.version
        elif self.mode == "replace":
            raise errors.replace_requires_existing_table(log.data_path)

        def body(txn) -> int:
            exists_now = txn.snapshot.version >= 0
            if exists_now and self.mode == "create":
                raise errors.table_already_exists(log.data_path)
            if exists_now and self.mode == "create_if_not_exists":
                self._reconcile_existing(txn.snapshot.metadata)
                return txn.snapshot.version
            metadata = Metadata(
                name=self.name,
                description=self.description,
                schema_string=self.schema.to_json(),
                partition_columns=self.partition_columns,
                configuration=self.configuration,
            )
            txn.update_metadata(metadata)
            actions: List[Action] = []
            replacing = exists_now and self.mode in ("replace", "create_or_replace")
            if replacing:
                actions.extend(f.remove() for f in txn.filter_files())
            if self.data is not None and self.data.num_rows:
                adds = write_exec.write_files(
                    log.data_path, self.data, txn.metadata, data_change=True
                )
                actions.extend(adds)
                txn.report_metrics(
                    numFiles=len(adds),
                    numOutputBytes=sum(a.size or 0 for a in adds),
                    numOutputRows=self.data.num_rows,
                )
            if replacing:
                op = ops.ReplaceTable(
                    txn.metadata,
                    or_create=self.mode == "create_or_replace",
                    as_select=self.data is not None,
                )
            else:
                op = ops.CreateTable(txn.metadata, as_select=self.data is not None)
            return txn.commit(actions, op)

        return log.with_new_transaction(body)
