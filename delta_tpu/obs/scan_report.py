"""Per-query scan reports — the EXPLAIN-style counterpart of the reference's
``DataSkippingReader`` metrics.

The process-wide ``scan.*`` counters aggregate across every query; a
:class:`ScanReport` answers "what did THIS query cost": files considered vs
pruned at the file tier, row groups total/pruned/late-skipped at the Parquet
tier, bytes read vs skipped, per-phase durations, and the residual predicate
IR. ``exec/scan.scan_to_table`` opens a report (contextvar-scoped, so
concurrent scans on different threads never cross), ``read_files_as_table``
contributes the row-group numbers from the same sums that feed the
``scan.rowgroups.*`` counters — the report and the counters can never
disagree — and the finished report is retrievable via
:func:`last_scan_report` and attached to the ``delta.scan`` span.

Zero-overhead when ``delta.tpu.telemetry.enabled=false``: no report is
opened, and :func:`contribute` is a single contextvar probe.
"""
from __future__ import annotations

import contextvars
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["ScanReport", "last_scan_report", "clear_last_report",
           "start_report", "current_report", "contribute",
           "record_rewrite_fired", "finish_report"]


@dataclass
class ScanReport:
    """One query's skipping ledger. Row-group and byte numbers are the exact
    per-scan deltas of the ``scan.rowgroups.*`` / ``scan.bytes.*`` counters."""

    path: str = ""
    version: int = -1
    predicate: Optional[str] = None  # residual predicate IR (SQL repr)
    columns: Optional[List[str]] = None
    files_total: int = 0            # snapshot files considered
    files_after_partition: int = 0  # survivors of partition pruning
    files_scanned: int = 0          # survivors of file-tier stats skipping
    row_groups_total: int = 0
    row_groups_pruned: int = 0        # footer-stats tier
    row_groups_late_skipped: int = 0  # late-materialization tier
    #: row groups whose device residual mask came back all-False — skipped
    #: without the host ever decoding them (ops/column_cache path)
    row_groups_device_skipped: int = 0
    bytes_read: int = 0
    bytes_skipped: int = 0
    #: the slice of ``bytes_skipped`` the footer-stats PLANNER avoided
    #: (row groups never opened); the remainder is late materialization
    bytes_skipped_planned: int = 0
    #: the slice of ``bytes_skipped`` the DEVICE mask avoided (all-False
    #: row groups) — disjoint from the host late-materialization slice
    bytes_device_skipped: int = 0
    #: row-group bytes decoded on host because the device mask kept at
    #: least one of their rows — the device path's survivor fetch, counted
    #: separately from plain host-decoded bytes
    bytes_device_survivor: int = 0
    #: ``"device"`` when the jitted residual path served this scan; None on
    #: the pure host path (declined / fallback / not attempted)
    device_residual: Optional[str] = None
    rows_out: int = 0
    phase_ms: Dict[str, int] = field(default_factory=dict)
    #: synthesized predicate rewrites (expr/synthesis) that excluded at
    #: least one file or row group this scan: {family, conjunct, rewrite}
    #: with shape fingerprints; one entry per (family, conjunct), matching
    #: the ``scan.rewrites.fired`` counter delta by construction
    rewrites_fired: List[Dict[str, str]] = field(default_factory=list)

    @property
    def files_pruned(self) -> int:
        return max(0, self.files_total - self.files_scanned)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "version": self.version,
            "predicate": self.predicate,
            "columns": list(self.columns) if self.columns is not None else None,
            "filesTotal": self.files_total,
            "filesAfterPartition": self.files_after_partition,
            "filesScanned": self.files_scanned,
            "filesPruned": self.files_pruned,
            "rowGroupsTotal": self.row_groups_total,
            "rowGroupsPruned": self.row_groups_pruned,
            "rowGroupsLateSkipped": self.row_groups_late_skipped,
            "rowGroupsDeviceSkipped": self.row_groups_device_skipped,
            "bytesRead": self.bytes_read,
            "bytesSkipped": self.bytes_skipped,
            "bytesSkippedPlanned": self.bytes_skipped_planned,
            "bytesDeviceSkipped": self.bytes_device_skipped,
            "bytesDeviceSurvivor": self.bytes_device_survivor,
            "deviceResidual": self.device_residual,
            "rowsOut": self.rows_out,
            "phaseMs": dict(self.phase_ms),
            "rewritesFired": [dict(f) for f in self.rewrites_fired],
        }


# the report being filled by the scan running in THIS context
_CURRENT: "contextvars.ContextVar[Optional[ScanReport]]" = contextvars.ContextVar(
    "delta_obs_scan_report", default=None
)
# last finished report, process-wide (operator pull surface)
_LAST_LOCK = threading.Lock()
_LAST: Optional[ScanReport] = None


def start_report(path: str, version: int) -> "contextvars.Token":
    """Open a report for the scan running in this context; returns the
    contextvar token for :func:`finish_report`."""
    return _CURRENT.set(ScanReport(path=path, version=version))


def current_report() -> Optional[ScanReport]:
    """The report being filled by the scan in THIS context, if any."""
    return _CURRENT.get()


def contribute(**deltas: int) -> None:
    """Add row-group / byte tallies into the in-flight report, if any —
    called from ``read_files_as_table`` with the same sums that bump the
    process counters. Field names are ``ScanReport`` attributes."""
    rep = _CURRENT.get()
    if rep is None:
        return
    for k, v in deltas.items():
        setattr(rep, k, getattr(rep, k) + v)


def record_rewrite_fired(family: str, conjunct: str, rewrite: str) -> None:
    """Attribute one fired synthesized rewrite (both pruning tiers call
    this with shape fingerprints). Deduped per (family, conjunct) within
    the in-flight report — a conjunct that fires at the file tier AND the
    row-group tier is one workload fact, not two — and the
    ``scan.rewrites.fired`` counter bumps exactly once per appended entry,
    so ``last_scan_report().rewritesFired`` matches the counter delta by
    construction. Without an in-flight report (DML reads, blackout) the
    counter still counts the event."""
    from delta_tpu.utils.telemetry import bump_counter

    rep = _CURRENT.get()
    if rep is not None:
        if any(f.get("family") == family and f.get("conjunct") == conjunct
               for f in rep.rewrites_fired):
            return
        rep.rewrites_fired.append(
            {"family": family, "conjunct": conjunct, "rewrite": rewrite})
    bump_counter("scan.rewrites.fired")


def finish_report(token: "contextvars.Token",
                  completed: bool = True) -> Optional[ScanReport]:
    """Close the in-flight report. ``completed=True`` publishes it as
    :func:`last_scan_report`; a failed scan passes ``False`` so a
    half-filled report never overwrites the last genuinely completed one."""
    global _LAST
    rep = _CURRENT.get()
    _CURRENT.reset(token)
    if rep is not None and completed:
        with _LAST_LOCK:
            _LAST = rep
    return rep


def last_scan_report() -> Optional[ScanReport]:
    """The most recently completed scan's report (None before any scan, or
    while telemetry is disabled)."""
    with _LAST_LOCK:
        return _LAST


def clear_last_report() -> None:
    global _LAST
    with _LAST_LOCK:
        _LAST = None
