"""SQL tokenizer for the Delta statement front end.

The reference parses its statements with a real ANTLR grammar
(`antlr4/.../DeltaSqlBase.g4`); the round-1 regex matcher mis-parsed quoted
strings containing keywords, comments, and newlines. This lexer produces a
proper token stream — with source offsets, so embedded expressions (WHERE /
SET / CHECK bodies) can be sliced out verbatim for the expression parser.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

from delta_tpu.utils.errors import DeltaParseError
from delta_tpu.utils import errors

__all__ = ["Token", "tokenize"]


@dataclass(frozen=True)
class Token:
    kind: str  # WORD | QUOTED_IDENT | STRING | NUMBER | PUNCT | END
    value: str  # normalized text (keywords upper-cased via .upper() at use)
    start: int  # offset of first char in source
    end: int  # offset past last char

    def is_word(self, *words: str) -> bool:
        return self.kind == "WORD" and self.value.upper() in words


_PUNCT = set("(),.=*<>!+-/%;")


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise DeltaParseError("Unterminated block comment")
            i = j + 2
            continue
        if c == "`":
            j = i + 1
            while j < n and sql[j] != "`":
                j += 1
            if j >= n:
                raise DeltaParseError("Unterminated backquoted identifier")
            out.append(Token("QUOTED_IDENT", sql[i + 1 : j], i, j + 1))
            i = j + 1
            continue
        if c in "'\"":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == c:
                    if j + 1 < n and sql[j + 1] == c:  # doubled-quote escape
                        buf.append(c)
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise DeltaParseError("Unterminated string literal")
            out.append(Token("STRING", "".join(buf), i, j + 1))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            while j < n and (sql[j].isdigit() or sql[j] in ".eE+-"):
                # stop a trailing +/- that isn't an exponent sign
                if sql[j] in "+-" and sql[j - 1] not in "eE":
                    break
                j += 1
            out.append(Token("NUMBER", sql[i:j], i, j))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            out.append(Token("WORD", sql[i:j], i, j))
            i = j
            continue
        if c in _PUNCT:
            out.append(Token("PUNCT", c, i, i + 1))
            i += 1
            continue
        raise errors.sql_unexpected_character(c, i)
    out.append(Token("END", "", n, n))
    return out
