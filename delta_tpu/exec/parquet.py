"""Parquet read/write executor (host data plane, Arrow C++ underneath).

The role Spark's `ParquetFileFormat` + `FileFormatWriter` play in the
reference (`files/TransactionalWrite.scala:182-192`, `DeltaFileFormat.scala`)
— encode/decode Parquet, collect per-file column stats — lands on Arrow's
native Parquet module here. Stats collection follows the protocol's
per-column ``minValues``/``maxValues``/``nullCount`` + ``numRecords`` schema
(`PROTOCOL.md:441-480`), truncated to the first
``dataSkippingNumIndexedCols`` leaf columns (`DeltaConfig.scala:383`).
"""
from __future__ import annotations

import datetime as _dt
import decimal as _decimal
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

__all__ = [
    "write_parquet_file",
    "read_parquet_files",
    "collect_stats",
    "stats_json",
    "json_stat_value",
]


def json_stat_value(v: Any, round_up: bool = False) -> Any:
    """Encode one Python min/max value for the protocol's JSON stats —
    shared by the decode path (:func:`collect_stats`) and the footer path
    (`exec.rowgroups.stats_from_footer`), so both emit identical bounds."""
    if isinstance(v, _dt.datetime):
        if round_up and v.microsecond % 1000:
            # maxValues truncated to ms must round UP or data skipping would
            # prune files containing sub-millisecond maxima
            v = v + _dt.timedelta(microseconds=1000 - v.microsecond % 1000)
        return v.strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
    if isinstance(v, _dt.date):
        return v.isoformat()
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return None
    if isinstance(v, bytes):
        return None  # binary stats not representable in JSON stats
    if isinstance(v, _decimal.Decimal):
        # JSON can't carry exact decimals as numbers; a float conversion can
        # shift the bound inward (wrongly pruning matching files) and an
        # outward nudge breaks the column's scale for the V2 stats_parsed
        # struct — absent bounds are the only always-safe encoding
        return None
    return v


def _stat_value(scalar: pa.Scalar, round_up: bool = False) -> Any:
    return json_stat_value(scalar.as_py(), round_up)


def collect_stats(table: pa.Table, num_indexed_cols: int = 32) -> Dict[str, Any]:
    """Per-file stats over the first ``num_indexed_cols`` leaf columns."""
    mins: Dict[str, Any] = {}
    maxs: Dict[str, Any] = {}
    nulls: Dict[str, Any] = {}
    for name in table.column_names[: num_indexed_cols if num_indexed_cols >= 0 else None]:
        col = table.column(name)
        nulls[name] = col.null_count
        t = col.type
        skippable = (
            pa.types.is_integer(t)
            or pa.types.is_floating(t)
            or pa.types.is_string(t)
            or pa.types.is_date(t)
            or pa.types.is_timestamp(t)
            or pa.types.is_boolean(t)
            or pa.types.is_decimal(t)
        )
        if not skippable or col.null_count == len(col):
            continue
        try:
            mn = _stat_value(pc.min(col))
            mx = _stat_value(pc.max(col), round_up=True)
        except pa.ArrowNotImplementedError:
            continue
        if mn is not None:
            mins[name] = mn
        if mx is not None:
            maxs[name] = mx
    return {
        "numRecords": table.num_rows,
        "minValues": mins,
        "maxValues": maxs,
        "nullCount": nulls,
    }


def stats_json(table: pa.Table, num_indexed_cols: int = 32) -> str:
    return json.dumps(collect_stats(table, num_indexed_cols))


def _compresses_well(col: pa.ChunkedArray, sample_bytes: int = 65536) -> bool:
    """Cheap entropy probe: snappy-compress the first ~64KB of the column's
    raw buffers; ratio < 0.9 means compression earns its keep. High-entropy
    numerics (random keys, hashes) fail this and store uncompressed — snappy
    on incompressible int64 pages costs 4x encode / 14x decode for ~10%."""
    try:
        chunk = col.chunk(0) if col.num_chunks else None
        if chunk is None or len(chunk) == 0:
            return True
        # sample the DATA buffer (last) — the validity bitmap compresses to
        # nothing and would misjudge every nullable high-entropy column
        bufs = [b for b in chunk.buffers() if b is not None]
        if not bufs:
            return True
        data = bufs[-1]
        raw = bytes(data.slice(0, min(sample_bytes, data.size)))  # zero-copy slice
        if len(raw) < 1024:
            return True
        return len(pa.compress(raw, codec="snappy", asbytes=True)) < 0.9 * len(raw)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, IndexError):
        return True


def write_parquet_file(
    table: pa.Table, abs_path: str, compression: Optional[str] = None
) -> Tuple[int, int]:
    """Write one Parquet file; returns (size_bytes, mtime_ms).

    Encoding policy (measured on store_sales-shaped data, single host core):

    - dictionary pages only for string/binary columns — dictionary-encoding
      high-cardinality numerics bloats files and makes reads 4-5x slower;
    - BYTE_STREAM_SPLIT for float columns (faster encode, much faster
      decode, compresses as well as plain+snappy). Gate with
      ``delta.tpu.write.byteStreamSplit=false`` for parquet-mr < 1.12
      readers (Spark <= 3.1);
    - per-column compression: snappy only where it earns its keep (strings,
      BYTE_STREAM_SPLIT float streams); high-entropy integer columns store
      uncompressed — snappy on random int64 pages costs 4x on encode and
      14x (!) on decode for a ~10% size win.

    ``delta.tpu.write.compression`` overrides: "auto" (policy above) or a
    codec name applied to every column."""
    from delta_tpu.utils.config import conf

    os.makedirs(os.path.dirname(abs_path), exist_ok=True)
    dict_cols = [
        f.name for f in table.schema
        if pa.types.is_string(f.type) or pa.types.is_large_string(f.type)
        or pa.types.is_binary(f.type)
    ]
    kwargs: Dict[str, Any] = {"use_dictionary": dict_cols or False}
    float_cols = [f.name for f in table.schema if pa.types.is_floating(f.type)]
    if float_cols and bool(conf.get("delta.tpu.write.byteStreamSplit", True)):
        kwargs["use_byte_stream_split"] = float_cols
    if compression is None:
        compression = str(conf.get("delta.tpu.write.compression", "auto"))
    if compression == "auto":
        codec: Any = {
            f.name: (
                "snappy"
                if f.name in dict_cols or f.name in float_cols
                or _compresses_well(table.column(f.name))
                else "none"
            )
            for f in table.schema
        }
    else:
        codec = compression
    # defragment before encode: heavily chunked tables (hash-join output,
    # many-block concats) encode one page set per chunk otherwise
    if table.num_rows and table.column(0).num_chunks > 8:
        table = table.combine_chunks()
    # bounded row groups are the skipping granule of the read path's second
    # pruning tier (exec/rowgroups): Arrow's 1Mi-row default would leave
    # most engine-written files as ONE group, with nothing to skip
    rg_rows = int(conf.get("delta.tpu.write.rowGroupRows", 131_072))
    if rg_rows > 0:
        kwargs["row_group_size"] = rg_rows
    pq.write_table(table, abs_path, compression=codec, **kwargs)
    st = os.stat(abs_path)
    from delta_tpu.utils.telemetry import bump_counter

    bump_counter("parquet.files.written")
    bump_counter("parquet.bytes.written", st.st_size)
    bump_counter("parquet.rows.written", table.num_rows)
    return st.st_size, int(st.st_mtime * 1000)


def read_parquet_files(
    abs_paths: Sequence[str],
    columns: Optional[Sequence[str]] = None,
    schema: Optional[pa.Schema] = None,
) -> List[pa.Table]:
    """Read data files; one table per file (callers attach partition values
    before concatenation). Files decode in parallel on a thread pool —
    Arrow's Parquet reader drops the GIL, the same host fan-out
    ``write_files``/``read_files_as_table`` already use."""

    def read_one(p: str) -> pa.Table:
        return pq.read_table(
            p, columns=list(columns) if columns else None, memory_map=True,
        )

    if len(abs_paths) <= 1:
        return [read_one(p) for p in abs_paths]
    from concurrent.futures import ThreadPoolExecutor

    from delta_tpu.utils import telemetry

    workers = min(len(abs_paths), os.cpu_count() or 4)
    # propagate the caller's span context into the pool: any span or event
    # a decode emits parents under the calling operation instead of
    # starting an orphan trace root in the worker thread
    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="delta-parquet-read"
    ) as pool:
        return list(pool.map(telemetry.propagated(read_one), abs_paths))
