"""Fault-tolerant distributed execution (ISSUE 20).

Unit and regression coverage for the supervision layer around
``parallel/executor.run_sharded`` and the multihost lease protocol
(``parallel/leases``): per-item retry + poison quarantine, heartbeat-driven
speculative re-dispatch (first completion wins), the degradation ladder,
the four ``dist.*`` fault points, and coordinator-side orphaned-slice
recovery / txnId reconciliation. The end-to-end subprocess version of the
crash-recovery scenario lives in ``test_multihost.py``; the seeded
whole-workload version in ``test_torture.py``.
"""
import json
import os
import threading
import time

import pyarrow as pa
import pytest

from delta_tpu.parallel import leases
from delta_tpu.parallel.executor import run_sharded
from delta_tpu.storage.faults import FaultPlan, SimulatedCrash
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf
from delta_tpu.utils.retries import TransientIOError


@pytest.fixture(autouse=True)
def _fresh_metrics():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


def _fast_retries(**over):
    kw = {
        "delta__tpu__distributed__retry__baseDelayMs": 1,
        "delta__tpu__distributed__retry__maxDelayMs": 5,
        "delta__tpu__distributed__retry__deadlineMs": 5_000,
    }
    kw.update(over)
    return conf.set_temporarily(**kw)


# -- retry + quarantine ------------------------------------------------------


def test_transient_failures_are_retried_to_success():
    calls = {}

    def fn(x):
        calls[x] = calls.get(x, 0) + 1
        if x == 2 and calls[x] == 1:
            raise TransientIOError("flaky once")
        return x * 10

    with _fast_retries():
        report = run_sharded([0, 1, 2, 3], fn, workers=2, label="t")
    assert report.results == [0, 10, 20, 30]
    assert report.retried == 1
    assert calls[2] == 2
    assert telemetry.counters("dist")["dist.items.retried"] == 1
    assert not report.quarantined


def test_exhausted_retries_quarantine_and_job_completes():
    def fn(x):
        if x == 1:
            raise TransientIOError("always down")
        return x

    with _fast_retries(delta__tpu__distributed__retry__maxAttempts=2):
        report = run_sharded([0, 1, 2], fn, workers=2, label="t",
                             on_failure="quarantine")
    assert report.results[0] == 0 and report.results[2] == 2
    assert report.results[1] is None
    [q] = report.quarantined
    assert q.index == 1 and q.attempts == 2
    assert "always down" in q.error
    assert report.quarantined_indices() == {1}
    assert telemetry.counters("dist")["dist.items.quarantined"] == 1


def test_permanent_error_never_retried():
    calls = {"n": 0}

    def fn(x):
        if x == 0:
            calls["n"] += 1
            raise ValueError("poison")
        return x

    with _fast_retries():
        report = run_sharded([0, 1], fn, workers=2, label="t",
                             on_failure="quarantine")
    assert calls["n"] == 1  # non-transient: a single attempt
    [q] = report.quarantined
    assert q.index == 0 and q.attempts == 1
    assert report.retried == 0


def test_on_failure_raise_aborts_with_partial_report():
    def fn(x):
        if x == 1:
            raise ValueError("poison")
        time.sleep(0.01)
        return x

    with _fast_retries():
        with pytest.raises(ValueError, match="poison") as ei:
            run_sharded([0, 1, 2, 3], fn, workers=2, label="t")
    report = ei.value.shard_report
    assert report is not None
    assert report.workers == 2


def test_invalid_on_failure_rejected():
    with pytest.raises(ValueError, match="on_failure"):
        run_sharded([1], lambda x: x, on_failure="retry")


def test_inline_path_retries_and_quarantines():
    """1 worker / 1 item runs with no pool — the retry and quarantine
    policies must apply identically."""
    calls = {"n": 0}

    def fn(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise TransientIOError("once")
        raise ValueError("then poison")

    with _fast_retries():
        report = run_sharded(["only"], fn, workers=1, label="t",
                             on_failure="quarantine")
    assert report.retried == 1
    assert report.quarantined[0].attempts == 2


# -- crash semantics (satellite 1) -------------------------------------------


def test_simulated_crash_pierces_quarantine():
    """A BaseException that is not an Exception is process death: never
    retried, never quarantined, always fatal."""
    def fn(x):
        if x == 1:
            raise SimulatedCrash("dist.itemExec")
        return x

    with _fast_retries():
        with pytest.raises(SimulatedCrash):
            run_sharded([0, 1, 2], fn, workers=2, label="t",
                        on_failure="quarantine")
    assert "dist.items.quarantined" not in telemetry.counters("dist")


def test_abort_drains_sibling_workers_before_reraise():
    """Regression (ISSUE 20 satellite): a mid-item crash re-raises only
    after every in-flight sibling drained, so the attached report carries
    every worker's finalized stats — including the sibling that was still
    busy when the crash hit."""
    sibling_done = threading.Event()

    def fn(x):
        if x == "slow":
            time.sleep(0.25)
            sibling_done.set()
            return "slow-done"
        time.sleep(0.02)
        raise SimulatedCrash("dist.itemExec")

    with _fast_retries():
        with pytest.raises(SimulatedCrash) as ei:
            run_sharded(["slow", "crash"], fn,
                        sizes=[100, 1], workers=2, label="t")
    assert sibling_done.is_set(), "sibling must have finished before re-raise"
    report = ei.value.shard_report
    busy = sum(s.busy_s for s in report.per_worker.values())
    assert busy >= 0.25, f"sibling's elapsed time missing from stats: {busy}"


# -- speculation -------------------------------------------------------------


def test_straggler_speculatively_redispatched_first_completion_wins():
    """A wedged first attempt is re-dispatched once its heartbeat age
    clears the priced timeout; the fresh attempt's completion resolves the
    item and the job does NOT wait for the wedged thread."""
    attempts = {}
    lock = threading.Lock()

    def fn(x):
        with lock:
            attempts[x] = attempts.get(x, 0) + 1
            mine = attempts[x]
        if x == 0 and mine == 1:
            time.sleep(2.0)  # the straggler: wedged well past the timeout
            return "late"
        return f"ok-{x}"

    with _fast_retries(
        delta__tpu__distributed__itemTimeoutMs=60,
        delta__tpu__distributed__supervisor__intervalMs=5,
        delta__tpu__distributed__speculation__slackFactor=1.0,
    ):
        t0 = time.perf_counter()
        report = run_sharded([0, 1, 2, 3], fn, workers=4, label="t")
        wall = time.perf_counter() - t0
    assert report.results[0] == "ok-0"  # the rescue's result, not "late"
    assert report.speculated >= 1
    assert report.rescued >= 1
    assert attempts[0] == 2
    assert wall < 1.5, f"job must not wait for the wedged attempt ({wall:.2f}s)"
    c = telemetry.counters("dist")
    assert c["dist.items.speculated"] >= 1
    assert c["dist.speculation.wins"] >= 1


def test_no_speculation_when_disabled():
    def fn(x):
        if x == 0:
            time.sleep(0.2)
        return x

    with _fast_retries(
        delta__tpu__distributed__itemTimeoutMs=20,
        delta__tpu__distributed__supervisor__intervalMs=5,
        delta__tpu__distributed__speculation__enabled=False,
    ):
        report = run_sharded([0, 1, 2], fn, workers=3, label="t")
    assert report.speculated == 0
    assert report.results == [0, 1, 2]


# -- fault points + degradation ladder ---------------------------------------


def test_item_exec_fault_point_drives_retry():
    plan = FaultPlan(script=[("dist.itemExec", "transient")])
    with _fast_retries(delta__tpu__faults__plan=plan):
        report = run_sharded([0, 1, 2, 3], lambda x: x, workers=2, label="t")
    assert not plan.script
    assert report.results == [0, 1, 2, 3]
    assert report.retried == 1


def test_worker_spawn_fault_survived_by_siblings():
    plan = FaultPlan(script=[("dist.workerSpawn", "transient")])
    with _fast_retries(delta__tpu__faults__plan=plan):
        report = run_sharded(list(range(8)), lambda x: x, workers=4,
                             label="t")
    assert not plan.script
    assert report.results == list(range(8))


def test_all_workers_lost_degrades_to_inline():
    plan = FaultPlan(
        script=[("dist.workerSpawn", "transient")] * 4)
    with _fast_retries(delta__tpu__faults__plan=plan):
        report = run_sharded(list(range(6)), lambda x: x, workers=4,
                             label="t")
    assert not plan.script
    assert report.results == list(range(6))
    assert report.degraded_inline == 6
    assert telemetry.counters("dist")["dist.degraded.pool"] == 1


def test_stale_worker_task_cannot_consume_next_jobs_fault_plan():
    # a lazily spawned pool thread can dequeue a worker task AFTER its job
    # already resolved (the main thread returns at resolved == n without
    # awaiting never-started tasks); run_sharded pins the fault plan at job
    # start, so a stale task's `dist.workerSpawn` fire draws from ITS job's
    # plan and can never consume script entries from the plan a LATER job
    # installed (cross-job fault leakage)
    from concurrent.futures import Future

    import delta_tpu.parallel.executor as ex

    captured = []

    class HoldLastPool(ex.ThreadPoolExecutor):
        def submit(self, fn, *args, **kwargs):
            if args and args[0] == 3:
                # withhold the last worker task: its items are rescued by
                # stealing, and the task body runs only when we say so
                captured.append(lambda: fn(*args, **kwargs))
                f = Future()
                f.set_result(None)
                return f
            return super().submit(fn, *args, **kwargs)

    orig_pool = ex.ThreadPoolExecutor
    ex.ThreadPoolExecutor = HoldLastPool
    try:
        with _fast_retries():
            report = run_sharded(list(range(6)), lambda x: x, workers=4,
                                 label="t")
    finally:
        ex.ThreadPoolExecutor = orig_pool
    assert report.results == list(range(6))
    assert len(captured) == 1

    plan = FaultPlan(script=[("dist.workerSpawn", "transient")] * 4)
    with _fast_retries(delta__tpu__faults__plan=plan):
        captured[0]()  # the stale task executes under the NEW job's plan
        assert len(plan.script) == 4, "stale worker consumed a script entry"
        report2 = run_sharded(list(range(6)), lambda x: x, workers=4,
                              label="t2")
    assert not plan.script
    assert report2.results == list(range(6))
    assert report2.degraded_inline == 6


def test_heartbeat_fault_is_benign():
    plan = FaultPlan(script=[("dist.heartbeat", "transient")])
    with _fast_retries(delta__tpu__faults__plan=plan):
        report = run_sharded(list(range(4)), lambda x: x, workers=2,
                             label="t")
    assert report.results == list(range(4))
    assert not report.quarantined


# -- leases ------------------------------------------------------------------


def _log_path(tmp_path) -> str:
    p = str(tmp_path / "_delta_log")
    os.makedirs(p, exist_ok=True)
    return p


def test_lease_write_heartbeat_clear_roundtrip(tmp_path):
    log_path = _log_path(tmp_path)
    path = leases.write_lease(log_path, "optimize@3", 1, {
        "txnId": "tok123", "groupKeys": [[["p", "1"]]], "readVersion": 3})
    assert path is not None and os.path.exists(path)
    [(got_path, body, mtime)] = leases.read_leases(log_path)
    assert got_path == path
    assert body["job"] == "optimize@3" and body["proc"] == 1
    assert body["txnId"] == "tok123" and body["pid"] == os.getpid()
    past = time.time() - 30
    os.utime(path, (past, past))
    leases.heartbeat_lease(path)
    assert os.stat(path).st_mtime > past + 25  # heartbeat refreshed mtime
    leases.clear_lease(path)
    assert not os.path.exists(path)
    assert leases.read_leases(log_path) == []


def test_lease_disabled_for_remote_paths_and_by_conf(tmp_path):
    assert not leases.enabled("s3://bucket/tbl/_delta_log")
    with conf.set_temporarily(delta__tpu__distributed__lease__enabled=False):
        assert leases.write_lease(_log_path(tmp_path), "j", 0, {}) is None


def test_lease_write_fault_degrades_uncovered(tmp_path):
    plan = FaultPlan(script=[("dist.leaseWrite", "transient")])
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        path = leases.write_lease(_log_path(tmp_path), "j", 0, {})
    assert path is None  # slice proceeds uncovered, not failed
    assert telemetry.counters("dist")["dist.degraded.lease"] == 1


def test_lease_write_crash_pierces(tmp_path):
    plan = FaultPlan(script=[("dist.leaseWrite", "crash_before_publish")])
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        with pytest.raises(SimulatedCrash):
            leases.write_lease(_log_path(tmp_path), "j", 0, {})


def test_torn_lease_file_skipped(tmp_path):
    log_path = _log_path(tmp_path)
    leases.write_lease(log_path, "j", 0, {"txnId": "t"})
    torn = os.path.join(leases.dist_dir(log_path),
                        f"lease-{int(time.time() * 1000):013d}-99999-1.json")
    with open(torn, "w", encoding="utf-8") as f:
        f.write('{"job": "j", "pro')  # half-written by a dying host
    bodies = leases.read_leases(log_path)
    assert len(bodies) == 1
    assert bodies[0][1]["proc"] == 0


def test_sweep_spares_own_live_lease_expires_dead_pids(tmp_path):
    """Satellite: the ``_dist/`` sweep shares the journal's liveness rule —
    this process's fresh lease is spared exactly like the journal's active
    segment, while a dead CI pid's stale lease expires (one immune lease
    per crashed run would grow the directory forever)."""
    log_path = _log_path(tmp_path)
    with conf.set_temporarily(delta__tpu__distributed__lease__ttlMs=1_000):
        own = leases.write_lease(log_path, "j", 0, {"txnId": "a"})
        ddir = leases.dist_dir(log_path)
        dead = os.path.join(ddir, "lease-0000000000001-999999-1.json")
        with open(dead, "w", encoding="utf-8") as f:
            json.dump({"job": "old", "pid": 999999}, f)
        past = time.time() - 10  # heartbeat 10s stale vs a 1s ttl
        os.utime(dead, (past, past))
        deleted = leases.sweep_leases(log_path)
    assert deleted == 1
    assert os.path.exists(own)
    assert not os.path.exists(dead)
    assert telemetry.counters("dist")["dist.lease.swept"] == 1


def test_sweep_spares_fresh_foreign_lease(tmp_path):
    """A foreign pid's lease with a LIVE heartbeat is not swept — the
    grace rule is heartbeat age, not pid ownership."""
    log_path = _log_path(tmp_path)
    ddir = leases.dist_dir(log_path)
    os.makedirs(ddir, exist_ok=True)
    fresh = os.path.join(ddir, "lease-0000000000002-999999-0.json")
    with open(fresh, "w", encoding="utf-8") as f:
        json.dump({"job": "peer", "pid": 999999}, f)
    assert leases.sweep_leases(log_path) == 0
    assert os.path.exists(fresh)


def test_live_writer_spared_shared_rule():
    """Unit test for the rule itself (obs/journal): newest file per
    embedded pid, only while touched within the grace window."""
    from delta_tpu.obs.journal import live_writer_spared

    now = time.time()
    stats = [
        ("j-0000000000001-111-a.log", 10, now),        # old file, pid 111
        ("j-0000000000002-111-b.log", 10, now),        # newest for pid 111
        ("j-0000000000003-222-a.log", 10, now - 500),  # newest but stale
    ]
    spared = live_writer_spared(stats, grace_s=60.0)
    assert spared == {"j-0000000000002-111-b.log"}


# -- end-to-end: quarantined OPTIMIZE + orphaned-slice recovery --------------


def _mk_partitioned_table(path: str, parts: int = 4, files_per_part: int = 3,
                          rows_per_file: int = 16):
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.log.deltalog import DeltaLog

    def batch(base):
        n = parts * rows_per_file
        return pa.table({
            "id": pa.array(range(base, base + n), pa.int64()),
            "part": pa.array([str(i % parts) for i in range(n)]),
        })

    DeltaTable.create(path, data=batch(0), partition_columns=["part"])
    log = DeltaLog.for_table(path)
    for i in range(1, files_per_part):
        WriteIntoDelta(log, "append", batch(i * parts * rows_per_file),
                       partition_columns=["part"]).run()
    return log


def _table_rows(log):
    from delta_tpu.exec.scan import scan_to_table

    return sorted(scan_to_table(log.update(), [], ["id"])
                  .column("id").to_pylist())


def test_optimize_quarantine_completes_commit_without_poison_group(tmp_path):
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.obs import journal

    path = str(tmp_path / "t")
    log = _mk_partitioned_table(path)
    before = _table_rows(log)
    plan = FaultPlan(script=[("dist.itemExec", "transient")])
    with _fast_retries(delta__tpu__faults__plan=plan,
                       delta__tpu__distributed__retry__maxAttempts=1):
        cmd = OptimizeCommand(log, workers=4, on_failure="quarantine")
        cmd.run()
    assert cmd.metrics["numQuarantinedGroups"] == 1
    assert len(cmd.shard_report.quarantined) == 1
    assert _table_rows(log) == before  # no committed row touched
    # the skipped group's files survive untouched: 4 partitions planned,
    # 3 rewritten, one left exactly as planned-around
    snap = log.update()
    per_part = {}
    for f in snap.all_files:
        key = tuple(sorted((f.partition_values or {}).items()))
        per_part[key] = per_part.get(key, 0) + 1
    assert sorted(per_part.values()) == [1, 1, 1, 3]
    journal.flush(log.log_path)
    ev = [e for e in journal.read_entries(log.log_path, kinds=("dist",))
          if e.get("event") == "dist.quarantine"]
    assert len(ev) == 1 and ev[0]["op"] == "optimize"
    assert ev[0]["items"][0]["attempts"] == 1


def _posed_optimize(log, proc: int, n_procs: int = 2, **kw):
    """Run a distributed OPTIMIZE posing as host ``proc`` of ``n_procs``."""
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.parallel import distributed as dist_mod

    cmd = OptimizeCommand(log, workers=2, distribute=True, **kw)
    orig = dist_mod.process_info
    dist_mod.process_info = lambda: (proc, n_procs)
    try:
        cmd.run()
    finally:
        dist_mod.process_info = orig
    return cmd


def _age_leases(log_path: str, by_s: float = 120.0):
    past = time.time() - by_s
    for p, _b, _m in leases.read_leases(log_path):
        os.utime(p, (past, past))


def test_orphaned_slice_recovered_by_coordinator(tmp_path):
    """Host 1 dies mid-rewrite (SimulatedCrash at dist.itemExec) leaving
    its lease behind; the coordinator's post-commit reconciliation re-plans
    the orphan's recorded group keys from a fresh snapshot and re-executes.
    End state: rows AND file topology identical to a single-process run."""
    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.obs import journal

    path = str(tmp_path / "t")
    ref_path = str(tmp_path / "ref")
    log = _mk_partitioned_table(path)
    ref_log = _mk_partitioned_table(ref_path)

    # reference: the same table optimized by one healthy process
    from delta_tpu.commands.optimize import OptimizeCommand

    OptimizeCommand(ref_log, workers=2).run()
    ref_rows = _table_rows(ref_log)
    ref_files = len(ref_log.update().all_files)

    # host 1 crashes mid-slice; its lease survives with a stale heartbeat
    plan = FaultPlan(script=[("dist.itemExec", "crash_before_publish")])
    with _fast_retries(delta__tpu__faults__plan=plan):
        with pytest.raises(SimulatedCrash):
            _posed_optimize(log, proc=1)
    assert len(leases.read_leases(log.log_path)) == 1
    _age_leases(log.log_path)

    # coordinator: commits its own slice, then recovers the orphan
    DeltaLog.invalidate_cache(path)
    log = DeltaLog(path)
    with conf.set_temporarily(
            delta__tpu__distributed__lease__settleMs=20):
        _posed_optimize(log, proc=0)

    assert _table_rows(log) == ref_rows
    assert len(log.update().all_files) == ref_files
    assert leases.read_leases(log.log_path) == []  # orphan cleared
    assert telemetry.counters("dist")["dist.slice.recovered"] == 1
    journal.flush(log.log_path)
    events = {e.get("event")
              for e in journal.read_entries(log.log_path, kinds=("dist",))}
    assert "dist.sliceRecovered" in events


def test_landed_commit_reconciled_not_reexecuted(tmp_path):
    """Host 1 commits but dies before clearing its lease: the coordinator
    finds the recorded txnId in the log tail and only clears the lease —
    a recovered slice is never double-committed."""
    from unittest import mock

    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.obs import journal

    path = str(tmp_path / "t")
    log = _mk_partitioned_table(path)

    with mock.patch.object(leases, "clear_lease"):  # the lost clear
        _posed_optimize(log, proc=1)
    assert len(leases.read_leases(log.log_path)) == 1
    v_after_host1 = log.update().version
    _age_leases(log.log_path)

    DeltaLog.invalidate_cache(path)
    log = DeltaLog(path)
    with conf.set_temporarily(
            delta__tpu__distributed__lease__settleMs=20):
        _posed_optimize(log, proc=0)

    # exactly one commit per slice: host 1's + the coordinator's own
    assert log.update().version == v_after_host1 + 1
    assert leases.read_leases(log.log_path) == []
    assert "dist.slice.recovered" not in telemetry.counters("dist")
    journal.flush(log.log_path)
    events = {e.get("event")
              for e in journal.read_entries(log.log_path, kinds=("dist",))}
    assert "dist.sliceReconciled" in events
    assert "dist.sliceRecovered" not in events


def test_recovery_is_idempotent_when_nothing_replannable(tmp_path):
    """An orphan whose partitions were already compacted re-plans to zero
    groups: recovery commits NOTHING (no empty commit, no counter)."""
    from delta_tpu.log.deltalog import DeltaLog

    path = str(tmp_path / "t")
    log = _mk_partitioned_table(path)

    plan = FaultPlan(script=[("dist.itemExec", "crash_before_publish")])
    with _fast_retries(delta__tpu__faults__plan=plan):
        with pytest.raises(SimulatedCrash):
            _posed_optimize(log, proc=1)
    _age_leases(log.log_path)

    # a full single-process OPTIMIZE compacts everything first
    from delta_tpu.commands.optimize import OptimizeCommand

    DeltaLog.invalidate_cache(path)
    log = DeltaLog(path)
    OptimizeCommand(log, workers=2).run()
    v = log.update().version

    files_before = len(log.update().all_files)
    with conf.set_temporarily(
            delta__tpu__distributed__lease__settleMs=20):
        cmd = _posed_optimize(log, proc=0)
    # the coordinator's own (empty-plan) OPTIMIZE may land its usual
    # metrics-only commit, but the RECOVERY adds no commit, rewrites no
    # file, and counts nothing recovered
    assert log.update().version <= v + 1
    assert cmd.metrics["numAddedFiles"] == 0
    assert len(log.update().all_files) == files_before
    assert leases.read_leases(log.log_path) == []
    assert "dist.slice.recovered" not in telemetry.counters("dist")


# -- doctor dimension --------------------------------------------------------


def test_doctor_distributed_dimension(tmp_path):
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.obs.doctor import doctor

    path = str(tmp_path / "t")
    DeltaTable.create(path, data=pa.table({"id": pa.array([1], pa.int64())}))
    from delta_tpu.log.deltalog import DeltaLog

    rep = doctor(DeltaLog.for_table(path))
    dim = {d.name: d for d in rep.dimensions}["distributed"]
    assert dim.severity == "ok"

    telemetry.bump_counter("dist.items.quarantined")
    telemetry.bump_counter("dist.degraded.probe")
    rep = doctor(DeltaLog.for_table(path))
    dim = {d.name: d for d in rep.dimensions}["distributed"]
    assert dim.severity == "warn"
    assert dim.metrics["itemsQuarantined"] == 1
    assert dim.metrics["degraded"] == 1
