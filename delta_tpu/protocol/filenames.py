"""Canonical log-file naming (reference: ``util/FileNames.scala:23-109``).

Kept byte-identical for on-disk compatibility:
  ``%020d.json``                                — delta commit
  ``%020d.checkpoint.parquet``                  — single-part checkpoint
  ``%020d.checkpoint.%010d.%010d.parquet``      — multi-part checkpoint
  ``%020d.crc``                                 — version checksum
  ``_last_checkpoint``                          — checkpoint pointer
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

DELTA_FILE_RE = re.compile(r"^(\d+)\.json$")
CHECKSUM_FILE_RE = re.compile(r"^(\d+)\.crc$")
CHECKPOINT_FILE_RE = re.compile(r"^(\d+)\.checkpoint(\.(\d+)\.(\d+))?\.parquet$")

LAST_CHECKPOINT = "_last_checkpoint"


def delta_file(version: int) -> str:
    return "%020d.json" % version


def checksum_file(version: int) -> str:
    return "%020d.crc" % version


def checkpoint_file_single(version: int) -> str:
    return "%020d.checkpoint.parquet" % version


def checkpoint_file_with_parts(version: int, num_parts: int) -> List[str]:
    return [
        "%020d.checkpoint.%010d.%010d.parquet" % (version, i + 1, num_parts)
        for i in range(num_parts)
    ]


def is_delta_file(name: str) -> bool:
    return DELTA_FILE_RE.match(_basename(name)) is not None


def is_checkpoint_file(name: str) -> bool:
    return CHECKPOINT_FILE_RE.match(_basename(name)) is not None


def is_checksum_file(name: str) -> bool:
    return CHECKSUM_FILE_RE.match(_basename(name)) is not None


def delta_version(name: str) -> int:
    m = DELTA_FILE_RE.match(_basename(name))
    if not m:
        raise ValueError(f"not a delta file: {name}")
    return int(m.group(1))


def checkpoint_version(name: str) -> int:
    m = CHECKPOINT_FILE_RE.match(_basename(name))
    if not m:
        raise ValueError(f"not a checkpoint file: {name}")
    return int(m.group(1))


def checkpoint_part(name: str) -> Optional[Tuple[int, int]]:
    """Returns (part, num_parts) for a multi-part checkpoint file, else None."""
    m = CHECKPOINT_FILE_RE.match(_basename(name))
    if not m or m.group(2) is None:
        return None
    return int(m.group(3)), int(m.group(4))


def checksum_version(name: str) -> int:
    m = CHECKSUM_FILE_RE.match(_basename(name))
    if not m:
        raise ValueError(f"not a checksum file: {name}")
    return int(m.group(1))


def get_file_version(name: str) -> Optional[int]:
    base = _basename(name)
    for rx in (DELTA_FILE_RE, CHECKSUM_FILE_RE, CHECKPOINT_FILE_RE):
        m = rx.match(base)
        if m:
            return int(m.group(1))
    return None


def _basename(name: str) -> str:
    return name.rsplit("/", 1)[-1]


def check_version_prefix(low: int) -> str:
    """Prefix string such that listing from it returns all files with
    version >= low (files are zero-padded so lexicographic order == numeric)."""
    return "%020d." % low
