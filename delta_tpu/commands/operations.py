"""Operations taxonomy — one record per user-facing operation.

Reference: ``DeltaOperations.scala:35-344``. Each operation carries
JSON-encoded parameters and a whitelist of operation metrics; both feed
``CommitInfo`` and DESCRIBE HISTORY.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "Operation",
    "Write",
    "StreamingUpdate",
    "Delete",
    "Truncate",
    "Merge",
    "Update",
    "CreateTable",
    "ReplaceTable",
    "Convert",
    "Optimize",
    "Vacuum",
    "SetTableProperties",
    "UnsetTableProperties",
    "AddColumns",
    "ChangeColumn",
    "ReplaceColumns",
    "UpgradeProtocol",
    "UpdateSchema",
    "AddConstraint",
    "DropConstraint",
    "ManualUpdate",
]


def _jenc(v: Any) -> str:
    """Parameters are JSON-encoded strings (DeltaOperations jsonEncodedValues)."""
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(",", ":"))


@dataclass(frozen=True)
class Operation:
    name: str = ""
    parameters: Dict[str, Any] = field(default_factory=dict)
    metric_whitelist: Sequence[str] = ()
    user_metadata: Optional[str] = None

    @property
    def json_encoded_values(self) -> Dict[str, str]:
        return {k: _jenc(v) for k, v in self.parameters.items() if v is not None}

    def changes_data(self) -> bool:
        return True


# Common metric whitelists (DeltaOperationMetrics, DeltaOperations.scala:344+).
WRITE_METRICS = ("numFiles", "numOutputBytes", "numOutputRows")
STREAMING_METRICS = ("numAddedFiles", "numRemovedFiles", "numOutputRows", "numOutputBytes")
DELETE_METRICS = (
    "numAddedFiles", "numRemovedFiles", "numDeletedRows", "numCopiedRows",
    "executionTimeMs", "scanTimeMs", "rewriteTimeMs",
)
DELETE_PARTITIONS_METRICS = ("numRemovedFiles",)
TRUNCATE_METRICS = ("numRemovedFiles",)
MERGE_METRICS = (
    "numSourceRows", "numTargetRowsInserted", "numTargetRowsUpdated",
    "numTargetRowsDeleted", "numTargetRowsCopied", "numOutputRows",
    "numTargetFilesAdded", "numTargetFilesRemoved", "executionTimeMs",
    "scanTimeMs", "rewriteTimeMs",
)
UPDATE_METRICS = (
    "numAddedFiles", "numRemovedFiles", "numUpdatedRows", "numCopiedRows",
    "executionTimeMs", "scanTimeMs", "rewriteTimeMs",
)
CONVERT_METRICS = ("numConvertedFiles",)
OPTIMIZE_METRICS = (
    "numAddedFiles", "numRemovedFiles", "numAddedBytes", "numRemovedBytes",
    "minFileSize", "maxFileSize", "p25FileSize", "p50FileSize", "p75FileSize",
)


def Write(mode: str, partition_by: Optional[List[str]] = None,
          predicate: Optional[str] = None, user_metadata: Optional[str] = None) -> Operation:
    return Operation(
        "WRITE",
        {"mode": mode, "partitionBy": json.dumps(partition_by, separators=(",", ":")) if partition_by is not None else None,
         "predicate": predicate},
        WRITE_METRICS, user_metadata,
    )


def StreamingUpdate(output_mode: str, query_id: str, epoch_id: int,
                    user_metadata: Optional[str] = None) -> Operation:
    return Operation(
        "STREAMING UPDATE",
        {"outputMode": output_mode, "queryId": query_id, "epochId": str(epoch_id)},
        STREAMING_METRICS, user_metadata,
    )


def Delete(predicate: Optional[List[str]] = None) -> Operation:
    return Operation("DELETE", {"predicate": json.dumps(predicate or [], separators=(",", ":"))}, DELETE_METRICS)


def Truncate() -> Operation:
    return Operation("TRUNCATE", {}, TRUNCATE_METRICS)


def Merge(predicate: Optional[str], updates: Sequence[Dict[str, Any]] = (),
          deletes: Sequence[Dict[str, Any]] = (), inserts: Sequence[Dict[str, Any]] = ()) -> Operation:
    return Operation(
        "MERGE",
        {
            "predicate": predicate,
            "matchedPredicates": json.dumps(list(updates) + list(deletes), separators=(",", ":")),
            "notMatchedPredicates": json.dumps(list(inserts), separators=(",", ":")),
        },
        MERGE_METRICS,
    )


def Update(predicate: Optional[str] = None) -> Operation:
    return Operation("UPDATE", {"predicate": predicate}, UPDATE_METRICS)


def CreateTable(metadata, is_managed: bool = False, as_select: bool = False) -> Operation:
    return Operation(
        "CREATE TABLE" + (" AS SELECT" if as_select else ""),
        {
            "isManaged": str(is_managed).lower(),
            "description": metadata.description,
            "partitionBy": json.dumps(metadata.partition_columns, separators=(",", ":")),
            "properties": json.dumps(metadata.configuration, separators=(",", ":")),
        },
        WRITE_METRICS if as_select else (),
    )


def ReplaceTable(metadata, is_managed: bool = False, or_create: bool = False,
                 as_select: bool = False) -> Operation:
    return Operation(
        ("CREATE OR " if or_create else "") + "REPLACE TABLE" + (" AS SELECT" if as_select else ""),
        {
            "isManaged": str(is_managed).lower(),
            "description": metadata.description,
            "partitionBy": json.dumps(metadata.partition_columns, separators=(",", ":")),
            "properties": json.dumps(metadata.configuration, separators=(",", ":")),
        },
        WRITE_METRICS if as_select else (),
    )


def Convert(num_files: int, partition_by: Sequence[str], source_format: str = "parquet") -> Operation:
    return Operation(
        "CONVERT",
        {"numFiles": num_files, "partitionedBy": json.dumps(list(partition_by), separators=(",", ":")),
         "sourceFormat": source_format},
        CONVERT_METRICS,
    )


def Optimize(predicate: Optional[List[str]] = None, z_order_by: Optional[List[str]] = None) -> Operation:
    op = Operation(
        "OPTIMIZE",
        {"predicate": json.dumps(predicate or [], separators=(",", ":")),
         "zOrderBy": json.dumps(z_order_by or [], separators=(",", ":"))},
        OPTIMIZE_METRICS,
    )
    return op


def Reorg(predicate: Optional[List[str]] = None) -> Operation:
    """REORG TABLE ... APPLY (PURGE) — distinct from OPTIMIZE in history so
    DV-materializing rewrites are auditable."""
    return Operation(
        "REORG",
        {"predicate": json.dumps(predicate or [], separators=(",", ":")),
         "applyPurge": True},
        OPTIMIZE_METRICS,
    )


def Vacuum(retention_hours: Optional[float] = None, retention_check_enabled: bool = True) -> Operation:
    return Operation(
        "VACUUM",
        {
            "specifiedRetentionMillis": (
                int(retention_hours * 3_600_000) if retention_hours is not None else None
            ),
            "retentionCheckEnabled": str(retention_check_enabled).lower(),
        },
        (),
    )


def SetTableProperties(properties: Dict[str, str]) -> Operation:
    return Operation("SET TBLPROPERTIES", {"properties": json.dumps(properties, separators=(",", ":"))}, ())


def UnsetTableProperties(keys: List[str], if_exists: bool) -> Operation:
    return Operation(
        "UNSET TBLPROPERTIES",
        {"properties": json.dumps(keys, separators=(",", ":")), "ifExists": str(if_exists).lower()},
        (),
    )


def AddColumns(columns: List[Dict[str, Any]]) -> Operation:
    return Operation("ADD COLUMNS", {"columns": json.dumps(columns, separators=(",", ":"))}, ())


def ChangeColumn(column_name: str, new_column: Dict[str, Any]) -> Operation:
    return Operation(
        "CHANGE COLUMN",
        {"column": json.dumps({column_name: new_column}, separators=(",", ":"))},
        (),
    )


def ReplaceColumns(columns: List[Dict[str, Any]]) -> Operation:
    return Operation("REPLACE COLUMNS", {"columns": json.dumps(columns, separators=(",", ":"))}, ())


def UpgradeProtocol(protocol) -> Operation:
    return Operation(
        "UPGRADE PROTOCOL",
        {"newProtocolVersion": json.dumps(protocol.to_dict(), separators=(",", ":"))},
        (),
    )


def UpdateSchema(old_schema, new_schema) -> Operation:
    return Operation(
        "UPDATE SCHEMA",
        {"oldSchema": old_schema.to_json(), "newSchema": new_schema.to_json()},
        (),
    )


def AddConstraint(name: str, expr: str) -> Operation:
    return Operation("ADD CONSTRAINT", {"name": name, "expr": expr}, ())


def DropConstraint(name: str, expr: Optional[str]) -> Operation:
    return Operation("DROP CONSTRAINT", {"name": name, "expr": expr}, ())


def ManualUpdate() -> Operation:
    """Test-only operation (DeltaOperations.ManualUpdate)."""
    return Operation("Manual Update", {}, ())
