"""Error taxonomy, mirroring the reference's user-facing error factory
(``DeltaErrors.scala``) and the public concurrency exception hierarchy
(``io/delta/exceptions/DeltaConcurrentExceptions.scala``, also surfaced to
Python in the reference via ``python/delta/exceptions.py``)."""
from __future__ import annotations

from typing import Iterable, Optional

__all__ = [
    "DeltaError",
    "DeltaAnalysisError",
    "DeltaIllegalArgumentError",
    "DeltaIllegalStateError",
    "CommitAttemptsExhausted",
    "DeltaFileNotFoundError",
    "DeltaIOError",
    "DeltaUnsupportedOperationError",
    "DeltaParseError",
    "MetadataChangedException",
    "ProtocolChangedException",
    "ConcurrentWriteException",
    "ConcurrentAppendException",
    "ConcurrentDeleteReadException",
    "ConcurrentDeleteDeleteException",
    "ConcurrentTransactionException",
    "DeltaConcurrentModificationException",
    "InvariantViolationError",
    "SchemaMismatchError",
    "ProtocolError",
    "VersionNotFoundError",
    "TimestampEarlierThanCommitRetentionError",
    "TemporallyUnstableInputError",
]


class DeltaError(Exception):
    """Base for all delta-tpu errors."""


class DeltaAnalysisError(DeltaError):
    pass


class DeltaIllegalArgumentError(DeltaError, ValueError):
    pass


class DeltaIllegalStateError(DeltaError, RuntimeError):
    pass


class CommitAttemptsExhausted(DeltaIllegalStateError):
    """A commit gave up after its attempts bound (delta.tpu.maxCommitAttempts
    or a maintenance `txn.transaction.commit_attempts_cap`). A dedicated
    subclass so background maintenance can classify losing-to-foreground
    without message matching; still a DeltaIllegalStateError to callers."""


class DeltaFileNotFoundError(DeltaError, FileNotFoundError):
    pass


class DeltaIOError(DeltaError, IOError):
    pass


class DeltaUnsupportedOperationError(DeltaError, NotImplementedError):
    pass


class InvariantViolationError(DeltaError):
    """Row-level constraint / NOT NULL violation
    (``schema/InvariantViolationException.scala``)."""


class DeltaParseError(DeltaAnalysisError):
    """SQL statement failed to tokenize or parse (≈ Spark ParseException)."""


class SchemaMismatchError(DeltaAnalysisError):
    """Write schema incompatible with table schema
    (``DeltaErrors.failedToMergeFields`` etc.)."""


class ProtocolError(DeltaError):
    """Table requires a newer reader/writer than this client
    (``DeltaErrors.InvalidProtocolVersionException``)."""


class VersionNotFoundError(DeltaAnalysisError):
    def __init__(self, user_version: int, earliest: int, latest: int):
        super().__init__(
            f"Cannot time travel Delta table to version {user_version}. "
            f"Available versions: [{earliest}, {latest}]."
        )
        self.user_version = user_version
        self.earliest = earliest
        self.latest = latest


class TimestampEarlierThanCommitRetentionError(DeltaAnalysisError):
    pass


class TemporallyUnstableInputError(DeltaAnalysisError):
    """Requested timestamp is after the latest commit timestamp."""

    def __init__(self, user_ts, commit_ts, latest_version: int):
        super().__init__(
            f"The provided timestamp ({user_ts}) is after the latest version "
            f"available to this table ({commit_ts}, version {latest_version})."
        )
        self.commit_ts = commit_ts
        self.latest_version = latest_version


# ---------------------------------------------------------------------------
# Concurrency exceptions (conflict-checker verdicts) — names match
# io/delta/exceptions/DeltaConcurrentExceptions.scala so users can map 1:1.
# ---------------------------------------------------------------------------

class DeltaConcurrentModificationException(DeltaError):
    """Base of the OCC conflict hierarchy."""

    def __init__(self, message: str, conflicting_commit: Optional[dict] = None):
        super().__init__(message)
        self.conflicting_commit = conflicting_commit


class ConcurrentWriteException(DeltaConcurrentModificationException):
    """A concurrent transaction wrote new data the current transaction read
    (or the commit file appeared non-atomically)."""


class MetadataChangedException(DeltaConcurrentModificationException):
    """The table metadata changed since the transaction's snapshot."""


class ProtocolChangedException(DeltaConcurrentModificationException):
    """The protocol version changed since the transaction's snapshot."""


class ConcurrentAppendException(DeltaConcurrentModificationException):
    """Files were added by a concurrent commit in a region this txn read."""


class ConcurrentDeleteReadException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction read."""


class ConcurrentDeleteDeleteException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction also deletes."""


class ConcurrentTransactionException(DeltaConcurrentModificationException):
    """Overlapping SetTransaction appId with a concurrent commit."""


def versions_not_contiguous(versions: Iterable[int]) -> DeltaIllegalStateError:
    return DeltaIllegalStateError(
        f"Versions ({list(versions)}) are not contiguous. This can happen when "
        "files have been manually deleted from the transaction log."
    )


# ---------------------------------------------------------------------------
# Error factories — the user-facing message contract, mirroring the relevant
# subset of ``DeltaErrors.scala`` (message text and remediation advice kept
# 1:1 where the situation exists in this engine).
# ---------------------------------------------------------------------------

_CONCURRENCY_DOC = "https://docs.delta.io/latest/concurrency-control.html"


def _concurrent_msg(base: str, commit: Optional[dict]) -> str:
    """``DeltaErrors.concurrentModificationExceptionMsg`` composition: base
    message + conflicting-commit provenance + doc pointer."""
    import json

    msg = base
    if commit:
        msg += f"\nConflicting commit: {json.dumps(commit, default=str)}"
    return msg + f"\nRefer to {_CONCURRENCY_DOC} for more details."


def concurrent_write_exception(commit: Optional[dict] = None) -> ConcurrentWriteException:
    return ConcurrentWriteException(_concurrent_msg(
        "A concurrent transaction has written new data since the current "
        "transaction read the table. Please try the operation again.",
        commit), commit)


def metadata_changed_exception(commit: Optional[dict] = None) -> MetadataChangedException:
    return MetadataChangedException(_concurrent_msg(
        "The metadata of the Delta table has been changed by a concurrent "
        "update. Please try the operation again.", commit), commit)


def protocol_changed_exception(commit: Optional[dict] = None) -> ProtocolChangedException:
    additional = ""
    if commit and commit.get("version") == 0:
        # DeltaErrors.scala:1164-1171 — empty-directory race hint
        additional = (
            "This happens when multiple writers are writing to an empty "
            "directory. Creating the table ahead of time will avoid this "
            "conflict. "
        )
    return ProtocolChangedException(_concurrent_msg(
        "The protocol version of the Delta table has been changed by a "
        f"concurrent update. {additional}Please try the operation again.",
        commit), commit)


def concurrent_append_exception(
    partition: str, commit: Optional[dict] = None,
    custom_retry: Optional[str] = None,
) -> ConcurrentAppendException:
    return ConcurrentAppendException(_concurrent_msg(
        f"Files were added to {partition} by a concurrent update. "
        + (custom_retry or "Please try the operation again."), commit), commit)


def concurrent_delete_read_exception(
    file: str, commit: Optional[dict] = None
) -> ConcurrentDeleteReadException:
    return ConcurrentDeleteReadException(_concurrent_msg(
        "This transaction attempted to read one or more files that were "
        f"deleted (for example {file}) by a concurrent update. "
        "Please try the operation again.", commit), commit)


def concurrent_delete_delete_exception(
    file: str, commit: Optional[dict] = None
) -> ConcurrentDeleteDeleteException:
    return ConcurrentDeleteDeleteException(_concurrent_msg(
        "This transaction attempted to delete one or more files that were "
        f"deleted (for example {file}) by a concurrent update. "
        "Please try the operation again.", commit), commit)


def concurrent_transaction_exception(
    commit: Optional[dict] = None, app_id: Optional[str] = None,
) -> ConcurrentTransactionException:
    detail = f" (conflicting appId={app_id})" if app_id else ""
    return ConcurrentTransactionException(_concurrent_msg(
        "This error occurs when multiple streaming queries are using the "
        f"same checkpoint to write into this table{detail}. Did you run "
        "multiple instances of the same streaming query at the same time?",
        commit), commit)


def not_a_delta_table(identifier: str, operation: Optional[str] = None) -> DeltaAnalysisError:
    if operation:
        return DeltaAnalysisError(
            f"{identifier} is not a Delta table. {operation} is only "
            "supported for Delta tables."
        )
    return DeltaAnalysisError(f"{identifier} is not a Delta table.")


def modify_append_only_table() -> DeltaUnsupportedOperationError:
    return DeltaUnsupportedOperationError(
        "This table is configured to only allow appends. If you would like "
        "to permit updates or deletes, use 'ALTER TABLE <table_name> SET "
        "TBLPROPERTIES (delta.appendOnly=false)'."
    )


def invalid_protocol_version(
    client_reader: int, client_writer: int, table_reader: int, table_writer: int
) -> ProtocolError:
    return ProtocolError(
        "Delta protocol version "
        f"(reader={table_reader}, writer={table_writer}) is too new for this "
        f"client (supports reader={client_reader}, writer={client_writer}). "
        "Please upgrade to a newer release."
    )


def not_null_invariant_violated(
    column: str, null_rows: Optional[int] = None
) -> InvariantViolationError:
    detail = f" ({null_rows} null rows)" if null_rows else ""
    return InvariantViolationError(
        f"NOT NULL constraint violated for column: {column}{detail}."
    )


def check_constraint_violated(
    name: str, expr_sql: str, values: Optional[dict] = None
) -> InvariantViolationError:
    lines = "".join(f"\n - {c} : {v}" for c, v in (values or {}).items())
    return InvariantViolationError(
        f"CHECK constraint {name} ({expr_sql}) violated by row with values:"
        f"{lines}"
    )


def new_check_constraint_violated(num: int, table: str, expr: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"{num} rows in {table} violate the new CHECK constraint ({expr})"
    )


def merge_conflicting_set_columns(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"There is a conflict from these SET columns: duplicate assignment "
        f"to {column!r}."
    )


def char_varchar_length_exceeded(
    column: str, declared: str, limit: int, sample
) -> InvariantViolationError:
    return InvariantViolationError(
        f"Exceeds char/varchar type length limitation: column {column} is "
        f"declared {declared} but value {sample!r} is longer than {limit} "
        "characters."
    )


def replace_where_mismatch(replace_where: str, detail: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Data written out does not match replaceWhere '{replace_where}'.\n"
        f"Invalid data would be written to {detail}."
    )


def unset_nonexistent_property(key: str, table: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Attempted to unset non-existent property '{key}' in table {table}"
    )


def retention_period_too_short(retention_hours: float, configured_hours: float):
    return DeltaIllegalArgumentError(
        "Are you sure you would like to vacuum files with such a low "
        f"retention period ({retention_hours} hours)? If you have writers "
        "that are currently writing to this table, there is a risk that you "
        "may corrupt the state of your Delta table.\nIf you are certain "
        "there are no operations being performed on this table, such as "
        "insert/upsert/delete/optimize, then you may turn off this check by "
        "setting delta.tpu.retentionDurationCheck.enabled = false\nIf you "
        "are not sure, please use a value not less than "
        f"{configured_hours} hours."
    )


def missing_part_files(version: int, cause: Exception) -> DeltaIllegalStateError:
    return DeltaIllegalStateError(
        f"Couldn't find all part files of the checkpoint version: {version} "
        f"({cause})"
    )


# ---------------------------------------------------------------------------
# Named factories for every analysis-time error path — no call site raises a
# bare f-string DeltaAnalysisError (enforced by tests/test_errors.py); each
# message carries what went wrong plus how to fix it, the DeltaErrors.scala
# contract.
# ---------------------------------------------------------------------------


def invalid_table_identifier(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Invalid table identifier: {name!r}. Use 'table', 'db.table', or a "
        "path identifier delta.`/path/to/table`."
    )


def table_already_exists_in_catalog(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Table {name!r} already exists in catalog. Use CREATE OR REPLACE to "
        "overwrite it, or DROP TABLE first."
    )


def table_being_created_concurrently(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Table {name!r} is being created concurrently by another writer. "
        "Wait for that create to finish, or retry the operation."
    )


def table_not_found_in_catalog(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Table {name!r} not found in catalog. Check the identifier, or use "
        "a path identifier delta.`/path/to/table` for path-addressed tables."
    )


def table_already_exists(path: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Table already exists: {path}. Use mode='overwrite' / CREATE OR "
        "REPLACE to replace it, or pick a different location."
    )


def unsupported_sql_statement(sql: str) -> DeltaParseError:
    return DeltaParseError(
        f"Unsupported SQL statement: {sql.strip()[:80]!r}. Supported "
        "statements: SELECT, CREATE/REPLACE TABLE, ALTER TABLE, "
        "INSERT/UPDATE/DELETE/MERGE, OPTIMIZE, VACUUM, DESCRIBE, RESTORE, "
        "CONVERT TO DELTA, GENERATE, SHALLOW CLONE."
    )


def unsupported_generate_mode(mode: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Unsupported GENERATE mode: {mode!r}. The only supported mode is "
        "'symlink_format_manifest'."
    )


def unsupported_table_format(fmt: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Unsupported table format: {fmt!r}. CREATE TABLE ... USING must be "
        "'delta'; to import an existing parquet table, use CONVERT TO DELTA "
        "parquet.`/path`."
    )


def unsupported_arrow_type(t) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Unsupported Arrow type for a Delta schema: {t}. Cast the column "
        "to a supported primitive, struct, array, or map type before writing."
    )


def arrow_mapping_missing(type_name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"No Arrow mapping for Delta type {type_name}. This type cannot be "
        "materialized by the vectorized reader."
    )


def add_column_anchor_not_found(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Couldn't resolve the position to add the column {column}: the "
        "AFTER anchor column does not exist at that nesting level."
    )


def column_already_exists(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(f"Column {column} already exists.")


def struct_not_found_at_position(position) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Struct not found at position {position}; the parent of a nested "
        "column operation must be a struct column."
    )


def column_not_in_schema(column: str, schema_cols=None) -> DeltaAnalysisError:
    detail = f" Available columns: {list(schema_cols)}." if schema_cols else ""
    return DeltaAnalysisError(f"Column {column} does not exist.{detail}")


def drop_column_index_below_zero(position: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Index {position} to drop column is lower than 0"
    )


def invalid_timestamp_format(ts, cause=None) -> DeltaAnalysisError:
    tail = f": {cause}" if cause is not None else "."
    return DeltaAnalysisError(
        f"Invalid timestamp {ts!r}. Provide epoch milliseconds or an "
        f"ISO-8601 string like '2024-05-01 12:00:00'{tail}"
    )


def column_not_found_in_table(column: str, available) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Column {column!r} not found among {list(available)}."
    )


def cannot_tokenize_predicate(fragment: str) -> DeltaParseError:
    return DeltaParseError(
        f"Cannot tokenize predicate at {fragment!r}. Check for unbalanced "
        "quotes or unsupported characters."
    )


def unexpected_end_of_expression(source: str) -> DeltaParseError:
    return DeltaParseError(
        f"Unexpected end of expression: {source!r}. The predicate ends "
        "mid-term — a operand or closing parenthesis is missing."
    )


def trailing_tokens(token, source: str) -> DeltaParseError:
    return DeltaParseError(
        f"Trailing tokens at {token} in {source!r}. Combine multiple "
        "conditions with AND/OR."
    )


def unexpected_keyword(text: str, source: str) -> DeltaParseError:
    return DeltaParseError(
        f"Unexpected keyword {text} in {source!r}."
    )


def bad_column_path(source: str) -> DeltaParseError:
    return DeltaParseError(
        f"Bad column path after '.' in {source!r}. Nested fields are "
        "addressed as parent.child (backquote names with special characters)."
    )


def unexpected_token(token, source: str) -> DeltaParseError:
    return DeltaParseError(f"Unexpected token {token} in {source!r}.")


def expected_type_name(token) -> DeltaParseError:
    return DeltaParseError(
        f"Expected type name, got {token}. Use a Delta type like INT, "
        "BIGINT, DOUBLE, STRING, DATE, TIMESTAMP, or DECIMAL(p, s)."
    )


def column_not_found_in_row(column: str, available) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Column not found: {column} in {list(available)}"
    )


def unsupported_function(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Unsupported function: {name}. See delta_tpu.expr.ir.FUNCTION_NAMES "
        "for the supported surface."
    )


def invalid_column_position_spec(spec: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Invalid column position spec {spec!r}. Use FIRST or AFTER "
        "<existing column>."
    )


def constraint_already_exists(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Constraint '{name}' already exists. DROP CONSTRAINT first to "
        "replace it."
    )


def constraint_does_not_exist(name: str, table: str = "") -> DeltaAnalysisError:
    where = f" in table {table}" if table else ""
    return DeltaAnalysisError(
        f"Constraint '{name}' does not exist{where}. Nothing to drop."
    )


def zorder_column_not_in_schema(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Z-order column {column!r} not in table schema."
    )


def zorder_on_partition_column(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot Z-order by partition column {column!r}: partition values "
        "are constant within a file, so they add no clustering. Z-order by "
        "data columns instead."
    )


def invalid_merge_clause(kind: str, matched: bool) -> DeltaAnalysisError:
    allowed = "UPDATE or DELETE" if matched else "INSERT"
    block = "WHEN MATCHED" if matched else "WHEN NOT MATCHED"
    return DeltaAnalysisError(
        f"Invalid {block} clause: {kind}. Only {allowed} is allowed here."
    )


def update_column_not_found(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Column {column!r} not found in table schema. SET clauses may only "
        "assign existing columns."
    )


# -- SQL parse family (DeltaSqlBase.g4 / ParseException analogues) ----------


def sql_unexpected_character(c: str, offset: int) -> DeltaParseError:
    return DeltaParseError(f"Unexpected character {c!r} at offset {offset}")


def sql_expected(what: str, offset, got=None) -> DeltaParseError:
    tail = f", got {got!r}" if got is not None else ""
    return DeltaParseError(f"Expected {what} at offset {offset}{tail}")


def sql_unexpected_input(offset, got) -> DeltaParseError:
    return DeltaParseError(f"Unexpected token at offset {offset}: {got!r}")


def sql_trailing_input(offset, got) -> DeltaParseError:
    return DeltaParseError(
        f"Unexpected trailing input at offset {offset}: {got!r}"
    )


def sql_invalid_decimal(args) -> DeltaParseError:
    return DeltaParseError(
        f"Invalid DECIMAL precision/scale: {args}. Use DECIMAL(precision, "
        "scale) with 1 <= precision <= 38 and 0 <= scale <= precision."
    )


def sql_unsupported_type(name: str) -> DeltaParseError:
    return DeltaParseError(
        f"Unsupported SQL type: {name!r}. Use a Delta type like INT, BIGINT, "
        "DOUBLE, STRING, DATE, TIMESTAMP, BOOLEAN, BINARY, or DECIMAL(p, s)."
    )


def sql_invalid_number(value, kind: str, offset) -> DeltaParseError:
    return DeltaParseError(f"Invalid {kind} {value!r} at offset {offset}")


def sql_bad_type_argument(offset, value) -> DeltaParseError:
    return DeltaParseError(f"Bad type argument at offset {offset}: {value!r}")


def sql_empty_set_expression(column: str) -> DeltaParseError:
    return DeltaParseError(f"Empty SET expression for column {column!r}")


def sql_insert_arity_mismatch(n_cols: int, n_vals: int) -> DeltaParseError:
    return DeltaParseError(
        f"INSERT columns ({n_cols}) and VALUES ({n_vals}) differ"
    )


def sql_unsupported_alter_action(offset) -> DeltaParseError:
    return DeltaParseError(f"Unsupported ALTER TABLE action at offset {offset}")


def sql_expected_statement(got) -> DeltaParseError:
    return DeltaParseError(f"Expected a statement keyword, got {got!r}")


def sql_star_only_in_count(func: str) -> DeltaParseError:
    return DeltaParseError(
        f"{func}(*) is not valid; '*' is only allowed in COUNT(*)."
    )


def sql_column_needs_group_by(column: str) -> DeltaParseError:
    return DeltaParseError(
        f"Column {column} must appear in GROUP BY or inside an aggregate "
        "function"
    )


def sql_expected_table_identifier(after: str, offset) -> DeltaParseError:
    return DeltaParseError(
        f"Expected table identifier after {after}. at offset {offset}"
    )


def create_table_needs_location(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"CREATE TABLE {name}: unregistered name needs LOCATION "
        "(or use delta.`/path`)"
    )


def parse_expected(what, got, source: str) -> DeltaParseError:
    return DeltaParseError(f"Expected {what} at token {got} in {source!r}")


# -- expression typing ------------------------------------------------------


def cannot_compare_types(left: str, right: str, sql: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(f"Cannot compare {left} with {right} in {sql}")


def cannot_apply_operator(op: str, left: str, right: str, sql: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot apply {op!r} to {left} and {right} in {sql}"
    )


def like_requires_strings(got: str, sql: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"LIKE requires string operands, got {got} in {sql}"
    )


# -- schema machinery (SchemaUtils / DeltaErrors schema family) -------------


def invalid_column_name(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f'Attribute name "{name}" contains invalid character(s) among '
        '" ,;{}()\\n\\t=". Please use alias to rename it.'
    )


def partition_column_not_found(column: str, schema_str: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Partition column `{column}` not found in schema {schema_str}"
    )


def duplicate_columns(context: str, first: str, second: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Found duplicate column(s) {context}: {first}, {second}"
    )


def generated_column_type_change(name: str, data_type: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Column {name} is a generated column or a column used by a "
        f"generated column; its data type {data_type} cannot be changed."
    )


def add_column_index_below_zero(position: int, name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Index {position} to add column {name} is lower than 0"
    )


def add_column_index_too_large(position: int, name: str, length: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Index {position} to add column {name} is larger than struct "
        f"length: {length}"
    )


def parent_not_struct(name: str, found: Optional[str] = None) -> DeltaAnalysisError:
    tail = f" Found {found}" if found else ""
    return DeltaAnalysisError(
        f"Cannot add {name} because its parent is not a StructType.{tail}"
    )


def replace_column_index_oob(position: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Index {position} to replace column is out of bounds"
    )


def array_access_needs_element_step(verb: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Incorrectly accessing an ArrayType during {verb}: use the element "
        "step"
    )


def nested_op_only_in_struct(verb: str, found: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Can only {verb} nested columns inside StructType. Found: {found}"
    )


def drop_column_index_too_large(position: int, length: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Index {position} to drop column equals to or is larger than "
        f"struct length: {length}"
    )


def array_access_element_path_hint(corrected_path: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        "An ArrayType was found. In order to access elements of an "
        f"ArrayType, specify {corrected_path}"
    )


def map_access_needs_key_or_value(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot access {name} in a MapType: use key or value"
    )


def column_path_not_nested(path: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Column path {path} descends into a non-nested type"
    )


def column_path_not_found(path: str, schema_str: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Couldn't find column {path} in schema {schema_str}"
    )


def parent_is_not_struct(parent: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(f"Parent {parent} is not a struct")


def position_after_column_not_found(column: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Couldn't find column {column} to position AFTER"
    )


def add_columns_must_be_nullable(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"ADD COLUMNS requires nullable columns, {name} is NOT NULL"
    )


def cannot_change_column_type(name: str, old: str, new: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot change column {name} from {old} to {new}"
    )


def cannot_change_nullable_to_not_null(name: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot change nullable column {name} to NOT NULL"
    )


# -- generated columns ------------------------------------------------------


def invalid_generation_expression(column: str, cause) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Invalid generation expression for column {column!r}: {cause}"
    )


def generation_expr_unknown_column(column: str, ref: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Generation expression for {column!r} references unknown column "
        f"{ref!r}"
    )


def generation_expr_references_generated(column: str, ref: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Generation expression for {column!r} references generated column "
        f"{ref!r}; generated columns cannot reference each other"
    )


def generation_expr_type_mismatch(column: str, got, want, cause) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Generation expression for {column!r} produces type {got}, which "
        f"cannot become declared type {want}: {cause}"
    )


# -- commands ---------------------------------------------------------------


def partition_path_segment_invalid(segment: str, rel_path: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Expecting partition column in path segment {segment!r} of {rel_path!r}"
    )


def partition_path_mismatch(rel_path: str, found, expected) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Partition columns in path {rel_path!r} ({sorted(found)}) don't "
        f"match the declared partition schema ({sorted(expected)}). "
        "CONVERT TO DELTA requires PARTITIONED BY matching the layout."
    )


def replace_requires_existing_table(path: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Table not found: {path} (REPLACE requires an existing table; use "
        "CREATE OR REPLACE)"
    )


def merge_unresolvable_qualifier(
    name: str, qualifier: str, target_alias, source_alias
) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot resolve {name!r} in MERGE: qualifier {qualifier!r} matches "
        f"neither target alias {target_alias!r} nor source alias "
        f"{source_alias!r}"
    )


def merge_unresolvable_column(name: str, target_cols, source_cols) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Cannot resolve {name!r} in MERGE (target={list(target_cols)}, "
        f"source={list(source_cols)})"
    )


def merge_clause_unresolvable(column: str, clause: str, source_cols) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"cannot resolve {column} in {clause} clause given columns "
        f"{list(source_cols)} (enable delta.tpu.schema.autoMerge.enabled to "
        "evolve the target schema instead)"
    )


def update_expression_type_mismatch(name: str, new_type, old_type) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"UPDATE expression for {name} has incompatible type {new_type} "
        f"(column is {old_type})"
    )


def partition_columns_mismatch(given, current) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Partition columns {list(given)} don't match the table's {current}"
    )


def replace_where_needs_partition_columns(pred_sql: str, partition_cols) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"replaceWhere {pred_sql!r} must reference only partition columns "
        f"{partition_cols}"
    )


def cdf_start_after_latest(start: int, latest: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"CDF start version {start} is after the latest table version {latest}"
    )


def cdf_start_after_end(start: int, end: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"CDF start version {start} is after end version {end}"
    )


def cdf_start_unavailable(start: int, earliest: int) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"CDF start version {start} is no longer available (earliest "
        f"retained commit is {earliest}); the change feed for cleaned-up "
        "versions is lost"
    )
