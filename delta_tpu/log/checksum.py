"""Per-version state checksums (``<v>.crc``), reference ``Checksum.scala``.

Written best-effort after each commit; on read, validated against the
snapshot's computed state — a cheap guard against state-reconstruction bugs
and log corruption.
"""
from __future__ import annotations

import json
import logging
from dataclasses import dataclass
from typing import Optional

from delta_tpu.protocol import filenames
from delta_tpu.storage.logstore import LogStore
from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import DeltaIllegalStateError

logger = logging.getLogger(__name__)

__all__ = ["VersionChecksum", "write_checksum", "read_checksum", "validate_checksum"]


@dataclass(frozen=True)
class VersionChecksum:
    table_size_bytes: int
    num_files: int
    num_metadata: int
    num_protocol: int
    num_transactions: int

    def to_json(self) -> str:
        return json.dumps(
            {
                "tableSizeBytes": self.table_size_bytes,
                "numFiles": self.num_files,
                "numMetadata": self.num_metadata,
                "numProtocol": self.num_protocol,
                "numTransactions": self.num_transactions,
            },
            separators=(",", ":"),
        )

    @staticmethod
    def from_json(s: str) -> "VersionChecksum":
        d = json.loads(s)
        return VersionChecksum(
            int(d.get("tableSizeBytes", 0)),
            int(d.get("numFiles", 0)),
            int(d.get("numMetadata", 0)),
            int(d.get("numProtocol", 0)),
            int(d.get("numTransactions", 0)),
        )

    @staticmethod
    def of_snapshot(snapshot) -> "VersionChecksum":
        return VersionChecksum(
            table_size_bytes=snapshot.size_in_bytes,
            num_files=snapshot.num_of_files,
            num_metadata=snapshot.num_of_metadata,
            num_protocol=snapshot.num_of_protocol,
            num_transactions=snapshot.num_of_set_transactions,
        )


def write_checksum(store: LogStore, log_path: str, version: int, checksum: VersionChecksum) -> None:
    """Best-effort write (``Checksum.scala:55-93``)."""
    if not conf.get("delta.tpu.writeChecksum.enabled"):
        return
    try:
        store.write(
            f"{log_path}/{filenames.checksum_file(version)}", [checksum.to_json()], overwrite=True
        )
    # delta-lint: ignore[crash-except] -- best-effort overwrite-PUT: a pierced
    # crash leaves no partial state and the .crc is advisory
    except Exception:  # noqa: BLE001 — checksum write must never fail a commit
        logger.warning("Failed to write checksum for version %s", version, exc_info=True)


def read_checksum(store: LogStore, log_path: str, version: int) -> Optional[VersionChecksum]:
    try:
        lines = store.read(f"{log_path}/{filenames.checksum_file(version)}")
        return VersionChecksum.from_json("".join(lines))
    except FileNotFoundError:
        return None
    except (ValueError, KeyError):
        logger.warning("Corrupt checksum file for version %s", version)
        return None


def validate_checksum(snapshot) -> None:
    """Compare stored vs computed state (``Checksum.scala:153-193``)."""
    stored = read_checksum(snapshot.store, snapshot.delta_log.log_path, snapshot.version)
    if stored is None:
        return
    computed = VersionChecksum.of_snapshot(snapshot)
    mismatches = []
    for name in ("table_size_bytes", "num_files", "num_metadata", "num_protocol"):
        if getattr(stored, name) != getattr(computed, name):
            mismatches.append(f"{name}: stored={getattr(stored, name)} computed={getattr(computed, name)}")
    if mismatches:
        msg = (
            f"State of version {snapshot.version} doesn't match its checksum: "
            + "; ".join(mismatches)
        )
        if conf.get("delta.tpu.state.corruptionIsFatal"):
            raise DeltaIllegalStateError(msg)
        logger.error(msg)
