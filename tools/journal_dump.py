"""Offline workload-journal inspector.

Prints a table's persisted journal (`delta_tpu/obs/journal.py` — one JSONL
entry per scan/commit/DML/router decision under
``<table>/_delta_log/_journal/``) without touching the engine's hot paths,
or runs the layout advisor over it::

    python tools/journal_dump.py /data/tbl                  # all entries
    python tools/journal_dump.py /data/tbl --kind scan      # one kind
    python tools/journal_dump.py /data/tbl --limit 20       # last N
    python tools/journal_dump.py /data/tbl --summary        # counts per kind
    python tools/journal_dump.py /data/tbl --advise         # advisor report
    python tools/journal_dump.py /data/tbl --autopilot      # action ledger
    python tools/journal_dump.py /data/tbl --shadow         # shadow scorecards

Entries print one JSON object per line (pipe into ``jq``); ``--advise``,
``--summary``, ``--autopilot`` and ``--shadow`` print one indented JSON
document — ``--autopilot`` renders the maintenance action ledger (planned
/ executed / skipped / deferred actions with their cited evidence and the
predicted-vs-realized audit verdicts), ``--shadow`` summarizes the shadow
optimizer's journaled scorecards (candidate rankings, verdicts, measured
deltas — `delta_tpu/replay/shadow.py`).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("table", help="table data path (the dir holding _delta_log)")
    ap.add_argument("--kind",
                    choices=["scan", "commit", "dml", "router", "autopilot",
                             "shadow"],
                    help="only entries of this kind")
    ap.add_argument("--limit", type=int, default=None,
                    help="last N entries (after kind filtering)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-kind counts + segment stats instead of entries")
    ap.add_argument("--advise", action="store_true",
                    help="run the layout advisor and print its report")
    ap.add_argument("--autopilot", action="store_true",
                    help="print the autopilot action ledger (planned/"
                         "executed/skipped actions + realized-improvement "
                         "verdicts)")
    ap.add_argument("--shadow", action="store_true",
                    help="summarize journaled shadow-run scorecards "
                         "(candidate rankings, verdicts, measured deltas)")
    args = ap.parse_args(argv)

    from delta_tpu.obs import journal

    log_path = os.path.join(args.table.rstrip("/"), "_delta_log")
    if args.autopilot:
        entries = journal.read_entries(log_path, kinds=["autopilot"],
                                       limit=args.limit)
        by_phase = Counter(e.get("phase", "?") for e in entries)
        verdicts = Counter(
            (e.get("audit") or {}).get("verdict")
            for e in entries if e.get("phase") == "executed")
        print(json.dumps({
            "table": args.table,
            "entries": len(entries),
            "byPhase": dict(by_phase),
            "executedVerdicts": {k: v for k, v in verdicts.items() if k},
            "ledger": entries,
        }, indent=1, default=str))
        return 0
    if args.shadow:
        entries = journal.read_entries(log_path, kinds=["shadow"],
                                       limit=args.limit)
        verdicts: Counter = Counter()
        runs = []
        for e in entries:
            sc = e.get("scorecard") or {}
            cands = sc.get("candidates") or []
            for c in cands:
                verdicts[c.get("verdict", "?")] += 1
            runs.append({
                "ts": e.get("ts"),
                "trace": sc.get("trace"),
                "topCandidate": sc.get("topCandidate"),
                "candidates": [
                    {"label": (c.get("candidate") or {}).get("label"),
                     "verdict": c.get("verdict"),
                     "score": c.get("score"),
                     "deltas": c.get("deltas")}
                    for c in cands],
            })
        print(json.dumps({
            "table": args.table,
            "shadowRuns": len(entries),
            "candidateVerdicts": dict(verdicts),
            "runs": runs,
        }, indent=1, default=str))
        return 0
    if args.advise:
        from delta_tpu.obs.advisor import advise

        print(json.dumps(advise(args.table, limit=args.limit).to_dict(),
                         indent=1, default=str))
        return 0

    entries = journal.read_entries(
        log_path, kinds=[args.kind] if args.kind else None, limit=args.limit
    )
    if args.summary:
        jdir = journal.journal_dir(log_path)
        try:
            segs = [n for n in sorted(os.listdir(jdir))
                    if n.startswith(journal.SEGMENT_PREFIX)]
            seg_bytes = sum(os.path.getsize(os.path.join(jdir, n)) for n in segs)
        except OSError:
            segs, seg_bytes = [], 0
        print(json.dumps({
            "table": args.table,
            "journalDir": jdir,
            "segments": len(segs),
            "bytes": seg_bytes,
            "entries": len(entries),
            "byKind": dict(Counter(e.get("kind", "?") for e in entries)),
        }, indent=1))
        return 0
    for e in entries:
        print(json.dumps(e, separators=(",", ":"), default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
