"""Utility commands: VACUUM, CONVERT TO DELTA, DESCRIBE, GENERATE.

Behavioral spec: `DeltaVacuumSuite` (manual clock + CheckFiles DSL),
`ConvertToDeltaSuiteBase`, `DescribeDelta*Suite`,
`DeltaGenerateSymlinkManifestSuite` (SURVEY §4).
"""
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.convert import ConvertToDeltaCommand
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.describe import describe_detail, describe_history
from delta_tpu.commands.vacuum import VacuumCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.hooks.symlink_manifest import MANIFEST_DIR, generate_full_manifest
from delta_tpu.schema.types import StringType, StructField, StructType
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaIllegalArgumentError


def write(log, data, mode="append", **kw):
    return WriteIntoDelta(log, mode, data, **kw).run()


class ManualClock:
    """Starts at real now (data file mtimes are real) and advances manually —
    the reference's ManualClock+set-mtime trick, inverted."""

    def __init__(self, now_ms=None):
        import time

        self.now = now_ms if now_ms is not None else int(time.time() * 1000)

    def __call__(self):
        return self.now

    def advance(self, ms):
        self.now += ms


HOUR = 3600 * 1000


# -- VACUUM -----------------------------------------------------------------


def test_vacuum_removes_unreferenced_after_retention(tmp_table):
    clock = ManualClock()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    write(log, {"id": [1, 2, 3]})
    removed_path = log.update().all_files[0].path
    DeleteCommand(log, None).run()
    write(log, {"id": [9]})

    # too young: nothing deleted
    res = VacuumCommand(log, retention_hours=200).run()
    assert res.files_deleted == 0
    assert os.path.exists(os.path.join(tmp_table, removed_path))

    clock.advance(201 * HOUR)
    # dry run reports but doesn't delete
    res = VacuumCommand(log, retention_hours=200, dry_run=True).run()
    assert res.files_deleted == 1
    assert os.path.exists(os.path.join(tmp_table, removed_path))
    res = VacuumCommand(log, retention_hours=200).run()
    assert res.files_deleted == 1
    assert not os.path.exists(os.path.join(tmp_table, removed_path))
    # live data survives
    assert scan_to_table(log.update()).column("id").to_pylist() == [9]


def test_vacuum_retention_check(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises(DeltaIllegalArgumentError):
        VacuumCommand(log, retention_hours=0).run()
    # disabled check allows it
    VacuumCommand(log, retention_hours=0, retention_check_enabled=False).run()


def test_vacuum_untracked_files_and_empty_dirs(tmp_table):
    clock = ManualClock()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    write(log, {"id": [1, 2], "c": ["a", "b"]}, partition_columns=["c"])
    # drop an orphan file into a partition dir + an orphan dir
    orphan = os.path.join(tmp_table, "c=a", "orphan.parquet")
    with open(orphan, "w") as f:
        f.write("junk")
    os.makedirs(os.path.join(tmp_table, "c=zzz"))
    clock.advance(200 * HOUR)
    res = VacuumCommand(log, retention_hours=168).run()
    assert res.files_deleted == 1
    assert not os.path.exists(orphan)
    assert not os.path.exists(os.path.join(tmp_table, "c=zzz"))
    # hidden dirs (incl. _delta_log) untouched
    assert os.path.isdir(os.path.join(tmp_table, "_delta_log"))
    assert sorted(scan_to_table(log.update()).column("id").to_pylist()) == [1, 2]


def test_vacuum_keeps_tombstoned_files_within_retention(tmp_table):
    clock = ManualClock()
    log = DeltaLog.for_table(tmp_table, clock=clock)
    write(log, {"id": [1]})
    kept = log.update().all_files[0].path
    DeleteCommand(log, None).run()
    clock.advance(10 * HOUR)  # younger than tombstone retention (168h)
    res = VacuumCommand(log).run()
    assert res.files_deleted == 0
    assert os.path.exists(os.path.join(tmp_table, kept))


# -- CONVERT ----------------------------------------------------------------


def test_convert_unpartitioned(tmp_table):
    os.makedirs(tmp_table)
    pq.write_table(pa.table({"id": [1, 2]}), os.path.join(tmp_table, "a.parquet"))
    pq.write_table(pa.table({"id": [3]}), os.path.join(tmp_table, "b.parquet"))
    log = DeltaLog.for_table(tmp_table)
    v = ConvertToDeltaCommand(log).run()
    assert v == 0
    t = scan_to_table(log.update())
    assert sorted(t.column("id").to_pylist()) == [1, 2, 3]
    # idempotent: converting again is a no-op
    assert ConvertToDeltaCommand(log).run() == 0


def test_convert_partitioned(tmp_table):
    os.makedirs(os.path.join(tmp_table, "c=x"))
    os.makedirs(os.path.join(tmp_table, "c=y"))
    pq.write_table(pa.table({"id": [1]}), os.path.join(tmp_table, "c=x", "a.parquet"))
    pq.write_table(pa.table({"id": [2]}), os.path.join(tmp_table, "c=y", "b.parquet"))
    log = DeltaLog.for_table(tmp_table)
    part_schema = StructType([StructField("c", StringType())])
    ConvertToDeltaCommand(log, partition_schema=part_schema).run()
    snap = log.update()
    assert snap.metadata.partition_columns == ["c"]
    t = scan_to_table(snap, ["c = 'y'"])
    assert t.column("id").to_pylist() == [2]


def test_convert_partitioned_requires_partition_schema(tmp_table):
    os.makedirs(os.path.join(tmp_table, "c=x"))
    pq.write_table(pa.table({"id": [1]}), os.path.join(tmp_table, "c=x", "a.parquet"))
    log = DeltaLog.for_table(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        ConvertToDeltaCommand(log).run()


def test_convert_merges_schemas(tmp_table):
    os.makedirs(tmp_table)
    pq.write_table(pa.table({"id": [1]}), os.path.join(tmp_table, "a.parquet"))
    pq.write_table(
        pa.table({"id": [2], "v": ["x"]}), os.path.join(tmp_table, "b.parquet")
    )
    log = DeltaLog.for_table(tmp_table)
    ConvertToDeltaCommand(log).run()
    t = scan_to_table(log.update())
    assert sorted(t.column("id").to_pylist()) == [1, 2]
    assert set(t.column_names) == {"id", "v"}


# -- DESCRIBE ---------------------------------------------------------------


def test_describe_detail(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "c": ["a", "b"]}, partition_columns=["c"],
          configuration={"delta.appendOnly": "false"})
    d = describe_detail(log)
    assert d["format"] == "delta"
    assert d["partitionColumns"] == ["c"]
    assert d["numFiles"] == 2
    assert d["sizeInBytes"] > 0
    assert d["properties"]["delta.appendOnly"] == "false"
    assert d["minReaderVersion"] == 1


def test_describe_history(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    DeleteCommand(log, None).run()
    hist = describe_history(log)
    assert len(hist) == 2
    assert hist[0]["operation"] == "DELETE"  # newest first
    assert hist[1]["operation"] == "WRITE"
    assert hist[0]["version"] == 1
    # operation metrics survive into history
    assert "numRemovedFiles" in hist[0].get("operationMetrics", {})


# -- GENERATE ---------------------------------------------------------------


def test_generate_full_manifest(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "c": ["a", "b"]}, partition_columns=["c"])
    n = generate_full_manifest(log)
    assert n == 2
    mpath = os.path.join(tmp_table, MANIFEST_DIR, "c=a", "manifest")
    with open(mpath) as f:
        lines = f.read().strip().splitlines()
    assert len(lines) == 1
    assert lines[0].startswith("file:")
    assert "c%3Da" in lines[0] or "c=a" in lines[0]


def test_incremental_manifest_hook(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(
        log,
        {"id": [1], "c": ["a"]},
        partition_columns=["c"],
        configuration={"delta.compatibility.symlinkFormatManifest.enabled": "true"},
    )
    mpath = os.path.join(tmp_table, MANIFEST_DIR, "c=a", "manifest")
    assert os.path.exists(mpath)
    # a delete that empties the partition removes its manifest
    DeleteCommand(log, "c = 'a'").run()
    assert not os.path.exists(mpath)


def test_convert_with_stats_enables_skipping(tmp_table):
    os.makedirs(tmp_table)
    pq.write_table(pa.table({"id": [1, 2]}), os.path.join(tmp_table, "a.parquet"))
    pq.write_table(pa.table({"id": [100, 200]}), os.path.join(tmp_table, "b.parquet"))
    log = DeltaLog.for_table(tmp_table)
    ConvertToDeltaCommand(log, collect_stats=True).run()
    snap = log.update()
    stats = [f.stats_dict() for f in snap.all_files]
    assert all(s and "numRecords" in s and "minValues" in s for s in stats)
    from delta_tpu.expr.parser import parse_predicate
    from delta_tpu.ops import pruning

    scan = pruning.files_for_scan(snap, [parse_predicate("id > 50")])
    assert len(scan.files) == 1, "min/max stats from convert must prune"


def test_convert_null_partition_token(tmp_table):
    os.makedirs(os.path.join(tmp_table, "c=__HIVE_DEFAULT_PARTITION__"))
    os.makedirs(os.path.join(tmp_table, "c=x"))
    pq.write_table(pa.table({"id": [1]}),
                   os.path.join(tmp_table, "c=__HIVE_DEFAULT_PARTITION__", "a.parquet"))
    pq.write_table(pa.table({"id": [2]}), os.path.join(tmp_table, "c=x", "b.parquet"))
    log = DeltaLog.for_table(tmp_table)
    part_schema = StructType([StructField("c", StringType())])
    ConvertToDeltaCommand(log, partition_schema=part_schema).run()
    t = scan_to_table(log.update())
    by_id = dict(zip(t.column("id").to_pylist(), t.column("c").to_pylist()))
    assert by_id[1] is None and by_id[2] == "x"


def test_convert_escaped_partition_values(tmp_table):
    # hive-escaped special chars in dir names round-trip through convert
    os.makedirs(os.path.join(tmp_table, "c=a%3Db"))  # value "a=b"
    pq.write_table(pa.table({"id": [1]}),
                   os.path.join(tmp_table, "c=a%3Db", "a.parquet"))
    log = DeltaLog.for_table(tmp_table)
    part_schema = StructType([StructField("c", StringType())])
    ConvertToDeltaCommand(log, partition_schema=part_schema).run()
    t = scan_to_table(log.update())
    assert t.column("c").to_pylist() == ["a=b"]


def test_convert_ignores_hidden_files_and_dirs(tmp_table):
    os.makedirs(os.path.join(tmp_table, "_staging"))
    pq.write_table(pa.table({"id": [9]}), os.path.join(tmp_table, "_staging", "x.parquet"))
    pq.write_table(pa.table({"id": [1]}), os.path.join(tmp_table, "a.parquet"))
    with open(os.path.join(tmp_table, ".hidden.parquet"), "wb") as f:
        f.write(b"junk")
    log = DeltaLog.for_table(tmp_table)
    ConvertToDeltaCommand(log).run()
    t = scan_to_table(log.update())
    assert t.column("id").to_pylist() == [1]


def test_convert_empty_dir_errors(tmp_table):
    os.makedirs(tmp_table)
    log = DeltaLog.for_table(tmp_table)
    from delta_tpu.utils.errors import DeltaFileNotFoundError

    with pytest.raises(DeltaFileNotFoundError):
        ConvertToDeltaCommand(log).run()


def test_convert_mixed_depth_partitions_rejected(tmp_table):
    os.makedirs(os.path.join(tmp_table, "c=x"))
    pq.write_table(pa.table({"id": [1]}), os.path.join(tmp_table, "c=x", "a.parquet"))
    pq.write_table(pa.table({"id": [2]}), os.path.join(tmp_table, "b.parquet"))
    log = DeltaLog.for_table(tmp_table)
    part_schema = StructType([StructField("c", StringType())])
    with pytest.raises(DeltaAnalysisError):
        ConvertToDeltaCommand(log, partition_schema=part_schema).run()


def test_post_convert_dml_works(tmp_table):
    os.makedirs(tmp_table)
    pq.write_table(pa.table({"id": [1, 2, 3]}), os.path.join(tmp_table, "a.parquet"))
    log = DeltaLog.for_table(tmp_table)
    ConvertToDeltaCommand(log).run()
    from delta_tpu.api.tables import DeltaTable

    t = DeltaTable.for_path(tmp_table)
    t.delete("id = 2")
    t.update({"id": "id * 10"}, "id = 3")
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 30]
    assert len(t.history()) == 3
