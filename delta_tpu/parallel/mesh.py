"""Device mesh helpers — the framework's parallel substrate.

The reference's distribution substrate is Spark's driver/executor fan-out
(SURVEY §2.8); ours is a `jax.sharding.Mesh`. Table-state kernels shard over a
1-D ``"shards"`` axis (the analogue of the reference's 50-way state
repartition, `Snapshot.scala:75-78`); collectives ride ICI within a slice and
DCN across hosts — all inserted by XLA from sharding annotations.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["state_mesh", "shard_count", "pad_to_multiple", "P", "NamedSharding"]

STATE_AXIS = "shards"


def state_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``n_devices`` (default: all local devices) with the
    table-state sharding axis."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (STATE_AXIS,))


def shard_count(mesh: Mesh) -> int:
    return mesh.shape[STATE_AXIS]


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n`` (and >= m)."""
    return max(((n + m - 1) // m) * m, m)
