"""Columnar segment decode vs the object-per-action LogReplay oracle.

The columnar path (``log/columnar.py``) must produce byte-identical state to
``LogReplay`` (the PROTOCOL.md "Action Reconciliation" reference) on random
logs exercising: unicode paths, "./" canonicalization, stats strings,
partition values, tags, metadata/protocol/txn evolution, commitInfo and cdc
noise, multi-part checkpoints, and empty lines.
"""
import json
import random

import numpy as np
import pytest

from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.log.columnar import decode_segment
from delta_tpu.log.replay import LogReplay, canonicalize_path
from delta_tpu.ops.replay_kernel import replay_columns
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import (
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)
from delta_tpu.storage.logstore import get_log_store


def _random_commit(rng, v, n_paths):
    actions = []
    actions.append(CommitInfo(operation="WRITE", operation_parameters={"mode": '"Append"'},
                              user_metadata='note with "txn" inside' if rng.random() < 0.2 else None))
    if v == 0:
        actions.append(Protocol())
        actions.append(Metadata(schema_string='{"type":"struct","fields":[]}',
                                partition_columns=["p"]))
    if rng.random() < 0.05:
        actions.append(Metadata(id=f"meta-{v}", schema_string='{"type":"struct","fields":[]}'))
    if rng.random() < 0.1:
        actions.append(SetTransaction(app_id=f"app-{rng.randrange(3)}", version=v))
    for _ in range(rng.randint(1, 8)):
        kind = rng.random()
        p = rng.choice([
            f"p=1/part-{rng.randrange(n_paths):05d}.parquet",
            f"./part-{rng.randrange(n_paths):05d}.parquet",
            f"ünï-{rng.randrange(n_paths):05d}.parquet",
        ])
        if kind < 0.7:
            actions.append(AddFile(
                path=p, partition_values={"p": "1"} if p.startswith("p=") else {},
                size=rng.randrange(1, 10_000), modification_time=v,
                data_change=True,
                stats=json.dumps({"numRecords": rng.randrange(100),
                                  "minValues": {"x": rng.randrange(50)}}) if rng.random() < 0.5 else None,
                tags=({"tag": "zorder"} if rng.random() < 0.2 else None),
            ))
        else:
            actions.append(RemoveFile(path=p, deletion_timestamp=v * 1000,
                                      data_change=True, size=rng.randrange(1, 10_000)))
    return actions


def _write_log(tmp_path, rng, n_versions, n_paths, checkpoint_at=None):
    log_path = str(tmp_path / "_delta_log")
    store = get_log_store(log_path)
    replay = LogReplay(min_file_retention_timestamp=0)
    for v in range(n_versions):
        actions = _random_commit(rng, v, n_paths)
        lines = [a.json() for a in actions]
        if rng.random() < 0.1:
            lines.insert(rng.randrange(len(lines)), "")  # stray empty line
        store.write(f"{log_path}/{filenames.delta_file(v)}", lines)
        replay.append(v, actions)
        if checkpoint_at is not None and v == checkpoint_at:
            ckpt_replay = LogReplay(0)
            ckpt_replay.current_version = -1
            # reconciled state so far becomes the checkpoint
            ckpt_actions = replay.checkpoint_actions()
            parts = 3 if len(ckpt_actions) > 10 else None
            ckpt_mod.write_checkpoint(store, log_path, v, ckpt_actions, parts=parts)
    return log_path, store, replay


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_decode_segment_matches_oracle(tmp_path, seed):
    rng = random.Random(seed)
    n_versions = 30
    log_path, store, replay = _write_log(tmp_path, rng, n_versions, n_paths=40)
    deltas = [f"{log_path}/{filenames.delta_file(v)}" for v in range(n_versions)]
    cols = decode_segment(store, [], deltas)

    alive, tomb = cols.replay(min_retention_ts=0)
    alive_paths = set(cols.paths_for(np.nonzero(alive)[0]))
    assert alive_paths == set(replay.active_files.keys())
    tomb_paths = set(cols.paths_for(np.nonzero(tomb)[0]))
    assert tomb_paths == {r.path for r in replay.get_tombstones()}

    # lazy materialization must equal the oracle's dataclasses exactly
    files = {a.path: a for a in cols.materialize(alive)}
    assert files == replay.active_files

    # non-file actions
    proto = [a for a in cols.other_actions if isinstance(a, Protocol)]
    metas = [a for a in cols.other_actions if isinstance(a, Metadata)]
    txns = {}
    for a in cols.other_actions:
        if isinstance(a, SetTransaction):
            txns[a.app_id] = a
    assert proto[-1] == replay.current_protocol
    assert metas[-1] == replay.current_metadata
    assert txns == replay.transactions


@pytest.mark.parametrize("seed", [3, 4])
def test_decode_segment_with_checkpoint_matches_oracle(tmp_path, seed):
    rng = random.Random(seed)
    n_versions = 25
    ckpt_v = 12
    log_path, store, replay = _write_log(tmp_path, rng, n_versions, n_paths=30,
                                         checkpoint_at=ckpt_v)
    inst = ckpt_mod.read_last_checkpoint(store, log_path)
    assert inst is not None and inst.version == ckpt_v
    ckpt_paths = ckpt_mod.CheckpointInstance(inst.version, inst.parts).paths(log_path)
    deltas = [f"{log_path}/{filenames.delta_file(v)}" for v in range(ckpt_v + 1, n_versions)]
    cols = decode_segment(store, ckpt_paths, deltas)

    alive, tomb = cols.replay(min_retention_ts=0)
    alive_paths = set(cols.paths_for(np.nonzero(alive)[0]))
    assert alive_paths == set(replay.active_files.keys())

    files = {a.path: a for a in cols.materialize(alive)}
    oracle = {p: a.with_data_change(False) if p in files and files[p].data_change is False else a
              for p, a in replay.active_files.items()}
    # files surviving from the checkpoint were normalized to dataChange=False
    for p, a in files.items():
        expect = replay.active_files[p]
        assert a == expect or a == expect.with_data_change(False)

    metas = [a for a in cols.other_actions if isinstance(a, Metadata)]
    assert metas[-1] == replay.current_metadata
    txns = {}
    for a in cols.other_actions:
        if isinstance(a, SetTransaction):
            txns[a.app_id] = a
    assert txns == replay.transactions


def test_winner_device_matches_host():
    import pyarrow as pa

    from delta_tpu.log.columnar import SegmentColumns

    rng = np.random.RandomState(0)
    n = 5000
    path_id = rng.randint(0, 700, n).astype(np.int32)
    is_add = rng.rand(n) < 0.8
    cols = SegmentColumns(
        path_dict=pa.array([f"p{i}" for i in range(700)]),
        path_id=path_id,
        is_add=is_add,
        size=rng.randint(0, 100, n).astype(np.int64),
        modification_time=np.zeros(n, np.int64),
        deletion_timestamp=np.where(is_add, 0, rng.randint(1, 1000, n)).astype(np.int64),
        stats=None,
        other_actions=[],
    )
    dev = replay_columns(cols, min_retention_ts=50, device=True)
    host = replay_columns(cols, min_retention_ts=50, device=False)
    assert (dev.alive == host.alive).all()
    assert (dev.tombstone == host.tombstone).all()
    assert int(dev.stats.num_files) == int(host.stats.num_files)
    assert int(dev.stats.total_size) == int(host.stats.total_size)
    assert int(dev.stats.num_tombstones) == int(host.stats.num_tombstones)


def test_tombstone_retention_masks(tmp_path):
    rng = random.Random(7)
    log_path, store, replay = _write_log(tmp_path, rng, 10, n_paths=12)
    deltas = [f"{log_path}/{filenames.delta_file(v)}" for v in range(10)]
    cols = decode_segment(store, [], deltas)
    for cutoff in (0, 3000, 100_000):
        _alive, tomb = cols.replay(min_retention_ts=cutoff)
        got = set(cols.paths_for(np.nonzero(tomb)[0]))
        expect = {r.path for r in replay.get_tombstones(cutoff)}
        assert got == expect, cutoff
