"""Round-4 expression-function surface (VERDICT item 6): to_date,
date_add/sub, datediff, minute/second, substr window semantics, lpad/rpad,
format_string, pow/exp/log/sqrt — exact row semantics as the spec, Arrow
and JAX evaluators checked against it, plus generated-column and CHECK
end-to-end uses (the reference whitelist:
``SupportedGenerationExpressions.scala``)."""
import datetime as dt

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.utils.jaxcompat import enable_x64
from delta_tpu.expr import ir
from delta_tpu.expr.jaxeval import NotDeviceCompilable, columns_from_numpy, compile_expr
from delta_tpu.expr.parser import parse_expression
from delta_tpu.expr.vectorized import evaluate

ROWS = [
    {"a": 4, "b": 2.0, "s": "hello", "d": dt.date(2021, 3, 14),
     "ds": "2021-03-14", "n": 3},
    {"a": -9, "b": 0.5, "s": "x", "d": dt.date(2020, 12, 31),
     "ds": "2020-12-31", "n": -2},
    {"a": None, "b": None, "s": None, "d": None, "ds": None, "n": None},
    {"a": 0, "b": -1.0, "s": "padded", "d": dt.date(1969, 7, 20),
     "ds": "bogus", "n": 0},
    {"a": 100, "b": 10.0, "s": "", "d": dt.date(2024, 2, 29),
     "ds": "2024-02-29", "n": 40},
]
TABLE = pa.Table.from_pylist(ROWS)

EXPRS = [
    "to_date(ds)",
    "date_add(d, 7)",
    "date_sub(d, 40)",
    "date_add(d, n)",
    "datediff(d, to_date(ds))",
    "datediff(date_add(d, 10), d)",
    "substr(s, 2)",
    "substr(s, 2, 3)",
    "substr(s, -3, 2)",
    "substr(s, -8, 5)",
    "substring(s, 0, 2)",
    "lpad(s, 8, '*')",
    "rpad(s, 3, 'ab')",
    "lpad(s, 2)",
    "format_string('%s-%d', s, a)",
    "pow(b, 2)",
    "pow(a, b)",
    "exp(b)",
    "log(b)",
    "log(2, a)",
    "sqrt(a)",
    "sqrt(b)",
]


@pytest.mark.parametrize("sql", EXPRS)
def test_vectorized_matches_row_eval(sql):
    e = parse_expression(sql)
    expected = [e.eval(r) for r in ROWS]
    got = evaluate(e, TABLE).to_pylist()
    for g, x in zip(got, expected):
        if isinstance(x, float) and g is not None:
            assert g == pytest.approx(x, rel=1e-12, nan_ok=True), sql
        else:
            assert g == x, f"{sql}: {got} != {expected}"


def test_minute_second_on_timestamps_vectorized():
    ts = [dt.datetime(2021, 1, 1, 10, 37, 55), None,
          dt.datetime(1999, 12, 31, 23, 59, 59)]
    tab = pa.table({"t": pa.array(ts, pa.timestamp("us"))})
    assert evaluate(parse_expression("minute(t)"), tab).to_pylist() == [37, None, 59]
    assert evaluate(parse_expression("second(t)"), tab).to_pylist() == [55, None, 59]


def test_minute_second_on_int_micros_row():
    e = parse_expression("minute(t)")
    us = 10 * 3_600_000_000 + 37 * 60_000_000 + 55 * 1_000_000
    assert e.eval({"t": us}) == 37
    assert parse_expression("second(t)").eval({"t": us}) == 55


def test_to_date_with_java_format():
    e = parse_expression("to_date(s, 'dd/MM/yyyy')")
    assert e.eval({"s": "14/03/2021"}) == dt.date(2021, 3, 14)
    assert e.eval({"s": "zzz"}) is None
    tab = pa.table({"s": pa.array(["14/03/2021", "bad", None])})
    assert evaluate(e, tab).to_pylist() == [dt.date(2021, 3, 14), None, None]


def test_to_date_unknown_format_token_rejected():
    from delta_tpu.utils.errors import DeltaAnalysisError

    with pytest.raises(DeltaAnalysisError, match="format token"):
        parse_expression("to_date(s, 'QQ-yyyy')").eval({"s": "x"})


def test_substr_window_edges():
    f = ir.Func.FUNCS["substr"]
    assert f("abc", -5, 4) == "ab"   # window starts before the string
    assert f("abc", 0, 2) == "ab"    # pos 0 behaves like 1
    assert f("abc", -2) == "bc"
    assert f("abc", 2, 0) == ""
    assert f(None, 1) is None


def test_pad_truncates_like_spark():
    f = ir.Func.FUNCS["lpad"]
    assert f("abcd", 2, "#") == "ab"
    assert f("ab", 5, "xy") == "xyxab"
    assert ir.Func.FUNCS["rpad"]("ab", 5, "xy") == "abxyx"
    assert f("ab", 0, "#") == ""


def test_log_domain_is_null():
    assert ir.Func.FUNCS["log"](-1.0) is None
    assert ir.Func.FUNCS["log"](1.0, 10.0) is None  # base 1
    assert ir.Func.FUNCS["sqrt"](-4) is None
    tab = pa.table({"b": pa.array([-1.0, 4.0])})
    assert evaluate(parse_expression("log(b)"), tab).to_pylist()[0] is None
    assert evaluate(parse_expression("sqrt(b)"), tab).to_pylist() == [None, 2.0]


# -- device evaluator -------------------------------------------------------


JAX_EXPRS = [
    "pow(b, 2)", "exp(b)", "log(b)", "sqrt(a)",
    "date_add(d, 7)", "date_sub(d, 3)", "datediff(d, d2)",
    "minute(t)", "second(t)",
]


@pytest.mark.parametrize("sql", JAX_EXPRS)
def test_jaxeval_matches_row_eval(sql):
    import jax

    rows = [
        {"a": 4, "b": 2.5, "d": 18700, "d2": 18600, "t": 5_000_000_000},
        {"a": 9, "b": 0.5, "d": 1, "d2": 0, "t": 59_000_000},
        {"a": 16, "b": -3.0, "d": -400, "d2": 20, "t": 3_600_000_000},
    ]
    cols = {k: np.array([r[k] for r in rows]) for k in rows[0]}
    e = parse_expression(sql)
    with enable_x64():
        out = compile_expr(e)(columns_from_numpy(cols))
    vals = np.asarray(out.values)
    valid = np.asarray(out.valid)
    for i, r in enumerate(rows):
        expect = e.eval(r)
        if isinstance(expect, dt.date):
            # device date lanes are epoch days
            expect = (expect - dt.date(1970, 1, 1)).days
        if expect is None:
            assert not valid[i], sql
        else:
            assert valid[i], sql
            assert vals[i] == pytest.approx(expect, rel=1e-12), sql


def test_jaxeval_rejects_string_functions():
    with pytest.raises(NotDeviceCompilable):
        compile_expr(parse_expression("lpad(s, 3)"))


# -- end-to-end: generated columns + CHECK constraints ----------------------


def test_generated_columns_using_new_functions(tmp_table):
    from delta_tpu import DeltaLog
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.schema.generated import GENERATION_EXPRESSION_KEY
    from delta_tpu.schema.types import (
        DateType, DoubleType, IntegerType, StringType, StructField, StructType,
    )

    schema = StructType([
        StructField("ds", StringType(), True),
        StructField("v", DoubleType(), True),
        StructField("day", DateType(), True,
                    {GENERATION_EXPRESSION_KEY: "to_date(ds)"}),
        StructField("due", DateType(), True,
                    {GENERATION_EXPRESSION_KEY: "date_add(to_date(ds), 30)"}),
        StructField("mag", DoubleType(), True,
                    {GENERATION_EXPRESSION_KEY: "round(pow(v, 2), 0)"}),
        StructField("tag", StringType(), True,
                    {GENERATION_EXPRESSION_KEY: "lpad(substr(ds, 1, 4), 6, '0')"}),
    ])
    from delta_tpu.api.tables import DeltaTable

    DeltaTable.create(tmp_table, schema)
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "ds": ["2021-03-14", "2024-02-29"], "v": [3.0, -2.0],
    })).run()
    t = scan_to_table(log.update()).sort_by("ds")
    assert t.column("day").to_pylist() == [dt.date(2021, 3, 14), dt.date(2024, 2, 29)]
    assert t.column("due").to_pylist() == [dt.date(2021, 4, 13), dt.date(2024, 3, 30)]
    assert t.column("mag").to_pylist() == [9.0, 4.0]
    assert t.column("tag").to_pylist() == ["002021", "002024"]


def test_check_constraint_using_new_functions(tmp_table):
    from delta_tpu import DeltaLog
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.alter import add_constraint
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.schema.types import DoubleType, StringType, StructType
    from delta_tpu.utils.errors import InvariantViolationError

    schema = StructType().add("ds", StringType()).add("v", DoubleType())
    DeltaTable.create(tmp_table, schema)
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"ds": ["2021-01-02"], "v": [4.0]})).run()
    add_constraint(log, "valid_day", "datediff(to_date(ds), to_date('2021-01-01')) >= 0")
    add_constraint(log, "v_domain", "sqrt(v) <= 10")
    WriteIntoDelta(log, "append", pa.table({"ds": ["2021-06-01"], "v": [25.0]})).run()
    with pytest.raises(InvariantViolationError):
        WriteIntoDelta(log, "append", pa.table({"ds": ["2020-12-30"], "v": [1.0]})).run()
    with pytest.raises(InvariantViolationError):
        WriteIntoDelta(log, "append", pa.table({"ds": ["2021-02-02"], "v": [10001.0]})).run()
