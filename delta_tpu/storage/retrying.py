"""RetryingLogStore — transparent transient-fault retry for idempotent ops.

Installed by :class:`delta_tpu.log.deltalog.DeltaLog` around whatever store
serves the table (above the fault injector, when one is configured, so
injected transients are actually retried). Every *idempotent* operation —
reads, listings, existence probes, deletes, and ``overwrite=True`` writes
(checkpoint parts, ``_last_checkpoint``, ``.crc``: deterministic content, a
double PUT is harmless) — retries under the shared
:class:`~delta_tpu.utils.retries.RetryPolicy`.

The ONE operation that must never retry blind is the commit create-if-absent
(``write(..., overwrite=False)``): a lost response leaves "did my file land?"
unknowable here, and a blind second attempt either double-commits or
misreads its own first attempt as a conflict. That call passes straight
through; ambiguity is resolved by token reconciliation in
``txn/transaction.py`` (which can actually read the winner back).
"""
from __future__ import annotations

from typing import Iterable, Iterator, List, Optional

from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.retries import RetryPolicy, call_with_retries

__all__ = ["RetryingLogStore", "policy_from_conf"]


def policy_from_conf() -> RetryPolicy:
    """Session-tunable retry policy (``delta.tpu.storage.retry.*``)."""
    from delta_tpu.utils.config import conf

    return RetryPolicy(
        max_attempts=int(conf.get("delta.tpu.storage.retry.maxAttempts")),
        base_delay_s=float(conf.get("delta.tpu.storage.retry.baseDelayMs")) / 1000.0,
        max_delay_s=float(conf.get("delta.tpu.storage.retry.maxDelayMs")) / 1000.0,
        deadline_s=float(conf.get("delta.tpu.storage.retry.deadlineMs")) / 1000.0,
    )


class RetryingLogStore(LogStore):
    """Wraps ``base``, retrying idempotent ops on transient failures."""

    def __init__(self, base: LogStore, policy: Optional[RetryPolicy] = None):
        self.base = base
        self.policy = policy or policy_from_conf()

    def _retry(self, op_name, fn):
        return call_with_retries(fn, policy=self.policy, op_name=op_name)

    # -- reads (idempotent) ---------------------------------------------

    def read(self, path: str) -> List[str]:
        return self._retry("read", lambda: self.base.read(path))

    def read_iter(self, path: str) -> Iterator[str]:
        # materialize under retry: a generator can't re-drive a failed read
        return iter(self.read(path))

    def read_bytes(self, path: str) -> bytes:
        return self._retry("read", lambda: self.base.read_bytes(path))

    def list_from(self, path: str) -> Iterator[FileStatus]:
        return iter(self._retry("list", lambda: list(self.base.list_from(path))))

    def exists(self, path: str) -> bool:
        return self._retry("exists", lambda: self.base.exists(path))

    # -- writes ----------------------------------------------------------

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        if not overwrite:
            # commit create-if-absent: NEVER retried here (see module doc)
            return self.base.write(path, lines, overwrite=False)
        lines = list(lines)
        return self._retry("write", lambda: self.base.write(path, lines, overwrite=True))

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        if not overwrite:
            return self.base.write_bytes(path, data, overwrite=False)
        return self._retry(
            "write", lambda: self.base.write_bytes(path, data, overwrite=True)
        )

    def delete(self, path: str) -> bool:
        # idempotent: a retried delete whose first attempt landed returns
        # False, which every caller treats as already-gone
        return self._retry("delete", lambda: self.base.delete(path))

    def mkdirs(self, path: str) -> None:
        return self._retry("mkdirs", lambda: self.base.mkdirs(path))

    # -- passthrough ------------------------------------------------------

    def is_partial_write_visible(self, path: str) -> bool:
        return self.base.is_partial_write_visible(path)

    def resolve_path(self, path: str) -> str:
        return self.base.resolve_path(path)

    def __getattr__(self, name):
        # test hooks / store extras (set_mtime, write_count, ...) pass through
        return getattr(self.base, name)

    def __repr__(self) -> str:
        return f"RetryingLogStore({self.base!r})"
