"""Static-analysis engine (ISSUE 10): the ``delta_tpu/analysis`` passes.

Three layers:

1. **Fixture suite** — per rule, a synthetic violation the rule catches and
   a near-miss it stays quiet on (the positive/negative contract of every
   lint).
2. **Mechanism** — inline waiver placement, baseline round-trip through the
   ``tools/analyze.py`` CLI, ``--json`` output shape.
3. **The tier-1 gate** — the engine runs clean over the real ``delta_tpu``
   package (zero non-baselined findings), which is the PR's acceptance
   criterion and every future PR's regression net.
"""
import json
import os

import pytest

from delta_tpu.analysis import all_passes, analyze_repo, repo_root
from delta_tpu.analysis.core import (AnalysisContext, apply_suppressions,
                                     run_passes)
from delta_tpu.analysis.passes.config_registry import ConfigRegistryPass
from delta_tpu.analysis.passes.crash_safety import CrashSafetyPass
from delta_tpu.analysis.passes.lock_discipline import LockDisciplinePass
from delta_tpu.analysis.passes.metric_catalog import MetricCatalogPass
from delta_tpu.analysis.passes.metric_descriptions import \
    MetricDescriptionsPass
from delta_tpu.analysis.passes.pool_naming import PoolNamingPass
from delta_tpu.analysis.passes.telemetry_spans import TelemetrySpansPass


def _run(pass_, sources):
    ctx = AnalysisContext.from_sources(sources)
    kept, _ = apply_suppressions(ctx, run_passes(ctx, [pass_]))
    return kept


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- lock-discipline ---------------------------------------------------------


def test_lock_guard_fires_on_unguarded_cross_thread_mutation():
    src = '''
import threading
_LOCK = threading.Lock()
_STATE = {}

def _writer_loop():
    _STATE["k"] = 1          # daemon side, no lock

def start():
    threading.Thread(target=_writer_loop, name="delta-journal-writer").start()

def record(v):
    with _LOCK:
        _STATE["k"] = v      # foreground side, locked
'''
    [f] = _run(LockDisciplinePass(), {"delta_tpu/mod.py": src})
    assert f.rule == "lock-guard"
    assert "_STATE" in f.message and "_writer_loop" in f.message


def test_lock_guard_quiet_when_all_sites_guarded_even_via_callers():
    """The caller-context fixpoint: a private helper whose every call site
    holds the lock counts as guarded (journal._write_batch shape)."""
    src = '''
import threading
_LOCK = threading.Lock()
_STATE = {}

def _flush():
    _STATE["k"] = 2          # guarded via the caller, not lexically

def _writer_loop():
    with _LOCK:
        _flush()

def start():
    threading.Thread(target=_writer_loop, name="delta-journal-writer").start()

def record(v):
    with _LOCK:
        _STATE["k"] = v
'''
    assert _run(LockDisciplinePass(), {"delta_tpu/mod.py": src}) == []


def test_lock_guard_fires_on_disjoint_locks_quiet_on_common():
    """The ISSUE's 'without a common lock' case: every site holds SOME lock
    but no lock is shared across the two threads — still a race."""
    src = '''
import threading
_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()
_STATE = {}

def _writer_loop():
    with _LOCK_A:
        _STATE["k"] = 1

def start():
    threading.Thread(target=_writer_loop, name="delta-journal-writer").start()

def record(v):
    with _LOCK_B:
        _STATE["k"] = v
'''
    fs = _run(LockDisciplinePass(), {"delta_tpu/mod.py": src})
    assert len(fs) == 2 and _rules(fs) == ["lock-guard"]
    assert all("no common lock" in f.message for f in fs)
    common = src.replace("with _LOCK_B:", "with _LOCK_A:")
    assert _run(LockDisciplinePass(), {"delta_tpu/mod.py": common}) == []


def test_lock_blocking_fires_under_lock_quiet_outside():
    src = '''
import threading
import time
_LOCK = threading.Lock()

def slow_inside(store):
    with _LOCK:
        time.sleep(0.1)
        store.read_iter("p")

def fine_outside(store):
    store.read_iter("p")
    time.sleep(0.1)
'''
    fs = _run(LockDisciplinePass(), {"delta_tpu/mod.py": src})
    assert _rules(fs) == ["lock-blocking"] and len(fs) == 2
    assert all("slow_inside" in f.message for f in fs)


def test_lock_order_cycle_detected_and_consistent_order_quiet():
    bad = '''
import threading
_A = threading.Lock()
_B = threading.Lock()

def one():
    with _A:
        with _B:
            pass

def two():
    with _B:
        with _A:
            pass
'''
    [f] = _run(LockDisciplinePass(), {"delta_tpu/mod.py": bad})
    assert f.rule == "lock-order" and "_A" in f.message and "_B" in f.message
    good = bad.replace("with _B:\n        with _A:",
                       "with _A:\n        with _B:")
    assert _run(LockDisciplinePass(), {"delta_tpu/mod.py": good}) == []


# -- crash-safety ------------------------------------------------------------


def test_crash_except_fires_on_fault_path_quiet_off_path():
    src = '''
def risky(store):
    try:
        store.write_bytes("p", b"x")
    except Exception:
        pass

def harmless():
    try:
        return 1 + 1
    except Exception:
        return 0
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-except" and "risky" in f.message


def test_crash_except_sees_fault_points_through_local_calls():
    src = '''
from delta_tpu.storage import faults as faults_mod

def _inner():
    faults_mod.fire("txn.groupLoop", "f")

def outer():
    try:
        _inner()
    except Exception:
        pass
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-except" and "txn.groupLoop" in f.message


def test_crash_swallow_fires_quiet_when_propagated():
    src = '''
def swallow(store):
    try:
        store.read("p")
    except BaseException:
        return None

def reraise(store):
    try:
        store.read("p")
    except BaseException:
        raise

def forward(store, state):
    try:
        store.read("p")
    except BaseException as e:
        state["err"] = e
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-swallow" and "swallow" in f.message


def test_crash_swallow_log_only_is_not_propagation():
    """Logging the caught BaseException is not forwarding it: the crash is
    still swallowed. Logging PLUS a real forward stays quiet."""
    src = '''
import logging
logger = logging.getLogger(__name__)

def log_only(store):
    try:
        store.read("p")
    except BaseException as e:
        logger.warning("failed: %s", e)

def log_and_forward(store, fut):
    try:
        store.read("p")
    except BaseException as e:
        logger.warning("failed: %s", e)
        fut.set_exception(e)
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-swallow" and "log_only" in f.message


def test_crash_rules_see_methods_of_function_nested_classes():
    """An HTTP-handler class defined inside a function (the
    object_store_emulator shape) must not escape the engine's view."""
    src = '''
def make_server(store):
    class Handler:
        def do_GET(self):
            try:
                store.read("p")
            except BaseException:
                pass
    return Handler
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-swallow" and "Handler.do_GET" in f.message


def test_crash_tmpfile_fires_without_finally_quiet_with():
    src = '''
import os

def leaky(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)

def clean(path, data):
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    finally:
        os.unlink(tmp)
'''
    [f] = _run(CrashSafetyPass(), {"delta_tpu/mod.py": src})
    assert f.rule == "crash-tmpfile" and "leaky" in f.message


# -- config-registry ---------------------------------------------------------

_MINI_CONFIG = '''
class SqlConf:
    _DEFAULTS = {
        "delta.tpu.good.knob": 1,
        "delta.tpu.dead.knob": 2,
        "delta.tpu.dynamic.family.a": 3,
    }
'''


def test_config_unregistered_and_dead_keys():
    src = '''
from delta_tpu.utils.config import conf

def f():
    conf.get("delta.tpu.good.knob")
    conf.get("delta.tpu.good.knob.typo", 5)
'''
    fs = _run(ConfigRegistryPass(), {
        "delta_tpu/utils/config.py": _MINI_CONFIG,
        "delta_tpu/mod.py": src,
    })
    by_rule = {f.rule: f for f in fs}
    assert "config-unregistered" in by_rule
    assert "delta.tpu.good.knob.typo" in by_rule["config-unregistered"].message
    dead = [f for f in fs if f.rule == "config-dead"]
    assert {m for f in dead for m in [f.message]} and len(dead) == 2
    assert any("delta.tpu.dead.knob" in f.message for f in dead)


def test_config_dynamic_fstring_prefix_shields_dead_keys():
    src = '''
from delta_tpu.utils.config import conf

def f(which):
    conf.get("delta.tpu.good.knob")
    conf.get("delta.tpu.dead.knob")
    conf.get(f"delta.tpu.dynamic.family.{which}")
'''
    fs = _run(ConfigRegistryPass(), {
        "delta_tpu/utils/config.py": _MINI_CONFIG,
        "delta_tpu/mod.py": src,
    })
    assert fs == []  # the f-string prefix covers the dynamic family


def test_config_fstring_outside_conf_read_does_not_shield():
    """Only an f-string READ exempts a family: a log-message f-string with
    the same prefix must not mute config-dead for those keys."""
    src = '''
from delta_tpu.utils.config import conf

def f(which):
    conf.get("delta.tpu.good.knob")
    conf.get("delta.tpu.dead.knob")
    print(f"delta.tpu.dynamic.family.{which} disabled")
'''
    fs = _run(ConfigRegistryPass(), {
        "delta_tpu/utils/config.py": _MINI_CONFIG,
        "delta_tpu/mod.py": src,
    })
    [f] = fs
    assert f.rule == "config-dead" and "dynamic.family.a" in f.message


def test_config_bare_prefix_read_does_not_neuter_dead_rule():
    """conf.get(f"delta.tpu.{x}") must not shield every registered key —
    a dynamic read exempts only a named family."""
    src = '''
from delta_tpu.utils.config import conf

def f(which):
    conf.get("delta.tpu.good.knob")
    conf.get("delta.tpu.dynamic.family.a")
    conf.get(f"delta.tpu.{which}")
'''
    fs = _run(ConfigRegistryPass(), {
        "delta_tpu/utils/config.py": _MINI_CONFIG,
        "delta_tpu/mod.py": src,
    })
    [f] = fs
    assert f.rule == "config-dead" and "delta.tpu.dead.knob" in f.message


def test_config_pass_silent_without_registry_file():
    src = 'from delta_tpu.utils.config import conf\nconf.get("delta.tpu.x")\n'
    assert _run(ConfigRegistryPass(), {"delta_tpu/mod.py": src}) == []


# -- pool-naming -------------------------------------------------------------


def test_pool_name_missing_unregistered_and_registered():
    src = '''
import threading
from concurrent.futures import ThreadPoolExecutor

def f(work):
    threading.Thread(target=work)                      # missing
    threading.Thread(target=work, name="rogue-lane")   # unregistered
    threading.Thread(target=work, name="delta-journal-writer")  # ok
    ThreadPoolExecutor(max_workers=2)                  # missing
    ThreadPoolExecutor(max_workers=2,
                       thread_name_prefix="delta-scan-decode")  # ok
'''
    fs = _run(PoolNamingPass(), {"delta_tpu/mod.py": src})
    assert _rules(fs) == ["pool-name"] and len(fs) == 3
    assert any("rogue-lane" in f.message for f in fs)


# -- telemetry-spans ---------------------------------------------------------


def test_span_missing_fires_and_instrumented_entry_quiet():
    bad = '''
class DoThing:
    def run(self):
        return 1
'''
    good = '''
from delta_tpu.utils.telemetry import record_operation

class DoThing:
    def run(self):
        with record_operation("delta.utility.thing"):
            return 1

def helper(x):
    return x  # no delta_log first arg: not an entry point
'''
    [f] = _run(TelemetrySpansPass(), {"delta_tpu/commands/thing.py": bad})
    assert f.rule == "span-missing" and "DoThing.run" in f.message
    assert _run(TelemetrySpansPass(),
                {"delta_tpu/commands/thing.py": good}) == []
    # exempt modules and non-command files never fire
    assert _run(TelemetrySpansPass(),
                {"delta_tpu/commands/dml_common.py": bad,
                 "delta_tpu/exec/thing.py": bad}) == []


# -- metric catalog + descriptions -------------------------------------------

_MINI_CATALOG = '''
GAUGES = frozenset({"g.one"})
COUNTERS = frozenset({"obs.hits"})
ENGINE_COUNTERS = frozenset({"scan.files"})
HISTOGRAMS = frozenset({"op.ms"})
DESCRIPTIONS = {
    "g.one": "A gauge.",
    "obs.hits": "Obs counter.",
    "scan.files": "Engine counter.",
    "op.ms": "A histogram.",
}
'''


def test_metric_uncataloged_fires_and_cataloged_quiet():
    src = '''
from delta_tpu.utils import telemetry

def f():
    telemetry.set_gauge("g.one", 1)
    telemetry.set_gauge("g.stray", 1)
    telemetry.bump_counter("scan.files")
    telemetry.bump_counter("scan.stray")
    telemetry.bump_counter("obs.stray")
    telemetry.observe("op.ms", 2.0)
    telemetry.observe("op.stray", 2.0)
'''
    fs = _run(MetricCatalogPass(), {
        "delta_tpu/obs/metric_names.py": _MINI_CATALOG,
        "delta_tpu/exec/mod.py": src,
    })
    assert _rules(fs) == ["metric-uncataloged"] and len(fs) == 4
    msgs = " | ".join(f.message for f in fs)
    assert "g.stray" in msgs and "scan.stray" in msgs \
        and "obs.stray" in msgs and "op.stray" in msgs


def test_metric_name_constant_resolves_to_catalog():
    # a bare-name first argument resolves when the file binds it exactly
    # once as a module-level constant string — the `_METRIC = "x.y"` idiom
    # can no longer hide an uncataloged call site
    src = '''
from delta_tpu.utils import telemetry

_HIT = "obs.hits"
_STRAY = "obs.veryStray"
_REBOUND = "obs.rebound"
_ANN: str = "op.stray"

def f(flag):
    global _REBOUND
    telemetry.bump_counter(_HIT)      # cataloged: quiet
    telemetry.bump_counter(_STRAY)    # resolved, uncataloged: fires
    telemetry.observe(_ANN, 2.0)      # AnnAssign resolves too: fires
    telemetry.bump_counter(_REBOUND)  # global-declared: opaque, quiet
    local = "obs.local"
    telemetry.bump_counter(local)     # shadowable local binding: quiet
'''
    fs = _run(MetricCatalogPass(), {
        "delta_tpu/obs/metric_names.py": _MINI_CATALOG,
        "delta_tpu/exec/mod.py": src,
    })
    assert _rules(fs) == ["metric-uncataloged"] and len(fs) == 2
    msgs = " | ".join(f.message for f in fs)
    assert "obs.veryStray" in msgs and "op.stray" in msgs
    assert "obs.hits" not in msgs and "obs.rebound" not in msgs


def test_metric_name_shadowed_constant_stays_opaque():
    # the same identifier bound twice anywhere in the file — a parameter, a
    # loop variable, a second assign — must not resolve: we count bindings
    # instead of doing scope analysis, so shadowing means silence, not a
    # wrong-name finding
    src = '''
from delta_tpu.utils import telemetry

_NAME = "obs.aliased"

def f(_NAME):
    telemetry.bump_counter(_NAME)
'''
    fs = _run(MetricCatalogPass(), {
        "delta_tpu/obs/metric_names.py": _MINI_CATALOG,
        "delta_tpu/exec/mod.py": src,
    })
    assert fs == []


def test_metric_overlap_and_obs_feed_counter_rule():
    catalog = _MINI_CATALOG.replace(
        'ENGINE_COUNTERS = frozenset({"scan.files"})',
        'ENGINE_COUNTERS = frozenset({"scan.files", "obs.hits"})')
    src = '''
from delta_tpu.utils import telemetry

def f():
    telemetry.bump_counter("maintenance.sweeps")  # obs-feed, not in COUNTERS
'''
    fs = _run(MetricCatalogPass(), {
        "delta_tpu/obs/metric_names.py": catalog,
        "delta_tpu/exec/mod.py": src,
    })
    assert sorted(_rules(fs)) == ["metric-overlap", "metric-uncataloged"]


def test_metric_descriptions_missing_stale_multiline():
    catalog = '''
GAUGES = frozenset({"g.documented", "g.undocumented", "g.multiline"})
COUNTERS = frozenset(set())
ENGINE_COUNTERS = frozenset(set())
HISTOGRAMS = frozenset(set())
DESCRIPTIONS = {
    "g.documented": "Fine.",
    "g.multiline": "Two\\nlines.",
    "g.gone": "Documents nothing.",
}
'''
    fs = _run(MetricDescriptionsPass(),
              {"delta_tpu/obs/metric_names.py": catalog})
    assert _rules(fs) == ["metric-multiline-description",
                          "metric-stale-description", "metric-undocumented"]


# -- suppression mechanics ---------------------------------------------------


def test_inline_and_standalone_waivers_scope_to_rule_and_line():
    src = '''
import threading
from concurrent.futures import ThreadPoolExecutor

def f(work):
    threading.Thread(target=work)  # delta-lint: ignore[pool-name] -- test rig
    # delta-lint: ignore[pool-name] -- standalone waiver form
    ThreadPoolExecutor(max_workers=2)
    threading.Thread(target=work)  # delta-lint: ignore[other-rule]
'''
    ctx = AnalysisContext.from_sources({"delta_tpu/mod.py": src})
    kept, suppressed = apply_suppressions(
        ctx, run_passes(ctx, [PoolNamingPass()]))
    assert len(suppressed) == 2
    [f] = kept  # the wrong-rule waiver does not silence
    assert f.rule == "pool-name" and f.line == 9


# -- baseline round-trip + CLI ----------------------------------------------


def _mini_repo(tmp_path):
    pkg = tmp_path / "delta_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n\n"
        "def f(work):\n"
        "    threading.Thread(target=work)\n")
    return tmp_path


def test_cli_baseline_round_trip_and_exit_codes(tmp_path, capsys):
    from tools.analyze import main

    root = str(_mini_repo(tmp_path))
    baseline = str(tmp_path / "baseline.json")
    # dirty tree, no baseline: exit 1
    assert main(["--root", root, "--baseline", baseline]) == 1
    # accept the debt, then a clean run: exit 0 and the finding is baselined
    assert main(["--root", root, "--baseline", baseline,
                 "--update-baseline"]) == 0
    assert main(["--root", root, "--baseline", baseline]) == 0
    data = json.loads(open(baseline, encoding="utf-8").read())
    assert data["version"] == 1 and len(data["findings"]) == 1
    [key] = data["findings"]
    assert key.startswith("pool-name|delta_tpu/mod.py|")
    # --no-baseline shows the debt again
    assert main(["--root", root, "--baseline", baseline,
                 "--no-baseline"]) == 1
    capsys.readouterr()


def test_cli_json_output_shape(tmp_path, capsys):
    from tools.analyze import main

    root = str(_mini_repo(tmp_path))
    assert main(["--root", root, "--baseline", "", "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["clean"] is False
    assert out["counts"] == {"pool-name": 1}
    [f] = out["findings"]
    assert f["rule"] == "pool-name" and f["path"] == "delta_tpu/mod.py"
    assert out["filesAnalyzed"] == 1 and "lock-discipline" in out["passes"]


def test_cli_unknown_rule_is_usage_error(tmp_path):
    from tools.analyze import main

    assert main(["--root", str(_mini_repo(tmp_path)),
                 "--rule", "no-such-rule"]) == 2


def test_cli_update_baseline_rejects_rule_filter(tmp_path):
    """--rule + --update-baseline would rewrite the baseline from only the
    filtered passes, silently un-baselining every other rule's debt."""
    from tools.analyze import main

    assert main(["--root", str(_mini_repo(tmp_path)),
                 "--rule", "pool-name", "--update-baseline"]) == 2

def test_baseline_absorbs_counts_not_blanket(tmp_path):
    """Two identical violations with ONE baselined: exactly one new finding
    remains — the baseline is a counted ledger, not a rule-wide mute."""
    from tools.analyze import main

    root = _mini_repo(tmp_path)
    baseline = str(tmp_path / "baseline.json")
    assert main(["--root", str(root), "--baseline", baseline,
                 "--update-baseline"]) == 0
    # a second identical construction appears
    (root / "delta_tpu" / "mod.py").write_text(
        "import threading\n\n"
        "def f(work):\n"
        "    threading.Thread(target=work)\n"
        "    threading.Thread(target=work)\n")
    report = analyze_repo(root=str(root), baseline_path=baseline)
    assert len(report.findings) == 1 and len(report.baselined) == 1


def test_baseline_surplus_is_reported_stale(tmp_path):
    """An accepted count larger than the current finding count is surplus —
    it would silently absorb a FUTURE identical violation, so the report
    flags it for regeneration."""
    from tools.analyze import main

    root = _mini_repo(tmp_path)
    (root / "delta_tpu" / "mod.py").write_text(
        "import threading\n\n"
        "def f(work):\n"
        "    threading.Thread(target=work)\n"
        "    threading.Thread(target=work)\n")
    baseline = str(tmp_path / "baseline.json")
    assert main(["--root", str(root), "--baseline", baseline,
                 "--update-baseline"]) == 0  # accepts count=2
    (root / "delta_tpu" / "mod.py").write_text(
        "import threading\n\n"
        "def f(work):\n"
        "    threading.Thread(target=work)\n")  # debt shrinks to 1
    report = analyze_repo(root=str(root), baseline_path=baseline)
    assert report.clean and len(report.baselined) == 1
    [stale] = report.stale_baseline
    assert stale.startswith("pool-name|delta_tpu/mod.py|")
    # a rule-filtered run must NOT call other rules' debt surplus: only
    # entries the chosen passes could have matched are judged
    filtered = analyze_repo(root=str(root), baseline_path=baseline,
                            passes=[p for p in all_passes()
                                    if p.name == "crash-safety"])
    assert filtered.stale_baseline == []


# -- the tier-1 gate ---------------------------------------------------------


def test_seven_passes_registered():
    names = [p.name for p in all_passes()]
    assert names == ["lock-discipline", "crash-safety", "config-registry",
                     "pool-naming", "telemetry-spans", "metric-catalog",
                     "metric-descriptions"]
    rules = [r for p in all_passes() for r in p.rules]
    assert len(rules) == len(set(rules)), "rule names must be globally unique"


def test_engine_runs_clean_over_the_real_package():
    """THE gate: zero non-baselined findings over delta_tpu/ with the
    checked-in baseline. A new finding means: fix it, waive it inline with
    a justification, or (for accepted debt) run
    ``python tools/analyze.py --update-baseline`` and justify the diff."""
    report = analyze_repo()
    assert report.files_analyzed > 100  # the real package, not a stub
    msg = "\n".join(f.format() for f in report.findings)
    assert report.clean, f"non-baselined static-analysis findings:\n{msg}"
    # the checked-in baseline holds no stale keys either
    assert report.stale_baseline == []


def test_checked_in_baseline_exists_and_parses():
    path = os.path.join(repo_root(), "tools", "analyze_baseline.json")
    data = json.loads(open(path, encoding="utf-8").read())
    assert data["version"] == 1
    assert isinstance(data["findings"], dict)
