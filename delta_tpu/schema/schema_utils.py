"""Schema enforcement & evolution rules.

Reference: ``schema/SchemaUtils.scala`` (1,112 lines — the behavioral spec,
per SURVEY §7 "Hard parts"). Key semantics reproduced here:

* column-name hygiene (``checkFieldNames :1049``);
* case-insensitive (but case-preserving) column resolution;
* write-compatibility enforcement: data columns must exist in the table
  schema unless ``mergeSchema`` evolution is requested;
* ``merge_schemas`` (``:817``): recursive struct/array/map merge, new fields
  appended at the end, NullType upgraded, type conflicts rejected (with an
  opt-in widening lattice for CONVERT's parquet import);
* ``is_read_compatible`` (``:265``) for streaming schema-change detection;
* ALTER helpers: add/drop column at a position, ``can_change_data_type``.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from delta_tpu.schema.types import (
    ArrayType,
    ByteType,
    DataType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    ShortType,
    StructField,
    StructType,
)
from delta_tpu.utils.errors import DeltaAnalysisError, SchemaMismatchError
from delta_tpu.utils import errors

__all__ = [
    "check_column_names",
    "check_column_name_duplication",
    "check_partition_columns",
    "find_field",
    "find_column_position",
    "merge_schemas",
    "enforce_write_compatibility",
    "normalize_column_names",
    "is_read_compatible",
    "add_column",
    "drop_column",
    "drop_column_at",
    "replace_column_at",
    "can_change_data_type",
    "column_path_to_name",
    "ARRAY_ELEMENT_INDEX",
    "MAP_KEY_INDEX",
    "MAP_VALUE_INDEX",
]

# Nested-position markers inside non-struct containers (SchemaUtils.scala:44-46)
ARRAY_ELEMENT_INDEX = 0
MAP_KEY_INDEX = 0
MAP_VALUE_INDEX = 1

# checkFieldNames (SchemaUtils.scala:1049): these break Parquet/Hive paths.
_INVALID_CHARS = set(' ,;{}()\n\t=')


def check_column_names(schema: StructType) -> None:
    def walk(dt: DataType, path: str):
        if isinstance(dt, StructType):
            for f in dt.fields:
                bad = [c for c in f.name if c in _INVALID_CHARS]
                if bad:
                    raise errors.invalid_column_name(path + f.name)
                walk(f.data_type, path + f.name + ".")
        elif isinstance(dt, ArrayType):
            walk(dt.element_type, path)
        elif isinstance(dt, MapType):
            walk(dt.key_type, path)
            walk(dt.value_type, path)

    walk(schema, "")


def check_partition_columns(partition_columns: Sequence[str], schema: StructType) -> None:
    names = {f.name.lower() for f in schema.fields}
    for c in partition_columns:
        if c.lower() not in names:
            raise errors.partition_column_not_found(c, schema.simple_string())


def find_field(schema: StructType, name: str) -> Optional[StructField]:
    """Case-insensitive lookup; dotted names traverse nested structs, with
    ``element`` / ``key`` / ``value`` stepping through arrays and maps."""
    parts = name.split(".")
    current: DataType = schema
    field = None
    for p in parts:
        low = p.lower()
        if isinstance(current, ArrayType) and low == "element":
            current = current.element_type
            continue
        if isinstance(current, MapType) and low in ("key", "value"):
            current = current.key_type if low == "key" else current.value_type
            continue
        if not isinstance(current, StructType):
            return None
        field = next((f for f in current.fields if f.name.lower() == low), None)
        if field is None:
            return None
        current = field.data_type
    return field


def column_path_to_name(path: Sequence[str]) -> str:
    return ".".join(path)


# ---------------------------------------------------------------------------
# Schema merging (evolution)
# ---------------------------------------------------------------------------

# Opt-in widening for parquet imports (CONVERT TO DELTA), matching the
# allowed conversions in mergeSchemas(allowImplicitConversions=true).
_WIDENING: List[Tuple[type, type]] = [
    (ByteType, ShortType),
    (ByteType, IntegerType),
    (ByteType, LongType),
    (ShortType, IntegerType),
    (ShortType, LongType),
    (IntegerType, LongType),
    (FloatType, DoubleType),
]


def _can_widen(from_t: DataType, to_t: DataType) -> bool:
    return any(isinstance(from_t, a) and isinstance(to_t, b) for a, b in _WIDENING)


# Numeric precedence for implicit SQL casts (Spark's TypeCoercion order):
# a type can implicitly cast to any type with higher precedence.
_NUMERIC_PRECEDENCE: List[type] = [
    ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType,
]


def _precedence(t: DataType) -> Optional[int]:
    for i, cls in enumerate(_NUMERIC_PRECEDENCE):
        if isinstance(t, cls):
            return i
    return None


def check_column_name_duplication(schema: StructType, context: str) -> None:
    """Reject case-insensitively duplicated column names, at any nesting
    level (the reference delegates to Spark's SchemaUtils before merging)."""

    def walk(dt: DataType, path: str):
        if isinstance(dt, StructType):
            seen = {}
            for f in dt.fields:
                low = f.name.lower()
                if low in seen:
                    raise errors.duplicate_columns(
                        context, f"{path}{seen[low]}", f"{path}{f.name}")
                seen[low] = f.name
                walk(f.data_type, path + f.name + ".")
        elif isinstance(dt, ArrayType):
            walk(dt.element_type, path + "element.")
        elif isinstance(dt, MapType):
            walk(dt.key_type, path + "key.")
            walk(dt.value_type, path + "value.")

    walk(schema, "")


def merge_schemas(
    current: StructType,
    new: StructType,
    allow_implicit_conversions: bool = False,
    keep_existing_type: bool = False,
    fixed_type_columns: Iterable[str] = (),
    path: str = "",
) -> StructType:
    """Merge ``new`` into ``current`` (``SchemaUtils.scala:817-922``):
    existing columns keep the current name case, position, nullability and
    metadata; new columns are appended. Byte/short/int always unify to the
    widest (Parquet stores all three as INT32, ``:901-909``);
    ``allow_implicit_conversions`` additionally accepts any valid implicit
    numeric cast (MERGE evolution, ``PreprocessTableMerge.scala:71``);
    ``keep_existing_type`` keeps the current type for any primitive clash
    (metadata-only evolution); ``fixed_type_columns`` (generated columns)
    may not change type at all."""
    if not path:
        check_column_name_duplication(new, "in the data to save")
    fixed = {c.lower() for c in fixed_type_columns}
    merged: List[StructField] = []
    new_by_lower = {f.name.lower(): f for f in new.fields}
    for cur in current.fields:
        incoming = new_by_lower.pop(cur.name.lower(), None)
        if incoming is None:
            merged.append(cur)
            continue
        if (
            not path
            and cur.name.lower() in fixed
            and cur.data_type != incoming.data_type
        ):
            raise errors.generated_column_type_change(
                cur.name, cur.data_type.simple_string())
        merged_type = _merge_types(
            cur.data_type, incoming.data_type, allow_implicit_conversions,
            keep_existing_type, path + cur.name,
        )
        # the reference keeps the CURRENT field's nullability and metadata
        merged.append(
            StructField(cur.name, merged_type, cur.nullable, dict(cur.metadata))
        )
    # Append genuinely new fields, preserving their order in `new`.
    remaining = set(new_by_lower)
    for f in new.fields:
        if f.name.lower() in remaining:
            merged.append(f)
    return StructType(merged)


def _merge_types(
    cur: DataType, new: DataType, widen: bool, keep_existing: bool, path: str
) -> DataType:
    from delta_tpu.schema.types import DecimalType

    if isinstance(cur, StructType) and isinstance(new, StructType):
        return merge_schemas(
            cur, new, widen, keep_existing, path=path + ".",
        )
    if isinstance(cur, ArrayType) and isinstance(new, ArrayType):
        return ArrayType(
            _merge_types(cur.element_type, new.element_type, widen, keep_existing,
                         path + ".element"),
            cur.contains_null,
        )
    if isinstance(cur, MapType) and isinstance(new, MapType):
        return MapType(
            _merge_types(cur.key_type, new.key_type, widen, keep_existing,
                         path + ".key"),
            _merge_types(cur.value_type, new.value_type, widen, keep_existing,
                         path + ".value"),
            cur.value_contains_null,
        )
    if isinstance(cur, NullType):
        return new
    if isinstance(new, NullType):
        return cur
    if cur == new:
        return cur
    if keep_existing and not isinstance(cur, (StructType, ArrayType, MapType)):
        return cur
    if widen:
        # implicit SQL cast: new side may cast up to current, or vice versa
        pc, pn = _precedence(cur), _precedence(new)
        if pc is not None and pn is not None:
            return cur if pn <= pc else new
    if isinstance(cur, DecimalType) and isinstance(new, DecimalType):
        if cur.precision != new.precision and cur.scale != new.scale:
            raise SchemaMismatchError(
                f"Failed to merge decimal types with incompatible precision "
                f"{cur.precision} and {new.precision} & scale {cur.scale} and {new.scale}"
            )
        if cur.precision != new.precision:
            raise SchemaMismatchError(
                f"Failed to merge decimal types with incompatible precision "
                f"{cur.precision} and {new.precision}"
            )
        raise SchemaMismatchError(
            f"Failed to merge decimal types with incompatible scale "
            f"{cur.scale} and {new.scale}"
        )
    # Parquet stores byte/short/int as INT32: always unify to the widest
    int32_family = (ByteType, ShortType, IntegerType)
    if isinstance(cur, int32_family) and isinstance(new, int32_family):
        order = {ByteType: 0, ShortType: 1, IntegerType: 2}
        return cur if order[type(cur)] >= order[type(new)] else new
    raise SchemaMismatchError(
        f"Failed to merge fields '{path}': incompatible types "
        f"{cur.simple_string()} and {new.simple_string()}"
    )


# ---------------------------------------------------------------------------
# Write enforcement
# ---------------------------------------------------------------------------

def enforce_write_compatibility(table_schema: StructType, data_schema: StructType) -> None:
    """Reject writes whose columns don't exist in the table (the
    ``A schema mismatch detected`` error family). Missing table columns in
    the data are fine (filled with nulls). Type equality is checked for
    overlapping columns (after normalization casts are the writer's job)."""
    extra = []
    mismatched = []
    table_by_lower = {f.name.lower(): f for f in table_schema.fields}
    for f in data_schema.fields:
        t = table_by_lower.get(f.name.lower())
        if t is None:
            extra.append(f.name)
        elif not _write_type_compatible(f.data_type, t.data_type):
            mismatched.append(
                f"{f.name}: data {f.data_type.simple_string()} vs table {t.data_type.simple_string()}"
            )
    if extra or mismatched:
        raise SchemaMismatchError(
            "A schema mismatch detected when writing to the Delta table.\n"
            + (f"Data columns not in the table schema: {extra}.\n" if extra else "")
            + (f"Type mismatches: {mismatched}.\n" if mismatched else "")
            + "To allow schema migration, set option mergeSchema=true."
        )


def _write_type_compatible(data_t: DataType, table_t: DataType) -> bool:
    """Data can be written into the table column: equal type, NullType, or an
    implicit numeric widening the write path will cast."""
    if data_t == table_t or isinstance(data_t, NullType):
        return True
    if _can_widen(data_t, table_t):
        return True
    if isinstance(data_t, StructType) and isinstance(table_t, StructType):
        table_by_lower = {f.name.lower(): f for f in table_t.fields}
        for f in data_t.fields:
            t = table_by_lower.get(f.name.lower())
            if t is None or not _write_type_compatible(f.data_type, t.data_type):
                return False
        return True
    if isinstance(data_t, ArrayType) and isinstance(table_t, ArrayType):
        return _write_type_compatible(data_t.element_type, table_t.element_type)
    if isinstance(data_t, MapType) and isinstance(table_t, MapType):
        return _write_type_compatible(data_t.key_type, table_t.key_type) and _write_type_compatible(
            data_t.value_type, table_t.value_type
        )
    return False


def normalize_column_names(table_schema: StructType, data_schema: StructType) -> List[Tuple[str, str]]:
    """(data_name, table_name) casing fixups (``normalizeColumnNames :223``)."""
    out = []
    table_by_lower = {f.name.lower(): f for f in table_schema.fields}
    for f in data_schema.fields:
        t = table_by_lower.get(f.name.lower())
        if t is not None and t.name != f.name:
            out.append((f.name, t.name))
    return out


def is_read_compatible(existing: StructType, new: StructType) -> bool:
    """Can data written with ``existing`` still be read as ``new``?
    (``isReadCompatible :265``) — new must contain every existing column with
    the same type and must not tighten nullability."""
    new_by_lower = {f.name.lower(): f for f in new.fields}
    for f in existing.fields:
        n = new_by_lower.get(f.name.lower())
        if n is None:
            return False
        if not _type_read_compatible(f.data_type, n.data_type):
            return False
        if f.nullable and not n.nullable:
            return False
    return True


def _type_read_compatible(old: DataType, new: DataType) -> bool:
    if isinstance(old, StructType) and isinstance(new, StructType):
        return is_read_compatible(old, new)
    if isinstance(old, ArrayType) and isinstance(new, ArrayType):
        return _type_read_compatible(old.element_type, new.element_type)
    if isinstance(old, MapType) and isinstance(new, MapType):
        return _type_read_compatible(old.key_type, new.key_type) and _type_read_compatible(
            old.value_type, new.value_type
        )
    return old == new


# ---------------------------------------------------------------------------
# ALTER helpers
# ---------------------------------------------------------------------------

def add_column(
    schema: StructType,
    field: StructField,
    position: Optional[Sequence[int]] = None,
) -> StructType:
    """Insert ``field`` at ``position`` (``addColumn :573-651``).

    ``position`` is a list of 0-based ordinals denoting a path through
    nested structs — e.g. ``[2, 1]`` inserts at index 1 inside the struct at
    top-level index 2. Inside containers, path steps use
    ``ARRAY_ELEMENT_INDEX`` / ``MAP_KEY_INDEX`` / ``MAP_VALUE_INDEX``. An
    int or None keeps the historical top-level behavior (None = append)."""
    if position is None:
        position = [len(schema.fields)]
    elif isinstance(position, int):
        position = [min(position, len(schema.fields))]
    position = list(position)
    if not position:
        raise errors.add_column_anchor_not_found(field.name)
    slice_pos = position[0]
    if slice_pos < 0:
        raise errors.add_column_index_below_zero(slice_pos, field.name)
    length = len(schema.fields)
    if slice_pos > length:
        raise errors.add_column_index_too_large(slice_pos, field.name, length)
    if len(position) == 1 and any(
        f.name.lower() == field.name.lower() for f in schema.fields
    ):
        raise errors.column_already_exists(field.name)
    if slice_pos == length:
        if len(position) > 1:
            raise errors.struct_not_found_at_position(slice_pos)
        return StructType(list(schema.fields) + [field])
    fields = list(schema.fields)
    if len(position) == 1:
        fields.insert(slice_pos, field)
        return StructType(fields)

    parent = fields[slice_pos]
    tail = position[1:]
    if not field.nullable and parent.nullable:
        raise DeltaAnalysisError(
            "A non-nullable nested field can't be added to a nullable parent. "
            "Please set the nullability of the parent column accordingly."
        )
    dt = parent.data_type
    if isinstance(dt, StructType):
        new_dt: DataType = add_column(dt, field, tail)
    elif isinstance(dt, ArrayType) and isinstance(dt.element_type, StructType):
        if tail[0] != ARRAY_ELEMENT_INDEX:
            raise DeltaAnalysisError(
                "Incorrectly accessing an ArrayType. Use arrayname.element."
                "elementname position to add to an array."
            )
        new_dt = ArrayType(
            add_column(dt.element_type, field, tail[1:]), dt.contains_null
        )
    elif isinstance(dt, MapType):
        if tail[0] == MAP_KEY_INDEX and isinstance(dt.key_type, StructType):
            new_dt = MapType(
                add_column(dt.key_type, field, tail[1:]),
                dt.value_type, dt.value_contains_null,
            )
        elif tail[0] == MAP_VALUE_INDEX and isinstance(dt.value_type, StructType):
            new_dt = MapType(
                dt.key_type,
                add_column(dt.value_type, field, tail[1:]),
                dt.value_contains_null,
            )
        else:
            raise errors.parent_not_struct(field.name)
    else:
        raise errors.parent_not_struct(field.name, dt.simple_string())
    fields[slice_pos] = StructField(
        parent.name, new_dt, parent.nullable, dict(parent.metadata)
    )
    return StructType(fields)


def drop_column(schema: StructType, name: str) -> StructType:
    """Remove a top-level column by name (convenience over
    ``drop_column_at``; ``dropColumn :663``)."""
    kept = [f for f in schema.fields if f.name.lower() != name.lower()]
    if len(kept) == len(schema.fields):
        raise errors.column_not_in_schema(name)
    if not kept:
        raise DeltaAnalysisError("Cannot drop all columns from a table")
    return StructType(kept)


def replace_column_at(
    schema: StructType, position: Sequence[int], new_field: StructField
) -> StructType:
    """Replace the field at a nested struct ``position`` (CHANGE COLUMN's
    in-place edit; container-index steps are not valid here)."""
    position = list(position)
    if not position:
        raise DeltaAnalysisError("Don't know which column to replace")
    slice_pos = position[0]
    if not 0 <= slice_pos < len(schema.fields):
        raise errors.replace_column_index_oob(slice_pos)
    fields = list(schema.fields)
    if len(position) == 1:
        fields[slice_pos] = new_field
        return StructType(fields)
    parent = fields[slice_pos]
    new_dt = _descend_replace(
        parent.data_type, position[1:],
        lambda inner, tail: replace_column_at(inner, tail, new_field),
        "replace",
    )
    fields[slice_pos] = StructField(
        parent.name, new_dt, parent.nullable, dict(parent.metadata)
    )
    return StructType(fields)


def _descend_replace(dt: DataType, tail: Sequence[int], recurse, verb: str):
    """Shared container traversal for positional edits: struct positions
    index fields; array/map positions use ARRAY_ELEMENT_INDEX /
    MAP_KEY_INDEX / MAP_VALUE_INDEX (the steps `find_column_position`
    emits). ``recurse(inner_struct, remaining_tail)`` produces the edited
    struct."""
    tail = list(tail)
    if isinstance(dt, StructType):
        return recurse(dt, tail)
    if isinstance(dt, ArrayType) and isinstance(dt.element_type, StructType):
        if tail[0] != ARRAY_ELEMENT_INDEX:
            raise errors.array_access_needs_element_step(verb)
        return ArrayType(recurse(dt.element_type, tail[1:]), dt.contains_null)
    if isinstance(dt, MapType):
        if tail[0] == MAP_KEY_INDEX and isinstance(dt.key_type, StructType):
            return MapType(
                recurse(dt.key_type, tail[1:]), dt.value_type,
                dt.value_contains_null,
            )
        if tail[0] == MAP_VALUE_INDEX and isinstance(dt.value_type, StructType):
            return MapType(
                dt.key_type, recurse(dt.value_type, tail[1:]),
                dt.value_contains_null,
            )
    raise errors.nested_op_only_in_struct(verb, dt.simple_string())


def drop_column_at(
    schema: StructType, position: Sequence[int]
) -> Tuple[StructType, StructField]:
    """Drop the field at a nested ``position``; returns (new schema, dropped
    field) (``dropColumn :663-689``)."""
    position = list(position)
    if not position:
        raise DeltaAnalysisError("Don't know where to drop the column")
    slice_pos = position[0]
    if slice_pos < 0:
        raise errors.drop_column_index_below_zero(slice_pos)
    length = len(schema.fields)
    if slice_pos >= length:
        raise errors.drop_column_index_too_large(slice_pos, length)
    fields = list(schema.fields)
    if len(position) == 1:
        # an empty struct is legal here: CHANGE COLUMN moves are
        # drop-then-add, transiently emptying single-field structs; the
        # user-facing DROP path (`drop_column`) still refuses emptying a table
        dropped = fields.pop(slice_pos)
        return StructType(fields), dropped
    parent = fields[slice_pos]
    box: List[StructField] = []

    def recurse(inner: StructType, tail):
        new_inner, dropped = drop_column_at(inner, tail)
        box.append(dropped)
        return new_inner

    new_dt = _descend_replace(parent.data_type, position[1:], recurse, "drop")
    fields[slice_pos] = StructField(
        parent.name, new_dt, parent.nullable, dict(parent.metadata)
    )
    return StructType(fields), box[0]


def find_column_position(column: Sequence[str], schema: StructType) -> List[int]:
    """Resolve a dotted column path to nested ordinals
    (``findColumnPosition :480-530``): struct fields by case-insensitive
    name; ``element`` steps into an array's struct element, ``key``/``value``
    into a map's struct sides."""
    out: List[int] = []
    current: DataType = schema
    parts = list(column)
    i = 0
    while i < len(parts):
        name = parts[i]
        if not isinstance(current, StructType):
            if isinstance(current, ArrayType):
                if name.lower() != "element":
                    raise errors.array_access_element_path_hint(
                        '.'.join(parts[:i] + ['element'] + parts[i:]))
                out.append(ARRAY_ELEMENT_INDEX)
                current = current.element_type
                i += 1
                continue
            if isinstance(current, MapType):
                if name.lower() == "key":
                    out.append(MAP_KEY_INDEX)
                    current = current.key_type
                elif name.lower() == "value":
                    out.append(MAP_VALUE_INDEX)
                    current = current.value_type
                else:
                    raise errors.map_access_needs_key_or_value(name)
                i += 1
                continue
            raise errors.column_path_not_nested('.'.join(parts))
        pos = next(
            (j for j, f in enumerate(current.fields) if f.name.lower() == name.lower()),
            -1,
        )
        if pos == -1:
            raise errors.column_path_not_found(
                '.'.join(parts[: i + 1]), schema.simple_string())
        out.append(pos)
        current = current.fields[pos].data_type
        i += 1
    return out


def can_change_data_type(from_t: DataType, to_t: DataType) -> bool:
    """ALTER CHANGE COLUMN type changes: NullType→anything, value-preserving
    numeric widening, or nested containers whose element change is legal.
    (Comment/nullability-loosening changes are handled by the caller.)

    Deliberate divergence from the reference (``SchemaUtils.scala:694``,
    which allows only NullType→anything and nested recursion): we also
    accept the ``_WIDENING`` lattice (byte→short→int→long, float→double).
    Widening is lossless, our Arrow read path casts old files up to the
    table schema on scan, and the write path normalizes new data to the
    widened type — so the strictness the reference needs to protect its
    fixed-width Parquet readers does not apply here.
    """
    if isinstance(from_t, NullType):
        return True
    if _can_widen(from_t, to_t):
        return True
    if isinstance(from_t, StructType) and isinstance(to_t, StructType):
        from_by_lower = {f.name.lower(): f for f in from_t.fields}
        seen = set()
        for t in to_t.fields:
            f = from_by_lower.get(t.name.lower())
            if f is None:
                # adding a column mid-change is legal only when nullable
                # (SchemaUtils.scala:731-733)
                if not t.nullable:
                    return False
                continue
            seen.add(t.name.lower())
            # tightening nullability is never legal (:705-707)
            if f.nullable and not t.nullable:
                return False
            if not can_change_data_type(f.data_type, t.data_type):
                return False
        # dropping columns via CHANGE COLUMN is not legal (:735-737)
        if len(seen) < len(from_t.fields):
            return False
        return True
    if isinstance(from_t, ArrayType) and isinstance(to_t, ArrayType):
        if from_t.contains_null and not to_t.contains_null:
            return False
        return can_change_data_type(from_t.element_type, to_t.element_type)
    if isinstance(from_t, MapType) and isinstance(to_t, MapType):
        if from_t.value_contains_null and not to_t.value_contains_null:
            return False
        return can_change_data_type(from_t.key_type, to_t.key_type) and can_change_data_type(
            from_t.value_type, to_t.value_type
        )
    return from_t == to_t
