"""Layout advisor — the longitudinal counterpart of the point-in-time doctor.

`obs/doctor` reads the CURRENT snapshot and ranks debt; this module reads
the persistent workload journal (`obs/journal`) and answers the question the
doctor cannot: *given the queries this table actually serves, what layout
should it have?* "Only Aggressive Elephants are Fast Elephants" (PAPERS.md)
shows metadata-layer layout tuning is safe and decisive once a workload
trace exists to drive it; "Optimal Predicate Pushdown Synthesis" needed
exactly the evidence collected here — which predicate shapes never pruned
and why — and `expr/synthesis` (PR 12) now consumes it: ``neverPruned``
splits layout vs shape vs synthesized-but-layout-bound vs stale history.

:func:`advise` aggregates journal history into **workload facts** (hot
columns by filter frequency, predicates that never pruned split by reason,
partition-access skew, commit-contention windows, the MERGE key-cache hit
trajectory) and emits ranked, evidence-backed :class:`Recommendation`\\ s:

* ``ZORDER`` / ``PARTITION`` — a frequently-filtered non-layout column
  whose scans almost never prune (cited: filter count, pruning miss rate);
* ``ROW_GROUP_SIZE`` — prunable predicates over files with ~1 row group
  each (nothing for the second tier to skip);
* ``CHECKPOINT_INTERVAL`` — sustained commit traffic with scan planning
  dominated by log-tail replay;
* ``COMMIT_CONTENTION`` — retry-heavy commit windows (scopes the
  group-commit work, ROADMAP item 3);
* ``CALIBRATION`` / ``HBM_BUDGET`` — router hindsight misses, or repeated
  cold device uploads that a larger resident key-cache budget would absorb.

Surfaced as ``DeltaTable.advise()``, the HTTP ``/advisor`` route, and
``tools/journal_dump.py --advise``. With the journal inert (telemetry
blackout or ``delta.tpu.journal.enabled=false``) or empty, the report
degrades to an explicit ``status="no history"`` — never a fabricated
recommendation.
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.obs import actions as actions_mod
from delta_tpu.obs import journal as journal_mod
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["Recommendation", "AdvisorReport", "advise"]

# thresholds — like the doctor's, deliberately simple and visible
ZORDER_MIN_FILTERS = 3
ZORDER_MIN_MISS_RATE = 0.5
PARTITION_MIN_FILTERS = 5
PARTITION_EQ_FRACTION = 0.8
ROW_GROUPS_PER_FILE_FLOOR = 1.5
CHECKPOINT_MIN_COMMITS = 20
CHECKPOINT_PLANNING_MS = 50.0
CONTENTION_MIN_COMMITS = 10
CONTENTION_RETRY_FRACTION = 0.2
CONTENTION_WINDOW_MS = 60_000
CALIBRATION_MIN_AUDITS = 5
CALIBRATION_MISS_RATE = 0.3
HBM_MIN_COLD_MERGES = 3
HBM_MAX_HIT_RATE = 0.25


@dataclass
class Recommendation:
    """One ranked, evidence-backed layout/tuning suggestion."""

    kind: str          # ZORDER | PARTITION | ROW_GROUP_SIZE | ...
    target: str        # column name or conf key
    score: float       # ranking weight (higher = stronger evidence)
    action: str        # the concrete command / conf change
    detail: str
    evidence: Dict[str, Any] = field(default_factory=dict)
    #: catalog key of the maintenance action that executes (or cites) this
    #: recommendation — `obs/actions.CATALOG`, resolved per kind at emit
    #: time so the autopilot consumes it without string matching
    remedy: str = ""
    #: latest shadow-run verdict covering this (kind, target), when one
    #: exists (`replay/shadow.shadow_verdicts`): measured score/deltas plus
    #: ``verdict`` confirmed|refuted|inconclusive. A DEDICATED field, not
    #: evidence — the autopilot copies ``evidence`` into each action's
    #: ``predicted`` payload, and a shadow verdict is measured, not
    #: predicted.
    shadow: Optional[Dict[str, Any]] = None

    def __post_init__(self) -> None:
        if not self.remedy:
            self.remedy = actions_mod.remedy_name(
                actions_mod.RECOMMENDATION_ACTIONS[self.kind])

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "target": self.target,
            "score": round(self.score, 3),
            "action": self.action,
            "remedy": self.remedy,
            "detail": self.detail,
            "evidence": dict(self.evidence),
            "shadowVerdict": (self.shadow or {}).get("verdict", "untested"),
            "shadow": dict(self.shadow) if self.shadow else None,
        }


@dataclass
class AdvisorReport:
    path: str
    version: int
    generated_at_ms: int
    status: str                       # "ok" | "no history"
    entries: int                      # journal entries aggregated
    facts: Dict[str, Any] = field(default_factory=dict)
    recommendations: List[Recommendation] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "version": self.version,
            "generatedAt": self.generated_at_ms,
            "status": self.status,
            "entries": self.entries,
            "facts": dict(self.facts),
            "recommendations": [r.to_dict() for r in self.recommendations],
            # every recommendation's ``remedy`` is a key of the shared
            # maintenance Action catalog (same one the doctor cites)
            "remedyCatalog": actions_mod.CATALOG_REF,
            "doctor": "point-in-time debt: DeltaTable.doctor() / "
                      "GET /doctor?path=<table>",
        }


# ---------------------------------------------------------------------------
# Fact extraction
# ---------------------------------------------------------------------------


def _scan_pruned(report: Dict[str, Any]) -> bool:
    """Did pruning have any effect the filtered columns can take credit
    for? The STATS tier is measured downstream of partition pruning
    (``filesPruned`` counts BOTH tiers — on a partitioned table every scan
    would look 'pruned' and mask a column whose min/max stats never fire).
    A scan partition-pruned to zero files counts as pruned: no file
    survived for the stats tier to be tested against."""
    base = report.get("filesAfterPartition")
    if base is None:
        base = report.get("filesTotal") or 0
    if base == 0 and (report.get("filesTotal") or 0) > 0:
        return True  # the partition tier excluded everything
    stats_files = max(0, base - (report.get("filesScanned") or 0))
    return bool(stats_files or report.get("rowGroupsPruned")
                or report.get("rowGroupsLateSkipped"))


def _column_facts(scans: List[dict]) -> Dict[str, Dict[str, Any]]:
    """Per-column filter frequency + pruning outcomes, from the scan
    fingerprints. A scan 'missed' for a column when it filtered on the
    column and nothing was pruned at either tier. Scans over zero-file
    (empty) tables are neutral — pruning could not possibly have fired,
    so they must not fabricate miss evidence."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in scans:
        fp = e.get("fingerprint")
        rep = e.get("report") or {}
        if not fp or (rep.get("filesTotal") or 0) <= 0:
            continue
        pruned = _scan_pruned(rep)
        eq_cols = set()
        part_cols = set()
        prunable = set(fp.get("prunableColumns") or ())
        for c in fp.get("conjuncts") or ():
            if c.get("shape", "").startswith(("eq(", "in(")):
                eq_cols.update(c.get("columns") or ())
            if c.get("partition"):
                part_cols.update(c.get("columns") or ())
        for col in fp.get("columns") or ():
            f = out.setdefault(col, {"filters": 0, "misses": 0, "eq": 0,
                                     "prunable": 0, "partitionFilters": 0})
            f["filters"] += 1
            if not pruned:
                f["misses"] += 1
            if col in eq_cols:
                f["eq"] += 1
            if col in prunable:
                f["prunable"] += 1
            if col in part_cols:
                f["partitionFilters"] += 1
    for f in out.values():
        f["missRate"] = round(f["misses"] / f["filters"], 4)
        f["eqFraction"] = round(f["eq"] / f["filters"], 4)
    return out


#: Shape-string tokens the synthesis layer (expr/synthesis) has rules for —
#: the ``staleShape`` recognizer for journal entries recorded BEFORE the
#: feature existed (their fingerprints carry no ``synthesizable`` field, so
#: only the normalized shape can witness that a fresh scan would now prune).
#: ``func(`` covers pre-r12 fingerprints, which rendered EVERY named
#:  function as the ``Func`` class name — whether that specific function is
#: covered can't be recovered from the legacy shape, and "fresh scans will
#: prune it or reclassify" is exactly staleShape's promise.
_SYNTH_SHAPE_TOKENS = ("mul(", "add(", "sub(", "div(", "mod(", "neg(",
                       "cast(", "substr(", "substring(", "like(",
                       "startswith(", "year(", "to_date(", "date_add(",
                       "date_sub(", "func(", "abs(", "coalesce(",
                       "casewhen(")


def _shape_synthesizable(key: str) -> bool:
    return any(tok in key for tok in _SYNTH_SHAPE_TOKENS)


def _never_pruned(scans: List[dict]) -> List[Dict[str, Any]]:
    """Predicate fingerprints whose scans NEVER pruned, with the reason:
    residual-only shapes can't prune even with rewrite synthesis; prunable
    shapes that never fired point at layout (clustering), not semantics —
    split into base-evaluable (``layout``) vs synthesis-only
    (``synthesizedLayout``); pre-synthesis history whose shape is now
    covered gets ``staleShape`` instead of polluting either bucket."""
    by_key: Dict[str, Dict[str, Any]] = {}
    for e in scans:
        fp = e.get("fingerprint")
        if not fp or not fp.get("key"):
            continue
        if ((e.get("report") or {}).get("filesTotal") or 0) <= 0:
            continue  # empty-table scan: no pruning evidence either way
        conjuncts = fp.get("conjuncts") or ()
        g = by_key.setdefault(fp["key"], {
            "fingerprint": fp["key"], "scans": 0, "pruned": 0,
            "columns": fp.get("columns") or [],
            "prunable": False, "basePrunable": False,
            "synthInfo": False,
            "partition": bool(conjuncts) and all(
                c.get("partition") for c in conjuncts),
        })
        g["scans"] += 1
        g["prunable"] = g["prunable"] or bool(fp.get("prunableColumns"))
        for c in conjuncts:
            if "synthesizable" in c:
                g["synthInfo"] = True
                if c.get("prunable") and not c.get("synthesizable"):
                    g["basePrunable"] = True
            elif c.get("prunable"):
                # pre-synthesis entry: prunable meant base-evaluable
                g["basePrunable"] = True
        if _scan_pruned(e.get("report") or {}):
            g["pruned"] += 1
    out = []
    for g in by_key.values():
        if g["pruned"]:
            continue
        if g["partition"]:
            # the filter IS pushed down (partition tier, exact) — blaming
            # clustering or rewrite synthesis would both be wrong
            g["reason"] = (
                "partition: pushed down at the partition tier but its "
                "values never excluded a partition — check the value "
                "distribution / partitioning scheme")
        elif g["basePrunable"]:
            g["reason"] = (
                "layout: shape is min/max-evaluable but stats never "
                "excluded anything — the filtered columns are not "
                "clustered")
        elif g["prunable"]:
            g["reason"] = (
                "synthesizedLayout: shape lowers only via predicate "
                "synthesis and its rewrites never excluded anything — "
                "the referenced columns are not clustered (layout, not "
                "shape)")
        elif not g["synthInfo"] and _shape_synthesizable(g["fingerprint"]):
            g["reason"] = (
                "staleShape: recorded before predicate synthesis covered "
                "this shape — fresh scans will prune it or reclassify "
                "the reason")
        else:
            g["reason"] = (
                "shape: not min/max-evaluable and predicate synthesis has "
                "no sound rewrite for it — only a residual filter can "
                "evaluate this conjunct")
        g.pop("pruned")
        g.pop("basePrunable")
        g.pop("synthInfo")
        out.append(g)
    return sorted(out, key=lambda g: -g["scans"])


def _partition_skew(scans: List[dict]) -> Dict[str, Any]:
    ratios = []
    for e in scans:
        rep = e.get("report") or {}
        total = rep.get("filesTotal") or 0
        if total > 0:
            after = rep.get("filesAfterPartition")
            # 0 survivors is perfect pruning, not missing data
            ratios.append((after if after is not None else total) / total)
    if not ratios:
        return {"scans": 0}
    half = len(ratios) // 2 or 1
    return {
        "scans": len(ratios),
        "meanPartitionSurvival": round(sum(ratios) / len(ratios), 4),
        "recentPartitionSurvival": round(
            sum(ratios[-half:]) / len(ratios[-half:]), 4),
    }


def _commit_facts(commits: List[dict]) -> Dict[str, Any]:
    total = len(commits)
    retried = conflicts = reconciled = contended_n = 0
    windows: Counter = Counter()
    batch_sizes: List[int] = []
    queue_waits: List[float] = []
    for e in commits:
        stats = e.get("stats") or {}
        attempts = int(stats.get("attempts") or 1)
        outcome = e.get("outcome", "committed")
        # each entry counts ONCE toward the fraction — a conflict that also
        # retried must not inflate it
        contended = attempts > 1 or outcome == "conflict"
        if contended:
            contended_n += 1
        if attempts > 1:
            retried += 1
        if outcome == "conflict":
            conflicts += 1
        if outcome == "reconciledWin":
            reconciled += 1
        if contended and e.get("ts"):
            windows[int(e["ts"]) // CONTENTION_WINDOW_MS] += 1
        # group-commit evidence: grouped commits journal their measured
        # batch size and coordinator queue wait (txn/group_commit)
        if stats.get("batchSize") is not None:
            try:
                bs = int(stats["batchSize"])
                qw = float(stats.get("queueWaitMs") or 0.0)
            except (TypeError, ValueError):
                pass  # malformed entry: skip BOTH so the lists stay paired
            else:
                batch_sizes.append(bs)
                queue_waits.append(qw)
    hot = [{"windowStart": w * CONTENTION_WINDOW_MS, "contendedCommits": n}
           for w, n in windows.most_common(8) if n >= 2]
    out = {
        "commits": total,
        "retried": retried,
        "conflicts": conflicts,
        "reconciled": reconciled,
        "retryFraction": round(contended_n / total, 4) if total else 0.0,
        "contentionWindows": hot,
    }
    if batch_sizes:
        waits = sorted(queue_waits)

        def _pct(p: float) -> float:
            return waits[min(len(waits) - 1, int(p * len(waits)))]

        out["groupedCommits"] = len(batch_sizes)
        out["meanBatchSize"] = round(sum(batch_sizes) / len(batch_sizes), 2)
        out["maxBatchSize"] = max(batch_sizes)
        out["queueWaitP50Ms"] = round(_pct(0.50), 3)
        out["queueWaitP99Ms"] = round(_pct(0.99), 3)
    return out


def _key_cache_facts(dmls: List[dict]) -> Dict[str, Any]:
    merges = [e for e in dmls if e.get("op") == "merge"]
    decisions = [e.get("decision") for e in merges if e.get("decision")]
    if not decisions:
        return {"merges": 0}
    hits = sum(1 for d in decisions if d == "resident")
    cold = sum(1 for d in decisions if d in ("device-cold", "device-upload"))
    half = len(decisions) // 2 or 1
    recent = decisions[-half:]
    return {
        "merges": len(decisions),
        "cacheHits": hits,
        "coldDeviceMerges": cold,
        "hitRate": round(hits / len(decisions), 4),
        "recentHitRate": round(
            sum(1 for d in recent if d == "resident") / len(recent), 4),
        "decisions": dict(Counter(decisions)),
    }


def _router_facts(routers: List[dict]) -> Dict[str, Any]:
    audits = [e.get("audit") or {} for e in routers]
    misses = sum(1 for a in audits if a.get("miss"))
    return {
        "audits": len(audits),
        "misses": misses,
        "missRate": round(misses / len(audits), 4) if audits else 0.0,
    }


def _row_group_facts(scans: List[dict]) -> Dict[str, Any]:
    """Row groups per scanned file — over predicated scans only.
    ``rowGroupsTotal`` is populated only when the scan consulted footers
    (a predicate or position hint); folding in unpredicated full-table
    scans (rowGroupsTotal=0, filesScanned>0) dilutes the ratio toward 0
    and fabricates a ROW_GROUP_SIZE recommendation."""
    rg = files = 0
    for e in scans:
        rep = e.get("report") or {}
        groups = rep.get("rowGroupsTotal") or 0
        if groups <= 0:
            continue
        rg += groups
        files += rep.get("filesScanned") or 0
    return {
        "rowGroupsPerScannedFile": round(rg / files, 3) if files else 0.0,
        "filesScanned": files,
    }


def _planning_ms(scans: List[dict]) -> float:
    vals = sorted((e.get("report") or {}).get("phaseMs", {}).get("planning", 0)
                  for e in scans)
    return float(vals[len(vals) // 2]) if vals else 0.0


def _autopilot_facts(entries: List[dict], now_ms: int,
                     state: Optional[Dict[str, dict]] = None
                     ) -> Tuple[Dict[str, Any], Dict[str, dict]]:
    """Aggregate the autopilot action ledger (journal kind ``autopilot``)
    into facts, and return the actions currently inside their cooldown
    keyed by action key (shared `obs/actions.attempts_in_cooldown`, the
    same rule the autopilot planner filters re-plans with) — the advisor
    cites those instead of re-recommending them."""
    cooldown_ms = conf.get_int("delta.tpu.autopilot.cooldownMs",
                               6 * 3_600_000)
    executed = [e for e in entries if e.get("phase") == "executed"]
    recent: List[Dict[str, Any]] = []
    for e in executed[-8:]:
        a = e.get("action") or {}
        audit = e.get("audit") or {}
        recent.append({
            "kind": a.get("kind"), "target": a.get("target") or "",
            "ts": e.get("ts"), "verdict": audit.get("verdict"),
            "predicted": audit.get("predicted") or {},
            "realized": audit.get("realized") or {},
        })
    in_cooldown = actions_mod.attempts_in_cooldown(entries, now_ms,
                                                   cooldown_ms, state=state)
    facts = {
        "entries": len(entries),
        "executed": len(executed),
        "recentActions": recent,
        "cooldownActive": sorted(in_cooldown),
    }
    return facts, in_cooldown


def _apply_cooldowns(recs: List[Recommendation],
                     in_cooldown: Dict[str, dict]
                     ) -> Tuple[List[Recommendation], List[Dict[str, Any]]]:
    """Drop recommendations whose remedy the autopilot already attempted
    inside the cooldown window; return (kept, suppressed-citations). The
    closed loop: an executed action must not be re-recommended until its
    realized effect has had time to show up in fresh journal history."""
    if not in_cooldown:
        return recs, []
    by_kind: Dict[str, List[dict]] = {}
    for e in in_cooldown.values():
        a = e.get("action") or {}
        by_kind.setdefault(a.get("kind"), []).append(e)
    kept: List[Recommendation] = []
    suppressed: List[Dict[str, Any]] = []
    for r in recs:
        hit = None
        for e in by_kind.get(r.remedy, ()):
            a = e.get("action") or {}
            targets = [t.strip().lower()
                       for t in (a.get("target") or "").split(",") if t.strip()]
            # column-targeted actions must match the column; table-scoped
            # actions (CHECKPOINT, OPTIMIZE, ...) match on kind alone
            if not targets or r.target.lower() in targets:
                hit = e
                break
        if hit is None:
            kept.append(r)
            continue
        audit = hit.get("audit") or {}
        suppressed.append({
            "kind": r.kind, "target": r.target, "remedy": r.remedy,
            "phase": hit.get("phase"), "executedAt": hit.get("ts"),
            "verdict": audit.get("verdict"),
            "predicted": audit.get("predicted") or {},
            "realized": audit.get("realized") or {},
            "detail": "suppressed: the autopilot attempted this action "
                      "inside its cooldown window — see the action ledger "
                      "(journal kind 'autopilot')",
        })
    return kept, suppressed


# ---------------------------------------------------------------------------
# Recommendation synthesis
# ---------------------------------------------------------------------------


def _recommend(facts: Dict[str, Any],
               partition_cols: List[str]) -> List[Recommendation]:
    recs: List[Recommendation] = []
    pcols = {c.lower() for c in partition_cols}
    scans_seen = facts.get("scans", 0)

    for col, f in (facts.get("columns") or {}).items():
        # skip columns that are the partition layout NOW, and columns whose
        # journaled evidence was all partition-tier filters (recorded when
        # the column WAS a partition column — e.g. before a repartition):
        # partition pruning already pushes those down exactly
        if col in pcols or f["partitionFilters"] >= f["filters"]:
            continue
        if (f["filters"] >= ZORDER_MIN_FILTERS
                and f["missRate"] >= ZORDER_MIN_MISS_RATE
                and f["prunable"] > 0):
            recs.append(Recommendation(
                kind="ZORDER", target=col,
                score=f["filters"] * f["missRate"],
                action=f"table.optimize().execute_z_order_by('{col}')",
                detail=f"'{col}' was filtered in {f['filters']} of "
                       f"{scans_seen} journaled scans but pruning missed "
                       f"{f['missRate']:.0%} of them — the column is not in "
                       "the table's layout; Z-ORDER clustering would make "
                       "its min/max stats selective",
                evidence={"filterCount": f["filters"],
                          "pruningMissRate": f["missRate"],
                          "scansConsidered": scans_seen},
            ))
        if (f["filters"] >= PARTITION_MIN_FILTERS
                and f["eqFraction"] >= PARTITION_EQ_FRACTION
                and f["missRate"] >= ZORDER_MIN_MISS_RATE):
            recs.append(Recommendation(
                kind="PARTITION", target=col,
                score=f["filters"] * f["eqFraction"] * 0.8,
                action=f"repartition by '{col}' (equality-dominated filter)",
                detail=f"'{col}' is equality/IN-filtered in "
                       f"{f['eqFraction']:.0%} of its {f['filters']} "
                       "journaled filters — a partition (or primary Z-ORDER) "
                       "column candidate",
                evidence={"filterCount": f["filters"],
                          "eqFraction": f["eqFraction"],
                          "pruningMissRate": f["missRate"]},
            ))

    rgf = facts.get("rowGroups") or {}
    col_facts = facts.get("columns") or {}
    any_prunable_miss = any(
        f["prunable"] > 0 and f["missRate"] >= ZORDER_MIN_MISS_RATE
        for f in col_facts.values())
    if (rgf.get("filesScanned", 0) > 0 and any_prunable_miss
            and 0 < rgf.get("rowGroupsPerScannedFile", 0.0)
            < ROW_GROUPS_PER_FILE_FLOOR):
        recs.append(Recommendation(
            kind="ROW_GROUP_SIZE", target="delta.tpu.write.rowGroupRows",
            score=2.0,
            action="rewrite hot files (OPTIMIZE) with bounded row groups — "
                   "check delta.tpu.write.rowGroupRows",
            detail=f"scanned files average "
                   f"{rgf['rowGroupsPerScannedFile']:.2f} row groups each: "
                   "the second pruning tier has nothing to skip inside them",
            evidence=dict(rgf),
        ))

    cf = facts.get("commits") or {}
    planning_p50 = facts.get("planningP50Ms", 0.0)
    if (cf.get("commits", 0) >= CHECKPOINT_MIN_COMMITS
            and planning_p50 >= CHECKPOINT_PLANNING_MS):
        recs.append(Recommendation(
            kind="CHECKPOINT_INTERVAL", target="delta.checkpointInterval",
            score=planning_p50 / CHECKPOINT_PLANNING_MS,
            action="lower delta.checkpointInterval (or run CHECKPOINT)",
            detail=f"{cf['commits']} journaled commits with scan planning "
                   f"p50 at {planning_p50:.0f} ms — the log tail is being "
                   "replayed on the read path",
            evidence={"commits": cf["commits"],
                      "planningP50Ms": planning_p50},
        ))
    if (cf.get("commits", 0) >= CONTENTION_MIN_COMMITS
            and cf.get("retryFraction", 0.0) >= CONTENTION_RETRY_FRACTION):
        if cf.get("groupedCommits"):
            # group commit is already on: cite the measured coordinator
            # evidence (journaled batchSize/queueWaitMs from the grouped
            # commits themselves) instead of inferring from time buckets
            recs.append(Recommendation(
                kind="COMMIT_CONTENTION", target="delta.tpu.commit.group",
                score=cf["retryFraction"] * 10.0,
                action="raise delta.tpu.commit.group.{maxBatch,maxWaitMs} "
                       "or stagger writer schedules",
                detail=f"{cf['retryFraction']:.0%} of {cf['commits']} "
                       f"journaled commits retried or conflicted despite "
                       f"grouping (mean batch {cf['meanBatchSize']}, queue "
                       f"wait p99 {cf['queueWaitP99Ms']:.1f} ms)",
                evidence={"commits": cf["commits"],
                          "retryFraction": cf["retryFraction"],
                          "groupedCommits": cf["groupedCommits"],
                          "meanBatchSize": cf["meanBatchSize"],
                          "maxBatchSize": cf["maxBatchSize"],
                          "queueWaitP50Ms": cf["queueWaitP50Ms"],
                          "queueWaitP99Ms": cf["queueWaitP99Ms"]},
            ))
        else:
            recs.append(Recommendation(
                kind="COMMIT_CONTENTION", target="delta.tpu.commit.group.enabled",
                score=cf["retryFraction"] * 10.0,
                action="set delta.tpu.commit.group.enabled=true (group "
                       "commit) or stagger writer schedules",
                detail=f"{cf['retryFraction']:.0%} of {cf['commits']} journaled "
                       f"commits retried or conflicted; "
                       f"{len(cf.get('contentionWindows') or [])} contention "
                       "window(s) recorded",
                evidence={"commits": cf["commits"],
                          "retryFraction": cf["retryFraction"],
                          "contentionWindows": cf.get("contentionWindows") or []},
            ))

    rf = facts.get("router") or {}
    if (rf.get("audits", 0) >= CALIBRATION_MIN_AUDITS
            and rf.get("missRate", 0.0) >= CALIBRATION_MISS_RATE):
        recs.append(Recommendation(
            kind="CALIBRATION", target="delta.tpu.router.calibration.enabled",
            score=rf["missRate"] * 8.0,
            action="set delta.tpu.router.calibration.enabled=true",
            detail=f"the router's hindsight miss rate over "
                   f"{rf['audits']} journaled audits is "
                   f"{rf['missRate']:.0%} — the shipped cost constants do "
                   "not match this hardware; enable the EWMA calibrator",
            evidence=dict(rf),
        ))

    kf = facts.get("keyCache") or {}
    if (kf.get("coldDeviceMerges", 0) >= HBM_MIN_COLD_MERGES
            and kf.get("hitRate", 1.0) <= HBM_MAX_HIT_RATE):
        recs.append(Recommendation(
            kind="HBM_BUDGET", target="delta.tpu.keyCache.maxBytes",
            score=float(kf["coldDeviceMerges"]),
            action="raise delta.tpu.keyCache.maxBytes / "
                   "delta.tpu.device.hbmBudgetBytes so merge key slabs stay "
                   "resident",
            detail=f"{kf['coldDeviceMerges']} of {kf['merges']} journaled "
                   f"device merges rebuilt the key slab cold (hit rate "
                   f"{kf['hitRate']:.0%}) — the resident key cache is being "
                   "evicted between merges",
            evidence=dict(kf),
        ))

    recs.sort(key=lambda r: -r.score)
    return recs


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _attach_shadow_verdicts(recs: List[Recommendation],
                            entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Attach the latest journaled shadow verdict to each matching
    recommendation (measured evidence the what-if replayer produced for
    this exact (kind, target)); returns the ``facts["shadow"]`` summary.
    Recommendations without a covering run stay ``shadowVerdict:
    untested`` — the advisor never fakes a measurement."""
    runs = sum(1 for e in entries if e.get("kind") == "shadow")
    if not runs:
        return {"runs": 0}
    from delta_tpu.replay.shadow import shadow_verdicts

    verdicts = shadow_verdicts(entries)
    attached: Dict[str, str] = {}
    for r in recs:
        hit = verdicts.get((r.kind, r.target.lower()))
        if hit is not None:
            r.shadow = dict(hit)
            attached[f"{r.kind}:{r.target}"] = str(hit.get("verdict"))
    return {"runs": runs, "attached": attached}


def advise(table, snapshot=None, limit: Optional[int] = None) -> AdvisorReport:
    """Aggregate a table's workload journal into facts + ranked
    recommendations. ``table`` is a DeltaTable, DeltaLog, or path (like
    :func:`~delta_tpu.obs.doctor.doctor`). Reads the journal from disk —
    a fresh process sees everything earlier processes recorded. ``limit``
    restricts to the last N journal entries."""
    from delta_tpu.log.deltalog import DeltaLog

    if isinstance(table, str):
        delta_log = DeltaLog.for_table(table)
    else:
        delta_log = getattr(table, "delta_log", table)
    with telemetry.record_operation("delta.utility.advise",
                                    path=delta_log.data_path):
        telemetry.bump_counter("advisor.runs")
        now = delta_log.clock()
        if not journal_mod.enabled(delta_log.log_path):
            return AdvisorReport(
                path=delta_log.data_path, version=-1, generated_at_ms=now,
                status="no history", entries=0,
                facts={"reason": "journal disabled (telemetry blackout or "
                                 "delta.tpu.journal.enabled=false)"},
            )
        journal_mod.flush(delta_log.log_path)
        entries = journal_mod.read_entries(delta_log.log_path, limit=limit)
        if not entries:
            return AdvisorReport(
                path=delta_log.data_path, version=-1, generated_at_ms=now,
                status="no history", entries=0,
                facts={"reason": "no journal entries recorded yet"},
            )
        snap = snapshot if snapshot is not None else delta_log.update()
        scans = [e for e in entries if e.get("kind") == "scan"]
        commits = [e for e in entries if e.get("kind") == "commit"]
        dmls = [e for e in entries if e.get("kind") == "dml"]
        routers = [e for e in entries if e.get("kind") == "router"]
        autopilots = [e for e in entries if e.get("kind") == "autopilot"]
        # ledger cooldown math runs on wall time: journal ts stamps come
        # from time.time(), while `now` (delta_log.clock) is injectable.
        # The sweep-proof sidecar rides along so suppression stays in
        # lockstep with the planner even after a ledger-segment sweep
        import time as _time

        ap_facts, in_cooldown = _autopilot_facts(
            autopilots, int(_time.time() * 1000),
            state=journal_mod.attempt_state(delta_log.log_path))
        facts: Dict[str, Any] = {
            "scans": len(scans),
            "columns": _column_facts(scans),
            "neverPruned": _never_pruned(scans),
            "partition": _partition_skew(scans),
            "commits": _commit_facts(commits),
            "keyCache": _key_cache_facts(dmls),
            "router": _router_facts(routers),
            "rowGroups": _row_group_facts(scans),
            "planningP50Ms": _planning_ms(scans),
            "autopilot": ap_facts,
        }
        recs = _recommend(facts, list(snap.metadata.partition_columns))
        recs, suppressed = _apply_cooldowns(recs, in_cooldown)
        facts["shadow"] = _attach_shadow_verdicts(recs, entries)
        if suppressed:
            ap_facts["suppressed"] = suppressed
        if recs:
            telemetry.bump_counter("advisor.recommendations", len(recs))
        telemetry.add_span_data(
            entries=len(entries), recommendations=len(recs),
            topKind=recs[0].kind if recs else None,
        )
        return AdvisorReport(
            path=delta_log.data_path, version=snap.version,
            generated_at_ms=now, status="ok", entries=len(entries),
            facts=facts, recommendations=recs,
        )
