"""Checkpoints: Parquet compaction of reconciled log state.

Format-compatible with the reference (``Checkpoints.scala``; schema spec
``PROTOCOL.md`` "Checkpoint Schema"): a checkpoint Parquet file holds one row
per action with nullable struct columns ``txn``/``add``/``remove``/
``metaData``/``protocol``, plus the ``_last_checkpoint`` pointer JSON.

Unlike the reference — which funnels the whole state through a
``repartition(1)`` single-task write (``Checkpoints.scala:262-303``) — the
writer here shards multi-part checkpoints across parts deterministically and
writes parts in parallel threads, which is both faster and exactly what the
multi-part naming scheme was designed for.
"""
from __future__ import annotations

import json
import math
import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import (
    Action,
    AddFile,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)
from delta_tpu.schema.types import (
    BooleanType,
    ByteType,
    DateType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    ShortType,
    StringType,
    TimestampType,
)
from delta_tpu.storage.logstore import LogStore
from delta_tpu.utils import errors
from delta_tpu.utils.errors import DeltaIllegalStateError

# Stats leaf types the VECTORIZED struct-stats builder can cast from one
# batched ndjson parse. Load-bearing invariant: write_stats_as_struct gates
# the engine default on this exact set so the columnar and dataclass
# checkpoint writers can never disagree on a table's checkpoint schema —
# keep it single-sourced (decimal / nested-struct leaves need the per-value
# coercion only the dataclass row builder does).
_SIMPLE_STATS_TYPES = (ByteType, ShortType, IntegerType, LongType, FloatType,
                       DoubleType, StringType, BooleanType, DateType,
                       TimestampType)

__all__ = [
    "CheckpointMetaData",
    "read_last_checkpoint",
    "write_last_checkpoint",
    "write_checkpoint",
    "write_stats_as_struct",
    "read_checkpoint_actions",
    "find_last_complete_checkpoint_before",
    "CheckpointInstance",
    "latest_complete_checkpoint",
]


@dataclass(frozen=True)
class CheckpointMetaData:
    """Content of ``_last_checkpoint`` (``Checkpoints.scala:51-58``)."""

    version: int
    size: int
    parts: Optional[int] = None

    def to_json(self) -> str:
        d: Dict[str, Any] = {"version": self.version, "size": self.size}
        if self.parts is not None:
            d["parts"] = self.parts
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(s: str) -> "CheckpointMetaData":
        d = json.loads(s)
        return CheckpointMetaData(int(d["version"]), int(d.get("size", -1)), d.get("parts"))


@dataclass(frozen=True, order=True)
class CheckpointInstance:
    """A (version, parts) candidate checkpoint (``Checkpoints.scala:60-106``).
    Ordering: higher version wins; at same version, multi-part > single-part
    is NOT the rule — the reference prefers fewer parts (None sorts last in
    its ordering); we order by (version, -num_parts-is-None) to match its
    ``isNotLaterThan`` usage where exact semantics only need version order."""

    version: int
    parts: Optional[int] = None

    def paths(self, log_path: str) -> List[str]:
        if self.parts is None:
            return [f"{log_path}/{filenames.checkpoint_file_single(self.version)}"]
        return [f"{log_path}/{p}" for p in filenames.checkpoint_file_with_parts(self.version, self.parts)]


def _run_all_parts(n: int, write_part) -> None:
    """Run ``write_part(i)`` for every part on a thread pool, ATTEMPTING ALL
    parts before re-raising the first (lowest-index) failure.

    ``list(ex.map(...))`` would cancel not-yet-started siblings when its
    iterator closes on the first exception — leaving a *timing-dependent*
    subset of parts on disk. Deterministic all-or-each-tried behavior
    matters for crash consistency: what a failed multi-part checkpoint
    leaves behind must not depend on thread scheduling (and one slow part's
    transient error shouldn't silently cancel its siblings mid-write).

    Part writers run under the submitting context's span chain
    (`telemetry.propagated`): their IO spans/events parent under the
    enclosing ``delta.checkpoint`` span on per-worker trace lanes instead
    of orphan roots."""
    from delta_tpu.utils import telemetry

    with ThreadPoolExecutor(max_workers=min(n, 16),
                            thread_name_prefix="delta-ckpt-part") as ex:
        wrapped = telemetry.propagated(write_part)
        futures = [ex.submit(wrapped, i) for i in range(n)]
        errors_ = [f.exception() for f in futures]  # waits for every part
    failed = [e for e in errors_ if e is not None]
    for e in failed:
        # a non-Exception BaseException (simulated process death from the
        # fault injector, KeyboardInterrupt) must win over ordinary part
        # failures — an `except Exception` recovery path may not survive it
        if not isinstance(e, Exception):
            raise e
    for e in failed:
        raise e


def read_last_checkpoint(store: LogStore, log_path: str) -> Optional[CheckpointMetaData]:
    """Read the ``_last_checkpoint`` pointer; on corruption/partial write fall
    back to None so callers re-list (``Checkpoints.scala:148-175``)."""
    p = f"{log_path}/{filenames.LAST_CHECKPOINT}"
    try:
        lines = store.read(p)
    except FileNotFoundError:
        return None
    try:
        return CheckpointMetaData.from_json("".join(lines))
    except (ValueError, KeyError):
        return None


def write_last_checkpoint(store: LogStore, log_path: str, md: CheckpointMetaData) -> None:
    store.write(f"{log_path}/{filenames.LAST_CHECKPOINT}", [md.to_json()], overwrite=True)


def latest_complete_checkpoint(
    instances: Sequence[CheckpointInstance], not_later_than: Optional[int] = None
) -> Optional[CheckpointInstance]:
    """Pick the latest checkpoint all of whose parts are present
    (``Checkpoints.scala:210-218``). ``instances`` are per-file candidates:
    single-part files appear once with parts=None; a multi-part file with
    (part i of n) appears as CheckpointInstance(version, n) once per part."""
    from collections import Counter

    if not_later_than is not None:
        instances = [c for c in instances if c.version <= not_later_than]
    counts = Counter(instances)
    complete = [
        inst
        for inst, cnt in counts.items()
        if (inst.parts is None and cnt >= 1) or (inst.parts is not None and cnt >= inst.parts)
    ]
    if not complete:
        return None
    # Highest version; tie → prefer single-part (simpler read path).
    return max(complete, key=lambda c: (c.version, -(c.parts or 0)))


def find_last_complete_checkpoint_before(
    store: LogStore, log_path: str, version: int
) -> Optional[CheckpointInstance]:
    """Backward scan in 1000-version windows (``Checkpoints.scala:187-204``)."""
    cur = max(0, version)
    while cur >= 0:
        start = max(0, cur - 1000)
        prefix = f"{log_path}/{filenames.check_version_prefix(start)}"
        candidates: List[CheckpointInstance] = []
        try:
            for st in store.list_from(prefix):
                name = st.name
                if filenames.is_checkpoint_file(name) and st.size > 0:
                    v = filenames.checkpoint_version(name)
                    if v < version if cur == version else v <= cur:
                        part = filenames.checkpoint_part(name)
                        candidates.append(
                            CheckpointInstance(v, part[1] if part else None)
                        )
        except FileNotFoundError:
            return None
        upper = version - 1 if cur == version else cur
        found = latest_complete_checkpoint(candidates, not_later_than=upper)
        if found:
            return found
        if start == 0:
            return None
        cur = start - 1
    return None


# ---------------------------------------------------------------------------
# Parquet serialization (SingleAction rows)
# ---------------------------------------------------------------------------

def _arrow_checkpoint_schema():
    import pyarrow as pa

    str_map = pa.map_(pa.string(), pa.string())
    dv_struct = pa.struct(
        [
            pa.field("storageType", pa.string()),
            pa.field("pathOrInlineDv", pa.string()),
            pa.field("sizeInBytes", pa.int64()),
            pa.field("cardinality", pa.int64()),
        ]
    )
    return pa.schema(
        [
            pa.field(
                "txn",
                pa.struct(
                    [
                        pa.field("appId", pa.string()),
                        pa.field("version", pa.int64()),
                        pa.field("lastUpdated", pa.int64()),
                    ]
                ),
            ),
            pa.field(
                "add",
                pa.struct(
                    [
                        pa.field("path", pa.string()),
                        pa.field("partitionValues", str_map),
                        pa.field("size", pa.int64()),
                        pa.field("modificationTime", pa.int64()),
                        pa.field("dataChange", pa.bool_()),
                        pa.field("stats", pa.string()),
                        pa.field("tags", str_map),
                        pa.field("deletionVector", dv_struct),
                    ]
                ),
            ),
            pa.field(
                "remove",
                pa.struct(
                    [
                        pa.field("path", pa.string()),
                        pa.field("deletionTimestamp", pa.int64()),
                        pa.field("dataChange", pa.bool_()),
                        pa.field("extendedFileMetadata", pa.bool_()),
                        pa.field("partitionValues", str_map),
                        pa.field("size", pa.int64()),
                        pa.field("tags", str_map),
                        pa.field("deletionVector", dv_struct),
                    ]
                ),
            ),
            pa.field(
                "metaData",
                pa.struct(
                    [
                        pa.field("id", pa.string()),
                        pa.field("name", pa.string()),
                        pa.field("description", pa.string()),
                        pa.field(
                            "format",
                            pa.struct(
                                [
                                    pa.field("provider", pa.string()),
                                    pa.field("options", str_map),
                                ]
                            ),
                        ),
                        pa.field("schemaString", pa.string()),
                        pa.field("partitionColumns", pa.list_(pa.string())),
                        pa.field("configuration", str_map),
                        pa.field("createdTime", pa.int64()),
                    ]
                ),
            ),
            pa.field(
                "protocol",
                pa.struct(
                    [
                        pa.field("minReaderVersion", pa.int32()),
                        pa.field("minWriterVersion", pa.int32()),
                        pa.field("readerFeatures", pa.list_(pa.string())),
                        pa.field("writerFeatures", pa.list_(pa.string())),
                    ]
                ),
            ),
        ]
    )


def _action_to_row(a: Action) -> Dict[str, Any]:
    if isinstance(a, AddFile):
        d = a.to_dict()
        d.setdefault("stats", None)
        d.setdefault("tags", None)
        d.setdefault("deletionVector", None)
        return {"add": d}
    if isinstance(a, RemoveFile):
        d = a.to_dict()
        for k in ("deletionTimestamp", "extendedFileMetadata", "partitionValues",
                  "size", "tags", "deletionVector"):
            d.setdefault(k, None)
        return {"remove": d}
    if isinstance(a, Metadata):
        d = a.to_dict()
        for k in ("name", "description", "createdTime"):
            d.setdefault(k, None)
        return {"metaData": d}
    if isinstance(a, Protocol):
        return {"protocol": a.to_dict()}
    if isinstance(a, SetTransaction):
        d = a.to_dict()
        d.setdefault("lastUpdated", None)
        return {"txn": d}
    raise ValueError(f"Action not checkpointable: {a!r}")


def _struct_stats_vectorizable(meta: Metadata) -> bool:
    """Can :func:`_v2_arrays_vectorized` type every stats leaf of this
    schema? (:data:`_SIMPLE_STATS_TYPES` leaves only — decimal and
    nested-struct leaves need per-value coercion.)"""
    schema = meta.schema
    known = {f.name for f in schema.fields}
    pcols = set(meta.partition_columns)
    if pcols and not pcols <= known:
        return False
    return all(isinstance(f.data_type, _SIMPLE_STATS_TYPES)
               for f in schema.fields)


def write_stats_as_struct(meta: Optional[Metadata]) -> bool:
    """Struct-stats gate for checkpoint writers. The table property
    ``delta.checkpoint.writeStatsAsStruct`` (or its session-level
    ``delta.tpu.properties.defaults.*`` tier) wins when set; otherwise the
    engine default is the session conf
    ``delta.tpu.checkpoint.writeStatsAsStruct`` — ON, unlike the reference,
    because the zero-JSON cold state-cache build depends on the typed
    columns (``ops/state_export.arrays_from_columns``).

    The engine default only applies to schemas the VECTORIZED builder can
    type (:func:`_struct_stats_vectorizable`): otherwise the columnar and
    dataclass writers would disagree — the same table's checkpoints would
    flip schema depending on which writer a given version happened to take.
    An explicit property=true still forces struct columns everywhere (the
    dataclass row builder coerces decimal/nested leaves per value)."""
    from delta_tpu.utils.config import DeltaConfigs, conf

    if meta is None:
        return False
    if DeltaConfigs.CHECKPOINT_WRITE_STATS_AS_STRUCT.is_explicit(meta):
        return DeltaConfigs.CHECKPOINT_WRITE_STATS_AS_STRUCT.from_metadata(meta)
    return (conf.get_bool("delta.tpu.checkpoint.writeStatsAsStruct", True)
            and _struct_stats_vectorizable(meta))


def _v2_schema_and_rows(actions: Sequence[Action]):
    """CheckpointV2 columns (``Checkpoints.scala:340-389``): typed
    ``add.partitionValues_parsed`` and ``add.stats_parsed`` structs, built
    from the state's own Metadata action. Returns (extra add fields,
    row-builder) or (None, None) when the table opts out (see
    :func:`write_stats_as_struct`)."""
    import pyarrow as pa

    from delta_tpu.expr.partition import typed_partition_row
    from delta_tpu.expr.vectorized import arrow_type_for

    meta = next((a for a in actions if isinstance(a, Metadata)), None)
    if meta is None or not write_stats_as_struct(meta):
        return None, None
    schema = meta.schema
    known = {f.name for f in schema.fields}
    pcols = list(meta.partition_columns)
    part_schema = meta.partition_schema
    data_fields = [f for f in schema.fields if f.name not in pcols]
    if not data_fields and not (pcols and set(pcols) <= known):
        # nothing to type (empty/unknown schema, e.g. synthetic logs):
        # Parquet cannot write empty structs
        return None, None

    extra_fields = []
    if pcols and set(pcols) <= known:
        extra_fields.append(pa.field(
            "partitionValues_parsed",
            pa.struct([
                pa.field(c, arrow_type_for(part_schema[c].data_type))
                for c in pcols
            ]),
        ))
    from delta_tpu.schema.types import (
        DateType,
        DecimalType,
        StructType,
        TimestampType,
    )

    def _null_count_type(dt):
        # protocol: nullCount nests per struct field (int64 at the leaves)
        if isinstance(dt, StructType):
            return pa.struct(
                [pa.field(f.name, _null_count_type(f.data_type)) for f in dt.fields]
            )
        return pa.int64()

    def _coerce_stat(v, dt):
        """Stats JSON carries dates/timestamps as ISO strings and nests per
        struct field — convert to the typed Arrow representation."""
        if v is None:
            return None
        if isinstance(dt, StructType):
            if not isinstance(v, dict):
                return None
            return {f.name: _coerce_stat(v.get(f.name), f.data_type)
                    for f in dt.fields}
        if isinstance(dt, DateType):
            import datetime as _dt

            return _dt.date.fromisoformat(str(v))
        if isinstance(dt, TimestampType):
            from delta_tpu.utils.timeparse import iso_to_naive_utc

            return iso_to_naive_utc(str(v))
        if isinstance(dt, DecimalType):
            from decimal import Decimal

            return Decimal(str(v))
        return v

    if data_fields:  # Parquet cannot write empty min/max structs
        val_struct = pa.struct(
            [pa.field(f.name, arrow_type_for(f.data_type)) for f in data_fields]
        )
        null_struct = pa.struct(
            [pa.field(f.name, _null_count_type(f.data_type)) for f in data_fields]
        )
        extra_fields.append(pa.field(
            "stats_parsed",
            pa.struct([
                pa.field("numRecords", pa.int64()),
                pa.field("minValues", val_struct),
                pa.field("maxValues", val_struct),
                pa.field("nullCount", null_struct),
            ]),
        ))

    def _null_count_value(v, dt):
        if isinstance(dt, StructType):
            v = v if isinstance(v, dict) else {}
            return {f.name: _null_count_value(v.get(f.name), f.data_type)
                    for f in dt.fields}
        return int(v) if isinstance(v, (int, float)) else None

    typed_pcols = bool(pcols) and set(pcols) <= known

    def build(add: AddFile) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if typed_pcols:
            out["partitionValues_parsed"] = typed_partition_row(add, part_schema)
        if not data_fields:
            return out
        s = add.stats_dict() or {}
        out["stats_parsed"] = {
            "numRecords": s.get("numRecords"),
            "minValues": {
                f.name: _coerce_stat((s.get("minValues") or {}).get(f.name),
                                     f.data_type)
                for f in data_fields
            },
            "maxValues": {
                f.name: _coerce_stat((s.get("maxValues") or {}).get(f.name),
                                     f.data_type)
                for f in data_fields
            },
            "nullCount": {
                f.name: _null_count_value(
                    (s.get("nullCount") or {}).get(f.name), f.data_type
                )
                for f in data_fields
            },
        }
        return out

    return extra_fields, build


def _segment_file_extras(cols) -> bool:
    """Does any FILE action in the columnar segment carry tags or a
    deletion vector? Conservative (substring scan over raw JSON lines /
    checkpoint struct validity): a false positive only skips the columnar
    fast path, never corrupts it."""
    for b in cols.batches:
        if b.kind == "json":
            for ln in b.lines or ():
                if b'"deletionVector"' in ln or b'"tags"' in ln:
                    return True
        else:
            t = b.table
            if t is None:
                continue
            for col_name in ("add", "remove"):
                if col_name not in t.column_names:
                    continue
                st = t.column(col_name)
                typ = st.type
                for i in range(typ.num_fields):
                    f = typ.field(i)
                    if f.name not in ("tags", "deletionVector"):
                        continue
                    import pyarrow.compute as pc

                    leaf = pc.struct_field(st, f.name)
                    if len(leaf) - leaf.null_count > 0:
                        return True
    return False


def _v2_arrays_vectorized(meta, part_strings, stats, n: int):
    """Vectorized CheckpointV2 columns straight from the columnar segment:
    the typed ``partitionValues_parsed`` / ``stats_parsed`` struct arrays
    for the n alive adds, built from ONE C++ ndjson parse of the stats
    strings plus Arrow casts — the row-at-a-time twin of
    :func:`_v2_schema_and_rows` without any dataclasses. Returns
    ``(extra add fields, child arrays)`` or None when a leaf needs
    per-value coercion (decimal / nested-struct columns) or a cast fails —
    the caller falls back to the dataclass row builder, which coerces
    exactly."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    from delta_tpu.expr.vectorized import arrow_type_for
    from delta_tpu.ops.state_export import stats_json_table
    from delta_tpu.utils.arrow import one_chunk as _one

    schema = meta.schema
    known = {f.name for f in schema.fields}
    pcols = list(meta.partition_columns)
    typed_pcols = bool(pcols) and set(pcols) <= known
    part_schema = meta.partition_schema
    data_fields = [f for f in schema.fields if f.name not in pcols]
    if not data_fields and not typed_pcols:
        return [], []  # nothing to type (empty/unknown schema)
    if not all(isinstance(f.data_type, _SIMPLE_STATS_TYPES) for f in data_fields):
        return None
    if typed_pcols and not all(
            isinstance(part_schema[c].data_type, _SIMPLE_STATS_TYPES)
            for c in pcols):
        return None

    def _json_repr_type(dt) -> pa.DataType:
        """The Arrow type the stats-JSON representation of ``dt`` parses
        to under an explicit schema: strings stay strings (NEVER inferred —
        a string column holding '2021-01-01' must round-trip verbatim),
        temporal values arrive as ISO strings, numbers widen."""
        if isinstance(dt, (StringType, DateType, TimestampType)):
            return pa.string()
        if isinstance(dt, BooleanType):
            return pa.bool_()
        if isinstance(dt, (FloatType, DoubleType)):
            return pa.float64()
        return pa.int64()

    def _cast_leaf(arr, dt):
        """Parsed/raw leaf → the field's typed Arrow representation. Stats
        JSON (and partition maps) carry dates/timestamps as ISO strings."""
        target = arrow_type_for(dt)
        arr = _one(arr)
        if arr.type == target:
            return arr
        if isinstance(dt, StringType):
            # a non-string parse of a string field means type inference
            # rewrote the literal (ISO-date-like values → timestamp);
            # rendering it back would persist a DIFFERENT string
            raise TypeError(f"string stats leaf parsed as {arr.type}")
        if isinstance(dt, TimestampType) and not pa.types.is_timestamp(arr.type):
            s = arr.cast(pa.string())
            try:
                return pc.cast(s, target)  # tz-naive = wall-clock UTC
            except Exception:
                z = pc.replace_substring_regex(s, r"Z$", "+00:00")
                return pc.cast(z, pa.timestamp("us", tz="UTC")).cast(target)
        if isinstance(dt, DateType) and not (
                pa.types.is_timestamp(arr.type) or pa.types.is_date(arr.type)):
            return arr.cast(pa.string()).cast(target)
        return arr.cast(target)

    fields: List = []
    children: List = []
    if typed_pcols:
        pv_fields = [pa.field(c, arrow_type_for(part_schema[c].data_type))
                     for c in pcols]
        try:
            pv_children = [_cast_leaf(part_strings[c], part_schema[c].data_type)
                           for c in pcols]
        except Exception:
            return None
        fields.append(pa.field("partitionValues_parsed", pa.struct(pv_fields)))
        children.append(pa.StructArray.from_arrays(pv_children, fields=pv_fields))
    if not data_fields:
        return fields, children

    val_fields = [pa.field(f.name, arrow_type_for(f.data_type))
                  for f in data_fields]
    null_fields = [pa.field(f.name, pa.int64()) for f in data_fields]
    sp_fields = [
        pa.field("numRecords", pa.int64()),
        pa.field("minValues", pa.struct(val_fields)),
        pa.field("maxValues", pa.struct(val_fields)),
        pa.field("nullCount", pa.struct(null_fields)),
    ]
    sp_type = pa.struct(sp_fields)
    fields.append(pa.field("stats_parsed", sp_type))

    # explicit parse schema: pins every leaf to its JSON representation so
    # the Arrow reader never type-infers (see stats_json_table docstring)
    repr_struct = pa.struct(
        [pa.field(f.name, _json_repr_type(f.data_type)) for f in data_fields])
    parse_schema = pa.schema([
        pa.field("numRecords", pa.int64()),
        pa.field("minValues", repr_struct),
        pa.field("maxValues", repr_struct),
        pa.field("nullCount", pa.struct(
            [pa.field(f.name, pa.int64()) for f in data_fields])),
    ])
    kind, parsed, idx = (
        stats_json_table(stats, explicit_schema=parse_schema)
        if stats is not None else ("empty", None, None))
    if kind in ("newline", "malformed"):
        return None
    if kind == "empty":
        children.append(pa.nulls(n, sp_type))
        return fields, children

    names = parsed.column_names
    k = parsed.num_rows

    def _sub(col_name: str, leaf_name: str):
        if col_name not in names:
            return None
        col = _one(parsed.column(col_name))
        if not pa.types.is_struct(col.type):
            return None
        if not any(col.type.field(i).name == leaf_name
                   for i in range(col.type.num_fields)):
            return None
        return _one(pc.struct_field(col, leaf_name))

    try:
        nr = (_one(parsed.column("numRecords")).cast(pa.int64())
              if "numRecords" in names else pa.nulls(k, pa.int64()))
        min_children, max_children, nc_children = [], [], []
        for f in data_fields:
            for dest, src in ((min_children, "minValues"),
                              (max_children, "maxValues")):
                leaf = _sub(src, f.name)
                dest.append(pa.nulls(k, arrow_type_for(f.data_type))
                            if leaf is None else _cast_leaf(leaf, f.data_type))
            leaf = _sub("nullCount", f.name)
            nc_children.append(pa.nulls(k, pa.int64()) if leaf is None
                               else leaf.cast(pa.int64()))
        sp = pa.StructArray.from_arrays(
            [nr,
             pa.StructArray.from_arrays(min_children, fields=val_fields),
             pa.StructArray.from_arrays(max_children, fields=val_fields),
             pa.StructArray.from_arrays(nc_children, fields=null_fields)],
            fields=sp_fields,
        )
    except Exception:
        return None
    # expand to all n rows: null struct where the file carries no stats
    inverse = np.full(n, -1, np.int64)
    inverse[idx] = np.arange(k)
    children.append(sp.take(pa.array(inverse, pa.int64(), mask=inverse < 0)))
    return fields, children


def write_checkpoint_columnar(
    store: LogStore,
    log_path: str,
    snapshot,
    part_size: int = 1_000_000,
) -> Optional[CheckpointMetaData]:
    """Columnar checkpoint writer: the surviving AddFiles stream straight
    from the snapshot's SoA columns into Arrow struct arrays — no dataclass
    materialization, no per-action dict building. At 1M files this is the
    difference between seconds and minutes; the reference funnels the same
    write through a single-task ``repartition(1)`` (`Checkpoints.scala:262-303`).

    Partitioned tables build their ``partitionValues`` map column
    vectorized from the segment's partition strings, and tables with
    struct stats enabled (:func:`write_stats_as_struct`, default on) get
    the typed ``partitionValues_parsed``/``stats_parsed`` columns from one
    batched ndjson parse (:func:`_v2_arrays_vectorized`). Returns None for
    the shapes that still need per-row coercion (tags/DVs on file actions,
    decimal or nested-struct stats leaves) and the caller takes the
    dataclass path. Tombstones and state actions (few) go through the row
    builder either way."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    meta = snapshot.metadata
    cols = snapshot._columnar
    if _segment_file_extras(cols):
        return None
    part_cols = list(meta.partition_columns)
    want_struct = write_stats_as_struct(meta)

    schema = _arrow_checkpoint_schema()
    add_type = schema.field("add").type
    str_map = pa.map_(pa.string(), pa.string())

    from delta_tpu.utils.arrow import one_chunk as _one_chunk

    rows = np.nonzero(snapshot._alive_mask)[0]
    n = len(rows)
    paths = pa.array(cols.paths_for(rows), pa.string())
    if cols.stats is not None and n:
        stats = _one_chunk(cols.stats.take(pa.array(rows, pa.int64())))
    else:
        stats = pa.nulls(n, pa.string())

    part_strings = None
    if part_cols:
        # raw partition-value strings, vectorized from the segment's map
        # columns / tail lines — never through AddFile dataclasses
        part_strings = cols.partition_strings(rows, part_cols)
        if part_strings is None:
            return None
        part_strings = {c: _one_chunk(a) for c, a in part_strings.items()}

    extras_by_name: Dict[str, Any] = {}
    if want_struct:
        built = _v2_arrays_vectorized(meta, part_strings, stats, n)
        if built is None:
            # runtime vectorization failure (pretty-printed stats, a cast
            # the batch path can't make): fall back to the dataclass row
            # builder, which coerces per value — NOT to a struct-less
            # columnar write, which would flip this table's checkpoint
            # schema between versions. (Schemas the vectorized builder
            # can't type at all never reach here under the engine default:
            # write_stats_as_struct gates on _struct_stats_vectorizable.)
            return None
        extra_fields, extra_children = built
        if extra_fields:
            extras_by_name = {
                f.name: c for f, c in zip(extra_fields, extra_children)}
            add_idx = schema.get_field_index("add")
            add_type = pa.struct(list(add_type) + extra_fields)
            schema = schema.set(add_idx, pa.field("add", add_type))

    if part_cols and n:
        # one map column for all rows: every row carries the same key set,
        # so offsets/keys are arithmetic and the values interleave with one
        # C++ take over the per-column string arrays
        kp = len(part_cols)
        offsets = pa.array(np.arange(n + 1, dtype=np.int32) * kp)
        keys = pa.array(part_cols, pa.string()).take(
            pa.array(np.tile(np.arange(kp, dtype=np.int64), n)))
        stacked = pa.concat_arrays([part_strings[c] for c in part_cols])
        perm = (np.tile(np.arange(kp, dtype=np.int64) * n, n)
                + np.repeat(np.arange(n, dtype=np.int64), kp))
        part_maps = pa.MapArray.from_arrays(
            offsets, keys, stacked.take(pa.array(perm))).cast(str_map)
    else:
        part_maps = pa.MapArray.from_arrays(
            pa.array(np.zeros(n + 1, np.int32)),
            pa.array([], pa.string()), pa.array([], pa.string()),
        ).cast(str_map)

    # few + may carry fields the columns don't (extendedFileMetadata):
    # protocol/metadata/txns/tombstones stay on the exact row path —
    # assembled directly, NOT via checkpoint_actions() (which would
    # materialize every AddFile, the exact cost this writer avoids)
    from dataclasses import replace as _dc_replace

    proto, meta_action, txns = snapshot._other_state
    head_actions: List[Action] = []
    if proto is not None:
        head_actions.append(proto)
    if meta_action is not None:
        head_actions.append(meta_action)
    head_actions.extend(txns.values())
    head_actions.extend(
        _dc_replace(r, data_change=False) for r in snapshot.tombstones
    )
    head_rows = [_action_to_row(a) for a in head_actions]
    head_cols = {
        f.name: [r.get(f.name) for r in head_rows] for f in schema
    }
    head = pa.Table.from_pydict(head_cols, schema=schema)

    children = []
    for f in add_type:
        if f.name == "path":
            children.append(paths)
        elif f.name == "partitionValues":
            children.append(part_maps)
        elif f.name == "size":
            children.append(pa.array(cols.size[rows]))
        elif f.name == "modificationTime":
            children.append(pa.array(cols.modification_time[rows]))
        elif f.name == "dataChange":
            children.append(pa.array(np.zeros(n, bool)))
        elif f.name == "stats":
            children.append(stats)
        elif f.name in extras_by_name:
            children.append(extras_by_name[f.name])
        else:  # tags / deletionVector: absent by the fast-path precondition
            children.append(pa.nulls(n, f.type))
    add_struct = pa.StructArray.from_arrays(children, fields=list(add_type))
    adds_tbl = pa.table(
        {f.name: (add_struct if f.name == "add" else pa.nulls(n, f.type))
         for f in schema},
        schema=schema,
    )
    full = pa.concat_tables([head, adds_tbl])

    total = full.num_rows
    parts = 1 if total <= part_size else math.ceil(total / part_size)
    if parts == 1:
        paths_out = [f"{log_path}/{filenames.checkpoint_file_single(snapshot.version)}"]
    else:
        paths_out = [f"{log_path}/{p}"
                     for p in filenames.checkpoint_file_with_parts(snapshot.version, parts)]
    chunk = math.ceil(total / parts)

    def _write_slice(i: int) -> None:
        sink = pa.BufferOutputStream()
        pq.write_table(full.slice(i * chunk, chunk), sink, compression="snappy")
        store.write_bytes(paths_out[i], sink.getvalue().to_pybytes(), overwrite=True)

    if parts == 1:
        _write_slice(0)
    else:
        _run_all_parts(parts, _write_slice)
    md = CheckpointMetaData(snapshot.version, total, None if parts == 1 else parts)
    write_last_checkpoint(store, log_path, md)
    from delta_tpu.utils.telemetry import bump_counter

    bump_counter("checkpoint.parts", parts)
    bump_counter("checkpoint.actions", total)
    return md


def write_checkpoint(
    store: LogStore,
    log_path: str,
    version: int,
    actions: Sequence[Action],
    parts: Optional[int] = None,
    part_size: int = 1_000_000,
    distribute: bool = False,
) -> CheckpointMetaData:
    """Write a checkpoint for ``version`` holding ``actions`` (the reconciled
    state from :meth:`LogReplay.checkpoint_actions`).

    Single-part by default; multi-part when ``parts`` given or the state
    exceeds ``part_size`` actions. Parts are written concurrently (the
    reference's multi-part support is read-only in this version — its writer
    is a single-task ``repartition(1)``; we go wider). Files are staged and
    atomically renamed when the store shows partial writes
    (``Checkpoints.scala:271-303``). Tables with struct stats enabled
    (:func:`write_stats_as_struct` — explicit
    ``delta.checkpoint.writeStatsAsStruct`` table property, else the
    session conf ``delta.tpu.checkpoint.writeStatsAsStruct``, default on)
    additionally get the V2 ``partitionValues_parsed``/``stats_parsed``
    typed columns."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.utils.telemetry import with_status

    n = len(actions)
    if parts is None:
        parts = 1 if n <= part_size else math.ceil(n / part_size)

    schema = _arrow_checkpoint_schema()
    v2_fields, v2_build = _v2_schema_and_rows(actions)
    if v2_fields:
        add_idx = schema.get_field_index("add")
        add_type = schema.field(add_idx).type
        new_add = pa.struct(list(add_type) + v2_fields)
        schema = schema.set(add_idx, pa.field("add", new_add))

    def _write_one(path: str, acts: Sequence[Action]) -> None:
        rows = [_action_to_row(a) for a in acts]
        if v2_build is not None:
            for a, r in zip(acts, rows):
                if isinstance(a, AddFile):
                    r["add"].update(v2_build(a))
        cols = {}
        for field_ in schema:
            cols[field_.name] = [r.get(field_.name) for r in rows]
        table = pa.Table.from_pydict(cols, schema=schema)
        sink = pa.BufferOutputStream()
        pq.write_table(table, sink, compression="snappy")
        store.write_bytes(path, sink.getvalue().to_pybytes(), overwrite=True)

    with with_status(f"Writing checkpoint at version {version}"):
        return _finish_write_checkpoint(
            store, log_path, version, actions, parts, n, _write_one,
            distribute)


def _finish_write_checkpoint(store, log_path, version, actions, parts, n,
                             _write_one, distribute):
    if distribute:
        from delta_tpu.parallel.distributed import process_info

        proc, n_procs = process_info()
    else:
        proc, n_procs = 0, 1

    if parts == 1:
        path = f"{log_path}/{filenames.checkpoint_file_single(version)}"
        if proc == 0:
            _write_one(path, actions)
        md = CheckpointMetaData(version, n, None)
        all_paths = [path]
    else:
        paths = [f"{log_path}/{p}" for p in filenames.checkpoint_file_with_parts(version, parts)]
        chunk = math.ceil(n / parts) if n else 0
        slices = [actions[i * chunk:(i + 1) * chunk] for i in range(parts)]
        if n_procs > 1:
            # each host writes its deterministic slice of the parts — the
            # reference fans part writes over executors; here over processes
            from delta_tpu.parallel.distributed import host_shard_indices

            mine = host_shard_indices(parts, proc, n_procs)
            paths_slices = [(paths[i], slices[i]) for i in mine]
        else:
            paths_slices = list(zip(paths, slices))
        if paths_slices:
            _run_all_parts(len(paths_slices),
                           lambda i: _write_one(*paths_slices[i]))
        md = CheckpointMetaData(version, n, parts)
        all_paths = paths
    if proc == 0:
        if n_procs > 1:
            _wait_for_paths(store, all_paths)
        # only the coordinating process publishes the pointer, and only
        # after every host's parts are visible — readers trust it
        write_last_checkpoint(store, log_path, md)
    from delta_tpu.utils.telemetry import bump_counter

    bump_counter("checkpoint.parts", parts)
    bump_counter("checkpoint.actions", n)
    return md


def _distributed_timeout_s() -> float:
    from delta_tpu.utils.config import conf

    return int(conf.get("delta.tpu.distributed.timeoutMs", 600_000)) / 1000


def _wait_for_paths(store: LogStore, paths: Sequence[str],
                    timeout_s: Optional[float] = None) -> None:
    """Poll until every path exists (multi-host checkpoint barrier over the
    shared store — no RPC, matching the engine's no-lock-service stance).
    Existence checks only — never downloads (`LogStore.exists`)."""
    import time as _time

    deadline = _time.monotonic() + (timeout_s or _distributed_timeout_s())
    pending = list(paths)
    while pending:
        pending = [p for p in pending if not store.exists(p)]
        if not pending:
            return
        if _time.monotonic() > deadline:
            raise DeltaIllegalStateError(
                f"Timed out waiting for checkpoint parts from other hosts: "
                f"{pending[:3]}{'...' if len(pending) > 3 else ''}"
            )
        _time.sleep(0.05)


def _row_to_action(name: str, d: Dict[str, Any]) -> Optional[Action]:
    if d is None:
        return None
    d = dict(d)
    if name == "add":
        d = _fix_maps(d, ("partitionValues", "tags"))
        return AddFile.from_dict(d)
    if name == "remove":
        d = _fix_maps(d, ("partitionValues", "tags"))
        return RemoveFile.from_dict(d)
    if name == "metaData":
        d = _fix_maps(d, ("configuration",))
        fmt = d.get("format")
        if fmt:
            d["format"] = _fix_maps(dict(fmt), ("options",))
        return Metadata.from_dict(d)
    if name == "protocol":
        return Protocol.from_dict(d)
    if name == "txn":
        return SetTransaction.from_dict(d)
    return None


def _fix_maps(d: Dict[str, Any], keys) -> Dict[str, Any]:
    # pyarrow renders map columns as list-of-(key,value)-tuples in to_pylist().
    for k in keys:
        v = d.get(k)
        if isinstance(v, list):
            d[k] = dict(v)
    return d


def read_checkpoint_actions(store: LogStore, paths: Sequence[str]) -> List[Action]:
    """Read one checkpoint (all its part files) back into actions.

    Part files fetch and decode concurrently via
    :func:`delta_tpu.log.columnar.decode_checkpoint_parts` (the writer
    already writes them that way). Output order is deterministic: parts in
    input order, per-column action order within a part."""
    from delta_tpu.log.columnar import decode_checkpoint_parts

    try:
        tables = decode_checkpoint_parts(store, paths)
    except FileNotFoundError as e:
        # all parts share one version; name the checkpoint, not the part
        version = filenames.get_file_version(os.path.basename(paths[0]))
        raise errors.missing_part_files(version, e) from e
    out: List[Action] = []
    for table in tables:
        for name in ("protocol", "metaData", "txn", "remove", "add"):
            if name not in table.column_names:
                continue
            col = table.column(name)
            for v in col.to_pylist():
                a = _row_to_action(name, v)
                if a is not None:
                    out.append(a)
    if not out:
        raise DeltaIllegalStateError(f"Empty checkpoint read from {list(paths)}")
    return out
