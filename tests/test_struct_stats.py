"""Struct-stats checkpoints: zero-JSON cold state-cache builds.

The checkpoint writer materializes parsed per-file stats as typed Parquet
struct columns (`add.stats_parsed`, plus `add.partitionValues_parsed` for
partitioned tables — `Checkpoints.scala` V2 / PROTOCOL.md §Checkpoints),
default-on via `delta.tpu.checkpoint.writeStatsAsStruct`; the cold read
path (`log/columnar.decode_checkpoint_parts` → `SegmentColumns.stats_parsed`
→ `ops/state_export.arrays_from_columns`) builds its float64 pruning lanes
straight from the typed leaves with ZERO stats-JSON parsing. These tests
pin the round trip (unpartitioned / partitioned / mixed-null), the
backward-compat and mixed-segment fallbacks, plan parity between the two
formats, and — via telemetry counters, not wall clock, so CI stays
deterministic — that the cold build actually takes the zero-JSON path.
"""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.ops.state_cache import DeviceStateCache
from delta_tpu.ops.state_export import arrays_from_columns
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import AddFile, Metadata, Protocol
from delta_tpu.schema.types import (
    DoubleType,
    LongType,
    StringType,
    StructType,
)
from delta_tpu.storage.logstore import get_log_store
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_caches():
    DeviceStateCache.reset()
    telemetry.clear_counters()
    yield
    DeviceStateCache.reset()


def _stats(i: int, null_x: bool = False) -> str:
    mins = {"y": i * 0.5} if null_x else {"x": i, "y": i * 0.5}
    maxs = {"y": i * 0.5 + 1} if null_x else {"x": i + 3, "y": i * 0.5 + 1}
    return json.dumps({
        "numRecords": 10,
        "minValues": mins,
        "maxValues": maxs,
        "nullCount": {"x": 10 if null_x else 0, "y": 0},
    })


def _synthetic_log(root, n=50, partitioned=False, null_every=None):
    """One commit holding protocol+metadata+n AddFiles with stats JSON.
    State-cache/planning tests never open the data files."""
    log_path = os.path.join(root, "_delta_log")
    store = get_log_store(log_path)
    schema = StructType().add("x", LongType()).add("y", DoubleType())
    pcols = []
    if partitioned:
        schema = schema.add("day", StringType())
        pcols = ["day"]
    meta = Metadata(schema_string=schema.to_json(), partition_columns=pcols)
    proto = Protocol(1, 2)
    adds = []
    for i in range(n):
        null_x = null_every is not None and i % null_every == 0
        pv = {"day": f"2021-03-{(i % 9) + 1:02d}"} if partitioned else {}
        adds.append(AddFile(
            path=f"f{i:05d}.parquet", size=100 + i, modification_time=i,
            data_change=True, stats=_stats(i, null_x), partition_values=pv,
        ))
    store.write(f"{log_path}/{filenames.delta_file(0)}",
                [proto.json(), meta.json()] + [a.json() for a in adds])
    return log_path, store, adds


def _checkpoint(root, struct: bool):
    with conf.set_temporarily(
            **{"delta.tpu.checkpoint.writeStatsAsStruct": struct}):
        log = DeltaLog.for_table(root)
        snap = log.update()
        md = log.checkpoint(snap)
    DeltaLog.clear_cache()
    DeviceStateCache.reset()
    return md


def _cold_arrays(root):
    snap = DeltaLog.for_table(root).update()
    return snap, arrays_from_columns(
        snap._columnar, snap._alive_mask, snap.metadata)


def _assert_lane_parity(a, b):
    assert a.paths == b.paths
    assert np.array_equal(a.size, b.size)
    assert np.array_equal(a.num_records, b.num_records)
    assert sorted(a.stats_min) == sorted(b.stats_min)
    for c in a.stats_min:
        assert np.array_equal(a.stats_min[c], b.stats_min[c], equal_nan=True)
        assert np.array_equal(a.stats_max[c], b.stats_max[c], equal_nan=True)
        assert np.array_equal(a.stats_null_count[c], b.stats_null_count[c])
    assert sorted(a.partition_codes) == sorted(b.partition_codes)
    for c in a.partition_codes:
        assert a.partition_dicts[c] == b.partition_dicts[c]
        assert np.array_equal(a.partition_codes[c], b.partition_codes[c])


# ---------------------------------------------------------------------------
# round trip: struct path vs JSON path must agree lane-for-lane
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", ["unpartitioned", "partitioned", "mixed_null"])
def test_struct_checkpoint_roundtrip_lane_parity(tmp_path, shape):
    kw = dict(partitioned=shape == "partitioned",
              null_every=5 if shape == "mixed_null" else None)
    root_s = str(tmp_path / "s")
    root_j = str(tmp_path / "j")
    _synthetic_log(root_s, **kw)
    _synthetic_log(root_j, **kw)
    _checkpoint(root_s, struct=True)
    _checkpoint(root_j, struct=False)

    telemetry.clear_counters()
    snap_s, arr_s = _cold_arrays(root_s)
    counters = telemetry.counters("stateExport.statsLanes")
    assert counters.get("stateExport.statsLanes.struct", 0) >= 1
    assert "stateExport.statsLanes.json" not in counters

    telemetry.clear_counters()
    snap_j, arr_j = _cold_arrays(root_j)
    assert telemetry.counters("stateExport.statsLanes").get(
        "stateExport.statsLanes.json", 0) >= 1

    assert snap_s.num_of_files == snap_j.num_of_files == 50
    assert arr_s is not None and arr_j is not None
    _assert_lane_parity(arr_s, arr_j)


def test_multipart_struct_checkpoint_roundtrip(tmp_path):
    """Multi-part struct checkpoints decode in parallel and reassemble in
    part order — lanes and snapshot state must match the single-part read."""
    root = str(tmp_path / "t")
    log_path, store, adds = _synthetic_log(root, n=40, partitioned=True)
    log = DeltaLog.for_table(root)
    snap = log.update()
    md = ckpt_mod.write_checkpoint(
        store, log_path, snap.version, snap.checkpoint_actions(), parts=3)
    assert md.parts == 3
    DeltaLog.clear_cache()
    DeviceStateCache.reset()
    telemetry.clear_counters()
    snap2, arr = _cold_arrays(root)
    assert snap2.segment.checkpoint_version == 0
    assert snap2.num_of_files == 40
    assert arr is not None
    assert telemetry.counters("stateExport.statsLanes").get(
        "stateExport.statsLanes.struct", 0) >= 1
    # replay order within the checkpoint is preserved part-for-part
    assert arr.paths == sorted(arr.paths)


def test_backward_compat_checkpoint_without_struct_column(tmp_path):
    """Checkpoints written before struct stats (or with the table opted
    out) must still read correctly under the default-on reader."""
    root = str(tmp_path / "t")
    _synthetic_log(root, n=30)
    _checkpoint(root, struct=False)
    telemetry.clear_counters()
    snap, arr = _cold_arrays(root)
    assert snap.num_of_files == 30
    assert arr is not None
    assert arr.stats_min["x"][7] == 7.0
    assert telemetry.counters("stateExport.statsLanes").get(
        "stateExport.statsLanes.json", 0) >= 1


def test_mixed_segment_struct_checkpoint_plus_json_tail(tmp_path):
    """Commits after the checkpoint carry stats only as JSON; the read path
    serves checkpoint rows from the struct and parses ONLY the tail rows."""
    root = str(tmp_path / "t")
    log_path, store, _ = _synthetic_log(root, n=30)
    _checkpoint(root, struct=True)
    tail = [AddFile(path=f"g{i}.parquet", size=1, modification_time=0,
                    data_change=True, stats=_stats(1000 + i))
            for i in range(3)]
    store.write(f"{log_path}/{filenames.delta_file(1)}",
                [a.json() for a in tail])
    DeltaLog.clear_cache()
    telemetry.clear_counters()
    snap, arr = _cold_arrays(root)
    assert snap.num_of_files == 33
    assert arr is not None
    assert telemetry.counters("stateExport.statsLanes").get(
        "stateExport.statsLanes.mixed", 0) >= 1
    by_path = dict(zip(arr.paths, arr.stats_min["x"]))
    assert by_path["g0.parquet"] == 1000.0  # tail row via the JSON fallback
    assert by_path["f00007.parquet"] == 7.0  # checkpoint row via the struct


def test_struct_checkpoint_replays_identically_through_dataclasses(tmp_path):
    """`read_checkpoint_actions` on a struct-stats checkpoint must yield the
    same actions (paths, stats JSON, partition values) as the JSON-stats
    checkpoint of the same state — the extra columns are strictly additive."""
    root_s = str(tmp_path / "s")
    root_j = str(tmp_path / "j")
    _synthetic_log(root_s, n=20, partitioned=True)
    _synthetic_log(root_j, n=20, partitioned=True)
    md_s = _checkpoint(root_s, struct=True)
    md_j = _checkpoint(root_j, struct=False)

    def read(root, md):
        lp = os.path.join(root, "_delta_log")
        acts = ckpt_mod.read_checkpoint_actions(
            get_log_store(lp),
            ckpt_mod.CheckpointInstance(md.version, md.parts).paths(lp))
        return {a.path: a for a in acts if isinstance(a, AddFile)}

    adds_s, adds_j = read(root_s, md_s), read(root_j, md_j)
    assert sorted(adds_s) == sorted(adds_j)
    for p, a in adds_s.items():
        b = adds_j[p]
        assert a.stats == b.stats
        assert a.partition_values == b.partition_values
        assert (a.size, a.modification_time) == (b.size, b.modification_time)


def test_plan_parity_between_struct_and_json_checkpoints(tmp_path):
    """Pruning plans must be identical whichever checkpoint format fed the
    state cache."""
    from delta_tpu.exec.scan import plan_scans

    root_s = str(tmp_path / "s")
    root_j = str(tmp_path / "j")
    _synthetic_log(root_s, n=60, partitioned=True)
    _synthetic_log(root_j, n=60, partitioned=True)
    _checkpoint(root_s, struct=True)
    _checkpoint(root_j, struct=False)
    queries = [
        ["x >= 10 AND x <= 14"],
        ["y >= 5.0 AND y <= 6.0"],
        ["day = '2021-03-04'"],
        ["day >= '2021-03-02' AND day <= '2021-03-05' AND x >= 20"],
        [],
    ]
    with conf.set_temporarily(**{"delta.tpu.stateCache.devicePlan.mode": "off"}):
        snap_s = DeltaLog.for_table(root_s).update()
        plans_s = plan_scans(snap_s, queries, k=16)
        DeltaLog.clear_cache()
        DeviceStateCache.reset()
        snap_j = DeltaLog.for_table(root_j).update()
        plans_j = plan_scans(snap_j, queries, k=16)
    for ps, pj in zip(plans_s, plans_j):
        assert ps.count == pj.count
        assert ps.overflow == pj.overflow
        assert sorted(ps.paths) == sorted(pj.paths)


def test_string_stats_with_iso_date_literals_round_trip_verbatim(tmp_path):
    """A STRING column whose values look like ISO dates must keep its
    stats_parsed min/max as the exact literals ('2021-01-01'), not the
    timestamp rendering the Arrow JSON reader would infer without the
    writer's explicit parse schema ('2021-01-01 00:00:00' — lexically
    larger than the true min, un-conservative for full-string skipping)."""
    import pyarrow.parquet as pq

    from delta_tpu.api.tables import DeltaTable

    root = str(tmp_path / "t")
    t = DeltaTable.create(root, data=pa.table({
        "s": pa.array(["2021-01-01", "2021-01-05"], pa.string()),
        "x": pa.array([1, 2], pa.int64()),
    }))
    md = t.delta_log.checkpoint()
    tab = pq.read_table(
        f"{t.delta_log.log_path}/{filenames.checkpoint_file_single(md.version)}")
    [add] = [r for r in tab.column("add").to_pylist() if r]
    assert add["stats_parsed"]["minValues"]["s"] == "2021-01-01"
    assert add["stats_parsed"]["maxValues"]["s"] == "2021-01-05"
    assert add["stats_parsed"]["minValues"]["x"] == 1


# ---------------------------------------------------------------------------
# the zero-JSON smoke: 10k-file cold cache build, asserted via counters
# ---------------------------------------------------------------------------


def test_cold_state_cache_build_10k_files_takes_zero_json_path(tmp_path):
    """BENCH metric 6's cold-build shape at CI scale: the whole cold
    DeviceStateCache build off a struct-stats checkpoint must never touch
    the stats-JSON parser (asserted via the statsLanes telemetry counters —
    deterministic, unlike wall clock)."""
    root = str(tmp_path / "t")
    _synthetic_log(root, n=10_000)
    _checkpoint(root, struct=True)
    telemetry.clear_counters()
    snap = DeltaLog.for_table(root).update()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None
    assert entry.num_rows == 10_000
    counters = telemetry.counters("stateExport.statsLanes")
    assert counters.get("stateExport.statsLanes.struct", 0) >= 1
    assert "stateExport.statsLanes.json" not in counters
    assert "stateExport.statsLanes.mixed" not in counters


# ---------------------------------------------------------------------------
# per-range k (plan_scans batch cliff regression)
# ---------------------------------------------------------------------------


def test_plan_ranges_accepts_per_range_k(tmp_path):
    from delta_tpu.ops.state_cache import RangeSet

    root = str(tmp_path / "t")
    _synthetic_log(root, n=50)
    snap = DeltaLog.for_table(root).update()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None
    c = entry.columns.index("x")
    wide = RangeSet(np.full(len(entry.columns), np.nan),
                    np.full(len(entry.columns), np.nan))
    wide.lo[c], wide.hi[c] = 0.0, 1e9  # matches every file
    plans = entry.plan_ranges([wide, wide], k=[4, entry.num_rows],
                              use_device=False)
    assert plans[0].count == plans[1].count == 50
    assert len(plans[0].rows) == 4 and plans[0].overflow
    assert len(plans[1].rows) == 50 and not plans[1].overflow


def test_plan_scans_keeps_single_term_queries_on_small_k(tmp_path, monkeypatch):
    """A multi-term (OR) query in the batch must not force k=num_rows onto
    the single-term queries sharing the dispatch (ADVICE perf cliff)."""
    from delta_tpu.exec import scan as scan_mod
    from delta_tpu.ops.state_cache import ResidentState

    root = str(tmp_path / "t")
    _synthetic_log(root, n=50)
    snap = DeltaLog.for_table(root).update()
    assert DeviceStateCache.instance().get(snap) is not None

    seen = {}
    orig = ResidentState.plan_ranges

    def spy(self, ranges, k=256, **kw):
        seen["k"] = list(k) if not np.isscalar(k) else k
        return orig(self, ranges, k=k, **kw)

    monkeypatch.setattr(ResidentState, "plan_ranges", spy)
    queries = [
        ["x >= 0 AND x <= 1000"],  # single-term: stays on k
        ["x >= 0 AND x <= 4 OR x >= 40 AND x <= 44"],  # 2 boxes: full rows
    ]
    with conf.set_temporarily(**{"delta.tpu.stateCache.devicePlan.mode": "off"}):
        plans = scan_mod.plan_scans(snap, queries, k=8)
    assert seen["k"] == [8, 50, 50]
    assert plans[0].count == 50 and plans[0].overflow
    assert len(plans[0].paths) == 8
    # the OR query's union is exact ([0,4] keeps files 0-4, [40,44] keeps
    # 37-44 with width-3 ranges) even though the caller's k truncates paths
    assert plans[1].count == 13 and plans[1].overflow
    assert len(plans[1].paths) == 8


# ---------------------------------------------------------------------------
# acceptance: 100k-file cold build, struct >= 3x faster than JSON, same plans
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_100k_cold_build_struct_3x_faster_same_plans(tmp_path):
    """Acceptance: at 100k files (BENCH metric-6 shape, CI-scaled), the
    struct-stats parse component of the cold build is >=3x faster than the
    JSON-stats path measured in the same run, with identical lanes (and
    therefore identical pruning plans — see the fast plan-parity test)."""
    root_s = str(tmp_path / "s")
    root_j = str(tmp_path / "j")
    _synthetic_log(root_s, n=100_000)
    _synthetic_log(root_j, n=100_000)
    _checkpoint(root_s, struct=True)
    _checkpoint(root_j, struct=False)

    def build(root):
        telemetry.clear_counters()
        snap = DeltaLog.for_table(root).update()
        arr = arrays_from_columns(snap._columnar, snap._alive_mask,
                                  snap.metadata)
        # warm caches/IO, then measure the second (steady) build's lane time
        telemetry.clear_counters()
        arr = arrays_from_columns(snap._columnar, snap._alive_mask,
                                  snap.metadata)
        us = telemetry.counters("stateExport.statsLanes").get(
            "stateExport.statsLanes.us", 0)
        return arr, us

    arr_s, us_struct = build(root_s)
    DeltaLog.clear_cache()
    arr_j, us_json = build(root_j)

    assert arr_s is not None and arr_j is not None
    _assert_lane_parity(arr_s, arr_j)
    assert us_struct > 0 and us_json > 0
    assert us_json >= 3 * us_struct, (
        f"struct stats-lane build {us_struct}us vs json {us_json}us "
        f"({us_json / max(us_struct, 1):.1f}x)")
