"""Bench regression gate (ISSUE 7 satellite): tools/bench_diff compares two
BENCH_*.json rounds with direction-aware percentage thresholds, and bench.py
grows a --compare mode wired to it. Pure-logic tests — no bench run."""
import json

import pytest

from tools.bench_diff import (DEFAULT_THRESHOLD_PCT, Regression, compare,
                              compare_files, main)


def _round(configs):
    head = next(iter(configs.values()))
    return {"metric": head["metric"], "value": head["value"],
            "unit": head["unit"], "vs_baseline": head.get("vs_baseline", 1),
            "all": configs}


def _cfg(metric, value, unit):
    return {"metric": metric, "value": value, "unit": unit, "vs_baseline": 1}


def test_throughput_regression_detected():
    prior = _round({"2": _cfg("merge", 1.0, "GB/s")})
    cur = _round({"2": _cfg("merge", 0.7, "GB/s")})  # 30% slower
    [r] = compare(cur, prior, threshold_pct=20)
    assert r.config == "2" and r.delta_pct == pytest.approx(30.0)
    assert "worse" in r.describe()
    # within threshold: clean
    assert compare(cur, prior, threshold_pct=35) == []
    # improvement is never a regression
    assert compare(prior, cur, threshold_pct=20) == []


def test_latency_units_regress_when_value_grows():
    prior = _round({"3": _cfg("point_query", 100.0, "ms")})
    worse = _round({"3": _cfg("point_query", 150.0, "ms")})
    better = _round({"3": _cfg("point_query", 60.0, "ms")})
    [r] = compare(worse, prior, threshold_pct=20)
    assert r.delta_pct == pytest.approx(50.0)
    assert compare(better, prior, threshold_pct=20) == []


def test_skipped_error_and_missing_configs_are_ignored():
    prior = _round({
        "2": _cfg("merge", 1.0, "GB/s"),
        "7": _cfg("probe", 100.0, "ms"),
        "8": {"metric": "config_8", "value": -1, "unit": "skipped",
              "vs_baseline": 0},
    })
    cur = _round({
        "2": {"metric": "config_2", "value": -1, "unit": "error",
              "vs_baseline": 0, "note": "boom"},
        "8": _cfg("probe8", 5.0, "ms"),       # prior skipped: no baseline
        "9": _cfg("new_config", 1.0, "s"),    # config only in current
        # config 7 absent from current entirely
    })
    assert compare(cur, prior) == []


def test_unit_change_makes_config_incomparable():
    prior = _round({"5": _cfg("replay", 500.0, "ms")})
    cur = _round({"5": _cfg("replay", 10.0, "commits/s")})
    assert compare(cur, prior, threshold_pct=1) == []


def test_bare_config_map_shape_accepted():
    # bench.py passes its raw results dict (no "all" wrapper)
    prior = {"2": _cfg("merge", 1.0, "GB/s")}
    cur = {"2": _cfg("merge", 0.5, "GB/s")}
    [r] = compare(cur, prior, threshold_pct=20)
    assert r.delta_pct == pytest.approx(50.0)


def test_compare_files_and_cli(tmp_path):
    prior_p = tmp_path / "BENCH_prior.json"
    cur_p = tmp_path / "BENCH_cur.json"
    prior_p.write_text(json.dumps(_round({"4": _cfg("tail", 100.0, "commits/s")})))
    cur_p.write_text(json.dumps(_round({"4": _cfg("tail", 50.0, "commits/s")})))
    [r] = compare_files(str(cur_p), str(prior_p))
    assert isinstance(r, Regression) and r.delta_pct == pytest.approx(50.0)
    assert main([str(prior_p), str(cur_p)]) == 3          # regression: rc 3
    assert main([str(cur_p), str(prior_p)]) == 0          # improvement: rc 0
    assert main([str(prior_p), str(cur_p), "--threshold", "60"]) == 0
    assert DEFAULT_THRESHOLD_PCT == 20.0


def test_bench_argv_parsing():
    from bench import _parse_argv

    assert _parse_argv([]) == (None, None, 20.0)
    assert _parse_argv(["2"]) == ("2", None, 20.0)
    assert _parse_argv(["--compare", "BENCH_r06.json"]) == (
        None, "BENCH_r06.json", 20.0)
    assert _parse_argv(["2x", "--compare", "b.json",
                        "--compare-threshold", "35"]) == ("2x", "b.json", 35.0)
    # malformed flags exit with a usage message, not a traceback
    with pytest.raises(SystemExit):
        _parse_argv(["--compare"])
    with pytest.raises(SystemExit):
        _parse_argv(["--compare-threshold"])
    with pytest.raises(SystemExit):
        _parse_argv(["--compare-threshold", "abc"])
    # a typo'd flag must not silently become the config selector (it would
    # run zero configs and pass the gate vacuously)
    with pytest.raises(SystemExit):
        _parse_argv(["--compare-thresold", "25", "--compare", "b.json"])


def test_gate_submetrics_walked_direction_aware():
    """ISSUE 9 satellite: a config's `gate` map of named sub-metrics (the
    contention config's per-leg p99 / throughput) is gated with the same
    direction-aware thresholds, reported as <config>.gate.<name>."""
    def with_gate(p99, tput, speedup):
        c = _cfg("commit_p99_speedup", speedup, "x")
        c["gate"] = {
            "grouped_p99_ms": {"value": p99, "unit": "ms"},
            "grouped_throughput": {"value": tput, "unit": "commits/s"},
        }
        return c

    prior = _round({"9": with_gate(10.0, 200.0, 3.0)})
    # p99 grows 50% (latency: worse), throughput up (better), headline flat
    cur = _round({"9": with_gate(15.0, 250.0, 3.0)})
    [r] = compare(cur, prior, threshold_pct=20)
    assert r.config == "9.gate.grouped_p99_ms"
    assert r.metric == "grouped_p99_ms"
    assert r.delta_pct == pytest.approx(50.0)

    # throughput collapse flags too; p99 improvement does not
    cur2 = _round({"9": with_gate(5.0, 100.0, 3.0)})
    [r2] = compare(cur2, prior, threshold_pct=20)
    assert r2.config == "9.gate.grouped_throughput"

    # headline regression still reported alongside gate entries
    cur3 = _round({"9": with_gate(10.0, 200.0, 1.0)})
    [r3] = compare(cur3, prior, threshold_pct=20)
    assert r3.config == "9"

    # a gate entry missing from either round is simply not compared
    cur4 = _round({"9": _cfg("commit_p99_speedup", 3.0, "x")})
    assert compare(cur4, prior, threshold_pct=20) == []


def test_findings_unit_is_lower_is_better():
    """The static-analysis gate (bench.py "analysis" entry): finding-count
    growth is a regression, shrinkage is an improvement."""
    prior = _round({"analysis": _cfg("analysis_findings", 1.0, "findings")})
    worse = _round({"analysis": _cfg("analysis_findings", 2.0, "findings")})
    [r] = compare(worse, prior, threshold_pct=20)
    assert r.config == "analysis" and r.unit == "findings"
    assert r.delta_pct == pytest.approx(100.0)
    assert compare(prior, worse, threshold_pct=20) == []  # improvement


def test_findings_regression_from_clean_zero_still_gates():
    """0 -> N findings must trip the gate even though a zero prior cannot
    anchor an ordinary percentage."""
    clean = _round({"analysis": _cfg("analysis_findings", 0.0, "findings")})
    dirty = _round({"analysis": _cfg("analysis_findings", 3.0, "findings")})
    [r] = compare(dirty, clean, threshold_pct=20)
    assert r.delta_pct == pytest.approx(300.0)
    assert compare(clean, clean, threshold_pct=20) == []
    # zero-prior latency configs keep the old no-anchor behavior
    z = _round({"7": _cfg("probe", 0.0, "ms")})
    nz = _round({"7": _cfg("probe", 5.0, "ms")})
    assert compare(nz, z, threshold_pct=20) == []
