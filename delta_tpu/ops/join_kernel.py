"""Device equi-join for MERGE — the mesh (all-gather) kernel + host fallback.

The reference runs MERGE phase 1 (findTouchedFiles) as a Spark inner join
source×target with a row-id/file-name UDF (`commands/MergeIntoCommand.scala:310-389`)
and phase 2 as an outer join + row-at-a-time clause interpreter (`:456-561`).
Here the join itself is a device kernel; clause application stays columnar
Arrow on the host (`commands/merge.py`).

Since PR 6 the PRIMARY single-chip join is the fused block-bucketed
membership probe in `ops/key_cache.py` (resident slab + O(matched) pair
download); `commands/merge.py` routes there first. This module remains the
multichip path (`delta.tpu.merge.devicePath.preferMesh`) — the sharded
all-gather sort-merge below — plus the exact host sort-merge fallback and
the shared `PendingJoin`/`JoinResult` contract both executors return.

Shape of the kernel (TPU-first, not a shuffle translation):

  An upsert MERGE is a small-source × large-target join, so instead of
  hash-partitioning both sides over the mesh (an all-to-all whose per-shard
  capacities are data-dependent — dynamic shapes XLA can't tile), the
  *target* keys stay sharded where they are and the *source* keys are
  `all_gather`ed over ICI (tiled, one collective). Each shard then runs a
  static-shaped sort-merge probe:

      sort source keys                       # bitonic-sort-backed on TPU
      lo/hi = searchsorted(slab keys)        # left/right bounds per key
      count = hi - lo                        # exact per-target match count

  and the per-source matched flags (needed for NOT MATCHED inserts and the
  reference's insert-only left-anti fast path, `:397-450`) come from the
  reverse probe reduced with `psum` over ICI.

Link economics (this is the part a CUDA translation would get wrong):

  - NULL/invalid keys are encoded as *sentinels* (a value provably outside
    both sides' valid range, distinct per side so invalid never matches
    invalid) instead of shipping validity arrays — halves the upload.
  - The device returns only **bit-packed match masks** (n/8 + m/8 bytes)
    plus a scalar multi-match flag. The target→source *pairing* for
    matched rows is recovered on the host with a vectorized searchsorted
    over the matched subset: the device answers the O(n) membership
    question, the host the O(matched) pairing one.
  - `inner_join_async` stages the upload + dispatch on a background thread
    (JAX transfers drop the GIL), so callers overlap the whole device leg
    with host-side Parquet decode and only block in `.result()`.
  - Before launching, the transfer plan is priced against the link profile
    (`parallel/link.py`); when the caller passes the host-join cost as
    ``budget_s`` and the link can't beat it, the launch is declined — on a
    network-tunneled chip bulk uploads run ~6 MB/s and the host hash join
    wins any cold >few-MB join, while on PCIe/DMA hosts the device path
    engages automatically.

Exactness: keys are int64 *values* (no hashing), so there are no false
matches. Composite integer keys are packed into one int64 lane by the
caller (`commands/merge.py`); non-integer keys stay on the host Arrow
hash join.
"""
from __future__ import annotations

import functools
from delta_tpu.utils.jaxcompat import enable_x64
import threading
from typing import Callable, NamedTuple, Optional

import numpy as np

__all__ = ["JoinResult", "PendingJoin", "inner_join", "inner_join_async"]


class JoinResult(NamedTuple):
    """Per-row join outcome (host numpy, unpadded)."""

    t_first_s: np.ndarray  # int64 per target row: first matching source row, -1 = no match
    s_matched: np.ndarray  # bool per source row: has at least one target match
    any_multi: bool  # some target row matched more than one source row

    @property
    def t_matched(self) -> np.ndarray:
        return self.t_first_s >= 0


class PendingJoin:
    """Handle for an in-flight device join; `.result()` blocks on the
    device→host transfer and finishes the host-side pairing recovery."""

    def __init__(self, finalize: Callable[[], JoinResult]):
        self._finalize = finalize
        self._result: Optional[JoinResult] = None

    def result(self) -> JoinResult:
        if self._result is None:
            self._result = self._finalize()
        return self._result


def _bucket(n: int) -> int:
    """Pad size: pow2 up to 4M (few compile shapes), then 2M granularity
    (padding a 10M-row slab to 16.7M would ship 67% more bytes over a
    ~6 MB/s link just to save a compile)."""
    p = 8
    while p < n:
        p *= 2
        if p >= 4_194_304:
            break
    if n <= p <= 4_194_304:
        return p
    g = 2_097_152
    return ((n + g - 1) // g) * g


def _probe_counts(jnp, base_sorted, probe_keys):
    lo = jnp.searchsorted(base_sorted, probe_keys, side="left", method="sort")
    hi = jnp.searchsorted(base_sorted, probe_keys, side="right", method="sort")
    return hi - lo


@functools.lru_cache(maxsize=None)
def _single_device_kernel_cached():
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax

    return _single_device_kernel(jax)


def _single_device_kernel(jax):
    import jax.numpy as jnp

    @jax.jit
    def kernel(t_key, s_key):
        s_sorted = jax.lax.sort(s_key)
        t_sorted = jax.lax.sort(t_key)
        count = _probe_counts(jnp, s_sorted, t_key)
        s_count = _probe_counts(jnp, t_sorted, s_key)
        t_bits = jnp.packbits((count > 0).astype(jnp.uint8))
        s_bits = jnp.packbits((s_count > 0).astype(jnp.uint8))
        return t_bits, s_bits, jnp.any(count > 1)

    return kernel


@functools.lru_cache(maxsize=None)
def _sharded_kernel_cached(mesh, axis):
    import jax

    return _sharded_kernel(jax, mesh, axis)


def _sharded_kernel(jax, mesh, axis):
    import jax.numpy as jnp
    from delta_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(), P()),
    )
    def kernel(t_key, s_key):
        # slabs arrive stacked (1, cap); source is gathered over ICI so every
        # shard probes the full (padded) source in original order
        tk = t_key[0]
        s_full = jax.lax.all_gather(s_key[0], axis, tiled=True)
        count = _probe_counts(jnp, jax.lax.sort(s_full), tk)
        t_bits = jnp.packbits((count > 0).astype(jnp.uint8))
        # reverse probe: this shard's target slab vs the full source; a source
        # row is matched iff any shard finds a hit → psum over ICI
        s_count = _probe_counts(jnp, jax.lax.sort(tk), s_full)
        s_hits = jax.lax.psum(jnp.minimum(s_count, 1), axis)
        multi = jax.lax.psum(jnp.any(count > 1).astype(jnp.int32), axis)
        return t_bits[None], jnp.packbits(s_hits.astype(jnp.uint8)), multi > 0

    return jax.jit(kernel)


def _pad(col: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full(cap, fill, dtype=col.dtype)
    out[: len(col)] = col
    return out


def _first_match_recovery(
    t_keys: np.ndarray,
    t_matched_idx: np.ndarray,
    s_keys: np.ndarray,
    s_ok: np.ndarray,
) -> np.ndarray:
    """For each matched target row, the lowest source row index with an equal
    key — vectorized binary search over the valid source keys, stable-sorted
    so ties resolve to the earliest original row."""
    vidx = np.flatnonzero(s_ok)
    vk = s_keys[vidx]
    order = np.argsort(vk, kind="stable")
    sk = vk[order]
    si = vidx[order]
    pos = np.searchsorted(sk, t_keys[t_matched_idx], side="left")
    return si[pos]


def _host_join(t_key64, t_ok, s_key64, s_ok) -> JoinResult:
    """Vectorized numpy sort-merge join — the device kernel's semantics
    without the device (used when no sentinel value exists)."""
    n, m = len(t_key64), len(s_key64)
    sk = np.sort(s_key64[s_ok])
    lo = np.searchsorted(sk, t_key64, side="left")
    hi = np.searchsorted(sk, t_key64, side="right")
    count = np.where(t_ok, hi - lo, 0)
    t_first_s = np.full(n, -1, np.int64)
    idx = np.flatnonzero(count > 0)
    if idx.size:
        t_first_s[idx] = _first_match_recovery(t_key64, idx, s_key64, s_ok)
    ts = np.sort(t_key64[t_ok])
    s_matched = s_ok & (
        np.searchsorted(ts, s_key64, side="right")
        > np.searchsorted(ts, s_key64, side="left")
    )
    return JoinResult(t_first_s, s_matched, bool((count > 1).any()))


def _sentinel_encode(t_key, t_ok, s_key, s_ok, dtype):
    """Replace invalid keys with per-side sentinels outside both sides'
    valid range (invalid never matches anything, including other invalids).
    Returns (t_enc, s_enc, t_pad_fill, s_pad_fill) or None when the valid
    values span the entire dtype range (fall back to the host join)."""
    info = np.iinfo(dtype)
    lo = min(
        np.min(t_key, where=t_ok, initial=info.max),
        np.min(s_key, where=s_ok, initial=info.max),
    )
    hi = max(
        np.max(t_key, where=t_ok, initial=info.min),
        np.max(s_key, where=s_ok, initial=info.min),
    )
    if hi <= info.max - 2:
        t_sent, s_sent = info.max, info.max - 1
    elif lo >= info.min + 2:
        t_sent, s_sent = info.min, info.min + 1
    else:
        return None
    t_enc = t_key if t_ok.all() else np.where(t_ok, t_key, dtype(t_sent))
    s_enc = s_key if s_ok.all() else np.where(s_ok, s_key, dtype(s_sent))
    return (
        np.ascontiguousarray(t_enc, dtype),
        np.ascontiguousarray(s_enc, dtype),
        dtype(t_sent),
        dtype(s_sent),
    )


def inner_join_async(
    t_keys: np.ndarray,
    t_valid: np.ndarray,
    s_keys: np.ndarray,
    s_valid: np.ndarray,
    mesh=None,
    budget_s: Optional[float] = None,
) -> Optional[PendingJoin]:
    """Launch the device membership probe without blocking.

    ``mesh`` is a 1-D `jax.sharding.Mesh` (target sharded contiguously,
    source gathered); None runs the single-device kernel. Rows with
    ``valid == False`` (SQL NULL keys) never match. Keys are narrowed to
    int32 when both sides' values fit — halves the upload.

    ``budget_s``: decline the launch (return None) when the link cost
    model prices the device leg above this budget — the caller's estimate
    of its fallback (host hash join) cost. None = always launch.
    """
    n, m = len(t_keys), len(s_keys)
    if n == 0 or m == 0:
        return PendingJoin(
            lambda: JoinResult(np.full(n, -1, np.int64), np.zeros(m, bool), False)
        )

    t_key64 = np.ascontiguousarray(t_keys, np.int64)
    s_key64 = np.ascontiguousarray(s_keys, np.int64)
    t_ok = np.asarray(t_valid, bool)
    s_ok = np.asarray(s_valid, bool)

    # narrow to int32 when exact; margin of 2 keeps sentinel room
    i32 = np.iinfo(np.int32)
    if (
        np.min(t_key64, where=t_ok, initial=0) >= i32.min + 2
        and np.max(t_key64, where=t_ok, initial=0) <= i32.max
        and np.min(s_key64, where=s_ok, initial=0) >= i32.min + 2
        and np.max(s_key64, where=s_ok, initial=0) <= i32.max
    ):
        kdtype: type = np.int32
        enc = _sentinel_encode(
            np.where(t_ok, t_key64, 0).astype(np.int32), t_ok,
            np.where(s_ok, s_key64, 0).astype(np.int32), s_ok, np.int32,
        )
    else:
        kdtype = np.int64
        enc = _sentinel_encode(t_key64, t_ok, s_key64, s_ok, np.int64)
    if enc is None:
        # valid keys span the whole dtype: no sentinel room. With a budget
        # the caller has its own fallback; without one, honor the contract
        # with the host numpy sort-merge join.
        if budget_s is not None:
            return None
        return PendingJoin(
            lambda: _host_join(t_key64, t_ok, s_key64, s_ok)
        )
    t_enc, s_enc, t_fill, s_fill = enc

    if mesh is None or getattr(mesh, "devices", np.empty(0)).size <= 1:
        p = 1
        cap_t, cap_s = _bucket(n), _bucket(m)
    else:
        from delta_tpu.parallel.mesh import shard_count

        p = shard_count(mesh)
        cap_t = _bucket((n + p - 1) // p) * p
        cap_s = _bucket((m + p - 1) // p) * p

    if budget_s is not None:
        from delta_tpu.parallel import link

        itemsize = np.dtype(kdtype).itemsize
        est = link.estimate_device_s(
            up_bytes=(cap_t + cap_s) * itemsize,
            down_bytes=cap_t // 8 + cap_s // 8,
            # per-shard work: the target slab sorts locally, the gathered
            # source is probed in full on every shard
            kernel_rows=cap_t // p + cap_s,
        )
        if est.device_s > budget_s:
            return None

    t_in = _pad(t_enc, cap_t, t_fill)
    s_in = _pad(s_enc, cap_s, s_fill)

    state: dict = {}

    def launch():
        import jax

        try:
            with enable_x64():
                if p == 1:
                    kernel = _single_device_kernel_cached()
                    args = [jax.device_put(t_in), jax.device_put(s_in)]
                    state["out"] = kernel(*args)
                else:
                    from delta_tpu.parallel.mesh import STATE_AXIS

                    kernel = _sharded_kernel_cached(mesh, STATE_AXIS)
                    state["out"] = kernel(
                        t_in.reshape(p, -1), s_in.reshape(p, -1)
                    )
                jax.block_until_ready(state["out"])
        except BaseException as e:  # surface in .result(), not on the thread
            state["err"] = e

    # uploads drop the GIL: stage transfer + dispatch off-thread so callers
    # overlap the device leg with host-side decode
    th = threading.Thread(target=launch, daemon=True,
                          name="delta-join-upload")
    th.start()

    def finalize() -> JoinResult:
        th.join()
        if "err" in state:
            raise state["err"]
        t_bits, s_bits, multi = state["out"]
        t_matched = np.unpackbits(np.asarray(t_bits).reshape(-1))[:n].astype(bool)
        s_matched = np.unpackbits(np.asarray(s_bits).reshape(-1))[:m].astype(bool)
        any_multi = bool(multi)
        t_first_s = np.full(n, -1, np.int64)
        idx = np.flatnonzero(t_matched)
        if idx.size:
            t_first_s[idx] = _first_match_recovery(t_key64, idx, s_key64, s_ok)
        return JoinResult(t_first_s, s_matched, any_multi)

    return PendingJoin(finalize)


def inner_join(
    t_keys: np.ndarray,
    t_valid: np.ndarray,
    s_keys: np.ndarray,
    s_valid: np.ndarray,
    mesh=None,
) -> JoinResult:
    """Blocking wrapper: join int64 target keys against int64 source keys on
    device (see `inner_join_async`)."""
    pending = inner_join_async(t_keys, t_valid, s_keys, s_valid, mesh=mesh)
    assert pending is not None  # no budget → always launches
    return pending.result()
