"""Columnar expression evaluation over Arrow tables (host data plane).

The reference evaluates predicates/projections row-at-a-time inside Spark
executors (e.g. ``MergeIntoCommand.scala:702-752``, codegen'd invariant checks
``constraints/CheckDeltaInvariant.scala``). Here the host data plane is Arrow:
expressions compile to ``pyarrow.compute`` kernel calls (Arrow's C++ vectorized
kernels — the native-performance role the JVM plays in the reference), with a
row-at-a-time fallback through :meth:`Expression.eval` for the long tail of
semantics (permissive casts, functions Arrow lacks).

NULL semantics match Spark SQL: comparisons with NULL are NULL, AND/OR are
Kleene, a predicate filter keeps only rows that are exactly TRUE.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.expr import ir
from delta_tpu.schema.types import (
    ArrayType,
    BinaryType,
    BooleanType,
    ByteType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    MapType,
    LongType,
    ShortType,
    StringType,
    StructType,
    TimestampType,
)
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["evaluate", "filter_table", "boolean_mask", "project", "arrow_type_for"]


def arrow_type_for(dt: DataType) -> pa.DataType:
    """Map our schema types to Arrow types (Parquet physical layout)."""
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, arrow_type_for(f.data_type), f.nullable) for f in dt.fields])
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, ArrayType):
        return pa.list_(arrow_type_for(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(arrow_type_for(dt.key_type), arrow_type_for(dt.value_type))
    raise errors.arrow_mapping_missing(dt.simple_string())


def _resolve_column(table: pa.Table, name: str) -> pa.ChunkedArray:
    if name in table.column_names:
        return table.column(name)
    lowered = name.lower()
    for c in table.column_names:
        if c.lower() == lowered:
            return table.column(c)
    raise errors.column_not_found_in_table(name, table.column_names)


def _as_array(v: Any, n: int) -> pa.ChunkedArray:
    if isinstance(v, pa.ChunkedArray):
        return v
    if isinstance(v, pa.Array):
        return pa.chunked_array([v])
    if isinstance(v, pa.Scalar):
        if not v.is_valid:
            return pa.chunked_array([pa.nulls(n)])
        return pa.chunked_array([pa.array([v.as_py()] * n, type=v.type)])
    return pa.chunked_array([pa.array([v] * n)])


def _row_fallback(expr: ir.Expression, table: pa.Table, rows=None) -> pa.ChunkedArray:
    """Exact-semantics fallback: row-at-a-time eval over python dicts."""
    if rows is None:
        rows = table.to_pylist()
    return pa.chunked_array([pa.array([expr.eval(r) for r in rows])]) if rows else pa.chunked_array(
        [pa.nulls(0)]
    )


def _numeric_coerce(l: Any, r: Any):
    """Arrow's kernels refuse string-vs-number and string-vs-temporal;
    mimic Spark's implicit cast of the string side."""
    lt = getattr(l, "type", None)
    rt = getattr(r, "type", None)
    if lt is not None and rt is not None:
        if pa.types.is_string(lt) and (pa.types.is_integer(rt) or pa.types.is_floating(rt)):
            return pc.cast(l, pa.float64(), safe=False), pc.cast(r, pa.float64(), safe=False)
        if pa.types.is_string(rt) and (pa.types.is_integer(lt) or pa.types.is_floating(lt)):
            return pc.cast(l, pa.float64(), safe=False), pc.cast(r, pa.float64(), safe=False)
        # ISO string literals against date/timestamp columns
        if pa.types.is_string(lt) and (pa.types.is_date(rt) or pa.types.is_timestamp(rt)):
            return pc.cast(l, rt), r
        if pa.types.is_string(rt) and (pa.types.is_date(lt) or pa.types.is_timestamp(lt)):
            return l, pc.cast(r, lt)
    return l, r


class _Vectorizer:
    def __init__(self, table: pa.Table):
        self.table = table
        self.n = table.num_rows
        self._rows = None  # lazy to_pylist() cache for the fallback path

    def _fallback(self, e: ir.Expression):
        if self._rows is None:
            self._rows = self.table.to_pylist()
        return _row_fallback(e, self.table, self._rows)

    def visit(self, e: ir.Expression):
        m = getattr(self, "_v_" + type(e).__name__, None)
        if m is None:
            return self._fallback(e)
        try:
            return m(e)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError,
                UnicodeEncodeError):
            return self._fallback(e)

    # -- leaves -----------------------------------------------------------
    def _v_Column(self, e: ir.Column):
        return _resolve_column(self.table, e.name)

    def _v_Literal(self, e: ir.Literal):
        return pa.scalar(e.value)

    def _v_Alias(self, e: ir.Alias):
        return self.visit(e.child)

    # -- boolean ----------------------------------------------------------
    def _v_And(self, e: ir.And):
        return pc.and_kleene(*self._bool_pair(e))

    def _v_Or(self, e: ir.Or):
        return pc.or_kleene(*self._bool_pair(e))

    def _bool_pair(self, e):
        l = self.visit(e.left)
        r = self.visit(e.right)
        # and_kleene needs at least one array argument
        if isinstance(l, pa.Scalar) and isinstance(r, pa.Scalar):
            l = _as_array(l, self.n)
        return l, r

    def _v_Not(self, e: ir.Not):
        return pc.invert(self.visit(e.child))

    # -- comparisons ------------------------------------------------------
    def _cmp(self, e, fn):
        l, r = _numeric_coerce(self.visit(e.left), self.visit(e.right))
        return fn(l, r)

    def _v_Eq(self, e):
        return self._cmp(e, pc.equal)

    def _v_Ne(self, e):
        return self._cmp(e, pc.not_equal)

    def _v_Lt(self, e):
        return self._cmp(e, pc.less)

    def _v_Le(self, e):
        return self._cmp(e, pc.less_equal)

    def _v_Gt(self, e):
        return self._cmp(e, pc.greater)

    def _v_Ge(self, e):
        return self._cmp(e, pc.greater_equal)

    def _v_NullSafeEq(self, e):
        l = _as_array(self.visit(e.left), self.n)
        r = _as_array(self.visit(e.right), self.n)
        eq = pc.equal(l, r)
        both_null = pc.and_(pc.is_null(l), pc.is_null(r))
        return pc.if_else(pc.is_null(eq), both_null, eq)

    def _v_In(self, e: ir.In):
        v = _as_array(self.visit(e.value), self.n)
        opts = [o.value for o in e.options if isinstance(o, ir.Literal)]
        if len(opts) != len(e.options):
            return self._fallback(e)
        has_null_opt = any(o is None for o in opts)
        vals = [o for o in opts if o is not None]
        found = pc.is_in(v, value_set=pa.array(vals, type=v.type) if vals else pa.nulls(0, v.type))
        if has_null_opt:
            # SQL IN: not-found with a NULL option is NULL, not FALSE
            found = pc.if_else(found, pa.scalar(True), pa.scalar(None, pa.bool_()))
        return pc.if_else(pc.is_null(v), pa.scalar(None, pa.bool_()), found)

    def _v_IsNull(self, e: ir.IsNull):
        return pc.is_null(_as_array(self.visit(e.child), self.n))

    def _v_IsNotNull(self, e: ir.IsNotNull):
        return pc.is_valid(_as_array(self.visit(e.child), self.n))

    # -- arithmetic ------------------------------------------------------
    def _v_Add(self, e):
        return self._cmp(e, pc.add)

    def _v_Sub(self, e):
        return self._cmp(e, pc.subtract)

    def _v_Mul(self, e):
        return self._cmp(e, pc.multiply)

    def _v_Div(self, e):
        l = self.visit(e.left)
        r = _as_array(self.visit(e.right), self.n)
        # Spark (ansi off): x / 0 is NULL; arrow raises / returns inf
        r = pc.if_else(pc.equal(r, pa.scalar(0).cast(r.type)), pa.scalar(None, r.type), r)
        lt = l.type
        if pa.types.is_integer(lt) and pa.types.is_integer(r.type):
            return pc.divide(pc.cast(l, pa.float64()), pc.cast(r, pa.float64()))
        return pc.divide(l, r)

    def _v_Neg(self, e: ir.Neg):
        return pc.negate(self.visit(e.child))

    def _v_Cast(self, e: ir.Cast):
        child = self.visit(e.child)
        target = arrow_type_for(e.data_type)
        try:
            return pc.cast(child, target, safe=False)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
            return self._fallback(e)

    # -- strings ----------------------------------------------------------
    def _v_Like(self, e: ir.Like):
        if not isinstance(e.right, ir.Literal):
            return self._fallback(e)
        return pc.match_like(self.visit(e.left), e.right.value)

    def _v_StartsWith(self, e: ir.StartsWith):
        if not isinstance(e.right, ir.Literal):
            return self._fallback(e)
        return pc.starts_with(self.visit(e.left), pattern=e.right.value)

    def _v_Coalesce(self, e: ir.Coalesce):
        return pc.coalesce(*[_as_array(self.visit(c), self.n) for c in e.children])

    def _v_CaseWhen(self, e: ir.CaseWhen):
        result = _as_array(self.visit(e.children[-1]), self.n)
        for i in reversed(range(e.n_branches)):
            cond = _as_array(self.visit(e.children[2 * i]), self.n)
            val = _as_array(self.visit(e.children[2 * i + 1]), self.n)
            # CASE matches only when the condition is exactly TRUE
            cond = pc.fill_null(cond, False)
            result = pc.if_else(cond, val, result)
        return result

    _ARROW_FUNCS = {
        "abs": pc.abs,
        "length": pc.utf8_length,
        "lower": pc.utf8_lower,
        "upper": pc.utf8_upper,
        "trim": pc.utf8_trim_whitespace,
        "floor": pc.floor,
        "ceil": pc.ceil,
        "year": pc.year,
        "month": pc.month,
        "day": pc.day,
        "exp": pc.exp,
        "log": lambda x: _ln_null(x),
        "sqrt": lambda x: _sqrt_null(x),
        "pow": lambda x, y: _pow_f64(x, y),
        "power": lambda x, y: _pow_f64(x, y),
    }

    def _v_Func(self, e: ir.Func):
        # concat / round / substring need special argument handling; the
        # rest map 1:1 onto an Arrow kernel. Anything else (or non-literal
        # substring/round arguments) keeps the exact row-eval semantics.
        if e.name == "concat":
            args = [self.visit(a) for a in e.children]
            types = [getattr(a, "type", None) for a in args]
            # stringified-operand semantics match Arrow's cast only for
            # strings and integers (floats/bools render differently than
            # str()) — anything else keeps the exact row semantics
            if all(t is not None and (pa.types.is_string(t) or pa.types.is_integer(t))
                   for t in types):
                args = [
                    a if pa.types.is_string(a.type) else pc.cast(a, pa.string())
                    for a in args
                ]
                # any NULL argument → NULL (binary_join's default emit_null)
                return pc.binary_join_element_wise(*args, "")
            return self._fallback(e)
        if e.name == "hour":
            arg = self.visit(e.children[0])
            t = getattr(arg, "type", None)
            if t is not None and pa.types.is_timestamp(t):
                return pc.hour(arg)
            return self._fallback(e)  # int-µs inputs keep row semantics
        def _int_literals(args):
            return all(
                isinstance(a, ir.Literal) and isinstance(a.value, int)
                and not isinstance(a.value, bool)
                for a in args
            )

        if e.name == "round" and (
            len(e.children) == 1
            or (_int_literals(e.children[1:])
                and e.children[1].value == 0)
        ):
            # only ndigits=0 vectorizes: integer boundaries are binary-exact
            # so Arrow's half_to_even agrees with Python's round(); for
            # ndigits>0 Arrow rounds the binary-scaled value (round(2.675,2)
            # → 2.68) while Python is correctly rounded (→ 2.67) — keep the
            # exact row semantics there
            return pc.round(
                self.visit(e.children[0]), ndigits=0,
                round_mode="half_to_even",
            )
        if (e.name in ("substring", "substr") and _int_literals(e.children[1:])
                and int(e.children[1].value) >= 0):
            # positive positions only: negative-position window semantics
            # (prefix consumed before the string) keep the exact row path
            s = self.visit(e.children[0])
            pos = int(e.children[1].value)
            start = max(pos - 1, 0)
            if len(e.children) > 2:
                stop = start + max(int(e.children[2].value), 0)
                return pc.utf8_slice_codeunits(s, start=start, stop=stop)
            return pc.utf8_slice_codeunits(s, start=start)
        if e.name in ("minute", "second"):
            arg = self.visit(e.children[0])
            t = getattr(arg, "type", None)
            if t is not None and pa.types.is_timestamp(t):
                return (pc.minute if e.name == "minute" else pc.second)(arg)
            return self._fallback(e)  # int-µs inputs keep row semantics
        if e.name == "to_date":
            arg = self.visit(e.children[0])
            t = getattr(arg, "type", None)
            if t is None or not pa.types.is_string(t):
                return self._fallback(e)
            try:
                if len(e.children) == 1:
                    # row semantics parse the first 10 chars as ISO; Arrow's
                    # date32 cast accepts exactly that for ISO strings, but
                    # errors (not NULLs) bad input — fall back then
                    return pc.cast(
                        pc.utf8_slice_codeunits(arg, start=0, stop=10),
                        pa.date32(),
                    )
                if isinstance(e.children[1], ir.Literal):
                    fmt = ir.java_fmt_to_strftime(e.children[1].value)
                    ts = pc.strptime(arg, format=fmt, unit="s", error_is_null=True)
                    return pc.cast(ts, pa.date32())
            except Exception:
                return self._fallback(e)
            return self._fallback(e)
        if e.name in ("date_add", "date_sub"):
            d = self.visit(e.children[0])
            n = self.visit(e.children[1])
            t = getattr(d, "type", None)
            if t is None or not pa.types.is_date(t):
                return self._fallback(e)
            days = pc.cast(pc.cast(d, pa.date32()), pa.int32())
            n32 = pc.cast(_as_array(n, self.n), pa.int32())
            out = (pc.add if e.name == "date_add" else pc.subtract)(days, n32)
            return pc.cast(out, pa.date32())
        if e.name == "datediff":
            a = self.visit(e.children[0])
            b = self.visit(e.children[1])
            ta, tb = getattr(a, "type", None), getattr(b, "type", None)
            if (ta is None or tb is None or not pa.types.is_date(ta)
                    or not pa.types.is_date(tb)):
                return self._fallback(e)
            return pc.subtract(pc.cast(pc.cast(a, pa.date32()), pa.int32()),
                               pc.cast(pc.cast(b, pa.date32()), pa.int32()))
        if e.name in ("lpad", "rpad"):
            tail = e.children[1:]
            if not (isinstance(tail[0], ir.Literal)
                    and isinstance(tail[0].value, int)):
                return self._fallback(e)
            pad = " "
            if len(tail) > 1:
                if not (isinstance(tail[1], ir.Literal)
                        and isinstance(tail[1].value, str) and tail[1].value):
                    return self._fallback(e)
                pad = tail[1].value
            n = int(tail[0].value)
            if n <= 0 or len(pad) != 1:
                return self._fallback(e)  # multi-char pad: row semantics
            s = self.visit(e.children[0])
            t = getattr(s, "type", None)
            if t is None or not pa.types.is_string(t):
                return self._fallback(e)
            padded = (pc.utf8_lpad if e.name == "lpad" else pc.utf8_rpad)(
                s, width=n, padding=pad
            )
            # Spark truncates to the target width when the input is longer
            return pc.utf8_slice_codeunits(padded, start=0, stop=n)
        if e.name == "log" and len(e.children) == 2:
            base = pc.cast(_as_array(self.visit(e.children[0]), self.n), pa.float64())
            x = pc.cast(_as_array(self.visit(e.children[1]), self.n), pa.float64())
            ok = pc.and_(pc.and_(pc.greater(x, 0.0), pc.greater(base, 0.0)),
                         pc.not_equal(base, 1.0))
            return pc.if_else(pc.fill_null(ok, False), pc.logb(x, base),
                              pa.scalar(None, pa.float64()))
        fn = self._ARROW_FUNCS.get(e.name)
        if fn is None:
            return self._fallback(e)
        args = [self.visit(a) for a in e.children]
        return fn(*args)


# domain-guarded math: the row evaluator yields NULL outside the domain
# (Spark semantics); raw Arrow kernels would yield NaN/-inf — mask them
def _ln_null(x):
    xf = pc.cast(x, pa.float64())
    return pc.if_else(pc.fill_null(pc.greater(xf, 0.0), False),
                      pc.ln(xf), pa.scalar(None, pa.float64()))


def _sqrt_null(x):
    xf = pc.cast(x, pa.float64())
    return pc.if_else(pc.fill_null(pc.greater_equal(xf, 0.0), False),
                      pc.sqrt(xf), pa.scalar(None, pa.float64()))


def _pow_f64(x, y):
    return pc.power(pc.cast(x, pa.float64()), pc.cast(y, pa.float64()))


def evaluate(expr: ir.Expression, table: pa.Table) -> pa.ChunkedArray:
    """Evaluate ``expr`` over every row of ``table``; result aligned by row."""
    v = _Vectorizer(table)
    return _as_array(v.visit(expr), table.num_rows)


def filter_table(table: pa.Table, expr: Optional[ir.Expression]) -> pa.Table:
    """Keep rows where ``expr`` is exactly TRUE (NULL drops, like SQL WHERE)."""
    if expr is None or table.num_rows == 0:
        return table
    return table.filter(boolean_mask(expr, table))


def boolean_mask(expr: ir.Expression, table: pa.Table):
    """Evaluate a predicate to a null-free boolean array (NULL → False)."""
    return pc.fill_null(pc.cast(evaluate(expr, table), pa.bool_()), False)


def project(table: pa.Table, exprs: Dict[str, ir.Expression]) -> pa.Table:
    """SELECT exprs: build a new table with one column per (name, expression)."""
    cols: List[pa.ChunkedArray] = []
    names: List[str] = []
    for name, e in exprs.items():
        arr = evaluate(e, table)
        cols.append(arr)
        names.append(name)
    return pa.table(cols, names=names)
