"""Streaming restart matrices — the `DeltaSourceSuite` families round 4's
review flagged as thin: restart at every admission boundary, restart
across OPTIMIZE/rearrange commits, offset monotonicity under mixed
admission limits, sink/source composition under restart, and
startingVersion interactions with restarts."""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.streaming.query import StreamingQuery
from delta_tpu.streaming.sink import DeltaSink
from delta_tpu.streaming.source import DeltaSource


def write(log, ids, mode="append"):
    WriteIntoDelta(log, mode, pa.table({"id": pa.array(ids, pa.int64())})).run()


def drain(source, start=None, limit=100):
    out, cur = [], start
    for _ in range(limit):
        anchor = cur if cur is not None else source.initial_offset()
        end = source.latest_offset(anchor)
        if end is None:
            return out, cur
        t = source.get_batch(cur, end)
        if t.num_rows:
            out.append(sorted(t.column("id").to_pylist()))
        cur = end
    raise AssertionError("source did not drain")


# -- restart at every admission boundary ------------------------------------


@pytest.mark.parametrize("max_files", [1, 2, 3])
def test_restart_at_each_boundary_no_loss_no_dup(tmp_table, max_files):
    """Drive to each intermediate offset, then RESTART (fresh source, same
    offset JSON): the union of batches is exactly the data, no overlap."""
    log = DeltaLog.for_table(tmp_table)
    for i in range(5):
        write(log, [i * 10, i * 10 + 1])
    source = DeltaSource(log, max_files_per_trigger=max_files)
    seen = []
    cur = None
    while True:
        anchor = cur if cur is not None else source.initial_offset()
        end = source.latest_offset(anchor)
        if end is None:
            break
        t = source.get_batch(cur, end)
        seen.extend(t.column("id").to_pylist())
        # restart: serialize the offset, build a brand-new source
        from delta_tpu.streaming.offset import DeltaSourceOffset

        cur = DeltaSourceOffset.from_json(end.json())
        source = DeltaSource(log, max_files_per_trigger=max_files)
    assert sorted(seen) == sorted(
        v for i in range(5) for v in (i * 10, i * 10 + 1))


def test_restart_mid_initial_snapshot_with_concurrent_appends(tmp_table):
    """New commits land while the initial snapshot is still being admitted
    in slices; a restarted source must deliver snapshot + tail exactly."""
    log = DeltaLog.for_table(tmp_table)
    for i in range(4):
        write(log, [i])
    source = DeltaSource(log, max_files_per_trigger=2)
    cur = source.latest_offset(source.initial_offset())
    got = source.get_batch(None, cur).column("id").to_pylist()
    write(log, [100])  # lands mid-snapshot-serving
    source2 = DeltaSource(log, max_files_per_trigger=2)
    rest, _ = drain(source2, cur)
    flat = got + [v for b in rest for v in b]
    assert sorted(flat) == [0, 1, 2, 3, 100]


def test_restart_across_rearrange_only_commit(tmp_table):
    """An OPTIMIZE-shaped commit (dataChange=false) between restarts must
    not re-emit rows."""
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, [i])
    source = DeltaSource(log)
    batches, cur = drain(source)
    assert batches == [[0, 1, 2]]
    OptimizeCommand(log).run()  # compacts 3 files -> 1, dataChange=false
    source2 = DeltaSource(log)
    batches, cur = drain(source2, cur)
    assert batches == []
    write(log, [7])
    batches, _ = drain(source2, cur)
    assert batches == [[7]]


def test_offsets_monotonic_under_mixed_limits(tmp_table):
    """Alternating admission limits across restarts never move an offset
    backwards."""
    log = DeltaLog.for_table(tmp_table)
    for i in range(6):
        write(log, [i])
    cur = None
    keys = []
    for limit in (1, 3, 2, 1000):
        source = DeltaSource(log, max_files_per_trigger=limit)
        anchor = cur if cur is not None else source.initial_offset()
        end = source.latest_offset(anchor)
        if end is None:
            break
        keys.append((end.reservoir_version, end.index))
        cur = end
    assert keys == sorted(keys)


# -- query-level restart composition ----------------------------------------


def test_query_restart_after_each_batch(tmp_path):
    src_path, dst_path, wal = (str(tmp_path / n) for n in ("s", "d", "w"))
    log = DeltaLog.for_table(src_path)
    for i in range(4):
        write(log, [i])
    total = 0
    for _ in range(8):  # fresh query object each loop = restart
        q = StreamingQuery(
            DeltaSource(log, max_files_per_trigger=1),
            DeltaSink(DeltaLog.for_table(dst_path), query_id="q1"), wal,
        )
        n = q.process_all_available()
        total += n
        if n == 0:
            break
    from delta_tpu.exec.scan import scan_to_table

    out = scan_to_table(DeltaLog.for_table(dst_path).update())
    assert sorted(out.column("id").to_pylist()) == [0, 1, 2, 3]


def test_query_restart_with_new_data_between_runs(tmp_path):
    src_path, dst_path, wal = (str(tmp_path / n) for n in ("s", "d", "w"))
    log = DeltaLog.for_table(src_path)
    write(log, [1])
    q = StreamingQuery(DeltaSource(log),
                       DeltaSink(DeltaLog.for_table(dst_path), query_id="q2"),
                       wal)
    q.process_all_available()
    write(log, [2])
    write(log, [3])
    q2 = StreamingQuery(DeltaSource(log),
                        DeltaSink(DeltaLog.for_table(dst_path), query_id="q2"),
                        wal)
    q2.process_all_available()
    from delta_tpu.exec.scan import scan_to_table

    out = scan_to_table(DeltaLog.for_table(dst_path).update())
    assert sorted(out.column("id").to_pylist()) == [1, 2, 3]


def test_starting_version_with_restart_and_delete_handling(tmp_table):
    """startingVersion skips history; a delete AFTER the start version
    still fails the stream unless ignoreDeletes."""
    log = DeltaLog.for_table(tmp_table)
    write(log, [1])
    write(log, [2])
    v = log.update().version
    source = DeltaSource(log, starting_version=v + 1)
    batches, cur = drain(source)
    assert batches == []
    write(log, [3])
    batches, cur = drain(source, cur)
    assert batches == [[3]]
    DeleteCommand(log, "id = 3").run()
    from delta_tpu.utils.errors import DeltaError

    with pytest.raises(DeltaError):
        drain(DeltaSource(log, starting_version=v + 1), cur)
    # ignoreDeletes lets a restarted stream pass the delete commit
    batches, _ = drain(
        DeltaSource(log, starting_version=v + 1, ignore_deletes=True), cur)
    assert batches == []
