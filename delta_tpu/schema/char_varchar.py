"""Char/Varchar semantics — the analogue of `CharVarcharUtils.scala`.

The Delta wire format has no char/varchar types: the reference replaces
them with STRING and records the declared type in the StructField metadata
under ``__CHAR_VARCHAR_TYPE_STRING`` (`CharVarcharUtils.scala:35-60`), then
enforces lengths on the write path. This module does the same for the
engine-native schema machinery:

  - :func:`replace_char_varchar_with_string` — wire-form conversion at
    table creation / column addition;
  - :func:`raw_type` — recover the declared char/varchar type of a field;
  - :func:`apply_write_semantics` — the write-path step: space-pad char
    values to their declared length, then reject any value longer than
    the bound (character count, like the reference).
"""
from __future__ import annotations

from typing import List, Optional

import pyarrow as pa

from delta_tpu.schema.types import (
    CharType,
    DataType,
    StringType,
    StructField,
    StructType,
    VarcharType,
    parse_data_type,
)
from delta_tpu.utils import errors

__all__ = [
    "CHAR_VARCHAR_TYPE_STRING_METADATA_KEY",
    "replace_char_varchar_with_string",
    "raw_type",
    "apply_write_semantics",
]

# the reference's metadata key, byte-compatible (`CharVarcharUtils.scala:38`)
CHAR_VARCHAR_TYPE_STRING_METADATA_KEY = "__CHAR_VARCHAR_TYPE_STRING"


def replace_char_varchar_with_string(schema: StructType) -> StructType:
    """Top-level char/varchar fields become STRING + type-string metadata
    (nested struct/array/map chars are not supported, matching the subset
    the standalone engine writes)."""
    fields: List[StructField] = []
    for f in schema.fields:
        if isinstance(f.data_type, (CharType, VarcharType)):
            md = dict(f.metadata or {})
            md[CHAR_VARCHAR_TYPE_STRING_METADATA_KEY] = f.data_type.name
            fields.append(StructField(f.name, StringType(), f.nullable, md))
        else:
            fields.append(f)
    return StructType(fields)


def raw_type(field: StructField) -> DataType:
    """The field's DECLARED type: char/varchar recovered from metadata,
    otherwise the stored type."""
    ts = (field.metadata or {}).get(CHAR_VARCHAR_TYPE_STRING_METADATA_KEY)
    if ts:
        try:
            dt = parse_data_type(ts)
        except ValueError:
            return field.data_type
        if isinstance(dt, (CharType, VarcharType)):
            return dt
    return field.data_type


def _bounded_fields(schema: StructType):
    for f in schema.fields:
        dt = raw_type(f)
        if isinstance(dt, (CharType, VarcharType)):
            yield f, dt


def apply_write_semantics(table: pa.Table, metadata) -> pa.Table:
    """Write-path char/varchar step over a batch:

    - over-length values first shed TRAILING SPACES down to the bound
      (the reference's char/varcharTypeWriteSideCheck trims before
      erroring — right-padded fixed-width feed data must keep working);
    - any value still longer than n characters raises the reference's
      length-violation error;
    - char(n): values space-pad on the right to exactly n characters
      (`CharVarcharUtils` readSidePadding done write-side here — the data
      file then carries the padded form, so every reader agrees).
    """
    import pyarrow.compute as pc

    schema: StructType = metadata.schema
    for f, dt in _bounded_fields(schema):
        name = _find_col(table, f.name)
        if name is None:
            continue
        col = table.column(name)
        if not pa.types.is_string(col.type):
            continue
        lens = pc.utf8_length(col)
        over = pc.greater(lens, dt.length)
        if pc.any(over).as_py():
            # trailing spaces beyond the bound trim away before judgment —
            # but over-length values TRUNCATE to exactly the bound (the
            # reference's varcharTypeWriteSideCheck: 'ab   ' → varchar(4)
            # stores 'ab  ', 4 chars — never a full rtrim, which would
            # diverge stored lengths/equality from the reference format)
            trimmed = pc.utf8_rtrim(col, characters=" ")
            still_over = pc.and_(over, pc.greater(pc.utf8_length(trimmed),
                                                  dt.length))
            if pc.any(still_over).as_py():
                sample = pa.table({name: trimmed}).filter(
                    still_over).column(name)[0].as_py()
                raise errors.char_varchar_length_exceeded(
                    f.name, dt.name, dt.length, sample)
            col = pc.if_else(
                over, pc.utf8_slice_codeunits(col, 0, dt.length), col)
            table = table.set_column(
                table.column_names.index(name),
                pa.field(name, pa.string(), f.nullable), col)
        if isinstance(dt, CharType):
            padded = pc.utf8_rpad(col, width=dt.length, padding=" ")
            # nulls stay null (utf8_rpad preserves them)
            table = table.set_column(
                table.column_names.index(name),
                pa.field(name, pa.string(), f.nullable), padded)
    return table


def pad_char_literals(expr, metadata, target_qualifiers=None):
    """Read-side char padding (the reference's `ApplyCharTypePadding`):
    string literals compared against a char(n) column pad to width n, so
    `c = 'ab'` matches the stored 'ab   '. Applies to =, <, <=, >, >=, IN
    with a char column on either side; other shapes pass through.

    Only refs that RESOLVE to the target table pad (the reference pads
    resolved char-typed attributes, never by name coincidence):
    ``target_qualifiers=None`` means every qualifier names the target —
    right for single-table contexts (scan/UPDATE/DELETE filters). MERGE
    passes the set of qualifiers that resolve to the target (its target
    alias, lowercased) so a SOURCE column that merely shares a name with a
    target char column — ``s.status = 'x'`` — keeps its literal unpadded
    instead of silently matching nothing."""
    from delta_tpu.expr import ir

    schema: StructType = metadata.schema
    widths = {}
    for f in schema.fields:
        dt = raw_type(f)
        if isinstance(dt, CharType):
            widths[f.name.lower()] = dt.length

    if not widths:
        return expr

    def width_of(node) -> Optional[int]:
        if not isinstance(node, ir.Column):
            return None
        low = node.name.lower()
        qual, _, col = low.rpartition(".")
        if qual and target_qualifiers is not None \
                and qual not in target_qualifiers:
            return None  # qualified ref resolving elsewhere (merge source)
        return widths.get(col)

    def pad(lit, n: int):
        if isinstance(lit, ir.Literal) and isinstance(lit.value, str) \
                and len(lit.value) < n:
            return ir.Literal(lit.value.ljust(n))
        return lit

    def lit_len(node) -> int:
        if isinstance(node, ir.Literal) and isinstance(node.value, str):
            return len(node.value)
        return 0

    def rpad_col(node, width: int):
        """Pad the COLUMN side out to the literal's length — the reference
        pads both sides to the longest (`ApplyCharTypePadding`), so a
        literal LONGER than char(n) still compares against the stored
        padded form: char(3) c = 'ab  ' matches stored 'ab '."""
        if isinstance(node, ir.Column) and width_of(node):
            return ir.Func("rpad", (node, ir.Literal(width), ir.Literal(" ")))
        return node

    def rewrite(node):
        t = type(node)
        if t in (ir.Eq, ir.Lt, ir.Le, ir.Gt, ir.Ge):
            n = width_of(node.left) or width_of(node.right)
            if n:
                width = max(n, lit_len(node.left), lit_len(node.right))
                l, r = pad(node.left, width), pad(node.right, width)
                if width > n:
                    l, r = rpad_col(l, width), rpad_col(r, width)
                return t(l, r)
        if t is ir.In:
            n = width_of(node.value)
            if n:
                width = max([n] + [lit_len(o) for o in node.options])
                value = node.value
                if width > n:
                    value = rpad_col(value, width)
                return ir.In(value, tuple(pad(o, width) for o in node.options))
        return None

    return expr.transform(rewrite)


def _find_col(table: pa.Table, name: str) -> Optional[str]:
    for c in table.column_names:
        if c.lower() == name.lower():
            return c
    return None
