"""Analysis core: findings, sources, suppressions, baseline, pass driver.

The model mirrors what Delta's Scala compiler + scalastyle gave the
reference for free (see PARITY.md): a *finding* is a (rule, file, message)
triple anchored to a line; a finding is silenced either by an inline
waiver — ``# delta-lint: ignore[rule] -- justification`` — which is a
reviewed, greppable annotation at the site, or by the checked-in baseline
(``tools/analyze_baseline.json``) which holds accepted pre-existing debt
keyed WITHOUT line numbers so ordinary edits don't churn it.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Finding", "SourceFile", "AnalysisContext", "AnalysisPass",
    "AnalysisReport", "run_passes", "apply_suppressions", "load_baseline",
    "baseline_payload", "analyze_repo", "repo_root", "default_baseline_path",
]

#: package the engine analyzes by default, relative to the repo root
DEFAULT_PACKAGE = "delta_tpu"

BASELINE_VERSION = 1


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``baseline_key`` deliberately omits the line
    number: accepted debt survives unrelated edits above it, and a *new*
    instance of an identical (rule, file, message) triple is absorbed only
    up to the baselined count."""

    rule: str
    path: str  # repo-relative posix path, e.g. "delta_tpu/obs/journal.py"
    line: int
    message: str

    @property
    def baseline_key(self) -> str:
        return f"{self.rule}|{self.path}|{self.message}"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


_SUPPRESS_RE = re.compile(r"#\s*delta-lint:\s*ignore\[([^\]]*)\]")


class SourceFile:
    """One parsed source file plus its suppression map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=self.rel)
        self.lines = text.splitlines()
        #: line number -> frozenset of suppressed rule names ("*" = all)
        self.suppressions: Dict[int, frozenset] = self._parse_suppressions()

    def _parse_suppressions(self) -> Dict[int, frozenset]:
        out: Dict[int, frozenset] = {}
        pending: List[frozenset] = []
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                before = line[: m.start()].strip()
                if before:  # trailing comment: applies to THIS line
                    out[i] = out.get(i, frozenset()) | rules
                else:  # standalone comment line: applies to the next code line
                    pending.append(rules)
                continue
            stripped = line.strip()
            if pending and stripped and not stripped.startswith("#"):
                acc = frozenset().union(*pending)
                out[i] = out.get(i, frozenset()) | acc
                pending = []
        return out

    def is_suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)


class AnalysisContext:
    """The file set one analysis run sees. Built from a directory tree
    (normal runs) or from in-memory sources (the fixture suite)."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files: List[SourceFile] = sorted(files, key=lambda f: f.rel)
        self._by_rel = {f.rel: f for f in self.files}

    @classmethod
    def from_dir(cls, root: str, package: str = DEFAULT_PACKAGE
                 ) -> "AnalysisContext":
        files = []
        pkg_dir = os.path.join(root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, root)
                with open(path, encoding="utf-8") as f:
                    files.append(SourceFile(rel, f.read()))
        return cls(files)

    @classmethod
    def from_sources(cls, sources: Mapping[str, str]) -> "AnalysisContext":
        return cls([SourceFile(rel, text) for rel, text in sources.items()])

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel.replace(os.sep, "/"))

    def find_suffix(self, suffix: str) -> Optional[SourceFile]:
        """The unique file whose path ends with ``suffix`` (posix), if any."""
        suffix = suffix.replace(os.sep, "/")
        matches = [f for f in self.files if f.rel.endswith(suffix)]
        return matches[0] if len(matches) == 1 else None


class AnalysisPass:
    """Base class for a pass. ``rules`` names every rule the pass can emit —
    the CLI rule table and the suppression/baseline vocabulary."""

    name: str = ""
    description: str = ""
    rules: Tuple[str, ...] = ()

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def run_passes(ctx: AnalysisContext,
               passes: Iterable[AnalysisPass]) -> List[Finding]:
    """Raw findings from ``passes`` over ``ctx``, deterministically ordered.
    Suppressions and the baseline are NOT applied here."""
    out: List[Finding] = []
    for p in passes:
        out.extend(p.run(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def apply_suppressions(ctx: AnalysisContext, findings: Iterable[Finding]
                       ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed) per the inline waivers."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        sf = ctx.get(f.path)
        if sf is not None and sf.is_suppressed(f.line, f.rule):
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def load_baseline(path: str) -> Dict[str, int]:
    """The baseline as ``{baseline_key: accepted_count}``; {} when absent."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    findings = data.get("findings", {}) if isinstance(data, dict) else {}
    out: Dict[str, int] = {}
    if isinstance(findings, dict):
        for k, v in findings.items():
            try:
                out[str(k)] = max(int(v), 0)
            except (TypeError, ValueError):
                continue
    return out


def baseline_payload(findings: Iterable[Finding]) -> Dict[str, object]:
    """The JSON payload ``--update-baseline`` writes for ``findings``."""
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.baseline_key] = counts.get(f.baseline_key, 0) + 1
    return {
        "version": BASELINE_VERSION,
        "comment": "Accepted pre-existing findings; regenerate with "
                   "`python tools/analyze.py --update-baseline`.",
        "findings": {k: counts[k] for k in sorted(counts)},
    }


@dataclass
class AnalysisReport:
    """One full run: what's new, what the waivers/baseline absorbed."""

    findings: List[Finding]          # new (fail the run)
    suppressed: List[Finding]        # inline-waived
    baselined: List[Finding]         # absorbed by the baseline file
    stale_baseline: List[str]        # baseline keys nothing matched anymore
    files_analyzed: int
    passes_run: Tuple[str, ...]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict[str, object]:
        return {
            "clean": self.clean,
            "filesAnalyzed": self.files_analyzed,
            "passes": list(self.passes_run),
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "staleBaseline": list(self.stale_baseline),
        }


def _apply_baseline(findings: List[Finding], baseline: Dict[str, int]
                    ) -> Tuple[List[Finding], List[Finding], List[str]]:
    remaining = dict(baseline)
    new: List[Finding] = []
    absorbed: List[Finding] = []
    for f in findings:
        if remaining.get(f.baseline_key, 0) > 0:
            remaining[f.baseline_key] -= 1
            absorbed.append(f)
        else:
            new.append(f)
    # ANY leftover count is surplus: it would silently absorb a future new
    # identical violation, so the operator is told to regenerate
    stale = sorted(k for k, v in remaining.items() if v > 0)
    return new, absorbed, stale


def repo_root() -> str:
    """The repository root (two levels above this file's package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), "tools", "analyze_baseline.json")


def analyze_repo(root: Optional[str] = None,
                 passes: Optional[Iterable[AnalysisPass]] = None,
                 baseline_path: Optional[str] = None,
                 ctx: Optional[AnalysisContext] = None) -> AnalysisReport:
    """Run the engine end to end: collect sources, run passes, apply inline
    waivers then the baseline. ``baseline_path=''`` skips the baseline."""
    from delta_tpu.analysis.passes import all_passes

    root = root or repo_root()
    if ctx is None:
        ctx = AnalysisContext.from_dir(root)
    chosen = list(passes) if passes is not None else all_passes()
    raw = run_passes(ctx, chosen)
    kept, suppressed = apply_suppressions(ctx, raw)
    if baseline_path is None:
        baseline_path = default_baseline_path(root)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    new, absorbed, stale = _apply_baseline(kept, baseline)
    # a rule-filtered run must not call OTHER rules' accepted debt surplus —
    # only entries this run's passes could have matched are judged stale
    covered = {r for p in chosen for r in p.rules}
    stale = [k for k in stale if k.split("|", 1)[0] in covered]
    return AnalysisReport(
        findings=new, suppressed=suppressed, baselined=absorbed,
        stale_baseline=stale, files_analyzed=len(ctx.files),
        passes_run=tuple(p.name for p in chosen),
    )
