"""Static-analysis CLI — run the ``delta_tpu/analysis`` engine.

    python tools/analyze.py                  # all passes, human output
    python tools/analyze.py --json           # machine output (bench wiring)
    python tools/analyze.py --rule lock-guard
    python tools/analyze.py --update-baseline  # accept current findings
    python tools/analyze.py --list-passes    # rule table

Exit status: 0 clean (every finding waived inline or baselined), 1 when
any non-baselined finding remains, 2 on usage errors. The baseline lives
at ``tools/analyze_baseline.json``; inline waivers are
``# delta-lint: ignore[rule] -- justification`` at the finding site.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from delta_tpu.analysis import (all_passes, analyze_repo,  # noqa: E402
                                default_baseline_path, repo_root)
from delta_tpu.analysis.core import (AnalysisContext,  # noqa: E402
                                     apply_suppressions, baseline_payload,
                                     run_passes)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="repository root (default: auto-detected)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to passes emitting this rule "
                         "(repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/analyze_baseline"
                         ".json); pass an empty string to disable")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show all non-waived "
                         "findings)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current non-waived findings as the new "
                         "baseline and exit 0")
    ap.add_argument("--list-passes", action="store_true",
                    help="print the pass/rule table and exit")
    args = ap.parse_args(argv)

    passes = all_passes()
    if args.list_passes:
        for p in passes:
            print(f"{p.name}: {p.description}")
            for r in p.rules:
                print(f"  - {r}")
        return 0
    if args.rule:
        wanted = set(args.rule)
        passes = [p for p in passes if wanted & set(p.rules)]
        unknown = wanted - {r for p in all_passes() for r in p.rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    root = args.root or repo_root()
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = default_baseline_path(root)
    if args.no_baseline:
        baseline_path = ""

    if args.update_baseline:
        if args.rule:
            # a rule-filtered run would rewrite the baseline WITHOUT the
            # other rules' accepted debt — silently un-baselining them
            print("--update-baseline cannot be combined with --rule: the "
                  "baseline always covers every pass", file=sys.stderr)
            return 2
        ctx = AnalysisContext.from_dir(root)
        raw = run_passes(ctx, passes)
        kept, _suppressed = apply_suppressions(ctx, raw)
        target = baseline_path or default_baseline_path(root)
        payload = baseline_payload(kept)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"baseline updated: {target} "
              f"({len(kept)} accepted finding(s))")
        return 0

    report = analyze_repo(root=root, passes=passes,
                          baseline_path=baseline_path)
    # findings that rode the baseline but might be filtered by --rule are
    # already scoped: analyze_repo ran only the chosen passes
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=1, sort_keys=True))
    else:
        for f in report.findings:
            print(f.format())
        counts = report.counts()
        summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
        print(f"{len(report.findings)} finding(s)"
              + (f" ({summary})" if summary else "")
              + f"; {len(report.suppressed)} waived inline, "
              f"{len(report.baselined)} baselined, "
              f"{report.files_analyzed} files, "
              f"passes: {', '.join(report.passes_run)}")
        for key in report.stale_baseline:
            print(f"baseline surplus (accepted count exceeds current "
                  f"findings — regenerate with --update-baseline): {key}",
                  file=sys.stderr)
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
