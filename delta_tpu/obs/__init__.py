"""Operator-facing observability: the interpretive layer over
``utils/telemetry``.

* :mod:`delta_tpu.obs.doctor` — table-health report (severities + remedies)
* :mod:`delta_tpu.obs.scan_report` — per-query data-skipping reports
* :mod:`delta_tpu.obs.server` — ``/metrics`` ``/healthz`` ``/events``
  ``/trace`` ``/doctor`` HTTP endpoint (opt-in)
* :mod:`delta_tpu.obs.flight_recorder` — incident files on operation failure
* :mod:`delta_tpu.obs.journal` — persistent per-table workload journal
* :mod:`delta_tpu.obs.advisor` — longitudinal layout advisor over the journal
* :mod:`delta_tpu.obs.router_audit` — routed decisions priced vs measured
* :mod:`delta_tpu.obs.calibration` — EWMA re-fit of the link cost constants
* :mod:`delta_tpu.obs.hbm_ledger` — device-memory accounting + soft budget
* :mod:`delta_tpu.obs.actions` — the shared maintenance-action catalog
  (doctor remedies ≡ advisor remedies ≡ autopilot actions)
* :mod:`delta_tpu.obs.metric_names` — the single catalog of metric names
* :mod:`delta_tpu.obs.fleet` — process-wide table registry + ranked sweeps
* :mod:`delta_tpu.obs.timeseries` — scraped metric rings (windowed series)
* :mod:`delta_tpu.obs.slo` — SLO objectives with multi-window burn alerts
* :mod:`delta_tpu.obs.trace_store` — distributed-trace span spool +
  cross-process stitching and straggler analysis

Importing this package installs the (inert-until-configured) flight-recorder
failure hook; everything else is pull-by-call.
"""
from delta_tpu.obs import flight_recorder as _flight_recorder
from delta_tpu.obs.advisor import AdvisorReport, advise
from delta_tpu.obs.doctor import TableHealthReport, doctor
from delta_tpu.obs.fleet import fleet_advise, fleet_doctor
from delta_tpu.obs.scan_report import ScanReport, last_scan_report
from delta_tpu.obs.server import ObsServer, start_server, stop_server

_flight_recorder.install()

__all__ = [
    "doctor", "TableHealthReport", "ScanReport", "last_scan_report",
    "ObsServer", "start_server", "stop_server", "advise", "AdvisorReport",
    "fleet_doctor", "fleet_advise",
]
