"""Partition-predicate evaluation over AddFile.partitionValues.

The reference rewrites partition filters into ``partitionValues[col]`` map
lookups with casts (``DeltaLog.rewritePartitionFilters``,
``DeltaLog.scala:524-547``); here predicates are evaluated per-file against
the typed partition values. Null/cast behavior matches: empty-string or
missing values are NULL, cast failures are NULL, and a predicate evaluating
to NULL does **not** match the file (Spark filter semantics) — except for
conflict checking, where callers use :func:`matches_maybe` (NULL counts as a
possible match, the conservative direction).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression
from delta_tpu.protocol.actions import AddFile, Metadata
from delta_tpu.schema.types import DateType, StringType, StructType, TimestampType

__all__ = [
    "typed_partition_row",
    "eval_on_file",
    "matches",
    "matches_maybe",
    "filter_files",
    "is_partition_predicate",
    "split_partition_and_data_predicates",
]


def typed_partition_row(add: AddFile, partition_schema: StructType) -> Dict[str, Any]:
    """Partition values cast from their string form to the column types."""
    row: Dict[str, Any] = {}
    for f in partition_schema.fields:
        raw: Optional[str] = None
        for k, v in (add.partition_values or {}).items():
            if k.lower() == f.name.lower():
                raw = v
                break
        if raw is None or raw == "" or raw == "__HIVE_DEFAULT_PARTITION__":
            row[f.name] = None
        elif isinstance(f.data_type, StringType):
            row[f.name] = raw
        elif isinstance(f.data_type, (DateType, TimestampType)):
            # natural temporal objects, NOT the device epoch-int encoding —
            # these rows feed Arrow columns (date32/timestamp) and the row
            # evaluator, where '2024-05-01'-style literals coerce correctly
            from delta_tpu.utils.timeparse import iso_to_date, iso_to_naive_utc

            try:
                if isinstance(f.data_type, DateType):
                    row[f.name] = iso_to_date(raw)
                else:
                    row[f.name] = iso_to_naive_utc(raw)
            except ValueError:
                row[f.name] = None  # cast failure → NULL (Spark semantics)
        else:
            row[f.name] = ir.cast_value(raw, f.data_type)
    return row


def eval_on_file(expr: ir.Expression, add: AddFile, partition_schema: StructType):
    return expr.eval(typed_partition_row(add, partition_schema))


def matches(expr: ir.Expression, add: AddFile, partition_schema: StructType) -> bool:
    """Spark filter semantics: NULL → no match."""
    return eval_on_file(expr, add, partition_schema) is True


def matches_maybe(expr: ir.Expression, add: AddFile, partition_schema: StructType) -> bool:
    """Conservative: NULL → possible match (used by the conflict checker)."""
    return eval_on_file(expr, add, partition_schema) is not False


def filter_files(
    files: Iterable[AddFile],
    predicates: Sequence[ir.Expression],
    metadata: Metadata,
) -> List[AddFile]:
    """Files surviving the conjunction of partition predicates."""
    if not predicates:
        return list(files)
    pschema = metadata.partition_schema
    pred = ir.and_all(list(predicates))
    return [f for f in files if matches(pred, f, pschema)]


def is_partition_predicate(expr: ir.Expression, partition_columns: Sequence[str]) -> bool:
    """True iff every referenced column is a partition column
    (≈ ``DeltaTableUtils.isPredicatePartitionColumnsOnly``)."""
    pset = {c.lower() for c in partition_columns}
    # Reference-free predicates (e.g. TRUE) are partition predicates too.
    return all(r.lower() in pset for r in ir.references(expr))


def split_partition_and_data_predicates(
    expr_or_str, partition_columns: Sequence[str]
):
    """Split a predicate's conjuncts into (partition-only, needs-data)
    (≈ ``DeltaTableUtils.splitMetadataAndDataPredicates``)."""
    expr = parse_expression(expr_or_str) if isinstance(expr_or_str, str) else expr_or_str
    partition_preds: List[ir.Expression] = []
    data_preds: List[ir.Expression] = []
    for conj in ir.split_conjuncts(expr):
        if is_partition_predicate(conj, partition_columns):
            partition_preds.append(conj)
        else:
            data_preds.append(conj)
    return partition_preds, data_preds
