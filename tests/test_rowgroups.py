"""Row-group data skipping (exec/rowgroups): footer-stats pushdown, late
materialization, the footer cache, and the consumers wired through it.

The core property: for ANY predicate, a scan with the second pruning tier on
is result-identical to a full decode — across nulls, NaN floats, timestamp
ms-truncation round-up, IN/OR shapes, schema-evolved files missing the
predicate column, and files with deletion vectors (whose positions must stay
PHYSICAL under skipping, or DV DML would corrupt files).
"""
import datetime as dt
import math
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec import rowgroups
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


N = 4000
RG = 500  # rows per row group → 8 groups per single-file write


def _assert_same(a: pa.Table, b: pa.Table):
    """Row-set equality, NaN-aware (pa.Table.equals has NaN != NaN) and
    order-insensitive (sorted by id)."""
    assert a.column_names == b.column_names
    a, b = a.sort_by("id"), b.sort_by("id")
    assert a.num_rows == b.num_rows
    for name in a.column_names:
        va, vb = a.column(name).to_pylist(), b.column(name).to_pylist()
        for x, y in zip(va, vb):
            if isinstance(x, float) and isinstance(y, float) \
                    and math.isnan(x) and math.isnan(y):
                continue
            assert x == y, (name, x, y)


def _table(n=N):
    """Mixed-type table with nulls, NaN, sub-ms timestamps, strings."""
    rng = np.random.RandomState(7)
    ids = np.arange(n, dtype=np.int64)
    f = rng.randn(n)
    f[rng.rand(n) < 0.05] = np.nan
    base = dt.datetime(2021, 1, 1)
    return pa.table({
        "id": ids,
        "v": pa.array([None if i % 17 == 0 else int(i % 100) for i in range(n)],
                      pa.int64()),
        "f": pa.array(f, pa.float64()),
        "name": pa.array(["k%04d" % (i % 500) for i in range(n)]),
        # microsecond tails exercise the ms-truncation round-up path
        "ts": pa.array([base + dt.timedelta(seconds=int(i), microseconds=i % 1000)
                        for i in range(n)], pa.timestamp("us")),
    })


@pytest.fixture
def rg_conf():
    with conf.set_temporarily(**{"delta.tpu.write.rowGroupRows": RG}):
        yield


@pytest.fixture
def rg_table(tmp_table, rg_conf):
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", _table()).run()
    return tmp_table


PREDICATES = [
    "id < 200",                                   # leading-group range
    "id >= 3700",                                 # trailing-group range
    "id >= 900 AND id < 1100",                    # straddles a boundary
    "id = 1234",                                  # point
    "id IN (10, 2500, 3999)",                     # IN across groups
    "id < 100 OR id >= 3900",                     # OR of two windows
    "v IS NULL AND id < 600",                     # null test + range
    "v IS NOT NULL AND id < 600",
    "f > 2.5",                                    # NaN-carrying float
    "name = 'k0007'",                             # string equality
    "name >= 'k0490'",                            # string range
    "ts < '2021-01-01 00:05:00'",                 # timestamp bound
    "ts >= '2021-01-01 00:55:00.000500'",         # sub-ms boundary
    "id < 0",                                     # empty result
    "id % 7 = 3 AND id < 900",                    # non-lowerable conjunct
]


@pytest.mark.parametrize("pred", PREDICATES)
def test_skipping_result_identical(rg_table, pred):
    t = DeltaTable.for_path(rg_table)
    with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
        full = t.to_arrow(filters=[pred])
    skipped = t.to_arrow(filters=[pred])
    _assert_same(skipped, full)


def test_selective_scan_prunes_and_counts(rg_table):
    telemetry.clear_counters()
    t = DeltaTable.for_path(rg_table)
    out = t.to_arrow(filters=["id < 200"])
    assert out.num_rows == 200
    c = telemetry.counters()
    assert c.get("scan.rowgroups.total", 0) == N // RG
    assert c.get("scan.rowgroups.pruned", 0) == N // RG - 1
    assert c.get("scan.bytes.skipped", 0) > 0


def test_skipping_off_decodes_everything(rg_table):
    telemetry.clear_counters()
    t = DeltaTable.for_path(rg_table)
    with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
        t.to_arrow(filters=["id < 200"])
    c = telemetry.counters()
    assert "scan.rowgroups.total" not in c
    assert "scan.rowgroups.pruned" not in c
    assert "footerCache.misses" not in c  # footers aren't even consulted


def test_late_materialization_skips_mask_empty_groups(rg_table):
    """A predicate footer stats can't lower still skips groups once the
    predicate columns are decoded and the mask comes back empty."""
    telemetry.clear_counters()
    t = DeltaTable.for_path(rg_table)
    out = t.to_arrow(filters=["id % 7919 = 600"])  # v%prime: never true > 600
    c = telemetry.counters()
    assert c.get("scan.rowgroups.pruned", 0) == 0  # stats keep everything
    assert c.get("scan.rowgroups.lateSkipped", 0) == N // RG - 1
    with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
        full = t.to_arrow(filters=["id % 7919 = 600"])
    _assert_same(out, full)


# -- deletion vectors: positions must stay physical ------------------------


@pytest.fixture
def dv_table(tmp_table, rg_conf):
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(
        log, "append", _table(),
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    ).run()
    return tmp_table


def test_dv_delete_with_pruning_keeps_physical_positions(dv_table):
    log = DeltaLog.for_table(dv_table)
    # two DV deletes against the SAME file: the second extends the DV using
    # positions read from a row-group-pruned decode — any logical/physical
    # confusion deletes the wrong rows
    DeleteCommand(log, "id >= 3900").run()
    DeleteCommand(log, "id < 50").run()
    t = DeltaTable.for_path(dv_table)
    out = t.to_arrow(columns=["id"])
    ids = sorted(out.column("id").to_pylist())
    assert ids == list(range(50, 3900))
    # and the survivors read back identically without skipping
    with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
        full = t.to_arrow(columns=["id"])
    assert sorted(full.column("id").to_pylist()) == ids


def test_dv_update_with_pruning(dv_table):
    log = DeltaLog.for_table(dv_table)
    UpdateCommand(log, {"name": "'touched'"}, "id >= 3800 AND v = 10").run()
    t = DeltaTable.for_path(dv_table)
    out = t.to_arrow()
    touched = out.filter(pa.compute.equal(out.column("name"), "touched"))
    expected = [i for i in range(3800, N) if i % 17 != 0 and i % 100 == 10]
    assert sorted(touched.column("id").to_pylist()) == expected
    assert out.num_rows == N  # update never loses rows


def test_scan_of_dv_file_with_pruning(dv_table):
    log = DeltaLog.for_table(dv_table)
    DeleteCommand(log, "id >= 100 AND id < 150").run()
    t = DeltaTable.for_path(dv_table)
    out = t.to_arrow(filters=["id < 300"])
    assert sorted(out.column("id").to_pylist()) == (
        list(range(100)) + list(range(150, 300))
    )


def test_dv_merge_with_pruning(dv_table):
    """DV-mode MERGE prunes candidate row groups by the target-only
    conjuncts of the condition — matched rows still mark the right
    PHYSICAL positions, unmatched rows stay live in place."""
    t = DeltaTable.for_path(dv_table)
    src = pa.table({
        "sid": pa.array([3950, 3999, 123456], pa.int64()),
        "sname": pa.array(["a", "b", "c"]),
    })
    telemetry.clear_counters()
    (t.merge(src, "id = sid AND id >= 3900")
     .when_matched_update({"name": "sname"})
     .when_not_matched_insert({
         "id": "sid", "v": "0", "f": "0.0", "name": "sname"}).execute())
    assert telemetry.counters().get("scan.rowgroups.pruned", 0) > 0
    out = t.to_arrow()
    assert out.num_rows == N + 1  # one insert, nothing lost
    by_id = dict(zip(out.column("id").to_pylist(),
                     out.column("name").to_pylist()))
    assert by_id[3950] == "a" and by_id[3999] == "b"
    assert by_id[123456] == "c"
    assert by_id[100] == "k0100"  # untouched row intact


def test_insert_only_merge_with_pruning(rg_table):
    t = DeltaTable.for_path(rg_table)
    src = pa.table({
        "sid": pa.array([500, 999999], pa.int64()),
        "sname": pa.array(["dup", "new"]),
    })
    telemetry.clear_counters()
    (t.merge(src, "id = sid AND id < 1000")
     .when_not_matched_insert({
         "id": "sid", "v": "1", "f": "1.0", "name": "sname"}).execute())
    # candidate files' groups outside id < 1000 never decode
    assert telemetry.counters().get("scan.rowgroups.pruned", 0) > 0
    out = t.to_arrow()
    assert out.num_rows == N + 1  # id=500 matched (no insert), 999999 new
    assert 999999 in out.column("id").to_pylist()


# -- schema evolution: missing predicate column keeps every group ----------


def test_evolved_file_missing_predicate_column(tmp_table, rg_conf):
    log = DeltaLog.for_table(tmp_table)
    old = pa.table({"id": pa.array(range(2000), pa.int64())})
    WriteIntoDelta(log, "append", old).run()
    new = pa.table({
        "id": pa.array(range(2000, 4000), pa.int64()),
        "extra": pa.array(range(2000), pa.int64()),
    })
    WriteIntoDelta(log, "append", new, merge_schema=True).run()
    t = DeltaTable.for_path(tmp_table)
    for pred in ["extra < 100", "extra < 100 OR id < 10", "extra IS NULL"]:
        with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
            full = t.to_arrow(filters=[pred])
        out = t.to_arrow(filters=[pred])
        _assert_same(out, full)


def test_predicate_column_outside_projection(rg_table):
    """A predicate column stored in the file but excluded from the decode
    projection must not late-skip matching groups (it would mask as
    all-null): late materialization disables itself and the result stays
    identical to a full decode."""
    from delta_tpu.exec.scan import read_files_as_table

    log = DeltaLog.for_table(rg_table)
    snap = log.update()
    out = read_files_as_table(
        log.data_path, snap.all_files, snap.metadata,
        columns=["id", "name"],
        predicate=parse_predicate("id >= 0 AND v = 50"),
    )
    # rows are NOT filtered by the decode — every row of surviving groups
    # comes back; with the guard, no group late-skips on the null mask
    assert out.num_rows == N
    with pytest.raises(ValueError):
        read_files_as_table(
            log.data_path, snap.all_files, snap.metadata,
            positions_of_interest=[np.array([0])] * (len(snap.all_files) + 1),
        )


# -- planner unit behavior -------------------------------------------------


def _write_rg_file(path, table, rg_rows):
    pq.write_table(table, path, row_group_size=rg_rows)
    return pq.read_metadata(path)


def test_planner_conservative_on_nan_bounds(tmp_path):
    # craft a file whose float bounds are NaN (legacy-writer shape is
    # simulated by an all-NaN group: Arrow then omits bounds → keep)
    p = str(tmp_path / "nan.parquet")
    t = pa.table({"f": pa.array([np.nan] * 10 + [5.0] * 10, pa.float64())})
    meta = _write_rg_file(p, t, 10)
    plan = rowgroups.plan_row_groups(meta, parse_predicate("f > 100.0"))
    # group 0 (all NaN, no bounds) must survive; group 1 (max=5) prunes
    assert 0 in plan.keep and 1 not in plan.keep


def test_planner_null_count_short_circuit(tmp_path):
    p = str(tmp_path / "nulls.parquet")
    t = pa.table({"v": pa.array([None] * 10 + list(range(10)), pa.int64())})
    meta = _write_rg_file(p, t, 10)
    plan = rowgroups.plan_row_groups(meta, parse_predicate("v IS NULL"))
    assert plan.keep == [0]  # group 1 has nullCount == 0
    plan = rowgroups.plan_row_groups(meta, parse_predicate("v IS NOT NULL"))
    assert plan.keep == [1]  # group 0 is all null


def test_planner_timestamp_bounds(tmp_path):
    p = str(tmp_path / "ts.parquet")
    base = dt.datetime(2021, 6, 1)
    t = pa.table({"ts": pa.array(
        [base + dt.timedelta(minutes=i) for i in range(20)], pa.timestamp("us")
    )})
    meta = _write_rg_file(p, t, 10)
    plan = rowgroups.plan_row_groups(
        meta, parse_predicate("ts >= '2021-06-01 00:15:00'"))
    assert plan.keep == [1]


def test_row_groups_for_positions(tmp_path):
    p = str(tmp_path / "pos.parquet")
    t = pa.table({"v": pa.array(range(40), pa.int64())})
    meta = _write_rg_file(p, t, 10)
    assert rowgroups.row_groups_for_positions(meta, [0, 35]) == {0, 3}
    assert rowgroups.row_groups_for_positions(meta, [11, 12]) == {1}
    assert rowgroups.row_groups_for_positions(meta, []) == frozenset()
    off = rowgroups.row_group_offsets(meta)
    assert list(off) == [0, 10, 20, 30, 40]


# -- footer cache ----------------------------------------------------------


def test_footer_cache_invalidation_on_rewrite(tmp_path):
    cache = rowgroups.FooterCache()
    p = str(tmp_path / "c.parquet")
    pq.write_table(pa.table({"v": pa.array(range(100), pa.int64())}), p)
    m1 = cache.get(p)
    assert cache.get(p) is m1  # hit: same parsed object
    # rewrite in place with different content (and force a distinct mtime)
    pq.write_table(pa.table({"v": pa.array(range(7), pa.int64())}), p)
    os.utime(p, ns=(1, 1))
    m2 = cache.get(p)
    assert m2 is not m1
    assert m2.num_rows == 7


def test_footer_cache_bounded_and_disabled(tmp_path):
    cache = rowgroups.FooterCache()
    paths = []
    for i in range(5):
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(pa.table({"v": pa.array([i], pa.int64())}), p)
        paths.append(p)
    with conf.set_temporarily(**{"delta.tpu.read.footerCacheEntries": 3}):
        for p in paths:
            cache.get(p)
        assert len(cache) == 3  # LRU bounded
    with conf.set_temporarily(**{"delta.tpu.read.footerCacheEntries": 0}):
        before = len(cache)
        m = cache.get(paths[0])
        assert m.num_rows == 1 and len(cache) == before  # nothing cached


# -- CONVERT footer-derived stats ------------------------------------------


def test_stats_from_footer_matches_decode(tmp_path):
    from delta_tpu.exec.parquet import collect_stats

    p = str(tmp_path / "s.parquet")
    t = _table(1000)
    meta = _write_rg_file(p, t, 300)
    footer = rowgroups.stats_from_footer(meta)
    assert footer is not None
    decoded = collect_stats(pq.read_table(p))
    assert footer["numRecords"] == decoded["numRecords"]
    assert footer["nullCount"] == decoded["nullCount"]
    # every decode-derived bound matches the footer-derived one, including
    # the timestamp max rounded UP to the next millisecond
    assert footer["minValues"] == decoded["minValues"]
    assert footer["maxValues"] == decoded["maxValues"]


def test_stats_from_footer_declines_statless_files(tmp_path):
    p = str(tmp_path / "ns.parquet")
    pq.write_table(pa.table({"v": pa.array(range(10), pa.int64())}), p,
                   write_statistics=False)
    assert rowgroups.stats_from_footer(pq.read_metadata(p)) is None


def test_convert_uses_footer_stats(tmp_path):
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    d = str(tmp_path / "conv")
    os.makedirs(d)
    pq.write_table(_table(1000), os.path.join(d, "part-0.parquet"),
                   row_group_size=300)
    telemetry.clear_counters()
    log = DeltaLog.for_table(d)
    ConvertToDeltaCommand(log, collect_stats=True).run()
    c = telemetry.counters()
    assert c.get("convert.stats.fromFooter", 0) == 1
    assert c.get("convert.stats.fromDecode", 0) == 0
    snap = log.update()
    [add] = snap.all_files
    st = add.stats_dict()
    assert st["numRecords"] == 1000
    assert st["minValues"]["id"] == 0 and st["maxValues"]["id"] == 999


# -- CDF + streaming consumers ---------------------------------------------


def test_cdf_dv_diff_reads_targeted_row_groups(tmp_table, rg_conf):
    from delta_tpu.exec.cdf import read_changes

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(
        log, "append", _table(),
        configuration={"delta.tpu.enableDeletionVectors": "true"},
    ).run()
    v = DeleteCommand(log, "id >= 3990").run()
    telemetry.clear_counters()
    changes = read_changes(log, v, v)
    deletes = changes.filter(
        pa.compute.equal(changes.column("_change_type"), "delete"))
    assert sorted(deletes.column("id").to_pylist()) == list(range(3990, N))
    c = telemetry.counters()
    # only the final row group (holding positions 3990+) decodes
    assert c.get("scan.rowgroups.pruned", 0) == N // RG - 1


def test_streaming_source_filters(tmp_table, rg_conf):
    from delta_tpu.streaming.source import DeltaSource

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", _table()).run()
    src = DeltaSource(log, filters=["id < 120"])
    end = src.latest_offset(src.initial_offset())
    batch = src.get_batch(None, end)
    assert sorted(batch.column("id").to_pylist()) == list(range(120))
    # unfiltered source unchanged
    src2 = DeltaSource(log)
    batch2 = src2.get_batch(None, src2.latest_offset(src2.initial_offset()))
    assert batch2.num_rows == N


# -- char(n) long-literal padding (satellite) ------------------------------


def test_char_long_literal_matches_stored_padded(tmp_table):
    from delta_tpu.schema.types import CharType, LongType, StructType

    schema = StructType().add("id", LongType()).add("c", CharType(3))
    t = DeltaTable.create(tmp_table, schema)
    data = pa.table({"id": pa.array([1, 2], pa.int64()),
                     "c": pa.array(["ab", "xyz"])})
    WriteIntoDelta(t.delta_log, "append", data).run()
    # stored form is 'ab ' (padded to 3); a 4-char literal with trailing
    # spaces must still match it (reference pads the column side up)
    out = t.to_arrow(filters=["c = 'ab  '"])
    assert out.column("id").to_pylist() == [1]
    out = t.to_arrow(filters=["c IN ('ab   ', 'zz')"])
    assert out.column("id").to_pylist() == [1]
    # over-length literal with non-space tail can never match
    out = t.to_arrow(filters=["c = 'abcd'"])
    assert out.num_rows == 0
    # short literals keep padding up (regression for the original path)
    out = t.to_arrow(filters=["c = 'ab'"])
    assert out.column("id").to_pylist() == [1]


# -- partitioned tables: mixed OR branches bind partition values -----------


def test_partitioned_mixed_or_predicate(tmp_table, rg_conf):
    log = DeltaLog.for_table(tmp_table)
    data = pa.table({
        "id": pa.array(range(2000), pa.int64()),
        "p": pa.array(["a" if i < 1000 else "b" for i in range(2000)]),
    })
    WriteIntoDelta(log, "append", data, partition_columns=["p"]).run()
    t = DeltaTable.for_path(tmp_table)
    pred = "p = 'a' OR id >= 1900"
    with conf.set_temporarily(**{"delta.tpu.read.rowGroupSkipping": False}):
        full = t.to_arrow(filters=[pred])
    out = t.to_arrow(filters=[pred])
    _assert_same(out, full)
    assert sorted(out.column("id").to_pylist()) == (
        list(range(1000)) + list(range(1900, 2000))
    )
