"""Engine-wide static analysis — concurrency & invariant lints as a subsystem.

PRs 6-9 made the engine deeply concurrent: the group-commit leader, the
coalescing checkpoint daemon (``delta-ckpt-async``), the journal writer
(``delta-journal-writer``), the MERGE slab uploader and the device-probe
staging thread all share state with foreground commits — and PR 9's worst
bugs (blocking tail reads under the commit lock, stranded drained members
on BaseException) were found only by hand-profiling. This package makes
that checking structural: an AST engine over the whole ``delta_tpu``
package with pluggable passes, a shared finding/suppression model and a
checked-in baseline, run as one tier-1 test and by ``tools/analyze.py``.

Passes (see ``delta_tpu/analysis/passes/``):

================  ===========================================================
``lock-discipline``  per-class/module lock→state map from ``with <lock>:``
                     regions; cross-thread unguarded mutation, blocking calls
                     (LogStore IO, ``time.sleep``, ``Thread.join``,
                     ``Future.result``) inside held-lock regions, and
                     lock-acquisition-order cycles
``crash-safety``     ``except Exception`` handlers on paths reachable from
                     named fault points (``SimulatedCrash`` must pierce),
                     swallowed ``BaseException``/bare ``except``, tmp-file
                     writes without try/finally cleanup (the PR 5 orphan
                     class)
``config-registry``  every constant ``delta.tpu.*`` conf read must resolve to
                     the ``utils/config.py`` registry (typo'd keys silently
                     return defaults otherwise); registered keys never read
                     are dead
``pool-naming``      every ``ThreadPoolExecutor``/``Thread`` construction
                     carries a registered ``delta-*`` pool name so Perfetto
                     lanes and ``adopt_span_context`` propagation stay total
``telemetry-spans``  every command entry point opens a ``delta.dml.*``/
                     ``delta.utility.*`` span (migrated from
                     ``tests/test_telemetry.py``)
``metric-catalog``   every constant-name metric call site resolves to
                     ``obs/metric_names.py`` (migrated)
``metric-descriptions``  every cataloged metric carries a one-line # HELP
                     description, none stale (migrated)
================  ===========================================================

Suppression: ``# delta-lint: ignore[rule]`` on the flagged line (or a
standalone comment line directly above it), with an optional justification
after ``--``. Repo-wide accepted debt lives in ``tools/analyze_baseline.json``
(``tools/analyze.py --update-baseline``). Pure stdlib — no runtime imports
of the engine modules it inspects.
"""
from __future__ import annotations

from delta_tpu.analysis.core import (AnalysisContext, AnalysisPass,
                                     AnalysisReport, Finding, analyze_repo,
                                     apply_suppressions, default_baseline_path,
                                     load_baseline, repo_root, run_passes)
from delta_tpu.analysis.passes import all_passes

__all__ = [
    "AnalysisContext", "AnalysisPass", "AnalysisReport", "Finding",
    "all_passes", "analyze_repo", "apply_suppressions",
    "default_baseline_path", "load_baseline", "publish_metrics",
    "repo_root", "run_passes",
]


def publish_metrics(report: AnalysisReport) -> None:
    """Publish per-rule finding counts as the cataloged ``analysis.findings``
    gauge (label: rule) so bench snapshots carry them via the include list
    and ``tools/bench_diff`` gates on finding-count regressions."""
    from delta_tpu.utils import telemetry

    counts = report.counts()
    telemetry.set_gauge("analysis.findings", sum(counts.values()),
                        rule="total")
    for rule, n in sorted(counts.items()):
        telemetry.set_gauge("analysis.findings", n, rule=rule)
