"""Name-based table catalog.

The reference resolves table *names* through Spark's DSv2 catalog plugin
(`catalog/DeltaCatalog.scala:57`, `DeltaTableV2.scala:50`), backed by a
metastore. This engine has no metastore; the equivalent is a small
name→path registry with optional JSON-file persistence, giving the API
surface (`DeltaTable.for_name`, CREATE/DROP by name) without path-typing
every call site.

Identifiers are case-insensitive, optionally qualified (``db.table``; the
default database is ``default``). ``delta.`/abs/path``` identifiers resolve
directly to paths, mirroring the reference's path-table escape hatch
(`DeltaTableIdentifier.scala`).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence

from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import DeltaAnalysisError

__all__ = ["Catalog", "default_catalog", "resolve_identifier"]


def _normalize(name: str) -> str:
    parts = [p.strip().strip("`") for p in name.split(".")]
    if len(parts) == 1:
        parts = ["default"] + parts
    if len(parts) != 2 or not all(parts):
        raise DeltaAnalysisError(f"Invalid table identifier: {name!r}")
    return ".".join(p.lower() for p in parts)


class Catalog:
    """name → path registry; optionally persisted as a JSON file so
    multiple processes share one namespace."""

    def __init__(self, store_path: Optional[str] = None):
        self._store_path = store_path
        self._tables: Dict[str, str] = {}
        self._lock = threading.RLock()
        if store_path and os.path.exists(store_path):
            self._load()

    # -- persistence ------------------------------------------------------
    #
    # Cross-process safety: every load-mutate-save cycle holds an OS file
    # lock (flock on <store>.lock) in addition to the in-process RLock, so
    # two processes registering tables concurrently cannot lose a write
    # (the in-process lock alone only orders threads).

    def _file_lock(self):
        import contextlib

        if not self._store_path:
            return contextlib.nullcontext()

        import fcntl

        @contextlib.contextmanager
        def locked():
            os.makedirs(os.path.dirname(self._store_path) or ".", exist_ok=True)
            with open(self._store_path + ".lock", "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)

        return locked()

    def _load(self) -> None:
        try:
            with open(self._store_path) as f:
                data = json.load(f)
            self._tables = dict(data.get("tables", {}))
        except (OSError, json.JSONDecodeError):
            self._tables = {}

    def _save(self) -> None:
        if not self._store_path:
            return
        os.makedirs(os.path.dirname(self._store_path) or ".", exist_ok=True)
        tmp = self._store_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"tables": self._tables}, f, indent=1, sort_keys=True)
        os.replace(tmp, self._store_path)

    # -- registry ---------------------------------------------------------

    def register(self, name: str, path: str) -> None:
        """Point ``name`` at an existing table location (external table)."""
        key = _normalize(name)
        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            if key in self._tables:
                raise DeltaAnalysisError(f"Table {name!r} already exists in catalog")
            self._tables[key] = os.path.abspath(path)
            self._save()

    def create_table(self, name: str, path: str, schema=None,
                     partition_columns: Sequence[str] = (),
                     configuration=None, data=None, mode: str = "create"):
        """CREATE TABLE by name: registers the identifier and runs the
        create command at ``path`` (`DeltaCatalog.createTable :183`)."""
        from delta_tpu.api.tables import DeltaTable

        key = _normalize(name)
        abs_path = os.path.abspath(path)
        # Claim the name inside the first critical section, then run the
        # (possibly long) CTAS/create outside the lock so unrelated catalog
        # operations aren't serialized behind data writes. A concurrent
        # creator of the same name now fails BEFORE materializing any data
        # (no orphan table directory); if our create fails, roll the claim
        # back so the name isn't left dangling.
        from delta_tpu.api.tables import DeltaTable as _DT

        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            prior = self._tables.get(key)
            if prior is not None and mode == "create":
                # a claim whose creator crashed mid-create (no table behind
                # the registered path) is stale — reclaimable, not an error
                if _DT.is_delta_table(prior):
                    raise DeltaAnalysisError(
                        f"Table {name!r} already exists in catalog"
                    )
                prior = None
            claimed = prior is None
            if claimed:
                # claim an unregistered name now, so a losing concurrent
                # creator fails before materializing data; until the create
                # commits, readers of this name see a claim, not a table. A
                # replace of an EXISTING registration keeps pointing at the
                # old location until the create succeeds.
                self._tables[key] = abs_path
                self._save()
        try:
            table = DeltaTable.create(
                path, schema, partition_columns, configuration, data, mode=mode
            )
        except BaseException:
            if claimed:
                with self._lock, self._file_lock():
                    if self._store_path:
                        self._load()
                    if self._tables.get(key) == abs_path:
                        self._tables.pop(key, None)
                        self._save()
            raise
        if not claimed:
            with self._lock, self._file_lock():
                if self._store_path:
                    self._load()
                self._tables[key] = abs_path
                self._save()
        return table

    def drop_table(self, name: str) -> None:
        """Remove the name mapping (the data/log stay on disk, like dropping
        an external table)."""
        key = _normalize(name)
        with self._lock, self._file_lock():
            if self._store_path:
                self._load()
            if key not in self._tables:
                raise DeltaAnalysisError(f"Table {name!r} not found in catalog")
            del self._tables[key]
            self._save()

    def table_path(self, name: str) -> str:
        key = _normalize(name)
        with self._lock:
            if self._store_path:
                self._load()
            path = self._tables.get(key)
        if path is None:
            raise DeltaAnalysisError(f"Table {name!r} not found in catalog")
        return path

    def table_exists(self, name: str) -> bool:
        try:
            self.table_path(name)
            return True
        except DeltaAnalysisError:
            return False

    def load_table(self, name: str):
        from delta_tpu.api.tables import DeltaTable

        return DeltaTable.for_path(self.table_path(name))

    def list_tables(self, database: str = "default"):
        with self._lock:
            if self._store_path:
                self._load()
            prefix = database.lower() + "."
            return sorted(
                k[len(prefix):] for k in self._tables if k.startswith(prefix)
            )


_default: Optional[Catalog] = None
_default_lock = threading.Lock()


def default_catalog() -> Catalog:
    """Process-default catalog; persists to ``delta.tpu.catalog.path`` when
    that conf is set, else stays in-memory."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Catalog(conf.get("delta.tpu.catalog.path"))
        return _default


def reset_default_catalog() -> None:
    global _default
    with _default_lock:
        _default = None


def resolve_identifier(identifier: str, catalog: Optional[Catalog] = None) -> str:
    """``delta.`/path``` → the path; anything else → catalog lookup."""
    ident = identifier.strip()
    if ident.lower().startswith("delta.`") and ident.endswith("`"):
        return ident[len("delta.`"):-1]
    return (catalog or default_catalog()).table_path(ident)
