"""Real 2-process DCN integration (VERDICT r3 item 3): two OS processes in a
`jax.distributed` CPU cluster drive multi-host scan, distributed checkpoint
part writing, and fragment-exchanged CONVERT against one shared table dir —
plus a unit check that vacuum's delete fan-out composes with the same
partitioner. No mocks: real subprocesses, real coordination service."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.parallel.distributed import host_partition, host_shard_indices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_cluster_scan_checkpoint_convert(tmp_path):
    table = str(tmp_path / "table")
    log = DeltaLog.for_table(table)
    for i in range(6):
        WriteIntoDelta(log, "append", pa.table({
            "id": np.arange(i * 10, (i + 1) * 10, dtype=np.int64),
            "v": np.random.rand(10),
        })).run()

    convert_dir = str(tmp_path / "plain")
    os.makedirs(convert_dir)
    for i in range(5):
        pq.write_table(
            pa.table({"a": np.arange(i * 4, (i + 1) * 4, dtype=np.int64)}),
            os.path.join(convert_dir, f"part-{i}.parquet"),
        )

    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)  # the virtual 8-device mesh is for in-proc tests
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "multihost_worker.py"),
             str(i), "2", str(port), table, convert_dir, out_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=150) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-3000:]

    results = []
    for i in range(2):
        with open(os.path.join(out_dir, f"result-{i}.json")) as f:
            results.append(json.load(f))

    # scan: the two hosts' partitions tile the table exactly
    assert all(r["count"] == 2 for r in results)
    assert results[0]["full_rows"] == 60
    assert results[0]["scan_rows"] + results[1]["scan_rows"] == 60
    ids = sorted(results[0]["scan_ids"] + results[1]["scan_ids"])
    assert ids == list(range(60))

    # checkpoint: all 4 parts exist, _last_checkpoint published once,
    # and a cold reader reconstructs from it
    from delta_tpu.log import checkpoints as ckpt_mod

    last = ckpt_mod.read_last_checkpoint(log.store, log.log_path)
    assert last is not None and last.parts == 4
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(table).update()
    assert snap.num_of_files == 6
    assert snap.segment.checkpoint_version == last.version

    # convert: both processes agree on the committed version; all files in
    assert results[0]["convert_version"] == results[1]["convert_version"]
    assert all(r["convert_files"] == 5 for r in results)
    DeltaLog.clear_cache()
    csnap = DeltaLog.for_table(convert_dir).update()
    t = sorted(
        __import__("delta_tpu.exec.scan", fromlist=["scan_to_table"])
        .scan_to_table(csnap).column("a").to_pylist()
    )
    assert t == list(range(20))


def _mk_dist_table(path: str, parts: int = 4, files_per: int = 3,
                   rows: int = 16) -> None:
    log = DeltaLog.for_table(path)
    for p in range(parts):
        for f in range(files_per):
            base = (p * files_per + f) * rows
            WriteIntoDelta(log, "append", pa.table({
                "id": np.arange(base, base + rows, dtype=np.int64),
                "part": pa.array([f"p{p}"] * rows),
                "v": np.arange(base, base + rows, dtype=np.float64),
            }), partition_columns=["part"]).run()


def _run_workers(tmp_path, table: str, mode: str, out_name: str,
                 extra_env=None):
    out_dir = str(tmp_path / out_name)
    os.makedirs(out_dir)
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               **(extra_env or {}))
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "multihost_worker.py"),
             str(i), "2", str(port), table, "-", out_dir, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=150) for p in procs]
    results = {}
    for i in range(2):
        f = os.path.join(out_dir, f"result-{i}.json")
        if os.path.exists(f):
            with open(f) as fh:
                results[i] = json.load(fh)
    return procs, outs, results


def test_two_process_sharded_optimize_merge_identity(tmp_path):
    """2-process sharded execution over a shared table: each host commits
    its byte-weighted LPT slice of the OPTIMIZE groups, proc 0 runs the
    probe-restricted MERGE — and the end state is row-identical to the same
    OPTIMIZE+MERGE run single-process on a clone."""
    table = str(tmp_path / "table")
    solo = str(tmp_path / "solo")
    _mk_dist_table(table)
    _mk_dist_table(solo)

    procs, outs, results = _run_workers(tmp_path, table, "dist", "out")
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-3000:]
    assert sorted(results) == [0, 1]

    # sharded scan: the two hosts' LPT slices tile the table exactly
    ids = sorted(results[0]["scan_ids"] + results[1]["scan_ids"])
    assert ids == list(range(192))

    # each host committed a disjoint slice of the 4 partition groups
    assert results[0]["optimize_groups"] + results[1]["optimize_groups"] == 4
    assert all(r["optimize_groups"] >= 1 for r in results.values())
    assert results[0]["optimize_version"] != results[1]["optimize_version"]
    assert all(r["shard_timings"] for r in results.values())

    # proc 0's MERGE ran the distributed probe and updated/inserted
    assert results[0]["merge_probed"] is True
    assert results[0]["merge_updated"] == 2
    assert results[0]["merge_inserted"] == 1

    # single-process reference on the clone: identical final rows
    from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.utils.config import conf

    slog = DeltaLog.for_table(solo)
    OptimizeCommand(slog, min_file_size=1 << 30).run()
    src = pa.table({
        "id": pa.array([3, 75, 1000], pa.int64()),
        "part": pa.array(["p0", "p3", "p0"]),
        "v": pa.array([-1.0, -2.0, -3.0]),
    })
    with conf.set_temporarily(
        **{"delta.tpu.distributed.merge.probe.enabled": False}
    ):
        MergeIntoCommand(
            slog, src, "t.id = s.id",
            [MergeClause("update", assignments=None)],
            [MergeClause("insert", assignments=None)],
            source_alias="s", target_alias="t").run()
    DeltaLog.clear_cache()
    want = scan_to_table(DeltaLog.for_table(solo).update()).sort_by("id")
    got = scan_to_table(DeltaLog.for_table(table).update()).sort_by("id")
    assert got.select(["id", "part", "v"]).to_pylist() == \
        want.select(["id", "part", "v"]).to_pylist()
    # both workers read back the same converged state, and the file
    # topology matches the single-process reference exactly
    assert results[0]["final_ids"] == results[1]["final_ids"]
    solo_files = DeltaLog.for_table(solo).update().num_of_files
    assert results[0]["final_files"] == results[1]["final_files"] == solo_files


def _mk_zipf_table(path: str, parts: int = 4, files_per: int = 2) -> int:
    """Partitioned table with zipf-skewed partition bytes (partition p holds
    ~1/(p+1) of the head's rows) — the workload where per-shard skew
    dominates makespan and the straggler analysis has something to name."""
    log = DeltaLog.for_table(path)
    base = 0
    for p in range(parts):
        rows = max(256 // (p + 1), 16)
        for _f in range(files_per):
            WriteIntoDelta(log, "append", pa.table({
                "id": np.arange(base, base + rows, dtype=np.int64),
                "part": pa.array([f"p{p}"] * rows),
                "v": np.arange(base, base + rows, dtype=np.float64),
            }), partition_columns=["part"]).run()
            base += rows
    return base


def test_two_process_distributed_optimize_stitches_one_trace(tmp_path):
    """The tentpole acceptance: a 2-process distributed OPTIMIZE under a
    coordinator root span produces ONE stitched trace — every span in every
    process's spool carries the coordinator's trace_id, parents resolve into
    a single tree, the stitched Chrome-trace span count equals the sum of
    all spools, and analyze_trace names the straggler shard and its makespan
    delta vs the LPT byte-share prediction on a zipf-skewed table."""
    from delta_tpu.obs import trace_store
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf

    table = str(tmp_path / "table")
    _mk_zipf_table(table)
    trace_dir = str(tmp_path / "spool")
    os.makedirs(trace_dir)

    with conf.set_temporarily(**{"delta.tpu.trace.dir": trace_dir,
                                 "delta.tpu.trace.sampleRate": 1.0}):
        with telemetry.record_operation("delta.test.coordinator") as root:
            wire = telemetry.span_context(wire=True)
            assert wire is not None and wire.split("-")[1] == root.trace_id
            procs, outs, results = _run_workers(
                tmp_path, table, "dist", "out",
                extra_env={"DELTA_TPU_TRACEPARENT": wire,
                           "DELTA_TPU_TRACE_DIR": trace_dir})
    trace_store.reset()  # release the coordinator's spool handle
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-3000:]
    assert sorted(results) == [0, 1]

    trace_id = root.trace_id
    assert len(trace_id) == 32

    # ONE trace: every spooled span in every process carries the
    # coordinator's trace id, and parents resolve into a single tree
    all_rows = trace_store.read_spools(trace_dir)
    assert {r["traceId"] for r in all_rows} == {trace_id}
    ids = {r["spanId"] for r in all_rows}
    orphans = [r for r in all_rows
               if r["parentId"] is not None and r["parentId"] not in ids]
    assert orphans == []
    roots = [r for r in all_rows if r["parentId"] is None]
    assert [r["op"] for r in roots] == ["delta.test.coordinator"]

    # stitched Chrome trace: span count == sum of both hosts' spools (plus
    # the coordinator's), three distinct process lanes
    trace = trace_store.stitch_trace(trace_dir, trace_id)
    rows = [r for r in trace["traceEvents"] if r.get("cat") == "delta"]
    assert len(rows) == len(all_rows)
    assert all(r["args"]["traceId"] == trace_id for r in rows)
    assert len({r["pid"] for r in rows}) == 3  # coordinator + 2 workers

    # straggler analysis: the sharded OPTIMIZE jobs name their slowest
    # shard and its delta vs the LPT-predicted byte share
    analysis = trace_store.analyze_trace(trace_dir, trace_id)
    assert analysis["rootOp"] == "delta.test.coordinator"
    assert analysis["spans"] == len(all_rows)
    assert analysis["criticalPath"][0]["op"] == "delta.test.coordinator"
    assert len(analysis["criticalPath"]) >= 2
    jobs = [j for j in analysis["jobs"] if j["label"] == "optimize"]
    assert len(jobs) == 2  # one sharded job per worker process
    assert {j["pid"] for j in jobs} == {r["pid"] for r in rows} - \
        {roots[0]["pid"]}
    sharded = [j for j in jobs if j["shards"]]
    assert sharded, "no pool-path OPTIMIZE job produced worker shards"
    for j in sharded:
        s = j["straggler"]
        assert s["busyUs"] == max(x["busyUs"] for x in j["shards"])
        assert s["busyUs"] - s["predictedUs"] == s["deltaUs"]
        assert j["lptBytes"] and j["skew"] >= 1.0
    assert analysis["straggler"] is not None


def test_two_process_optimize_survives_worker_crash(tmp_path):
    """SimulatedCrash of worker 1 mid-OPTIMIZE: the surviving host completes
    and commits its slice; the crashed host commits NOTHING (its half-done
    rewrite leaves only uncommitted orphan data files), and the log replays
    to a consistent snapshot with every original row intact."""
    table = str(tmp_path / "table")
    _mk_dist_table(table)
    snap0 = DeltaLog.for_table(table).update()
    v0, files0 = snap0.version, snap0.num_of_files

    procs, outs, results = _run_workers(tmp_path, table, "dist-crash", "out")
    assert procs[0].returncode == 0, outs[0][1].decode()[-3000:]
    assert procs[1].returncode != 0
    assert b"SimulatedCrash" in outs[1][1]
    assert 0 in results and 1 not in results  # proc 1 died before reporting

    # ledger reconciles: exactly the survivor's commit landed, all rows live
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(table).update()
    assert snap.version == v0 + 1 == results[0]["final_version"]
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(snap)
    assert sorted(t.column("id").to_pylist()) == list(range(192))
    # survivor compacted its slice: fewer files than before, more than the
    # fully-compacted 4 (the crashed host's slice is still un-compacted)
    assert 4 < snap.num_of_files < files0
    assert results[0]["final_files"] == snap.num_of_files


def test_two_process_crash_recovery_acceptance(tmp_path):
    """ISSUE 20 acceptance: a worker host is killed mid-OPTIMIZE after
    publishing its lease; the coordinator recovers the orphaned slice. The
    end state is row- AND topology-identical to a single-process run, every
    group was committed exactly once (disjoint remove sets across exactly
    two commits), and the stitched trace shows the recovery span."""
    import time

    from delta_tpu.obs import trace_store
    from delta_tpu.parallel import leases
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf

    table = str(tmp_path / "table")
    solo = str(tmp_path / "solo")
    _mk_dist_table(table)
    _mk_dist_table(solo)
    log_path = DeltaLog.for_table(table).log_path
    snap0 = DeltaLog.for_table(table).update()
    v0, files0 = snap0.version, snap0.num_of_files

    trace_dir = str(tmp_path / "spool")
    out_dir = str(tmp_path / "out")
    os.makedirs(trace_dir)
    os.makedirs(out_dir)

    def run_worker(i, extra_env=None):
        # only the traced phase (the coordinator) gets the spool dir —
        # phase 1 has no traceparent and would spool under its own trace id
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   **(extra_env or {}))
        env.pop("XLA_FLAGS", None)
        p = subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "multihost_worker.py"),
             str(i), "2", "0", table, "-", out_dir, "dist-recover"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        return p, p.communicate(timeout=150)

    # phase 1: host 1 dies mid-slice, leaving its lease orphaned
    p1, out1 = run_worker(1)
    assert p1.returncode != 0
    assert b"SimulatedCrash" in out1[1]
    orphans = leases.read_leases(log_path)
    assert len(orphans) == 1
    assert orphans[0][1]["proc"] == 1 and orphans[0][1]["txnId"]
    assert DeltaLog.for_table(table).update().version == v0  # no commit
    past = time.time() - 120  # the dead host's heartbeat goes stale
    os.utime(orphans[0][0], (past, past))

    # phase 2: the coordinator, under a traced root span
    with conf.set_temporarily(**{"delta.tpu.trace.dir": trace_dir,
                                 "delta.tpu.trace.sampleRate": 1.0}):
        with telemetry.record_operation("delta.test.recovery") as root:
            wire = telemetry.span_context(wire=True)
            p0, out0 = run_worker(
                0, extra_env={"DELTA_TPU_TRACEPARENT": wire,
                              "DELTA_TPU_TRACE_DIR": trace_dir})
    trace_store.reset()
    assert p0.returncode == 0, out0[1].decode()[-3000:]
    with open(os.path.join(out_dir, "result-0.json")) as f:
        result = json.load(f)

    # end state: row- and topology-identical to a single-process run
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.exec.scan import scan_to_table

    OptimizeCommand(DeltaLog.for_table(solo), min_file_size=1 << 30).run()
    DeltaLog.clear_cache()
    ssnap = DeltaLog.for_table(solo).update()
    want = sorted(scan_to_table(ssnap).column("id").to_pylist())
    assert result["final_ids"] == want == list(range(192))
    assert result["final_files"] == ssnap.num_of_files < files0

    # the worker recovered exactly one slice and cleared its lease
    assert result["recovered"] == 1
    assert result["leases_left"] == 0
    assert leases.read_leases(log_path) == []
    assert "dist.sliceRecovered" in result["dist_events"]
    assert "dist.sliceReconciled" not in result["dist_events"]

    # exactly one commit per group: two commits (coordinator's slice + the
    # recovery), whose remove sets are disjoint and tile the original files
    snap = DeltaLog.for_table(table).update()
    assert snap.version == v0 + 2 == result["final_version"]
    removed = []
    for v in (v0 + 1, v0 + 2):
        with open(os.path.join(log_path, f"{v:020d}.json")) as f:
            removed.append({json.loads(line)["remove"]["path"]
                            for line in f if '"remove"' in line})
    assert removed[0] & removed[1] == set()
    assert len(removed[0] | removed[1]) == files0

    # the stitched trace carries the recovery span under the one trace id
    rows = trace_store.read_spools(trace_dir)
    assert {r["traceId"] for r in rows} == {root.trace_id}
    recovery_spans = [r for r in rows if r["op"] == "delta.dist.sliceRecovery"]
    assert len(recovery_spans) == 1
    analysis = trace_store.analyze_trace(trace_dir, root.trace_id)
    [rec] = analysis["recoveries"]
    assert rec["outcome"] == "recovered"
    assert rec["proc"] == 1 and rec["groups"] >= 1


def test_vacuum_composes_with_scan_partitioning():
    """The same strided partitioner drives vacuum's delete fan-out and the
    distributed scan: for any (index, count) the slices tile the work list
    without overlap — the composition property the multi-host paths rely on."""
    items = [f"f{i}" for i in range(13)]
    for count in (1, 2, 3, 5):
        seen = []
        for index in range(count):
            seen += host_partition(items, index, count)
        assert sorted(seen) == sorted(items)
        # disjointness
        assert len(seen) == len(set(seen))
        for index in range(count):
            idx = host_shard_indices(len(items), index, count)
            assert idx == list(range(index, len(items), count))


def test_convert_fragment_exchange_empty_slice_and_token(tmp_path):
    """A host with an empty file slice publishes a schema-less fragment
    (fewer files than processes must not crash), and fragments are
    namespaced by a listing hash so a retry after the data changed cannot
    consume stale ones."""
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    d = str(tmp_path / "plain")
    os.makedirs(d)
    pq.write_table(pa.table({"a": np.arange(3, dtype=np.int64)}),
                   os.path.join(d, "only.parquet"))
    log = DeltaLog.for_table(d)
    cmd = ConvertToDeltaCommand(log, collect_stats=True, distribute=True)
    files = cmd._list_parquet_files()
    assert len(files) == 1
    # "proc 1" has the empty slice: publish its (schema-less) fragment
    m1, f1 = cmd._exchange_fragments(1, 2, None, [], files)
    assert m1 is None and f1 == []
    # "proc 0" computed the file and gathers both fragments
    abs_p = os.path.join(d, files[0][0])
    schema = pq.ParquetFile(abs_p).schema_arrow
    adds0 = [{"i": 0, "rel": files[0][0], "size": files[0][1],
              "mtime": files[0][2], "stats": None}]
    merged, all_adds = cmd._exchange_fragments(0, 2, schema, adds0, files)
    assert merged is not None and len(all_adds) == 1
    # token changes when the listing changes (stale fragments unreachable)
    t1 = cmd._listing_token(files)
    pq.write_table(pa.table({"a": np.arange(2, dtype=np.int64)}),
                   os.path.join(d, "second.parquet"))
    t2 = cmd._listing_token(cmd._list_parquet_files())
    assert t1 != t2
