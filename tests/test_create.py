"""CREATE / REPLACE / CTAS command (reference spec:
``DeltaTableCreationTests``, 1,923 LoC core cases) and the name catalog."""
import os
import unittest.mock

import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.create import CreateDeltaTableCommand
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.schema.types import IntegerType, LongType, StringType, StructType
from delta_tpu.utils.errors import DeltaAnalysisError

SCHEMA = StructType().add("id", LongType()).add("v", StringType())


def test_create_empty_table(tmp_table):
    t = DeltaTable.create(tmp_table, SCHEMA, configuration={"delta.appendOnly": "false"})
    snap = t.delta_log.update()
    assert snap.version == 0
    assert snap.metadata.schema.to_json() == SCHEMA.to_json()
    assert snap.all_files == []
    h = t.delta_log.history.get_history()
    assert h[0].operation == "CREATE TABLE"


def test_create_existing_errors(tmp_table):
    DeltaTable.create(tmp_table, SCHEMA)
    with pytest.raises(DeltaAnalysisError, match="already exists"):
        DeltaTable.create(tmp_table, SCHEMA)


def test_create_if_not_exists_noop_when_matching(tmp_table):
    DeltaTable.create(tmp_table, SCHEMA)
    v = DeltaTable.create(tmp_table, SCHEMA, mode="create_if_not_exists")
    assert v.delta_log.snapshot.version == 0


def test_create_if_not_exists_schema_mismatch_errors(tmp_table):
    DeltaTable.create(tmp_table, SCHEMA)
    other = StructType().add("x", IntegerType())
    with pytest.raises(DeltaAnalysisError, match="does not match"):
        DeltaTable.create(tmp_table, other, mode="create_if_not_exists")


def test_create_if_not_exists_partitioning_mismatch_errors(tmp_table):
    DeltaTable.create(tmp_table, SCHEMA, partition_columns=["v"])
    with pytest.raises(DeltaAnalysisError, match="partitioning"):
        DeltaTable.create(tmp_table, SCHEMA, partition_columns=["id"],
                          mode="create_if_not_exists")


def test_ctas_one_commit(tmp_table):
    data = pa.table({"id": [1, 2], "v": ["a", "b"]})
    t = DeltaTable.create(tmp_table, data=data)
    snap = t.delta_log.update()
    assert snap.version == 0  # metadata + files in ONE commit
    assert len(snap.all_files) >= 1
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2]
    h = t.delta_log.history.get_history()
    assert h[0].operation == "CREATE TABLE AS SELECT"


def test_replace_requires_existing(tmp_table):
    with pytest.raises(DeltaAnalysisError, match="REPLACE requires"):
        DeltaTable.replace(tmp_table, SCHEMA)


def test_create_or_replace_fresh(tmp_table):
    t = DeltaTable.replace(tmp_table, SCHEMA, or_create=True)
    assert t.delta_log.snapshot.version == 0


def test_replace_swaps_schema_and_drops_files_atomically(tmp_table):
    t = DeltaTable.create(tmp_table, data=pa.table({"id": [1, 2], "v": ["a", "b"]}))
    new_schema = StructType().add("x", LongType())
    t2 = DeltaTable.replace(tmp_table, new_schema,
                            data=pa.table({"x": [10]}))
    snap = t2.delta_log.update()
    assert snap.version == 1  # one commit for the whole replace
    assert snap.metadata.schema.field_names == ["x"]
    assert scan_to_table(snap).to_pylist() == [{"x": 10}]
    h = t2.delta_log.history.get_history()
    assert h[0].operation == "REPLACE TABLE AS SELECT"
    # old data files are tombstoned, not orphaned
    assert len(snap.tombstones) >= 1


def test_replace_keeps_table_id(tmp_table):
    t = DeltaTable.create(tmp_table, SCHEMA)
    tid = t.delta_log.update().metadata.id
    DeltaTable.replace(tmp_table, StructType().add("x", LongType()))
    assert DeltaLog.for_table(tmp_table).update().metadata.id == tid


def test_create_requires_schema_or_data(tmp_table):
    with pytest.raises(DeltaAnalysisError, match="schema or data"):
        CreateDeltaTableCommand(DeltaLog.for_table(tmp_table)).run()


def test_create_partitioned_ctas(tmp_table):
    data = pa.table({"id": [1, 2, 3], "p": ["a", "a", "b"]})
    t = DeltaTable.create(tmp_table, data=data, partition_columns=["p"])
    snap = t.delta_log.update()
    assert snap.metadata.partition_columns == ["p"]
    assert sorted(t.to_arrow(filters=["p = 'a'"]).column("id").to_pylist()) == [1, 2]


# -- name catalog (≈ DeltaCatalog.scala:57) ---------------------------------


def test_catalog_create_load_drop(tmp_path):
    from delta_tpu.catalog.catalog import Catalog

    cat = Catalog()
    data = pa.table({"id": [1, 2]})
    cat.create_table("db1.sales", str(tmp_path / "sales"), data=data)
    assert cat.table_exists("db1.sales")
    assert cat.table_exists("DB1.SALES")  # case-insensitive
    t = cat.load_table("db1.sales")
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2]
    assert cat.list_tables("db1") == ["sales"]
    cat.drop_table("db1.sales")
    assert not cat.table_exists("db1.sales")
    # dropping is external-table style: the data survives on disk
    assert DeltaTable.is_delta_table(str(tmp_path / "sales"))


def test_catalog_duplicate_name_errors(tmp_path):
    from delta_tpu.catalog.catalog import Catalog

    cat = Catalog()
    cat.create_table("t", str(tmp_path / "a"), SCHEMA)
    with pytest.raises(DeltaAnalysisError, match="already exists"):
        cat.create_table("t", str(tmp_path / "b"), SCHEMA)


def test_catalog_losing_creator_fails_before_writing_data(tmp_path):
    """The name is claimed in the first critical section, so a concurrent
    create of the same name fails BEFORE materializing any table data —
    no orphan directory is left behind (DeltaCatalog's staged-create
    atomicity, `DeltaCatalog.scala:329-403`)."""
    import os

    from delta_tpu.catalog.catalog import Catalog

    cat = Catalog()
    cat.create_table("t", str(tmp_path / "a"), SCHEMA)
    loser = str(tmp_path / "b")
    with pytest.raises(DeltaAnalysisError, match="already exists"):
        cat.create_table("t", loser, SCHEMA)
    assert not os.path.exists(loser), "losing creator must not write data"
    assert cat.table_path("t") == str(tmp_path / "a")


def test_catalog_failed_create_rolls_back_claim(tmp_path):
    """If the create itself fails after the name was claimed, the claim is
    rolled back so the name isn't left dangling at a nonexistent table."""
    from delta_tpu.catalog.catalog import Catalog

    cat = Catalog()
    with pytest.raises(Exception):
        cat.create_table("bad", str(tmp_path / "bad"), schema=None, data=None)
    assert not cat.table_exists("bad")
    # the name is reusable afterwards
    cat.create_table("bad", str(tmp_path / "ok"), SCHEMA)
    assert cat.table_exists("bad")


def test_catalog_persistence(tmp_path):
    from delta_tpu.catalog.catalog import Catalog

    store = str(tmp_path / "catalog.json")
    cat = Catalog(store)
    cat.create_table("t", str(tmp_path / "t"), SCHEMA)
    cat2 = Catalog(store)  # fresh instance sees the registration
    assert cat2.table_exists("t")
    assert cat2.table_path("t") == str(tmp_path / "t")


def test_for_name_and_path_identifier(tmp_path):
    from delta_tpu.catalog.catalog import Catalog
    from delta_tpu.utils.config import conf
    from delta_tpu.catalog import catalog as cat_mod

    store = str(tmp_path / "cat.json")
    with conf.set_temporarily(**{"delta.tpu.catalog.path": store}):
        cat_mod.reset_default_catalog()
        cat_mod.default_catalog().create_table(
            "people", str(tmp_path / "people"), data=pa.table({"id": [7]})
        )
        t = DeltaTable.for_name("people")
        assert t.to_arrow().column("id").to_pylist() == [7]
        # delta.`path` escape hatch
        t2 = DeltaTable.for_name(f"delta.`{tmp_path / 'people'}`")
        assert t2.to_arrow().column("id").to_pylist() == [7]
    cat_mod.reset_default_catalog()


def test_register_external_table(tmp_path):
    from delta_tpu.catalog.catalog import Catalog

    path = str(tmp_path / "ext")
    DeltaTable.create(path, data=pa.table({"id": [9]}))
    cat = Catalog()
    cat.register("ext", path)
    assert cat.load_table("ext").to_arrow().column("id").to_pylist() == [9]


def test_catalog_live_inflight_create_blocks_concurrent(tmp_path):
    """A live in-progress creator's claim must NOT be reclaimable: the
    concurrent creator errors instead of hijacking the name (round-4 review:
    the stale-claim reclaim must distinguish crashed from live)."""
    import threading

    from delta_tpu.catalog.catalog import Catalog

    cat = Catalog(str(tmp_path / "cat.json"))
    gate = threading.Event()
    release = threading.Event()
    errors_b = []

    orig_create = DeltaTable.create.__func__

    def slow_create(cls, *a, **kw):
        gate.set()
        release.wait(timeout=10)
        return orig_create(cls, *a, **kw)

    a_path, b_path = str(tmp_path / "a"), str(tmp_path / "b")

    def creator_a():
        with unittest.mock.patch.object(
            DeltaTable, "create", classmethod(slow_create)
        ):
            cat.create_table("t", a_path, SCHEMA)

    ta = threading.Thread(target=creator_a)
    ta.start()
    assert gate.wait(timeout=10)
    # B races while A is mid-create: must fail, must not write data
    try:
        cat.create_table("t", b_path, SCHEMA)
    except DeltaAnalysisError as e:
        errors_b.append(str(e))
    release.set()
    ta.join(timeout=10)
    assert errors_b and "concurrently" in errors_b[0]
    assert not os.path.exists(b_path)
    assert cat.table_path("t") == a_path
    assert DeltaTable.is_delta_table(a_path)


def test_catalog_crashed_claim_is_reclaimable(tmp_path):
    """A claim whose owner pid is dead (crashed creator) is stale: a new
    creator takes the name over cleanly."""
    import json as _json

    from delta_tpu.catalog.catalog import Catalog

    store = str(tmp_path / "cat.json")
    dead = {"path": str(tmp_path / "ghost"), "pid": 2**22 + 12345,
            "host": __import__("socket").gethostname(), "ts_ms": 0}
    with open(store, "w") as f:
        _json.dump({"tables": {}, "claims": {"default.t": dead}}, f)
    cat = Catalog(store)
    cat.create_table("t", str(tmp_path / "real"), SCHEMA)
    assert cat.table_path("t") == str(tmp_path / "real")


def test_catalog_register_refuses_live_claim(tmp_path):
    import socket
    import time as _time
    import json as _json

    from delta_tpu.catalog.catalog import Catalog

    store = str(tmp_path / "cat.json")
    live = {"path": str(tmp_path / "x"), "pid": os.getpid(),
            "host": socket.gethostname(), "ts_ms": int(_time.time() * 1000)}
    with open(store, "w") as f:
        _json.dump({"tables": {}, "claims": {"default.t": live}}, f)
    cat = Catalog(store)
    with pytest.raises(DeltaAnalysisError, match="concurrently"):
        cat.register("t", str(tmp_path / "y"))


def test_catalog_same_host_claim_expires(tmp_path):
    """A same-host claim whose pid is (or appears) alive still expires past
    claimTimeoutMs — a recycled pid must not block the name forever."""
    import socket
    import json as _json

    from delta_tpu.catalog.catalog import Catalog
    from delta_tpu.utils.config import conf

    store = str(tmp_path / "cat.json")
    stale = {"path": str(tmp_path / "x"), "pid": 1,  # alive (init), not ours
             "host": socket.gethostname(), "ts_ms": 0}  # ancient
    with open(store, "w") as f:
        _json.dump({"tables": {}, "claims": {"default.t": stale}}, f)
    cat = Catalog(store)
    with conf.set_temporarily(**{"delta.tpu.catalog.claimTimeoutMs": 1}):
        cat.create_table("t", str(tmp_path / "real"), SCHEMA)
    assert cat.table_path("t") == str(tmp_path / "real")
