"""ALTER TABLE commands — properties, columns, constraints.

Mirrors `commands/alterDeltaTableCommands.scala:68-578`: SET/UNSET
TBLPROPERTIES, ADD COLUMNS, CHANGE COLUMN (comment/nullability/type per the
`can_change_data_type` rules), ADD/DROP CONSTRAINT. Each is one metadata-only
transaction.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Dict, Optional, Sequence

from delta_tpu.commands import operations as ops
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.expr.vectorized import boolean_mask
from delta_tpu.schema import schema_utils
from delta_tpu.schema.constraints import CONSTRAINT_PROP_PREFIX
from delta_tpu.schema.types import StructField, StructType
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors
from delta_tpu.utils.telemetry import record_operation

__all__ = [
    "set_table_properties",
    "unset_table_properties",
    "add_columns",
    "change_column",
    "add_constraint",
    "drop_constraint",
]


def set_table_properties(delta_log, properties: Dict[str, str]) -> int:
    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        cfg.update({k: str(v) for k, v in properties.items()})
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.SetTableProperties(properties))

    with record_operation("delta.utility.alter.setProperties",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)


def unset_table_properties(delta_log, keys: Sequence[str], if_exists: bool = False) -> int:
    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        norm = {k.lower(): k for k in cfg}
        for k in keys:
            actual = norm.get(k.lower())
            if actual is None:
                if not if_exists:
                    raise errors.unset_nonexistent_property(
                        k, delta_log.data_path
                    )
                continue
            del cfg[actual]
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.UnsetTableProperties(list(keys), if_exists))

    with record_operation("delta.utility.alter.unsetProperties",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)


def _position_spec(schema: StructType, parent_parts, leaf_spec):
    """Resolve a column position: ``parent_parts`` is the dotted path to the
    enclosing struct ([] = top level), ``leaf_spec`` is None (append),
    "first", or ("after", sibling)."""
    from delta_tpu.schema.types import ArrayType, MapType

    if parent_parts:
        parent_pos = schema_utils.find_column_position(parent_parts, schema)
        parent = schema
        for step in parent_pos:
            if isinstance(parent, StructType):
                parent = parent.fields[step].data_type
            elif isinstance(parent, ArrayType):
                parent = parent.element_type
            elif isinstance(parent, MapType):
                parent = (
                    parent.key_type
                    if step == schema_utils.MAP_KEY_INDEX
                    else parent.value_type
                )
            else:
                raise errors.parent_is_not_struct('.'.join(parent_parts))
        if not isinstance(parent, StructType):
            raise errors.parent_is_not_struct('.'.join(parent_parts))
    else:
        parent_pos = []
        parent = schema
    if leaf_spec is None:
        idx = len(parent.fields)
    elif leaf_spec == "first":
        idx = 0
    elif isinstance(leaf_spec, tuple) and leaf_spec[0] == "after":
        sib = leaf_spec[1].lower()
        match = next(
            (i for i, f in enumerate(parent.fields) if f.name.lower() == sib), None
        )
        if match is None:
            raise errors.position_after_column_not_found(leaf_spec[1])
        idx = match + 1
    else:
        raise errors.invalid_column_position_spec(leaf_spec)
    return list(parent_pos) + [idx]


def add_columns(
    delta_log,
    new_fields: Sequence[StructField],
    positions: Optional[Dict[str, object]] = None,
) -> int:
    """ADD COLUMNS (`:163`). New columns must be nullable (existing files
    have no values for them). A dotted field name (``s.x``) adds inside the
    named nested struct; ``positions`` maps a field name to ``"first"`` or
    ``("after", sibling)`` within its parent (default: append at the end),
    matching the reference's FIRST/AFTER grammar."""
    from delta_tpu.schema.char_varchar import replace_char_varchar_with_string

    positions = positions or {}
    new_fields = list(
        replace_char_varchar_with_string(StructType(list(new_fields))).fields)

    def body(txn):
        meta = txn.metadata
        schema = meta.schema
        for f in new_fields:
            if not f.nullable:
                raise errors.add_columns_must_be_nullable(f.name)
            parts = f.name.split(".")
            leaf = replace(f, name=parts[-1])
            pos = _position_spec(schema, parts[:-1], positions.get(f.name))
            schema = schema_utils.add_column(schema, leaf, pos)
        txn.update_metadata(replace(meta, schema_string=schema.to_json()))
        op = ops.AddColumns(
            [{"column": f.json_value()} for f in new_fields]
        )
        return txn.commit([], op)

    with record_operation("delta.utility.alter.addColumns",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)


def change_column(
    delta_log,
    name: str,
    new_type=None,
    nullable: Optional[bool] = None,
    comment: Optional[str] = None,
    position=None,
) -> int:
    """CHANGE COLUMN (`:251`): widen type (int→long etc.), relax nullability
    (never tighten — existing data may violate it), set a comment. Dotted
    names edit nested struct fields in place; ``position`` ("first" or
    ("after", sibling)) moves the column within its parent."""

    def body(txn):
        meta = txn.metadata
        schema = meta.schema
        parts = name.split(".")
        pos = schema_utils.find_column_position(parts, schema)
        field = schema_utils.find_field(schema, name)
        if field is None:
            raise errors.column_not_in_schema(name)
        new_field = field
        if new_type is not None and new_type != field.data_type:
            if not schema_utils.can_change_data_type(field.data_type, new_type):
                raise errors.cannot_change_column_type(
                    name, field.data_type.simple_string(),
                    new_type.simple_string())
            new_field = replace(new_field, data_type=new_type)
        if nullable is not None:
            if not nullable and field.nullable:
                raise errors.cannot_change_nullable_to_not_null(name)
            new_field = replace(new_field, nullable=nullable)
        if comment is not None:
            md = dict(new_field.metadata or {})
            md["comment"] = comment
            new_field = replace(new_field, metadata=md)
        if position is None:
            schema = schema_utils.replace_column_at(schema, pos, new_field)
        else:
            schema, _ = schema_utils.drop_column_at(schema, pos)
            new_pos = _position_spec(schema, parts[:-1], position)
            schema = schema_utils.add_column(schema, new_field, new_pos)
        txn.update_metadata(replace(meta, schema_string=schema.to_json()))
        op = ops.ChangeColumn(name, new_field.json_value())
        return txn.commit([], op)

    with record_operation("delta.utility.alter.changeColumn",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)


def add_constraint(delta_log, name: str, expr_sql: str) -> int:
    """ADD CONSTRAINT (`:519`): validates existing rows satisfy the check
    before committing, like the reference (which runs a full scan)."""
    import pyarrow.compute as pc

    from delta_tpu.exec.scan import scan_to_table

    key = CONSTRAINT_PROP_PREFIX + name.lower()

    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        if any(k.lower() == key for k in cfg):
            raise errors.constraint_already_exists(name)
        expr = parse_predicate(expr_sql)
        existing = scan_to_table(txn.snapshot)
        if existing.num_rows:
            ok = boolean_mask(expr, existing)
            bad = (pc.sum(pc.invert(ok)).as_py() or 0)
            if bad:
                raise errors.new_check_constraint_violated(
                    bad, delta_log.data_path, expr_sql
                )
        txn.read_whole_table()
        cfg[key] = expr_sql
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.AddConstraint(name, expr_sql))

    with record_operation("delta.utility.alter.addConstraint",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)


def drop_constraint(delta_log, name: str, if_exists: bool = True) -> int:
    key = CONSTRAINT_PROP_PREFIX + name.lower()

    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        actual = next((k for k in cfg if k.lower() == key), None)
        if actual is None:
            if if_exists:
                return txn.commit([], ops.DropConstraint(name, None))
            raise errors.constraint_does_not_exist(name)
        expr = cfg.pop(actual)
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.DropConstraint(name, expr))

    with record_operation("delta.utility.alter.dropConstraint",
                          path=delta_log.data_path):
        return delta_log.with_new_transaction(body)
